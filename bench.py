"""Benchmark driver: TPC-H Q1 on the flat index, single chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Baseline (BASELINE.md): the reference's Druid-accelerated TPC-H Q1 at SF10 —
59,986,052 lineitem rows in 18,340 ms avg on a 4-node cluster
(docs/benchmark/BenchMarkDetails.org:140-163) = 3.27M rows aggregated/sec.
vs_baseline = our rows-aggregated/sec/chip over that.

Env knobs: SDOT_BENCH_SF (default 1.0), SDOT_BENCH_REPS (default 5).
Per-query detail goes to stderr; stdout carries only the JSON line.
"""

import json
import os
import sys
import time

import numpy as np


def log(msg):
    print(msg, file=sys.stderr, flush=True)


DROP_COLS = [
    "l_comment", "o_comment", "c_comment", "s_comment", "ps_comment",
    "cn_comment", "cr_comment", "sn_comment", "sr_comment",
    "c_address", "s_address", "o_clerk",
]

BASELINE_ROWS_PER_SEC = 59_986_052 / 18.340


def build_flat(sf: float):
    import pandas as pd
    cache_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             ".bench_cache")
    os.makedirs(cache_dir, exist_ok=True)
    path = os.path.join(cache_dir, f"tpch_flat_sf{sf}.parquet")
    if os.path.exists(path):
        log(f"loading cached flat table {path}")
        return pd.read_parquet(path)
    from spark_druid_olap_tpu.tools import tpch
    t0 = time.perf_counter()
    tables = tpch.generate(sf)
    flat = tpch.flatten(tables)
    flat = flat.drop(columns=[c for c in DROP_COLS if c in flat.columns])
    log(f"generated flat SF{sf}: {len(flat):,} rows x {len(flat.columns)} "
        f"cols in {time.perf_counter() - t0:.1f}s")
    try:
        flat.to_parquet(path)
    except Exception as e:
        log(f"cache write failed ({e}); continuing")
    return flat


def main():
    sf = float(os.environ.get("SDOT_BENCH_SF", "1.0"))
    reps = int(os.environ.get("SDOT_BENCH_REPS", "5"))

    import jax
    log(f"backend={jax.default_backend()} devices={jax.devices()}")

    import spark_druid_olap_tpu as sdot
    from spark_druid_olap_tpu.tools import tpch

    flat = build_flat(sf)
    n_rows = len(flat)

    ctx = sdot.Context()
    t0 = time.perf_counter()
    ctx.ingest_dataframe("tpch_flat", flat, time_column="l_shipdate",
                         target_rows=1 << 20)
    ctx.register_star_schema(tpch.star_schema("tpch_flat"))
    log(f"ingest: {time.perf_counter() - t0:.1f}s "
        f"({ctx.store.get('tpch_flat').num_segments} segments)")
    del flat

    # rewrite star-join queries onto the flat datasource name directly:
    # fact-only queries reference 'lineitem'; map it to the flat index
    import re

    def q_for_flat(sql: str) -> str:
        return re.sub(r"\bfrom\s+lineitem\b", "from tpch_flat", sql)

    q1 = q_for_flat(tpch.QUERIES["q1"])

    # warm-up (compile)
    t0 = time.perf_counter()
    r = ctx.sql(q1)
    log(f"q1 cold (compile+transfer): {time.perf_counter() - t0:.2f}s, "
        f"{len(r)} groups")

    times = []
    for i in range(reps):
        t0 = time.perf_counter()
        ctx.sql(q1)
        times.append(time.perf_counter() - t0)
    med = float(np.median(times))
    log(f"q1 warm: median {med * 1000:.1f}ms over {reps} reps "
        f"(min {min(times)*1000:.1f} max {max(times)*1000:.1f})")

    # extra per-query detail (stderr only)
    for name in ("shipdate_range", "q6"):
        sql = q_for_flat(tpch.QUERIES[name])
        ctx.sql(sql)  # warm
        t0 = time.perf_counter()
        ctx.sql(sql)
        log(f"{name}: {(time.perf_counter() - t0) * 1000:.1f}ms")

    rows_per_sec = n_rows / med
    out = {
        "metric": f"tpch_sf{sf}_q1_rows_aggregated_per_sec_per_chip",
        "value": round(rows_per_sec, 1),
        "unit": "rows/s/chip",
        "vs_baseline": round(rows_per_sec / BASELINE_ROWS_PER_SEC, 3),
    }
    print(json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
