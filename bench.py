"""Benchmark driver: full TPC-H 22-query suite on the star-schema index,
single chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Headline value: geometric-mean per-query latency (ms) over the 22-query
suite at SDOT_BENCH_SF. Latencies are dispatch-floor-adjusted: the fixed
per-dispatch overhead (host<->device round trip — ~70ms through a tunneled
chip, ~0 on a local one) is measured with a trivial compiled device query
and subtracted from engine-mode query timings, so the number reflects
engine latency rather than link RTT.

vs_baseline: the reference's Druid-accelerated TPC-H SF10 numbers on a
4-node cluster (BASELINE.md / docs/benchmark/BenchMarkDetails.org:140-163)
for the five published full-table queries {Q1, Q3, Q5, Q7, Q8} — geomean
over those queries of (our lineitem-rows/sec) / (their 59,986,052 rows /
published ms), i.e. per-chip scan-throughput ratio at possibly different
scale factors.

Env knobs: SDOT_BENCH_SF (default 1.0), SDOT_BENCH_REPS (default 5),
SDOT_BENCH_QUERIES (comma list, default all 22).
Per-query detail goes to stderr; stdout carries only the JSON line.
"""

import json
import os
import sys
import time

import numpy as np


def log(msg):
    print(msg, file=sys.stderr, flush=True)


# reference Druid avg ms, TPC-H SF10 (BASELINE.md table 1)
BASELINE_MS = {"q1": 18340.0, "q3": 10669.0, "q5": 16722.0,
               "q7": 862.0, "q8": 20429.0}
BASELINE_ROWS = 59_986_052

DROP_COLS = [
    "l_comment", "o_comment", "c_comment", "s_comment", "ps_comment",
    "cn_comment", "cr_comment", "sn_comment", "sr_comment",
    "c_address", "s_address", "o_clerk",
]

ALL22 = ["q1", "q2", "q3", "q4", "q5", "q6", "q7", "q8", "q9", "q10",
         "q11", "q12", "q13", "q14", "q15", "q16", "q17", "q18", "q19",
         "q20", "q21", "q22"]


def cache_dir():
    d = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     ".bench_cache")
    os.makedirs(d, exist_ok=True)
    return d


def build_tables(sf: float):
    """Generate (or load cached) base tables + flat index."""
    import pandas as pd
    from spark_druid_olap_tpu.tools import tpch
    d = cache_dir()
    names = ["lineitem", "orders", "partsupp", "part", "supplier",
             "customer", "nation", "region"]
    paths = {n: os.path.join(d, f"tpch_{n}_sf{sf}.parquet") for n in names}
    flat_path = os.path.join(d, f"tpch_flat_sf{sf}.parquet")
    if all(os.path.exists(p) for p in paths.values()) and \
            os.path.exists(flat_path):
        log(f"loading cached tables from {d}")
        tables = {n: pd.read_parquet(p) for n, p in paths.items()}
        return tables, pd.read_parquet(flat_path)
    t0 = time.perf_counter()
    tables = tpch.generate(sf)
    flat = tpch.flatten(tables)
    flat = flat.drop(columns=[c for c in DROP_COLS if c in flat.columns])
    log(f"generated SF{sf}: lineitem {len(tables['lineitem']):,} rows "
        f"in {time.perf_counter() - t0:.1f}s")
    try:
        for n, p in paths.items():
            tables[n].to_parquet(p)
        flat.to_parquet(flat_path)
    except Exception as e:
        log(f"cache write failed ({e}); continuing")
    return tables, flat


def setup(sf: float):
    import spark_druid_olap_tpu as sdot
    from spark_druid_olap_tpu.tools import tpch
    tables, flat = build_tables(sf)
    n_rows = len(flat)
    ctx = sdot.Context()
    t0 = time.perf_counter()
    ctx.ingest_dataframe("tpch_flat", flat, time_column="l_shipdate",
                         target_rows=1 << 20)
    del flat
    for name, df in tables.items():
        if name in ("nation", "region"):
            continue
        tcol = {"lineitem": "l_shipdate", "orders": "o_orderdate"}.get(name)
        ctx.ingest_dataframe(name, df, time_column=tcol, target_rows=1 << 20)
    for name, df in tpch.nation_region_views(tables).items():
        ctx.ingest_dataframe(name, df)
    ctx.register_star_schema(tpch.star_schema("tpch_flat"))
    log(f"ingest: {time.perf_counter() - t0:.1f}s "
        f"({ctx.store.get('tpch_flat').num_segments} flat segments)")
    return ctx, n_rows


def measure_floor(ctx, reps: int) -> float:
    """Fixed per-dispatch overhead: a compiled trivial device query, timed
    end-to-end (dominated by the host<->device round trip)."""
    q = ("select count(*) as c from supplier where s_suppkey = 1"
         if "supplier" in ctx.store.names()
         else "select count(*) as c from lineorder where lo_orderkey = 1")
    ctx.sql(q)
    ts = []
    for _ in range(max(reps, 5)):
        t0 = time.perf_counter()
        ctx.sql(q)
        ts.append(time.perf_counter() - t0)
    floor = float(np.median(ts)) * 1000
    log(f"dispatch floor: {floor:.1f}ms")
    return floor


def setup_ssb(sf: float):
    """SSB suite (SDOT_BENCH_SUITE=ssb): 13 star-join queries on the
    denormalized lineorder index (BASELINE config 3)."""
    import spark_druid_olap_tpu as sdot
    from spark_druid_olap_tpu.tools import ssb
    ctx = sdot.Context()
    t0 = time.perf_counter()
    tables, flat = ssb.setup_context(ctx, sf=sf, target_rows=1 << 20)
    n = len(flat)
    log(f"ssb SF{sf}: {n:,} lineorder rows, ingest+gen "
        f"{time.perf_counter() - t0:.1f}s")
    return ctx, n, ssb.QUERIES


def main():
    sf = float(os.environ.get("SDOT_BENCH_SF", "1.0"))
    reps = int(os.environ.get("SDOT_BENCH_REPS", "5"))
    suite = os.environ.get("SDOT_BENCH_SUITE", "tpch")
    qsel = os.environ.get("SDOT_BENCH_QUERIES", "")

    import jax
    log(f"backend={jax.default_backend()} devices={jax.devices()}")

    from spark_druid_olap_tpu.tools import tpch

    if suite == "ssb":
        ctx, n_rows, queries = setup_ssb(sf)
        names = [s.strip() for s in qsel.split(",") if s.strip()] \
            or list(queries)
    else:
        queries = tpch.QUERIES
        names = [s.strip() for s in qsel.split(",") if s.strip()] or ALL22
        ctx, n_rows = setup(sf)
    floor_ms = measure_floor(ctx, reps)

    lat = {}
    for name in names:
        # queries run as written over the base tables; the planner's
        # star-join collapse routes fact+dim joins onto the flat index
        sql = queries[name]
        try:
            t0 = time.perf_counter()
            r = ctx.sql(sql)
            cold = time.perf_counter() - t0
        except Exception as e:
            log(f"{name}: FAILED ({type(e).__name__}: {e})")
            lat[name] = float("nan")
            continue
        mode = ctx.history.entries()[-1].stats.get("mode", "?")
        n_reps = 1 if cold > 3.0 else reps
        ts = []
        for _ in range(n_reps):
            t0 = time.perf_counter()
            ctx.sql(sql)
            ts.append(time.perf_counter() - t0)
        wall = float(np.median(ts)) * 1000
        adj = max(wall - floor_ms, 0.05) if mode == "engine" else wall
        lat[name] = adj
        log(f"{name}: {adj:.1f}ms adjusted ({wall:.1f}ms wall, cold "
            f"{cold:.2f}s, mode={mode}, {len(r)} rows)")

    ok = {k: v for k, v in lat.items() if np.isfinite(v)}
    geomean = float(np.exp(np.mean(np.log([max(v, 0.05)
                                           for v in ok.values()]))))
    n_fail = len(lat) - len(ok)
    log(f"geomean over {len(ok)}/{len(lat)} queries: {geomean:.1f}ms"
        + (f" ({n_fail} FAILED)" if n_fail else ""))

    # vs_baseline: per-chip row-throughput ratio on the published queries
    ratios = []
    for qn, base_ms in BASELINE_MS.items():
        if qn in ok:
            ours = n_rows / max(ok[qn], 0.05)          # rows/ms
            theirs = BASELINE_ROWS / base_ms
            ratios.append(ours / theirs)
            log(f"  vs_baseline {qn}: {ours / theirs:.1f}x")
    vs = float(np.exp(np.mean(np.log(ratios)))) if ratios else 0.0

    out = {
        "metric": f"{suite}_sf{sf}_{len(lat)}query_geomean_latency_ms",
        "value": round(geomean, 2),
        "unit": "ms",
        "vs_baseline": round(vs, 3),
    }
    print(json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
