"""Benchmark driver: full TPC-H 22-query suite on the star-schema index,
single chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.

Headline value: geometric-mean per-query WALL latency (ms) over the
22-query suite at SDOT_BENCH_SF. A dispatch-floor-adjusted geomean (fixed
per-dispatch host<->device round trip — ~70ms through a tunneled chip,
~0 on a local one — measured with a trivial compiled device query and
subtracted from engine-mode timings) is also reported, clearly labelled,
as "adjusted_geomean_ms".

vs_baseline: the reference's Druid-accelerated TPC-H SF10 numbers on a
4-node cluster (BASELINE.md / docs/benchmark/BenchMarkDetails.org:140-163)
for the five published full-table queries {Q1, Q3, Q5, Q7, Q8} — geomean
over those queries of (our lineitem-rows/sec) / (their 59,986,052 rows /
published ms), i.e. per-chip scan-throughput ratio at possibly different
scale factors. Computed from UNADJUSTED wall time, like the reference's
end-to-end latencies.

Backend selection: this script OWNS platform choice (round-1 failure:
the axon TPU plugin overrides JAX_PLATFORMS and backend init can hang or
return transient UNAVAILABLE). Each candidate platform is probed in a
SUBPROCESS with a hard timeout so a hung PJRT init cannot hang the bench;
transient failures retry with backoff; if no accelerator comes up the
suite still runs on CPU and the JSON records "platform": "cpu". A total
init failure emits a diagnosable JSON line with an "error" field, never
a bare traceback.

Env knobs: SDOT_BENCH_SF (default 1.0), SDOT_BENCH_REPS (default 5),
SDOT_BENCH_QUERIES (comma list, default all 22), SDOT_BENCH_PLATFORM
(force: axon|tpu|cpu, skips probing), SDOT_BENCH_PROBE_TIMEOUT (seconds,
default 180). Per-query detail goes to stderr; stdout carries only the
JSON line.
"""

import json
import os
import subprocess
import sys
import time

import numpy as np


def log(msg):
    print(msg, file=sys.stderr, flush=True)


# -----------------------------------------------------------------------------
# backend selection (owns platform choice; see module docstring)
# -----------------------------------------------------------------------------

_PROBE_SRC = r"""
import json, sys
plat = sys.argv[1]
try:
    import jax
    jax.config.update("jax_platforms", plat)
    devs = jax.devices()
    import jax.numpy as jnp
    x = jnp.arange(8)
    assert int(x.sum()) == 28
    print(json.dumps({"ok": True, "platform": jax.default_backend(),
                      "n_devices": len(devs),
                      "device0": str(devs[0])}))
except Exception as e:
    print(json.dumps({"ok": False, "error_type": type(e).__name__,
                      "error": str(e)[:1000]}))
"""


def _probe_platform(plat: str, timeout_s: float):
    """Try to init `plat` in a subprocess (a hung PJRT init can't hang us).
    Returns (ok, info_dict)."""
    t0 = time.perf_counter()
    try:
        r = subprocess.run([sys.executable, "-c", _PROBE_SRC, plat],
                           capture_output=True, text=True,
                           timeout=timeout_s)
    except subprocess.TimeoutExpired:
        return False, {"error_type": "Timeout",
                       "error": f"backend '{plat}' init exceeded "
                                f"{timeout_s:.0f}s"}
    dt = time.perf_counter() - t0
    line = (r.stdout.strip().splitlines() or [""])[-1]
    try:
        info = json.loads(line)
    except (json.JSONDecodeError, ValueError):
        info = {"ok": False, "error_type": "ProbeCrash",
                "error": (r.stderr or r.stdout)[-1000:]}
    info["init_seconds"] = round(dt, 1)
    return bool(info.get("ok")), info


def select_platform():
    """Pick the JAX platform for this run. Returns (platform, diagnostics).

    Order: SDOT_BENCH_PLATFORM override -> axon (the tunneled-TPU plugin,
    retried with backoff: UNAVAILABLE can be transient while the relay
    attaches) -> tpu -> cpu. Never raises."""
    diags = []
    forced = os.environ.get("SDOT_BENCH_PLATFORM", "").strip()
    try:
        timeout_s = float(os.environ.get("SDOT_BENCH_PROBE_TIMEOUT", "180"))
    except ValueError:
        timeout_s = 180.0
    if forced:
        log(f"platform forced to '{forced}' via SDOT_BENCH_PLATFORM")
        return forced, diags

    # always probe axon: the plugin self-registers via sitecustomize even
    # when JAX_PLATFORMS is unset, and an absent plugin fails fast
    candidates = [("axon", 3), ("tpu", 2), ("cpu", 1)]
    backoffs = [10.0, 30.0]
    for plat, tries in candidates:
        for attempt in range(tries):
            ok, info = _probe_platform(plat, timeout_s)
            info["platform_tried"] = plat
            info["attempt"] = attempt + 1
            diags.append(info)
            if ok:
                log(f"platform '{plat}' up in {info['init_seconds']}s: "
                    f"{info.get('n_devices')}x {info.get('device0')}")
                return plat, diags
            log(f"platform '{plat}' attempt {attempt + 1}/{tries} failed "
                f"({info.get('error_type')}): "
                f"{str(info.get('error'))[:200]}")
            transient = ("UNAVAILABLE" in str(info.get("error", ""))
                         or info.get("error_type") == "Timeout")
            if attempt + 1 < tries and transient:
                wait = backoffs[min(attempt, len(backoffs) - 1)]
                log(f"  retrying '{plat}' in {wait:.0f}s")
                time.sleep(wait)
            elif not transient:
                break
    return None, diags


# reference Druid avg ms, TPC-H SF10 (BASELINE.md table 1)
BASELINE_MS = {"q1": 18340.0, "q3": 10669.0, "q5": 16722.0,
               "q7": 862.0, "q8": 20429.0}
BASELINE_ROWS = 59_986_052

DROP_COLS = [
    "l_comment", "o_comment", "c_comment", "s_comment", "ps_comment",
    "cn_comment", "cr_comment", "sn_comment", "sr_comment",
    "c_address", "s_address", "o_clerk",
]

ALL22 = ["q1", "q2", "q3", "q4", "q5", "q6", "q7", "q8", "q9", "q10",
         "q11", "q12", "q13", "q14", "q15", "q16", "q17", "q18", "q19",
         "q20", "q21", "q22"]


def cache_dir():
    d = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     ".bench_cache")
    os.makedirs(d, exist_ok=True)
    return d


def _stream_sf() -> float:
    """Scale factor at/above which the flat index is built and ingested
    out-of-core (chunked flatten to Parquet + row-group streaming ingest)
    instead of through whole-frame pandas."""
    return float(os.environ.get("SDOT_BENCH_STREAM_SF", "3"))


def build_tables(sf: float):
    """Generate (or load cached) base tables + the flat index.

    Returns (tables, flat_df_or_None, flat_path, n_flat_rows): at/above
    SDOT_BENCH_STREAM_SF the flat index exists only as a Parquet file
    (flat_df is None) — the out-of-core regime.
    """
    import pandas as pd
    from spark_druid_olap_tpu.tools import tpch
    d = cache_dir()
    names = ["lineitem", "orders", "partsupp", "part", "supplier",
             "customer", "nation", "region"]
    paths = {n: os.path.join(d, f"tpch_{n}_sf{sf}.parquet") for n in names}
    flat_path = os.path.join(d, f"tpch_flat_sf{sf}.parquet")
    streaming = sf >= _stream_sf()
    if all(os.path.exists(p) for p in paths.values()) and \
            os.path.exists(flat_path):
        log(f"loading cached tables from {d}")
        tables = {n: pd.read_parquet(p) for n, p in paths.items()}
        if streaming:
            import pyarrow.parquet as pq
            n_flat = pq.ParquetFile(flat_path).metadata.num_rows
            return tables, None, flat_path, n_flat
        flat = pd.read_parquet(flat_path)
        return tables, flat, flat_path, len(flat)
    t0 = time.perf_counter()
    tables = tpch.generate(sf)
    log(f"generated SF{sf}: lineitem {len(tables['lineitem']):,} rows "
        f"in {time.perf_counter() - t0:.1f}s")
    li_path = paths["lineitem"]
    try:
        for n, p in paths.items():
            tables[n].to_parquet(p)
    except Exception as e:
        log(f"cache write failed ({e}); continuing")
        if streaming:
            # the streamed flatten reads lineitem back from Parquet; a
            # failed/partial cache write must not be silently reused
            import tempfile
            li_path = os.path.join(tempfile.mkdtemp(prefix="sdot_li_"),
                                   "lineitem.parquet")
            tables["lineitem"].to_parquet(li_path)
    if streaming:
        t0 = time.perf_counter()
        n_flat = tpch.flatten_stream(tables, li_path, flat_path,
                                     batch_rows=1 << 21,
                                     drop_columns=DROP_COLS)
        log(f"streamed flatten: {n_flat:,} rows in "
            f"{time.perf_counter() - t0:.1f}s")
        return tables, None, flat_path, n_flat
    flat = tpch.flatten(tables)
    flat = flat.drop(columns=[c for c in DROP_COLS if c in flat.columns])
    try:
        flat.to_parquet(flat_path)
    except Exception as e:
        log(f"cache write failed ({e}); continuing")
    return tables, flat, flat_path, len(flat)


def _bench_config():
    """Session config for MEASURED contexts: the semantic result cache and
    the compiled-statement (plan/cplan) caches would serve warm reps from
    memory, so the reported latency would measure the cache, not the
    engine. Set ONCE at context creation — toggling mid-run would change
    the config fingerprint and thrash the session result caches."""
    return {"sdot.cache.enabled": False,
            "sdot.plan.cache.enabled": False}


def setup(sf: float):
    import spark_druid_olap_tpu as sdot
    from spark_druid_olap_tpu.tools import tpch
    tables, flat, flat_path, n_rows = build_tables(sf)
    ctx = sdot.Context(_bench_config())
    t0 = time.perf_counter()
    if flat is None:
        ctx.ingest_parquet_stream("tpch_flat", flat_path,
                                  time_column="l_shipdate",
                                  target_rows=1 << 20,
                                  batch_rows=1 << 21)
    else:
        ctx.ingest_dataframe("tpch_flat", flat, time_column="l_shipdate",
                             target_rows=1 << 20)
    del flat
    for name, df in tables.items():
        if name in ("nation", "region"):
            continue
        tcol = {"lineitem": "l_shipdate", "orders": "o_orderdate"}.get(name)
        ctx.ingest_dataframe(name, df, time_column=tcol, target_rows=1 << 20)
    for name, df in tpch.nation_region_views(tables).items():
        ctx.ingest_dataframe(name, df)
    # second star at partsupp grain (q2/q11/q16/q20-class pushdown)
    ctx.ingest_dataframe("partsupp_flat", tpch.flatten_partsupp(tables),
                         target_rows=1 << 20)
    ctx.register_star_schema(tpch.partsupp_star_schema("partsupp_flat"))
    ctx.register_star_schema(tpch.star_schema("tpch_flat"))
    log(f"ingest: {time.perf_counter() - t0:.1f}s "
        f"({ctx.store.get('tpch_flat').num_segments} flat segments)")
    return ctx, n_rows


def measure_floor(ctx, reps: int) -> float:
    """Fixed per-dispatch overhead: a compiled trivial device query, timed
    end-to-end (dominated by the host<->device round trip)."""
    q = ("select count(*) as c from supplier where s_suppkey = 1"
         if "supplier" in ctx.store.names()
         else "select count(*) as c from lineorder where lo_orderkey = 1")
    ctx.sql(q)
    ts = []
    for _ in range(max(reps, 5)):
        t0 = time.perf_counter()
        ctx.sql(q)
        ts.append(time.perf_counter() - t0)
    floor = float(np.median(ts)) * 1000
    log(f"dispatch floor: {floor:.1f}ms")
    return floor


def setup_ssb(sf: float):
    """SSB suite (SDOT_BENCH_SUITE=ssb): 13 star-join queries on the
    denormalized lineorder index (BASELINE config 3). At/above
    SDOT_BENCH_STREAM_SF (SF30 = 180M rows) the lineorder fact and the
    flat index are generated and ingested out-of-core, cached in
    .bench_cache like the TPC-H SF10 path."""
    import spark_druid_olap_tpu as sdot
    from spark_druid_olap_tpu.tools import ssb
    ctx = sdot.Context(_bench_config())
    t0 = time.perf_counter()
    if sf >= _stream_sf():
        import pandas as pd
        import pyarrow.parquet as pq
        d = cache_dir()
        lo_path = os.path.join(d, f"ssb_lineorder_sf{sf}.parquet")
        flat_path = os.path.join(d, f"ssb_flat_sf{sf}.parquet")
        dim_names = ["date", "customer", "supplier", "part"]
        dim_paths = {n: os.path.join(d, f"ssb_{n}_sf{sf}.parquet")
                     for n in dim_names}
        cached = all(os.path.exists(p) for p in
                     [flat_path, *dim_paths.values()])
        if cached:
            log(f"loading cached SSB SF{sf} from {d}")
            dims = {n: pd.read_parquet(p) for n, p in dim_paths.items()}
        else:
            dims, n_lo = ssb.generate_stream(sf, lo_path)
            log(f"ssb SF{sf}: streamed {n_lo:,} lineorder rows in "
                f"{time.perf_counter() - t0:.1f}s")
            t1 = time.perf_counter()
            n_flat = ssb.flatten_stream(dims, lo_path, flat_path,
                                        batch_rows=1 << 21)
            log(f"streamed flatten: {n_flat:,} rows in "
                f"{time.perf_counter() - t1:.1f}s")
            try:
                for n, p in dim_paths.items():
                    dims[n].to_parquet(p)
            except Exception as e:   # noqa: BLE001
                log(f"dim cache write failed ({e}); continuing")
        n = pq.ParquetFile(flat_path).metadata.num_rows
        ctx.ingest_parquet_stream("ssb_flat", flat_path,
                                  time_column="lo_orderdate",
                                  target_rows=1 << 20,
                                  batch_rows=1 << 21)
        # base lineorder (raw 6M*sf fact) is NOT ingested in the
        # out-of-core regime: all 13 SSB queries are star joins that
        # collapse onto the flat index (bench asserts mode=engine)
        for name, df in dims.items():
            ctx.ingest_dataframe(name, df, target_rows=1 << 20)
        ctx.register_star_schema(ssb.star_schema("ssb_flat"))
    else:
        tables, flat = ssb.setup_context(ctx, sf=sf, target_rows=1 << 20)
        n = len(flat)
    log(f"ssb SF{sf}: {n:,} lineorder rows, ingest+gen "
        f"{time.perf_counter() - t0:.1f}s")
    return ctx, n, ssb.QUERIES


def metric_name(suite, sf):
    return f"{suite}_sf{sf}_geomean_latency_ms"


def fail_json(suite, sf, reason, diags):
    """Emit a diagnosable JSON line (rc=0) instead of a traceback."""
    out = {
        "metric": metric_name(suite, sf),
        "value": None,
        "unit": "ms",
        "vs_baseline": 0.0,
        "error": reason,
        "probe_diagnostics": diags[-6:],
    }
    print(json.dumps(out), flush=True)


def numerics_check():
    """Integer-exactness differential check on the LIVE backend — proves the
    lane/limb aggregation routes are exact under real TPU dtypes (f64
    unsupported, i64 emulated): values past the f32 2^24 cliff, sums past
    2^32. Returns (ok, detail)."""
    import pandas as pd
    import spark_druid_olap_tpu as sdot
    r = np.random.default_rng(5)
    n = 200_000
    df = pd.DataFrame({
        "g": r.choice(["a", "b", "c"], n),
        "big": (r.integers(0, 1 << 30, n) + (1 << 24)).astype(np.int64),
        "sgn": r.integers(-(1 << 26), 1 << 26, n).astype(np.int64),
    })
    ctx = sdot.Context()
    ctx.ingest_dataframe("numcheck", df, target_rows=1 << 16)
    res = ctx.sql(
        "select g, sum(big) as sb, sum(sgn) as ss, min(big) as mb, "
        "max(big) as xb, count(*) as n from numcheck group by g"
    ).to_pandas().sort_values("g").reset_index(drop=True)
    mode = ctx.history.entries()[-1].stats.get("mode", "?")
    gb = df.groupby("g")
    want = pd.DataFrame({
        "sb": gb["big"].sum(), "ss": gb["sgn"].sum(),
        "mb": gb["big"].min(), "xb": gb["big"].max(), "n": gb.size(),
    }).reset_index()
    for c in ("sb", "ss", "mb", "xb", "n"):
        got = res[c].to_numpy().astype(np.int64)
        if not np.array_equal(got, want[c].to_numpy()):
            return False, f"{c}: got {got.tolist()} " \
                          f"want {want[c].tolist()} (mode={mode})"

    # device result-reduction epilogues on the LIVE backend: top-k
    # selection, HAVING compaction, correlated-lookup broadcast join
    df2 = pd.DataFrame({
        "k": r.integers(0, 20_000, n),
        "q": r.integers(1, 50, n).astype(np.int64),
    })
    ctx.ingest_dataframe("epicheck", df2, target_rows=1 << 16)
    g2 = df2.groupby("k")["q"].sum()
    topk = ctx.sql("select k, sum(q) as s from epicheck group by k "
                   "order by s desc limit 5").to_pandas()
    st = ctx.history.entries()[-1].stats
    want_top = g2.sort_values(ascending=False).head(5).to_numpy()
    if not np.array_equal(topk["s"].to_numpy().astype(np.int64), want_top):
        return False, f"topk: got {topk['s'].tolist()} " \
                      f"want {want_top.tolist()}"
    if not st.get("topk_device"):
        return False, f"topk epilogue did not engage ({st})"
    hav = ctx.sql("select k, sum(q) as s from epicheck group by k "
                  "having sum(q) > 400").to_pandas()
    want_h = g2[g2 > 400]
    if len(hav) != len(want_h) or \
            not np.array_equal(np.sort(hav["s"].to_numpy().astype(np.int64)),
                               np.sort(want_h.to_numpy())):
        return False, f"having: {len(hav)} rows want {len(want_h)}"
    corr = ctx.sql(
        "select count(*) as n from epicheck "
        "where q < (select 0.5 * avg(i_q) from "
        "  (select k as i_k, q as i_q from epicheck) i "
        "   where i_k = k)").to_pandas()
    thr = df2.groupby("k")["q"].mean() * 0.5
    want_c = int((df2.q < df2.k.map(thr)).sum())
    if int(corr["n"][0]) != want_c:
        return False, f"lookup: got {int(corr['n'][0])} want {want_c}"
    return True, f"exact incl. topk/having/lookup epilogues (mode={mode})"


def run_pallas_ab(reps: int = 3):
    """Pallas wave A-B on a canned 4-lane shared-scan storm.

    Runs the same fused wave through the jaxpr path (wave off) and the
    hand-scheduled pallas kernel (wave on), differentially checks the
    answers, and reports per-leg wall ms plus the wave counter deltas.
    Storms need concurrent queries, so this uses a small dedicated store
    rather than the suite context. On a plain-CPU backend without
    SDOT_PALLAS=interpret the wave never engages — records
    {"available": False}. In interpret mode the ON leg runs the kernel
    through the pallas interpreter (a correctness vehicle, not a fast
    one), so "speedup" below 1 there is expected and the "interpret"
    flag says so.
    """
    import threading

    from spark_druid_olap_tpu.ops import pallas_groupby as PG
    if not (os.environ.get("SDOT_PALLAS", "") == "interpret"
            or PG._tpu_backend()):
        return {"available": False}

    import pandas as pd
    from spark_druid_olap_tpu.ir import spec as S
    from spark_druid_olap_tpu.parallel.executor import QueryEngine
    from spark_druid_olap_tpu.segment.ingest import ingest_dataframe
    from spark_druid_olap_tpu.segment.store import SegmentStore
    from spark_druid_olap_tpu.utils.config import Config

    rng = np.random.default_rng(7)
    n = 40_000
    df = pd.DataFrame({
        "ts": pd.Timestamp("2015-01-01")
        + pd.to_timedelta(rng.integers(0, 365 * 24 * 3600, n), unit="s"),
        "region": rng.choice(["east", "west", "north", "south"], n),
        "product": rng.choice([f"p{i:03d}" for i in range(50)], n),
        "status": rng.choice(["O", "F"], n),
        "qty": rng.integers(1, 52, n).astype(np.int64),
        "price": rng.uniform(1.0, 100.0, n),
    })
    store = SegmentStore()
    store.register(ingest_dataframe("sales", df, time_column="ts",
                                    target_rows=4096))
    aggs = (S.AggregationSpec("doublesum", "revenue", field="price"),
            S.AggregationSpec("longsum", "units", field="qty"),
            S.AggregationSpec("count", "n"))
    shared = S.SelectorFilter("status", "O")
    specs = [
        S.GroupByQuerySpec("sales", (S.DimensionSpec("region", "region"),),
                           aggs, filter=shared),
        S.GroupByQuerySpec(
            "sales", (S.DimensionSpec("region", "region"),), aggs,
            filter=S.LogicalFilter("and", (
                shared, S.BoundFilter("qty", lower=10, numeric=True)))),
        S.TimeseriesQuerySpec("sales", aggs,
                              granularity=S.Granularity("month"),
                              filter=shared),
        S.TopNQuerySpec("sales", S.DimensionSpec("product", "product"),
                        "revenue", 7, aggs, filter=shared),
    ]
    eng = QueryEngine(store, config=Config({
        "sdot.sharedscan.enabled": True,
        "sdot.wlm.batch.window.ms": 500.0,
        "sdot.wlm.enabled": False,
        "sdot.pallas.wave.enabled": False,
    }))

    def run_batch():
        res = [None] * len(specs)
        errs = [None] * len(specs)
        bar = threading.Barrier(len(specs))

        def worker(i):
            bar.wait()
            try:
                res[i] = eng.execute(specs[i]).to_pandas()
            except Exception as e:      # noqa: BLE001 — surfaced below
                errs[i] = e

        th = [threading.Thread(target=worker, args=(i,))
              for i in range(len(specs))]
        for t in th:
            t.start()
        for t in th:
            t.join()
        for e in errs:
            if e is not None:
                raise e
        return res

    def leg(wave):
        eng.config.set("sdot.pallas.wave.enabled", bool(wave))
        p0 = eng.sharedscan.stats()["pallas"]
        run_batch()                     # warm: compile this leg's program
        frames, ts = None, []
        for _ in range(max(reps, 1)):
            t0 = time.perf_counter()
            frames = run_batch()
            ts.append(time.perf_counter() - t0)
        p1 = eng.sharedscan.stats()["pallas"]
        delta = {k: int(p1[k]) - int(p0[k])
                 for k in ("launches", "tiles", "fallbacks")}
        return frames, float(np.median(ts)) * 1000, delta

    off_frames, off_ms, off_delta = leg(False)
    on_frames, on_ms, on_delta = leg(True)

    match = True
    for a, b in zip(off_frames, on_frames):
        aa = a.reset_index(drop=True)
        bb = b.reset_index(drop=True)
        if list(aa.columns) != list(bb.columns) or len(aa) != len(bb):
            match = False
            continue
        for c in aa.columns:
            av, bv = aa[c].to_numpy(), bb[c].to_numpy()
            if av.dtype.kind in "fc":
                if not np.allclose(av.astype(float), bv.astype(float),
                                   rtol=1e-4, atol=1e-8, equal_nan=True):
                    match = False
            elif not np.array_equal(av, bv):
                match = False
    out = {"available": True, "lanes": len(specs),
           "interpret": bool(PG._interpret()),
           "off_ms": round(off_ms, 2), "on_ms": round(on_ms, 2),
           "speedup": round(off_ms / max(on_ms, 1e-9), 3),
           "pallas_off": off_delta, "pallas_on": on_delta,
           "answers_match": bool(match)}
    log(f"pallas A-B: off {off_ms:.1f}ms / on {on_ms:.1f}ms "
        f"(x{out['speedup']}, launches {on_delta['launches']}, "
        f"match={match})")
    return out


def run_mesh_ab(reps: int = 3):
    """Multi-chip mesh A-B: the same fused shared-scan storms at every
    power-of-two device count the process exposes.

    Two canned storms over a TPC-H flat subset run coalesced at
    n ∈ {1, 2, 4, 8} devices (1 = no mesh, the single-device baseline;
    the cost model is off so the mesh decision is unconditional).
    Reports per-device-count median wall ms and the geomean over the
    storm shapes, the merge-collective bytes the mesh tier statically
    accounts (ring convention: merged payload x (n-1) x waves), mesh
    dispatch counters, and an answers-match gate against the 1-device
    leg. On a real pod this measures ICI scaling; under
    ``--xla_force_host_platform_device_count=8`` (the CI recipe in
    docs/MESH.md) the wall numbers measure host-core contention, not
    interconnect — the accounting + match gate are the pinned part.
    """
    import threading

    import jax

    counts = [n for n in (1, 2, 4, 8) if n <= len(jax.devices())]
    if not counts or counts[-1] < 2:
        return {"available": False,
                "reason": "single-device process; set XLA_FLAGS="
                          "--xla_force_host_platform_device_count=8"}

    from spark_druid_olap_tpu.ir import spec as S
    from spark_druid_olap_tpu.parallel.executor import QueryEngine
    from spark_druid_olap_tpu.parallel.mesh import make_mesh
    from spark_druid_olap_tpu.tools import tpch
    from spark_druid_olap_tpu.utils.config import Config

    sf = float(os.environ.get("SDOT_BENCH_MESH_SF", "0.01"))
    import spark_druid_olap_tpu as sdot
    ctx = sdot.Context()
    tpch.setup_context(ctx, sf=sf, target_rows=2048, flat_only=True)
    store = ctx.store

    aggs = (S.AggregationSpec("doublesum", "rev", field="l_extendedprice"),
            S.AggregationSpec("longsum", "q", field="l_quantity"),
            S.AggregationSpec("count", "n"),
            S.AggregationSpec("doublemax", "mx", field="l_extendedprice"))
    storms = {
        "flag_status": [
            S.GroupByQuerySpec(
                "tpch_flat",
                (S.DimensionSpec("l_returnflag", "l_returnflag"),
                 S.DimensionSpec("l_linestatus", "l_linestatus")), aggs),
            S.GroupByQuerySpec(
                "tpch_flat", (S.DimensionSpec("l_shipmode", "l_shipmode"),),
                aggs, filter=S.SelectorFilter("l_returnflag", "N")),
            S.TimeseriesQuerySpec("tpch_flat", aggs,
                                  granularity=S.Granularity("month")),
        ],
        "sketch_mix": [
            S.GroupByQuerySpec(
                "tpch_flat", (S.DimensionSpec("l_shipmode", "l_shipmode"),),
                aggs + (S.AggregationSpec("cardinality", "uo",
                                          field="l_orderkey"),)),
            S.GroupByQuerySpec(
                "tpch_flat",
                (S.DimensionSpec("l_returnflag", "l_returnflag"),),
                aggs + (S.AggregationSpec("thetasketch", "sk",
                                          field="l_suppkey"),)),
        ],
    }

    def run_batch(eng, specs):
        res = [None] * len(specs)
        errs = [None] * len(specs)
        bar = threading.Barrier(len(specs))

        def worker(i):
            bar.wait()
            try:
                res[i] = eng.execute(specs[i]).to_pandas()
            except Exception as e:      # noqa: BLE001 — surfaced below
                errs[i] = e

        th = [threading.Thread(target=worker, args=(i,))
              for i in range(len(specs))]
        for t in th:
            t.start()
        for t in th:
            t.join()
        for e in errs:
            if e is not None:
                raise e
        return res

    def leg(n):
        eng = QueryEngine(store, config=Config({
            "sdot.sharedscan.enabled": True,
            "sdot.wlm.batch.window.ms": 500.0,
            "sdot.wlm.enabled": False,
            "sdot.querycostmodel.enabled": False,
        }), mesh=make_mesh(n) if n > 1 else None)
        frames, storm_ms = {}, {}
        for name, specs in storms.items():
            run_batch(eng, specs)       # warm: compile this leg's program
            ts = []
            for _ in range(max(reps, 1)):
                t0 = time.perf_counter()
                frames[name] = run_batch(eng, specs)
                ts.append(time.perf_counter() - t0)
            storm_ms[name] = float(np.median(ts)) * 1000
        mst = eng.sharedscan.stats()["mesh"]
        gm = float(np.exp(np.mean([np.log(max(v, 1e-9))
                                   for v in storm_ms.values()])))
        return frames, {
            "geomean_ms": round(gm, 2),
            "storm_ms": {k: round(v, 2) for k, v in storm_ms.items()},
            "collective_bytes": int(mst["collective_bytes"]),
            "mesh_dispatches": int(mst["dispatches"]),
            "mesh_groups": int(mst["groups"]),
            "fallbacks": dict(mst["fallbacks"]),
        }

    def frames_match(a, b):
        aa = a.reset_index(drop=True)
        bb = b.reset_index(drop=True)
        if list(aa.columns) != list(bb.columns) or len(aa) != len(bb):
            return False
        for c in aa.columns:
            av, bv = aa[c].to_numpy(), bb[c].to_numpy()
            if av.dtype.kind in "fc":
                if not np.allclose(av.astype(float), bv.astype(float),
                                   rtol=1e-9, atol=1e-12, equal_nan=True):
                    return False
            elif not np.array_equal(av, bv):
                return False
        return True

    base_frames, legs = None, {}
    match = True
    for n in counts:
        frames, stats = leg(n)
        legs[str(n)] = stats
        if base_frames is None:
            base_frames = frames
        else:
            for name in storms:
                for a, b in zip(base_frames[name], frames[name]):
                    match = match and frames_match(a, b)
    gm1 = legs[str(counts[0])]["geomean_ms"]
    gmN = legs[str(counts[-1])]["geomean_ms"]
    out = {"available": True, "device_counts": counts, "legs": legs,
           "scaling_vs_single": round(gm1 / max(gmN, 1e-9), 3),
           "answers_match": bool(match)}
    curve = ", ".join("%ddev %sms" % (n, legs[str(n)]["geomean_ms"])
                      for n in counts)
    log(f"mesh A-B: {curve} (x{out['scaling_vs_single']} at {counts[-1]} "
        f"devices, collective "
        f"{legs[str(counts[-1])]['collective_bytes']}B, match={match})")
    return out


def run_join_ab(reps: int = 3):
    """Device-join-tier A-B over star-unservable queries (join/).

    Three shapes the star rewrite cannot collapse onto the flat fact
    index — a fact-to-fact join, a self-join funnel, and an equi plus
    non-equi range join — run through the broadcast join tier and then
    through the host pandas tier over the SAME stores
    (``sdot.join.enabled`` toggled; the config fingerprint keys every
    cache, so both legs execute for real). Reports per-query median
    wall ms for both legs, the tier's own accounting (mode, build
    bytes, static match width, shuffle bytes), and two gates: every
    query must actually engage the tier (``last_stats["join"]``
    present — a silent host fallback would "pass" while measuring
    nothing) and must answer exactly like the host. The gates are the
    pinned part; on the CPU fallback backend the wall numbers measure
    host-core speed, not device bandwidth.
    """
    import pandas as pd

    import spark_druid_olap_tpu as sdot
    from spark_druid_olap_tpu.utils.config import JOIN_ENABLED

    rng = np.random.default_rng(18)
    n = int(os.environ.get("SDOT_BENCH_JOIN_ROWS", "20000"))
    regions = ["na", "emea", "apac", "latam"]
    orders = pd.DataFrame({
        "ts": (np.datetime64("2024-03-01")
               + rng.integers(0, 90, n).astype("timedelta64[D]")
               ).astype("datetime64[ns]"),
        "order_id": np.arange(n, dtype=np.int64),
        # ~5 orders per user: the self-join's widest build group stays
        # far under the default sdot.join.max.matches budget
        "user_id": rng.integers(0, max(n // 5, 1), n).astype(np.int64),
        "region": rng.choice(regions, n),
        "channel": rng.choice(["web", "app", "store"], n),
        "amount": rng.normal(80, 30, n).round(2),
    })
    m = n // 3
    shipments = pd.DataFrame({
        "ts": (np.datetime64("2024-03-02")
               + rng.integers(0, 90, m).astype("timedelta64[D]")
               ).astype("datetime64[ns]"),
        # duplicate order_ids: some orders ship in several parcels
        "order_id": rng.integers(0, n, m).astype(np.int64),
        "carrier": rng.choice(["ups", "dhl", "fedex", "ems"], m),
        "weight": rng.normal(4.0, 1.5, m).round(3),
    })
    bands = list(zip([-1e9, 25.0, 50.0, 75.0, 100.0, 150.0],
                     [25.0, 50.0, 75.0, 100.0, 150.0, 1e9]))
    rates = pd.DataFrame([
        {"ts": pd.Timestamp("2024-03-01"), "region": rg,
         "band": "b%d" % i, "lo": lo, "hi": hi}
        for rg in regions for i, (lo, hi) in enumerate(bands)])

    queries = {
        # fact-to-fact: both sides are event tables, no star edge
        "fact_to_fact": """
            SELECT s.carrier AS c, count(*) AS n, sum(o.amount) AS amt
            FROM orders o JOIN shipments s ON o.order_id = s.order_id
            GROUP BY s.carrier ORDER BY c""",
        # self-join funnel: pairs of orders by the same user where the
        # second is bigger (alias scoping rewrites the legs)
        "self_join_funnel": """
            SELECT a.channel AS c, count(*) AS n
            FROM orders a JOIN orders b
              ON a.user_id = b.user_id AND a.amount < b.amount
            GROUP BY a.channel ORDER BY c""",
        # equi key (region) + non-equi range residual (amount banding)
        "non_equi_range": """
            SELECT r.band AS b, count(*) AS n, sum(o.amount) AS amt
            FROM orders o JOIN rates r
              ON o.region = r.region
             AND o.amount >= r.lo AND o.amount < r.hi
            GROUP BY r.band ORDER BY b""",
    }

    ctx = sdot.Context()
    try:
        ctx.ingest_dataframe("orders", orders, time_column="ts",
                             target_rows=2048)
        ctx.ingest_dataframe("shipments", shipments, time_column="ts",
                             target_rows=1024)
        ctx.ingest_dataframe("rates", rates, time_column="ts",
                             target_rows=64)

        def timed(q):
            ctx.sql(q)                    # warm: compile this leg
            ts = []
            df = None
            for _ in range(max(reps, 1)):
                t0 = time.perf_counter()
                df = ctx.sql(q).to_pandas()
                ts.append(time.perf_counter() - t0)
            return df, float(np.median(ts)) * 1000

        def frames_match(a, b):
            # float tolerance matches the repo's differential comparator
            # (tests/conftest.assert_frames_equal): metrics are stored
            # f32, so device accumulation order differs from the host's
            # f64 pandas sums at ~1e-5 relative on non-x64 backends
            aa = a.reset_index(drop=True)
            bb = b.reset_index(drop=True)
            if list(aa.columns) != list(bb.columns) or len(aa) != len(bb):
                return False
            for c in aa.columns:
                av, bv = aa[c].to_numpy(), bb[c].to_numpy()
                if av.dtype.kind in "fc":
                    if not np.allclose(av.astype(float), bv.astype(float),
                                       rtol=1e-4, atol=1e-6,
                                       equal_nan=True):
                        return False
                elif not np.array_equal(av, bv):
                    return False
            return True

        legs, match = {}, True
        for name, q in queries.items():
            dev, dev_ms = timed(q)
            js = dict(ctx.engine.last_stats.get("join") or {})
            ctx.config.set(JOIN_ENABLED.key, False)
            try:
                host, host_ms = timed(q)
            finally:
                ctx.config.set(JOIN_ENABLED.key, True)
            ok = frames_match(dev, host)
            engaged = bool(js)
            match = match and ok and engaged
            legs[name] = {
                "join_ms": round(dev_ms, 2),
                "host_ms": round(host_ms, 2),
                "speedup_vs_host": round(host_ms / max(dev_ms, 1e-9), 2),
                "mode": js.get("mode"),
                "build_bytes": js.get("build_bytes"),
                "match_width": js.get("match_width"),
                "shuffle_bytes": js.get("shuffle_bytes"),
                "rows": int(len(dev)),
                "tier_engaged": engaged,
                "answers_match": bool(ok),
            }
            log(f"join A-B {name}: {dev_ms:.1f}ms {js.get('mode')} vs "
                f"{host_ms:.1f}ms host (x{legs[name]['speedup_vs_host']}, "
                f"width={js.get('match_width')}, match={ok})")
    finally:
        ctx.close()
    return {"available": True, "n_rows": n, "queries": legs,
            "answers_match": bool(match)}


def run_encode_ab(reps: int = 3):
    """Encoded-vs-raw A-B over the cold tier (encode/ + tier/).

    Builds one synthetic store, checkpoints it twice — raw and with
    ``sdot.encode.enabled`` — then reopens each snapshot through the
    tiered path at the SAME byte budget and replays one aggregation
    mix. Reports the on-disk compression ratio, per-leg wall ms, the
    EFFECTIVE scan rate (LOGICAL bytes scanned per second — the encoded
    leg faults ratio× fewer physical bytes for the same logical scan),
    and the hot-set residency each leg ends with under the shared
    budget (the encoded leg should hold more segment-chunks resident).
    Differential: both legs must return identical frames.
    """
    import shutil
    import tempfile

    import pandas as pd
    import spark_druid_olap_tpu as sdot

    rng = np.random.default_rng(11)
    n = 200_000
    df = pd.DataFrame({
        "ts": pd.Timestamp("2015-01-01")
        + pd.to_timedelta(np.sort(rng.integers(0, 365 * 24 * 3600, n)),
                          unit="s"),
        "region": rng.choice(["east", "west", "north", "south"], n),
        "product": rng.choice([f"p{i:03d}" for i in range(100)], n),
        "status": rng.choice(["O", "F", "P"], n, p=[0.7, 0.2, 0.1]),
        "qty": rng.integers(1, 52, n).astype(np.int64),
        "price": rng.uniform(1.0, 100.0, n),
    })
    queries = [
        "select region, sum(price), sum(qty), count(*) from sales "
        "group by region",
        "select product, sum(price) from sales where status = 'O' "
        "group by product order by sum(price) desc limit 7",
        "select year(ts) y, month(ts) m, count(*) from sales "
        "group by year(ts), month(ts)",
    ]
    root = tempfile.mkdtemp(prefix="sdot-encab-")
    try:
        legs, frames = {}, {}
        budget = None
        for leg, enabled in (("raw", False), ("encoded", True)):
            sub = os.path.join(root, leg)
            seed = sdot.Context({"sdot.persist.path": sub,
                                 "sdot.encode.enabled": enabled})
            seed.ingest_dataframe("sales", df, time_column="ts",
                                  target_rows=8192)
            seed.checkpoint()
            col_bytes = sum(
                c["size"] for c in
                seed.store.get("sales").metadata()["columns"].values())
            seed.close()
            if budget is None:
                # sized off the RAW leg so both legs share one number:
                # raw must evict under it, encoded should mostly fit
                budget = max(1 << 20, int(col_bytes) // 3)
            ctx = sdot.Context({"sdot.persist.path": sub,
                                "sdot.cache.enabled": False,
                                "sdot.plan.cache.enabled": False,
                                "sdot.tier.enabled": True,
                                "sdot.tier.budget.bytes": budget,
                                "sdot.tier.wave.io.bytes": budget // 4})
            frames[leg] = {q: ctx.sql(q).to_pandas() for q in queries}
            ts, logical = [], 0
            for _ in range(max(reps, 1)):
                t0 = time.perf_counter()
                for q in queries:
                    ctx.sql(q)
                    st = ctx.history.entries()[-1].stats
                    logical += int(st.get("bytes_scanned", 0) or 0)
                ts.append(time.perf_counter() - t0)
            last = ctx.history.entries()[-1].stats
            tier_st = (ctx.persist.tier.stats_snapshot()
                       if ctx.persist.tier else {})
            enc_st = last.get("encoding") or {}
            ctx.close()
            ms = float(np.median(ts)) * 1000
            legs[leg] = {
                "wall_ms": round(ms, 2),
                "column_bytes": int(col_bytes),
                "bytes_faulted": int(tier_st.get("bytes_faulted", 0)),
                "hot_entries": int(tier_st.get("hot_entries", 0)),
                "hot_bytes": int(tier_st.get("hot_bytes", 0)),
                # effective = LOGICAL bytes the queries scanned per
                # second of wall; physical fault traffic is ratio× less
                # on the encoded leg
                "effective_scan_gbps": round(
                    (logical / max(len(ts), 1)) / max(ms / 1000, 1e-9)
                    / 1e9, 3),
            }
            if enc_st:
                legs[leg]["encoding"] = enc_st
        match = all(
            _frames_equal(frames["raw"][q], frames["encoded"][q])
            for q in queries)
        enc = legs["encoded"].get("encoding", {})
        out = {"available": True, "budget_bytes": int(budget),
               "ratio": enc.get("ratio"),
               "raw": legs["raw"], "encoded": legs["encoded"],
               "resident_gain": round(
                   legs["encoded"]["hot_entries"]
                   / max(legs["raw"]["hot_entries"], 1), 2),
               "answers_match": bool(match)}
        log(f"encode A-B: ratio {out['ratio']}x, raw "
            f"{legs['raw']['wall_ms']:.1f}ms / encoded "
            f"{legs['encoded']['wall_ms']:.1f}ms, resident "
            f"{legs['raw']['hot_entries']} -> "
            f"{legs['encoded']['hot_entries']} chunks (match={match})")
        return out
    finally:
        shutil.rmtree(root, ignore_errors=True)


def run_window_ab(reps: int = 3):
    """Window post-pass + KLL percentile A-B on a canned store.

    Leg 1 (windows): a storm of OVER(...) statements — ranks over a
    GROUP BY base, moving/cumulative frames and lag over a row-level
    scan base — runs through the device window post-pass, and every
    answer is differentially checked against an exact pandas
    computation of the same window. Leg 2 (percentile): each
    percentile_approx answer is gated against numpy's exact order
    statistics within the sketch's declared rank-error bound
    (sdot.quantile.rank_bound): the estimate must land between the
    exact values at rank (q - eps) and (q + eps). Both checks ship in
    the JSON as hard ok flags; timings compare the device post-pass
    wall against the exact host reference.
    """
    import pandas as pd
    from spark_druid_olap_tpu.context import Context
    from spark_druid_olap_tpu.ops import kll as KLL

    rng = np.random.default_rng(11)
    n = 30_000
    df = pd.DataFrame({
        "ts": pd.Timestamp("2015-01-01")
        + pd.to_timedelta(rng.integers(0, 365 * 24 * 3600, n), unit="s"),
        "id": np.arange(n, dtype=np.int64),   # unique ORDER BY key:
        "region": rng.choice(["east", "west", "north", "south"], n),
        "product": rng.choice([f"p{i:03d}" for i in range(20)], n),
        "qty": rng.integers(1, 52, n).astype(np.int64),
        "price": rng.uniform(1.0, 100.0, n),
    })                                        # ties would make moving
    ctx = Context({"sdot.cache.enabled": False})  # frames order-dependent
    ctx.ingest_dataframe("wsales", df, time_column="ts",
                         target_rows=4096)

    # -- exact pandas references ------------------------------------
    t0 = time.perf_counter()
    agg = (df.groupby(["region", "product"], as_index=False)
             .agg(units=("qty", "sum")))
    agg["r"] = (agg.groupby("region")["units"]
                .rank(method="min", ascending=False).astype(np.int64))
    flt = (df[df["qty"] > 25].sort_values(["region", "id"],
                                          kind="mergesort"))
    mv = flt[["id", "region", "qty"]].copy()
    mv["mv"] = (flt.groupby("region")["qty"]
                .rolling(4, min_periods=1).sum()
                .reset_index(level=0, drop=True)).astype(np.int64)
    head = df[df["id"] < 2000].sort_values(["region", "id"],
                                           kind="mergesort")
    lg = head[["id", "region", "price"]].copy()
    lg["prev"] = head.groupby("region")["price"].shift(1)
    cum = head[["id", "region"]].copy()
    cum["cavg"] = (head.groupby("region")["price"]
                   .expanding().mean().reset_index(level=0, drop=True))
    cum["rn"] = (head.groupby("region").cumcount() + 1).astype(np.int64)
    host_ms = (time.perf_counter() - t0) * 1000

    storm = [
        ("rank_over_groupby",
         "SELECT region, product, SUM(qty) AS units, "
         "RANK() OVER (PARTITION BY region ORDER BY SUM(qty) DESC) AS r "
         "FROM wsales GROUP BY region, product", agg),
        ("moving_sum_scan",
         "SELECT id, region, qty, SUM(qty) OVER (PARTITION BY region "
         "ORDER BY id ROWS BETWEEN 3 PRECEDING AND CURRENT ROW) AS mv "
         "FROM wsales WHERE qty > 25", mv),
        ("lag_scan",
         "SELECT id, region, price, LAG(price, 1) OVER "
         "(PARTITION BY region ORDER BY id) AS prev "
         "FROM wsales WHERE id < 2000", lg),
        ("cumulative_avg_rownum",
         "SELECT id, region, AVG(price) OVER (PARTITION BY region "
         "ORDER BY id) AS cavg, ROW_NUMBER() OVER "
         "(PARTITION BY region ORDER BY id) AS rn "
         "FROM wsales WHERE id < 2000", cum),
    ]

    mismatches = []
    for name, sql, ref in storm:            # cold + differential pass
        got = ctx.sql(sql).to_pandas()
        stats = ctx.history.entries()[-1].stats
        if "window" not in stats:
            mismatches.append(f"{name}: window post-pass did not engage "
                              f"(mode={stats.get('mode')})")
        elif not _frames_equal(got, ref.reset_index(drop=True)):
            mismatches.append(name)
    ts = []
    for _ in range(max(reps, 1)):           # warm: post-pass wall
        t0 = time.perf_counter()
        for _, sql, _ref in storm:
            ctx.sql(sql)
        ts.append(time.perf_counter() - t0)
    window_ms = float(np.median(ts)) * 1000

    # -- percentile leg: KLL vs exact order statistics ---------------
    eps = KLL.rank_bound(ctx.config)
    pct_fail = []
    for q in (0.5, 0.9):
        got = ctx.sql(
            f"SELECT region, PERCENTILE_APPROX(price, {q}) AS p "
            f"FROM wsales GROUP BY region").to_pandas()
        for _, row in got.iterrows():
            vals = np.sort(df.loc[df["region"] == row["region"],
                                  "price"].to_numpy())
            lo = vals[max(int(np.floor((q - eps) * len(vals))), 0)]
            hi = vals[min(int(np.ceil((q + eps) * len(vals))),
                          len(vals) - 1)]
            if not (lo <= float(row["p"]) <= hi):
                pct_fail.append(f"{row['region']}@q{q}: {row['p']:.4f} "
                                f"outside [{lo:.4f}, {hi:.4f}]")

    out = {"available": True, "n_rows": n, "n_statements": len(storm),
           "window_ms": round(window_ms, 2),
           "host_ref_ms": round(host_ms, 2),
           "windows_match": not mismatches,
           "percentile_rank_bound": eps,
           "percentile_within_bound": not pct_fail}
    if mismatches:
        out["window_mismatches"] = mismatches
    if pct_fail:
        out["percentile_failures"] = pct_fail
    log(f"window A-B: {len(storm)} statements {window_ms:.1f}ms device "
        f"post-pass vs {host_ms:.1f}ms host ref "
        f"(match={not mismatches}, percentile_ok={not pct_fail})")
    return out


def _frames_equal(a, b) -> bool:
    """Order-insensitive equality with float tolerance (shared by the
    encode A-B differential)."""
    cols = sorted(a.columns)
    if cols != sorted(b.columns) or len(a) != len(b):
        return False
    a = a[cols].sort_values(cols).reset_index(drop=True)
    b = b[cols].sort_values(cols).reset_index(drop=True)
    for c in cols:
        av, bv = a[c].to_numpy(), b[c].to_numpy()
        if av.dtype.kind in "fc":
            if not np.allclose(av.astype(float), bv.astype(float),
                               rtol=1e-4, atol=1e-8, equal_nan=True):
                return False
        elif not np.array_equal(av, bv):
            return False
    return True


def main():
    sf = float(os.environ.get("SDOT_BENCH_SF", "1.0"))
    reps = int(os.environ.get("SDOT_BENCH_REPS", "5"))
    suite = os.environ.get("SDOT_BENCH_SUITE", "tpch")
    qsel = os.environ.get("SDOT_BENCH_QUERIES", "")

    platform, diags = select_platform()
    if platform is None:
        fail_json(suite, sf, "no JAX backend initialized (axon/tpu/cpu "
                  "all failed; see probe_diagnostics)", diags)
        return

    import jax
    try:
        jax.config.update("jax_platforms", platform)
        # persistent XLA compilation cache: the 22-query suite front-loads
        # ~40 distinct programs at tens of seconds each; across bench runs
        # (and the probe subprocess) warm compiles come back in ms.
        # Best-effort: a cache failure must never abort the bench.
        try:
            cache = os.path.join(cache_dir(), "xla_cache")
            os.makedirs(cache, exist_ok=True)
            jax.config.update("jax_compilation_cache_dir", cache)
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs", 1.0)
            jax.config.update(
                "jax_persistent_cache_min_entry_size_bytes", 0)
        except Exception as e:    # noqa: BLE001
            log(f"compilation cache unavailable ({e}); continuing")
        devices = jax.devices()
        log(f"backend={jax.default_backend()} devices={devices}")
    except Exception as e:
        fail_json(suite, sf,
                  f"backend '{platform}' failed in-process init: "
                  f"{type(e).__name__}: {e}", diags)
        return
    if platform == "cpu":
        # exact differential math on the fallback platform (tests' config)
        jax.config.update("jax_enable_x64", True)

    numerics = None
    if os.environ.get("SDOT_BENCH_CHECK", "1") != "0":
        try:
            ok, detail = numerics_check()
            numerics = {"exact": ok, "detail": detail}
            log(f"numerics check: {'OK' if ok else 'FAILED'} — {detail}")
        except Exception as e:
            numerics = {"exact": False,
                        "detail": f"{type(e).__name__}: {e}"}
            log(f"numerics check crashed: {e}")

    from spark_druid_olap_tpu.tools import tpch

    try:
        if suite == "ssb":
            ctx, n_rows, queries = setup_ssb(sf)
            names = [s.strip() for s in qsel.split(",") if s.strip()] \
                or list(queries)
        else:
            queries = tpch.QUERIES
            names = [s.strip() for s in qsel.split(",")
                     if s.strip()] or ALL22
            ctx, n_rows = setup(sf)
        floor_ms = measure_floor(ctx, reps)
    except Exception as e:
        fail_json(suite, sf,
                  f"setup/ingest failed on '{platform}': "
                  f"{type(e).__name__}: {e}", diags)
        return

    # measured unit costs (VERDICT r4 item 1: calibrate BEFORE bench).
    # SDOT_BENCH_UNIT_COSTS points at scripts/calibrate_chip.py output —
    # the perf gates (compaction, sorted-run, ffl ceiling) then run on
    # constants fit on THIS backend instead of the r3 probe defaults.
    unit_costs = None
    uc_path = os.environ.get("SDOT_BENCH_UNIT_COSTS", "").strip()
    if uc_path:
        try:
            with open(uc_path) as f:
                doc = json.load(f)
            # validate BEFORE the first config.set: a malformed entry must
            # not leave the session half-calibrated while the snapshot
            # claims defaults were used
            fitted = {k: float(v) for k, v in doc.get("fitted", {}).items()}
            if doc.get("backend") not in (None, jax.default_backend()):
                log(f"unit costs in {uc_path} were fit on "
                    f"'{doc.get('backend')}' but this run is "
                    f"'{jax.default_backend()}'; NOT applying")
            else:
                for k, v in fitted.items():
                    ctx.config.set(k, v)
                unit_costs = {"source": uc_path, "values": fitted}
                log(f"applied {len(fitted)} measured unit costs "
                    f"from {uc_path}")
        except Exception as e:   # noqa: BLE001 — calibration is optional
            log(f"unit-cost load failed ({type(e).__name__}: {e}); "
                f"continuing with per-backend defaults")

    # parallel prewarm (VERDICT r2 #10 compile diet): compile-heavy first
    # executions overlap across a thread pool — per-signature compile
    # ownership lets different programs compile concurrently (largely
    # server-side on a tunneled chip), so the cold suite pays
    # max(compile) depth instead of sum(compile)
    prewarm_s = 0.0
    try:
        n_pre = int(os.environ.get(
            "SDOT_BENCH_PREWARM", "4" if platform == "axon" else "0"))
    except ValueError:
        n_pre = 0
    if sf >= 10:
        # concurrent first binds at SF10+ can transiently exceed the
        # device-cache budget (eviction can't reclaim buffers still
        # referenced by in-flight programs)
        n_pre = min(n_pre, 2)
    if n_pre > 0:
        from concurrent.futures import ThreadPoolExecutor
        t0 = time.perf_counter()
        errs = {}
        with ThreadPoolExecutor(max_workers=n_pre) as pool:
            futs = {pool.submit(ctx.sql, queries[n]): n for n in names}
            for f, n in futs.items():
                try:
                    f.result()
                except Exception as e:   # noqa: BLE001 — timed loop reports
                    errs[n] = f"{type(e).__name__}: {e}"
        prewarm_s = time.perf_counter() - t0
        log(f"parallel prewarm ({n_pre} threads): {prewarm_s:.1f}s"
            + (f", {len(errs)} failed: {errs}" if errs else ""))

    wall_lat, adj_lat = {}, {}
    gbps = {}
    gbps_basis = {}
    try:
        profile_n = int(os.environ.get("SDOT_BENCH_PROFILE_N", "4"))
    except ValueError:
        profile_n = 4
    ndisp = {}
    klaunch = {}
    zero_dispatch = []
    zero_dispatch_served = []
    fusion_fallback = []
    qphases = {}            # per-query stats["phases"] from the measured rep
    host_overhead = {}      # engine queries: wall minus device dispatch ms

    def _fusion_stats():
        # engine fusion-planner counters (0s until any engine query runs);
        # host-mode suites (numerics) have no sharedscan tier
        try:
            return dict(ctx.engine.sharedscan.stats().get("fusion") or {})
        except Exception:   # noqa: BLE001 — counters are advisory
            return {}

    def _pallas_stats():
        # engine wave-kernel counters (launches/tiles/fallbacks/vmem peak)
        try:
            return dict(ctx.engine.sharedscan.stats().get("pallas") or {})
        except Exception:   # noqa: BLE001 — counters are advisory
            return {}

    cold_total_s = 0.0
    n_engine = 0
    host_queries = []
    suite_t0 = time.perf_counter()
    try:
        budget_s = float(os.environ.get("SDOT_BENCH_TIME_BUDGET", "2400"))
    except ValueError:
        budget_s = 2400.0
    for name in names:
        # queries run as written over the base tables; the planner's
        # star-join collapse routes fact+dim joins onto the flat index
        sql = queries[name]
        fus0 = _fusion_stats()
        try:
            t0 = time.perf_counter()
            r = ctx.sql(sql)
            cold = time.perf_counter() - t0
        except Exception as e:
            log(f"{name}: FAILED ({type(e).__name__}: {e})")
            wall_lat[name] = adj_lat[name] = float("nan")
            continue
        cold_total_s += cold
        mode = ctx.history.entries()[-1].stats.get("mode", "?")
        n_engine += mode == "engine"
        if mode != "engine":
            host_queries.append(f"{name}:{mode}")
        over_budget = (time.perf_counter() - suite_t0) > budget_s
        if over_budget:
            # past the soft budget, the cold run (already paid) is the
            # only sample — wall for these queries includes compile
            log(f"{name}: over SDOT_BENCH_TIME_BUDGET, cold sample only")
        n_reps = 0 if over_budget else (1 if cold > 3.0 else reps)
        ts = [cold] if over_budget else []
        try:
            for _ in range(n_reps):
                t0 = time.perf_counter()
                ctx.sql(sql)
                ts.append(time.perf_counter() - t0)
        except Exception as e:
            # a transient failure mid-reps (tunneled-chip flakiness) must
            # not kill the run; time from the surviving reps or cold time
            log(f"{name}: warm rep failed ({type(e).__name__}: {e}); "
                f"using {len(ts) or 'cold'} sample(s)")
            if not ts:
                ts = [cold]
        wall = float(np.median(ts)) * 1000
        adj = max(wall - floor_ms, 0.05) if mode == "engine" else wall
        wall_lat[name] = wall
        adj_lat[name] = adj
        # roofline: achieved scan bandwidth from the engine's own byte
        # accounting (VERDICT r2 #2). Denominator is MEASURED device time
        # (one profiled rep, amortized dispatches with data-dependent
        # syncs) — floor-adjusted wall is RTT-contaminated and prints
        # nonsense (e.g. "1140GB/s") when the floor estimate overshoots a
        # short query (VERDICT r3 weak #2). Falls back to adjusted wall
        # (marked) only when the profiled rep fails.
        # capture the MEASURED rep's stats before the profiling rep below
        # appends its own history entry (ADVICE r4: reading entries()[-1]
        # after that rep would report the profiling run's counters)
        meas_stats = dict(ctx.history.entries()[-1].stats)
        # fusion-plan regression guard (extends the zero_dispatch pattern):
        # a plan_fallbacks advance during this query's reps means a fused
        # group silently reverted to the unfused (per-lane re-eval)
        # program — the single-pass win regressed without failing anything
        fus1 = _fusion_stats()
        if mode == "engine" and (int(fus1.get("plan_fallbacks", 0))
                                 > int(fus0.get("plan_fallbacks", 0))):
            fusion_fallback.append(name)
            log(f"{name}: WARNING fusion planner fell back to the unfused "
                f"program during this query's reps — fused dispatch is no "
                f"longer single-pass")
        bs = meas_stats.get("bytes_scanned")
        gb = ""
        if mode == "engine" and bs:
            dev_ms = None
            if not over_budget and profile_n > 0:
                from spark_druid_olap_tpu.parallel import executor as _ex
                try:
                    _ex.set_profile_dispatch(profile_n)
                    ctx.sql(sql)
                    dev_ms = ctx.history.entries()[-1].stats.get(
                        "profile_device_ms")
                except Exception:   # noqa: BLE001 — profiling is optional
                    dev_ms = None
                finally:
                    _ex.set_profile_dispatch(None)
            if dev_ms:
                gbps[name] = round(bs / (dev_ms / 1000.0) / 1e9, 2)
                gbps_basis[name] = "device"
                gb = f", {gbps[name]:.1f}GB/s dev ({dev_ms:.1f}ms)"
            else:
                gbps[name] = round(bs / (adj / 1000.0) / 1e9, 2)
                gbps_basis[name] = "adjusted_wall"
                gb = f", {gbps[name]:.1f}GB/s (wall-est)"
        nd = meas_stats.get("n_dispatch")
        nt = meas_stats.get("n_transfer")
        kl = meas_stats.get("kernel_launches")
        if kl:
            klaunch[name] = int(kl)
        dd = ""
        if nd is not None:
            ndisp[name] = int(nd)
            dd = f", {nd}+{nt}rt"   # program dispatches + host->dev transfers
            if mode == "engine" and int(nd) == 0:
                # an engine-mode query that reports zero device dispatches
                # measured a cache hit, not an execution (TPC-H q20
                # regression: the ungated subquery cache served its
                # decorrelated inners on warm reps). The session now
                # annotates LEGITIMATE cache service via "served_from"
                # (result cache, or the gated subquery cache serving every
                # scan leg of a decorrelated plan) — those are recorded in
                # a separate list so the guard itself can't silently rot:
                # an unannotated zero-dispatch engine query is always a
                # loud accounting bug.
                served = meas_stats.get("served_from")
                if not served:
                    # a sketch lane answered by a materialized rollup
                    # reaggregates STORED registers — host-side merge of
                    # persisted sketch state is a legitimate zero-dispatch
                    # answer, not a cache accident. Only sketch aggs get
                    # this exemption; a plain agg off a rollup still
                    # scans the rollup's segments on device.
                    roll = str(meas_stats.get("rollup", ""))
                    if roll.startswith("rollup:") and any(
                            fn in sql.lower() for fn in
                            ("percentile_approx", "approx_percentile",
                             "approx_count_distinct", "approx_distinct",
                             "theta_sketch")):
                        served = f"sketch-{roll}"
                if served:
                    zero_dispatch_served.append(
                        {"query": name, "served_from": str(served)})
                    log(f"{name}: zero device dispatches, served from "
                        f"{served} (annotated; exempt from the guard)")
                else:
                    zero_dispatch.append(name)
                    log(f"{name}: WARNING engine-mode query reported ZERO "
                        f"device dispatches — a cache is serving the "
                        f"measured rep")
        elif mode == "engine":
            # engine mode must always account its dispatches; a missing
            # counter would quietly disable the zero-dispatch guard
            zero_dispatch.append(name)
            log(f"{name}: WARNING engine-mode query is MISSING the "
                f"n_dispatch counter — the zero-dispatch guard cannot "
                f"audit it")
        cm = meas_stats.get("compact_m")
        if cm:
            dd += f", lm={cm}"      # late-materialization budget engaged
        if meas_stats.get("compact_overflow"):
            dd += ", lm-overflow"
        # host critical-path accounting from the always-on phase profiler:
        # host overhead is the measured wall minus the device-dispatch
        # phase — everything the host does around the actual execution
        # (parse/plan/admit/cache/bind/demux). Tracked per engine query so
        # the round-over-round guard below can flag host-side regressions
        # that adjusted geomean (dominated by dispatch) would hide.
        ph = meas_stats.get("phases")
        if isinstance(ph, dict) and ph:
            qphases[name] = {k: float(v) for k, v in ph.items()}
            if mode == "engine":
                host_overhead[name] = round(
                    max(wall - float(ph.get("dispatch", 0.0)), 0.0), 3)
        log(f"{name}: {wall:.1f}ms wall ({adj:.1f}ms floor-adjusted, cold "
            f"{cold:.2f}s, mode={mode}, {len(r)} rows{gb}{dd})")

    def geomean(d):
        vals = [max(v, 0.05) for v in d.values() if np.isfinite(v)]
        return float(np.exp(np.mean(np.log(vals)))) if vals else float("nan")

    ok_wall = {k: v for k, v in wall_lat.items() if np.isfinite(v)}
    gm_wall = geomean(wall_lat)
    gm_adj = geomean(adj_lat)
    n_fail = len(wall_lat) - len(ok_wall)
    log(f"geomean over {len(ok_wall)}/{len(wall_lat)} queries: "
        f"{gm_wall:.1f}ms wall / {gm_adj:.1f}ms adjusted"
        + (f" ({n_fail} FAILED)" if n_fail else ""))

    # vs_baseline: per-chip row-throughput ratio on the published queries,
    # from UNADJUSTED wall time (the reference's numbers are end-to-end)
    ratios = []
    for qn, base_ms in BASELINE_MS.items():
        if qn in ok_wall:
            ours = n_rows / max(ok_wall[qn], 0.05)     # rows/ms
            theirs = BASELINE_ROWS / base_ms
            ratios.append(ours / theirs)
            log(f"  vs_baseline {qn}: {ours / theirs:.1f}x (wall)")
    vs = float(np.exp(np.mean(np.log(ratios)))) if ratios else 0.0

    out = {
        "metric": metric_name(suite, sf),
        "value": round(gm_wall, 2) if np.isfinite(gm_wall) else None,
        "unit": "ms",
        "vs_baseline": round(vs, 3),
        "platform": platform,
        "adjusted_geomean_ms": round(gm_adj, 2) if np.isfinite(gm_adj)
        else None,
        "dispatch_floor_ms": round(floor_ms, 1),
        "n_queries": len(wall_lat),
        "n_engine_mode": n_engine,
        "host_queries": host_queries,
        "n_failed": n_fail,
        "rows": n_rows,
        "numerics": numerics,
        # compile-diet regression surface (VERDICT r2 #10): total cold
        # (first-execution, compile-inclusive) seconds across the suite
        # INCLUDING the parallel prewarm wall; the persistent XLA cache
        # makes repeat runs near-warm
        "cold_total_s": round(cold_total_s + prewarm_s, 1),
        "prewarm_s": round(prewarm_s, 1),
    }
    if unit_costs is not None:
        out["unit_costs"] = unit_costs
    if ndisp:
        # device round trips per query: on the tunneled chip each costs
        # the dispatch floor, so this is wall time's dominant term made
        # auditable (and the target of dispatch-reduction work)
        out["n_dispatch"] = ndisp
    if klaunch:
        # hand-scheduled wave-kernel launches per query (slot 2 of the
        # dispatch counter; nonzero only when the pallas wave path ran)
        out["kernel_launches"] = klaunch
    if zero_dispatch:
        out["zero_dispatch_engine"] = zero_dispatch
    if zero_dispatch_served:
        out["zero_dispatch_served"] = zero_dispatch_served
    if qphases:
        # suite-level host critical path: per-phase geomean (ms) over the
        # queries that reported the phase. Inclusive timers — parents
        # contain children — so rows are read individually, not summed.
        pnames = sorted({p for d in qphases.values() for p in d})
        out["phases"] = {
            p: round(geomean({q: d[p] for q, d in qphases.items()
                              if p in d}), 3)
            for p in pnames}
        log("host phases (geomean ms over reporting queries): "
            + ", ".join(f"{p}={v}" for p, v in out["phases"].items()))
    if host_overhead:
        out["host_overhead_ms"] = host_overhead
        # regression guard vs the previous BENCH round file (repo root):
        # flag engine queries whose host overhead grew >25% (and by at
        # least 1ms — sub-ms jitter is timer noise, not a regression).
        # Older rounds predate this counter; the guard stays inert until
        # a round with host_overhead_ms exists to compare against.
        prev = {}
        try:
            import glob as _glob
            rounds = sorted(_glob.glob(
                os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "BENCH_r*.json")))
            if rounds:
                with open(rounds[-1], "r", encoding="utf-8") as f:
                    doc = json.load(f)
                doc = doc.get("parsed") or doc
                prev = dict(doc.get("host_overhead_ms") or {})
        except Exception:   # noqa: BLE001 — the guard is advisory
            prev = {}
        regressed = []
        for qn, cur in host_overhead.items():
            old = prev.get(qn)
            if old is None or float(old) <= 0:
                continue
            if cur > float(old) * 1.25 and cur - float(old) >= 1.0:
                regressed.append({"query": qn, "prev_ms": round(float(old), 3),
                                  "now_ms": cur})
                log(f"{qn}: WARNING host overhead regressed "
                    f"{float(old):.1f}ms -> {cur:.1f}ms (>25%)")
        if regressed:
            out["host_overhead_regressions"] = regressed
    fus_end = _fusion_stats()
    if fus_end:
        # deterministic CSE counters for the whole suite: how much
        # predicate work and column streaming the fusion planner removed
        out["fusion"] = fus_end
    if fusion_fallback:
        out["fusion_fallback_engine"] = fusion_fallback
    pal_end = _pallas_stats()
    if pal_end:
        out["pallas"] = pal_end
    try:
        out["pallas_ab"] = run_pallas_ab()
    except Exception as e:   # noqa: BLE001 — the A-B leg is advisory
        out["pallas_ab"] = {"available": False,
                            "error": f"{type(e).__name__}: {e}"}
    try:
        out["encode_ab"] = run_encode_ab()
    except Exception as e:   # noqa: BLE001 — the A-B leg is advisory
        out["encode_ab"] = {"available": False,
                            "error": f"{type(e).__name__}: {e}"}
    try:
        out["mesh_ab"] = run_mesh_ab()
    except Exception as e:   # noqa: BLE001 — the A-B leg is advisory
        out["mesh_ab"] = {"available": False,
                          "error": f"{type(e).__name__}: {e}"}
    try:
        out["join_ab"] = run_join_ab()
    except Exception as e:   # noqa: BLE001 — the A-B leg is advisory
        out["join_ab"] = {"available": False,
                          "error": f"{type(e).__name__}: {e}"}
    try:
        out["window_ab"] = run_window_ab()
    except Exception as e:   # noqa: BLE001 — the A-B leg is advisory
        out["window_ab"] = {"available": False,
                            "error": f"{type(e).__name__}: {e}"}
    if gbps:
        try:
            peak = float(os.environ.get("SDOT_BENCH_HBM_PEAK_GBPS", "819"))
        except ValueError:
            peak = 819.0                       # v5e HBM ~819 GB/s
        out["scan_gbps"] = gbps
        out["scan_gbps_basis"] = gbps_basis
        # peak claims only from device-time measurements — a wall-based
        # estimate can overshoot arbitrarily when RTT dominates
        dev_vals = [v for k, v in gbps.items()
                    if gbps_basis.get(k) == "device"]
        if dev_vals:
            best = max(dev_vals)
            out["scan_gbps_max"] = round(best, 2)
            out["hbm_peak_pct_max"] = round(100.0 * best / peak, 2)
    if n_fail == len(wall_lat) and wall_lat:
        out["error"] = "all queries failed; see stderr for per-query errors"
    print(json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
