"""Broker-side shard-level subquery result cache.

A dashboard storm re-sends the same small query family every few
seconds; interval pruning already skips shards whose time envelope
misses the filter, but every *surviving* shard still costs an RPC and a
historical-side execution. This cache short-circuits that: the partial
result of one (subquery shape, shard) pair is kept on the broker and
replayed on the next identical scatter.

Key discipline (the correctness core):

- ``body_key`` — SHA-256 of the UNPATCHED subquery body (canonical
  serialized spec, before the per-shard datasource rewrite), so one
  logical query maps to one key family across all shards;
- shard identity — ``(datasource, shard index, n_shards)``. NOT the
  node id and NOT the epoch: shard composition is a pure function of
  (manifests, shard count), so the same shard served by a different
  node after a topology change is byte-identical data and the entry
  stays valid across epochs (epoch-invariance, tested);
- ``ingest_version`` — any re-ingest bumps it, so staleness is
  structurally impossible rather than TTL-approximated.

Values are the decoded ``(columns, data, stats)`` partials — cheap to
merge, already materialized. Entries are LRU-evicted against a byte
budget; sizes are estimated from the encoded wire frame the broker just
received (or re-encoded for local fallbacks).

Thread safety: one leaf lock around the OrderedDict; get/put never call
out while holding it.
"""

from __future__ import annotations

import hashlib
import threading
from collections import OrderedDict
from typing import Optional, Tuple


def body_key(body: bytes) -> str:
    """Canonical key of one subquery shape (pre-patch body bytes)."""
    return hashlib.sha256(body).hexdigest()


class SubqueryCache:
    """LRU (subquery shape, shard, ingest version) -> partial result."""

    def __init__(self, max_bytes: int):
        self.max_bytes = int(max_bytes)
        self._lock = threading.Lock()   # LEAF — no calls out while held
        self._entries: "OrderedDict[tuple, Tuple[object, int]]" = \
            OrderedDict()
        self._bytes = 0
        self.counters = {"hits": 0, "misses": 0, "puts": 0,
                         "evictions": 0}

    @property
    def enabled(self) -> bool:
        return self.max_bytes > 0

    @staticmethod
    def key(bkey: str, datasource: str, shard_index: int, n_shards: int,
            ingest_version: int) -> tuple:
        return (bkey, datasource, int(shard_index), int(n_shards),
                int(ingest_version))

    def get(self, key: tuple) -> Optional[object]:
        if not self.enabled:
            return None
        with self._lock:
            ent = self._entries.get(key)
            if ent is None:
                self.counters["misses"] += 1
                return None
            self._entries.move_to_end(key)
            self.counters["hits"] += 1
            return ent[0]

    def put(self, key: tuple, value: object, nbytes: int) -> None:
        if not self.enabled:
            return
        nbytes = max(1, int(nbytes))
        if nbytes > self.max_bytes:
            return                      # would evict everything for one entry
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old[1]
            self._entries[key] = (value, nbytes)
            self._bytes += nbytes
            self.counters["puts"] += 1
            while self._bytes > self.max_bytes and self._entries:
                _, (_, sz) = self._entries.popitem(last=False)
                self._bytes -= sz
                self.counters["evictions"] += 1

    def invalidate_datasource(self, datasource: str) -> None:
        """Drop every shard entry of one datasource (defensive hook for
        explicit drops; normal staleness is handled by the
        ingest-version key term)."""
        with self._lock:
            dead = [k for k in self._entries if k[1] == datasource]
            for k in dead:
                self._bytes -= self._entries.pop(k)[1]

    def stats(self) -> dict:
        with self._lock:
            return {"enabled": self.enabled, "entries": len(self._entries),
                    "bytes": self._bytes, "max_bytes": self.max_bytes,
                    **self.counters}
