"""Plan epochs: versioned active-node lists in deep storage.

Elastic topology without a coordinator or a restart. The shard plan
stays a pure function (cluster/assign.py), but its node-list input is
now *versioned*: a small epoch record under ``<persist-root>/.cluster/``
(dot-prefixed, so the datasource catalog scan never mistakes it for a
datasource). Publishing a new record with one node added or removed IS
the whole membership protocol — the broker and every historical poll
the record and run the handover dance themselves:

1. a joining historical sees an epoch that includes it, warms its newly
   owned shards from the cold tier, and only then advertises the epoch
   on ``/readyz``;
2. the broker keeps scattering against the OLD epoch until every shard
   of the new plan has at least one owner advertising it warm, then
   swaps atomically (in-flight scatters finish on the captured old
   state);
3. a leaving historical watches the same readiness condition, then
   drains in-flight subqueries and fences.

Durability discipline is exactly the persist manifest protocol
(persist/snapshot.py): records are written tmp + fsync + ``os.replace``
into ``epoch-%010d.json``, then a ``CURRENT`` pointer flips atomically.
A crash between the record write and the CURRENT flip leaves an inert
orphan — readers stay on the old epoch, and the next publish allocates
past the orphan (numbers are never reused). The ``epoch.publish`` fault
site sits exactly in that window so the crash is testable.

Node identity: each member has a stable *logical id* (``n0``, ``n1``,
…) assigned at join and never reused. The stability-aware owner
assignment hashes logical ids, not list indexes or addresses, so a
node's shards survive an address change and a removal elsewhere in the
list — and a replayed harness run with fresh ports computes the
identical plan. ``generation`` bumps when an id rejoins after leaving,
which is what lets the broker reset that node's breaker state instead
of inheriting the predecessor's open circuit.

Concurrent publishers (two operators running ``add-node`` at once)
serialize on a lock file; the claim/release pair is registered with the
sdlint leaks pass, so a publish path that could exit holding the lock
is a lint finding, not a wedged cluster.
"""

from __future__ import annotations

import dataclasses
import json
import os
import time
from typing import Dict, Optional, Sequence, Tuple

from spark_druid_olap_tpu.persist.snapshot import fsync_dir

EPOCH_DIR = ".cluster"
CURRENT = "CURRENT"
LOCK = "publish.lock"
_FMT = "epoch-%010d.json"


class EpochBusy(RuntimeError):
    """Another publisher holds the epoch publish lock."""


class EpochCorrupt(RuntimeError):
    """No parseable epoch record behind a CURRENT pointer."""


@dataclasses.dataclass(frozen=True)
class EpochRecord:
    """One versioned membership snapshot.

    ``nodes`` are ``host:port`` strings (index order = node id within
    this epoch); ``ids`` are the parallel stable logical identifiers;
    ``generations`` maps logical id -> generation (bumped on rejoin).
    ``epoch`` 0 with ``path`` None is the implicit bootstrap record
    derived from ``sdot.cluster.nodes`` when deep storage holds no
    published record yet — byte-identical on every member because the
    config is."""

    epoch: int
    nodes: Tuple[str, ...]
    ids: Tuple[str, ...]
    generations: Dict[str, int]
    created_at: float = 0.0
    note: str = ""

    @property
    def addresses(self) -> Tuple[Tuple[str, int], ...]:
        out = []
        for part in self.nodes:
            host, _, port = part.rpartition(":")
            out.append((host, int(port)))
        return tuple(out)

    def id_of(self, address: str) -> Optional[str]:
        try:
            return self.ids[self.nodes.index(address)]
        except ValueError:
            return None

    def to_dict(self) -> dict:
        return {"epoch": self.epoch, "nodes": list(self.nodes),
                "ids": list(self.ids),
                "generations": dict(self.generations),
                "created_at": self.created_at, "note": self.note}

    @staticmethod
    def from_dict(d: dict) -> "EpochRecord":
        nodes = tuple(str(n) for n in d["nodes"])
        ids = tuple(str(i) for i in d.get("ids") or default_ids(len(nodes)))
        if len(ids) != len(nodes):
            raise ValueError("epoch record ids/nodes length mismatch")
        return EpochRecord(
            epoch=int(d["epoch"]), nodes=nodes, ids=ids,
            generations={str(k): int(v)
                         for k, v in (d.get("generations") or {}).items()},
            created_at=float(d.get("created_at", 0.0)),
            note=str(d.get("note", "")))


def default_ids(n: int) -> Tuple[str, ...]:
    return tuple(f"n{i}" for i in range(n))


def bootstrap_record(nodes: Sequence[str]) -> EpochRecord:
    """Implicit epoch 0 from the static config node list (never written
    to disk): the pre-elasticity behavior, and the base every published
    epoch diffs against."""
    nodes = tuple(nodes)
    ids = default_ids(len(nodes))
    return EpochRecord(epoch=0, nodes=nodes, ids=ids,
                       generations={i: 0 for i in ids})


def epoch_root(persist_root: str) -> str:
    return os.path.join(os.path.abspath(persist_root), EPOCH_DIR)


def _list_epochs(eroot: str):
    out = []
    try:
        entries = os.listdir(eroot)
    except OSError:
        return out
    for n in entries:
        if n.startswith("epoch-") and n.endswith(".json"):
            try:
                out.append(int(n[len("epoch-"):-len(".json")]))
            except ValueError:
                continue
    return sorted(out)


def read_epoch(persist_root: str) -> Optional[EpochRecord]:
    """Current published epoch record, or None when none was ever
    published (members fall back to the bootstrap record). CURRENT is
    authoritative: an orphan record past it (crash between the record
    write and the pointer flip) stays inert until republished."""
    eroot = epoch_root(persist_root)
    cur = os.path.join(eroot, CURRENT)
    try:
        with open(cur) as f:
            n = int(json.load(f)["epoch"])
    except (OSError, ValueError, KeyError):
        return None
    try:
        with open(os.path.join(eroot, _FMT % n)) as f:
            return EpochRecord.from_dict(json.load(f))
    except (OSError, ValueError, KeyError) as e:
        # the pointer exists but its record is gone/corrupt: fall back
        # to the newest older record rather than flapping to bootstrap
        # (which would look like a mass topology change)
        for v in reversed(_list_epochs(eroot)):
            if v >= n:
                continue
            try:
                with open(os.path.join(eroot, _FMT % v)) as f:
                    return EpochRecord.from_dict(json.load(f))
            except (OSError, ValueError, KeyError):
                continue
        raise EpochCorrupt(f"CURRENT points at epoch {n} but no "
                           f"parseable record exists: {e}") from e


def claim_publish(persist_root: str,
                  stale_after_s: float = 30.0) -> str:
    """Take the publish lock (O_CREAT|O_EXCL lock file). Returns the
    lock path as the claim token; MUST be released via
    :func:`release_publish` (sdlint leaks pair). A lock file older than
    ``stale_after_s`` is a crashed publisher and is broken."""
    eroot = epoch_root(persist_root)
    os.makedirs(eroot, exist_ok=True)
    path = os.path.join(eroot, LOCK)
    for _attempt in range(2):
        try:
            fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
            os.write(fd, str(os.getpid()).encode())
            os.close(fd)
            return path
        except FileExistsError:
            try:
                age = time.time() - os.path.getmtime(path)
            except OSError:
                continue        # released between the open and the stat
            if age > stale_after_s:
                try:
                    os.remove(path)
                except OSError:
                    pass
                continue
            raise EpochBusy(
                f"epoch publish in progress ({path}, {age:.1f}s old)")
    raise EpochBusy(f"epoch publish lock {path} could not be claimed")


def release_publish(token: str) -> None:
    try:
        os.remove(token)
    except OSError:
        pass


def next_record(prev: Optional[EpochRecord], nodes: Sequence[str],
                next_epoch: int, note: str = "") -> EpochRecord:
    """Build the successor record: surviving logical ids carry over
    (same id, same generation — their shards don't move), brand-new
    addresses get the next free id, and an address that left and came
    back keeps its id but bumps its generation (fresh breaker state,
    same shard affinity)."""
    nodes = tuple(nodes)
    if len(set(nodes)) != len(nodes):
        raise ValueError(f"duplicate addresses in node list: {nodes}")
    prev_map = {} if prev is None else dict(zip(prev.nodes, prev.ids))
    gens = {} if prev is None else dict(prev.generations)
    used = set(gens) | set(prev_map.values())
    ids = []
    for addr in nodes:
        nid = prev_map.get(addr)
        if nid is None:
            # an id is never reused by a different address; scan for the
            # lowest free one so bootstrap-compatible lists keep n0..nK
            i = 0
            while f"n{i}" in used:
                i += 1
            nid = f"n{i}"
            used.add(nid)
            gens[nid] = next_epoch
        ids.append(nid)
    # ids that left keep their generation entry: if the same id's
    # address ever rejoins it would be a *new* id, but an id explicitly
    # re-added via add-node after remove-node bumps below
    gens = {i: g for i, g in gens.items() if i in ids}
    return EpochRecord(epoch=next_epoch, nodes=nodes, ids=tuple(ids),
                       generations=gens, created_at=time.time(),
                       note=note)


def publish_epoch(persist_root: str, nodes: Sequence[str],
                  note: str = "", fault=None) -> EpochRecord:
    """Publish a new epoch record atomically and return it.

    Protocol (persist/snapshot.py discipline): allocate max+1 over the
    record FILES (not CURRENT — an orphan must never be overwritten),
    write tmp + fsync + os.replace + dir fsync, then flip CURRENT the
    same way. The ``epoch.publish`` fault site fires between the two
    steps: an error rule there models the publisher dying after the
    record landed but before the flip — readers keep the old epoch and
    a re-publish allocates past the orphan."""
    tok = claim_publish(persist_root)
    try:
        eroot = epoch_root(persist_root)
        prev = read_epoch(persist_root)
        have = _list_epochs(eroot)
        nxt = max([prev.epoch if prev else 0] + have) + 1
        rec = next_record(prev, nodes, nxt, note=note)
        tmp = os.path.join(eroot, f".tmp-{os.getpid()}-{nxt}.json")
        with open(tmp, "w") as f:
            json.dump(rec.to_dict(), f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, os.path.join(eroot, _FMT % nxt))
        fsync_dir(eroot)
        if fault is not None:
            # crash window: the record exists, CURRENT still points at
            # the previous epoch
            fault.fire("epoch.publish", key=f"epoch:{nxt}")
        ctmp = os.path.join(eroot, CURRENT + ".tmp")
        with open(ctmp, "w") as f:
            json.dump({"epoch": nxt}, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(ctmp, os.path.join(eroot, CURRENT))
        fsync_dir(eroot)
        return rec
    finally:
        release_publish(tok)
