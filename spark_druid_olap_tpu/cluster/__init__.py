"""Distributed serving tier: broker + replicated historicals.

The reference system's L1/L2 plane — a broker that scatters per-segment
subqueries to historical servers (``DruidMetadataCache.assignHistoricalServers``)
and merges partials — realized as real processes over the engine:

- ``assign.py``    deterministic shard plan from deep-storage manifests
                   (no coordinator service: the persist/ root IS the
                   coordination substrate)
- ``historical.py``  a serving node: PersistManager recovery, slice to
                   owned shards, subquery RPC over the full QueryEngine
                   (WLM lanes, result cache, rollup rewrite, shared-scan
                   coalescing all apply per node)
- ``broker.py``    plans once, scatters per-shard subqueries, merges
                   partials (merge-closed aggs + HLL/theta register
                   merge), runs TopN/limit/ordering epilogues, fails a
                   shard over to replicas with decorrelated-jitter
                   backoff, probes node health
- ``wire.py``      pickle-free binary result encoding for the RPC
- ``merge.py``     host-side partial-merge kernels (exact int sums,
                   NaN-null floats, register max/min for sketches)

``python -m spark_druid_olap_tpu.cluster`` launches either role.
"""

from spark_druid_olap_tpu.cluster.assign import (  # noqa: F401
    ClusterPlan,
    DatasourcePlan,
    Shard,
    plan_cluster,
    shard_name,
)
