"""Autoscale hook: WLM queue depth -> spawn / retire historicals.

The broker already polls every historical for health; this hook rides
the same cadence and samples each node's WLM lane stats
(``GET /metadata/wlm``). When the fleet-mean queued-query depth sits
above ``sdot.cluster.autoscale.queue.high`` the hook signals
**scale-out**, below ``queue.low`` **scale-in** — with a cooldown
between decisions so one burst can't flap the fleet.

The hook decides; it does not provision. The ``spawn`` / ``retire``
callbacks are registered by whoever owns process lifecycle (the
loadtest harness forks a local historical; an operator wires
``scripts/start-sdot-cluster.sh add-node``; a k8s adapter would scale a
StatefulSet) and are expected to end in :func:`cluster.epoch.
publish_epoch` — the epoch machinery then runs the warm-before-ready /
drain-then-fence handover exactly as for a manual topology change.
With no callbacks registered, decisions only increment counters (dry
run), which is the safe default.

Deliberately clock-injectable and sampling-free so the decision logic
is unit-testable without a cluster: the broker supplies ``depths`` (one
int per live node) and the hook is a pure threshold/cooldown machine.
"""

from __future__ import annotations

import time
from typing import Callable, Optional, Sequence


class AutoscaleHook:
    """Threshold + cooldown decision machine over fleet queue depths."""

    def __init__(self, queue_high: float, queue_low: float,
                 cooldown_s: float,
                 spawn: Optional[Callable[[], None]] = None,
                 retire: Optional[Callable[[], None]] = None,
                 clock: Callable[[], float] = time.monotonic):
        if queue_low >= queue_high:
            raise ValueError(
                f"autoscale queue.low ({queue_low}) must be below "
                f"queue.high ({queue_high}) or the fleet flaps")
        self.queue_high = float(queue_high)
        self.queue_low = float(queue_low)
        self.cooldown_s = float(cooldown_s)
        self.spawn = spawn
        self.retire = retire
        self._clock = clock
        self._last_decision: Optional[float] = None
        self.counters = {"samples": 0, "scale_out": 0, "scale_in": 0,
                         "suppressed_cooldown": 0, "callback_errors": 0}

    def observe(self, depths: Sequence[float],
                handover_in_progress: bool = False) -> Optional[str]:
        """Feed one sample of per-node queued depths; returns the
        decision ("out" / "in") or None. A pending epoch handover
        suppresses decisions — scaling while shards are mid-movement
        would stack epochs faster than nodes can warm."""
        self.counters["samples"] += 1
        if not depths or handover_in_progress:
            return None
        mean = sum(float(d) for d in depths) / len(depths)
        if mean > self.queue_high:
            want = "out"
        elif mean < self.queue_low and len(depths) > 1:
            # never retire the last historical
            want = "in"
        else:
            return None
        now = self._clock()
        if self._last_decision is not None \
                and now - self._last_decision < self.cooldown_s:
            self.counters["suppressed_cooldown"] += 1
            return None
        self._last_decision = now
        self.counters["scale_out" if want == "out" else "scale_in"] += 1
        cb = self.spawn if want == "out" else self.retire
        if cb is not None:
            try:
                cb()
            except Exception:  # noqa: BLE001 — provisioning is best-effort
                self.counters["callback_errors"] += 1
        return want

    def stats(self) -> dict:
        return {"queue_high": self.queue_high, "queue_low": self.queue_low,
                "cooldown_s": self.cooldown_s,
                "has_callbacks": self.spawn is not None
                or self.retire is not None,
                **self.counters}
