"""Cluster entrypoint.

Historical (one per node id in the list)::

    python -m spark_druid_olap_tpu.cluster historical \
        --persist /data/sdot --nodes h0:9101,h1:9102 --node-id 0

Broker (fronts the cluster on the ordinary SQL HTTP surface)::

    python -m spark_druid_olap_tpu.cluster broker \
        --persist /data/sdot --nodes h0:9101,h1:9102 --port 8082

Every member must see the same --persist root and the same --nodes
list: the shard plan is recomputed identically from those two inputs.
``scripts/start-sdot-cluster.sh`` wraps the N+1 process spawn.

Topology changes go through plan epochs (cluster/epoch.py) — no
restart of the running members::

    python -m spark_druid_olap_tpu.cluster epoch show --persist /data/sdot
    python -m spark_druid_olap_tpu.cluster epoch add-node h2:9103 \
        --persist /data/sdot
    python -m spark_druid_olap_tpu.cluster epoch remove-node h1:9102 \
        --persist /data/sdot

``add-node`` publishes the record; the new historical process is
started separately (``scripts/start-sdot-cluster.sh add-node`` does
both). ``remove-node`` publishes the shrunken record; the removed
node drains its in-flight subqueries and fences itself once the
survivors cover its shards.
"""

from __future__ import annotations

import argparse


def _common(p: argparse.ArgumentParser) -> None:
    p.add_argument("--persist", required=True,
                   help="deep storage root (sdot.persist.path); the "
                        "coordination substrate")
    p.add_argument("--nodes", required=True,
                   help="comma-separated host:port historical list; "
                        "index order = node id")
    p.add_argument("--replication", type=int, default=2,
                   help="shard copies across historicals (default 2)")
    p.add_argument("--shards", type=int, default=0,
                   help="shards per datasource (0 = one per node)")
    p.add_argument("--set", action="append", default=[], metavar="K=V",
                   help="extra sdot.* config overrides (repeatable)")


def _epoch_cmd(ap: argparse.ArgumentParser, args) -> int:
    import json

    from spark_druid_olap_tpu.cluster import epoch as EP

    rec = EP.read_epoch(args.persist)
    if args.action == "show":
        if rec is None:
            print(json.dumps({"epoch": None, "nodes": [],
                              "note": "no epoch record published; "
                                      "members use the static --nodes "
                                      "bootstrap"}))
        else:
            print(json.dumps(rec.to_dict()))
        return 0
    if not args.address:
        ap.error(f"epoch {args.action} needs a host:port address")
    base = rec.nodes if rec is not None else tuple(
        n.strip() for n in args.nodes.split(",") if n.strip())
    if not base and args.action == "add-node":
        ap.error("no epoch record exists yet; pass the current "
                 "membership via --nodes")
    if args.action == "add-node":
        if args.address in base:
            ap.error(f"{args.address} is already a member")
        new_nodes = tuple(base) + (args.address,)
    else:
        if args.address not in base:
            ap.error(f"{args.address} is not a member of {list(base)}")
        new_nodes = tuple(n for n in base if n != args.address)
        if not new_nodes:
            ap.error("refusing to publish an empty cluster")
    out = EP.publish_epoch(args.persist, new_nodes,
                           note=args.note or args.action)
    print(json.dumps(out.to_dict()))
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="python -m spark_druid_olap_tpu.cluster")
    sub = ap.add_subparsers(dest="role", required=True)
    h = sub.add_parser("historical", help="serve assigned shards")
    _common(h)
    h.add_argument("--node-id", type=int, required=True,
                   help="this node's index into --nodes")
    b = sub.add_parser("broker", help="scatter/merge front over the nodes")
    _common(b)
    b.add_argument("--host", default="0.0.0.0")
    b.add_argument("--port", type=int, default=8082)
    e = sub.add_parser("epoch", help="show or roll the plan epoch "
                                     "(elastic topology, no restart)")
    e.add_argument("action", choices=["show", "add-node", "remove-node"])
    e.add_argument("address", nargs="?",
                   help="host:port for add-node / remove-node")
    e.add_argument("--persist", required=True,
                   help="deep storage root (the coordination substrate)")
    e.add_argument("--nodes", default="",
                   help="bootstrap host:port list; only needed when no "
                        "epoch record has been published yet")
    e.add_argument("--note", default="",
                   help="free-form note stored in the epoch record")
    args = ap.parse_args(argv)

    if args.role == "epoch":
        return _epoch_cmd(ap, args)

    overrides = {
        "sdot.persist.path": args.persist,
        "sdot.cluster.nodes": args.nodes,
        "sdot.cluster.replication": args.replication,
        "sdot.cluster.shards": args.shards,
    }
    for kv in args.set:
        k, _, v = kv.partition("=")
        overrides[k] = v

    if args.role == "historical":
        from spark_druid_olap_tpu.cluster.historical import HistoricalNode
        node = HistoricalNode(overrides, node_id=args.node_id)
        host, port = node.addresses[node.node_id]
        print(f"sdot historical {node.node_id} booting on "
              f"http://{host}:{port} (readyz flips 200 when shards load)",
              flush=True)
        node.start(background=False)
        return 0

    overrides["sdot.cluster.role"] = "broker"
    import spark_druid_olap_tpu as sdot
    from spark_druid_olap_tpu.server.http import SqlServer
    ctx = sdot.Context(overrides)
    n_ds = len(ctx.cluster.plan.datasources) if ctx.cluster else 0
    print(f"sdot broker on http://{args.host}:{args.port} — "
          f"{len(ctx.cluster.nodes)} nodes, {n_ds} planned datasources",
          flush=True)
    SqlServer(ctx, args.host, args.port).start(background=False)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
