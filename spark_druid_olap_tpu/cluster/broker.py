"""Broker: scatter per-shard subqueries, merge partials, survive nodes.

The client hangs off the broker engine's ``cluster`` attribute and is
consulted by ``QueryEngine._execute_admitted`` after the result-cache
lookup: a distributed answer populates the broker's own cache, so
dashboard storms are absorbed locally and only cold queries scatter.

Plan-once / scatter / merge (≈ the reference's broker merging historical
partials; Theseus's scatter–gather over partition-local operators):

1. ``should_distribute`` — spec shape + every agg merge-closed + the
   broker's in-memory ingest version matches the planned manifest
   (read-your-writes: a datasource ingested or appended after boot is
   served locally until the next checkpoint + restart).
2. ``execute`` — strips broker-side phases (post-aggs, HAVING, ORDER
   BY/LIMIT; TopN becomes a per-shard GroupBy), scatters one subquery
   per shard over a thread pool, each shard trying its replica chain
   with decorrelated-jitter backoff between passes; merges partials
   (cluster/merge.py) and runs the engine's own ``_agg_epilogue``.
3. Any non-retryable condition — serde gap, node-side EngineFallback,
   replicas exhausted — returns ``None``: the engine falls through to
   ordinary local execution (the broker holds a full recovered copy),
   so distribution is an accelerator, never a new failure mode.

Node health: RPC connection errors / timeouts mark the node down
reactively; a background prober (GET /readyz, decorrelated-jitter
interval) marks nodes down AND back up, so a restarted historical
resumes primary routing without operator action. On top of reactive
marks, graceful degradation (docs/CHAOS.md):

- per-node circuit breakers (cluster/breaker.py) skip a node without an
  RPC after K consecutive failures, with half-open probes after a
  cooldown;
- hedged scatter: a subquery that hasn't answered within the hedge
  delay (fixed, or a latency quantile of recent RPCs) races a duplicate
  to the next replica and takes the first answer;
- ``sdot.cluster.partial.results``: when every replica of a shard is
  unreachable, surviving shards still answer, annotated with
  ``degraded={missing_shards, coverage_rows}`` — never cached. Strict
  mode keeps the exact-or-ShardUnavailable contract.

Elastic topology (cluster/epoch.py): all routing state lives in an
immutable-per-epoch ``_EpochState`` (node list, plan, breaker board,
down map). The prober tick polls deep storage for a newer epoch record;
a newer one becomes the *pending* state, and the broker keeps
scattering against the ACTIVE state until every shard of the pending
plan has at least one owner advertising it warm on the extended
``/readyz`` (``assign.plan_fully_warm``) — then the swap is one
reference assignment, and in-flight scatters (which captured the old
state at entry) finish against nodes that are still draining, never
fenced. Each epoch gets a FRESH breaker board, and within an epoch a
node whose ``/readyz`` boot generation changes gets its breaker reset —
a rejoining process never inherits its predecessor's open circuit.

The shard-level subquery cache (cluster/subqcache.py) sits in front of
the scatter: partials are keyed by (subquery shape, shard identity,
ingest version) — node- and epoch-free — so a repeated dashboard storm
re-sends RPCs only for shards whose data could have changed, and a
topology change invalidates nothing.
"""

from __future__ import annotations

import dataclasses
import http.client
import json
import os
import random as _random
import threading
import time as _time
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional, Tuple

import numpy as np

from spark_druid_olap_tpu.cluster import epoch as EP
from spark_druid_olap_tpu.cluster import merge as MG
from spark_druid_olap_tpu.cluster import subqcache as SQC
from spark_druid_olap_tpu.cluster import wire as WIRE
from spark_druid_olap_tpu.cluster.assign import (
    parse_nodes, plan_cluster, plan_diff, plan_fully_warm, shard_name)
from spark_druid_olap_tpu.cluster.autoscale import AutoscaleHook
from spark_druid_olap_tpu.cluster.breaker import BreakerBoard
from spark_druid_olap_tpu.ir import serde as SERDE
from spark_druid_olap_tpu.ir import spec as S
from spark_druid_olap_tpu.result import QueryResult
from spark_druid_olap_tpu.utils.config import (
    CLUSTER_AUTOSCALE_COOLDOWN_SECONDS,
    CLUSTER_AUTOSCALE_ENABLED,
    CLUSTER_AUTOSCALE_QUEUE_HIGH,
    CLUSTER_AUTOSCALE_QUEUE_LOW,
    CLUSTER_BREAKER_COOLDOWN_SECONDS,
    CLUSTER_BREAKER_FAILURES,
    CLUSTER_HEDGE_AFTER_MS,
    CLUSTER_HEDGE_ENABLED,
    CLUSTER_HEDGE_MIN_MS,
    CLUSTER_HEDGE_QUANTILE,
    CLUSTER_INGEST_PUSH,
    CLUSTER_LOCAL_FALLBACK,
    CLUSTER_NODES,
    CLUSTER_PARTIAL_RESULTS,
    CLUSTER_PROBE_INTERVAL_SECONDS,
    CLUSTER_PROBE_JITTER,
    CLUSTER_REBALANCE_STRATEGY,
    CLUSTER_REPLICATION,
    CLUSTER_RETRY_BACKOFF_CAP_SECONDS,
    CLUSTER_RETRY_BACKOFF_START_SECONDS,
    CLUSTER_RETRY_TRIES,
    CLUSTER_RPC_TIMEOUT_SECONDS,
    CLUSTER_SCATTER_THREADS,
    CLUSTER_SHARDS,
    CLUSTER_SUBQ_CACHE_ENABLED,
    CLUSTER_SUBQ_CACHE_MAX_BYTES,
    PERSIST_PATH,
)
from spark_druid_olap_tpu.utils.retry import backoff


class ClusterError(RuntimeError):
    """A shard stayed unreachable through every replica and retry pass,
    and local fallback is disabled."""


class ShardUnavailable(ClusterError):
    """Every replica of a shard stayed unreachable. In strict mode this
    propagates to the caller; in partial-results mode the broker catches
    it per shard and answers degraded from the survivors."""


class _BreakerOpen(Exception):
    """Internal: the node's circuit breaker refused the attempt."""

    def __init__(self, node_id: int):
        super().__init__(f"breaker open for node {node_id}")
        self.node_id = node_id


class _HedgeRace:
    """First-success race between a primary RPC leg and a delayed hedge
    leg. ``close()`` (sdlint leaks pair) cancels the race so a late
    loser can neither win nor leak into the next attempt."""

    def __init__(self, total: int):
        self._lock = threading.Lock()   # leaf — never calls out while held
        self.done = threading.Event()
        self.total = total
        self.finished = 0
        self.cancelled = False
        self.winner = None              # (status, body, node_id)
        self.errors: List[Tuple[int, Exception]] = []

    def settle(self, nid, out, err) -> None:
        """One leg finished (out), failed (err), or stood down (both
        None — a hedge whose primary answered inside the delay)."""
        with self._lock:
            self.finished += 1
            if err is not None:
                self.errors.append((nid, err))
            elif out is not None and self.winner is None \
                    and not self.cancelled:
                self.winner = out
            if self.winner is not None or self.finished >= self.total:
                self.done.set()

    def result(self):
        with self._lock:
            return self.winner, list(self.errors)

    def close(self) -> None:
        with self._lock:
            self.cancelled = True


class _LocalFallback(Exception):
    """Internal: this query must run on the broker's own engine."""

    def __init__(self, reason: str):
        super().__init__(reason)
        self.reason = reason


class _EpochState:
    """Everything the scatter path reads about ONE topology epoch —
    captured once at query entry, so an epoch swap mid-scatter cannot
    mix node lists, plans, or breaker boards. ``down`` and ``boot_ids``
    are mutable (guarded by the client lock) but die with the state."""

    __slots__ = ("record", "nodes", "plan", "breakers", "down",
                 "boot_ids")

    def __init__(self, record, plan, breakers):
        self.record = record                # EpochRecord
        self.nodes = record.addresses       # ((host, port), ...)
        self.plan = plan
        self.breakers = breakers
        self.down: Dict[int, float] = {}    # node id -> down-since
        self.boot_ids: Dict[int, str] = {}  # node id -> last seen boot gen


class ClusterClient:
    def __init__(self, ctx):
        self.ctx = ctx
        self.engine = ctx.engine
        self.config = ctx.config
        boot_nodes = parse_nodes(self.config.get(CLUSTER_NODES))
        if not boot_nodes:
            raise ValueError("ClusterClient needs sdot.cluster.nodes")
        root = self.config.get(PERSIST_PATH)
        if not root:
            raise ValueError(
                "the cluster tier coordinates through deep storage; "
                "set sdot.persist.path on every member")
        self.root = root
        self.strategy = str(self.config.get(CLUSTER_REBALANCE_STRATEGY))
        self.rpc_timeout = float(
            self.config.get(CLUSTER_RPC_TIMEOUT_SECONDS))
        self.tries = max(1, int(self.config.get(CLUSTER_RETRY_TRIES)))
        self.backoff_start = float(
            self.config.get(CLUSTER_RETRY_BACKOFF_START_SECONDS))
        self.backoff_cap = float(
            self.config.get(CLUSTER_RETRY_BACKOFF_CAP_SECONDS))
        self.local_fallback = bool(self.config.get(CLUSTER_LOCAL_FALLBACK))
        self.fault = getattr(ctx.engine, "fault", None)
        # epoch 0 is implicit (the static config list) unless deep
        # storage already holds a published record — then that record,
        # not the config, is the fleet's truth
        rec = EP.read_epoch(root)
        if rec is None:
            rec = EP.bootstrap_record(
                tuple(f"{h}:{p}" for h, p in boot_nodes))
        self._active: _EpochState = self._mk_state(rec)
        self._pending: Optional[_EpochState] = None
        self.last_rebalance: Optional[dict] = None
        self.subq_cache = SQC.SubqueryCache(
            int(self.config.get(CLUSTER_SUBQ_CACHE_MAX_BYTES))
            if bool(self.config.get(CLUSTER_SUBQ_CACHE_ENABLED)) else 0)
        self.autoscale: Optional[AutoscaleHook] = None
        if bool(self.config.get(CLUSTER_AUTOSCALE_ENABLED)):
            self.autoscale = AutoscaleHook(
                float(self.config.get(CLUSTER_AUTOSCALE_QUEUE_HIGH)),
                float(self.config.get(CLUSTER_AUTOSCALE_QUEUE_LOW)),
                float(self.config.get(CLUSTER_AUTOSCALE_COOLDOWN_SECONDS)))
        self.hedge_enabled = bool(self.config.get(CLUSTER_HEDGE_ENABLED))
        self.hedge_after_ms = float(self.config.get(CLUSTER_HEDGE_AFTER_MS))
        self.hedge_quantile = float(self.config.get(CLUSTER_HEDGE_QUANTILE))
        self.hedge_min_ms = float(self.config.get(CLUSTER_HEDGE_MIN_MS))
        self.probe_jitter = bool(self.config.get(CLUSTER_PROBE_JITTER))
        self._latencies = deque(maxlen=512)     # recent subquery RPC seconds
        self._lock = threading.Lock()
        # distributed ingest (read-your-writes): per-datasource push
        # state — which owners confirmed which shards, and whether any
        # acked batch is still in flight to its owners. LOCK ORDER:
        # _lock before _ingest_lock (neither calls out while held).
        self.ingest_push_enabled = bool(
            self.config.get(CLUSTER_INGEST_PUSH))
        self._ingest_lock = threading.Lock()
        self._ingested: Dict[str, dict] = {}
        # per-shard-store batch ids, dense from 1: the historical's
        # out-of-order dedup collapses a contiguous prefix into its
        # watermark, which only works when ids have no per-shard gaps
        self._ingest_seq: Dict[str, int] = {}
        self._boot_id = f"{os.getpid()}.{_time.time_ns()}"
        self.counters = {"queries": 0, "scatters": 0, "subqueries": 0,
                         "retries": 0, "failovers": 0, "local_fallbacks": 0,
                         "shards_pruned": 0, "merge_ms": 0.0,
                         "probe_marks_down": 0, "probe_marks_up": 0,
                         "wire_corrupt": 0, "hedges_launched": 0,
                         "hedges_won": 0, "degraded_queries": 0,
                         "epoch_checks": 0, "epoch_swaps": 0,
                         "breaker_resets": 0,
                         "subq_cache_hits": 0, "subq_cache_misses": 0,
                         "ingest_pushes": 0, "ingest_push_failures": 0,
                         "ingest_rows_pushed": 0, "ryw_scatters": 0,
                         "join_scatters": 0, "join_shuffle_bytes": 0}
        self._pool = ThreadPoolExecutor(
            max_workers=max(1, int(self.config.get(CLUSTER_SCATTER_THREADS))),
            thread_name_prefix="sdot-scatter")
        self._stop = threading.Event()
        self._prober: Optional[threading.Thread] = None
        interval = float(self.config.get(CLUSTER_PROBE_INTERVAL_SECONDS))
        if interval > 0:
            self._prober = threading.Thread(
                target=self._probe_loop, args=(interval,),
                name="sdot-cluster-prober", daemon=True)
            self._prober.start()

    def close(self) -> None:
        self._stop.set()
        if self._prober is not None:
            self._prober.join(timeout=2.0)
            self._prober = None
        self._pool.shutdown(wait=False)

    # -- epoch state -----------------------------------------------------------
    # back-compat views over the ACTIVE epoch: code and tests written
    # against the static-topology broker keep reading .nodes/.plan/
    # .breakers and transparently follow swaps
    @property
    def nodes(self):
        return self._active.nodes

    @property
    def plan(self):
        return self._active.plan

    @property
    def breakers(self):
        return self._active.breakers

    def _mk_state(self, record) -> _EpochState:
        plan = plan_cluster(
            self.root, len(record.nodes),
            int(self.config.get(CLUSTER_REPLICATION)),
            int(self.config.get(CLUSTER_SHARDS)),
            node_keys=record.ids, epoch=record.epoch,
            strategy=self.strategy)
        # a FRESH breaker board per epoch: node id i of epoch E+1 may be
        # a different machine than node id i of epoch E, and must not
        # inherit its circuit state (satellite bugfix, structurally)
        breakers = BreakerBoard(
            len(record.nodes),
            int(self.config.get(CLUSTER_BREAKER_FAILURES)),
            float(self.config.get(CLUSTER_BREAKER_COOLDOWN_SECONDS)))
        return _EpochState(record, plan, breakers)

    def check_epoch(self) -> bool:
        """One step of the broker's handover dance: adopt a newer disk
        record as the *pending* state, and swap it active once every
        shard of its plan is advertised warm by at least one owner.
        Called from the prober tick; tests with the prober disabled call
        it directly. Returns True when the active epoch changed."""
        with self._lock:
            self.counters["epoch_checks"] += 1
        try:
            rec = EP.read_epoch(self.root)
        except EP.EpochCorrupt:
            return False        # stay on the running epoch; nothing sane on disk
        if rec is None:
            return False
        act = self._active
        pend = self._pending
        if rec.epoch > act.record.epoch and (
                pend is None or pend.record.epoch != rec.epoch):
            # a newer record supersedes any half-warmed pending epoch —
            # its nodes re-advertise under the newest epoch instead
            pend = self._mk_state(rec)
            with self._lock:
                self._pending = pend
        if pend is None:
            return False
        if not plan_fully_warm(pend.plan, self._gather_adverts(pend)):
            return False
        diff = plan_diff(act.plan, pend.plan)
        with self._lock:
            self._active = pend
            self._pending = None
            self.counters["epoch_swaps"] += 1
            self.last_rebalance = {
                "from_epoch": act.record.epoch,
                "to_epoch": pend.record.epoch,
                "strategy": self.strategy, **diff.summary()}
            # the new epoch's nodes re-slice shards from the MANIFEST:
            # pushed-but-uncheckpointed batches are not in their stores,
            # so every read-your-writes confirmation is void. Dropping
            # the state fails the version/confirmation gate and the
            # broker serves those datasources locally — acked batches
            # are its own journaled rows, so an epoch swap can never
            # drop one. In-flight pushes hold references to the OLD
            # state objects and land harmlessly there.
            with self._ingest_lock:
                self._ingested = {}
        return True

    def _gather_adverts(self, st: _EpochState) -> Dict[int, set]:
        """node id -> shard-store names that node advertises warm for
        ``st``'s epoch (extended /readyz). Unreachable nodes simply
        advertise nothing — the gate stays closed until they answer."""
        out: Dict[int, set] = {}
        want = str(st.record.epoch)
        for nid in range(len(st.nodes)):
            _ok, info = self._probe(st, nid)
            ep = ((info or {}).get("epochs") or {}).get(want)
            if isinstance(ep, dict) and ep.get("ready"):
                out[nid] = set(ep.get("shards") or ())
        return out

    # -- health ----------------------------------------------------------------
    def _mark_down(self, st: _EpochState, node_id: int,
                   probe: bool = False) -> None:
        with self._lock:
            if node_id not in st.down:
                st.down[node_id] = _time.time()
                if probe:
                    self.counters["probe_marks_down"] += 1

    def _mark_up(self, st: _EpochState, node_id: int,
                 probe: bool = False) -> None:
        with self._lock:
            if st.down.pop(node_id, None) is not None and probe:
                self.counters["probe_marks_up"] += 1

    def _is_down(self, st: _EpochState, node_id: int) -> bool:
        with self._lock:
            return node_id in st.down

    def _probe_loop(self, interval: float) -> None:
        # decorrelated jitter so N brokers probing the same rejoining
        # historical spread out instead of thundering in lockstep; each
        # tick lands in [interval/2, 1.5*interval]
        rng = _random.Random()
        delay = interval
        while not self._stop.wait(delay):
            try:
                self.check_epoch()
            except Exception:  # noqa: BLE001 — a bad record must not kill probes
                pass
            st = self._active
            depths = []
            for nid in range(len(st.nodes)):
                if self._stop.is_set():
                    return
                ok, info = self._probe(st, nid)
                if ok:
                    self._mark_up(st, nid, probe=True)
                else:
                    self._mark_down(st, nid, probe=True)
                boot = (info or {}).get("boot")
                if boot is not None:
                    prev = st.boot_ids.get(nid)
                    if prev is not None and prev != boot:
                        # same address, new process generation: the
                        # predecessor's circuit state is meaningless
                        st.breakers.reset(nid)
                        with self._lock:
                            self.counters["breaker_resets"] += 1
                    st.boot_ids[nid] = boot
                if ok and self.autoscale is not None:
                    d = self._wlm_depth(st, nid)
                    if d is not None:
                        depths.append(d)
            if self.autoscale is not None:
                self.autoscale.observe(
                    depths,
                    handover_in_progress=self._pending is not None)
            if self.probe_jitter:
                delay = backoff(interval * 0.5, interval * 1.5, 1,
                                prev=delay, rng=rng)
            else:
                delay = interval

    def _probe(self, st: _EpochState, node_id: int):
        """GET /readyz -> (ready, parsed body or None)."""
        host, port = st.nodes[node_id]
        conn = http.client.HTTPConnection(
            host, port, timeout=min(2.0, self.rpc_timeout))
        try:
            conn.request("GET", "/readyz")
            resp = conn.getresponse()
            body = resp.read()
            try:
                info = json.loads(body.decode("utf-8"))
            except ValueError:
                info = None
            return resp.status == 200, info
        except OSError:
            return False, None
        finally:
            conn.close()

    def _wlm_depth(self, st: _EpochState, node_id: int) -> Optional[float]:
        """One node's total queued-query depth (autoscale signal)."""
        host, port = st.nodes[node_id]
        conn = http.client.HTTPConnection(
            host, port, timeout=min(2.0, self.rpc_timeout))
        try:
            conn.request("GET", "/metadata/wlm")
            resp = conn.getresponse()
            body = resp.read()
            if resp.status != 200:
                return None
            lanes = json.loads(body.decode("utf-8")).get("lanes") or []
            return float(sum(ln.get("queued", 0) for ln in lanes))
        except (OSError, ValueError):
            return None
        finally:
            conn.close()

    # -- distributed ingest (read-your-writes) ---------------------------------
    def ingest_begin(self, name: str):
        """First half of a cluster write: called by Context.stream_ingest
        BEFORE the batch is journaled locally, so there is no instant at
        which a batch is acked but not accounted in-flight. Returns a
        token for :meth:`ingest_finish`, or None when the datasource is
        not in the active plan (push pointless — broker-local anyway)."""
        if not self.ingest_push_enabled:
            return None
        st = self._active
        if st.plan.datasources.get(name) is None:
            return None
        with self._ingest_lock:
            state = self._ingested.setdefault(name, {
                "epoch": st.record.epoch, "inflight": 0,
                "version": -1, "target": -1, "shards": {}})
            state["inflight"] += 1
        # the token pins the state OBJECT: an epoch swap mid-push
        # replaces self._ingested wholesale, and a finish landing on the
        # orphaned object can never corrupt the new epoch's accounting
        return (st, state)

    def ingest_finish(self, token, name: str, df, kwargs: dict) -> None:
        """Second half: push the (already locally durable and acked)
        batch to every owner of its time-matched shard, then settle the
        read-your-writes watermark. ``df=None`` means the local apply
        failed — nothing was acked, just release the in-flight slot.
        Never raises: a push failure only costs scatter eligibility."""
        st, state = token
        sh = None
        confirmed: set = set()
        try:
            dp = st.plan.datasources.get(name)
            if df is not None and len(df) and dp is not None:
                sh = self._target_shard(dp, name, df, kwargs)
                if sh is not None:
                    confirmed = self._push_to_owners(
                        st, dp, name, sh, df, kwargs)
        except Exception:  # noqa: BLE001 — ACK already happened; never re-raise
            with self._lock:
                self.counters["ingest_push_failures"] += 1
        finally:
            ver = self.engine.store.datasource_version(name)
            with self._ingest_lock:
                if sh is not None:
                    prior = state["shards"].get(sh.index)
                    if prior is None:
                        # first push to this shard: before it, every
                        # owner held exactly the manifest rows
                        prior = set(sh.owners)
                    state["shards"][sh.index] = prior & confirmed
                # ``target`` tracks the newest local version observed at
                # a settle — when the LAST in-flight push settles, every
                # acked batch has been offered to its owners, so target
                # is exactly the version whose content they confirm
                state["target"] = max(state["target"], ver)
                state["inflight"] -= 1
                if state["inflight"] <= 0:
                    state["inflight"] = 0
                    state["version"] = state["target"]

    def _target_shard(self, dp, name: str, df, kwargs: dict):
        """The shard whose time envelope best matches the batch (max
        overlap; for a batch past every envelope — the common streaming
        case — the nearest, i.e. newest, shard)."""
        shards = dp.shards
        if not shards:
            return None
        tc = kwargs.get("time_column")
        if not tc:
            ds = self.engine.store._datasources.get(name)
            t = getattr(ds, "time", None)
            tc = t.name if t is not None else None
        if not tc or tc not in df.columns:
            return shards[-1]
        from spark_druid_olap_tpu.segment.ingest import _to_epoch_millis
        millis = _to_epoch_millis(df[tc])
        lo, hi = int(millis.min()), int(millis.max())
        best, best_ov = None, None
        for sh in shards:
            ov = min(hi, sh.max_ms) - max(lo, sh.min_ms)
            if best_ov is None or ov > best_ov:
                best, best_ov = sh, ov
        return best

    def _push_to_owners(self, st: _EpochState, dp, name: str, sh, df,
                        kwargs: dict) -> set:
        """Offer one batch to every owner of ``sh``; -> confirmed node
        ids. ALL replicas must apply for scatter read-your-writes to
        hold (a scatter may read any replica), so a down owner simply
        drops out of the confirmed set and the broker serves this
        datasource locally until a checkpoint + epoch re-plan."""
        from spark_druid_olap_tpu.persist.wal import encode_batch
        from spark_druid_olap_tpu.segment.append import wal_kwargs_to_dict
        body = encode_batch(df)
        sname = shard_name(name, sh.index, dp.n_shards)
        with self._ingest_lock:
            bid = self._ingest_seq.get(sname, 0) + 1
            self._ingest_seq[sname] = bid
        payload = WIRE.encode_ingest(name, sname, bid,
                                     wal_kwargs_to_dict(kwargs), body,
                                     src=self._boot_id)
        confirmed = set()
        for nid in sh.owners:
            for _attempt in range(2):       # one retry on connect error
                try:
                    status, _resp = self._ingest_rpc(st, nid, payload)
                except OSError:
                    self._mark_down(st, nid)
                    continue
                self._mark_up(st, nid)
                if status == 200:
                    confirmed.add(nid)
                break       # a coherent non-200 won't improve on retry
        with self._lock:
            self.counters["ingest_pushes"] += 1
            self.counters["ingest_rows_pushed"] += len(df)
            if confirmed != set(sh.owners):
                self.counters["ingest_push_failures"] += 1
        return confirmed

    def _ingest_rpc(self, st: _EpochState, node_id: int,
                    payload: bytes) -> Tuple[int, bytes]:
        inj = self.fault
        key = f"node:{node_id}"
        if inj is not None:
            # chaos site: the push leg dying on the wire (the batch is
            # already durable + acked on the broker; the only stake is
            # scatter eligibility)
            inj.fire("rpc.ingest", key)
        host, port = st.nodes[node_id]
        conn = http.client.HTTPConnection(host, port,
                                          timeout=self.rpc_timeout)
        try:
            conn.request("POST", "/cluster/ingest", payload,
                         {"Content-Type": "application/octet-stream"})
            resp = conn.getresponse()
            body = resp.read()
        finally:
            conn.close()
        return resp.status, body

    def _ryw_state(self, name: str, ver: int) -> Optional[dict]:
        """The push state iff it proves every owner of every touched
        shard holds ALL acked batches for ``name`` at local version
        ``ver`` — i.e. scattering now preserves read-your-writes.
        None -> serve locally (always safe: the broker holds the rows)."""
        st = self._active
        with self._ingest_lock:
            state = self._ingested.get(name)
            if state is None \
                    or state.get("epoch") != st.record.epoch \
                    or state["inflight"] != 0 \
                    or state["version"] != ver:
                return None
            if not all(bool(s) for s in state["shards"].values()):
                return None     # some touched shard lost all its owners
            return {i: tuple(sorted(s))
                    for i, s in state["shards"].items()}

    # -- eligibility -----------------------------------------------------------
    def should_distribute(self, q) -> bool:
        if not isinstance(q, (S.GroupByQuerySpec, S.TimeseriesQuerySpec,
                              S.TopNQuerySpec, S.SelectQuerySpec,
                              S.SearchQuerySpec)):
            return False
        dp = self.plan.datasources.get(getattr(q, "datasource", None))
        if dp is None:
            return False
        # read-your-writes: post-boot ingest/appends bumped the broker's
        # in-memory version past the planned manifest — serve locally so
        # writes are immediately visible, UNLESS the ingest push path
        # proves every owner already applied every acked batch
        ver = self.engine.store.datasource_version(q.datasource)
        if ver != dp.ingest_version \
                and self._ryw_state(q.datasource, ver) is None:
            return False
        # Select/Search carry no aggregations: their merges (concat +
        # re-page, count sum + re-limit) are always closed
        for a in getattr(q, "aggregations", ()):
            if a.kind not in MG.MERGEABLE_KINDS:
                return False
        return True

    # -- scatter / merge -------------------------------------------------------
    def execute(self, q, t0: float) -> Optional[QueryResult]:
        """Distributed answer, or None to run locally (never raises for
        conditions local execution can absorb)."""
        self.counters["queries"] += 1
        # capture the epoch state ONCE: a swap mid-scatter must not mix
        # the old plan with the new node list (old-epoch nodes keep
        # serving through their drain grace precisely for us)
        st = self._active
        try:
            if isinstance(q, S.SelectQuerySpec):
                return self._execute_select(q, st, t0)
            if isinstance(q, S.SearchQuerySpec):
                return self._execute_search(q, st, t0)
            return self._execute_agg(q, st, t0)
        except _LocalFallback as e:
            return self._local(e.reason)

    def _scatter(self, q, sub, st: _EpochState, t0: float):
        """Scatter ``sub`` to every (interval-surviving) shard of the
        query's datasource and drain the replies. Returns
        ``(parts, meta)`` where ``parts`` is ``[(shard_index, data)]``
        in shard-index order (Select needs block order; agg merges are
        order-free) and ``meta`` carries the scatter accounting shared
        by every query shape. Raises :class:`_LocalFallback` whenever
        local execution must take over."""
        try:
            body = json.dumps(SERDE.query_to_dict(sub)).encode("utf-8")
        except (ValueError, TypeError) as e:
            raise _LocalFallback(f"serde: {e}") from e
        dp = st.plan.datasources.get(q.datasource)
        if dp is None:
            raise _LocalFallback("datasource not in the captured plan")
        # read-your-writes scatter: the local version ran past the
        # manifest but the push path confirmed every owner — scatter,
        # restricted to the confirmed replica sets. A version that fails
        # the proof (including races since should_distribute) serves
        # locally, which is always correct.
        ver = self.engine.store.datasource_version(q.datasource)
        ryw = None
        if ver != dp.ingest_version:
            ryw = self._ryw_state(q.datasource, ver)
            if ryw is None:
                raise _LocalFallback(
                    "post-manifest writes not confirmed on owners")
            self.counters["ryw_scatters"] += 1
        deadline = None
        tm = getattr(q.context, "timeout_millis", None)
        if tm:
            deadline = t0 + float(tm) / 1000.0
        # interval pruning: shards are contiguous time blocks, so a shard
        # whose [min_ms, max_ms] envelope cannot overlap any query
        # interval need not be scattered to at all (≈ Druid's time-chunk
        # pruning on the broker). Pushed appends grow a shard PAST its
        # planned envelope, so pruning is off in read-your-writes mode —
        # stale bounds must not prune the shard holding the new rows.
        shards = dp.shards
        pruned = 0
        if getattr(q, "intervals", None) and ryw is None:
            keep = tuple(
                sh for sh in shards
                if any(sh.max_ms >= lo and sh.min_ms < hi
                       for lo, hi in q.intervals))
            pruned = len(shards) - len(keep)
            shards = keep
        self.counters["shards_pruned"] += pruned
        if not shards:
            # every shard outside the interval: the empty answer is
            # cheaper (and shape-exact) on the broker's local engine
            raise _LocalFallback("all shards pruned by query interval")
        partial = bool(self.config.get(CLUSTER_PARTIAL_RESULTS))
        # shard-level cache in front of the scatter: a hit replays the
        # decoded partial (merge never mutates parts) with zero RPCs;
        # keys are (shape, shard, ingest version) — node- and
        # epoch-free, so entries survive topology changes
        bkey = SQC.body_key(body)
        cache = self.subq_cache
        futs = []
        parts, nodes_used = [], set()
        missing, covered_rows, total_rows = [], 0, 0
        cache_hits = 0
        for sh in shards:
            total_rows += sh.rows
            # cache under the broker's LOCAL version (== the manifest
            # version outside read-your-writes mode): every acked append
            # bumps it, so a partial computed over pushed rows can never
            # be replayed for a version that has since grown
            ck = cache.key(bkey, q.datasource, sh.index, dp.n_shards,
                           ver)
            data = cache.get(ck) if cache.enabled else None
            if data is not None:
                cache_hits += 1
                parts.append((sh.index, data))
                covered_rows += sh.rows
                continue
            name = shard_name(q.datasource, sh.index, dp.n_shards)
            owners = sh.owners if ryw is None \
                else ryw.get(sh.index, sh.owners)
            futs.append((sh, ck, self._pool.submit(
                self._run_shard, st, body, name, owners, deadline,
                partial)))
        self.counters["scatters"] += len(futs)
        if cache.enabled:
            self.counters["subq_cache_hits"] += cache_hits
            self.counters["subq_cache_misses"] += len(futs)
        err: Optional[Exception] = None
        for sh, ck, f in futs:
            try:
                data, nid, nbytes = f.result()
                parts.append((sh.index, data))
                nodes_used.add(nid)
                covered_rows += sh.rows
                cache.put(ck, data, nbytes)
            except ShardUnavailable as e:
                # degraded mode: answer from the survivors and say so
                if partial:
                    missing.append(sh.index)
                    continue
                if err is None:
                    err = e
            except Exception as e:  # noqa: BLE001 — every shard must drain
                if err is None:
                    err = e
        if err is not None:
            raise err
        degraded = None
        if missing:
            self.counters["degraded_queries"] += 1
            degraded = {"missing_shards": sorted(missing),
                        "coverage_rows": covered_rows,
                        "total_rows": total_rows}
        parts.sort(key=lambda t: t[0])
        meta = {"shards": len(futs) + cache_hits, "pruned": pruned,
                "nodes_used": nodes_used, "cache_hits": cache_hits,
                "cache_enabled": cache.enabled, "degraded": degraded,
                "epoch": st.record.epoch}
        return parts, meta

    def _finish(self, q, r: QueryResult, meta: dict, merge_ms: float,
                t0: float) -> QueryResult:
        """Shared result annotation for every distributed query shape."""
        self.counters["merge_ms"] += merge_ms
        r.degraded = meta["degraded"]
        cl_stats = {
            "mode": "scatter", "shards": meta["shards"],
            "shards_pruned": meta["pruned"],
            "nodes": sorted(meta["nodes_used"]),
            "epoch": meta["epoch"],
            "merge_ms": round(merge_ms, 3)}
        if meta["cache_enabled"]:
            cl_stats["subq_cache_hits"] = meta["cache_hits"]
        if meta["degraded"] is not None:
            cl_stats["degraded"] = meta["degraded"]
        self.engine.last_stats["cluster"] = cl_stats
        self.engine.last_stats["datasource"] = q.datasource
        self.engine.last_stats["total_ms"] = \
            (_time.perf_counter() - t0) * 1000
        return r

    def _execute_agg(self, q, st: _EpochState, t0: float) -> QueryResult:
        sub, posts, having, limit, key_cols, aggs = _strip(q)
        tagged, meta = self._scatter(q, sub, st, t0)
        parts = [d for _, d in tagged]
        # quantile finalization happens exactly once, here: name ->
        # fraction so the broker's merged KLL registers estimate at the
        # query's asked-for rank (engines shipped raw registers)
        fractions = {a.name: a.fraction for a in q.aggregations
                     if getattr(a, "fraction", None) is not None}
        t_m = _time.perf_counter()
        if parts:
            columns, data, n = MG.merge_partials(parts, key_cols, aggs,
                                                 fractions)
        else:
            # every shard missing (degraded): shape-exact empty answer
            columns, data, n = \
                list(key_cols) + [name for name, _ in aggs], {}, 0
        merge_ms = (_time.perf_counter() - t_m) * 1000
        names = list(columns)
        if n == 0:
            # match the engine's empty-scan shape (posts stay unevaluated)
            names += [p.name for p in posts]
            r = QueryResult.empty(names)
        else:
            data = self.engine._agg_epilogue(data, names, posts, having,
                                             limit)
            r = QueryResult(names, data)
        return self._finish(q, r, meta, merge_ms, t0)

    def _execute_select(self, q: S.SelectQuerySpec, st: _EpochState,
                        t0: float) -> QueryResult:
        """Distributed paged select: every shard answers an EXTENDED
        first page (offset + page_size rows — the broker cannot know
        how the global offset splits across shards), the broker concats
        the blocks in shard-index order (shards are contiguous time
        blocks), re-sorts by the time column when it is in the output
        (stable, so intra-shard row order survives), and re-pages."""
        sub = dataclasses.replace(q, page_size=q.page_offset + q.page_size,
                                  page_offset=0)
        tagged, meta = self._scatter(q, sub, st, t0)
        t_m = _time.perf_counter()
        ds = self.engine.store.get(q.datasource)
        cols = list(q.columns) or ds.column_names()
        blocks = [d for _, d in tagged if d and len(next(iter(d.values())))]
        if q.descending:
            blocks = blocks[::-1]
        if not blocks:
            r = QueryResult.empty(cols)
            return self._finish(
                q, r, meta, (_time.perf_counter() - t_m) * 1000, t0)
        data = {c: np.concatenate([b[c] for b in blocks]) for c in cols}
        tname = ds.time.name if ds.time is not None else None
        if tname is not None and tname in data:
            tv = np.asarray(data[tname])
            if tv.dtype.kind == "M":
                tv = tv.astype("datetime64[ms]").astype(np.int64)
            order = np.argsort(-tv if q.descending else tv, kind="stable")
            data = {c: v[order] for c, v in data.items()}
        page = slice(q.page_offset, q.page_offset + q.page_size)
        data = {c: v[page] for c, v in data.items()}
        r = QueryResult(cols, data)
        return self._finish(
            q, r, meta, (_time.perf_counter() - t_m) * 1000, t0)

    def _execute_search(self, q: S.SearchQuerySpec, st: _EpochState,
                        t0: float) -> QueryResult:
        """Distributed dimension-value search: per-(dimension, value)
        occurrence counts SUM across shards (each shard counted its own
        rows), rows re-sort to the single-engine order — dimensions in
        query order, values in ascending (global-dictionary) order —
        and the limit re-applies after the merge."""
        sub = dataclasses.replace(q, limit=None)
        tagged, meta = self._scatter(q, sub, st, t0)
        t_m = _time.perf_counter()
        value_shape = q.value_output is not None
        vcol = q.value_output if value_shape else "value"
        ccol = q.count_output if value_shape else "count"
        columns = [vcol, ccol] if value_shape \
            else ["dimension", vcol, ccol]
        counts: Dict[tuple, int] = {}
        for _, d in tagged:
            if not d:
                continue
            n = len(d[ccol])
            for i in range(n):
                key = (d[vcol][i],) if value_shape \
                    else (d["dimension"][i], d[vcol][i])
                counts[key] = counts.get(key, 0) + int(d[ccol][i])
        dim_pos = {name: i for i, name in enumerate(q.dimensions)}
        keys = sorted(counts,
                      key=(lambda k: k[0]) if value_shape
                      else (lambda k: (dim_pos.get(k[0], len(dim_pos)),
                                       k[1])))
        if q.limit is not None:
            keys = keys[: q.limit]
        data = {ccol: np.array([counts[k] for k in keys],
                               dtype=np.int64),
                vcol: np.array([k[-1] for k in keys], dtype=object)}
        if not value_shape:
            data["dimension"] = np.array([k[0] for k in keys],
                                         dtype=object)
        r = QueryResult(columns, data)
        return self._finish(
            q, r, meta, (_time.perf_counter() - t_m) * 1000, t0)

    def _local(self, reason: str) -> None:
        self.counters["local_fallbacks"] += 1
        self.engine.last_stats["cluster"] = {"mode": "local",
                                             "reason": reason[:200]}
        return None

    def _run_shard(self, st: _EpochState, body: bytes, shard_ds: str,
                   owners: Tuple[int, ...], deadline: Optional[float],
                   partial: bool = False):
        """One shard's replica chain against one captured epoch state.
        Returns (data dict, serving node, encoded frame bytes). Raises
        _LocalFallback for conditions remote retries cannot fix,
        ShardUnavailable when every replica stayed unreachable (caught
        per shard in partial mode; otherwise strict-mode contract, with
        whole-query local fallback when that is enabled)."""
        payload = WIRE.patch_subquery(body, shard_ds,
                                      epoch=st.record.epoch)
        delay = None
        attempt = 0
        last = "no attempt"
        for _pass in range(self.tries):
            # up-and-closed nodes first; downed / breaker-open replicas
            # are still tried last (the prober may lag a recovery, and a
            # cooled-down breaker admits a half-open probe)
            chain = sorted(owners, key=lambda n: (self._is_down(st, n),
                                                  st.breakers.is_open(n)))
            hedge_after = self._hedge_after_s() if _pass == 0 else None
            for pos, nid in enumerate(chain):
                if deadline is not None and _time.time() >= deadline:
                    raise _LocalFallback("deadline during scatter")
                self.counters["subqueries"] += 1
                if _pass or pos:
                    self.counters["retries"] += 1
                backup = chain[1] if (hedge_after is not None and pos == 0
                                      and len(chain) > 1) else None
                try:
                    status, resp, served = self._attempt(
                        st, nid, payload, deadline, backup, hedge_after)
                except _BreakerOpen as e:
                    last = f"node {e.node_id}: breaker open"
                    continue
                except OSError as e:
                    self.counters["failovers"] += 1
                    last = f"node {nid}: {type(e).__name__}"
                    continue
                if status == 200:
                    try:
                        _, data, _stats = WIRE.decode_result(resp)
                    except ValueError as e:
                        # corrupt / truncated frame: the bytes are bad,
                        # not the query — retryable on a replica
                        self.counters["wire_corrupt"] += 1
                        last = f"node {served}: {e}"
                        continue
                    return data, served, len(resp)
                info = WIRE.decode_error(resp)
                kind = info.get("error", "")
                if kind in ("EngineFallback", "Unsupported", "BadQuery"):
                    # the node cannot answer this query shape; neither
                    # will any replica — run the whole query locally
                    raise _LocalFallback(f"node {served}: {kind}: "
                                         f"{info.get('message', '')[:120]}")
                # AdmissionRejected (node shedding), unknown shard
                # (stale rejoin), Draining (mid-handover fence), or a
                # node-side crash: retryable on a replica / next pass
                last = f"node {served}: http {status} {kind}"
                if status == 404:
                    self._mark_down(st, served)
            delay = backoff(self.backoff_start, self.backoff_cap,
                            attempt, prev=delay)
            attempt += 1
            if self._stop.wait(delay):
                break
        if partial:
            # degraded mode supersedes whole-query local fallback: the
            # caller answers from the surviving shards
            raise ShardUnavailable(
                f"shard {shard_ds} unreachable on nodes {list(owners)} "
                f"after {self.tries} passes ({last})")
        if self.local_fallback:
            raise _LocalFallback(f"replicas exhausted for {shard_ds} "
                                 f"({last})")
        raise ShardUnavailable(
            f"shard {shard_ds} unreachable on nodes {list(owners)} "
            f"after {self.tries} passes ({last})")

    # -- one attempt: breakers + optional hedge --------------------------------
    def _hedge_after_s(self) -> Optional[float]:
        """Hedge delay in seconds, or None when hedging shouldn't run
        (disabled, or the auto quantile has too few samples)."""
        if not self.hedge_enabled:
            return None
        if self.hedge_after_ms > 0:
            return self.hedge_after_ms / 1000.0
        with self._lock:
            lat = sorted(self._latencies)
        if len(lat) < 32:
            return None
        q = lat[min(len(lat) - 1, int(len(lat) * self.hedge_quantile))]
        return max(q, self.hedge_min_ms / 1000.0)

    def _attempt(self, st: _EpochState, nid: int, payload: bytes,
                 deadline: Optional[float],
                 backup: Optional[int], hedge_after: Optional[float]):
        """One subquery attempt against ``nid``, optionally racing a
        hedge to ``backup`` after ``hedge_after`` seconds. Returns
        (status, body, serving node)."""
        if backup is None or hedge_after is None:
            status, resp = self._guarded_rpc(st, nid, payload, deadline)
            return status, resp, nid
        race = _HedgeRace(total=2)
        try:
            for leg_nid, leg_delay in ((nid, 0.0), (backup, hedge_after)):
                threading.Thread(
                    target=self._race_leg,
                    args=(race, st, leg_nid, payload, deadline, leg_delay),
                    name="sdot-hedge", daemon=True).start()
            race.done.wait(self.rpc_timeout + hedge_after + 5.0)
            win, errors = race.result()
        finally:
            race.close()
        if win is not None:
            status, resp, served = win
            if served != nid:
                with self._lock:
                    self.counters["hedges_won"] += 1
            return status, resp, served
        for err_nid, err in errors:     # prefer the primary's error
            if err_nid == nid:
                raise err
        if errors:
            raise errors[0][1]
        raise OSError(f"hedge race against nodes {nid}/{backup} timed out")

    def _race_leg(self, race: _HedgeRace, st: _EpochState, nid: int,
                  payload: bytes, deadline: Optional[float],
                  delay_s: float) -> None:
        out, err = None, None
        try:
            if delay_s > 0:
                if race.done.wait(delay_s) or race.cancelled:
                    return          # primary answered inside the delay
                with self._lock:
                    self.counters["hedges_launched"] += 1
            try:
                status, resp = self._guarded_rpc(st, nid, payload, deadline)
                out = (status, resp, nid)
            except (_BreakerOpen, OSError) as e:
                err = e
        finally:
            race.settle(nid, out, err)

    def _guarded_rpc(self, st: _EpochState, node_id: int, payload: bytes,
                     deadline: Optional[float],
                     path: str = "/cluster/subquery") -> Tuple[int, bytes]:
        """_rpc wrapped in the node's circuit breaker + health marks."""
        tok = st.breakers.before_attempt(node_id)
        ok = False
        try:
            if tok is None:
                raise _BreakerOpen(node_id)
            try:
                status, resp = self._rpc(st, node_id, payload, deadline,
                                         path=path)
            except OSError:
                self._mark_down(st, node_id)
                raise
            ok = status < 500       # any coherent reply = node is alive
        finally:
            if tok is not None:
                st.breakers.settle(tok, ok)
        self._mark_up(st, node_id)
        return status, resp

    def _rpc(self, st: _EpochState, node_id: int, payload: bytes,
             deadline: Optional[float],
             path: str = "/cluster/subquery") -> Tuple[int, bytes]:
        inj = self.fault
        key = f"node:{node_id}"
        if inj is not None:
            inj.fire("rpc.connect", key)
        host, port = st.nodes[node_id]
        timeout = self.rpc_timeout
        if deadline is not None:
            timeout = max(0.05, min(timeout, deadline - _time.time()))
        t0 = _time.perf_counter()
        conn = http.client.HTTPConnection(host, port, timeout=timeout)
        try:
            if inj is not None:
                inj.fire("rpc.request", key)
            ctype = "application/json" if path == "/cluster/subquery" \
                else "application/octet-stream"
            conn.request("POST", path, payload, {"Content-Type": ctype})
            resp = conn.getresponse()
            body = resp.read()
        finally:
            conn.close()
        with self._lock:
            self._latencies.append(_time.perf_counter() - t0)
        if inj is not None:
            body = inj.mutate("rpc.response", body, key)
        return resp.status, body

    # -- introspection ---------------------------------------------------------
    def stats(self) -> dict:
        st = self._active
        pend = self._pending
        with self._lock:
            down = {nid: round(_time.time() - t, 1)
                    for nid, t in st.down.items()}
            counters = dict(self.counters)
            rebalance = dict(self.last_rebalance) \
                if self.last_rebalance else None
        out = {
            "enabled": True,
            "nodes": [{"id": i, "host": h, "port": p,
                       "key": st.record.ids[i],
                       "state": "down" if i in down else "up",
                       "down_seconds": down.get(i)}
                      for i, (h, p) in enumerate(st.nodes)],
            "replication": st.plan.replication,
            "breakers": st.breakers.snapshot(),
            "datasources": {
                name: {"shards": dp.n_shards,
                       "segments": dp.num_segments,
                       "rows": dp.num_rows,
                       "ingest_version": dp.ingest_version,
                       "owners": {str(sh.index): list(sh.owners)
                                  for sh in dp.shards}}
                for name, dp in st.plan.datasources.items()},
            "counters": counters,
            "epoch": {"active": st.record.epoch,
                      "pending": pend.record.epoch
                      if pend is not None else None,
                      "strategy": self.strategy},
            "rebalance": rebalance,
            "subq_cache": self.subq_cache.stats(),
            "ingest": self._ingest_stats(),
        }
        if self.autoscale is not None:
            out["autoscale"] = self.autoscale.stats()
        return out

    def _ingest_stats(self) -> dict:
        with self._ingest_lock:
            return {
                "push_enabled": self.ingest_push_enabled,
                "datasources": {
                    name: {"version": state["version"],
                           "inflight": state["inflight"],
                           "shards": {str(i): sorted(s) for i, s in
                                      state["shards"].items()}}
                    for name, state in self._ingested.items()}}


def _strip(q):
    """(subquery, posts, having, limit, key_cols, aggs) — the subquery
    keeps scan phases (filter, granularity, intervals, aggregations);
    everything that must see ALL groups (post-aggs, HAVING, ORDER
    BY/LIMIT, TopN threshold) runs broker-side after the merge."""
    gran = getattr(q, "granularity", None)
    gran_kind = gran.kind if gran is not None else "all"
    if isinstance(q, S.TopNQuerySpec):
        sub = S.GroupByQuerySpec(
            datasource=q.datasource, dimensions=(q.dimension,),
            aggregations=q.aggregations, post_aggregations=(),
            filter=q.filter, having=None, limit=None,
            granularity=q.granularity, intervals=q.intervals,
            context=q.context)
        posts = q.post_aggregations
        having = None
        limit = S.topn_limit(q)
        dims = (q.dimension,)
    elif isinstance(q, S.GroupByQuerySpec):
        sub = dataclasses.replace(q, post_aggregations=(), having=None,
                                  limit=None)
        posts, having, limit = q.post_aggregations, q.having, q.limit
        dims = q.dimensions
    else:
        sub = dataclasses.replace(q, post_aggregations=())
        posts, having, limit = q.post_aggregations, None, None
        dims = ()
    key_cols = (["timestamp"] if gran_kind != "all" else []) \
        + [d.output_name for d in dims]
    aggs = [(a.name, a.kind) for a in q.aggregations]
    return sub, posts, having, limit, key_cols, aggs
