"""Broker-side merge of per-shard partial aggregates.

Exactness contract (the failover acceptance bar is byte-identical
answers vs single-engine execution):

- integer sums accumulate as Python ints (arbitrary precision), so a
  sum that overflows int64 across shards still matches the engine's
  wide-int object columns;
- float sums accumulate in float64 skipping NaN identity cells
  (all-NaN group -> NaN, matching ``_identity_row``);
- min/max are NaN/None-aware with the same null-wins-never rule;
- sketch aggregates merge RAW registers (HLL: elementwise max, theta:
  elementwise min, KLL: lex-min survivor + exact count sum — all
  associative and commutative) and the estimate is finalized ONCE
  here, so the distributed estimate equals the single-engine estimate
  exactly, not approximately.

The mergeable-kind set derives from ``ops/agg_registry.AGG_CLOSURE``
(the declared merge closure): anything routed sum/min/max/count or
sketch-valued is distributable. ``anyvalue`` is excluded on purpose —
its "pick any" contract is only deterministic within one engine's scan
order, and the broker must never change an answer.
"""

from __future__ import annotations

from typing import Dict, List, Sequence, Tuple

import numpy as np

from spark_druid_olap_tpu.ops import hll as HLL
from spark_druid_olap_tpu.ops import kll as KLL
from spark_druid_olap_tpu.ops import theta as TH
from spark_druid_olap_tpu.ops.agg_registry import AGG_CLOSURE

# druid-level agg kind -> merge op
MERGE_OP: Dict[str, str] = {}
for _k, _spec in AGG_CLOSURE.items():
    if _spec["sketch"] is not None:
        MERGE_OP[_k] = _spec["sketch"]                  # hll | theta | kll
    elif _k != "anyvalue" and _spec["route"] in ("count", "sum"):
        MERGE_OP[_k] = "sum"
    elif _k != "anyvalue" and _spec["route"] in ("min", "max"):
        MERGE_OP[_k] = _spec["route"]

MERGEABLE_KINDS = frozenset(MERGE_OP)


def _is_null(v) -> bool:
    if v is None:
        return True
    if isinstance(v, (float, np.floating)):
        return bool(np.isnan(v))
    return False


def _sort_token(v):
    # None sorts first; within a column all non-null cells share a type
    return (0, 0) if v is None else (1, v)


def _key_cell(v):
    """Group-key cell normalized for dict identity: NaN / NaT become
    None (NaN != NaN would split one null group per shard). np.array
    re-materializes None as NaN/NaT under the saved key dtype."""
    if isinstance(v, (float, np.floating)) and np.isnan(v):
        return None
    if isinstance(v, np.datetime64) and np.isnat(v):
        return None
    return v


class _Acc:
    """One group's accumulators, one slot per aggregate column."""

    __slots__ = ("slots",)

    def __init__(self, n: int):
        self.slots = [None] * n


def merge_partials(parts: Sequence[Dict[str, np.ndarray]],
                   key_cols: Sequence[str],
                   aggs: Sequence[Tuple[str, str]],
                   fractions: Dict[str, float] = None,
                   ) -> Tuple[List[str], Dict[str, np.ndarray], int]:
    """Merge shard partials into one canonical result.

    ``parts``: per-shard column dicts (every part carries all key and
    agg columns). ``aggs``: (output name, druid kind) in output order.
    ``fractions``: output name -> quantile fraction for 'quantile'
    aggregations (the broker finalizes each merged KLL register row at
    that fraction, defaulting to the median).
    Returns (columns, data, n_rows) with rows canonically sorted by the
    key tuple (None first) — the epilogue's own ORDER BY re-sorts when
    the query asks for one, and the canonical order makes unordered
    results deterministic regardless of shard arrival order."""
    fractions = fractions or {}
    ops = [(name, MERGE_OP[kind]) for name, kind in aggs]
    groups: Dict[tuple, _Acc] = {}
    float_domain = {name: False for name, _ in ops}
    key_dtypes: Dict[str, np.dtype] = {}

    for data in parts:
        if not data:
            continue
        n = len(data[key_cols[0]]) if key_cols else (
            len(data[ops[0][0]]) if ops else 0)
        kcols = []
        for k in key_cols:
            arr = data[k]
            if k not in key_dtypes and arr.dtype != object:
                key_dtypes[k] = arr.dtype
            kcols.append(arr)
        acols = []
        for name, op in ops:
            arr = data[name]
            if op == "sum" and arr.dtype != object \
                    and arr.dtype.kind == "f":
                float_domain[name] = True
            acols.append(arr)
        for i in range(n):
            key = tuple(_key_cell(c[i]) for c in kcols)
            acc = groups.get(key)
            if acc is None:
                acc = groups[key] = _Acc(len(ops))
            slots = acc.slots
            for j, (_, op) in enumerate(ops):
                v = acols[j][i]
                if op in ("hll", "theta", "kll"):
                    # v is a 1-D register row — EXCEPT when the shard's
                    # segments all pruned away and its engine emitted
                    # the scalar identity (_identity_row): that cell
                    # carries no registers and merges as a no-op
                    if not isinstance(v, np.ndarray) or v.ndim != 1:
                        continue
                    # copy on first sight so the in-place merge never
                    # writes a buffer another group row shares
                    if slots[j] is None:
                        slots[j] = np.array(v, copy=True)
                    elif op == "hll":
                        np.maximum(slots[j], v, out=slots[j])
                    elif op == "kll":
                        slots[j] = KLL.merge(slots[j], v)
                    else:
                        np.minimum(slots[j], v, out=slots[j])
                    continue
                if _is_null(v):
                    continue
                if isinstance(v, np.generic):
                    v = v.item()
                cur = slots[j]
                if cur is None:
                    slots[j] = v
                elif op == "sum":
                    slots[j] = cur + v
                elif op == "min":
                    slots[j] = v if v < cur else cur
                else:
                    slots[j] = v if v > cur else cur

    keys = sorted(groups, key=lambda t: tuple(_sort_token(v) for v in t))
    n_out = len(keys)
    columns = list(key_cols) + [name for name, _ in ops]
    data_out: Dict[str, np.ndarray] = {}
    for ki, k in enumerate(key_cols):
        vals = [key[ki] for key in keys]
        dt = key_dtypes.get(k)
        if dt is not None:
            data_out[k] = np.array(vals, dtype=dt)
        else:
            arr = np.empty(n_out, dtype=object)
            for i, v in enumerate(vals):
                arr[i] = v
            data_out[k] = arr
    for j, (name, op) in enumerate(ops):
        cells = [groups[key].slots[j] for key in keys]
        if op in ("hll", "theta", "kll"):
            m = next((len(c) for c in cells if c is not None), 0)
            if n_out == 0 or m == 0:
                # no shard contributed registers: count sketches
                # estimate 0, quantile sketches estimate NaN
                data_out[name] = (
                    np.full(n_out, np.nan, dtype=np.float64)
                    if op == "kll" else np.zeros(n_out, dtype=np.int64))
                continue
            # a group no shard had registers for uses the empty-register
            # identity (hll: all-zero registers, theta: all-one lane
            # minima, kll: all-EMPTY survivors / zero counts) — count
            # sketches estimate 0, a quantile of nothing is NaN
            if op == "kll":
                fill = KLL.identity_registers(m)
                regs = np.stack([fill if c is None else c for c in cells])
                data_out[name] = KLL.estimate(
                    regs, fractions.get(name, 0.5))
                continue
            fill = np.zeros(m, dtype=np.int64) if op == "hll" \
                else np.ones(m, dtype=np.float64)
            regs = np.stack([fill if c is None else c for c in cells])
            est = HLL.estimate(regs) if op == "hll" else TH.estimate(regs)
            data_out[name] = np.round(est).astype(np.int64)
            continue
        data_out[name] = _finalize_scalar(cells, float_domain[name])
    return columns, data_out, n_out


def _finalize_scalar(cells: List, force_float: bool) -> np.ndarray:
    """Column from merged scalar accumulators, matching engine dtypes:
    float64 (NaN nulls) for float-domain columns, int64 when every int
    fits, else object (wide ints / None nulls, the epilogue's
    object-column comparators handle these)."""
    if force_float or any(isinstance(v, float) for v in cells):
        return np.array([np.nan if v is None else float(v)
                         for v in cells], dtype=np.float64)
    if all(v is not None for v in cells):
        if all(-(2 ** 63) <= v < 2 ** 63 for v in cells):
            return np.array(cells, dtype=np.int64)
        arr = np.empty(len(cells), dtype=object)
        for i, v in enumerate(cells):
            arr[i] = v
        return arr
    if not any(v is not None for v in cells):
        # every group null (e.g. min over no non-null rows): engine
        # emits float64 NaN for numeric nulls
        return np.full(len(cells), np.nan, dtype=np.float64)
    arr = np.empty(len(cells), dtype=object)
    for i, v in enumerate(cells):
        arr[i] = v
    return arr
