"""Historical node: boot from deep storage, serve owned shards.

Boot sequence (order matters for the health contract):

1. the HTTP server starts FIRST — ``/healthz`` answers immediately,
   ``/readyz`` answers 503 until boot completes, so orchestrators and
   the broker's prober can watch recovery progress;
2. a full ``Context`` is created over the shared persist root —
   ``PersistManager.recover()`` rebuilds every datasource from
   snapshots + WAL tails exactly as a single-process engine would;
3. the node computes the SAME shard plan as the broker (pure function
   of deep storage + the node list), slices each owned shard out of the
   recovered datasource with ``segment/store.py:slice_segments``,
   registers it under its shard name at the manifest's ingest version,
   and drops the full datasource — memory is bounded by owned rows;
4. ``ready`` flips True; ``/readyz`` goes 200 and the broker routes
   primary traffic here.

The subquery RPC wraps the ordinary ``QueryEngine.execute``: WLM lane
admission, the per-node result cache, and shared-scan coalescing all
apply to subqueries, so each historical absorbs its own slice of a
dashboard storm. ``partial_sketches`` makes sketch aggregates return
raw registers for the broker's exact register merge.

A datasource whose recovered state runs PAST the planned manifest (WAL
tail appended after the last checkpoint) is kept whole and unsliced:
the broker's matching ingest-version check already serves it locally,
and slicing would silently drop the WAL rows here.

Elastic topology (cluster/epoch.py): a watcher thread polls deep
storage for a newer plan epoch and runs this node's side of the
handover —

- still a member: **warm before advertise** — newly owned shards are
  re-recovered from the cold tier (``PersistManager.restore`` +
  ``slice_tiered``/``slice_segments``) while the node keeps serving the
  old epoch; only when every new shard is registered does the node
  advertise the epoch on the extended ``/readyz``, which is what the
  broker's swap gate reads. Old-epoch-only shard stores are retired
  lazily, once a request stamped with the new epoch proves the broker
  has swapped.
- dropped from the record: **drain then fence** — the node keeps
  serving until it observes the same every-shard-warm condition the
  broker gates on (``assign.plan_fully_warm``), waits a grace period
  for the broker's poll lag, fires the ``node.drain`` chaos site, stops
  admitting subqueries (503 ``Draining``), waits for in-flight ones to
  finish (bounded), and fences. The begin/end subquery pair is
  registered with the sdlint leaks pass: no path may leave drain
  holding an in-flight count.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, Optional
from urllib.parse import urlparse

from spark_druid_olap_tpu.cluster import epoch as EP
from spark_druid_olap_tpu.cluster import wire as WIRE
from spark_druid_olap_tpu.cluster.assign import (
    parse_nodes, plan_cluster, plan_fully_warm, shard_name)
from spark_druid_olap_tpu.server.http import SqlServer
from spark_druid_olap_tpu.utils.config import (
    CLUSTER_EPOCH_DRAIN_GRACE_SECONDS,
    CLUSTER_EPOCH_DRAIN_TIMEOUT_SECONDS,
    CLUSTER_EPOCH_POLL_SECONDS,
    CLUSTER_NODE_ID,
    CLUSTER_NODES,
    CLUSTER_REBALANCE_STRATEGY,
    CLUSTER_REPLICATION,
    CLUSTER_ROLE,
    CLUSTER_SHARDS,
    PERSIST_PATH,
)


class DrainGate:
    """In-flight subquery accounting for the leave protocol. Every
    admitted subquery holds a token from :meth:`begin_subquery` that
    MUST be returned via :meth:`end_subquery` (sdlint leaks pair);
    after :meth:`start_drain` no new tokens are issued and
    :meth:`wait_drained` blocks until the outstanding ones return."""

    def __init__(self):
        self._lock = threading.Lock()   # leaf — never calls out while held
        self._inflight = 0
        self._draining = False
        self._idle = threading.Event()
        self._idle.set()

    @property
    def draining(self) -> bool:
        return self._draining

    @property
    def inflight(self) -> int:
        return self._inflight

    def begin_subquery(self):
        """Admit one subquery; None once draining (caller fences)."""
        with self._lock:
            if self._draining:
                return None
            self._inflight += 1
            self._idle.clear()
            return True

    def end_subquery(self, tok) -> None:
        if tok is None:
            return
        with self._lock:
            self._inflight -= 1
            if self._inflight <= 0:
                self._idle.set()

    def start_drain(self) -> None:
        with self._lock:
            self._draining = True
            if self._inflight <= 0:
                self._idle.set()

    def wait_drained(self, timeout_s: float) -> bool:
        """True when every in-flight subquery finished in time."""
        return self._idle.wait(timeout_s)


class HistoricalServer(SqlServer):
    """SqlServer + the cluster subquery RPC. Everything else — /sql,
    /metadata/*, /healthz — is inherited, so a historical is also a
    directly-queryable engine over its shards (handy for debugging a
    single node's slice)."""

    def __init__(self, node: "HistoricalNode", host: str, port: int):
        super().__init__(None, host, port)   # ctx attaches after boot
        self.node = node
        self.ready_check = lambda: node.ready
        # extended readiness: per-epoch shard adverts (the broker's
        # handover gate), the boot generation (breaker reset on rejoin)
        # and the draining flag — all plain attribute reads, keeping
        # the lock-free /readyz contract
        self.ready_info = node.ready_info

    def _handle_post(self, h):
        path = urlparse(h.path).path
        if path == "/cluster/subquery":
            n = int(h.headers.get("Content-Length", "0"))
            raw = h.rfile.read(n) if n else b"{}"
            code, body, ctype = self.node.handle_subquery(raw)
            h._send(code, body, ctype)
            return
        if path == "/cluster/ingest":
            n = int(h.headers.get("Content-Length", "0"))
            raw = h.rfile.read(n) if n else b""
            code, body, ctype = self.node.handle_ingest(raw)
            h._send(code, body, ctype)
            return
        if path == "/cluster/join/partition":
            n = int(h.headers.get("Content-Length", "0"))
            raw = h.rfile.read(n) if n else b"{}"
            code, body, ctype = self.node.handle_join_partition(raw)
            h._send(code, body, ctype)
            return
        if path == "/cluster/join/exec":
            n = int(h.headers.get("Content-Length", "0"))
            raw = h.rfile.read(n) if n else b""
            code, body, ctype = self.node.handle_join_exec(raw)
            h._send(code, body, ctype)
            return
        super()._handle_post(h)


class HistoricalNode:
    """One serving process. ``overrides`` is the shared cluster config
    (persist path, node list, replication, shard count) — identical on
    every member, which is what makes the independently-computed plans
    identical."""

    def __init__(self, overrides: Optional[dict] = None,
                 node_id: Optional[int] = None):
        from spark_druid_olap_tpu.utils.config import Config
        self.overrides = dict(overrides or {})
        self.overrides[CLUSTER_ROLE.key] = "historical"
        cfg = Config(self.overrides)
        self.addresses = parse_nodes(str(cfg.get(CLUSTER_NODES)))
        if not self.addresses:
            raise ValueError("HistoricalNode needs sdot.cluster.nodes")
        if node_id is None:
            node_id = int(cfg.get(CLUSTER_NODE_ID))
        self.node_id = int(node_id)
        self.overrides[CLUSTER_NODE_ID.key] = self.node_id
        if not 0 <= self.node_id < len(self.addresses):
            raise ValueError(
                f"node id {self.node_id} outside the node list "
                f"(n={len(self.addresses)})")
        # this process's identity is its ADDRESS; node_id is just its
        # index within the current epoch's node list and is recomputed
        # on every epoch change
        host, port = self.addresses[self.node_id]
        self.address = f"{host}:{port}"
        # fresh per process: a broker seeing this change behind the same
        # address resets that node's breaker (rejoin must not inherit
        # the predecessor's open circuit)
        self.boot_id = f"{os.getpid()}.{time.time_ns()}"
        self.ready = False
        self.ctx = None
        self.plan = None
        self.epoch_record: Optional[EP.EpochRecord] = None
        self.shards_loaded = 0
        self.shards_warmed = 0          # via epoch handover, post-boot
        self.epochs_joined = 0
        self.drain = DrainGate()
        self.fenced = False
        self._epochs: Dict[int, dict] = {}   # epoch -> readyz advert
        self._max_req_epoch = 0              # newest clusterEpoch seen
        # distributed ingest: pushed batches apply serially per node,
        # deduped on (broker boot generation, push counter) so a broker
        # retry after a lost ACK never double-applies rows
        self._ingest_lock = threading.Lock()
        # shard -> (src, watermark, pending ids > watermark): concurrent
        # producers race their pushes onto the wire, so batch ids arrive
        # OUT OF ORDER per shard — a high-watermark alone would swallow
        # a late-arriving earlier id as a duplicate (confirmed but never
        # applied, silently breaking scatter read-your-writes)
        self._applied_batches: Dict[str, tuple] = {}
        self.batches_applied = 0
        self.batch_rows_applied = 0
        self._watch_stop = threading.Event()
        self._watcher: Optional[threading.Thread] = None
        self.server: Optional[HistoricalServer] = None

    # -- lifecycle ------------------------------------------------------------
    def start(self, background: bool = True) -> "HistoricalNode":
        host, port = self.addresses[self.node_id]
        self.server = HistoricalServer(self, host, port)
        self.server.start(background=True)
        self.boot()
        if not background:
            try:
                threading.Event().wait()
            except KeyboardInterrupt:
                pass
            finally:
                self.stop()
        return self

    def boot(self) -> None:
        import spark_druid_olap_tpu as sdot
        self.ctx = sdot.Context(self.overrides)
        self.server.ctx = self.ctx
        # sketch aggregates ship raw registers to the broker (both the
        # solo and the fused shared-scan decode honor this flag)
        self.ctx.engine.partial_sketches = True
        cfg = self.ctx.config
        # a published epoch record supersedes the static config list;
        # with none, the implicit bootstrap epoch 0 reproduces the
        # pre-elasticity behavior byte for byte
        rec = EP.read_epoch(cfg.get(PERSIST_PATH))
        if rec is None:
            rec = EP.bootstrap_record(
                tuple(f"{h}:{p}" for h, p in self.addresses))
        self.epoch_record = rec
        my = rec.nodes.index(self.address) if self.address in rec.nodes \
            else None
        self.plan = plan_cluster(
            cfg.get(PERSIST_PATH), len(rec.nodes),
            int(cfg.get(CLUSTER_REPLICATION)),
            int(cfg.get(CLUSTER_SHARDS)),
            node_keys=rec.ids, epoch=rec.epoch,
            strategy=str(cfg.get(CLUSTER_REBALANCE_STRATEGY)))
        if my is not None:
            self.node_id = my
            self._load_shards()
            self._advertise(rec.epoch)
        # a node booted BEFORE the epoch that adds it: serve nothing,
        # stay process-ready, and let the watcher warm it on join
        self.ready = True
        poll = float(cfg.get(CLUSTER_EPOCH_POLL_SECONDS))
        if poll > 0:
            self._watcher = threading.Thread(
                target=self._watch_loop, args=(poll,),
                name="sdot-epoch-watch", daemon=True)
            self._watcher.start()

    def stop(self) -> None:
        self.ready = False
        self._watch_stop.set()
        if self._watcher is not None:
            self._watcher.join(timeout=2.0)
            self._watcher = None
        if self.server is not None:
            self.server.stop()
        if self.ctx is not None:
            self.ctx.close()

    def _load_shards(self) -> None:
        from spark_druid_olap_tpu.segment.store import slice_segments
        store = self.ctx.store
        owned_by_ds = self.plan.shards_of(self.node_id)
        for name in store.names():
            dp = self.plan.datasources.get(name)
            if dp is None:
                # WAL-only datasource (no published manifest): not in
                # the plan, broker serves it locally — keep it whole
                continue
            if store.datasource_version(name) != dp.ingest_version \
                    or store.get(name).num_segments != dp.num_segments:
                # recovery replayed WAL past the planned snapshot;
                # slicing by manifest segment indexes would drop those
                # rows. Keep whole — the broker's version check routes
                # this datasource locally until the next checkpoint.
                continue
            full = store.get(name)
            tiered = getattr(full, "tier", None) is not None
            if tiered:
                from spark_druid_olap_tpu.tier.loader import slice_tiered
            for sh in owned_by_ds.get(name, ()):
                sname = shard_name(name, sh.index, dp.n_shards)
                # tiered recovery: shards stay loadable handles, so the
                # node's hot set covers ONLY its owned segments' bytes
                # and boots without faulting the whole datasource
                shard = slice_tiered(full, sh.segment_indexes,
                                     name=sname) if tiered \
                    else slice_segments(full, sh.segment_indexes,
                                        name=sname)
                store.restore(shard, ingest_version=dp.ingest_version)
                self.shards_loaded += 1
            # serve ONLY owned shards: per-node memory is bounded by
            # assigned rows, the point of the tier
            store.drop(name)

    # -- epoch lifecycle -------------------------------------------------------
    def _advertise(self, epoch: int) -> None:
        """Publish this node's warm-shard set for ``epoch`` on the
        extended /readyz. Only fully-warmed epochs are ever advertised —
        the broker's swap gate reads exactly this."""
        owned = []
        for name, shs in self.plan.shards_of(self.node_id).items():
            dp = self.plan.datasources[name]
            owned += [shard_name(name, sh.index, dp.n_shards)
                      for sh in shs]
        self._epochs[epoch] = {"ready": True, "shards": sorted(owned)}
        for e in sorted(self._epochs)[:-2]:
            del self._epochs[e]     # older epochs can no longer swap in

    def ready_info(self) -> dict:
        """Extra /readyz fields (lock-free: attribute reads only)."""
        rec = self.epoch_record
        return {"node": self.node_id, "boot": self.boot_id,
                "epoch": rec.epoch if rec is not None else None,
                "draining": self.drain.draining,
                "epochs": dict(self._epochs)}

    def _watch_loop(self, poll_s: float) -> None:
        while not self._watch_stop.wait(poll_s):
            try:
                self.check_epoch()
            except Exception:  # noqa: BLE001 — a bad record must not kill the node
                pass
            if self.fenced:
                return

    def check_epoch(self) -> Optional[str]:
        """Run one step of this node's handover dance against the
        current deep-storage epoch record. Called from the watcher
        thread; tests with the watcher disabled call it directly.
        Returns "warmed" (joined / rebalanced into the new epoch),
        "left" (drained and fenced), or None (nothing newer)."""
        if self.ctx is None or self.fenced:
            return None
        cfg = self.ctx.config
        root = cfg.get(PERSIST_PATH)
        try:
            rec = EP.read_epoch(root)
        except EP.EpochCorrupt:
            return None             # stay on the running epoch
        cur = self.epoch_record
        if rec is None or (cur is not None and rec.epoch <= cur.epoch):
            self._retire_stale()
            return None
        new_plan = plan_cluster(
            root, len(rec.nodes),
            int(cfg.get(CLUSTER_REPLICATION)),
            int(cfg.get(CLUSTER_SHARDS)),
            node_keys=rec.ids, epoch=rec.epoch,
            strategy=str(cfg.get(CLUSTER_REBALANCE_STRATEGY)))
        if self.address in rec.nodes:
            self._warm_epoch(rec, new_plan)
            return "warmed"
        self._leave(rec, new_plan)
        return "left"

    def _warm_epoch(self, rec: EP.EpochRecord, new_plan) -> None:
        """Warm every newly owned shard from the cold tier, THEN flip
        to the new epoch and advertise. The node keeps serving the old
        epoch's shards throughout (both shard-store sets coexist until
        a new-epoch request proves the broker swapped)."""
        from spark_druid_olap_tpu.segment.store import slice_segments
        my = rec.nodes.index(self.address)
        store = self.ctx.store
        for name, shs in new_plan.shards_of(my).items():
            dp = new_plan.datasources[name]
            have = set(store.names())
            need = [sh for sh in shs
                    if shard_name(name, sh.index, dp.n_shards) not in have]
            if not need:
                continue
            had_full = name in have
            if not had_full:
                # re-materialize the full datasource from deep storage
                # (tiered snapshots recover as loadable handles, so this
                # faults in only what slicing touches)
                self.ctx.persist.restore(name)
            full = store.get(name)
            if store.datasource_version(name) != dp.ingest_version \
                    or full.num_segments != dp.num_segments:
                # WAL past the manifest (see _load_shards): every broker
                # recovered the same tail and serves this datasource
                # locally, so its shards are vacuously warm
                if not had_full:
                    store.drop(name)
                continue
            tiered = getattr(full, "tier", None) is not None
            if tiered:
                from spark_druid_olap_tpu.tier.loader import slice_tiered
            for sh in need:
                sname = shard_name(name, sh.index, dp.n_shards)
                shard = slice_tiered(full, sh.segment_indexes,
                                     name=sname) if tiered \
                    else slice_segments(full, sh.segment_indexes,
                                        name=sname)
                store.restore(shard, ingest_version=dp.ingest_version)
                # a freshly sliced shard holds only manifest rows:
                # pushed-batch history no longer applies to it
                with self._ingest_lock:
                    self._applied_batches.pop(sname, None)
                self.shards_warmed += 1
            if not had_full:
                store.drop(name)
        self.plan = new_plan
        self.node_id = my
        self.epoch_record = rec
        self.epochs_joined += 1
        self._advertise(rec.epoch)

    def _leave(self, rec: EP.EpochRecord, new_plan) -> None:
        """The new epoch dropped this node: keep serving until the new
        epoch can answer without us, then drain in-flight subqueries
        and fence."""
        cfg = self.ctx.config
        grace = float(cfg.get(CLUSTER_EPOCH_DRAIN_GRACE_SECONDS))
        timeout = float(cfg.get(CLUSTER_EPOCH_DRAIN_TIMEOUT_SECONDS))
        deadline = time.monotonic() + timeout
        # same pure gate the broker swaps on: neither side can observe
        # "ready" before the other could
        while (time.monotonic() < deadline
               and not self._watch_stop.is_set()
               and not plan_fully_warm(new_plan,
                                       self._gather_adverts(rec))):
            self._watch_stop.wait(0.05)
        # absorb the broker's poll lag: it may still scatter the OLD
        # epoch at us for one more probe interval after warm
        self._watch_stop.wait(grace)
        inj = getattr(self.ctx.engine, "fault", None)
        if inj is not None:
            from spark_druid_olap_tpu.fault import FaultInjected
            try:
                # chaos site: an error rule models the node dying
                # mid-handover instead of draining gracefully
                inj.fire("node.drain", key=f"node:{self.node_id}")
            except FaultInjected:
                self.drain.start_drain()    # hard fence, no drain wait
                self._fence(rec, new_plan)
                return
        self.drain.start_drain()
        # bounded: a stuck query must not pin a retired node forever
        self.drain.wait_drained(timeout)
        self._fence(rec, new_plan)

    def _fence(self, rec: EP.EpochRecord, new_plan) -> None:
        self.fenced = True
        self.ready = False              # /readyz goes 503
        self.epoch_record = rec
        self.plan = new_plan
        self._epochs.clear()            # advertise nothing

    def _gather_adverts(self, rec: EP.EpochRecord) -> Dict[int, set]:
        """node id -> warm shard names advertised for ``rec``'s epoch
        (same shape the broker gathers; unreachable nodes advertise
        nothing)."""
        import http.client
        out: Dict[int, set] = {}
        want = str(rec.epoch)
        for nid, (host, port) in enumerate(rec.addresses):
            conn = http.client.HTTPConnection(host, port, timeout=2.0)
            try:
                conn.request("GET", "/readyz")
                resp = conn.getresponse()
                info = json.loads(resp.read().decode("utf-8"))
                ep = (info.get("epochs") or {}).get(want)
                if isinstance(ep, dict) and ep.get("ready"):
                    out[nid] = set(ep.get("shards") or ())
            except (OSError, ValueError):
                pass
            finally:
                conn.close()
        return out

    def _retire_stale(self) -> None:
        """Drop shard stores the current plan no longer assigns here —
        but only after a request stamped with the current (or a newer)
        epoch proves the requesting broker swapped; until then the old
        epoch's scatters still need them."""
        rec = self.epoch_record
        if rec is None or self.plan is None \
                or self._max_req_epoch < rec.epoch:
            return
        keep = set()
        for name, shs in self.plan.shards_of(self.node_id).items():
            dp = self.plan.datasources[name]
            keep |= {shard_name(name, sh.index, dp.n_shards)
                     for sh in shs}
        store = self.ctx.store
        for n in list(store.names()):
            if "::shard" in n and n not in keep:
                store.drop(n)

    # -- RPC ------------------------------------------------------------------
    def handle_subquery(self, raw: bytes):
        """-> (http status, payload, content type). 200 carries a wire-
        encoded partial result; everything else is a JSON error whose
        ``error`` kind the broker uses to pick retry-on-replica vs
        fall-back-to-local. Every admitted subquery holds a drain token
        for its whole execution — the leave protocol's fence waits on
        exactly these."""
        if not self.ready:
            return 503, WIRE.encode_error(
                "NotReady", "recovery / shard load in progress"), \
                "application/json"
        tok = self.drain.begin_subquery()
        try:
            if tok is None:
                # fencing mid-handover: retryable — the broker's replica
                # chain (or its local fallback) absorbs it
                return 503, WIRE.encode_error(
                    "Draining", "node draining for epoch handover"), \
                    "application/json"
            return self._subquery_admitted(raw)
        finally:
            self.drain.end_subquery(tok)

    def handle_join_partition(self, raw: bytes):
        """Partitioned-join hop 1: filter one owned shard, tag rows with
        their join-key partition id (join/partitioned.py). Same
        admission contract as subqueries: readiness gate + drain token,
        so epoch fences cover join exchanges too."""
        if not self.ready:
            return 503, WIRE.encode_error(
                "NotReady", "recovery / shard load in progress"), \
                "application/json"
        tok = self.drain.begin_subquery()
        try:
            if tok is None:
                return 503, WIRE.encode_error(
                    "Draining", "node draining for epoch handover"), \
                    "application/json"
            from spark_druid_olap_tpu.join import partitioned as JP
            try:
                req = json.loads(raw.decode("utf-8"))
                if self.ctx.store._datasources.get(
                        str(req.get("store"))) is None:
                    return 404, WIRE.encode_error(
                        "UnknownShard",
                        f"shard {req.get('store')!r} not loaded"), \
                        "application/json"
                body = JP.partition_request(self.ctx, req)
            except (ValueError, KeyError, TypeError,
                    JP.JoinUnsupported) as e:
                return 400, WIRE.encode_error("BadJoin", str(e)), \
                    "application/json"
            return 200, body, "application/octet-stream"
        finally:
            self.drain.end_subquery(tok)

    def handle_join_exec(self, raw: bytes):
        """Partitioned-join hop 2: device-join one aligned partition
        pair and return per-group partials (join/partitioned.py)."""
        if not self.ready:
            return 503, WIRE.encode_error(
                "NotReady", "recovery / shard load in progress"), \
                "application/json"
        tok = self.drain.begin_subquery()
        try:
            if tok is None:
                return 503, WIRE.encode_error(
                    "Draining", "node draining for epoch handover"), \
                    "application/json"
            from spark_druid_olap_tpu.join import partitioned as JP
            try:
                body = JP.exec_request(self.ctx, raw)
            except (ValueError, KeyError, TypeError,
                    JP.JoinUnsupported) as e:
                return 400, WIRE.encode_error("BadJoin", str(e)), \
                    "application/json"
            return 200, body, "application/octet-stream"
        finally:
            self.drain.end_subquery(tok)

    def handle_ingest(self, raw: bytes):
        """Apply one pushed ingest batch to an owned shard store.

        -> (http status, payload, content type). The broker already
        journaled and acked the batch — this node holds NO durability
        responsibility; it only folds the rows into its in-memory shard
        so distributed scatters keep read-your-writes. Every error here
        is therefore safe: the broker just serves the datasource locally
        until the next checkpoint re-plans the shard.

        Applies are idempotent per (broker boot, push counter): a retry
        after a lost confirmation re-acks without re-appending."""
        if not self.ready:
            return 503, WIRE.encode_error(
                "NotReady", "recovery / shard load in progress"), \
                "application/json"
        tok = self.drain.begin_subquery()
        try:
            if tok is None:
                return 503, WIRE.encode_error(
                    "Draining", "node draining for epoch handover"), \
                    "application/json"
            return self._ingest_admitted(raw)
        finally:
            self.drain.end_subquery(tok)

    def _ingest_admitted(self, raw: bytes):
        from spark_druid_olap_tpu.persist.wal import decode_batch
        from spark_druid_olap_tpu.segment.append import append_dataframe
        inj = getattr(self.ctx.engine, "fault", None)
        if inj is not None:
            from spark_druid_olap_tpu.fault import FaultInjected
            try:
                # chaos site: an owner that crashes applying a pushed
                # batch (retryable on a replica; never loses the batch —
                # the broker's journal is the durability point)
                inj.fire("hist.ingest", key=f"node:{self.node_id}")
            except FaultInjected as e:
                return 500, WIRE.encode_error("Injected", str(e)), \
                    "application/json"
        try:
            header, body = WIRE.decode_ingest(raw)
            sname = str(header["shard"])
            batch_key = (str(header.get("src") or ""),
                         int(header["batch"]))
        except (ValueError, KeyError, TypeError) as e:
            return 400, WIRE.encode_error("BadIngest", str(e)), \
                "application/json"
        store = self.ctx.store
        with self._ingest_lock:
            if store._datasources.get(sname) is None:
                # not an owned shard under the current plan: stale
                # broker plan or mid-rejoin — broker tries a replica
                return 404, WIRE.encode_error(
                    "UnknownShard", f"shard {sname!r} not loaded"), \
                    "application/json"
            src, bid = batch_key
            state = self._applied_batches.get(sname)
            if state is None or state[0] != src:
                state = (src, 0, set())     # new broker boot resets ids
            _, mark, pending = state
            if bid <= mark or bid in pending:
                return 200, json.dumps(
                    {"applied": False, "duplicate": True,
                     "shard": sname, "batch": bid}
                ).encode("utf-8"), "application/json"
            try:
                df = decode_batch(body)
                kwargs = header.get("kwargs") or {}
                new_ds = append_dataframe(
                    store._datasources[sname], df,
                    target_rows=int(kwargs.get("target_rows") or (1 << 20)))
                # register (not restore): the version bump invalidates
                # this node's result cache for the shard, exactly as a
                # local append would
                store.register(new_ds)
            except Exception as e:  # noqa: BLE001 — apply errors are retryable
                return 500, WIRE.encode_error(
                    "IngestFailed", f"{type(e).__name__}: {e}"), \
                    "application/json"
            pending.add(bid)
            while mark + 1 in pending:      # keep the pending set tiny:
                mark += 1                   # contiguous prefix collapses
                pending.discard(mark)       # into the watermark
            self._applied_batches[sname] = (src, mark, pending)
            self.batches_applied += 1
            self.batch_rows_applied += len(df)
        return 200, json.dumps(
            {"applied": True, "shard": sname, "batch": bid,
             "rows": len(df)}).encode("utf-8"), "application/json"

    def _subquery_admitted(self, raw: bytes):
        inj = getattr(self.ctx.engine, "fault", None)
        if inj is not None:
            from spark_druid_olap_tpu.fault import FaultInjected
            try:
                # chaos site: a delay rule models a slow node, an error
                # rule a node-side 5xx crash (retryable on a replica)
                inj.fire("hist.handle", key=f"node:{self.node_id}")
            except FaultInjected as e:
                return 500, WIRE.encode_error("Injected", str(e)), \
                    "application/json"
        from spark_druid_olap_tpu.ir.serde import query_from_dict
        from spark_druid_olap_tpu.parallel.executor import (
            EngineFallback, QueryCancelled, QueryTimeout)
        from spark_druid_olap_tpu.wlm.lanes import AdmissionRejected
        try:
            d, req_epoch = WIRE.split_subquery(raw)
            q = query_from_dict(d)
        except (ValueError, KeyError, TypeError) as e:
            return 400, WIRE.encode_error("BadQuery", str(e)), \
                "application/json"
        if req_epoch is not None and req_epoch > self._max_req_epoch:
            # a broker stamped a newer epoch: proof it swapped, so
            # old-epoch-only shard stores can be retired (done on the
            # watcher tick, not in the query path)
            self._max_req_epoch = req_epoch
        engine = self.ctx.engine
        try:
            r = engine.execute(q)
        except KeyError as e:
            # unknown shard store: stale plan or mid-rejoin — the
            # broker marks this node down and asks a replica
            return 404, WIRE.encode_error("UnknownDatasource", str(e)), \
                "application/json"
        except AdmissionRejected as e:
            return 429, WIRE.encode_error(
                "AdmissionRejected", str(e),
                retryAfterSeconds=float(getattr(e, "retry_after_s", 1.0))), \
                "application/json"
        except EngineFallback as e:
            # this node cannot answer the shape (e.g. sketch over the
            # hashed tier); no replica can either — broker runs it
            # locally through its own session-level host tier
            return 422, WIRE.encode_error("EngineFallback", str(e)), \
                "application/json"
        except (QueryCancelled, QueryTimeout) as e:
            return 504, WIRE.encode_error(type(e).__name__, str(e)), \
                "application/json"
        ls = engine.last_stats
        stats = {"node": self.node_id,
                 "cache": ls.get("cache"),
                 "sharedscan": ls.get("sharedscan"),
                 "total_ms": ls.get("total_ms")}
        return 200, WIRE.encode_result(r.columns, r.data, stats), \
            "application/octet-stream"
