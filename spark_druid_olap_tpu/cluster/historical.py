"""Historical node: boot from deep storage, serve owned shards.

Boot sequence (order matters for the health contract):

1. the HTTP server starts FIRST — ``/healthz`` answers immediately,
   ``/readyz`` answers 503 until boot completes, so orchestrators and
   the broker's prober can watch recovery progress;
2. a full ``Context`` is created over the shared persist root —
   ``PersistManager.recover()`` rebuilds every datasource from
   snapshots + WAL tails exactly as a single-process engine would;
3. the node computes the SAME shard plan as the broker (pure function
   of deep storage + the node list), slices each owned shard out of the
   recovered datasource with ``segment/store.py:slice_segments``,
   registers it under its shard name at the manifest's ingest version,
   and drops the full datasource — memory is bounded by owned rows;
4. ``ready`` flips True; ``/readyz`` goes 200 and the broker routes
   primary traffic here.

The subquery RPC wraps the ordinary ``QueryEngine.execute``: WLM lane
admission, the per-node result cache, and shared-scan coalescing all
apply to subqueries, so each historical absorbs its own slice of a
dashboard storm. ``partial_sketches`` makes sketch aggregates return
raw registers for the broker's exact register merge.

A datasource whose recovered state runs PAST the planned manifest (WAL
tail appended after the last checkpoint) is kept whole and unsliced:
the broker's matching ingest-version check already serves it locally,
and slicing would silently drop the WAL rows here.
"""

from __future__ import annotations

import json
import threading
from typing import Optional
from urllib.parse import urlparse

from spark_druid_olap_tpu.cluster import wire as WIRE
from spark_druid_olap_tpu.cluster.assign import (
    parse_nodes, plan_cluster, shard_name)
from spark_druid_olap_tpu.server.http import SqlServer
from spark_druid_olap_tpu.utils.config import (
    CLUSTER_NODE_ID,
    CLUSTER_NODES,
    CLUSTER_REPLICATION,
    CLUSTER_ROLE,
    CLUSTER_SHARDS,
    PERSIST_PATH,
)


class HistoricalServer(SqlServer):
    """SqlServer + the cluster subquery RPC. Everything else — /sql,
    /metadata/*, /healthz — is inherited, so a historical is also a
    directly-queryable engine over its shards (handy for debugging a
    single node's slice)."""

    def __init__(self, node: "HistoricalNode", host: str, port: int):
        super().__init__(None, host, port)   # ctx attaches after boot
        self.node = node
        self.ready_check = lambda: node.ready

    def _handle_post(self, h):
        if urlparse(h.path).path == "/cluster/subquery":
            n = int(h.headers.get("Content-Length", "0"))
            raw = h.rfile.read(n) if n else b"{}"
            code, body, ctype = self.node.handle_subquery(raw)
            h._send(code, body, ctype)
            return
        super()._handle_post(h)


class HistoricalNode:
    """One serving process. ``overrides`` is the shared cluster config
    (persist path, node list, replication, shard count) — identical on
    every member, which is what makes the independently-computed plans
    identical."""

    def __init__(self, overrides: Optional[dict] = None,
                 node_id: Optional[int] = None):
        from spark_druid_olap_tpu.utils.config import Config
        self.overrides = dict(overrides or {})
        self.overrides[CLUSTER_ROLE.key] = "historical"
        cfg = Config(self.overrides)
        self.addresses = parse_nodes(str(cfg.get(CLUSTER_NODES)))
        if not self.addresses:
            raise ValueError("HistoricalNode needs sdot.cluster.nodes")
        if node_id is None:
            node_id = int(cfg.get(CLUSTER_NODE_ID))
        self.node_id = int(node_id)
        self.overrides[CLUSTER_NODE_ID.key] = self.node_id
        if not 0 <= self.node_id < len(self.addresses):
            raise ValueError(
                f"node id {self.node_id} outside the node list "
                f"(n={len(self.addresses)})")
        self.ready = False
        self.ctx = None
        self.plan = None
        self.shards_loaded = 0
        self.server: Optional[HistoricalServer] = None

    # -- lifecycle ------------------------------------------------------------
    def start(self, background: bool = True) -> "HistoricalNode":
        host, port = self.addresses[self.node_id]
        self.server = HistoricalServer(self, host, port)
        self.server.start(background=True)
        self.boot()
        if not background:
            try:
                threading.Event().wait()
            except KeyboardInterrupt:
                pass
            finally:
                self.stop()
        return self

    def boot(self) -> None:
        import spark_druid_olap_tpu as sdot
        self.ctx = sdot.Context(self.overrides)
        self.server.ctx = self.ctx
        # sketch aggregates ship raw registers to the broker (both the
        # solo and the fused shared-scan decode honor this flag)
        self.ctx.engine.partial_sketches = True
        cfg = self.ctx.config
        self.plan = plan_cluster(
            cfg.get(PERSIST_PATH), len(self.addresses),
            int(cfg.get(CLUSTER_REPLICATION)),
            int(cfg.get(CLUSTER_SHARDS)))
        self._load_shards()
        self.ready = True

    def stop(self) -> None:
        self.ready = False
        if self.server is not None:
            self.server.stop()
        if self.ctx is not None:
            self.ctx.close()

    def _load_shards(self) -> None:
        from spark_druid_olap_tpu.segment.store import slice_segments
        store = self.ctx.store
        owned_by_ds = self.plan.shards_of(self.node_id)
        for name in store.names():
            dp = self.plan.datasources.get(name)
            if dp is None:
                # WAL-only datasource (no published manifest): not in
                # the plan, broker serves it locally — keep it whole
                continue
            if store.datasource_version(name) != dp.ingest_version \
                    or store.get(name).num_segments != dp.num_segments:
                # recovery replayed WAL past the planned snapshot;
                # slicing by manifest segment indexes would drop those
                # rows. Keep whole — the broker's version check routes
                # this datasource locally until the next checkpoint.
                continue
            full = store.get(name)
            tiered = getattr(full, "tier", None) is not None
            if tiered:
                from spark_druid_olap_tpu.tier.loader import slice_tiered
            for sh in owned_by_ds.get(name, ()):
                sname = shard_name(name, sh.index, dp.n_shards)
                # tiered recovery: shards stay loadable handles, so the
                # node's hot set covers ONLY its owned segments' bytes
                # and boots without faulting the whole datasource
                shard = slice_tiered(full, sh.segment_indexes,
                                     name=sname) if tiered \
                    else slice_segments(full, sh.segment_indexes,
                                        name=sname)
                store.restore(shard, ingest_version=dp.ingest_version)
                self.shards_loaded += 1
            # serve ONLY owned shards: per-node memory is bounded by
            # assigned rows, the point of the tier
            store.drop(name)

    # -- RPC ------------------------------------------------------------------
    def handle_subquery(self, raw: bytes):
        """-> (http status, payload, content type). 200 carries a wire-
        encoded partial result; everything else is a JSON error whose
        ``error`` kind the broker uses to pick retry-on-replica vs
        fall-back-to-local."""
        if not self.ready:
            return 503, WIRE.encode_error(
                "NotReady", "recovery / shard load in progress"), \
                "application/json"
        inj = getattr(self.ctx.engine, "fault", None)
        if inj is not None:
            from spark_druid_olap_tpu.fault import FaultInjected
            try:
                # chaos site: a delay rule models a slow node, an error
                # rule a node-side 5xx crash (retryable on a replica)
                inj.fire("hist.handle", key=f"node:{self.node_id}")
            except FaultInjected as e:
                return 500, WIRE.encode_error("Injected", str(e)), \
                    "application/json"
        from spark_druid_olap_tpu.ir.serde import query_from_dict
        from spark_druid_olap_tpu.parallel.executor import (
            EngineFallback, QueryCancelled, QueryTimeout)
        from spark_druid_olap_tpu.wlm.lanes import AdmissionRejected
        try:
            q = query_from_dict(json.loads(raw.decode("utf-8")))
        except (ValueError, KeyError, TypeError) as e:
            return 400, WIRE.encode_error("BadQuery", str(e)), \
                "application/json"
        engine = self.ctx.engine
        try:
            r = engine.execute(q)
        except KeyError as e:
            # unknown shard store: stale plan or mid-rejoin — the
            # broker marks this node down and asks a replica
            return 404, WIRE.encode_error("UnknownDatasource", str(e)), \
                "application/json"
        except AdmissionRejected as e:
            return 429, WIRE.encode_error(
                "AdmissionRejected", str(e),
                retryAfterSeconds=float(getattr(e, "retry_after_s", 1.0))), \
                "application/json"
        except EngineFallback as e:
            # this node cannot answer the shape (e.g. sketch over the
            # hashed tier); no replica can either — broker runs it
            # locally through its own session-level host tier
            return 422, WIRE.encode_error("EngineFallback", str(e)), \
                "application/json"
        except (QueryCancelled, QueryTimeout) as e:
            return 504, WIRE.encode_error(type(e).__name__, str(e)), \
                "application/json"
        ls = engine.last_stats
        stats = {"node": self.node_id,
                 "cache": ls.get("cache"),
                 "sharedscan": ls.get("sharedscan"),
                 "total_ms": ls.get("total_ms")}
        return 200, WIRE.encode_result(r.columns, r.data, stats), \
            "application/octet-stream"
