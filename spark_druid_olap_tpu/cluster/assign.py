"""Deterministic segment-shard -> historical assignment.

≈ ``DruidMetadataCache.assignHistoricalServers`` with deep storage as the
coordination substrate: the plan is a pure function of (published
manifests, node list, replication factor), so the broker and every
historical compute the IDENTICAL plan independently — no coordinator
process, no gossip. A topology change is a new *plan epoch*
(cluster/epoch.py): members converge on the new plan without a restart.

Sharding reuses the multi-host cut algorithm
(``parallel/multihost.py:assign_segments_to_hosts``): contiguous
time-blocks of segments balanced by row count. Contiguity keeps each
shard one time range, so the broker's interval pruning could skip whole
nodes the way Druid's time-chunk assignment does.

Owner placement is **stability-aware** (rendezvous / highest-random-
weight hashing over stable logical node ids, with bounded loads): each
(datasource, shard) ranks every node by a CRC-derived score and takes
the best-ranked nodes with remaining capacity (``ceil(k / n)`` per
copy position) as owners. Adding a node moves roughly ~R/(N+1) of the
assignments — those where the newcomer out-ranks the incumbent plus
the capacity rebalance tail; removing a node moves little beyond its
own assignments. ``plan_diff``
reports exactly which (shard, copy) pairs move between two plans —
the elasticity harness asserts measured movement against it, and
against the old modular rotation (``strategy="modular"``, kept as a
kill switch) whose every N→N±1 transition reshuffles nearly all owners.
"""

from __future__ import annotations

import dataclasses
import zlib
from typing import Dict, Optional, Tuple

from spark_druid_olap_tpu.parallel.multihost import assign_segments_to_hosts
from spark_druid_olap_tpu.persist import snapshot as SNAP


def shard_name(datasource: str, index: int, n_shards: int) -> str:
    """Store name a historical registers shard ``index`` under. The
    full-name prefix keeps result-cache keys and WLM attribution legible
    per node; '::' cannot appear in SQL identifiers, so shard stores are
    unreachable from user queries."""
    return f"{datasource}::shard{index}of{n_shards}"


@dataclasses.dataclass(frozen=True)
class Shard:
    index: int
    segment_indexes: Tuple[int, ...]   # indexes into the manifest's segment list
    rows: int
    owners: Tuple[int, ...]            # node ids, primary first
    # time envelope over member segments ([min_ms, max_ms] inclusive);
    # empty shard keeps the (0, -1) sentinel, which no interval overlaps
    min_ms: int = 0
    max_ms: int = -1


@dataclasses.dataclass(frozen=True)
class DatasourcePlan:
    name: str
    snapshot_version: int
    ingest_version: int
    num_rows: int
    num_segments: int
    shards: Tuple[Shard, ...]

    @property
    def n_shards(self) -> int:
        return len(self.shards)


@dataclasses.dataclass(frozen=True)
class ClusterPlan:
    n_nodes: int
    replication: int
    datasources: Dict[str, DatasourcePlan]
    # stable logical node ids, parallel to node indexes (epoch record
    # ids; "n0".."nK" for the implicit bootstrap epoch). The owner
    # hash keys — NOT addresses (ports change per run) and NOT indexes
    # (they shift on removal).
    node_keys: Tuple[str, ...] = ()
    epoch: int = 0

    def shards_of(self, node_id: int) -> Dict[str, Tuple[Shard, ...]]:
        """datasource -> shards this node owns (primary or replica)."""
        out = {}
        for name, dp in self.datasources.items():
            owned = tuple(sh for sh in dp.shards if node_id in sh.owners)
            if owned:
                out[name] = owned
        return out


_M64 = (1 << 64) - 1


def _score(ds_name: str, shard_index: int, node_key: str) -> int:
    """Rendezvous weight of one node for one shard. CRC32 of the parts
    (stable across processes, unlike salted str hash) combined through a
    splitmix64-style finalizer — a plain CRC over the concatenation is
    affine in the shard digits, so rankings would barely vary per shard
    and whole datasources would pile onto one node."""
    h = (zlib.crc32(ds_name.encode("utf-8")) * 0x9E3779B1
         ^ (shard_index + 1) * 0x85EBCA77
         ^ zlib.crc32(node_key.encode("utf-8")) * 0xC2B2AE3D) & _M64
    h ^= h >> 30
    h = (h * 0xBF58476D1CE4E5B9) & _M64
    h ^= h >> 27
    h = (h * 0x94D049BB133111EB) & _M64
    return h ^ (h >> 31)


def _ranked(ds_name: str, shard_index: int,
            node_keys: Tuple[str, ...]) -> Tuple[int, ...]:
    """All nodes ordered by rendezvous score for one shard, best first.
    Determinism tiebreak on the logical key so equal scores can't
    reorder between processes."""
    return tuple(sorted(
        range(len(node_keys)),
        key=lambda j: (-_score(ds_name, shard_index, node_keys[j]),
                       node_keys[j])))


def _owners_balanced(ds_name: str, k: int, node_keys: Tuple[str, ...],
                     r: int) -> Tuple[Tuple[int, ...], ...]:
    """Bounded-load rendezvous for all ``k`` shards of one datasource.

    Pure rendezvous makes no balance promise at small shard counts — a
    2-shard datasource can land both primaries on one node, which
    starves the other node's WLM lanes and breaks every the-other-node-
    serves-something expectation. So each copy position (primary,
    first replica, …) caps per-node load at ``ceil(k / n)``: shards
    take their highest-ranked node with remaining capacity. Stability
    survives: a shard moves only when its ranked chain or the capacity
    frontier shifts, so an N→N+1 epoch still moves ~1/(N+1) of the
    assignments instead of the modular rotation's almost-all — and
    ``plan_diff`` reports the exact set either way."""
    n = len(node_keys)
    cap = -(-k // n)
    loads = [[0] * n for _ in range(r)]
    out = []
    for i in range(k):
        ranked = _ranked(ds_name, i, node_keys)
        owners: list = []
        for c in range(r):
            pick = next((j for j in ranked
                         if j not in owners and loads[c][j] < cap),
                        None)
            if pick is None:
                # capacity exhausted by the distinctness constraint
                # (only possible when r is close to n): relax the cap
                pick = next(j for j in ranked if j not in owners)
            owners.append(pick)
            loads[c][pick] += 1
        out.append(tuple(owners))
    return tuple(out)


def _plan_datasource(manifest: dict, n_nodes: int, replication: int,
                     n_shards: int, node_keys: Tuple[str, ...],
                     strategy: str) -> DatasourcePlan:
    name = manifest["datasource"]
    segs = manifest["segments"]            # [[id, start, end, min_ms, max_ms]]
    rows = [int(e[2]) - int(e[1]) for e in segs]
    want = n_shards if n_shards > 0 else n_nodes
    k = max(1, min(want, len(segs)))
    cut = assign_segments_to_hosts(rows, k)
    # modular fallback: primary rotation by datasource-name CRC (the
    # pre-epoch placement; nearly every owner moves on N -> N±1)
    base = zlib.crc32(name.encode("utf-8"))
    r = min(max(1, replication), n_nodes)
    stable = (_owners_balanced(name, k, node_keys, r)
              if strategy != "modular" else None)
    shards = []
    for i in range(k):
        members = tuple(int(j) for j in range(len(cut)) if int(cut[j]) == i)
        if strategy == "modular":
            primary = (base + i) % n_nodes
            owners = tuple((primary + c) % n_nodes for c in range(r))
        else:
            owners = stable[i]
        shards.append(Shard(index=i, segment_indexes=members,
                            rows=sum(rows[j] for j in members),
                            owners=owners,
                            min_ms=min((int(segs[j][3]) for j in members),
                                       default=0),
                            max_ms=max((int(segs[j][4]) for j in members),
                                       default=-1)))
    return DatasourcePlan(
        name=name,
        snapshot_version=int(manifest["snapshot_version"]),
        ingest_version=int(manifest["ingest_version"]),
        num_rows=int(manifest["num_rows"]),
        num_segments=len(segs),
        shards=tuple(shards))


def plan_cluster(persist_root: str, n_nodes: int, replication: int,
                 n_shards: int = 0,
                 manifests: Optional[Dict[str, dict]] = None,
                 node_keys: Optional[Tuple[str, ...]] = None,
                 epoch: int = 0,
                 strategy: str = "stable") -> ClusterPlan:
    """Compute the full cluster plan from deep storage.

    ``manifests`` injects a pre-scanned catalog (tests, or a broker that
    already holds one); otherwise the root is scanned fresh.
    ``node_keys`` are the epoch record's stable logical ids (defaults to
    the bootstrap ``n0..nK``). Determinism contract: identical
    (manifests, node_keys, replication, n_shards, strategy) -> identical
    plan, on any process, in any order of discovery."""
    if n_nodes < 1:
        raise ValueError("cluster plan needs at least one node")
    if node_keys is None:
        node_keys = tuple(f"n{i}" for i in range(n_nodes))
    if len(node_keys) != n_nodes:
        raise ValueError(f"{len(node_keys)} node keys for {n_nodes} nodes")
    if strategy not in ("stable", "modular"):
        raise ValueError(f"unknown assignment strategy {strategy!r}")
    if manifests is None:
        manifests = SNAP.datasource_manifests(persist_root)
    dss = {}
    for name in sorted(manifests):
        dss[name] = _plan_datasource(manifests[name], n_nodes,
                                     replication, n_shards,
                                     tuple(node_keys), strategy)
    return ClusterPlan(n_nodes=n_nodes,
                       replication=min(max(1, replication), n_nodes),
                       datasources=dss,
                       node_keys=tuple(node_keys),
                       epoch=int(epoch))


@dataclasses.dataclass(frozen=True)
class PlanDiff:
    """Exact assignment movement between two plans, keyed by logical
    node id (so epochs with shifted indexes compare correctly). One
    entry per (datasource, shard index, node key) ownership pair."""

    added: Tuple[Tuple[str, int, str], ...]    # pairs to warm
    removed: Tuple[Tuple[str, int, str], ...]  # pairs to retire
    total: int                                  # assignments in `new`
    unchanged: int

    @property
    def moved(self) -> int:
        return len(self.added)

    def summary(self) -> dict:
        return {"moved": self.moved, "removed": len(self.removed),
                "unchanged": self.unchanged, "total": self.total}


def _assignment_pairs(plan: ClusterPlan):
    pairs = set()
    for name, dp in plan.datasources.items():
        for sh in dp.shards:
            for nid in sh.owners:
                pairs.add((name, sh.index, plan.node_keys[nid]))
    return pairs


def plan_diff(old: ClusterPlan, new: ClusterPlan) -> PlanDiff:
    """Deterministic movement report: which (shard, copy) ownership
    pairs exist in ``new`` but not ``old`` (must be warmed) and vice
    versa (may be retired). When a datasource's shard count differs
    between the plans its composition changed, and every one of its new
    pairs counts as added — shard indexes only compare within an equal
    cut."""
    a = _assignment_pairs(old)
    b = _assignment_pairs(new)
    # shard counts must match per datasource for index-wise comparison
    recut = {name for name in new.datasources
             if name in old.datasources
             and old.datasources[name].n_shards
             != new.datasources[name].n_shards}
    if recut:
        a = {p for p in a if p[0] not in recut}
    added = tuple(sorted(b - a))
    removed = tuple(sorted(a - b))
    return PlanDiff(added=added, removed=removed, total=len(b),
                    unchanged=len(b) - len(added))


def plan_fully_warm(plan: ClusterPlan, adverts: Dict[int, set]) -> bool:
    """The epoch-handover gate, as a pure function both sides share:
    ``adverts`` maps node id (index into ``plan``'s node list) to the
    set of shard-store names that node advertises warm for this epoch
    (from the extended ``/readyz``). True when every (datasource,
    shard) of the plan has at least one owner advertising it — the
    broker swaps on this condition, and a leaving historical begins its
    drain on the same condition, so neither can observe "ready" before
    the other could."""
    for name, dp in plan.datasources.items():
        for sh in dp.shards:
            sname = shard_name(name, sh.index, dp.n_shards)
            if not any(sname in adverts.get(nid, ())
                       for nid in sh.owners):
                return False
    return True


def parse_nodes(spec: str) -> Tuple[Tuple[str, int], ...]:
    """'host:port,host:port' -> ((host, port), ...); index = node id."""
    out = []
    for part in (spec or "").replace(";", ",").split(","):
        part = part.strip()
        if not part:
            continue
        host, _, port = part.rpartition(":")
        if not host or not port.isdigit():
            raise ValueError(f"bad cluster node address {part!r} "
                             "(want host:port)")
        out.append((host, int(port)))
    return tuple(out)
