"""Deterministic segment-shard -> historical assignment.

≈ ``DruidMetadataCache.assignHistoricalServers`` with deep storage as the
coordination substrate: the plan is a pure function of (published
manifests, node list, replication factor), so the broker and every
historical compute the IDENTICAL plan independently — no coordinator
process, no gossip. A topology change (node list edit) is a restart, the
way Druid treats a historical tier resize as a coordinator rebalance.

Sharding reuses the multi-host cut algorithm
(``parallel/multihost.py:assign_segments_to_hosts``): contiguous
time-blocks of segments balanced by row count. Contiguity keeps each
shard one time range, so the broker's interval pruning could skip whole
nodes the way Druid's time-chunk assignment does.
"""

from __future__ import annotations

import dataclasses
import zlib
from typing import Dict, Optional, Tuple

from spark_druid_olap_tpu.parallel.multihost import assign_segments_to_hosts
from spark_druid_olap_tpu.persist import snapshot as SNAP


def shard_name(datasource: str, index: int, n_shards: int) -> str:
    """Store name a historical registers shard ``index`` under. The
    full-name prefix keeps result-cache keys and WLM attribution legible
    per node; '::' cannot appear in SQL identifiers, so shard stores are
    unreachable from user queries."""
    return f"{datasource}::shard{index}of{n_shards}"


@dataclasses.dataclass(frozen=True)
class Shard:
    index: int
    segment_indexes: Tuple[int, ...]   # indexes into the manifest's segment list
    rows: int
    owners: Tuple[int, ...]            # node ids, primary first
    # time envelope over member segments ([min_ms, max_ms] inclusive);
    # empty shard keeps the (0, -1) sentinel, which no interval overlaps
    min_ms: int = 0
    max_ms: int = -1


@dataclasses.dataclass(frozen=True)
class DatasourcePlan:
    name: str
    snapshot_version: int
    ingest_version: int
    num_rows: int
    num_segments: int
    shards: Tuple[Shard, ...]

    @property
    def n_shards(self) -> int:
        return len(self.shards)


@dataclasses.dataclass(frozen=True)
class ClusterPlan:
    n_nodes: int
    replication: int
    datasources: Dict[str, DatasourcePlan]

    def shards_of(self, node_id: int) -> Dict[str, Tuple[Shard, ...]]:
        """datasource -> shards this node owns (primary or replica)."""
        out = {}
        for name, dp in self.datasources.items():
            owned = tuple(sh for sh in dp.shards if node_id in sh.owners)
            if owned:
                out[name] = owned
        return out


def _plan_datasource(manifest: dict, n_nodes: int, replication: int,
                     n_shards: int) -> DatasourcePlan:
    name = manifest["datasource"]
    segs = manifest["segments"]            # [[id, start, end, min_ms, max_ms]]
    rows = [int(e[2]) - int(e[1]) for e in segs]
    want = n_shards if n_shards > 0 else n_nodes
    k = max(1, min(want, len(segs)))
    cut = assign_segments_to_hosts(rows, k)
    # primary rotation by datasource-name CRC spreads different
    # datasources' shard-0 primaries across nodes (Python's str hash is
    # process-salted; CRC32 is stable everywhere)
    base = zlib.crc32(name.encode("utf-8"))
    r = min(max(1, replication), n_nodes)
    shards = []
    for i in range(k):
        members = tuple(int(j) for j in range(len(cut)) if int(cut[j]) == i)
        primary = (base + i) % n_nodes
        owners = tuple((primary + c) % n_nodes for c in range(r))
        shards.append(Shard(index=i, segment_indexes=members,
                            rows=sum(rows[j] for j in members),
                            owners=owners,
                            min_ms=min((int(segs[j][3]) for j in members),
                                       default=0),
                            max_ms=max((int(segs[j][4]) for j in members),
                                       default=-1)))
    return DatasourcePlan(
        name=name,
        snapshot_version=int(manifest["snapshot_version"]),
        ingest_version=int(manifest["ingest_version"]),
        num_rows=int(manifest["num_rows"]),
        num_segments=len(segs),
        shards=tuple(shards))


def plan_cluster(persist_root: str, n_nodes: int, replication: int,
                 n_shards: int = 0,
                 manifests: Optional[Dict[str, dict]] = None) -> ClusterPlan:
    """Compute the full cluster plan from deep storage.

    ``manifests`` injects a pre-scanned catalog (tests, or a broker that
    already holds one); otherwise the root is scanned fresh. Determinism
    contract: identical (manifests, n_nodes, replication, n_shards) ->
    identical plan, on any process, in any order of discovery."""
    if n_nodes < 1:
        raise ValueError("cluster plan needs at least one node")
    if manifests is None:
        manifests = SNAP.datasource_manifests(persist_root)
    dss = {}
    for name in sorted(manifests):
        dss[name] = _plan_datasource(manifests[name], n_nodes,
                                     replication, n_shards)
    return ClusterPlan(n_nodes=n_nodes,
                       replication=min(max(1, replication), n_nodes),
                       datasources=dss)


def parse_nodes(spec: str) -> Tuple[Tuple[str, int], ...]:
    """'host:port,host:port' -> ((host, port), ...); index = node id."""
    out = []
    for part in (spec or "").replace(";", ",").split(","):
        part = part.strip()
        if not part:
            continue
        host, _, port = part.rpartition(":")
        if not host or not port.isdigit():
            raise ValueError(f"bad cluster node address {part!r} "
                             "(want host:port)")
        out.append((host, int(port)))
    return tuple(out)
