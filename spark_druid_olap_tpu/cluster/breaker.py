"""Per-node circuit breakers for the broker's scatter path.

One breaker per historical. State machine per node:

- **closed** — attempts flow; ``failures`` consecutive subquery
  failures open it.
- **open** — :meth:`before_attempt` returns ``None`` (the broker skips
  the node without an RPC) until ``cooldown_s`` elapses.
- **half-open** — after the cooldown, exactly ONE in-flight probe
  attempt is admitted; success closes the breaker, failure re-opens it
  (and restarts the cooldown).

Every admitted attempt is a claim token that MUST be settled
(``settle(tok, ok)``) — the sdlint leaks pass enforces the pair — so a
crashed attempt can't wedge a breaker half-open forever.

Lock order: ``BreakerBoard._lock`` is a LEAF lock — no other lock is
ever taken while it is held (``before_attempt``/``settle`` never call
out), and it nests safely under ``ClusterClient._lock`` (see
docs/LINT.md, lock-order registry).
"""

from __future__ import annotations

import threading
import time


class _Claim:
    """Token for one admitted attempt against one node."""

    __slots__ = ("node_id", "probe")

    def __init__(self, node_id, probe):
        self.node_id = node_id
        self.probe = probe


class _Breaker:
    __slots__ = ("consecutive", "open_since", "probing")

    def __init__(self):
        self.consecutive = 0      # consecutive failures while closed
        self.open_since = None    # monotonic timestamp, None = closed
        self.probing = False      # a half-open probe is in flight


class BreakerBoard:
    """Breaker state for all nodes of one broker."""

    def __init__(self, n_nodes, failures, cooldown_s):
        self.failures = int(failures)
        self.cooldown_s = float(cooldown_s)
        self._lock = threading.Lock()   # LEAF — never calls out while held
        self._nodes = [_Breaker() for _ in range(n_nodes)]
        self.counters = {"opens": 0, "closes": 0, "skips": 0, "probes": 0}

    @property
    def enabled(self):
        return self.failures > 0

    def before_attempt(self, node_id):
        """Admit or refuse an attempt. Returns a claim token (settle it!)
        or ``None`` when the breaker is open and still cooling down."""
        if not self.enabled:
            return _Claim(node_id, False)
        with self._lock:
            b = self._nodes[node_id]
            if b.open_since is None:
                return _Claim(node_id, False)
            if b.probing or (time.monotonic() - b.open_since
                             < self.cooldown_s):
                self.counters["skips"] += 1
                return None
            b.probing = True
            self.counters["probes"] += 1
            return _Claim(node_id, True)

    def settle(self, tok, ok):
        """Record the outcome of an admitted attempt."""
        if tok is None or not self.enabled:
            return
        with self._lock:
            b = self._nodes[tok.node_id]
            if tok.probe:
                b.probing = False
            if ok:
                b.consecutive = 0
                if b.open_since is not None:
                    b.open_since = None
                    self.counters["closes"] += 1
            elif tok.probe:
                # a failed half-open probe re-opens (restart the cooldown)
                b.open_since = time.monotonic()
            else:
                b.consecutive += 1
                if (b.open_since is None
                        and b.consecutive >= self.failures):
                    b.open_since = time.monotonic()
                    self.counters["opens"] += 1

    def reset(self, node_id):
        """Forget one node's breaker state (close it, zero the failure
        count). Called when the node's *generation* changes — a
        historical that left and rejoined, or a restarted process
        reusing the slot — so the successor never inherits the
        predecessor's open circuit (the PR 12 rejoin bug). Counter
        totals are preserved; only per-node state clears."""
        with self._lock:
            self._nodes[node_id] = _Breaker()

    def is_open(self, node_id):
        """True when attempts against the node are currently refused
        (used only to order replica chains, never to skip outright)."""
        if not self.enabled:
            return False
        with self._lock:
            return self._nodes[node_id].open_since is not None

    def snapshot(self):
        with self._lock:
            return {"enabled": self.enabled,
                    "states": ["open" if b.open_since is not None
                               else "closed" for b in self._nodes],
                    **self.counters}
