"""Pickle-free wire format for subquery results.

Layout: ``b"SDW1" + uint32le(header_len) + header_json + buffers +
uint32le(crc32 of everything before it)``.
Numeric / datetime columns travel as raw little-endian buffers described
by ``dtype.str`` + shape in the header (2-D shapes carry partial sketch
register blocks); long-run 1-D integer columns (granular time buckets,
dictionary codes) ship RLE-compressed instead when that shrinks them,
with the codec chunk header inline in the frame header — fully
self-describing, no cross-node config; object columns (decoded strings,
wide ints, None nulls) travel as JSON lists — Python ints survive JSON with arbitrary
precision, which is what keeps exact int128-ish sums exact across the
wire. No pickle anywhere: a historical's RPC port must not be a
remote-code-execution port.

The CRC32 trailer makes a truncated or bit-flipped frame *detectable*:
without it a corrupted raw LE buffer decodes into plausible garbage and
silently poisons the broker merge. ``decode_result`` raises ValueError
on mismatch and the broker treats that as a retryable failure (ask a
replica) rather than trusting the bytes.
"""

from __future__ import annotations

import json
import math
import struct
import zlib
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

MAGIC = b"SDW1"
_LEN = struct.Struct("<I")


def _jsonable_cell(v: Any):
    if v is None:
        return None
    if isinstance(v, np.generic):
        v = v.item()
    if isinstance(v, float) and not math.isfinite(v):
        # JSON has no NaN/Inf literal worth trusting cross-parser; a
        # non-finite float in an object column is a null cell
        return None
    return v


def _maybe_rle(arr: np.ndarray):
    """RLE chunk for a 1-D integer result column, or None when it would
    not shrink. Broker-bound partials are dominated by granular time
    buckets and dictionary codes — long-run columns — so shipping runs
    instead of rows cuts shard-merge traffic for free. Self-describing:
    the codec header (encode/codecs.py) travels IN the frame header, so
    encoder and decoder can never disagree about the layout and no
    config key has to match across nodes."""
    if arr.ndim != 1 or arr.dtype.kind not in "iub" or len(arr) < 64:
        return None
    from spark_druid_olap_tpu.encode import codecs as EN
    try:
        payload, header = EN.encode_array(arr, EN.RLE)
    except EN.EncodingError:
        return None
    if len(payload) >= arr.nbytes:
        return None
    return payload, header


def encode_result(columns: List[str], data: Dict[str, np.ndarray],
                  stats: Optional[dict] = None) -> bytes:
    n = int(len(data[columns[0]])) if columns else 0
    header: Dict[str, Any] = {"n": n, "stats": stats or {}, "cols": []}
    bufs: List[bytes] = []
    for name in columns:
        arr = np.asarray(data[name])
        if arr.dtype == object:
            header["cols"].append({
                "name": name, "kind": "obj",
                "values": [_jsonable_cell(v) for v in arr.tolist()]})
        else:
            arr = np.ascontiguousarray(arr)
            rle = _maybe_rle(arr)
            if rle is not None:
                payload, eh = rle
                header["cols"].append({
                    "name": name, "kind": "enc", "dtype": arr.dtype.str,
                    "shape": list(arr.shape), "nbytes": len(payload),
                    "enc": eh})
                bufs.append(payload)
                continue
            raw = arr.tobytes()
            header["cols"].append({
                "name": name, "kind": "bin", "dtype": arr.dtype.str,
                "shape": list(arr.shape), "nbytes": len(raw)})
            bufs.append(raw)
    hb = json.dumps(header, separators=(",", ":")).encode("utf-8")
    body = b"".join([MAGIC, _LEN.pack(len(hb)), hb] + bufs)
    return body + _LEN.pack(zlib.crc32(body))


def decode_result(payload: bytes) -> Tuple[List[str], Dict[str, np.ndarray],
                                           dict]:
    """-> (columns, data, stats). Raises ValueError on a malformed frame."""
    if len(payload) < 12 or payload[:4] != MAGIC:
        raise ValueError("bad wire magic")
    (crc,) = _LEN.unpack_from(payload, len(payload) - 4)
    if zlib.crc32(payload[:-4]) != crc:
        raise ValueError("wire CRC mismatch (truncated or corrupt frame)")
    payload = payload[:-4]
    (hlen,) = _LEN.unpack_from(payload, 4)
    off = 8 + hlen
    header = json.loads(payload[8:off].decode("utf-8"))
    columns: List[str] = []
    data: Dict[str, np.ndarray] = {}
    for col in header["cols"]:
        name = col["name"]
        columns.append(name)
        if col["kind"] == "obj":
            vals = col["values"]
            arr = np.empty(len(vals), dtype=object)
            for i, v in enumerate(vals):
                arr[i] = v
            data[name] = arr
        elif col["kind"] == "enc":
            from spark_druid_olap_tpu.encode import codecs as EN
            nb = int(col["nbytes"])
            try:
                arr = EN.decode_array(payload[off:off + nb], col["enc"])
            except (EN.EncodingError, KeyError) as e:
                raise ValueError(f"bad encoded wire column {name}: {e}") \
                    from e
            if arr.dtype.str != col["dtype"] or arr.shape != \
                    tuple(col["shape"]):
                raise ValueError(
                    f"encoded wire column {name}: decoded "
                    f"{arr.dtype.str}{list(arr.shape)}, header says "
                    f"{col['dtype']}{col['shape']}")
            data[name] = arr
            off += nb
        else:
            nb = int(col["nbytes"])
            arr = np.frombuffer(payload[off:off + nb],
                                dtype=np.dtype(col["dtype"]))
            data[name] = arr.reshape(col["shape"]).copy()
            off += nb
    return columns, data, header.get("stats", {})


def patch_subquery(body: bytes, shard_ds: str,
                   epoch: Optional[int] = None) -> bytes:
    """Retarget an encoded subquery at one shard store and stamp the
    broker's plan epoch into the request envelope. Decoding the JSON
    once per shard beats re-running full spec serde per shard.

    ``clusterEpoch`` is an envelope field, not part of the query spec:
    the historical pops it before serde (:func:`split_subquery`) and
    uses it to learn which epoch the requesting broker has swapped to —
    the signal that old-epoch-only shard stores can be retired."""
    d = json.loads(body.decode("utf-8"))
    d["dataSource"] = shard_ds
    if epoch is not None:
        d["clusterEpoch"] = int(epoch)
    return json.dumps(d, separators=(",", ":")).encode("utf-8")


def split_subquery(raw: bytes) -> Tuple[dict, Optional[int]]:
    """Decode a subquery request into (spec dict, clusterEpoch or None),
    removing the envelope field so spec serde sees only the query."""
    d = json.loads(raw.decode("utf-8"))
    ep = d.pop("clusterEpoch", None)
    return d, (int(ep) if ep is not None else None)


_JOIN_MAGIC = b"SDJ1"


def encode_join_exec(spec: dict,
                     sides: Dict[str, Tuple[List[str],
                                            Dict[str, np.ndarray]]]) -> bytes:
    """One partitioned-join exec request: ``b"SDJ1" + uint32le(header_len)
    + header_json + side frames + uint32le(crc32)``. Each side ("probe",
    "build") is a full SDW1 result frame (same codec path as shard
    partials — RLE'd code columns, JSON object columns, CRC per frame),
    concatenated in header order with lengths in the header. ``spec`` is
    the JSON-safe lowered join plan (keys, group-by, aggs, residual as
    serde expr dicts) — no pickle, same RCE posture as subqueries."""
    frames = [(name, encode_result(cols, data))
              for name, (cols, data) in sides.items()]
    header = {"spec": spec,
              "frames": [{"side": name, "nbytes": len(fb)}
                         for name, fb in frames]}
    hb = json.dumps(header, separators=(",", ":")).encode("utf-8")
    body = b"".join([_JOIN_MAGIC, _LEN.pack(len(hb)), hb]
                    + [fb for _, fb in frames])
    return body + _LEN.pack(zlib.crc32(body))


def decode_join_exec(payload: bytes) -> Tuple[dict, Dict[str, Tuple[
        List[str], Dict[str, np.ndarray]]]]:
    """-> (spec, {side: (columns, data)}). ValueError on a bad frame."""
    if len(payload) < 12 or payload[:4] != _JOIN_MAGIC:
        raise ValueError("bad join wire magic")
    (crc,) = _LEN.unpack_from(payload, len(payload) - 4)
    if zlib.crc32(payload[:-4]) != crc:
        raise ValueError("join wire CRC mismatch (truncated or corrupt)")
    payload = payload[:-4]
    (hlen,) = _LEN.unpack_from(payload, 4)
    off = 8 + hlen
    header = json.loads(payload[8:off].decode("utf-8"))
    sides = {}
    for fr in header["frames"]:
        nb = int(fr["nbytes"])
        cols, data, _ = decode_result(payload[off:off + nb])
        sides[str(fr["side"])] = (cols, data)
        off += nb
    return header["spec"], sides


_INGEST_MAGIC = b"SDI1"


def encode_ingest(name: str, shard: str, batch_id: int, kwargs: dict,
                  body: bytes, src: str = "") -> bytes:
    """One pushed ingest batch: ``b"SDI1" + uint32le(header_len) +
    header_json + body + uint32le(crc32 of everything before it)``.
    ``body`` is the batch in the SAME Arrow-IPC encoding the WAL
    journals (persist/wal.py:encode_batch) — the broker pushes the
    exact bytes it committed, so owner and journal can never disagree
    about the rows. ``(src, batch_id)`` identifies the push: ``src`` is
    the broker's boot generation and ``batch_id`` its per-process push
    counter; owners dedup on the pair so a retried push never
    double-applies, and a restarted broker (fresh ``src``) never has
    its counter restart read as a replay."""
    header = {"name": name, "shard": shard, "batch": int(batch_id),
              "src": src, "kwargs": kwargs}
    hb = json.dumps(header, separators=(",", ":")).encode("utf-8")
    frame = b"".join([_INGEST_MAGIC, _LEN.pack(len(hb)), hb, body])
    return frame + _LEN.pack(zlib.crc32(frame))


def decode_ingest(payload: bytes) -> Tuple[dict, bytes]:
    """-> (header dict, body bytes). Raises ValueError on a malformed
    frame — same detectability contract as the subquery wire format."""
    if len(payload) < 12 or payload[:4] != _INGEST_MAGIC:
        raise ValueError("bad ingest wire magic")
    (crc,) = _LEN.unpack_from(payload, len(payload) - 4)
    if zlib.crc32(payload[:-4]) != crc:
        raise ValueError(
            "ingest wire CRC mismatch (truncated or corrupt frame)")
    payload = payload[:-4]
    (hlen,) = _LEN.unpack_from(payload, 4)
    off = 8 + hlen
    header = json.loads(payload[8:off].decode("utf-8"))
    return header, payload[off:]


def encode_error(kind: str, message: str, **extra) -> bytes:
    return json.dumps({"error": kind, "message": message, **extra},
                      separators=(",", ":")).encode("utf-8")


def decode_error(payload: bytes) -> dict:
    try:
        d = json.loads(payload.decode("utf-8", "replace"))
        if isinstance(d, dict) and "error" in d:
            return d
    except ValueError:
        pass
    return {"error": "Unknown", "message": payload[:200].decode(
        "utf-8", "replace")}
