"""Materialized rollup datasources (mv/).

Pre-aggregated, segment-backed rollup datasources declared with
``CREATE ROLLUP``, built through the existing engine, and transparently
substituted for the base datasource by the planner when a query is
answerable from the rollup (mv/match.py). ≈ Druid rollup at ingest plus
Sparkline rewriting queries onto the rolled-up index.
"""

from spark_druid_olap_tpu.mv.registry import (  # noqa: F401
    RollupDef, create_rollup, drop_rollup, refresh_rollup, rollups_view)
