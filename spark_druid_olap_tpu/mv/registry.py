"""Rollup lifecycle: CREATE / DROP / REFRESH + metadata views.

A rollup is built by running a GroupBy over the base datasource through the
normal planner/engine path (host fallback included) and re-ingesting the
result as a first-class segment-backed datasource named
``__rollup_<name>`` — Druid's rollup-at-ingest, built from the engine's own
aggregation semantics so stored partials are definitionally consistent with
what the planner would compute from base segments.

Staleness contract: the definition records the base's ingest version at
build time (:meth:`SegmentStore.datasource_version`); any later re-ingest /
stream append / drop of the base bumps that version and the rollup is
bypassed by the matcher until ``REFRESH ROLLUP`` rebuilds it. Stale results
are never served.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Tuple

import numpy as np
import pandas as pd

from spark_druid_olap_tpu.ir import expr as E
from spark_druid_olap_tpu.sql import ast as A

BACKING_PREFIX = "__rollup_"

# merge-closed declarable aggregate functions (avg derives at query time
# from sum+count; sketches are not losslessly re-aggregable)
_ALLOWED_FNS = ("sum", "min", "max", "count")

_ALLOWED_GRAINS = ("year", "quarter", "month", "week", "day")


@dataclasses.dataclass
class RollupDef:
    name: str
    base: str
    backing: str
    dims: Tuple[str, ...]
    agg_exprs: Tuple[E.Expr, ...]
    granularity: Optional[str]
    time_column: Optional[str]          # base time column (bucketed) or None
    built_version: int = -1
    # True when bucketing was proven to be the IDENTITY map at build time
    # (day granularity over a day-resolution time column): rollup time
    # values equal base values row-for-row, so the matcher may carry time
    # filters/intervals/extractions over verbatim
    time_identity: bool = False
    # agg input identity (mv.match.agg_key) -> stored partial column
    agg_map: Dict[tuple, str] = dataclasses.field(default_factory=dict)


def _validate(ctx, stmt: A.CreateRollup):
    try:
        ds = ctx.store.get(stmt.base)
    except KeyError:
        raise ValueError(f"unknown datasource {stmt.base!r}") from None
    cols = set(ds.column_names())
    for d in stmt.dimensions:
        if d not in cols:
            raise ValueError(f"rollup dimension {d!r} is not a column of "
                             f"{stmt.base!r}")
        if d == ds.time_column:
            raise ValueError(
                f"the time column {d!r} cannot be a rollup dimension; use "
                f"GRANULARITY to keep a bucketed time axis")
    for e in stmt.aggregations:
        if not isinstance(e, E.AggCall) or e.distinct or e.approx \
                or e.fn not in _ALLOWED_FNS:
            raise ValueError(
                f"rollup aggregation {E.to_sql(e)} is not merge-closed; "
                f"allowed: {', '.join(_ALLOWED_FNS)} (avg derives from "
                f"sum+count at query time)")
    if stmt.granularity is not None:
        if stmt.granularity not in _ALLOWED_GRAINS:
            raise ValueError(f"granularity {stmt.granularity!r} not in "
                             f"{_ALLOWED_GRAINS}")
        if ds.time_column is None:
            raise ValueError(
                f"GRANULARITY requires a time column on {stmt.base!r}")


def _coerce_numeric_objects(df: pd.DataFrame) -> pd.DataFrame:
    """Engine results can carry wide-int columns as object arrays of
    Python ints; re-ingest needs real numeric dtypes (an object column
    would dictionary-encode as a string dimension)."""
    for c in df.columns:
        if df[c].dtype == object:
            vals = df[c].tolist()
            if vals and all(isinstance(v, (int, float))
                            and not isinstance(v, bool) for v in vals):
                df[c] = pd.to_numeric(df[c])
    return df


def _build_backing(ctx, r: RollupDef) -> None:
    """(Re)build the backing datasource + agg identity map for ``r``."""
    from spark_druid_olap_tpu.mv.match import agg_key
    from spark_druid_olap_tpu.parallel.executor import EngineFallback
    from spark_druid_olap_tpu.planner import builder as B
    from spark_druid_olap_tpu.planner import host_exec
    from spark_druid_olap_tpu.utils import host_eval as _he
    from spark_druid_olap_tpu.utils.config import TZ_ID

    items = [A.SelectItem(E.Column(d), alias=d) for d in r.dims]
    group = [E.Column(d) for d in r.dims]
    if r.granularity is not None:
        bucket = E.Func("date_trunc", (E.Literal(r.granularity),
                                       E.Column(r.time_column)))
        items.append(A.SelectItem(bucket, alias=r.time_column))
        group.append(bucket)
    for i, e in enumerate(r.agg_exprs):
        items.append(A.SelectItem(e, alias=f"agg_{i}"))
    stmt = A.SelectStmt(items=tuple(items),
                        relation=A.TableRef(r.base),
                        group_by=tuple(group) or None)

    built_version = ctx.store.datasource_version(r.base)
    base_ds = ctx.store.get(r.base)
    # identity proof must hold for EVERY row; a partial store only sees
    # its host's rows, and a per-host divergent rewrite decision would
    # diverge program shapes across the mesh
    r.time_identity = bool(
        r.granularity == "day" and base_ds.time is not None
        and not base_ds.is_partial
        and not base_ds.time.ms_in_day.any())
    ctx._mv_building = True
    tz_tok = _he.SESSION_TZ.set(ctx.config.get(TZ_ID))
    try:
        from spark_druid_olap_tpu.planner.plans import PlanUnsupported
        from spark_druid_olap_tpu.sql.session import execute_planned
        try:
            pq = B.build(ctx, stmt)
        except PlanUnsupported as e:
            raise ValueError(
                f"rollup {r.name!r} definition is not engine-plannable: "
                f"{e}") from e
        try:
            df = execute_planned(ctx, pq)
        except EngineFallback:
            df = host_exec.execute_select(ctx, stmt)
    finally:
        _he.SESSION_TZ.reset(tz_tok)
        ctx._mv_building = False

    df = _coerce_numeric_objects(df.copy())
    kwargs = {}
    if r.granularity is not None:
        if not np.issubdtype(df[r.time_column].to_numpy().dtype,
                             np.datetime64):
            df[r.time_column] = pd.to_datetime(df[r.time_column])
        kwargs["time_column"] = r.time_column
    ctx.ingest_dataframe(r.backing, df, **kwargs)

    # authoritative agg identity: the specs the builder actually planned.
    # Only output partials count — hidden helper aggs (e.g. the count
    # behind a sum-of-literal post-agg) have no stored column.
    out_cols = set(df.columns)
    from spark_druid_olap_tpu.ir import spec as S
    agg_map: Dict[tuple, str] = {}
    for a in S.query_aggregations(pq.specs[0]):
        if a.kind != "anyvalue" and a.name in out_cols:
            agg_map.setdefault(agg_key(a), a.name)
    r.agg_map = agg_map
    r.built_version = built_version


def create_rollup(ctx, stmt: A.CreateRollup) -> RollupDef:
    if stmt.name in ctx.rollups:
        raise ValueError(f"rollup {stmt.name!r} already exists "
                         f"(DROP ROLLUP first, or REFRESH)")
    _validate(ctx, stmt)
    ds = ctx.store.get(stmt.base)
    r = RollupDef(
        name=stmt.name, base=stmt.base,
        backing=BACKING_PREFIX + stmt.name,
        dims=tuple(stmt.dimensions), agg_exprs=tuple(stmt.aggregations),
        granularity=stmt.granularity,
        time_column=ds.time_column if stmt.granularity is not None else None)
    _build_backing(ctx, r)
    ctx.rollups[stmt.name] = r
    return r


def drop_rollup(ctx, name: str) -> None:
    r = ctx.rollups.pop(name, None)
    if r is None:
        raise ValueError(f"unknown rollup {name!r}")
    try:
        ctx.store.drop(r.backing)
    except KeyError:
        pass


def refresh_rollup(ctx, name: str) -> RollupDef:
    r = ctx.rollups.get(name)
    if r is None:
        raise ValueError(f"unknown rollup {name!r}")
    _build_backing(ctx, r)
    return r


def handle_statement(ctx, stmt) -> str:
    """Session dispatch for the rollup DDL statements."""
    if isinstance(stmt, A.CreateRollup):
        r = create_rollup(ctx, stmt)
        rows = ctx.store.get(r.backing).num_rows
        return f"rollup {r.name} created ({rows} rows)"
    if isinstance(stmt, A.DropRollup):
        drop_rollup(ctx, stmt.name)
        return f"rollup {stmt.name} dropped"
    if isinstance(stmt, A.RefreshRollup):
        r = refresh_rollup(ctx, stmt.name)
        rows = ctx.store.get(r.backing).num_rows
        return f"rollup {r.name} refreshed ({rows} rows)"
    raise TypeError(f"not a rollup statement: {type(stmt).__name__}")


def clear_rollups(ctx, datasource: Optional[str] = None) -> None:
    """CLEAR METADATA interaction: a full clear forgets every rollup (their
    backing datasources died with the store); a per-datasource clear drops
    rollups built ON that datasource (their base version bump would bypass
    them forever) and any rollup addressed by name."""
    if not getattr(ctx, "rollups", None):
        return
    if datasource is None:
        ctx.rollups.clear()
        return
    for name in [n for n, r in ctx.rollups.items()
                 if r.base == datasource or n == datasource]:
        try:
            drop_rollup(ctx, name)
        except ValueError:
            pass


def rollup_to_dict(r: RollupDef) -> dict:
    """JSON form of a rollup definition for persist/'s catalog.json.
    ``built_version`` rides along so post-recovery staleness checks
    compare against the RESTORED base ingest version — a rollup stale at
    crash time is still stale (and bypassed) after recovery."""
    from spark_druid_olap_tpu.ir.serde import expr_to_dict
    return {
        "name": r.name, "base": r.base, "backing": r.backing,
        "dims": list(r.dims),
        "aggs": [expr_to_dict(e) for e in r.agg_exprs],
        "granularity": r.granularity,
        "timeColumn": r.time_column,
        "builtVersion": int(r.built_version),
        "timeIdentity": bool(r.time_identity),
        # agg_key tuples are (kind, field, sql|None, filter_repr) — all
        # JSON scalars; lists round-trip back to tuples below
        "aggMap": [[list(k), v] for k, v in r.agg_map.items()],
    }


def rollup_from_dict(d: dict) -> RollupDef:
    from spark_druid_olap_tpu.ir.serde import expr_from_dict
    return RollupDef(
        name=d["name"], base=d["base"], backing=d["backing"],
        dims=tuple(d["dims"]),
        agg_exprs=tuple(expr_from_dict(e) for e in d["aggs"]),
        granularity=d.get("granularity"),
        time_column=d.get("timeColumn"),
        built_version=int(d.get("builtVersion", -1)),
        time_identity=bool(d.get("timeIdentity", False)),
        agg_map={tuple(k): v for k, v in d.get("aggMap", ())})


def rollups_view(ctx) -> pd.DataFrame:
    """``sys_rollups`` / ``GET /metadata/rollups`` — one row per rollup."""
    from spark_druid_olap_tpu.mv.match import is_fresh
    rows = []
    for name in sorted(getattr(ctx, "rollups", {}) or {}):
        r = ctx.rollups[name]
        try:
            n_rows = ctx.store.get(r.backing).num_rows
        except KeyError:
            n_rows = 0
        rows.append({
            "name": r.name,
            "base": r.base,
            "datasource": r.backing,
            "dimensions": ",".join(r.dims),
            "aggregations": ",".join(E.to_sql(e) for e in r.agg_exprs),
            "granularity": r.granularity or "all",
            "rows": n_rows,
            "built_version": r.built_version,
            "base_version": ctx.store.datasource_version(r.base),
            "fresh": bool(is_fresh(ctx, r)),
        })
    cols = ["name", "base", "datasource", "dimensions", "aggregations",
            "granularity", "rows", "built_version", "base_version", "fresh"]
    return pd.DataFrame(rows, columns=cols)
