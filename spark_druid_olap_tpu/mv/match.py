"""Rollup eligibility + spec rewrite.

Decides whether a planned :class:`GroupByQuerySpec` can be answered from a
materialized rollup datasource and, if so, rewrites it in place of the base
scan. Runs inside the pushdown builder BEFORE spec transforms, so a
rewritten GroupBy still benefits from the timeseries/topN/search lowerings.

Eligibility (≈ Sparkline's rewrite onto the Druid rollup index; derivability
mirrors cache/subsume.py's merge table):

* every grouping dimension is *covered*: its source column is a rollup
  dimension, or is join-key-equivalent to one (``FDGraph.equivalents`` —
  value-equal on the flat datasource, so the rollup column substitutes
  exactly);
* time extractions over the base time column need the rollup's bucket
  granularity to nest inside the extraction grain (a ``day`` rollup can
  serve ``year(t)``; a ``month`` rollup cannot serve ``week``);
* every aggregation is merge-closed derivable from a stored partial:
  count -> longsum of the stored count, sum/min/max re-aggregate with the
  same kind, ``anyvalue`` carries over a covered column; sketches
  (cardinality/theta) are never derivable;
* the filter references only covered columns (never the raw time column —
  time predicates arrive as intervals);
* intervals are empty, or every endpoint is aligned to the rollup's
  bucket granularity.

Exception: when the rollup build PROVED bucketing to be the identity map
(``day`` granularity over a day-resolution time column, the BI-typical
date-keyed index), the rollup's time values equal the base's row-for-row,
so time filters, extractions, and arbitrary interval endpoints all carry
over verbatim.

A stale rollup (base re-ingested since the build) is never considered.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import numpy as np

from spark_druid_olap_tpu.cache import subsume as SUB
from spark_druid_olap_tpu.cache.keys import normalize_filter
from spark_druid_olap_tpu.ir import expr as E
from spark_druid_olap_tpu.ir import spec as S
from spark_druid_olap_tpu.ops.filters import columns_of_filter

# extraction field -> minimum calendar resolution it needs preserved
_FIELD_GRAIN = {
    "year": "year", "quarter": "quarter", "month": "month", "week": "week",
    "day": "day", "dow": "day", "doy": "day", "hour": "hour",
    "minute": "minute",
}

# agg kinds that re-aggregate losslessly over stored partials of same kind
_REAGG_KINDS = ("longsum", "doublesum", "longmin", "longmax", "doublemin",
                "doublemax")


def agg_key(a: S.AggregationSpec) -> tuple:
    """Identity of an aggregation's INPUT (kind + source), independent of
    its output name — a query's hidden avg-sum matches a declared sum."""
    return (a.kind, a.field,
            None if a.expr is None else E.to_sql(a.expr),
            repr(normalize_filter(a.filter)))


def _gran_covers(rollup_gran: str, field: str) -> bool:
    """True if re-deriving ``field`` from rollup buckets of ``rollup_gran``
    is exact: the bucket grain nests inside the field's grain."""
    need = _FIELD_GRAIN.get(field)
    if need is None and field.startswith("trunc_"):
        need = field[len("trunc_"):]
    if need is None:
        return False
    return rollup_gran == need or rollup_gran in SUB._SOURCES.get(need, ())


def is_fresh(ctx, r) -> bool:
    """Backing datasource registered AND built from the base's current
    ingest version (any re-ingest/drop of the base bumps it)."""
    try:
        ctx.store.get(r.backing)
    except KeyError:
        return False
    return r.built_version == ctx.store.datasource_version(r.base)


def try_rewrite(ctx, q) -> Tuple[Optional[S.GroupByQuerySpec], Optional[str]]:
    """Return (rewritten spec, rollup name), or (None, None)."""
    rollups = getattr(ctx, "rollups", None)
    if not rollups or getattr(ctx, "_mv_building", False):
        return None, None
    from spark_druid_olap_tpu.utils.config import MV_REWRITE_ENABLED
    if not ctx.config.get(MV_REWRITE_ENABLED):
        return None, None
    if not isinstance(q, S.GroupByQuerySpec):
        return None, None

    def backing_rows(r):
        try:
            return ctx.store.get(r.backing).num_rows
        except KeyError:
            return 0

    # smallest fresh candidate first: fewest rows scanned wins
    candidates = sorted(
        (r for r in rollups.values()
         if r.base == q.datasource and is_fresh(ctx, r)),
        key=lambda r: (backing_rows(r), r.name))
    for r in candidates:
        rq = _rewrite_one(ctx, q, r)
        if rq is not None:
            return rq, r.name
    return None, None


def _rewrite_one(ctx, q: S.GroupByQuerySpec, r):
    gran = getattr(q, "granularity", None)
    if gran is not None and not gran.is_all():
        return None  # SQL-planned GroupBys carry grain via extractions

    try:
        base_tcol = ctx.store.get(r.base).time_column
    except KeyError:
        return None
    covered = set(r.dims)
    fd = None
    try:
        fd = ctx.catalog.fd_graph_for(r.base, ctx.store)
    except Exception:  # noqa: BLE001 — no star schema is not an error
        fd = None

    # identity-bucketed time (day over day-resolution data): the rollup's
    # time values EQUAL the base's, so the time column behaves like any
    # covered dimension — filters, extractions, intervals carry verbatim
    tid = getattr(r, "time_identity", False)

    def cov(col: str) -> Optional[str]:
        """Rollup column holding values equal to ``col``, or None."""
        if col == base_tcol:
            return col if tid else None  # bucketed, raw only under identity
        if col in covered:
            return col
        if fd is not None:
            for e in fd.equivalents(col):
                if e in covered:
                    return e
        return None

    def rename_expr(ex):
        """Rewrite an expression onto covered columns; None if impossible."""
        mapping = {}
        for c in E.columns_in(ex):
            cc = cov(c)
            if cc is None:
                return None
            mapping[c] = cc

        def rep(n):
            if isinstance(n, E.Column) and n.name in mapping:
                return E.Column(mapping[n.name])
            return n
        return E.transform(ex, rep)

    # -- dimensions -----------------------------------------------------------
    new_dims = []
    for d in q.dimensions:
        ext = d.extraction
        if ext is None:
            c = cov(d.dimension)
            if c is None:
                return None
            new_dims.append(dataclasses.replace(d, dimension=c))
        elif isinstance(ext, S.TimeExtraction):
            if d.dimension == base_tcol:
                # served from the bucketed time column, which keeps the
                # base column's name — carries over verbatim when exact
                if not tid and (r.granularity is None
                                or not _gran_covers(r.granularity,
                                                    ext.field)):
                    return None
                new_dims.append(d)
            else:
                c = cov(d.dimension)  # date-typed dim, stored raw
                if c is None:
                    return None
                new_dims.append(dataclasses.replace(d, dimension=c))
        elif isinstance(ext, S.ExprExtraction):
            ex2 = rename_expr(ext.expr)
            if ex2 is None:
                return None
            src = cov(d.dimension)
            if src is None:
                return None
            new_dims.append(dataclasses.replace(
                d, dimension=src,
                extraction=dataclasses.replace(ext, expr=ex2)))
        elif isinstance(ext, (S.LookupExtraction, S.RegexExtraction)):
            c = cov(d.dimension)
            if c is None:
                return None
            new_dims.append(dataclasses.replace(d, dimension=c))
        else:
            return None

    # -- filter ---------------------------------------------------------------
    new_filter, ok = _rewrite_filter(q.filter, cov, rename_expr)
    if not ok:
        return None

    # -- aggregations ---------------------------------------------------------
    new_aggs = []
    for a in q.aggregations:
        if a.kind == "anyvalue":
            c = cov(a.field)
            if c is None:
                return None
            new_aggs.append(dataclasses.replace(a, field=c))
            continue
        stored = r.agg_map.get(agg_key(a))
        if stored is None:
            return None
        if a.kind == "count":
            # stored partial counts re-aggregate as a long sum
            new_aggs.append(S.AggregationSpec("longsum", a.name,
                                              field=stored))
        elif a.kind in _REAGG_KINDS:
            new_aggs.append(S.AggregationSpec(a.kind, a.name, field=stored))
        else:
            return None  # sketches are not merge-closed from partials

    # -- intervals ------------------------------------------------------------
    if q.intervals is not None and not tid:
        if r.granularity is None:
            return None
        for lo, hi in q.intervals:
            ends = np.array([int(lo), int(hi)], dtype=np.int64)
            if not np.array_equal(
                    SUB._bucket_start_ms(r.granularity, ends), ends):
                return None  # endpoint splits a bucket

    return dataclasses.replace(
        q, datasource=r.backing, dimensions=tuple(new_dims),
        aggregations=tuple(new_aggs), filter=new_filter)


def _rewrite_filter(f, cov, rename_expr):
    """Rewrite a filter tree onto rollup columns. Returns (filter, ok).

    Exactness: the rollup groups by ALL its dimensions, so every rollup
    row carries the exact dimension values of its source rows — a filter
    over covered columns selects exactly the source rows' groups."""
    if f is None:
        return None, True
    if isinstance(f, S.SpatialFilter):
        return None, False  # spatial axes are per-row, lost in rollup
    if isinstance(f, S.LogicalFilter):
        kids = []
        for c in f.fields:
            nc, ok = _rewrite_filter(c, cov, rename_expr)
            if not ok:
                return None, False
            kids.append(nc)
        return dataclasses.replace(f, fields=tuple(kids)), True
    if isinstance(f, S.ExprFilter):
        ex2 = rename_expr(f.expr)
        if ex2 is None:
            return None, False
        return dataclasses.replace(f, expr=ex2), True
    cols = columns_of_filter(f)
    if len(cols) != 1:
        return None, False
    c = cov(next(iter(cols)))
    if c is None:
        return None, False
    return dataclasses.replace(f, dimension=c), True
