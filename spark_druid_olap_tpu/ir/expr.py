"""Scalar expression IR.

Plays the role Catalyst ``Expression`` trees play in the reference: the common
currency between the SQL front end, the planner's rewrite rules, and code
generation. Where the reference compiles unsupported-but-deterministic
expressions to **JavaScript executed inside Druid**
(``jscodegen/JSCodeGenerator.scala:59-66``), we compile them to **XLA** via
``ops/expr_compile.py`` — and, exactly like ``JSCodeGenerator`` returning
``None``, the compiler bails cleanly on unsupported nodes so the planner can
leave a host-side residual.

Deliberately small: no exprIds/resolution machinery — names are resolved by
the planner against the (globally-unique, star-schema-wide) column namespace,
which the reference also requires (``StarSchemaInfo.scala:127-165``).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Sequence, Tuple


class FrozenIntSet:
    """Immutable sorted int64 membership set with O(1) repr/eq/hash.

    Decorrelated semi/anti joins (EXISTS -> key IN <list>) produce key lists
    reaching millions of values; carrying them as plain tuples would make
    ``repr(query)`` (the executor's program-cache key) and structural
    equality O(n). The digest stands in for the contents everywhere except
    actual membership tests, which use the sorted array directly.
    """

    __slots__ = ("array", "_digest")

    def __init__(self, values):
        import numpy as np
        arr = values if isinstance(values, np.ndarray) \
            else np.fromiter((int(v) for v in values), dtype=np.int64)
        arr = np.unique(arr.astype(np.int64, copy=False))
        arr.setflags(write=False)
        object.__setattr__(self, "array", arr)
        import hashlib
        object.__setattr__(
            self, "_digest", hashlib.sha1(arr.tobytes()).hexdigest())

    def __iter__(self):
        return iter(self.array.tolist())

    def __len__(self):
        return int(len(self.array))

    def __contains__(self, v):
        import numpy as np
        i = int(np.searchsorted(self.array, int(v)))
        return i < len(self.array) and int(self.array[i]) == int(v)

    def __repr__(self):
        return f"FrozenIntSet(n={len(self.array)}, sha={self._digest[:16]})"

    def __eq__(self, o):
        return isinstance(o, FrozenIntSet) and self._digest == o._digest

    def __hash__(self):
        return hash(self._digest)


class Expr:
    """Base scalar expression node."""

    def children(self) -> Tuple["Expr", ...]:
        return ()

    # -- convenience builders (used by tests and the planner) -----------------
    def __add__(self, o): return BinaryOp("+", self, lit(o))
    def __sub__(self, o): return BinaryOp("-", self, lit(o))
    def __mul__(self, o): return BinaryOp("*", self, lit(o))
    def __truediv__(self, o): return BinaryOp("/", self, lit(o))
    def __radd__(self, o): return BinaryOp("+", lit(o), self)
    def __rsub__(self, o): return BinaryOp("-", lit(o), self)
    def __rmul__(self, o): return BinaryOp("*", lit(o), self)
    def eq(self, o): return Comparison("=", self, lit(o))
    def ne(self, o): return Comparison("!=", self, lit(o))
    def lt(self, o): return Comparison("<", self, lit(o))
    def le(self, o): return Comparison("<=", self, lit(o))
    def gt(self, o): return Comparison(">", self, lit(o))
    def ge(self, o): return Comparison(">=", self, lit(o))


def lit(v) -> "Expr":
    return v if isinstance(v, Expr) else Literal(v)


@dataclasses.dataclass(frozen=True)
class Column(Expr):
    name: str
    # The table-alias qualifier as WRITTEN ('s2.region' -> qual='s2'),
    # carried as non-comparing metadata for the planner's alias-scoping
    # pass (planner/scoping.py) — correlated self-references like
    # 's2.region = s.region' are unresolvable from bare names alone.
    # Stripped (None) everywhere after that pass; excluded from eq/repr
    # so resolved trees and cache keys are unaffected.
    qual: Optional[str] = dataclasses.field(default=None, compare=False,
                                            repr=False)


@dataclasses.dataclass(frozen=True)
class Literal(Expr):
    value: Any


@dataclasses.dataclass(frozen=True)
class BinaryOp(Expr):
    op: str  # + - * / %
    left: Expr
    right: Expr

    def children(self): return (self.left, self.right)


@dataclasses.dataclass(frozen=True)
class Comparison(Expr):
    op: str  # = != < <= > >=
    left: Expr
    right: Expr

    def children(self): return (self.left, self.right)


@dataclasses.dataclass(frozen=True)
class And(Expr):
    parts: Tuple[Expr, ...]

    def children(self): return self.parts


@dataclasses.dataclass(frozen=True)
class Or(Expr):
    parts: Tuple[Expr, ...]

    def children(self): return self.parts


@dataclasses.dataclass(frozen=True)
class Not(Expr):
    child: Expr

    def children(self): return (self.child,)


@dataclasses.dataclass(frozen=True)
class IsNull(Expr):
    child: Expr
    negated: bool = False

    def children(self): return (self.child,)


@dataclasses.dataclass(frozen=True)
class InList(Expr):
    child: Expr
    values: Tuple[Any, ...]
    negated: bool = False

    def children(self): return (self.child,)


@dataclasses.dataclass(frozen=True)
class Between(Expr):
    child: Expr
    low: Expr
    high: Expr
    negated: bool = False

    def children(self): return (self.child, self.low, self.high)


@dataclasses.dataclass(frozen=True)
class Like(Expr):
    child: Expr
    pattern: str           # SQL LIKE pattern (% and _)
    negated: bool = False

    def children(self): return (self.child,)


@dataclasses.dataclass(frozen=True)
class Func(Expr):
    """Named scalar function call (``year``, ``month``, ``extract``,
    ``date_trunc``, ``substr``, ``lower``, ``abs``, ...)."""

    name: str
    args: Tuple[Expr, ...]

    def children(self): return self.args


@dataclasses.dataclass(frozen=True)
class Cast(Expr):
    child: Expr
    to: str  # 'long' | 'double' | 'string' | 'date' | 'timestamp'

    def children(self): return (self.child,)


@dataclasses.dataclass(frozen=True)
class Case(Expr):
    """CASE WHEN c1 THEN v1 [WHEN ...] ELSE e END."""

    branches: Tuple[Tuple[Expr, Expr], ...]
    otherwise: Optional[Expr]

    def children(self):
        out = []
        for c, v in self.branches:
            out += [c, v]
        if self.otherwise is not None:
            out.append(self.otherwise)
        return tuple(out)


# -- aggregate call (only valid inside SELECT/HAVING/ORDER trees) --------------
# comparison-operator mirror for operand swaps (a <op> b == b <flip> a);
# the single source shared by planner/executor rewrites
FLIP_CMP = {"<": ">", "<=": ">=", ">": "<", ">=": "<=",
            "=": "=", "!=": "!=", "<>": "<>"}


class _FrozenTableBase:
    """Shared identity protocol for frozen lookup tables: a sha1 digest
    stands in for the contents everywhere except actual lookups — the
    executor's program-cache key is ``repr(query)`` (like
    :class:`FrozenIntSet`)."""

    __slots__ = ()

    def _freeze(self, arrays):
        import hashlib
        h = hashlib.sha1()
        for a in arrays:
            a.setflags(write=False)
            h.update(a.tobytes())
        object.__setattr__(self, "_digest", h.hexdigest())

    def __len__(self):
        return int(len(self.values))

    def __repr__(self):
        return f"{type(self).__name__}(n={len(self)}, " \
               f"sha={self._digest[:16]})"

    def __eq__(self, o):
        return type(o) is type(self) and self._digest == o._digest

    def __hash__(self):
        return hash(self._digest)


class FrozenKeyedTable(_FrozenTableBase):
    """Immutable sorted int64-key -> float64-value map."""

    __slots__ = ("keys", "values", "_digest")

    def __init__(self, keys, values):
        import numpy as np
        k = np.asarray(keys, dtype=np.int64)
        v = np.asarray(values, dtype=np.float64)
        assert k.shape == v.shape and k.ndim == 1
        order = np.argsort(k, kind="stable")
        object.__setattr__(self, "keys", k[order])
        object.__setattr__(self, "values", v[order])
        self._freeze((self.keys, self.values))


class FrozenKeyedTable2(_FrozenTableBase):
    """Immutable (int32-range, int32-range) composite-key -> float64-value
    map, sorted lexicographically. Key domains MUST fit int32: the host
    packs pairs into one int64 (k1*2^32 + offset(k2)) and the device
    compares i32 pairs — wider keys would wrap. Enforced here so every
    construction path (planner, serde) keeps the invariant."""

    __slots__ = ("keys1", "keys2", "values", "_digest")

    def __init__(self, keys1, keys2, values):
        import numpy as np
        k1 = np.asarray(keys1, dtype=np.int64)
        k2 = np.asarray(keys2, dtype=np.int64)
        v = np.asarray(values, dtype=np.float64)
        assert k1.shape == k2.shape == v.shape and k1.ndim == 1
        for k in (k1, k2):
            if len(k) and (k.min() < -(2**31) or k.max() >= 2**31):
                raise ValueError(
                    "FrozenKeyedTable2 keys must fit int32")
        order = np.lexsort((k2, k1))
        object.__setattr__(self, "keys1", k1[order])
        object.__setattr__(self, "keys2", k2[order])
        object.__setattr__(self, "values", v[order])
        self._freeze((self.keys1, self.keys2, self.values))


@dataclasses.dataclass(frozen=True)
class KeyedLookup2(Expr):
    """Composite-key broadcast join: the table value at integer pair
    (key1, key2), NULL/default on miss — the decorrelated form of a
    scalar subquery correlated on TWO columns (TPC-H q20's
    'where l_partkey = ps_partkey and l_suppkey = ps_suppkey' shape).
    Device lowering binary-searches the lexicographically-sorted pair
    arrays (no int64 needed on 32-bit backends)."""

    key1: Expr
    key2: Expr
    table: FrozenKeyedTable2
    default: Optional[float] = None

    def children(self):
        return (self.key1, self.key2)


@dataclasses.dataclass(frozen=True)
class KeyedLookup(Expr):
    """Scalar broadcast-join: the table value at integer ``key`` (NULL when
    absent). Produced by correlated-scalar-subquery inlining — the
    decorrelated per-key aggregate of ``(select agg(..) from inner where
    inner.k = outer.k)`` becomes a device gather (binary search over the
    sorted key array), keeping the OUTER query engine-pushable (TPC-H
    q2/q17 shape; ≈ Spark's RewriteCorrelatedScalarSubquery followed by a
    broadcast hash join, collapsed into the scan)."""

    key: Expr
    table: FrozenKeyedTable
    # value for keys absent from the table: None = SQL NULL (NaN-coded);
    # a float for aggregates with a non-NULL empty-group identity
    # (count(*) over zero rows is 0, not NULL)
    default: Optional[float] = None

    def children(self):
        return (self.key,)


@dataclasses.dataclass(frozen=True)
class AggCall(Expr):
    """sum/min/max/avg/count/count_distinct over an argument expression."""

    fn: str                      # sum | min | max | avg | count | count_distinct
    arg: Optional[Expr]          # None for count(*)
    distinct: bool = False
    approx: bool = False         # approximate count-distinct (HLL)
    fraction: Optional[float] = None  # quantile for percentile_approx

    def children(self):
        return (self.arg,) if self.arg is not None else ()


@dataclasses.dataclass(frozen=True)
class WindowCall(Expr):
    """``fn(args) OVER (PARTITION BY ... ORDER BY ... [ROWS ...])``.

    Never reaches the pushdown builder or the host evaluator: the
    session's window post-pass (``window/plan.py``) strips these from
    the statement, runs the base query through the normal engine /
    cluster / mesh path, and computes the window columns on device over
    the (merged) result frame.

    ``frame`` is a ROWS frame as (preceding, following) row counts with
    ``None`` meaning UNBOUNDED on that side; ``frame is None`` means the
    SQL default (unbounded preceding .. current row when ORDER BY is
    present, the whole partition otherwise)."""

    fn: str                               # rank | dense_rank | row_number |
    #                                       lag | lead | sum|min|max|avg|count
    args: Tuple[Expr, ...]
    partition_by: Tuple[Expr, ...] = ()
    order_by: Tuple[Tuple[Expr, bool], ...] = ()   # (expr, ascending)
    frame: Optional[Tuple[Optional[int], Optional[int]]] = None

    def children(self):
        return tuple(self.args) + tuple(self.partition_by) \
            + tuple(x for x, _ in self.order_by)


def walk(e: Expr):
    yield e
    for c in e.children():
        yield from walk(c)


def columns_in(e: Expr):
    return {n.name for n in walk(e) if isinstance(n, Column)}


def agg_calls_in(e: Expr):
    return [n for n in walk(e) if isinstance(n, AggCall)]


def transform(e: Expr, fn):
    """Bottom-up rewrite: rebuild each node from transformed children, then
    apply ``fn``. ≈ Catalyst ``transformUp``."""
    if isinstance(e, BinaryOp):
        e2 = BinaryOp(e.op, transform(e.left, fn), transform(e.right, fn))
    elif isinstance(e, Comparison):
        e2 = Comparison(e.op, transform(e.left, fn), transform(e.right, fn))
    elif isinstance(e, And):
        e2 = And(tuple(transform(p, fn) for p in e.parts))
    elif isinstance(e, Or):
        e2 = Or(tuple(transform(p, fn) for p in e.parts))
    elif isinstance(e, Not):
        e2 = Not(transform(e.child, fn))
    elif isinstance(e, IsNull):
        e2 = IsNull(transform(e.child, fn), e.negated)
    elif isinstance(e, InList):
        e2 = InList(transform(e.child, fn), e.values, e.negated)
    elif isinstance(e, Between):
        e2 = Between(transform(e.child, fn), transform(e.low, fn),
                     transform(e.high, fn), e.negated)
    elif isinstance(e, Like):
        e2 = Like(transform(e.child, fn), e.pattern, e.negated)
    elif isinstance(e, Func):
        e2 = Func(e.name, tuple(transform(a, fn) for a in e.args))
    elif isinstance(e, Cast):
        e2 = Cast(transform(e.child, fn), e.to)
    elif isinstance(e, Case):
        e2 = Case(tuple((transform(c, fn), transform(v, fn))
                        for c, v in e.branches),
                  None if e.otherwise is None else transform(e.otherwise, fn))
    elif isinstance(e, AggCall):
        e2 = AggCall(e.fn, None if e.arg is None else transform(e.arg, fn),
                     e.distinct, e.approx, e.fraction)
    elif isinstance(e, WindowCall):
        e2 = WindowCall(e.fn, tuple(transform(a, fn) for a in e.args),
                        tuple(transform(p, fn) for p in e.partition_by),
                        tuple((transform(x, fn), asc)
                              for x, asc in e.order_by),
                        e.frame)
    elif isinstance(e, KeyedLookup):
        e2 = KeyedLookup(transform(e.key, fn), e.table, e.default)
    elif isinstance(e, KeyedLookup2):
        e2 = KeyedLookup2(transform(e.key1, fn), transform(e.key2, fn),
                          e.table, e.default)
    else:
        e2 = e
    return fn(e2)


def to_sql(e: Expr) -> str:
    """Debug/explain rendering."""
    if isinstance(e, Column):
        return e.name
    if isinstance(e, Literal):
        return repr(e.value)
    if isinstance(e, BinaryOp):
        return f"({to_sql(e.left)} {e.op} {to_sql(e.right)})"
    if isinstance(e, Comparison):
        return f"({to_sql(e.left)} {e.op} {to_sql(e.right)})"
    if isinstance(e, And):
        return "(" + " AND ".join(to_sql(p) for p in e.parts) + ")"
    if isinstance(e, Or):
        return "(" + " OR ".join(to_sql(p) for p in e.parts) + ")"
    if isinstance(e, Not):
        return f"(NOT {to_sql(e.child)})"
    if isinstance(e, IsNull):
        return f"({to_sql(e.child)} IS {'NOT ' if e.negated else ''}NULL)"
    if isinstance(e, InList):
        vals = repr(e.values) if isinstance(e.values, FrozenIntSet) \
            else ", ".join(repr(v) for v in e.values)
        return f"({to_sql(e.child)} {'NOT ' if e.negated else ''}IN ({vals}))"
    if isinstance(e, Between):
        return (f"({to_sql(e.child)} {'NOT ' if e.negated else ''}BETWEEN "
                f"{to_sql(e.low)} AND {to_sql(e.high)})")
    if isinstance(e, Like):
        return f"({to_sql(e.child)} {'NOT ' if e.negated else ''}LIKE {e.pattern!r})"
    if isinstance(e, Func):
        return f"{e.name}({', '.join(to_sql(a) for a in e.args)})"
    if isinstance(e, Cast):
        return f"CAST({to_sql(e.child)} AS {e.to})"
    if isinstance(e, Case):
        parts = " ".join(f"WHEN {to_sql(c)} THEN {to_sql(v)}"
                         for c, v in e.branches)
        tail = f" ELSE {to_sql(e.otherwise)}" if e.otherwise is not None else ""
        return f"CASE {parts}{tail} END"
    if isinstance(e, AggCall):
        arg = "*" if e.arg is None else to_sql(e.arg)
        d = "DISTINCT " if e.distinct else ""
        frac = f", {e.fraction!r}" if e.fraction is not None else ""
        return f"{e.fn}({d}{arg}{frac})"
    if isinstance(e, WindowCall):
        arg = ", ".join(to_sql(a) for a in e.args)
        parts = []
        if e.partition_by:
            parts.append("PARTITION BY "
                         + ", ".join(to_sql(p) for p in e.partition_by))
        if e.order_by:
            parts.append("ORDER BY " + ", ".join(
                to_sql(x) + ("" if asc else " DESC")
                for x, asc in e.order_by))
        if e.frame is not None:
            parts.append(f"ROWS {e.frame!r}")
        return f"{e.fn}({arg}) OVER ({' '.join(parts)})"
    if isinstance(e, KeyedLookup):
        return f"lookup[{e.table!r}]({to_sql(e.key)})"
    if isinstance(e, KeyedLookup2):
        return f"lookup[{e.table!r}]({to_sql(e.key1)}, {to_sql(e.key2)})"
    return repr(e)
