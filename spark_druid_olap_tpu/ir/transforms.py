"""Spec-level rewrite rules over generated QuerySpecs.

≈ ``QuerySpecTransforms`` (reference ``druid/query/QuerySpecTransforms.scala``):
a rule executor run on the query spec *after* the planner builds it —
GroupBy -> TimeSeries when there are no dimensions, GroupBy -> TopN for a
single-dim ordered-limit aggregate, add a count aggregation when a group-by
has none (so empty groups can be dropped), merge redundant bound filters.
"""

from __future__ import annotations

from typing import Callable, List, Optional

from spark_druid_olap_tpu.ir import spec as S
from spark_druid_olap_tpu.utils.config import (
    ALLOW_TOPN,
    Config,
    TOPN_THRESHOLD,
)

Rule = Callable[[S.QuerySpec, Config], Optional[S.QuerySpec]]


def groupby_to_timeseries(q: S.QuerySpec, conf: Config):
    """No dimensions -> timeseries (reference :119-142)."""
    if not isinstance(q, S.GroupByQuerySpec):
        return None
    if q.dimensions or q.having is not None or q.limit is not None:
        return None
    return S.TimeseriesQuerySpec(
        datasource=q.datasource, aggregations=q.aggregations,
        post_aggregations=q.post_aggregations, filter=q.filter,
        granularity=q.granularity, intervals=q.intervals, context=q.context)


def groupby_to_topn(q: S.QuerySpec, conf: Config):
    """Single dim + order-by-one-metric-desc + limit -> topN
    (reference :279-332; gated like spark.sparklinedata.druid.allow.topn)."""
    if not isinstance(q, S.GroupByQuerySpec):
        return None
    if not conf.get(ALLOW_TOPN):
        return None
    if (len(q.dimensions) != 1 or q.limit is None or q.limit.limit is None
            or len(q.limit.columns) != 1 or q.having is not None
            or not q.granularity.is_all()):
        return None
    oc = q.limit.columns[0]
    if oc.ascending:
        return None
    agg_names = {a.name for a in q.aggregations} | \
        {p.name for p in q.post_aggregations}
    if oc.name not in agg_names:
        return None
    if q.limit.limit > conf.get(TOPN_THRESHOLD):
        return None
    return S.TopNQuerySpec(
        datasource=q.datasource, dimension=q.dimensions[0], metric=oc.name,
        threshold=q.limit.limit, aggregations=q.aggregations,
        post_aggregations=q.post_aggregations, filter=q.filter,
        granularity=q.granularity, intervals=q.intervals, context=q.context)


def add_count_when_no_aggs(q: S.QuerySpec, conf: Config):
    """GroupBy with zero aggregations (e.g. SELECT DISTINCT dims) gets a
    hidden count (reference :104-117 adds an 'addCountAggregate')."""
    if not isinstance(q, S.GroupByQuerySpec):
        return None
    if q.aggregations:
        return None
    import dataclasses
    return dataclasses.replace(
        q, aggregations=(S.AggregationSpec("count", "__count__"),))


RULES: List[Rule] = [add_count_when_no_aggs, groupby_to_topn,
                     groupby_to_timeseries]


def transform(q: S.QuerySpec, conf: Config) -> S.QuerySpec:
    """Run rules to fixpoint (bounded) — ≈ TransformExecutor batches."""
    for _ in range(4):
        changed = False
        for rule in RULES:
            r = rule(q, conf)
            if r is not None:
                q = r
                changed = True
        if not changed:
            break
    return q
