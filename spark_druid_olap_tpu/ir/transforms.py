"""Spec-level rewrite rules over generated QuerySpecs.

≈ ``QuerySpecTransforms`` (reference ``druid/query/QuerySpecTransforms.scala``):
a rule executor run on the query spec *after* the planner builds it —
GroupBy -> TimeSeries when there are no dimensions, GroupBy -> TopN for a
single-dim ordered-limit aggregate, add a count aggregation when a group-by
has none (so empty groups can be dropped), merge redundant bound filters.
"""

from __future__ import annotations

from typing import Callable, List, Optional

from spark_druid_olap_tpu.ir import spec as S
from spark_druid_olap_tpu.utils.config import (
    ALLOW_TOPN,
    Config,
    TOPN_THRESHOLD,
)

Rule = Callable[[S.QuerySpec, Config], Optional[S.QuerySpec]]


def groupby_to_timeseries(q: S.QuerySpec, conf: Config):
    """No dimensions -> timeseries (reference :119-142)."""
    if not isinstance(q, S.GroupByQuerySpec):
        return None
    if q.dimensions or q.having is not None or q.limit is not None:
        return None
    return S.TimeseriesQuerySpec(
        datasource=q.datasource, aggregations=q.aggregations,
        post_aggregations=q.post_aggregations, filter=q.filter,
        granularity=q.granularity, intervals=q.intervals, context=q.context)


def groupby_to_topn(q: S.QuerySpec, conf: Config):
    """Single dim + order-by-one-metric-desc + limit -> topN
    (reference :279-332; gated like spark.sparklinedata.druid.allow.topn)."""
    if not isinstance(q, S.GroupByQuerySpec):
        return None
    if not conf.get(ALLOW_TOPN):
        return None
    if (len(q.dimensions) != 1 or q.limit is None or q.limit.limit is None
            or len(q.limit.columns) != 1 or q.having is not None
            or not q.granularity.is_all()):
        return None
    oc = q.limit.columns[0]
    if oc.ascending:
        return None
    agg_names = {a.name for a in q.aggregations} | \
        {p.name for p in q.post_aggregations}
    if oc.name not in agg_names:
        return None
    if q.limit.limit > conf.get(TOPN_THRESHOLD):
        return None
    return S.TopNQuerySpec(
        datasource=q.datasource, dimension=q.dimensions[0], metric=oc.name,
        threshold=q.limit.limit, aggregations=q.aggregations,
        post_aggregations=q.post_aggregations, filter=q.filter,
        granularity=q.granularity, intervals=q.intervals, context=q.context)


def groupby_to_search(q: S.QuerySpec, conf: Config):
    """GroupBy over ONE dim whose only row filter is a contains/like
    pattern on that same dim, counting rows -> dictionary-scan Search query
    (reference :225-277). The search tier scans the (small) dictionary
    instead of planning a dense group-by over the full key space."""
    if not isinstance(q, S.GroupByQuerySpec):
        return None
    if (len(q.dimensions) != 1 or q.having is not None
            or q.limit is not None or q.post_aggregations
            or not q.granularity.is_all()):
        return None
    d = q.dimensions[0]
    if d.extraction is not None:
        return None
    a = q.aggregations[0] if len(q.aggregations) == 1 else None
    if a is None or a.kind != "count" or a.filter is not None \
            or a.field is not None or a.expr is not None:
        # a filtered/field count is NOT the row count the search tier returns
        return None
    f = q.filter
    if not (isinstance(f, S.PatternFilter) and f.dimension == d.dimension
            and f.kind in ("contains", "like")):
        return None
    if f.kind == "like":
        inner = f.pattern
        if not (inner.startswith("%") and inner.endswith("%")
                and len(inner) > 2):
            return None
        inner = inner[1:-1]
        if any(ch in inner for ch in "%_"):
            return None
        needle = inner
    else:
        needle = f.pattern
    return S.SearchQuerySpec(
        datasource=q.datasource, dimensions=(d.dimension,), query=needle,
        case_sensitive=True, filter=None, intervals=q.intervals,
        context=q.context, value_output=d.output_name,
        count_output=q.aggregations[0].name)


def add_count_when_no_aggs(q: S.QuerySpec, conf: Config):
    """GroupBy with zero aggregations (e.g. SELECT DISTINCT dims) gets a
    hidden count (reference :104-117 adds an 'addCountAggregate')."""
    if not isinstance(q, S.GroupByQuerySpec):
        return None
    if q.aggregations:
        return None
    import dataclasses
    return dataclasses.replace(
        q, aggregations=(S.AggregationSpec("count", "__count__"),))


def merge_spatial_bounds(filter_spec, ds):
    """Collapse conjunctive numeric BoundFilters on a spatial dim's axis
    columns into one SpatialFilter (reference: the combine-spatial-filters
    transform, QuerySpecTransforms.scala:180-223, and the spatial rewrite in
    ProjectFilterTransfom.scala:289-319). Enables segment bounding-box
    pruning; open sides become +/-inf. Only rewrites when at least one axis
    is bounded."""
    import math
    if filter_spec is None or not getattr(ds, "spatial", None):
        return filter_spec
    if isinstance(filter_spec, S.LogicalFilter) and filter_spec.op == "and":
        conjs = list(filter_spec.fields)
    else:
        conjs = [filter_spec]
    axis_to_dim = {}
    for sname, axes in ds.spatial.items():
        for ax in axes:
            axis_to_dim[ax] = sname
    # per spatial dim: accumulated [lo, hi] per axis
    boxes = {}
    used = []
    rest = []
    for c in conjs:
        if isinstance(c, S.BoundFilter) and c.dimension in axis_to_dim \
                and not c.lower_strict and not c.upper_strict:
            sname = axis_to_dim[c.dimension]
            box = boxes.setdefault(sname, {})
            try:
                lo = -math.inf if c.lower is None else float(c.lower)
                hi = math.inf if c.upper is None else float(c.upper)
            except (TypeError, ValueError):
                rest.append(c)
                continue
            cur = box.get(c.dimension, (-math.inf, math.inf))
            box[c.dimension] = (max(cur[0], lo), min(cur[1], hi))
            used.append(c)
        else:
            rest.append(c)
    if not boxes:
        return filter_spec
    for sname, box in boxes.items():
        axes = ds.spatial[sname]
        rest.append(S.SpatialFilter(
            dimension=sname, axes=axes,
            min_coords=tuple(box.get(ax, (-math.inf, math.inf))[0]
                             for ax in axes),
            max_coords=tuple(box.get(ax, (-math.inf, math.inf))[1]
                             for ax in axes)))
    if len(rest) == 1:
        return rest[0]
    return S.LogicalFilter("and", tuple(rest))


RULES: List[Rule] = [add_count_when_no_aggs, groupby_to_search,
                     groupby_to_topn,
                     groupby_to_timeseries]


def transform(q: S.QuerySpec, conf: Config,
              extra_rules=()) -> S.QuerySpec:
    """Run rules to fixpoint (bounded) — ≈ TransformExecutor batches.
    ``extra_rules`` come from installed extension modules."""
    rules = RULES + list(extra_rules)
    for _ in range(4):
        changed = False
        for rule in rules:
            r = rule(q, conf)
            if r is not None:
                q = r
                changed = True
        if not changed:
            break
    return q
