from spark_druid_olap_tpu.ir import expr as E
from spark_druid_olap_tpu.ir.spec import *  # noqa: F401,F403

__all__ = ["E"]
