"""JSON serde for query specs and expressions.

The IR's wire format — what travels over the serving layer and the ``ON
DATASOURCE ... EXECUTE QUERY '<json>'`` raw-query command (≈ the reference
parsing raw Druid JSON in ``PlanUtil.logicalPlan:49-66``; our JSON dialect
mirrors Druid's query JSON shape where it makes sense: ``queryType``,
``dimensions``, ``aggregations``, ``filter``, ``intervals``)."""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Optional

from spark_druid_olap_tpu.ir import expr as E
from spark_druid_olap_tpu.ir import spec as S

# -- expressions --------------------------------------------------------------

_EXPR_TYPES = {
    "column": E.Column, "literal": E.Literal, "binary": E.BinaryOp,
    "cmp": E.Comparison, "and": E.And, "or": E.Or, "not": E.Not,
    "isnull": E.IsNull, "in": E.InList, "between": E.Between,
    "like": E.Like, "func": E.Func, "cast": E.Cast, "case": E.Case,
    "agg": E.AggCall, "lookup": E.KeyedLookup,
    "lookup2": E.KeyedLookup2,
}
_EXPR_NAMES = {v: k for k, v in _EXPR_TYPES.items()}


def expr_to_dict(e: Optional[E.Expr]):
    if e is None:
        return None
    t = _EXPR_NAMES.get(type(e))
    if t is None:
        raise ValueError(f"unserializable expr {type(e).__name__}")
    if isinstance(e, E.Column):
        return {"t": t, "name": e.name}
    if isinstance(e, E.Literal):
        v = e.value
        import datetime as _dt
        if isinstance(v, (_dt.date, _dt.datetime)):
            return {"t": t, "value": v.isoformat(), "date": True}
        return {"t": t, "value": v}
    if isinstance(e, E.BinaryOp):
        return {"t": t, "op": e.op, "left": expr_to_dict(e.left),
                "right": expr_to_dict(e.right)}
    if isinstance(e, E.Comparison):
        return {"t": t, "op": e.op, "left": expr_to_dict(e.left),
                "right": expr_to_dict(e.right)}
    if isinstance(e, (E.And, E.Or)):
        return {"t": t, "parts": [expr_to_dict(p) for p in e.parts]}
    if isinstance(e, E.Not):
        return {"t": t, "child": expr_to_dict(e.child)}
    if isinstance(e, E.IsNull):
        return {"t": t, "child": expr_to_dict(e.child), "negated": e.negated}
    if isinstance(e, E.InList):
        return {"t": t, "child": expr_to_dict(e.child),
                "values": list(e.values), "negated": e.negated}
    if isinstance(e, E.Between):
        return {"t": t, "child": expr_to_dict(e.child),
                "low": expr_to_dict(e.low), "high": expr_to_dict(e.high),
                "negated": e.negated}
    if isinstance(e, E.Like):
        return {"t": t, "child": expr_to_dict(e.child),
                "pattern": e.pattern, "negated": e.negated}
    if isinstance(e, E.Func):
        return {"t": t, "name": e.name,
                "args": [expr_to_dict(a) for a in e.args]}
    if isinstance(e, E.Cast):
        return {"t": t, "child": expr_to_dict(e.child), "to": e.to}
    if isinstance(e, E.Case):
        return {"t": t,
                "branches": [[expr_to_dict(c), expr_to_dict(v)]
                             for c, v in e.branches],
                "otherwise": expr_to_dict(e.otherwise)}
    if isinstance(e, E.AggCall):
        return {"t": t, "fn": e.fn, "arg": expr_to_dict(e.arg),
                "distinct": e.distinct, "approx": e.approx}
    if isinstance(e, E.KeyedLookup):
        import numpy as np
        return {"t": t, "key": expr_to_dict(e.key),
                "keys": [int(k) for k in e.table.keys],
                "values": [None if np.isnan(v) else float(v)
                           for v in e.table.values],
                "default": e.default}
    if isinstance(e, E.KeyedLookup2):
        import numpy as np
        return {"t": t, "key1": expr_to_dict(e.key1),
                "key2": expr_to_dict(e.key2),
                "keys1": [int(k) for k in e.table.keys1],
                "keys2": [int(k) for k in e.table.keys2],
                "values": [None if np.isnan(v) else float(v)
                           for v in e.table.values],
                "default": e.default}
    raise AssertionError


def expr_from_dict(d) -> Optional[E.Expr]:
    if d is None:
        return None
    t = d["t"]
    if t == "column":
        return E.Column(d["name"])
    if t == "literal":
        if d.get("date"):
            import datetime as _dt
            s = d["value"]
            return E.Literal(_dt.date.fromisoformat(s[:10]) if len(s) <= 10
                             else _dt.datetime.fromisoformat(s))
        return E.Literal(d["value"])
    if t == "binary":
        return E.BinaryOp(d["op"], expr_from_dict(d["left"]),
                          expr_from_dict(d["right"]))
    if t == "cmp":
        return E.Comparison(d["op"], expr_from_dict(d["left"]),
                            expr_from_dict(d["right"]))
    if t == "and":
        return E.And(tuple(expr_from_dict(p) for p in d["parts"]))
    if t == "or":
        return E.Or(tuple(expr_from_dict(p) for p in d["parts"]))
    if t == "not":
        return E.Not(expr_from_dict(d["child"]))
    if t == "isnull":
        return E.IsNull(expr_from_dict(d["child"]), d.get("negated", False))
    if t == "in":
        return E.InList(expr_from_dict(d["child"]), tuple(d["values"]),
                        d.get("negated", False))
    if t == "between":
        return E.Between(expr_from_dict(d["child"]),
                         expr_from_dict(d["low"]), expr_from_dict(d["high"]),
                         d.get("negated", False))
    if t == "like":
        return E.Like(expr_from_dict(d["child"]), d["pattern"],
                      d.get("negated", False))
    if t == "func":
        return E.Func(d["name"], tuple(expr_from_dict(a) for a in d["args"]))
    if t == "cast":
        return E.Cast(expr_from_dict(d["child"]), d["to"])
    if t == "case":
        return E.Case(tuple((expr_from_dict(c), expr_from_dict(v))
                            for c, v in d["branches"]),
                      expr_from_dict(d.get("otherwise")))
    if t == "agg":
        return E.AggCall(d["fn"], expr_from_dict(d.get("arg")),
                         d.get("distinct", False), d.get("approx", False))
    if t == "lookup":
        import numpy as np
        vals = np.array([np.nan if v is None else v for v in d["values"]],
                        dtype=np.float64)
        return E.KeyedLookup(
            expr_from_dict(d["key"]),
            E.FrozenKeyedTable(np.asarray(d["keys"], dtype=np.int64),
                               vals),
            d.get("default"))
    if t == "lookup2":
        import numpy as np
        vals = np.array([np.nan if v is None else v for v in d["values"]],
                        dtype=np.float64)
        return E.KeyedLookup2(
            expr_from_dict(d["key1"]), expr_from_dict(d["key2"]),
            E.FrozenKeyedTable2(np.asarray(d["keys1"], dtype=np.int64),
                                np.asarray(d["keys2"], dtype=np.int64),
                                vals),
            d.get("default"))
    raise ValueError(f"unknown expr type {t!r}")


# -- filters ------------------------------------------------------------------

def filter_to_dict(f: Optional[S.FilterSpec]):
    if f is None:
        return None
    if isinstance(f, S.SelectorFilter):
        return {"type": "selector", "dimension": f.dimension,
                "value": f.value}
    if isinstance(f, S.BoundFilter):
        return {"type": "bound", "dimension": f.dimension,
                "lower": _jsonable(f.lower), "upper": _jsonable(f.upper),
                "lowerStrict": f.lower_strict, "upperStrict": f.upper_strict,
                "numeric": f.numeric}
    if isinstance(f, S.InFilter):
        if isinstance(f.values, E.FrozenIntSet):
            return {"type": "in", "dimension": f.dimension,
                    "values": f.values.array.tolist(), "intset": True}
        return {"type": "in", "dimension": f.dimension,
                "values": [_jsonable(v) for v in f.values]}
    if isinstance(f, S.PatternFilter):
        return {"type": f.kind, "dimension": f.dimension,
                "pattern": f.pattern}
    if isinstance(f, S.NullFilter):
        return {"type": "null", "dimension": f.dimension,
                "negated": f.negated}
    if isinstance(f, S.LogicalFilter):
        return {"type": f.op,
                "fields": [filter_to_dict(x) for x in f.fields]}
    if isinstance(f, S.ExprFilter):
        return {"type": "expression", "expr": expr_to_dict(f.expr)}
    if isinstance(f, S.SpatialFilter):
        # Druid-shaped (SpatialFilterSpec/RectangularBound) plus our axes
        return {"type": "spatial", "dimension": f.dimension,
                "axes": list(f.axes),
                "bound": {"type": "rectangular",
                          "minCoords": [_jsonable(v) for v in f.min_coords],
                          "maxCoords": [_jsonable(v) for v in f.max_coords]}}
    raise ValueError(type(f).__name__)


def _jsonable(v):
    import datetime as _dt
    import numpy as np
    if isinstance(v, (_dt.date, _dt.datetime)):
        return v.isoformat()
    if isinstance(v, np.generic):
        return v.item()
    return v


def filter_from_dict(d) -> Optional[S.FilterSpec]:
    if d is None:
        return None
    t = d["type"]
    if t == "selector":
        return S.SelectorFilter(d["dimension"], d.get("value"))
    if t == "bound":
        return S.BoundFilter(d["dimension"], d.get("lower"), d.get("upper"),
                             d.get("lowerStrict", False),
                             d.get("upperStrict", False),
                             d.get("numeric", False))
    if t == "in":
        if d.get("intset"):
            return S.InFilter(d["dimension"], E.FrozenIntSet(d["values"]))
        return S.InFilter(d["dimension"], tuple(d["values"]))
    if t in ("like", "regex", "contains"):
        return S.PatternFilter(d["dimension"], t, d["pattern"])
    if t == "null":
        return S.NullFilter(d["dimension"], d.get("negated", False))
    if t in ("and", "or", "not"):
        return S.LogicalFilter(
            t, tuple(filter_from_dict(x) for x in d["fields"]))
    if t == "expression":
        return S.ExprFilter(expr_from_dict(d["expr"]))
    if t == "spatial":
        b = d["bound"]
        return S.SpatialFilter(
            d["dimension"], tuple(d.get("axes", ())),
            tuple(float(v) for v in b["minCoords"]),
            tuple(float(v) for v in b["maxCoords"]))
    raise ValueError(f"unknown filter type {t!r}")


# -- dimensions / aggregations ------------------------------------------------

def dim_to_dict(d: S.DimensionSpec):
    out = {"dimension": d.dimension, "outputName": d.output_name}
    if isinstance(d.extraction, S.TimeExtraction):
        out["extractionFn"] = {"type": "time", "field": d.extraction.field}
    elif isinstance(d.extraction, S.ExprExtraction):
        out["extractionFn"] = {"type": "expression",
                               "expr": expr_to_dict(d.extraction.expr),
                               "cardinality": d.extraction.cardinality}
    elif isinstance(d.extraction, S.LookupExtraction):
        # Druid-shaped map lookup extraction fn
        out["extractionFn"] = {
            "type": "lookup",
            "lookup": {"type": "map", "map": dict(d.extraction.lookup)},
            "retainMissingValue": d.extraction.retain_missing,
            "replaceMissingValueWith": d.extraction.replace_missing_with}
    elif isinstance(d.extraction, S.RegexExtraction):
        out["extractionFn"] = {
            "type": "regex", "expr": d.extraction.pattern,
            "index": d.extraction.index,
            "replaceMissingValue": d.extraction.replace_missing,
            "replaceMissingValueWith": d.extraction.replace_missing_with}
    return out


def dim_from_dict(d) -> S.DimensionSpec:
    ex = None
    fn = d.get("extractionFn")
    if fn is not None:
        if fn["type"] == "time":
            ex = S.TimeExtraction(fn["field"])
        elif fn["type"] == "lookup":
            ex = S.LookupExtraction(
                tuple(sorted(fn["lookup"]["map"].items())),
                fn.get("retainMissingValue", False),
                fn.get("replaceMissingValueWith"))
        elif fn["type"] == "regex":
            ex = S.RegexExtraction(
                fn["expr"], fn.get("index", 1),
                fn.get("replaceMissingValue", False),
                fn.get("replaceMissingValueWith"))
        else:
            ex = S.ExprExtraction(expr_from_dict(fn["expr"]),
                                  fn.get("cardinality"))
    return S.DimensionSpec(d["dimension"], d.get("outputName",
                                                 d["dimension"]), ex)


def agg_to_dict(a: S.AggregationSpec):
    out = {"type": a.kind, "name": a.name}
    if a.field is not None:
        out["fieldName"] = a.field
    if a.expr is not None:
        out["expr"] = expr_to_dict(a.expr)
    if a.filter is not None:
        out["filter"] = filter_to_dict(a.filter)
    if a.fraction is not None:
        out["fraction"] = a.fraction
    return out


def agg_from_dict(d) -> S.AggregationSpec:
    return S.AggregationSpec(d["type"], d["name"], d.get("fieldName"),
                             expr_from_dict(d.get("expr")),
                             filter_from_dict(d.get("filter")),
                             d.get("fraction"))


# -- query specs --------------------------------------------------------------

def query_to_dict(q: S.QuerySpec) -> dict:
    base = {"dataSource": q.datasource,
            "intervals": [list(i) for i in q.intervals]
            if getattr(q, "intervals", None) else None}
    ctxq = getattr(q, "context", None)
    if ctxq is not None and (ctxq.query_id is not None
                             or ctxq.timeout_millis is not None
                             or ctxq.prefer_sharded is not None
                             or ctxq.lane is not None
                             or ctxq.tenant is not None
                             or ctxq.priority is not None):
        # ≈ Druid's query "context" (QuerySpecContext :558-571; lane ≈
        # Druid's context "lane"/"priority" laning keys)
        base["context"] = {"queryId": ctxq.query_id,
                           "timeout": ctxq.timeout_millis,
                           "preferSharded": ctxq.prefer_sharded}
        if ctxq.lane is not None:
            base["context"]["lane"] = ctxq.lane
        if ctxq.tenant is not None:
            base["context"]["tenant"] = ctxq.tenant
        if ctxq.priority is not None:
            base["context"]["priority"] = ctxq.priority
    if isinstance(q, S.GroupByQuerySpec):
        base.update({
            "queryType": "groupBy",
            "dimensions": [dim_to_dict(d) for d in q.dimensions],
            "aggregations": [agg_to_dict(a) for a in q.aggregations],
            "postAggregations": [{"name": p.name,
                                  "expr": expr_to_dict(p.expr)}
                                 for p in q.post_aggregations],
            "filter": filter_to_dict(q.filter),
            "having": expr_to_dict(q.having.expr) if q.having else None,
            "limitSpec": {
                "columns": [{"dimension": c.name, "ascending": c.ascending}
                            for c in q.limit.columns],
                "limit": q.limit.limit} if q.limit else None,
            "granularity": {"type": q.granularity.kind,
                            "duration": q.granularity.duration_millis},
        })
        return base
    if isinstance(q, S.TimeseriesQuerySpec):
        base.update({
            "queryType": "timeseries",
            "aggregations": [agg_to_dict(a) for a in q.aggregations],
            "postAggregations": [{"name": p.name,
                                  "expr": expr_to_dict(p.expr)}
                                 for p in q.post_aggregations],
            "filter": filter_to_dict(q.filter),
            "granularity": {"type": q.granularity.kind,
                            "duration": q.granularity.duration_millis},
        })
        return base
    if isinstance(q, S.TopNQuerySpec):
        base.update({
            "queryType": "topN",
            "dimension": dim_to_dict(q.dimension),
            "metric": q.metric, "threshold": q.threshold,
            "aggregations": [agg_to_dict(a) for a in q.aggregations],
            "postAggregations": [{"name": p.name,
                                  "expr": expr_to_dict(p.expr)}
                                 for p in q.post_aggregations],
            "filter": filter_to_dict(q.filter),
        })
        return base
    if isinstance(q, S.SelectQuerySpec):
        base.update({
            "queryType": "select", "columns": list(q.columns),
            "filter": filter_to_dict(q.filter),
            "pagingSpec": {"pageSize": q.page_size, "offset": q.page_offset},
            "descending": q.descending,
        })
        return base
    if isinstance(q, S.SearchQuerySpec):
        base.update({
            "queryType": "search", "searchDimensions": list(q.dimensions),
            "query": q.query, "caseSensitive": q.case_sensitive,
            "filter": filter_to_dict(q.filter), "limit": q.limit,
        })
        if q.value_output is not None:
            base["valueOutput"] = q.value_output
            base["countOutput"] = q.count_output
        return base
    raise ValueError(type(q).__name__)


def query_to_json(q: S.QuerySpec) -> str:
    return json.dumps(query_to_dict(q))


def _gran_from(d) -> S.Granularity:
    if d is None:
        return S.GRAN_ALL
    if isinstance(d, str):
        return S.Granularity(d)
    return S.Granularity(d.get("type", "all"), d.get("duration"))


def query_from_dict(d: dict, default_ds: Optional[str] = None) -> S.QuerySpec:
    qt = d.get("queryType", "groupBy")
    ds = d.get("dataSource") or default_ds
    if ds is None:
        raise ValueError("query needs a dataSource")
    intervals = tuple(tuple(i) for i in d["intervals"]) \
        if d.get("intervals") else None
    posts = tuple(S.PostAggregationSpec(p["name"], expr_from_dict(p["expr"]))
                  for p in d.get("postAggregations", []) or [])
    aggs = tuple(agg_from_dict(a) for a in d.get("aggregations", []) or [])
    filt = filter_from_dict(d.get("filter"))
    cd = d.get("context") or {}
    qctx = S.QueryContext(cd.get("queryId"), cd.get("timeout"),
                          cd.get("preferSharded"), cd.get("lane"),
                          cd.get("tenant"), cd.get("priority")) \
        if cd else S.QueryContext()
    if qt == "groupBy":
        limit = None
        if d.get("limitSpec"):
            ls = d["limitSpec"]
            limit = S.LimitSpec(
                tuple(S.OrderByColumn(c["dimension"],
                                      c.get("ascending", True))
                      for c in ls.get("columns", [])), ls.get("limit"))
        having = None
        if d.get("having") is not None:
            having = S.HavingSpec(expr_from_dict(d["having"]))
        return S.GroupByQuerySpec(
            ds, tuple(dim_from_dict(x) for x in d.get("dimensions", [])),
            aggs, posts, filt, having, limit, _gran_from(d.get("granularity")),
            intervals, qctx)
    if qt == "timeseries":
        return S.TimeseriesQuerySpec(ds, aggs, posts, filt,
                                     _gran_from(d.get("granularity")),
                                     intervals, qctx)
    if qt == "topN":
        return S.TopNQuerySpec(ds, dim_from_dict(d["dimension"]),
                               d["metric"], d["threshold"], aggs, posts,
                               filt, _gran_from(d.get("granularity")),
                               intervals, qctx)
    if qt == "select":
        ps = d.get("pagingSpec", {})
        return S.SelectQuerySpec(ds, tuple(d.get("columns", [])), filt,
                                 intervals, ps.get("pageSize", 10000),
                                 ps.get("offset", 0),
                                 d.get("descending", False), qctx)
    if qt == "search":
        return S.SearchQuerySpec(ds, tuple(d.get("searchDimensions", [])),
                                 d.get("query", ""),
                                 d.get("caseSensitive", False), filt,
                                 d.get("limit"), intervals, qctx,
                                 d.get("valueOutput"), d.get("countOutput"))
    raise ValueError(f"unknown queryType {qt!r}")


def query_from_json(s: str, default_ds: Optional[str] = None) -> S.QuerySpec:
    return query_from_dict(json.loads(s), default_ds)
