"""Time-interval accumulation for pushdown.

≈ ``QueryIntervals.scala``: conjunctive time predicates intersect into a
single [lo, hi) milli-interval; a contradiction yields the empty interval.
Disjunctive time predicates are NOT turned into intervals (they stay filters),
matching the reference's conjunct-only extraction
(``IntervalConditionExtractor``).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

from spark_druid_olap_tpu.ops.time_ops import date_literal_to_millis

MIN_MS = -(1 << 62)
MAX_MS = 1 << 62


@dataclasses.dataclass
class IntervalAccumulator:
    lo: int = MIN_MS
    hi: int = MAX_MS
    tz: str = "UTC"

    def _ms(self, value) -> int:
        # naive literals are session-local wall clock, zoned ones are
        # absolute instants (one policy: time_ops.literal_to_utc_millis)
        from spark_druid_olap_tpu.ops.time_ops import literal_to_utc_millis
        return literal_to_utc_millis(value, self.tz)

    def ge(self, value):            # t >= v
        self.lo = max(self.lo, self._ms(value))

    def gt(self, value):            # t > v  (ms precision)
        self.lo = max(self.lo, self._ms(value) + 1)

    def le(self, value):            # t <= v
        self.hi = min(self.hi, self._ms(value) + 1)

    def lt(self, value):            # t < v
        self.hi = min(self.hi, self._ms(value))

    def eq(self, value):
        ms = self._ms(value)
        self.lo = max(self.lo, ms)
        self.hi = min(self.hi, ms + 1)

    @property
    def empty(self) -> bool:
        return self.lo >= self.hi

    def constrained(self) -> bool:
        return self.lo != MIN_MS or self.hi != MAX_MS

    def to_intervals(self) -> Optional[Tuple[Tuple[int, int], ...]]:
        if not self.constrained():
            return None
        return ((self.lo, self.hi),)
