"""Engine query IR — the ``DruidQuerySpec`` equivalent.

The reference models Druid's JSON query language as a sealed case-class
hierarchy (``DruidQuerySpec.scala``, 1126 LoC: extraction fns :31-103,
DimensionSpec :108-138, FilterSpec :152-281, AggregationSpec :283-377,
PostAggregationSpec :379-430, limit/having :437-507, QuerySpec :573-1098).
Here the same *capability surface* is a typed IR that lowers onto in-tree
XLA/Pallas kernels instead of serializing to JSON for an external cluster.

The IR is intentionally serializable (dataclasses of plain values + ``Expr``
trees) so it can travel over the serving layer (``ON DATASOURCE ... EXECUTE
QUERY <json>`` equivalent) and be rewritten by ``ir/transforms.py``
(≈ ``QuerySpecTransforms``).
"""

from __future__ import annotations

import dataclasses
from typing import Any, List, Optional, Sequence, Tuple

from spark_druid_olap_tpu.ir import expr as E

Interval = Tuple[int, int]  # [lo, hi) epoch millis, UTC


# =============================================================================
# Filters (reference: FilterSpec hierarchy, DruidQuerySpec.scala:152-281)
# =============================================================================

class FilterSpec:
    pass


@dataclasses.dataclass(frozen=True)
class SelectorFilter(FilterSpec):
    """dimension == value (reference: SelectorFilterSpec)."""
    dimension: str
    value: Optional[str]  # None selects nulls


@dataclasses.dataclass(frozen=True)
class BoundFilter(FilterSpec):
    """Range filter on a dim (lexicographic via sorted dictionary) or metric
    (numeric). Reference: BoundFilterSpec :214-253."""
    dimension: str
    lower: Optional[Any] = None
    upper: Optional[Any] = None
    lower_strict: bool = False
    upper_strict: bool = False
    numeric: bool = False


@dataclasses.dataclass(frozen=True)
class InFilter(FilterSpec):
    """dimension IN (values) (reference: ExtractionFnFilterSpec via InSet /
    Druid `in` filter)."""
    dimension: str
    values: Tuple[str, ...]


@dataclasses.dataclass(frozen=True)
class PatternFilter(FilterSpec):
    """LIKE / regex / contains on a dim. Evaluated over the (small, sorted)
    dictionary on host -> constant code-mask on device; replaces Druid's
    regex/search/javascript filters (reference :176-213)."""
    dimension: str
    kind: str      # 'like' | 'regex' | 'contains'
    pattern: str


@dataclasses.dataclass(frozen=True)
class NullFilter(FilterSpec):
    dimension: str
    negated: bool = False  # True => IS NOT NULL


@dataclasses.dataclass(frozen=True)
class LogicalFilter(FilterSpec):
    """and/or/not (reference: LogicalFilterSpec / NotFilterSpec :254-269)."""
    op: str  # 'and' | 'or' | 'not'
    fields: Tuple[FilterSpec, ...]


@dataclasses.dataclass(frozen=True)
class ExprFilter(FilterSpec):
    """Arbitrary boolean expression compiled to XLA — the in-tree replacement
    for the JavaScript filter fallback (reference:
    JavascriptFilterSpec + JSCodeGenerator)."""
    expr: E.Expr


@dataclasses.dataclass(frozen=True)
class SpatialFilter(FilterSpec):
    """Rectangular-bound filter on a declared spatial dimension (reference:
    ``SpatialFilterSpec``/``RectangularBound`` DruidQuerySpec.scala:255-281).

    ``axes`` are the resolved numeric axis columns (declared at ingest via
    ``spatial_dims``); coordinates are inclusive on both bounds. Open sides
    use +/-inf. Beyond the row mask, the executor prunes whole segments
    whose per-axis bounding box misses the rectangle — the scan-era analog
    of Druid's R-tree index."""
    dimension: str
    axes: Tuple[str, ...]
    min_coords: Tuple[float, ...]
    max_coords: Tuple[float, ...]


TrueFilter = LogicalFilter("and", ())


# =============================================================================
# Dimension / extraction specs (reference: DruidQuerySpec.scala:31-138)
# =============================================================================

class ExtractionSpec:
    pass


@dataclasses.dataclass(frozen=True)
class TimeExtraction(ExtractionSpec):
    """Extract a calendar field or truncate to a grain, from the time column
    or a date-typed dim (reference: TimeFormatExtractionFunctionSpec)."""
    field: str  # 'year'|'quarter'|'month'|'week'|'day'|'dow'|'doy'|'hour'|'minute'|'trunc_<grain>'


@dataclasses.dataclass(frozen=True)
class ExprExtraction(ExtractionSpec):
    """Computed dimension: arbitrary expression over source columns, compiled
    to XLA (reference: JavaScriptExtractionFunctionSpec via JSCodeGenerator)."""
    expr: E.Expr
    cardinality: Optional[int] = None  # planner's bound on distinct outputs


@dataclasses.dataclass(frozen=True)
class LookupExtraction(ExtractionSpec):
    """Map-based dimension value translation (reference:
    LookUpExtractionFunctionSpec / InExtractionFnSpec,
    DruidQuerySpec.scala:66-103). Missing keys keep the original value when
    ``retain_missing``, become ``replace_missing_with`` when set, else null.
    Evaluated as a host transform of the (small) dictionary, then a constant
    code-remap LUT gather on device."""
    lookup: Tuple[Tuple[str, Optional[str]], ...]   # (from, to) pairs
    retain_missing: bool = False
    replace_missing_with: Optional[str] = None


@dataclasses.dataclass(frozen=True)
class RegexExtraction(ExtractionSpec):
    """Regex capture-group extraction (reference:
    RegexExtractionFunctionSpec, DruidQuerySpec.scala:56-58). Non-matching
    values pass through unchanged unless ``replace_missing``, in which case
    they become ``replace_missing_with`` (null by default)."""
    pattern: str
    index: int = 1
    replace_missing: bool = False
    replace_missing_with: Optional[str] = None


@dataclasses.dataclass(frozen=True)
class DimensionSpec:
    """One GROUP BY output dimension (reference: DefaultDimensionSpec /
    ExtractionDimensionSpec :108-138)."""
    dimension: str                      # source column (or '__time')
    output_name: str
    extraction: Optional[ExtractionSpec] = None


# =============================================================================
# Aggregations (reference: AggregationSpec :283-377; post-aggs :379-430)
# =============================================================================

@dataclasses.dataclass(frozen=True)
class AggregationSpec:
    """kind: count | longsum | doublesum | longmin | longmax | doublemin |
    doublemax | cardinality (HLL approximate count-distinct, reference
    CardinalityAggregationSpec :340-360 / HyperUniqueAggregationSpec).

    ``field`` names a source column; ``expr`` (exclusive with field) is a
    computed input compiled to XLA (reference: JavascriptAggregationSpec via
    JSAggGenerator). ``filter`` makes it a filtered aggregation
    (reference: FilteredAggregationSpec :362-377). ``fraction`` is the
    quantile for ``kind == "quantile"`` (percentile_approx), carried on
    the spec so the broker can finalize merged KLL registers with the
    same fraction the engine would."""
    kind: str
    name: str
    field: Optional[str] = None
    expr: Optional[E.Expr] = None
    filter: Optional[FilterSpec] = None
    fraction: Optional[float] = None


@dataclasses.dataclass(frozen=True)
class PostAggregationSpec:
    """Arithmetic over aggregation outputs, evaluated in the merge epilogue
    (reference: ArithmeticPostAggregationSpec :379-430). ``expr`` refers to
    aggregation names as columns."""
    name: str
    expr: E.Expr


# =============================================================================
# Limit / having / granularity (reference :140-150, :437-507)
# =============================================================================

@dataclasses.dataclass(frozen=True)
class OrderByColumn:
    name: str
    ascending: bool = True


@dataclasses.dataclass(frozen=True)
class LimitSpec:
    columns: Tuple[OrderByColumn, ...]
    limit: Optional[int] = None


@dataclasses.dataclass(frozen=True)
class HavingSpec:
    """Post-aggregation predicate; expr over agg/dim output names (reference:
    HavingSpec json tree)."""
    expr: E.Expr


@dataclasses.dataclass(frozen=True)
class Granularity:
    """'all' | 'none' (row time) | calendar grains | duration millis
    (reference: DruidQueryGranularity.scala)."""
    kind: str = "all"
    duration_millis: Optional[int] = None

    def is_all(self) -> bool:
        return self.kind == "all"


GRAN_ALL = Granularity("all")


# =============================================================================
# Query specs (reference: sealed QuerySpec, DruidQuerySpec.scala:573-1098)
# =============================================================================

@dataclasses.dataclass(frozen=True)
class QueryContext:
    """Per-query execution knobs (reference: QuerySpecContext :558-571)."""
    query_id: Optional[str] = None
    timeout_millis: Optional[int] = None
    prefer_sharded: Optional[bool] = None  # force mesh execution on/off
    # workload management (wlm/): admission lane, quota tenant, queue
    # priority (higher first). None = classified by the WorkloadManager.
    lane: Optional[str] = None
    tenant: Optional[str] = None
    priority: Optional[int] = None


class QuerySpec:
    pass


@dataclasses.dataclass(frozen=True)
class GroupByQuerySpec(QuerySpec):
    datasource: str
    dimensions: Tuple[DimensionSpec, ...]
    aggregations: Tuple[AggregationSpec, ...]
    post_aggregations: Tuple[PostAggregationSpec, ...] = ()
    filter: Optional[FilterSpec] = None
    having: Optional[HavingSpec] = None
    limit: Optional[LimitSpec] = None
    granularity: Granularity = GRAN_ALL
    intervals: Optional[Tuple[Interval, ...]] = None
    context: QueryContext = QueryContext()


@dataclasses.dataclass(frozen=True)
class TimeseriesQuerySpec(QuerySpec):
    """GroupBy with no dimensions — pure (time-bucketed) aggregate
    (reference: TimeSeriesQuerySpec :709-744)."""
    datasource: str
    aggregations: Tuple[AggregationSpec, ...]
    post_aggregations: Tuple[PostAggregationSpec, ...] = ()
    filter: Optional[FilterSpec] = None
    granularity: Granularity = GRAN_ALL
    intervals: Optional[Tuple[Interval, ...]] = None
    context: QueryContext = QueryContext()


@dataclasses.dataclass(frozen=True)
class TopNQuerySpec(QuerySpec):
    """Single-dim ordered-limit aggregate; per-shard partial top-K + merge,
    approximate like Druid's topN engine (reference: TopNQuerySpec
    :767-822)."""
    datasource: str
    dimension: DimensionSpec
    metric: str                      # aggregation name ordered by (desc)
    threshold: int
    aggregations: Tuple[AggregationSpec, ...]
    post_aggregations: Tuple[PostAggregationSpec, ...] = ()
    filter: Optional[FilterSpec] = None
    granularity: Granularity = GRAN_ALL
    intervals: Optional[Tuple[Interval, ...]] = None
    context: QueryContext = QueryContext()


@dataclasses.dataclass(frozen=True)
class SelectQuerySpec(QuerySpec):
    """Raw-row paged scan (non-aggregate pushdown; reference: SelectSpec /
    PagingSpec :977-1098). ``page_offset`` is the resume cursor — the
    checkpoint/resume analog of Druid paging identifiers."""
    datasource: str
    columns: Tuple[str, ...]
    filter: Optional[FilterSpec] = None
    intervals: Optional[Tuple[Interval, ...]] = None
    page_size: int = 10000
    page_offset: int = 0
    descending: bool = False
    context: QueryContext = QueryContext()


@dataclasses.dataclass(frozen=True)
class SearchQuerySpec(QuerySpec):
    """Dimension-value search: which dictionary values (optionally restricted
    by a row filter) contain the query string (reference: SearchQuerySpec
    :870-975)."""
    datasource: str
    dimensions: Tuple[str, ...]
    query: str
    case_sensitive: bool = False
    filter: Optional[FilterSpec] = None
    limit: Optional[int] = None
    intervals: Optional[Tuple[Interval, ...]] = None
    context: QueryContext = QueryContext()
    # set when rewritten FROM a group-by (QuerySpecTransforms
    # GroupBy->Search, reference :225-277): result columns become
    # [value_output, count_output] instead of [dimension, value, count]
    value_output: Optional[str] = None
    count_output: Optional[str] = None


def topn_limit(q: "TopNQuerySpec") -> LimitSpec:
    """The ORDER BY metric DESC LIMIT threshold epilogue a TopN implies.
    One definition shared by the engine (parallel/executor.py) and the
    broker's post-merge epilogue (cluster/broker.py), so the broker's
    re-sort of merged TopN partials can never drift from the engine's
    own order/limit epilogue."""
    return LimitSpec((OrderByColumn(q.metric, ascending=False),),
                     q.threshold)


def filter_and(parts: Sequence[Optional[FilterSpec]]) -> Optional[FilterSpec]:
    fs = tuple(p for p in parts if p is not None)
    if not fs:
        return None
    if len(fs) == 1:
        return fs[0]
    return LogicalFilter("and", fs)


def query_aggregations(q: QuerySpec) -> Tuple[AggregationSpec, ...]:
    return getattr(q, "aggregations", ())


def query_dimensions(q: QuerySpec) -> Tuple[DimensionSpec, ...]:
    if isinstance(q, GroupByQuerySpec):
        return q.dimensions
    if isinstance(q, TopNQuerySpec):
        return (q.dimension,)
    return ()
