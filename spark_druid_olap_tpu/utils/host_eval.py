"""Host-side (numpy) evaluator for ``ir.expr`` trees.

Three jobs, mirroring three reference facilities:

1. evaluate post-aggregation arithmetic over merged agg columns
   (≈ ``ArithmeticPostAggregationSpec`` evaluated inside Druid);
2. evaluate HAVING predicates and residual (unpushable) filters over small
   host-side result sets (≈ the FilterExec Spark leaves above the Druid scan,
   ``DruidStrategy.scala:244-270``);
3. evaluate dimension-expression transforms over the *dictionary domain*
   (code -> value) at plan time — the host half of the dictionary-functional
   string strategy.

Operates elementwise over numpy arrays or python scalars; string columns are
object arrays (dictionaries are small, python-loop cost is irrelevant).
"""

from __future__ import annotations

import datetime as _dt
import math
import re

import numpy as np
import pandas as pd

from spark_druid_olap_tpu.ir import expr as E
from spark_druid_olap_tpu.ops.time_ops import (
    date_literal_to_days,
    days_from_civil,
)


class HostEvalError(Exception):
    pass


def _is_str_like(v):
    if isinstance(v, str):
        return True
    return isinstance(v, np.ndarray) and v.dtype == object


def _map1(v, fn):
    if isinstance(v, np.ndarray) and v.dtype == object:
        return np.array([fn(x) for x in v], dtype=object)
    return fn(v)


import contextvars

# session timezone for host-side time bucketing/extraction (set by the SQL
# session around each statement; contextvars are per-thread, so concurrent
# server sessions don't interfere)
SESSION_TZ = contextvars.ContextVar("sdot_session_tz", default="UTC")


def _to_days(v):
    """Coerce scalar-or-array date-ish value to int days. datetime64
    INSTANTS shift into the session timezone's wall-clock day; calendar
    dates and date literals never shift."""
    if isinstance(v, np.ndarray):
        if np.issubdtype(v.dtype, np.datetime64):
            tz = SESSION_TZ.get()
            from spark_druid_olap_tpu.ops import timezone as TZ
            if not TZ.is_utc(tz):
                ms = v.astype("datetime64[ms]").astype(np.int64)
                nat = np.isnat(v)
                if nat.any():
                    # NaT is int64-min; shifting it would demand an
                    # astronomically-sized offset LUT
                    ms = ms.copy()
                    ms[~nat] = TZ.shift_millis_np(ms[~nat], tz)
                else:
                    ms = TZ.shift_millis_np(ms, tz)
                return np.floor_divide(ms, 86_400_000)
            return v.astype("datetime64[D]").astype(np.int64)
        if v.dtype == object:
            return np.array([date_literal_to_days(x) for x in v],
                            dtype=np.int64)
        return v.astype(np.int64)
    return date_literal_to_days(v)


def _civil(days):
    days = np.asarray(days)
    dates = days.astype("datetime64[D]")
    y = dates.astype("datetime64[Y]").astype(np.int64) + 1970
    m = (dates.astype("datetime64[M]").astype(np.int64) % 12) + 1
    d = (dates - dates.astype("datetime64[M]")).astype(np.int64) + 1
    return y, m, d


class Precomputed(E.Expr):
    """An already-computed value injected into an expression tree (used by
    the host executor for row-wise subquery results)."""

    def __init__(self, arr):
        self.arr = arr


def _compare(op: str, a, b):
    """Two-valued comparison over already-evaluated operands (shared by
    eval_expr and the 3VL predicate walker, which evaluates operands once
    for both the result and the null masks)."""
    a, b = _cmp_promote(a, b)
    ops = {"=": "eq", "!=": "ne", "<": "lt", "<=": "le", ">": "gt",
           ">=": "ge"}
    import operator
    return getattr(operator, ops[op])(a, b)


def eval_expr(e: E.Expr, env: dict):
    """Evaluate ``e``; ``env`` maps column name -> scalar or numpy array."""
    if isinstance(e, Precomputed):
        return e.arr
    if isinstance(e, E.Column):
        if e.name not in env:
            raise HostEvalError(f"unbound column {e.name!r}")
        return env[e.name]
    if isinstance(e, E.Literal):
        return e.value
    if isinstance(e, E.BinaryOp):
        a = eval_expr(e.left, env)
        b = eval_expr(e.right, env)
        a, b = _date_promote(a, b, e.op)
        if e.op == "+":
            return a + b
        if e.op == "-":
            return a - b
        if e.op == "*":
            return a * b
        if e.op == "/":
            return np.divide(a, b)
        if e.op == "%":
            return np.mod(a, b)
        raise HostEvalError(e.op)
    if isinstance(e, E.Comparison):
        return _compare(e.op, eval_expr(e.left, env),
                        eval_expr(e.right, env))
    if isinstance(e, E.And):
        out = True
        for p in e.parts:
            out = np.logical_and(out, eval_expr(p, env))
        return out
    if isinstance(e, E.Or):
        out = False
        for p in e.parts:
            out = np.logical_or(out, eval_expr(p, env))
        return out
    if isinstance(e, E.Not):
        return np.logical_not(eval_expr(e.child, env))
    if isinstance(e, E.IsNull):
        v = eval_expr(e.child, env)
        isnull = _map_null(v)
        return np.logical_not(isnull) if e.negated else isnull
    if isinstance(e, E.InList):
        v = eval_expr(e.child, env)
        if isinstance(e.values, E.FrozenIntSet):
            arr = np.asarray(v)
            if arr.dtype == object or arr.dtype.kind == "f":
                arr = pd.to_numeric(pd.Series(arr),
                                    errors="coerce").to_numpy()
                # fractional probes match no integer set member
                ok = ~np.isnan(arr) & (arr == np.floor(arr))
                vi = np.where(ok, arr, 0).astype(np.int64)
            else:
                ok = None
                vi = arr.astype(np.int64)
            idx = np.clip(np.searchsorted(e.values.array, vi), 0,
                          max(len(e.values.array) - 1, 0))
            out = (len(e.values.array) > 0) \
                & (e.values.array[idx] == vi) if len(e.values.array) \
                else np.zeros(len(vi), dtype=bool)
            if ok is not None:
                out = out & ok
        elif _is_str_like(v):
            vals = set(e.values)
            out = _map1(v, lambda x: x in vals)
        else:
            out = np.isin(v, [x for x in e.values])
        return np.logical_not(out) if e.negated else out
    if isinstance(e, E.Between):
        v = eval_expr(e.child, env)
        lo = eval_expr(e.low, env)
        hi = eval_expr(e.high, env)
        v1, lo = _cmp_promote(v, lo)
        v2, hi = _cmp_promote(v, hi)
        out = np.logical_and(v1 >= lo, v2 <= hi)
        return np.logical_not(out) if e.negated else out
    if isinstance(e, E.Like):
        v = eval_expr(e.child, env)
        from spark_druid_olap_tpu.ops.expr_compile import like_to_regex
        rx = re.compile(like_to_regex(e.pattern))
        # NULLs (None/NaN in object arrays) match nothing under either
        # polarity here; eval_pred3's Like branch adds the UNKNOWN mask
        out = _map1(v, lambda s: bool(rx.match(s))
                    if isinstance(s, str) else False)
        if isinstance(out, np.ndarray):
            out = out.astype(bool)
        return np.logical_not(out) if e.negated else out
    if isinstance(e, E.Func):
        return _func(e, env)
    if isinstance(e, E.Cast):
        v = eval_expr(e.child, env)
        to = e.to.lower()
        if to in ("double", "float", "decimal"):
            return np.asarray(v, dtype=np.float64) if isinstance(v, np.ndarray) \
                else float(v)
        if to in ("long", "int", "bigint", "integer"):
            if _is_str_like(v):
                return _map1(v, lambda s: int(float(s)))
            return np.asarray(v).astype(np.int64) if isinstance(v, np.ndarray) \
                else int(v)
        if to in ("string", "varchar"):
            if isinstance(v, np.ndarray):
                return np.array([str(x) for x in v], dtype=object)
            return str(v)
        if to in ("date", "timestamp"):
            return _to_days(v)
        raise HostEvalError(f"cast {to}")
    if isinstance(e, E.KeyedLookup):
        k = np.asarray(eval_expr(e.key, env))
        keys, vals = e.table.keys, e.table.values
        miss = np.nan if e.default is None else float(e.default)
        if k.dtype == object or k.dtype.kind == "f":
            kn = pd.to_numeric(pd.Series(k.reshape(-1)),
                               errors="coerce").to_numpy()
            ok = ~np.isnan(kn) & (kn == np.floor(kn))
            ki = np.where(ok, kn, 0).astype(np.int64)
        else:
            ok = None
            ki = k.reshape(-1).astype(np.int64)
        if len(keys) == 0:
            return np.full(ki.shape, miss)
        idx = np.clip(np.searchsorted(keys, ki), 0, len(keys) - 1)
        found = keys[idx] == ki
        if ok is not None:
            # NULL key: the correlated set is empty -> miss value
            found &= ok
        out = np.where(found, vals[idx], miss)
        return out.reshape(k.shape)
    if isinstance(e, E.KeyedLookup2):
        k1 = np.asarray(eval_expr(e.key1, env))
        k2 = np.asarray(eval_expr(e.key2, env))
        miss = np.nan if e.default is None else float(e.default)

        def intify(k):
            if k.dtype == object or k.dtype.kind == "f":
                kn = pd.to_numeric(pd.Series(k.reshape(-1)),
                                   errors="coerce").to_numpy()
                ok = ~np.isnan(kn) & (kn == np.floor(kn))
                return np.where(ok, kn, 0).astype(np.int64), ok
            return k.reshape(-1).astype(np.int64), None

        a, ok1 = intify(k1)
        b, ok2 = intify(k2)
        tab = e.table
        if len(tab) == 0:
            return np.full(a.shape, miss)
        # monotone int64 packing: keys2 offset into [0, 2^32) preserves
        # the lexicographic order of (k1, k2) pairs. Table keys fit int32
        # (FrozenKeyedTable2 invariant); PROBE values outside that range
        # must miss — their packing would wrap into false matches
        inr = (a >= -(2**31)) & (a < 2**31) & (b >= -(2**31)) & (b < 2**31)
        a0 = np.where(inr, a, 0)
        b0 = np.where(inr, b, 0)
        packed = tab.keys1 * (1 << 32) + (tab.keys2 + (1 << 31))
        probe = a0 * (1 << 32) + (b0 + (1 << 31))
        idx = np.clip(np.searchsorted(packed, probe), 0, len(tab) - 1)
        found = (packed[idx] == probe) & inr
        for ok in (ok1, ok2):
            if ok is not None:
                found &= ok
        out = np.where(found, tab.values[idx], miss)
        return out.reshape(k1.shape)
    if isinstance(e, E.Case):
        otherwise = eval_expr(e.otherwise, env) if e.otherwise is not None else 0
        out = otherwise
        for c, v in reversed(e.branches):
            cond = eval_expr(c, env)
            if not np.any(cond):
                # dead branch: skip so e.g. a NaN (SQL NULL) arm doesn't
                # promote an integer result to float64 when no row hits it
                continue
            val = eval_expr(v, env)
            out = np.where(cond, val, out)
        return out
    raise HostEvalError(f"node {type(e).__name__}")


def _map_null(v):
    if v is None:
        return np.ones((), dtype=bool)
    if isinstance(v, float) and math.isnan(v):
        return np.ones((), dtype=bool)
    if isinstance(v, np.ndarray):
        if v.dtype == object:
            return _map1(v, lambda x: x is None
                         or (isinstance(x, float) and math.isnan(x)))
        if np.issubdtype(v.dtype, np.floating):
            return np.isnan(v)
        if np.issubdtype(v.dtype, np.datetime64) \
                or np.issubdtype(v.dtype, np.timedelta64):
            return np.isnat(v)
    return np.zeros(np.shape(v), dtype=bool)


def eval_pred3(e: E.Expr, env: dict) -> np.ndarray:
    """SQL three-valued WHERE/HAVING mask: TRUE keeps the row; UNKNOWN
    (NULL-involved, NaN/None-coded) folds to FALSE at the root, but
    propagates through NOT/AND/OR with Kleene semantics first — so
    ``NOT (x > NULL)`` and ``x <> NULL`` correctly DROP rows where a
    plain boolean evaluation would keep them."""
    t, u = _pred3(e, env)
    out = np.logical_and(t, np.logical_not(u))
    return np.asarray(out, dtype=bool)


def _pred3(e: E.Expr, env: dict):
    """-> (definitely_true, unknown) boolean masks (disjoint). All logic
    via np.logical_* so scalar (builtin-bool) operands stay safe."""
    NOT, AND, OR = np.logical_not, np.logical_and, np.logical_or

    def b(x):
        return np.asarray(x, dtype=bool)

    if isinstance(e, E.Not):
        t, u = _pred3(e.child, env)
        return AND(NOT(t), NOT(u)), u
    if isinstance(e, E.And):
        parts = [_pred3(p, env) for p in e.parts]
        t_all = parts[0][0]
        f_any = AND(NOT(parts[0][0]), NOT(parts[0][1]))
        for t, u in parts[1:]:
            t_all = AND(t_all, t)
            f_any = OR(f_any, AND(NOT(t), NOT(u)))
        return t_all, AND(NOT(t_all), NOT(f_any))
    if isinstance(e, E.Or):
        parts = [_pred3(p, env) for p in e.parts]
        t_any = parts[0][0]
        f_all = AND(NOT(parts[0][0]), NOT(parts[0][1]))
        for t, u in parts[1:]:
            t_any = OR(t_any, t)
            f_all = AND(f_all, AND(NOT(t), NOT(u)))
        return t_any, AND(NOT(t_any), NOT(f_all))
    if isinstance(e, E.Comparison):
        a = eval_expr(e.left, env)
        bb = eval_expr(e.right, env)
        u = OR(_map_null(a), _map_null(bb))
        res = b(_compare(e.op, a, bb))      # operands evaluated once
        res, u = np.broadcast_arrays(res, u)
        return AND(res, NOT(u)), u
    if isinstance(e, E.IsNull):
        res = b(eval_expr(e, env))
        return res, np.zeros(res.shape, dtype=bool)
    if isinstance(e, E.Between):
        inner = E.And((E.Comparison(">=", e.child, e.low),
                       E.Comparison("<=", e.child, e.high)))
        if e.negated:
            inner = E.Not(inner)
        return _pred3(inner, env)
    if isinstance(e, (E.InList, E.Like)):
        # membership/pattern matching implements its own list-null
        # rules; the probe being NULL makes the result UNKNOWN (never
        # TRUE — 'NOT LIKE' over a NULL must drop the row)
        u = _map_null(eval_expr(e.child, env))
        res = b(eval_expr(e, env))
        res, u = np.broadcast_arrays(res, u)
        return AND(res, NOT(u)), u
    v = eval_expr(e, env)
    u = _map_null(v)
    if isinstance(v, np.ndarray) and v.dtype == object:
        res = b(_map1(v, bool))
    elif np.any(u):
        res = b(np.where(u, False, np.nan_to_num(v)))
    else:
        res = b(v)
    res, u = np.broadcast_arrays(res, u)
    return AND(res, NOT(u)), u


def _date_promote(a, b, op):
    """date +/- int means day arithmetic."""
    a_date = isinstance(a, (np.datetime64, _dt.date)) or (
        isinstance(a, np.ndarray) and np.issubdtype(a.dtype, np.datetime64))
    if a_date and op in "+-":
        return _to_days(a), b
    return a, b


def _cmp_promote(a, b):
    """Make date-vs-string / date-vs-date comparisons integer-day compares."""
    def dateish(v):
        return isinstance(v, (np.datetime64, _dt.date)) or (
            isinstance(v, np.ndarray) and np.issubdtype(v.dtype, np.datetime64))
    if dateish(a) or dateish(b):
        return _to_days(a), _to_days(b)
    return a, b


def _func(e: E.Func, env):
    name = e.name.lower()
    args = [eval_expr(a, env) for a in e.args]
    if name in ("year", "month", "day", "quarter", "dow", "doy", "week",
                "hour", "minute", "second"):
        days = _to_days(args[0])
        y, m, d = _civil(days)
        if name == "year":
            return y
        if name == "month":
            return m
        if name == "day":
            return d
        if name == "quarter":
            return (m - 1) // 3 + 1
        if name == "dow":
            return (np.asarray(days) + 3) % 7 + 1
        if name == "doy":
            jan1 = np.array([days_from_civil(int(yy), 1, 1) for yy in np.atleast_1d(y)])
            return np.asarray(days) - (jan1 if jan1.size > 1 else jan1[0]) + 1
        if name == "week":
            return (np.asarray(days) + 3) // 7
        raise HostEvalError(f"{name} needs sub-day time")
    if name in ("date_add", "dateadd"):
        return _to_days(args[0]) + np.asarray(args[1])
    if name in ("date_sub",):
        return _to_days(args[0]) - np.asarray(args[1])
    if name == "datediff":
        return _to_days(args[0]) - _to_days(args[1])
    if name == "add_months":
        raw = _to_days(args[0])
        was_scalar = np.ndim(raw) == 0
        days = np.atleast_1d(raw)
        n = np.asarray(args[1])
        dates = days.astype("datetime64[D]")
        months = dates.astype("datetime64[M]")
        dom = (dates - months).astype(np.int64)          # 0-based day
        nm = (months.astype(np.int64) + n).astype("datetime64[M]")
        month_len = ((nm + 1).astype("datetime64[D]")
                     - nm.astype("datetime64[D]")).astype(np.int64)
        out = nm.astype("datetime64[D]") + np.minimum(dom, month_len - 1)
        return out[0] if was_scalar else out
    if name in ("date_trunc", "trunc"):
        grain = args[0].lower()
        days = _to_days(args[1])
        dates = np.asarray(days).astype("datetime64[D]")
        if grain == "day":
            return dates
        if grain == "week":
            return ((np.asarray(days) + 3) // 7 * 7 - 3).astype("datetime64[D]")
        if grain == "month":
            return dates.astype("datetime64[M]").astype("datetime64[D]")
        if grain == "year":
            return dates.astype("datetime64[Y]").astype("datetime64[D]")
        if grain == "quarter":
            mi = dates.astype("datetime64[M]").astype(np.int64)
            return (mi // 3 * 3).astype("datetime64[M]").astype("datetime64[D]")
        raise HostEvalError(grain)
    if name in ("lower", "upper", "trim", "ltrim", "rtrim", "reverse"):
        fn = {"lower": str.lower, "upper": str.upper, "trim": str.strip,
              "ltrim": str.lstrip, "rtrim": str.rstrip,
              "reverse": lambda s: s[::-1]}[name]
        return _map1(args[0], fn)
    if name in ("substr", "substring"):
        start = int(args[1])
        ln = int(args[2]) if len(args) > 2 else None
        i0 = start - 1 if start > 0 else start
        return _map1(args[0],
                     lambda s: s[i0: i0 + ln] if ln is not None else s[i0:])
    if name == "concat":
        def cc(*xs):
            return "".join(str(x) for x in xs)
        arrs = [a for a in args if isinstance(a, np.ndarray)]
        if not arrs:
            return cc(*args)
        n = len(arrs[0])
        return np.array(["".join(str(a[i] if isinstance(a, np.ndarray) else a)
                                 for a in args) for i in range(n)], dtype=object)
    if name == "replace":
        return _map1(args[0], lambda s: s.replace(args[1], args[2]))
    if name in ("length", "char_length"):
        out = _map1(args[0], len)
        return out.astype(np.int64) if isinstance(out, np.ndarray) else out
    if name in ("lpad", "rpad"):
        n = int(args[1])
        fill = args[2] if len(args) > 2 else " "
        fn = (lambda s: s.rjust(n, fill)) if name == "lpad" \
            else (lambda s: s.ljust(n, fill))
        return _map1(args[0], fn)
    if name == "abs":
        return np.abs(args[0])
    if name == "round":
        if len(args) > 1:
            return np.round(np.asarray(args[0], dtype=np.float64), int(args[1]))
        return np.round(np.asarray(args[0], dtype=np.float64))
    if name in ("floor", "ceil", "sqrt", "exp", "ln", "log"):
        fn = {"floor": np.floor, "ceil": np.ceil, "sqrt": np.sqrt,
              "exp": np.exp, "ln": np.log, "log": np.log}[name]
        return fn(np.asarray(args[0], dtype=np.float64))
    if name in ("power", "pow"):
        return np.power(np.asarray(args[0], dtype=np.float64), args[1])
    if name == "regexp_extract":
        import re as _re
        rx = _re.compile(str(args[1]))
        idx = int(args[2]) if len(args) > 2 else 1

        def rex(s):
            m = rx.search(s) if isinstance(s, str) else None
            return m.group(idx) if m is not None else None
        return _map1(args[0], rex)
    if name == "__lookup_pairs":
        # LOOKUP(col, 'name') after session resolution: args[1] is the
        # (from, to) pairs; missing keys map to null (Druid SQL LOOKUP)
        table = dict(args[1])

        def lk(s):
            return table.get(s)
        return _map1(args[0], lk)
    if name == "coalesce":
        out = args[-1]
        for a in reversed(args[:-1]):
            isnull = _map_null(a) if isinstance(a, np.ndarray) else (a is None)
            out = np.where(isnull, out, a)
        return out
    fn = EXTRA_FUNCTIONS.get(name)
    if fn is not None:
        arrs = [a for a in args if isinstance(a, np.ndarray)]
        if not arrs:
            return fn(*args)
        n = len(arrs[0])
        out = np.array([fn(*[(a[i] if isinstance(a, np.ndarray) else a)
                             for a in args]) for i in range(n)],
                       dtype=object)
        # only narrow to float64 when every non-null element is already
        # numeric: a function returning '123' must stay a string
        if all(v is None or isinstance(v, (int, float, bool, np.number))
               for v in out):
            try:
                return out.astype(np.float64)
            except (ValueError, TypeError):
                return out
        return out
    raise HostEvalError(f"function {name}")


# module-contributed SQL scalar functions (≈ the reference registering UDFs
# into Spark's global FunctionRegistry via BaseModule.registerFunctions);
# Context.install_module populates this
EXTRA_FUNCTIONS: dict = {}
