"""Retry with exponential backoff.

≈ ``RetryUtils.scala``: ``retryOnError(ifException)(name, f)(numTries, start,
cap)`` — exponential backoff used around flaky operations (in the reference:
overlord task polling, cluster metadata fetches; here: server-side ingest and
any external IO)."""

from __future__ import annotations

import logging
import random
import time
from typing import Callable, Optional, TypeVar

T = TypeVar("T")
log = logging.getLogger("sdot.retry")


def backoff(start: float, cap: float, attempt: int,
            prev: Optional[float] = None,
            rng: Optional[random.Random] = None) -> float:
    """Decorrelated-jitter backoff (the AWS architecture-blog variant):
    ``min(cap, uniform(start, prev * 3))``. A herd of concurrent
    retriers hitting the same failure spreads out instead of
    re-colliding on the deterministic 2^n schedule — exactly the shape
    WLM's 429 + Retry-After invites. ``prev=None`` (or a bare
    ``(start, cap, attempt)`` call — the pre-jitter signature) seeds
    the chain from the deterministic envelope, so the delay is always
    within [start, cap] and the envelope stays cap-bounded."""
    if prev is None:
        prev = min(cap, start * (2 ** attempt))
        if attempt == 0:
            return prev            # first retry stays prompt and exact
    r = rng.uniform if rng is not None else random.uniform
    return min(cap, r(start, max(start, prev * 3.0)))


def retry_on_error(
    fn: Callable[[], T],
    name: str = "operation",
    tries: int = 5,
    start: float = 0.2,
    cap: float = 5.0,
    retryable: Optional[Callable[[BaseException], bool]] = None,
) -> T:
    last: Optional[BaseException] = None
    delay: Optional[float] = None
    for attempt in range(tries):
        try:
            return fn()
        except BaseException as e:  # noqa: BLE001 — filtered by retryable
            if retryable is not None and not retryable(e):
                raise
            last = e
            if attempt == tries - 1:
                break
            delay = backoff(start, cap, attempt, prev=delay)
            log.warning("%s failed (attempt %d/%d): %s; retrying in %.2fs",
                        name, attempt + 1, tries, e, delay)
            time.sleep(delay)
    assert last is not None
    raise last
