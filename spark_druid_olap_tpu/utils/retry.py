"""Retry with exponential backoff.

≈ ``RetryUtils.scala``: ``retryOnError(ifException)(name, f)(numTries, start,
cap)`` — exponential backoff used around flaky operations (in the reference:
overlord task polling, cluster metadata fetches; here: server-side ingest and
any external IO)."""

from __future__ import annotations

import logging
import time
from typing import Callable, Optional, TypeVar

T = TypeVar("T")
log = logging.getLogger("sdot.retry")


def backoff(start: float, cap: float, attempt: int) -> float:
    return min(cap, start * (2 ** attempt))


def retry_on_error(
    fn: Callable[[], T],
    name: str = "operation",
    tries: int = 5,
    start: float = 0.2,
    cap: float = 5.0,
    retryable: Optional[Callable[[BaseException], bool]] = None,
) -> T:
    last: Optional[BaseException] = None
    for attempt in range(tries):
        try:
            return fn()
        except BaseException as e:  # noqa: BLE001 — filtered by retryable
            if retryable is not None and not retryable(e):
                raise
            last = e
            if attempt == tries - 1:
                break
            delay = backoff(start, cap, attempt)
            log.warning("%s failed (attempt %d/%d): %s; retrying in %.2fs",
                        name, attempt + 1, tries, e, delay)
            time.sleep(delay)
    assert last is not None
    raise last
