"""Three-tier configuration system.

Mirrors the reference's conf layering (SURVEY.md §5 "Config / flag system"):

1. per-datasource options at registration time
   (reference: ``DefaultSource.scala:197-308`` — ~17 DataSource options);
2. session-level flags under the ``sdot.*`` namespace
   (reference: ``spark.sparklinedata.*`` SQLConf entries,
   ``DruidPlanner.scala:60-169``);
3. per-session overrides of datasource options via
   ``sdot.datasource.option.<name>``
   (reference: ``DruidRelationInfo.scala:103-138``).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Optional


@dataclasses.dataclass(frozen=True)
class ConfigEntry:
    key: str
    default: Any
    doc: str
    parse: Callable[[str], Any] = lambda s: s
    #: semantic keys change query RESULTS or compiled programs and belong
    #: in cache fingerprints; operational keys (quotas, cadence, history
    #: sizing) must NOT churn every cache on tuning (sdlint keys/K4)
    semantic: bool = True


def _parse_bool(s: str) -> bool:
    return str(s).strip().lower() in ("1", "true", "yes", "on")


_REGISTRY: Dict[str, ConfigEntry] = {}


def _entry(key: str, default: Any, doc: str, parse=None,
           semantic: bool = True) -> ConfigEntry:
    if parse is None:
        if isinstance(default, bool):
            parse = _parse_bool
        elif isinstance(default, int):
            parse = int
        elif isinstance(default, float):
            parse = float
        else:
            parse = lambda s: s
    e = ConfigEntry(key, default, doc, parse, semantic)
    _REGISTRY[key] = e
    return e


# --- planner flags (reference: DruidPlanner.scala:60-169) ---------------------
DEBUG_TRANSFORMATIONS = _entry(
    "sdot.debug.transformations", False,
    "Log each planner transform's input and output (reference: "
    "spark.sparklinedata.druid.debug.transformations).")
TZ_ID = _entry(
    "sdot.timezone", "UTC",
    "Timezone for time bucketing and interval arithmetic (reference: "
    "spark.sparklinedata.tz.id).")
SELECT_PAGE_SIZE = _entry(
    "sdot.select.pagesize", 10000,
    "Rows per page for non-aggregate (select) scans (reference: "
    "spark.sparklinedata.druid.selectquery.pagesize).")
SELECT_DEVICE_MIN_ROWS = _entry(
    "sdot.select.device.min.rows", 1 << 17,
    "Min datasource rows before a select (raw scan) query evaluates its "
    "filter on device (compiled mask program, 32x bit-packed transfer); "
    "below it the host numpy path wins (device dispatch floor). 0 forces "
    "the device path when a device filter exists.")
ALLOW_TOPN = _entry(
    "sdot.querycostmodel.topn.allow", True,
    "Allow rewriting single-dim ordered-limit group-bys to the approximate "
    "topN path (reference: spark.sparklinedata.druid.allow.topn).")
TOPN_THRESHOLD = _entry(
    "sdot.querycostmodel.topn.threshold", 100000,
    "Max limit value eligible for the topN rewrite (reference: "
    "spark.sparklinedata.druid.topn.threshold).")
QUERY_HISTORY = _entry(
    "sdot.enable.query.history", True,
    "Record executed engine queries with timings into the bounded history "
    "queue (reference: spark.sparklinedata.enable.druid.query.history).")
QUERY_HISTORY_SIZE = _entry(
    "sdot.query.history.size", 500,
    "Bounded size of the in-memory query history queue (reference: "
    "DruidQueryHistory MAX_SIZE=500).")
PHASES_ENABLED = _entry(
    "sdot.phases.enabled", True,
    "Per-query host-path phase profiler (utils/phases.py): attribute "
    "host time to named phases (parse, plan.*, wlm.admit, compile, "
    "bind, dispatch, ...) emitted as stats[\"phases\"] and aggregated "
    "into BENCH JSON. Two clock reads per phase — cheap enough to stay "
    "always-on (< 1% of wall; no Druid analog).", semantic=False)
NON_AGG_PUSHDOWN = _entry(
    "sdot.nonagg.handling", "push_project_and_filters",
    "Handling of non-aggregate queries: push_project_and_filters | "
    "push_filters | push_none (reference: NonAggregateQueryHandling, "
    "DruidRelationInfo.scala:27-32).")
MODULES = _entry(
    "sdot.modules", "",
    "Comma-separated extension modules to install at Context creation, as "
    "package.module:ClassName (reference: spark.sparklinedata.modules via "
    "ModuleLoader).")
# --- cost model knobs (reference: DruidQueryCostModel via DruidPlanner) -------
COST_MODEL_ENABLED = _entry(
    "sdot.querycostmodel.enabled", True,
    "Use the cost model to pick single-chip vs sharded execution and the "
    "segments-per-wave; if false always use the sharded path (reference: "
    "spark.sparklinedata.querycostmodel.enabled).")
COST_PER_ROW_SCAN = _entry(
    "sdot.querycostmodel.historical.processing.cost", 1e-8,
    "Abstract cost to scan+filter one row on one chip (reference: "
    "historicalProcessingCostPerRow).", float)
COST_PER_ROW_MERGE = _entry(
    "sdot.querycostmodel.historical.merge.cost", 7e-8,
    "Abstract cost to merge one output row across shards (reference: "
    "historicalTimeSeriesProcessingCostPerRow).", float)
COST_PER_BYTE_TRANSPORT = _entry(
    "sdot.querycostmodel.transport.cost", 2.5e-9,
    "Abstract cost to move one byte host<->device or across DCN (reference: "
    "sparkSchedulingCostPerTask/shuffleCostPerByte family).", float)
COST_COMPILE = _entry(
    "sdot.querycostmodel.compile.cost", 0.05,
    "Fixed abstract cost charged per distinct compiled program (XLA "
    "compilation amortization; no reference analog — TPU-specific).", float)
COST_SHARD_EFFICIENCY = _entry(
    "sdot.querycostmodel.shard.efficiency", 1.0,
    "Calibrated parallel efficiency of the mesh's scan split in (0, 1]: "
    "1.0 = N chips scan N-fold faster (real ICI-connected TPUs); a "
    "virtual mesh over shared host cores measures far lower and the "
    "single-vs-sharded decision must reflect that. Fit by "
    "tools/calibrate.py from measured wall times.", float)
COST_PER_BYTE_INTERCONNECT = _entry(
    "sdot.querycostmodel.interconnect.cost", 5e-10,
    "Abstract cost to move one byte across the device interconnect (ICI) "
    "during the cross-chip merge of per-device partial aggregates — the "
    "mesh tier's analog of the reference's broker-merge transport term. "
    "Prices the reduction payload (merged partial bytes x (n_dev - 1)) "
    "so wide outputs on small scans correctly prefer single-device "
    "execution.", float)
COST_SORT_ROW = _entry(
    "sdot.querycostmodel.sort.seconds.per.row", 2.2e-10,
    "Measured seconds per row of a 2-operand device lax.sort (the "
    "compaction position sort / hashed slot sort). Default = v5e "
    "measurement (1.3ms / 6M rows); tools/calibrate.py refits it on the "
    "live backend — the CPU fallback's x64 sort is ~1000x this, which "
    "is what flips the compaction and sorted-run gates there.", float)
COST_SORT_PAYLOAD_ROW = _entry(
    "sdot.querycostmodel.sort.payload.seconds.per.row", 6.7e-10,
    "Measured seconds per row per EXTRA sort payload operand "
    "(v5e: +4ms / 6M rows each). Fit by tools/calibrate.py.", float)
COST_SCATTER_UPDATE = _entry(
    "sdot.querycostmodel.scatter.seconds.per.update", 6.7e-9,
    "Measured seconds per update of an XLA scatter/segment-sum into a "
    "group table that FITS in cache (v5e: ~40ms / 6M updates, index "
    "order irrelevant; fit at a 128KB table by tools/calibrate.py). The "
    "past-cache thrash regime is the separate scatter.big constant.",
    float)
COST_SCATTER_UPDATE_BIG = _entry(
    "sdot.querycostmodel.scatter.big.seconds.per.update", 6.7e-9,
    "Measured seconds per scatter update when the group table exceeds "
    "sdot.querycostmodel.table.cache.bytes. On TPU this equals the "
    "small-table constant (HBM scatters are size-invariant, measured); "
    "on the CPU fallback random updates into a table past LLC are "
    "~30-50x the in-cache cost — the regime behind the measured SF10 "
    "compacted-vs-uncompacted crossover. Fit by tools/calibrate.py.",
    float)
COST_TABLE_CACHE_BYTES = _entry(
    "sdot.querycostmodel.table.cache.bytes", 24 << 20,
    "Group-table byte size above which scatter updates are costed at the "
    "big-table constant (≈ the host LLC on the CPU fallback; irrelevant "
    "on TPU where both constants are equal).", int)
COST_GATHER_PROBE = _entry(
    "sdot.querycostmodel.gather.seconds.per.probe", 7e-9,
    "Measured seconds per probe of a flattened 1D device gather "
    "(v5e: ~7ms / M probes). Fit by tools/calibrate.py.", float)
COST_FUSED_ROW = _entry(
    "sdot.querycostmodel.fused.seconds.per.row", 2.3e-9,
    "Measured seconds per row of the fused Pallas small-K group-by "
    "kernel's single streamed pass (v5e: ~2.3ms / M rows). Governs the "
    "ffl-route compaction ceiling: below it, compact-then-re-gather "
    "loses to just streaming every row through the kernel.", float)
# --- engine knobs (TPU-specific; no reference analog) -------------------------
SEGMENT_ROWS = _entry(
    "sdot.segment.target.rows", 1 << 20,
    "Target rows per time-sharded segment at ingest.")
SCAN_COMPACT = _entry(
    "sdot.engine.scan.compact", True,
    "Late materialization: when the filter-selectivity estimate says few "
    "rows survive, sort survivors to a static prefix and run group-key "
    "building, value derivation, and aggregation at O(survivors) instead "
    "of O(rows). Overflow of the estimated budget retries uncompacted.")
SCAN_COMPACT_MIN_ROWS = _entry(
    "sdot.engine.scan.compact.min.rows", 1 << 21,
    "Scans below this many rows never compact (the sort pass wins "
    "nothing at small scale).")
GROUPBY_PALLAS_MAX_KEYS = _entry(
    "sdot.engine.groupby.pallas.max.keys", 64,
    "Dense group-by uses the fused single-pass Pallas TPU kernel when the "
    "fused key cardinality is at most this (0 disables). Also honors env "
    "SDOT_PALLAS=0|interpret.")
PALLAS_WAVE_ENABLED = _entry(
    "sdot.pallas.wave.enabled", True,
    "Shared-scan fused groups lower each dispatch wave to ONE "
    "hand-scheduled Pallas mega-kernel (ops/pallas_wave.py) when every "
    "lane's aggregations are wave-eligible: union columns tile through "
    "VMEM once, CSE'd shared predicates evaluate once per tile, and all "
    "lanes' filtered aggregates accumulate in kernel scratch. False "
    "routes back to the XLA jaxpr-fused program (kill switch). Requires "
    "a TPU-class backend or SDOT_PALLAS=interpret (CPU CI).")
PALLAS_WAVE_TILE_BYTES = _entry(
    "sdot.pallas.wave.tile.bytes", 8 << 20,
    "VMEM budget (bytes) the wave mega-kernel's tile planner fits the "
    "double-buffered union-column tiles plus the resident scratch "
    "accumulator block into (~half of a v5e core's 16MB VMEM).", int)
PALLAS_WAVE_MAX_LANES = _entry(
    "sdot.pallas.wave.max.lanes", 16,
    "Max fused lanes (distinct constituent plans) a single wave "
    "mega-kernel accumulates; larger groups fall back to the jaxpr-fused "
    "program (trace size and scratch rows grow per lane).", int)
MESH_ENABLED = _entry(
    "sdot.mesh.enabled", True,
    "Shared-scan fused groups (parallel/sharedscan.py) shard their "
    "segment waves across the local device mesh (parallel/meshexec.py): "
    "each device scans its segment slice — through the Pallas wave "
    "mega-kernel when the group is wave-eligible — and per-lane partial "
    "aggregates merge on the interconnect with the register algebra "
    "AGG_CLOSURE declares (psum sums/counts, pmax min-sentinel-free "
    "maxima + HLL registers, pmin minima + theta hash minima). False "
    "pins the fused tier to single-device execution (kill switch); solo "
    "queries keep their own cost-model shard decision either way.")
MESH_AUTO = _entry(
    "sdot.mesh.auto", False,
    "Build the local device mesh automatically at Context startup when "
    "more than one device is visible — how subprocess deployments "
    "(cluster historicals via --set sdot.mesh.auto=true) opt their "
    "engines into the multi-chip mesh tier without a code-level mesh "
    "handle. The in-process equivalent is Context(auto_mesh=True).")
MESH_MIN_SEGMENTS = _entry(
    "sdot.mesh.min.segments", 2,
    "Minimum selected segments before the fused tier shards a group "
    "across the mesh; below it one device owns the whole scan (a "
    "1-segment-per-device split pays collective latency for no scan "
    "parallelism).", int)
GROUPBY_MATMUL_MAX_KEYS = _entry(
    "sdot.engine.groupby.matmul.max.keys", 4096,
    "Dense group-by uses the MXU one-hot matmul path when the fused key "
    "cardinality is at most this; above it, scatter-add.")
JOIN_ENABLED = _entry(
    "sdot.join.enabled", True,
    "General (non-star) joins execute on the device join tier "
    "(join/broadcast.py, join/partitioned.py) when the statement shape "
    "qualifies; False routes every non-star join to the host pandas "
    "fallback (kill switch — answers are identical, only placement "
    "changes).")
JOIN_BROADCAST_MAX_BYTES = _entry(
    "sdot.join.broadcast.max.bytes", 64 << 20,
    "Build-side byte ceiling for the broadcast hash-join tier: when the "
    "smaller side's estimated bytes fit, its hash table is built once "
    "per node, device-resident, and probed inside the segment wave "
    "loop. Bigger builds go to the cluster partitioned tier (when a "
    "broker is attached) or the host fallback.", int)
JOIN_MAX_MATCHES = _entry(
    "sdot.join.max.matches", 64,
    "Widest per-key duplicate group the device probe expands in "
    "registers (the static match-expansion width C). A build side with "
    "a hotter key declines to the host fallback instead of "
    "materializing an oversized expansion.", int)
JOIN_PARTITIONS = _entry(
    "sdot.join.partitions", 0,
    "Hash-partition count for the cluster partitioned-join exchange "
    "(both sides re-shard on the join key through the historicals). "
    "0 = one partition per cluster node.", int)
JOIN_MODE = _entry(
    "sdot.join.mode", "auto",
    "Join-tier placement override: 'auto' (cost model picks), "
    "'broadcast', 'partitioned', or 'host' (device join tiers "
    "disabled for this statement shape only).")
GROUPBY_DENSE_MAX_KEYS = _entry(
    "sdot.engine.groupby.dense.max.keys", 1 << 22,
    "Max fused key cardinality for the dense device group-by; above it the "
    "engine switches to the hashed group-by (ops/hash_groupby.py).")
GROUPBY_SORTED_MIN_KEYS = _entry(
    "sdot.engine.groupby.sorted.min.keys", 1024,
    "Medium-K routing: key cardinalities at or above this route to the "
    "sorted-run tier even below dense.max.keys when the backend's sort "
    "is cheap (the sorted-run auto gate). The dense one-hot matmul "
    "writes ~N*K onehot bytes through HBM per scan — at v5e bandwidth "
    "that crosses the one-sort-plus-payloads cost near K~512. 0 "
    "disables the medium-K reroute.")
GROUPBY_HASH_SLOTS = _entry(
    "sdot.engine.groupby.hash.slots", 0,
    "Group-table slot count for the hashed group-by (any value; used "
    "as-is). 0 = auto-size to the next power of two above the group-count "
    "upper bound min(key space, selected rows). Overflow retries at 4x up "
    "to sdot.engine.groupby.hash.max.slots.")
DEVICE_CACHE_BYTES = _entry(
    "sdot.engine.device.cache.bytes", 8 << 30,
    "Budget for device-resident bound column arrays (host-side bytes "
    "tracked per upload). When a new binding would exceed it the whole "
    "array cache is dropped and rebuilt on demand — bounding HBM held by "
    "shifting segment selections (paged selects, moving intervals).")
GROUPBY_HASH_MAX_SLOTS = _entry(
    "sdot.engine.groupby.hash.max.slots", 1 << 24,
    "Max hash-table slot count; a query whose actual group count exceeds "
    "what this table can hold falls back to the host tier (reference "
    "contract: Druid groupBy v2 spills, never refuses — "
    "DruidQuerySpec.scala:558-571).")
HAVING_DEVICE_MIN_KEYS = _entry(
    "sdot.engine.having.device.min.keys", 1 << 16,
    "Min fused key cardinality before an exact-comparable HAVING (int "
    "literal vs limb/i32/i64/f64 aggregate) evaluates on device and only "
    "passing groups transfer (two dispatches: finals+mask count, then "
    "gather). Below it the full [K] result transfers and the host "
    "filters.")
DATABASE_DEFAULT = _entry(
    "sdot.database.default", "",
    "Default database namespace: an unqualified table name that is not "
    "registered resolves to '<default>.<name>' when that is (reference: "
    "multi-database operation across non-default Hive DBs, "
    "MultiDBTest.scala). Databases are dotted name prefixes in the one "
    "store; 'db.table' in FROM always addresses explicitly.")
BACKEND_RETRY_SECONDS = _entry(
    "sdot.engine.backend.retry.seconds", 30.0,
    "Cooldown between re-attach probes after the device backend is lost "
    "mid-session (e.g. the TPU tunnel dies): statements keep being served "
    "by the host tier, and at most one probe per cooldown window checks "
    "whether the device answers again (≈ the reference's ZK-watch cache "
    "invalidation re-planning against live servers, "
    "CuratorConnection.scala:77-136).", float)
TOPN_DEVICE_MIN_KEYS = _entry(
    "sdot.engine.topn.device.min.keys", 8192,
    "Min fused key cardinality before an ordered-limit group-by / topN "
    "runs its top-k selection on device (lax.top_k over the merged "
    "partials, transferring only the candidate rows). Below it the full "
    "[K] result transfers and the host sorts (cheap at small K).")
GROUPBY_HASH_MAX_SLOTS_CPU = _entry(
    "sdot.engine.groupby.hash.max.slots.cpu", 1 << 23,
    "Hash-table slot ceiling on non-TPU backends (effective cap = "
    "min(this, sdot.engine.groupby.hash.max.slots)). Measured basis: x64 "
    "scatters into a 16M-slot table thrash the host cache so badly the "
    "pandas host tier is ~3x faster (q18-inner SF10: 530s engine vs 193s "
    "host) — above the ceiling the query demotes to the host tier.")
GROUPBY_HASH_SORTED = _entry(
    "sdot.engine.groupby.hash.sortedrun", "auto",
    "Sorted-run aggregation for the hashed group-by tier "
    "(ops/sorted_groupby.py): ride agg values as sort payloads and "
    "replace per-agg scatters with prefix scans + run-boundary reads. "
    "'auto' = on for TPU backends (the sort is ~30x cheaper than one "
    "scatter there) and off on the CPU fallback (x64 sort dominates); "
    "'on'/'off' force it (tests force 'on' for differential coverage).")
GROUPBY_HASH_COMPACT_MIN = _entry(
    "sdot.engine.groupby.hash.compact.min.slots", 1 << 18,
    "Min hash-table slot count before the hashed group-by compacts on "
    "device (two dispatches: build table + read occupancy count, then "
    "gather only occupied slots) instead of transferring the full [T] "
    "table. Worth one extra dispatch RTT whenever the table is sized "
    "far above the actual group count.")
WAVE_MAX_BYTES = _entry(
    "sdot.engine.wave.max.bytes", 0,
    "Per-device byte budget for one execution wave's scan arrays; a scan "
    "whose bound arrays exceed it runs in multiple bounded waves over the "
    "segment axis. 0 = auto (60% of the device's reported HBM limit, or "
    "unbounded when the backend reports none). Reference analog: the cost "
    "model's segments-per-query limit bounding per-historical work "
    "(DruidQueryCostModel.scala:343-414).")
HLL_LOG2M = _entry(
    "sdot.engine.hll.log2m", 11,
    "log2 of the HLL register count for approximate count-distinct "
    "(reference: Druid hyperUnique uses 2^11 registers).")
QUANTILE_LANES = _entry(
    "sdot.quantile.lanes", 256,
    "Sample lanes per KLL level for percentile_approx (ops/kll.py). "
    "Register width is 2*4*lanes + 4 int32 per group; rank error "
    "shrinks ~1/sqrt(lanes). Must match across every engine in a "
    "cluster — registers merge elementwise at the broker.")
QUANTILE_RANK_BOUND = _entry(
    "sdot.quantile.rank_bound", 0.05,
    "Maximum |rank(estimate) - fraction| the bench/loadtest percentile "
    "differential gates accept from the KLL estimate (rank space, not "
    "value space — value error is unbounded for heavy-tailed data).")
WINDOW_ENABLED = _entry(
    "sdot.window.enabled", True,
    "Window-function post-pass (window/): OVER (PARTITION BY ... ORDER "
    "BY ...) computed by segment-sorted device kernels over the grouped "
    "(and, clustered, broker-merged) result frame. Off = window queries "
    "raise unsupported.")
WINDOW_MAX_FRAME = _entry(
    "sdot.window.max.frame", 1024,
    "Largest bounded ROWS frame (preceding + following + 1) the device "
    "window kernels lower via shift-stacking; wider frames raise "
    "unsupported rather than materializing an unbounded shift stack.")
# --- semantic result cache (cache/) -------------------------------------------
CACHE_ENABLED = _entry(
    "sdot.cache.enabled", True,
    "Semantic query-result cache over engine aggregate results "
    "(cache/result_cache.py): identical queries are served from host "
    "memory without touching the device. Keys fold in the per-datasource "
    "ingest version, so staleness is structural — any re-ingest, stream "
    "append or drop invalidates (≈ Druid's broker/historical result "
    "caches keyed on segment versions).")
CACHE_MAX_BYTES = _entry(
    "sdot.cache.max_bytes", 256 << 20,
    "Byte budget for materialized results held by the semantic result "
    "cache; least-recently-used entries evict past it. Results larger "
    "than the whole budget are never admitted.")
CACHE_SUBSUMPTION = _entry(
    "sdot.cache.subsumption", True,
    "Answer queries from SUPERSET cached entries without re-executing "
    "(cache/subsume.py): coarser-granularity timeseries from a cached "
    "finer one, TopN and dim-filtered GroupBy from a cached "
    "unfiltered/unlimited GroupBy over the same dims, and "
    "having/limit/post-agg re-evaluation on cached partials.")
# --- materialized rollup datasources (mv/) ------------------------------------
MV_REWRITE_ENABLED = _entry(
    "sdot.mv.rewrite.enabled", True,
    "Automatically rewrite eligible GroupBy queries onto a registered "
    "materialized rollup datasource (mv/match.py): grouping dims covered "
    "by the rollup dims (join-key equivalences count), merge-closed "
    "derivable aggregations, dim-only filters, cleanly-coarsening "
    "granularity. Stale rollups (base re-ingested since the build) are "
    "bypassed, never served (≈ Sparkline rewriting onto the Druid "
    "rollup index).")
PLAN_CACHE_ENABLED = _entry(
    "sdot.plan.cache.enabled", True,
    "Statement plan cache (pushdown + composite plans keyed on store "
    "version and config fingerprint). Benchmarks disable it so measured "
    "reps time the full rewrite/build/execute path instead of a "
    "statement-cache hit.")
PLAN_MEMO_ENABLED = _entry(
    "sdot.plan.memo.enabled", True,
    "Memoize the planning-cascade outcome per canonical statement "
    "(window extraction, resolution, rewrites, built plan, join "
    "recognition, composite plan — including NEGATIVE recognizer "
    "results), keyed like the plan cache on store version + config "
    "fingerprint plus a lookup-table fingerprint. A warm repeated "
    "statement skips straight from canonical key to the cached "
    "compiled program; distinct from sdot.plan.cache.enabled, which "
    "benchmarks disable. Purely a host-latency optimization: the "
    "memoized plan is bit-identical to a cold re-plan.",
    semantic=False)
PLAN_MEMO_ENTRIES = _entry(
    "sdot.plan.memo.entries", 128,
    "Max memoized planning-cascade outcomes; least-recently-used "
    "statements evict past it.", int, semantic=False)
# --- workload management (wlm/) -----------------------------------------------
WLM_ENABLED = _entry(
    "sdot.wlm.enabled", True,
    "Admission control in front of the engine (wlm/): every query is "
    "classified into a named lane with bounded concurrency and queue "
    "depth; overload sheds with a retryable rejection (HTTP 429 + "
    "Retry-After) instead of melting every in-flight query (≈ Druid "
    "query laning / QueryScheduler).", semantic=False)
WLM_LANES = _entry(
    "sdot.wlm.lanes",
    "interactive:slots=8,queue=64;reporting:slots=4,queue=32;"
    "batch:slots=2,queue=16",
    "Lane layout: 'name:slots=N,queue=N,wait_ms=N,timeout_ms=N,"
    "priority=N;...'. slots = concurrent queries in the lane, queue = "
    "bounded wait-queue depth past which admissions shed, wait_ms = max "
    "queue-wait budget (0 = only the query's own timeout bounds it), "
    "timeout_ms = default QueryContext timeout applied when the client "
    "set none, priority = default admission priority (higher first).",
    semantic=False)
WLM_DEFAULT_LANE = _entry(
    "sdot.wlm.default.lane", "interactive",
    "Lane for queries with no explicit context.lane (before cost-based "
    "demotion is considered).", semantic=False)
WLM_BATCH_COST = _entry(
    "sdot.wlm.batch.cost.threshold", 0.5,
    "Estimated single-chip cost units (parallel/cost.estimate) at or "
    "above which a query without an explicit lane is demoted to the "
    "'batch' lane (≈ Druid HiLoQueryLaningStrategy). 0 disables "
    "cost-based demotion. Per-tenant quotas ride the same config "
    "channel as free-form keys: 'sdot.wlm.quota.<tenant>' = "
    "'concurrent=N,budget=F,refill=F' ('default' is the template for "
    "tenants without an explicit entry).", float, semantic=False)
# --- shared-scan multi-query execution (parallel/sharedscan.py) ---------------
SHAREDSCAN_ENABLED = _entry(
    "sdot.sharedscan.enabled", False,
    "Coalesce concurrent eligible queries (engine-mode GroupBy / "
    "Timeseries / TopN) over the same datasource into ONE fused device "
    "program: each segment wave's column union binds once and every "
    "constituent's filter + aggregation lanes evaluate against the "
    "shared in-HBM bind, then results demultiplex per query (each still "
    "populating the result cache under its own canonical key). Off by "
    "default: solo workloads pay the hold window for nothing.")
WLM_BATCH_WINDOW_MS = _entry(
    "sdot.wlm.batch.window.ms", 8.0,
    "Micro-batch hold window for the shared-scan tier: the first "
    "eligible query on a datasource holds this long for companions "
    "before dispatching (group-commit semantics). Held time counts "
    "against the query's own timeout_millis. The window closes early "
    "when sdot.sharedscan.max.queries constituents have joined.", float)
SHAREDSCAN_MAX_QUERIES = _entry(
    "sdot.sharedscan.max.queries", 8,
    "Constituent cap per coalesced group: the hold window closes early "
    "at this size, bounding fused-program width (compile cost and "
    "output-buffer size grow with every extra query lane).")
SHAREDSCAN_FUSION_ENABLED = _entry(
    "sdot.sharedscan.fusion.enabled", True,
    "Cross-lane fusion planner (planner/fusion.py): canonicalize every "
    "lane's filter tree into a shared sub-expression DAG, lower each "
    "distinct sub-predicate ONCE per fused program (shared masks first, "
    "then per-lane base = row_valid & shared & residual), and thread "
    "the same CSE cache through the solo dense/hashed cores for "
    "queries whose own tree repeats sub-predicates. Bit-identical "
    "answers by construction (masks combine with exact bool ops); any "
    "planning error falls back to unfused lowering. Folded into every "
    "affected compile signature, so toggling recompiles rather than "
    "reusing a mismatched program.")
SHAREDSCAN_FUSION_MAX_NODES = _entry(
    "sdot.sharedscan.fusion.max.nodes", 512,
    "Planner cost guard: per-group cap on distinct predicate nodes the "
    "fusion analysis will canonicalize. A group over the cap plans "
    "unfused (the host-side DAG walk is O(nodes) per execution and "
    "must stay negligible next to the dispatch floor). 0 = uncapped.")
# --- durable segment persistence (persist/) -----------------------------------
PERSIST_PATH = _entry(
    "sdot.persist.path", "",
    "Root directory of the on-disk snapshot store (deep storage). Empty "
    "disables persistence entirely: the segment store is volatile, as the "
    "reference is without its Druid deep-storage tier. Set to a directory "
    "to enable versioned checkpoints, the stream-ingest WAL, and startup "
    "recovery.")
PERSIST_ENABLED = _entry(
    "sdot.persist.enabled", True,
    "Master gate for the persist subsystem when sdot.persist.path is set "
    "(lets an operator keep the path configured but run volatile).")
PERSIST_RECOVER = _entry(
    "sdot.persist.recover.on.start", True,
    "Recover published snapshots + WAL tails into the segment store at "
    "Context creation. Off = the directory is only written, never read "
    "(fresh-start semantics with durability still on).")
PERSIST_WAL_FSYNC = _entry(
    "sdot.persist.wal.fsync", True,
    "fsync the write-ahead journal before a stream_ingest batch is "
    "considered committed. Off trades the kill -9 durability guarantee "
    "for append throughput (an OS crash can lose the un-synced tail; "
    "replay still stops cleanly at the first torn record).",
    semantic=False)
PERSIST_CHECKPOINT_SECONDS = _entry(
    "sdot.persist.checkpoint.interval.seconds", 0.0,
    "Cadence of the background checkpointer folding dirty datasources "
    "(new/re-ingested, or WAL tail past the byte budget) into fresh "
    "snapshots. 0 disables the thread; CHECKPOINT statements and "
    "Context.checkpoint() still work.", float, semantic=False)
PERSIST_CHECKPOINT_MAX_BYTES = _entry(
    "sdot.persist.checkpoint.max.bytes", 0,
    "Byte budget for ONE background checkpoint pass: dirty datasources "
    "snapshot in ascending size order until the pass would exceed it; "
    "the rest stay dirty for the next tick (bounds the I/O burst a "
    "cadence tick can issue). 0 = unbounded.", int, semantic=False)
PERSIST_KEEP_SNAPSHOTS = _entry(
    "sdot.persist.keep.snapshots", 2,
    "Published snapshot versions retained per datasource; older versions "
    "are pruned after each successful publish. Must be >= 1 (the current "
    "version is never pruned).", semantic=False)
PERSIST_VERIFY_CHECKSUMS = _entry(
    "sdot.persist.verify.checksums", True,
    "Verify per-file CRC32 checksums against the manifest during "
    "recovery. A mismatch quarantines that snapshot version and recovery "
    "falls back to the previous one (or the WAL alone) — the engine "
    "always starts.", semantic=False)
PERSIST_GROUP_COMMIT = _entry(
    "sdot.persist.wal.group.commit", True,
    "Route stream-ingest WAL appends through the shared commit queue: "
    "one fsync covers every frame queued by concurrent producers, and "
    "each ACK is released only after its covering fsync (ACK-implies-"
    "durable unchanged, fsync cost amortized). Off = one fsync per "
    "append, the original path.", semantic=False)
PERSIST_APPEND_PARALLEL = _entry(
    "sdot.persist.append.parallel", True,
    "Build a stream-append's dimension/metric columns across a thread "
    "pool (per-column dictionary union + order-preserving remap are "
    "independent, so the result is bit-identical to the serial build). "
    "Only engages past a small batch-row floor.", semantic=False)
PERSIST_COMPACT_SECONDS = _entry(
    "sdot.persist.compact.interval.seconds", 0.0,
    "Cadence of the background compactor rolling a stream-appended tail "
    "of many small segments into time-partitioned segments (atomic "
    "generation swap: snapshot publish + WAL truncate + quiet in-memory "
    "swap, no ingest-version bump — caches and rollup staleness are "
    "untouched because the rows are identical). 0 disables the thread; "
    "PersistManager.compact() still works.", float, semantic=False)
PERSIST_COMPACT_MIN_SEGMENTS = _entry(
    "sdot.persist.compact.min.segments", 8,
    "Segment-count floor below which the compactor leaves a datasource "
    "alone (compacting a handful of segments buys nothing and churns "
    "snapshot versions).", int, semantic=False)
# --- host-tier safety valve ---------------------------------------------------
HOST_GATHER_PAGE_BYTES = _entry(
    "sdot.host.gather.page.bytes", 32 << 20,
    "Byte budget for ONE paged cross-process gather when "
    "Datasource.complete() reassembles a partial store's column on the "
    "host tier; larger columns exchange in multiple bounded pages "
    "instead of one unbounded allgather.")
# --- distributed serving tier (cluster/) --------------------------------------
CLUSTER_NODES = _entry(
    "sdot.cluster.nodes", "",
    "Comma-separated host:port list of historical nodes, index order = "
    "node id. Empty disables the cluster tier (single-process engine). "
    "Every process of one cluster — broker and historicals — must be "
    "given the identical list: the deterministic shard assignment "
    "(cluster/assign.py) is a pure function of this list plus the deep "
    "storage manifests.", semantic=False)
CLUSTER_ROLE = _entry(
    "sdot.cluster.role", "",
    "Role of THIS process in the cluster: 'broker' attaches the "
    "scatter/merge client to the engine; 'historical' is set by the "
    "cluster entrypoint on serving nodes; empty = not clustered.",
    semantic=False)
CLUSTER_NODE_ID = _entry(
    "sdot.cluster.node.id", 0,
    "This historical's index into sdot.cluster.nodes (which address it "
    "serves on and which shards it owns).", int, semantic=False)
CLUSTER_REPLICATION = _entry(
    "sdot.cluster.replication", 2,
    "Copies of each segment shard across historicals (clamped to the "
    "node count). The broker retries a failed shard on each replica "
    "before declaring the shard unreachable.", int, semantic=False)
CLUSTER_SHARDS = _entry(
    "sdot.cluster.shards", 0,
    "Segment shards per datasource the broker scatters over; 0 = one "
    "per node. Semantic: the shard composition fixes the partial-merge "
    "grouping (float accumulation order), so cached results are keyed "
    "on it.", int)
CLUSTER_RPC_TIMEOUT_SECONDS = _entry(
    "sdot.cluster.rpc.timeout.seconds", 30.0,
    "Socket timeout for one broker->historical subquery RPC. A timeout "
    "marks the node down and fails the attempt over to a replica.",
    float, semantic=False)
CLUSTER_RETRY_TRIES = _entry(
    "sdot.cluster.retry.tries", 3,
    "Full passes over a shard's replica set before the broker gives up "
    "on remote execution (then: local fallback if enabled, else fail). "
    "Between passes it sleeps with decorrelated-jitter backoff "
    "(utils/retry.py).", int, semantic=False)
CLUSTER_RETRY_BACKOFF_START_SECONDS = _entry(
    "sdot.cluster.retry.backoff.start.seconds", 0.05,
    "Base delay of the decorrelated-jitter backoff between replica-set "
    "passes.", float, semantic=False)
CLUSTER_RETRY_BACKOFF_CAP_SECONDS = _entry(
    "sdot.cluster.retry.backoff.cap.seconds", 2.0,
    "Delay ceiling of the decorrelated-jitter backoff between "
    "replica-set passes.", float, semantic=False)
CLUSTER_PROBE_INTERVAL_SECONDS = _entry(
    "sdot.cluster.probe.interval.seconds", 1.0,
    "Cadence of the broker's background health prober (GET /readyz on "
    "every node). A failing probe marks the node down — its shards "
    "route to replicas — and a passing one marks it back up. "
    "0 disables probing (nodes are still marked down reactively on "
    "RPC failure).", float, semantic=False)
CLUSTER_SCATTER_THREADS = _entry(
    "sdot.cluster.scatter.threads", 16,
    "Worker threads in the broker's scatter pool (concurrent subquery "
    "RPCs across all in-flight queries).", int, semantic=False)
CLUSTER_LOCAL_FALLBACK = _entry(
    "sdot.cluster.local.fallback", True,
    "When every replica of some shard is unreachable, execute the whole "
    "query on the broker's own engine (it holds a full recovered copy) "
    "instead of failing. Answers are identical; only placement changes.",
    semantic=False)
CLUSTER_PARTIAL_RESULTS = _entry(
    "sdot.cluster.partial.results", False,
    "Degraded mode: when every replica of some shard is unreachable, "
    "answer from the surviving shards and annotate the result with "
    "degraded={missing_shards, coverage_rows} instead of raising "
    "ShardUnavailable / falling back whole-query (this takes precedence "
    "over sdot.cluster.local.fallback for unreachable shards). Degraded "
    "answers are NEVER cached, so cached entries stay exact full "
    "answers and the key needs no new term.", semantic=False)
CLUSTER_BREAKER_FAILURES = _entry(
    "sdot.cluster.breaker.failures", 3,
    "Consecutive subquery failures against one node that open its "
    "circuit breaker (the broker then skips the node without an RPC "
    "until the cooldown elapses). 0 disables breakers.",
    int, semantic=False)
CLUSTER_BREAKER_COOLDOWN_SECONDS = _entry(
    "sdot.cluster.breaker.cooldown.seconds", 5.0,
    "How long an open breaker rejects attempts before letting ONE "
    "half-open probe RPC through; that probe's outcome closes or "
    "re-opens the breaker.", float, semantic=False)
CLUSTER_HEDGE_ENABLED = _entry(
    "sdot.cluster.hedge.enabled", False,
    "Hedged scatter: when a subquery RPC has not answered within the "
    "hedge delay, race a duplicate request to the next replica and take "
    "whichever answers first (the loser is discarded; replicas are "
    "exact copies, so answers are identical either way).",
    semantic=False)
CLUSTER_HEDGE_AFTER_MS = _entry(
    "sdot.cluster.hedge.after.ms", 0.0,
    "Fixed hedge delay in milliseconds; 0 = automatic (the observed "
    "subquery-latency quantile below, once enough samples exist).",
    float, semantic=False)
CLUSTER_HEDGE_QUANTILE = _entry(
    "sdot.cluster.hedge.quantile", 0.95,
    "Latency quantile of recent subquery RPCs used as the automatic "
    "hedge delay when sdot.cluster.hedge.after.ms is 0.",
    float, semantic=False)
CLUSTER_HEDGE_MIN_MS = _entry(
    "sdot.cluster.hedge.min.ms", 10.0,
    "Floor for the automatic hedge delay (keeps the quantile estimate "
    "from hedging every RPC while the sample window is still cold).",
    float, semantic=False)
CLUSTER_PROBE_JITTER = _entry(
    "sdot.cluster.probe.jitter", True,
    "Decorrelated jitter (utils/retry.backoff) on the background "
    "readyz prober's interval so N brokers don't probe a rejoining "
    "historical in lockstep; each tick lands in [0.5x, 1.5x] of "
    "sdot.cluster.probe.interval.seconds.", semantic=False)
# --- elastic topology: plan epochs (cluster/epoch.py) -------------------------
CLUSTER_EPOCH_POLL_SECONDS = _entry(
    "sdot.cluster.epoch.poll.seconds", 1.0,
    "Cadence at which a HISTORICAL polls deep storage for a newer plan "
    "epoch (cluster/epoch.py) and runs its side of the handover — warm "
    "newly owned shards before advertising, or drain-then-fence when "
    "the new epoch drops it. 0 disables the watcher thread (tests "
    "drive node.check_epoch() manually). The broker piggybacks its "
    "epoch check on the readyz prober interval.", float, semantic=False)
CLUSTER_EPOCH_DRAIN_GRACE_SECONDS = _entry(
    "sdot.cluster.epoch.drain.grace.seconds", 0.5,
    "How long a leaving historical keeps serving AFTER it observes the "
    "new epoch fully warm, before it starts draining — absorbs the "
    "window where the broker has not yet polled the same readiness and "
    "still scatters against the old epoch.", float, semantic=False)
CLUSTER_EPOCH_DRAIN_TIMEOUT_SECONDS = _entry(
    "sdot.cluster.epoch.drain.timeout.seconds", 10.0,
    "Upper bound a leaving historical waits for its in-flight "
    "subqueries to finish before fencing anyway (a stuck query must "
    "not pin a retired node forever).", float, semantic=False)
CLUSTER_REBALANCE_STRATEGY = _entry(
    "sdot.cluster.rebalance.strategy", "stable",
    "Shard owner placement: 'stable' (rendezvous hashing over logical "
    "node ids — an N->N+1 epoch moves ~1/(N+1) of the assignments, see "
    "cluster/assign.py) or 'modular' (the legacy CRC rotation, kept as "
    "a kill switch; nearly every owner moves on any topology change). "
    "Placement never changes answers, only which node serves a shard.",
    semantic=False)
CLUSTER_SUBQ_CACHE_ENABLED = _entry(
    "sdot.cluster.subq.cache.enabled", False,
    "Broker-side shard-level subquery result cache: partial results "
    "are cached per (subquery shape, shard identity, ingest version), "
    "so a repeated dashboard storm skips unchanged shards entirely. "
    "Keys carry shard identity — not node identity — so entries "
    "survive epoch transitions; the ingest-version term makes staleness "
    "impossible, so answers are bit-identical with the cache off. "
    "Opt-in: identical repeated queries are already absorbed by the "
    "broker's semantic result cache, and chaos/failover tests rely on "
    "repeats actually exercising the RPC path — enable it for mixed "
    "dashboard workloads whose queries share subquery shapes.",
    semantic=False)
CLUSTER_SUBQ_CACHE_MAX_BYTES = _entry(
    "sdot.cluster.subq.cache.max.bytes", 64 << 20,
    "Byte budget of the broker's shard-level subquery cache (LRU "
    "eviction).", int, semantic=False)
CLUSTER_INGEST_PUSH = _entry(
    "sdot.cluster.ingest.push", True,
    "Distributed ingest: after a stream-ingest batch is journaled and "
    "acknowledged on the broker (durability is ALWAYS local), push it "
    "to the time-matched shard's owners so distributed queries keep "
    "read-your-writes instead of falling back to broker-local serving "
    "until the next checkpoint. Off, or when any owner push fails, the "
    "broker's ingest-version check simply serves the datasource locally "
    "— never a correctness difference, only where the scan runs.",
    semantic=False)
CLUSTER_AUTOSCALE_ENABLED = _entry(
    "sdot.cluster.autoscale.enabled", False,
    "Autoscale hook (cluster/autoscale.py): the broker samples every "
    "historical's WLM queue depth on the prober cadence and calls the "
    "registered spawn/retire callbacks — which publish a new plan "
    "epoch — when the fleet-mean depth crosses the high/low marks. "
    "Without registered callbacks, decisions only increment counters "
    "(dry run).", semantic=False)
CLUSTER_AUTOSCALE_QUEUE_HIGH = _entry(
    "sdot.cluster.autoscale.queue.high", 8.0,
    "Fleet-mean WLM queued-query depth above which the autoscale hook "
    "signals scale-out (spawn a historical, publish an epoch adding "
    "it).", float, semantic=False)
CLUSTER_AUTOSCALE_QUEUE_LOW = _entry(
    "sdot.cluster.autoscale.queue.low", 0.5,
    "Fleet-mean WLM queued-query depth below which the autoscale hook "
    "signals scale-in (drain and retire one historical via a new "
    "epoch). Must be well under the high mark or the fleet flaps.",
    float, semantic=False)
CLUSTER_AUTOSCALE_COOLDOWN_SECONDS = _entry(
    "sdot.cluster.autoscale.cooldown.seconds", 30.0,
    "Minimum wall-clock spacing between autoscale decisions; epoch "
    "handovers in progress also suppress new signals.",
    float, semantic=False)
# --- deterministic fault injection (fault/) -----------------------------------
FAULT_PLAN = _entry(
    "sdot.fault.plan", "",
    "JSON FaultPlan ({\"seed\": S, \"rules\": [...]}) activating named "
    "injection sites across cluster RPC, persist I/O, the cold tier, "
    "and WLM admission — see docs/CHAOS.md for the site catalog and "
    "rule schema. Empty (default) = every site is a zero-cost no-op. "
    "Injected faults only provoke the recovery paths; strict-mode "
    "answers remain exact, so results stay cacheable.", semantic=False)
# --- out-of-core tiered storage (tier/) ---------------------------------------
TIER_ENABLED = _entry(
    "sdot.tier.enabled", False,
    "Recover datasources as TIERED stores: column bytes stay in the "
    "persist/ snapshot (cold tier) and fault on demand into a "
    "byte-budgeted hot set instead of loading eagerly at boot "
    "(tier/loader.py; requires sdot.persist.path). Consulted ONCE at "
    "recovery — flipping it mid-session changes nothing until the next "
    "Context, so cached results within a session are unaffected; the "
    "wave-composition effects of tiering key off the per-query "
    "sdot.tier.wave.io.bytes (semantic) instead.", semantic=False)
TIER_BUDGET_BYTES = _entry(
    "sdot.tier.budget.bytes", 2 << 30,
    "Byte budget of the hot set (per process — on a cluster historical "
    "this bounds the node's owned-shard residency). Chunks over budget "
    "evict by query-history popularity, oldest-touch first; chunks "
    "pinned by in-flight queries never evict, so peak residency is "
    "budget + in-flight bytes.", int, semantic=False)
TIER_VERIFY_CHECKSUMS = _entry(
    "sdot.tier.verify.checksums", True,
    "Verify each cold blob's CRC32 against the manifest on the FIRST "
    "fault that touches it (recovery itself only checks structure, "
    "keeping boot O(manifest)). A mismatch quarantines the snapshot "
    "version and re-recovers per PERSIST semantics.", semantic=False)
TIER_PREFETCH_ENABLED = _entry(
    "sdot.tier.prefetch.enabled", True,
    "Run the cold-tier prefetcher threads: the wave loop enqueues wave "
    "i+2's chunks while wave i computes on device, hiding cold loads "
    "behind dispatch. Purely a latency optimization — demand faults "
    "serve everything when disabled.", semantic=False)
TIER_PREFETCH_THREADS = _entry(
    "sdot.tier.prefetch.threads", 2,
    "Prefetcher worker threads draining the cold-load queue.",
    int, semantic=False)
TIER_DECODED_CACHE_BYTES = _entry(
    "sdot.tier.decoded.cache.bytes", 128 << 20,
    "Byte budget of the decode-ahead cache: decoded arrays for hot "
    "ENCODED chunks, accounted at DECODED size on top of the encoded "
    "hot set (not against sdot.tier.budget.bytes; combined residency "
    "is budget + decoded cache). The prefetcher decodes into it and "
    "demand faults serve from it, taking decode off the critical path "
    "(counters \"decode_ms_saved\" in stats[\"tier\"]). Decoded "
    "entries evict before any encoded payload. 0 disables decode-"
    "ahead; raw (unencoded) stores are unaffected.", int,
    semantic=False)
TIER_WAVE_IO_BYTES = _entry(
    "sdot.tier.wave.io.bytes", 256 << 20,
    "Per-wave host-I/O byte cap on a tiered scan (the wave planner's "
    "I/O term, parallel/cost.py:tier_io_budget): forces enough waves "
    "that prefetch can overlap loads with compute. 0 disables the "
    "term. Semantic: changes the wave composition and with it float "
    "accumulation order.", int)
# --- compressed columnar encoding (encode/) -----------------------------------
ENCODE_ENABLED = _entry(
    "sdot.encode.enabled", False,
    "Write snapshot column blobs ENCODED (bit-packed dictionary codes, "
    "RLE runs, frame-of-reference+delta time columns — encode/codecs.py) "
    "with a per-column chooser at checkpoint/compaction time. Snapshots "
    "without an encoding block load as raw little-endian unchanged; a "
    "tiered recovery faults encoded bytes, so the hot-set budget holds "
    "compression-ratio x more data. Decoded arrays are bit-identical to "
    "the raw path; the flag is still folded into compile signatures "
    "defensively.", semantic=False)
ENCODE_MIN_RATIO = _entry(
    "sdot.encode.min.ratio", 1.2,
    "Minimum whole-column compression ratio (raw bytes / estimated "
    "encoded bytes) the chooser demands before it encodes a column at "
    "all — below it the column stays raw little-endian (encoding that "
    "barely shrinks only adds decode latency).", float, semantic=False)
ENCODE_RLE_MAX_RUN_FRAC = _entry(
    "sdot.encode.rle.max.run.frac", 0.5,
    "RLE eligibility cutoff: runs/rows above this fraction disqualifies "
    "the RLE candidate outright (near-unique columns degenerate to one "
    "run per row, where RLE is larger than raw).", float, semantic=False)


# Families of runtime-shaped keys (tenant / datasource suffixes) that
# cannot be declared one-by-one with _entry(). This tuple IS the declared
# contract for them: the sdlint contracts pass accepts any read of a key
# under these prefixes, and anything else must be an _entry. Add a prefix
# here (with a pointer to the consuming module) before introducing a new
# dynamic family.
DYNAMIC_KEY_PREFIXES = (
    "sdot.wlm.quota.",          # per-tenant quota grammar (wlm/quota.py)
    "sdot.datasource.option.",  # per-session datasource option overrides
                                # (Config.datasource_option_overrides)
)


class Config:
    """A mutable key-value session config over the registered entries.

    Unknown ``sdot.*`` keys are accepted (forward compatibility), mirroring the
    reference importing every ``spark.sparklinedata.*`` SparkConf key into the
    session conf (``SPLSessionState.scala:90-103``).
    """

    DATASOURCE_OVERRIDE_PREFIX = "sdot.datasource.option."

    def __init__(self, overrides: Optional[Dict[str, Any]] = None):
        self._values: Dict[str, Any] = {}
        if overrides:
            for k, v in overrides.items():
                self.set(k, v)

    def set(self, key: str, value: Any) -> None:
        entry = _REGISTRY.get(key)
        if entry is not None and isinstance(value, str) and not isinstance(entry.default, str):
            value = entry.parse(value)
        self._values[key] = value

    def fingerprint(self) -> tuple:
        """Hashable snapshot of the SEMANTIC overrides — result/plan
        caches key on it so a session config change (timezone, HLL
        precision, ...) can never serve results computed under the old
        settings. Keys declared ``semantic=False`` (admission quotas,
        lane layouts, history sizing) are excluded: they shape scheduling
        and observability, never results, and folding them in would
        invalidate every cache on each operational tuning step. Unknown
        keys are kept — forward compatibility must fail toward
        correctness, not cache retention."""
        out = []
        for k, v in self._values.items():
            e = _REGISTRY.get(k)
            if e is not None and not e.semantic:
                continue
            if k.startswith("sdot.wlm.quota."):
                continue    # dynamic family, admission-only
            out.append((k, repr(v)))
        return tuple(sorted(out))

    def get(self, entry_or_key) -> Any:
        if isinstance(entry_or_key, ConfigEntry):
            return self._values.get(entry_or_key.key, entry_or_key.default)
        entry = _REGISTRY.get(entry_or_key)
        if entry is not None:
            return self._values.get(entry.key, entry.default)
        return self._values.get(entry_or_key)

    def is_set(self, entry_or_key) -> bool:
        """Whether the key was EXPLICITLY set this session (even to its
        default value) — per-backend default resolution (cost.unit_cost)
        must never override an operator's explicit choice."""
        key = entry_or_key.key if isinstance(entry_or_key, ConfigEntry) \
            else entry_or_key
        return key in self._values

    def datasource_option_overrides(self) -> Dict[str, Any]:
        """Per-session overrides of datasource options (tier 3)."""
        p = self.DATASOURCE_OVERRIDE_PREFIX
        return {k[len(p):]: v for k, v in self._values.items() if k.startswith(p)}

    def prefixed(self, prefix: str) -> Dict[str, Any]:
        """Every explicitly-set key under ``prefix`` (free-form config
        families like ``sdot.wlm.quota.<tenant>`` ride the unknown-key
        channel and enumerate themselves this way)."""
        return {k: v for k, v in self._values.items()
                if k.startswith(prefix)}

    def copy(self) -> "Config":
        c = Config()
        c._values = dict(self._values)
        return c

    @staticmethod
    def registry() -> Dict[str, ConfigEntry]:
        return dict(_REGISTRY)
