"""Per-query host-path phase profiler.

Every query pays a ~1.7 ms dispatch floor on the device; everything
else is host work spread across parsing, a recognizer cascade, caches,
binding and demux.  This module attributes that host time to *named
phases* with two monotonic-clock reads per phase, cheap enough to stay
always-on (< 1% of wall, enforced by tests/test_phases.py).

Usage::

    tok = PH.begin()                 # open a per-query accumulator
    with PH.phase("plan.build"):
        ...
    PH.add("dispatch", seconds)      # hot loops: pre-measured interval
    phases = PH.end(tok)             # {"plan.build": ms, ...}

Semantics:

- The accumulator is thread-local.  ``begin()`` returns ``None`` when
  an accumulator is already open (nested query execution, e.g. UNION
  branches re-entering the select path) — inner phases then merge into
  the outer accumulator and the inner ``end(None)`` is a no-op.
- ``phase()``/``add()`` outside any open accumulator are no-ops, so
  background threads (tier prefetcher) and non-query entry points can
  share the instrumented call sites for free.
- Phases are *inclusive*: a phase nested inside another counts in
  both, so the per-query sum may exceed wall time.  Readers should
  treat each entry as "time attributable to this stage", not as a
  partition of the wall clock.
- ``stash(name, seconds)`` records time measured *before* the
  accumulator could be opened (statement parse happens before the
  select path begins); the next ``begin()`` on the same thread folds
  the stash in.  ``clear_stash()`` drops leftovers so one statement's
  parse can never leak into the next.

The ``PHASES`` registry below is the single source of truth for phase
names; sdlint cross-checks every ``PH.phase("...")``/``PH.add("...")``
call site against it and against the docs/STATS.md phase table.
"""
from __future__ import annotations

import threading
import time
from typing import Dict, Optional

# name -> one-line meaning (kept a pure literal: sdlint parses it)
PHASES = {
    "parse": "SQL text -> AST (memoized; counted when actually run)",
    "plan.memo": "planning-cascade memo lookup",
    "plan.window": "window-function extraction",
    "plan.resolve": "database/alias-scope/lookup resolution",
    "plan.rewrite": "derived-table merge, decorrelation, subquery inlining",
    "plan.build": "SELECT -> PlannedQuery spec build",
    "plan.rollup": "materialized-rollup rewrite match",
    "plan.star": "star-join collapse over the FROM list",
    "plan.join": "general-join recognition",
    "plan.composite": "composite (host-assist) plan build",
    "wlm.admit": "workload-manager admission",
    "cache.lookup": "result-cache probe",
    "compile": "program build + jit (per signature, first run only)",
    "tier.fault": "tiered-store faults on the demand path",
    "tier.decode": "encoded-chunk decode on the demand path",
    "bind": "host->device array binding",
    "dispatch": "device execution + result fetch",
    "demux": "shared-scan per-lane demux/decode",
    "epilogue": "window post-pass and result epilogue",
}

_tls = threading.local()


def _acc() -> Optional[Dict[str, float]]:
    return getattr(_tls, "acc", None)


def begin(enabled: bool = True) -> Optional[Dict[str, float]]:
    """Open a per-query accumulator; None if nested or disabled."""
    stash = getattr(_tls, "stash", None)
    _tls.stash = None
    if not enabled or getattr(_tls, "acc", None) is not None:
        return None
    acc: Dict[str, float] = {}
    if stash:
        for k, v in stash.items():
            acc[k] = acc.get(k, 0.0) + v
    _tls.acc = acc
    return acc


def end(tok: Optional[Dict[str, float]]) -> Optional[Dict[str, float]]:
    """Close the accumulator opened by begin(); returns {name: ms}.

    Idempotent and nested-safe: ``end(None)`` is a no-op returning
    None, and closing twice (finally blocks) is harmless.
    """
    if tok is None:
        return None
    if getattr(_tls, "acc", None) is tok:
        _tls.acc = None
    return {k: v * 1000.0 for k, v in tok.items()}


class _Phase:
    __slots__ = ("name", "acc", "t0")

    def __init__(self, name: str) -> None:
        self.name = name
        self.acc = None
        self.t0 = 0.0

    def __enter__(self) -> "_Phase":
        self.acc = _acc()
        if self.acc is not None:
            self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> None:
        if self.acc is not None:
            dt = time.perf_counter() - self.t0
            self.acc[self.name] = self.acc.get(self.name, 0.0) + dt
            self.acc = None


def phase(name: str) -> _Phase:
    """Context manager timing one phase; no-op without an open acc."""
    return _Phase(name)


def add(name: str, seconds: float) -> None:
    """Fold a pre-measured interval into the open accumulator."""
    acc = _acc()
    if acc is not None:
        acc[name] = acc.get(name, 0.0) + seconds


def stash(name: str, seconds: float) -> None:
    """Record time measured before begin(); folded into the next one."""
    st = getattr(_tls, "stash", None)
    if st is None:
        st = {}
        _tls.stash = st
    st[name] = st.get(name, 0.0) + seconds


def clear_stash() -> None:
    """Drop any pending stash (statement boundary)."""
    _tls.stash = None
