"""Pluggable extension modules.

≈ the reference's module system (``SparklineDataModule.scala:70-151``):
``BaseModule`` exposes ``registerFunctions`` / logical rules / physical
rules / parser extensions, and ``ModuleLoader`` reflectively instantiates
classes named in conf ``spark.sparklinedata.modules``. Here a ``Module``
can contribute:

- **SQL scalar functions** (host tier always; single-string-arg functions
  additionally vectorize on device through the dictionary string-function
  path, so grouping/filtering on them still pushes down),
- **query-spec rewrite rules** (run by the spec transform executor after
  the builder, alongside the built-in topN/timeseries rules),
- **statement handlers** (front-parsed commands tried before the SQL
  parser, like the reference's ``SPLParser`` command grammar).

Modules are named in conf ``sdot.modules`` as comma-separated
``package.module:ClassName`` entries and installed at ``Context`` creation;
``Context.install_module`` installs one programmatically.
"""

from __future__ import annotations

import importlib
from typing import Callable, Dict, List, Optional, Tuple


class Module:
    """Base extension module. Override any subset of the three providers."""

    def functions(self) -> Dict[str, Callable]:
        """name -> scalar python callable (applied elementwise on host; on
        device via the dictionary path when the single argument is a string
        dimension)."""
        return {}

    def spec_rules(self) -> List[Callable]:
        """Extra ``(QuerySpec, Config) -> Optional[QuerySpec]`` rewrite
        rules (≈ DruidLogicalOptimizer extra batches)."""
        return []

    def statement_handlers(self) -> List[Callable]:
        """Extra ``(ctx, sql) -> Optional[QueryResult]`` front handlers
        tried before SQL parsing (≈ SPLParser commands)."""
        return []

    def install(self, ctx) -> None:
        for name, fn in self.functions().items():
            ctx.functions[name.lower()] = fn
        ctx.spec_rules.extend(self.spec_rules())
        ctx.statement_handlers.extend(self.statement_handlers())


def load_module(spec: str) -> Module:
    """Instantiate ``package.module:ClassName`` (≈ ModuleLoader's reflective
    ``Class.forName``, SparklineDataModule.scala:120-150)."""
    modname, _, clsname = spec.partition(":")
    if not clsname:
        raise ValueError(
            f"module spec {spec!r} must be 'package.module:ClassName'")
    cls = getattr(importlib.import_module(modname), clsname)
    mod = cls()
    if not isinstance(mod, Module):
        raise TypeError(f"{spec} is not a Module")
    return mod


def install_from_config(ctx, csv: str) -> List[Module]:
    out = []
    for spec in [s.strip() for s in csv.split(",") if s.strip()]:
        mod = load_module(spec)
        mod.install(ctx)
        out.append(mod)
    return out
