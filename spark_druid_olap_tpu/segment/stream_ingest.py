"""Out-of-core Parquet ingest: row-group streaming under a bounded memory
footprint.

≈ the Druid batch index task the reference submits
(``DruidOverlordClient.submitTask``, ``client/DruidOverlordClient.scala:
65-125``; ``quickstart/tpch_index_task.json.template``): Druid's indexer
streams the input, shuffles rows into time-partitioned segments, and builds
per-segment dictionaries/columns without ever materializing the dataset as
rows. The TPU translation keeps the *final columnar arrays* (what the engine
scans) as the only O(dataset) allocation:

- **Pass A (metadata)**: stream batches once to collect per-dim value sets
  (-> sorted global dictionaries), per-metric min/max + nullability (-> i32
  vs wide-i64 storage), and a day-granularity time histogram.
- **Partitioning**: pack days into segments of ~target_rows (the time-axis
  shuffle at day granularity; rows within a segment stay arrival-ordered —
  segment pruning needs only per-segment time bounds, not row order).
- **Pass B (encode+scatter)**: stream batches again; encode each column
  against the global dictionaries and scatter rows directly into their
  final preallocated destination slots via per-segment cursors.

Peak memory = final store columns + one in-flight batch + dictionaries,
versus the in-memory path's full raw DataFrame + sorted copy + encoded
columns all coexisting.
"""

from __future__ import annotations

from typing import Dict, Iterable, Optional

import numpy as np
import pandas as pd

from spark_druid_olap_tpu.segment.column import (
    ColumnKind,
    DimColumn,
    MetricColumn,
    MILLIS_PER_DAY,
    TimeColumn,
)
from spark_druid_olap_tpu.segment.ingest import _to_epoch_millis, infer_kind
from spark_druid_olap_tpu.segment.store import Datasource, Segment


def _arrow_batches(path, batch_rows):
    import pyarrow.parquet as pq
    pf = pq.ParquetFile(path)
    return pf, pf.iter_batches(batch_size=batch_rows)


def _series_of(batch, col) -> pd.Series:
    return batch.column(col).to_pandas()


def _valid_mask(raw: np.ndarray) -> np.ndarray:
    """Vectorized non-null mask over an object array (None/NaN/pd.NA)."""
    return ~pd.isna(raw)


def _kind_from_arrow(t) -> Optional[ColumnKind]:
    """Deterministic column-kind inference from the Parquet/Arrow schema
    (a first-batch pandas dtype would flip int->float depending on where
    nulls fall)."""
    import pyarrow as pa
    if pa.types.is_string(t) or pa.types.is_large_string(t):
        return ColumnKind.DIM
    if pa.types.is_floating(t) or pa.types.is_decimal(t):
        return ColumnKind.DOUBLE
    if pa.types.is_integer(t) or pa.types.is_boolean(t):
        return ColumnKind.LONG
    if pa.types.is_timestamp(t) or pa.types.is_date(t):
        return ColumnKind.DATE
    return None


def ingest_parquet_stream(
    name: str,
    path: str,
    time_column: Optional[str] = None,
    dimensions: Optional[Iterable[str]] = None,
    metrics: Optional[Iterable[str]] = None,
    target_rows: int = 1 << 20,
    batch_rows: int = 1 << 20,
    metric_kinds: Optional[Dict[str, ColumnKind]] = None,
    n_hosts: Optional[int] = None,
    host_id: Optional[int] = None,
) -> Datasource:
    """Stream a Parquet file into a datasource without materializing it.

    With ``n_hosts``/``host_id`` this becomes the multi-host per-process
    ingest (≈ each Druid middle-manager indexing only its own time
    chunks): pass A (dictionaries, ranges, histogram) still streams the
    whole file — its products are the GLOBAL metadata every process must
    agree on and they are tiny — but pass B allocates and scatters ONLY
    the rows of this host's segments, so per-host peak memory is
    ~1/n_hosts of the dataset (plus one in-flight batch)."""
    dim_names = set(dimensions) if dimensions is not None else None
    metric_names = set(metrics) if metrics is not None else None
    metric_kinds = metric_kinds or {}

    # -- pass A: schema, dictionaries, time histogram, metric ranges ----------
    pf, batches = _arrow_batches(path, batch_rows)
    n_total = pf.metadata.num_rows
    cols = [f.name for f in pf.schema_arrow]
    kinds: Dict[str, ColumnKind] = {}
    uniques: Dict[str, np.ndarray] = {}
    has_null: Dict[str, bool] = {c: False for c in cols}
    int_min: Dict[str, int] = {}
    int_max: Dict[str, int] = {}
    # global float/date ranges: injected as the partial datasource's
    # metric bounds so cost-model selectivity is identical on every host
    flt_min: Dict[str, float] = {}
    flt_max: Dict[str, float] = {}
    day_counts: Dict[int, int] = {}
    first = True
    for batch in batches:
        for c in cols:
            s = _series_of(batch, c)
            if first:
                k = _kind_from_arrow(
                    pf.schema_arrow.field(c).type) or infer_kind(s)
                if dim_names is not None and c in dim_names:
                    k = ColumnKind.DIM
                elif metric_names is not None and c in metric_names:
                    k = metric_kinds.get(c) or (
                        k if k != ColumnKind.DIM else ColumnKind.DOUBLE)
                elif c in metric_kinds:
                    k = metric_kinds[c]
                kinds[c] = k
            k = kinds[c]
            if c == time_column:
                ms = _to_epoch_millis(s)
                days = np.floor_divide(ms, MILLIS_PER_DAY)
                d, cnt = np.unique(days, return_counts=True)
                for di, ci in zip(d.tolist(), cnt.tolist()):
                    day_counts[di] = day_counts.get(di, 0) + ci
                continue
            if k == ColumnKind.DIM:
                raw = s.to_numpy(dtype=object)
                valid = _valid_mask(raw)
                if not valid.all():
                    has_null[c] = True
                vals = np.unique(raw[valid].astype(str))
                prev = uniques.get(c)
                uniques[c] = vals if prev is None \
                    else np.union1d(prev, vals)
            elif k in (ColumnKind.LONG,):
                v = s.to_numpy()
                if np.issubdtype(v.dtype, np.floating):
                    has_null[c] |= bool(np.isnan(v).any())
                    v = v[~np.isnan(v)]
                if len(v):
                    lo, hi = int(np.min(v)), int(np.max(v))
                    int_min[c] = min(int_min.get(c, lo), lo)
                    int_max[c] = max(int_max.get(c, hi), hi)
            elif k == ColumnKind.DOUBLE:
                v = s.to_numpy(np.float64, na_value=np.nan)
                has_null[c] |= bool(np.isnan(v).any())
                v = v[~np.isnan(v)]
                if len(v):
                    lo, hi = float(v.min()), float(v.max())
                    flt_min[c] = min(flt_min.get(c, lo), lo)
                    flt_max[c] = max(flt_max.get(c, hi), hi)
            elif k == ColumnKind.DATE:
                d = np.floor_divide(_to_epoch_millis(s), MILLIS_PER_DAY)
                if len(d):
                    lo, hi = int(d.min()), int(d.max())
                    int_min[c] = min(int_min.get(c, lo), lo)
                    int_max[c] = max(int_max.get(c, hi), hi)
        first = False

    # -- segment partitioning over the day histogram --------------------------
    if time_column is not None and day_counts:
        days_sorted = sorted(day_counts)
        seg_first_day = [days_sorted[0]]
        acc = 0
        for d in days_sorted:
            if acc >= target_rows:
                seg_first_day.append(d)
                acc = 0
            acc += day_counts[d]
        seg_of_day = np.asarray(seg_first_day, dtype=np.int64)
        seg_rows = np.zeros(len(seg_first_day), dtype=np.int64)
        for d, cnt in day_counts.items():
            seg_rows[np.searchsorted(seg_of_day, d, side="right") - 1] += cnt
    else:
        n_seg = max(1, -(-n_total // target_rows))
        per = -(-n_total // n_seg) if n_total else 1
        seg_rows = np.full(n_seg, per, dtype=np.int64)
        seg_rows[-1] = n_total - per * (n_seg - 1) if n_total else 0
        seg_of_day = None
    seg_starts = np.concatenate([[0], np.cumsum(seg_rows)[:-1]])

    # -- multi-host: this process materializes only its segments --------------
    assignment = None
    local_of_seg = None          # [n_seg] local row start, -1 when remote
    n_alloc = int(n_total)
    if n_hosts is not None and int(n_hosts) > 1:
        from spark_druid_olap_tpu.parallel.multihost import (
            assign_segments_to_hosts)
        assignment = assign_segments_to_hosts(seg_rows, int(n_hosts))
        is_local = assignment == int(host_id or 0)
        local_sizes = np.where(is_local, seg_rows, 0)
        local_starts = np.concatenate([[0], np.cumsum(local_sizes)[:-1]]) \
            if len(local_sizes) else np.zeros(0, np.int64)
        local_of_seg = np.where(is_local, local_starts, -1)
        n_alloc = int(local_sizes.sum())

    # -- preallocate final columns -------------------------------------------
    ii = np.iinfo(np.int32)

    def metric_dtype(c):
        from spark_druid_olap_tpu.segment.column import narrow_int_dtype
        k = kinds[c]
        if k == ColumnKind.DOUBLE:
            return np.float32
        if k == ColumnKind.DATE:
            return np.int32
        lo, hi = int_min.get(c, 0), int_max.get(c, 0)
        wide = lo < ii.min or hi > ii.max
        return np.int64 if wide else narrow_int_dtype(lo, hi)

    out: Dict[str, np.ndarray] = {}
    validity: Dict[str, np.ndarray] = {}
    dicts: Dict[str, np.ndarray] = {}
    for c in cols:
        if c == time_column:
            out["__days__"] = np.zeros(n_alloc, np.int32)
            out["__ms__"] = np.zeros(n_alloc, np.int32)
            continue
        if kinds[c] == ColumnKind.DIM:
            from spark_druid_olap_tpu.segment.column import narrow_int_dtype
            dicts[c] = uniques.get(c, np.array([], dtype=object))
            out[c] = np.zeros(n_alloc, narrow_int_dtype(
                0, max(len(dicts[c]) - 1, 0)))
        else:
            out[c] = np.zeros(n_alloc, metric_dtype(c))
        if has_null[c]:
            validity[c] = np.zeros(n_alloc, bool)

    # -- pass B: encode + scatter into destination slots ----------------------
    cursors = seg_starts.copy()
    seg_min_ms = np.full(len(seg_rows), np.iinfo(np.int64).max)
    seg_max_ms = np.full(len(seg_rows), np.iinfo(np.int64).min)
    _, batches = _arrow_batches(path, batch_rows)
    for batch in batches:
        bn = batch.num_rows
        if time_column is not None:
            ms = _to_epoch_millis(_series_of(batch, time_column))
            days = np.floor_divide(ms, MILLIS_PER_DAY)
            seg_idx = np.searchsorted(seg_of_day, days,
                                      side="right") - 1 \
                if seg_of_day is not None else np.zeros(bn, np.int64)
            dest = np.empty(bn, np.int64)
            order = np.argsort(seg_idx, kind="stable")
            ss = seg_idx[order]
            uniq, starts, counts = np.unique(ss, return_index=True,
                                             return_counts=True)
            for s_, st, cnt in zip(uniq.tolist(), starts.tolist(),
                                   counts.tolist()):
                dest[order[st: st + cnt]] = cursors[s_] + np.arange(cnt)
                cursors[s_] += cnt
                m = ms[order[st: st + cnt]]
                # GLOBAL segment time bounds: every process computes all
                # of them (metadata must agree across hosts)
                seg_min_ms[s_] = min(seg_min_ms[s_], int(m.min()))
                seg_max_ms[s_] = max(seg_max_ms[s_], int(m.max()))
        else:
            # sequential fill; segment boundaries respected by construction
            start = int(cursors[0])
            dest = np.arange(start, start + bn)
            cursors[0] += bn
            seg_idx = np.searchsorted(seg_starts, dest, side="right") - 1

        if local_of_seg is not None:
            # keep only this host's rows; global dest -> local dest
            lstart = local_of_seg[seg_idx]
            keep = lstart >= 0
            dest = (lstart + (dest - seg_starts[seg_idx]))[keep]
        else:
            keep = slice(None)

        if time_column is not None:
            out["__days__"][dest] = days[keep].astype(np.int32)
            out["__ms__"][dest] = (ms[keep]
                                   - days[keep] * MILLIS_PER_DAY) \
                .astype(np.int32)
        for c in cols:
            if c == time_column:
                continue
            s = _series_of(batch, c)
            k = kinds[c]
            if k == ColumnKind.DIM:
                raw = s.to_numpy(dtype=object)[keep]
                valid = _valid_mask(raw)
                safe = np.where(valid, raw, "").astype(str)
                codes = np.searchsorted(dicts[c], safe)
                codes = np.clip(codes, 0,
                                max(len(dicts[c]) - 1, 0)).astype(np.int32)
                codes[~valid] = 0
                out[c][dest] = codes
                if c in validity:
                    validity[c][dest] = valid
            elif k == ColumnKind.DATE:
                msd = _to_epoch_millis(s)[keep]
                out[c][dest] = np.floor_divide(
                    msd, MILLIS_PER_DAY).astype(np.int32)
            else:
                v = s.to_numpy()[keep]
                if c in validity:
                    # null-free batches surface as int dtype: still valid
                    if np.issubdtype(v.dtype, np.floating):
                        ok = ~np.isnan(v)
                        validity[c][dest] = ok
                        v = np.where(ok, v, 0)
                    else:
                        validity[c][dest] = True
                out[c][dest] = v.astype(out[c].dtype)

    # -- assemble the datasource ----------------------------------------------
    dims: Dict[str, DimColumn] = {}
    mets: Dict[str, MetricColumn] = {}
    time_col = None
    for c in cols:
        if c == time_column:
            time_col = TimeColumn(name=c, days=out["__days__"],
                                  ms_in_day=out["__ms__"])
            continue
        if kinds[c] == ColumnKind.DIM:
            dims[c] = DimColumn(
                name=c, dictionary=np.asarray(dicts[c], dtype=object),
                codes=out[c], validity=validity.get(c))
        else:
            mets[c] = MetricColumn(name=c, values=out[c],
                                   validity=validity.get(c),
                                   kind=kinds[c])
    segments = []
    kept_assignment = []
    for i, (st, cnt) in enumerate(zip(seg_starts.tolist(),
                                      seg_rows.tolist())):
        if cnt <= 0:
            continue
        if time_column is not None:
            lo, hi = int(seg_min_ms[i]), int(seg_max_ms[i])
        else:
            lo = hi = 0
        segments.append(Segment(id=f"{name}_{i:05d}", start_row=int(st),
                                end_row=int(st + cnt), min_millis=lo,
                                max_millis=hi))
        if assignment is not None:
            kept_assignment.append(int(assignment[i]))
    ds = Datasource(name=name, time=time_col, dims=dims, metrics=mets,
                    segments=segments, spatial={},
                    host_assignment=(np.asarray(kept_assignment, np.int32)
                                     if assignment is not None else None),
                    host_id=int(host_id or 0))
    if assignment is not None:
        # inject GLOBAL metric bounds from pass A — local values would
        # give each host a different cost-model selectivity (and thus
        # divergent program shapes: a mesh deadlock)
        for c, m in mets.items():
            if kinds[c] == ColumnKind.DOUBLE:
                m._bounds_cache = (flt_min.get(c), flt_max.get(c))
            else:
                m._bounds_cache = (int_min.get(c), int_max.get(c))
    return ds


def flatten_join_stream(base_path: str, out_path: str, joins,
                        batch_rows: int = 1 << 20,
                        drop_columns=None) -> int:
    """Chunked denormalization: stream the fact table from Parquet, merge
    each chunk against (smaller) in-memory dimension frames, and append to
    an output Parquet file — the full flat frame never materializes.

    ``joins``: list of (dim_df, left_on, right_on). Returns rows written.
    """
    import pyarrow as pa
    import pyarrow.parquet as pq
    writer = None
    n_out = 0
    _, batches = _arrow_batches(base_path, batch_rows)
    try:
        for batch in batches:
            chunk = batch.to_pandas()
            for dim_df, left_on, right_on in joins:
                chunk = chunk.merge(dim_df, left_on=left_on,
                                    right_on=right_on)
            if drop_columns:
                chunk = chunk.drop(columns=[c for c in drop_columns
                                            if c in chunk.columns])
            table = pa.Table.from_pandas(chunk, preserve_index=False)
            if writer is None:
                writer = pq.ParquetWriter(out_path, table.schema)
            writer.write_table(table)
            n_out += len(chunk)
    finally:
        if writer is not None:
            writer.close()
    return n_out
