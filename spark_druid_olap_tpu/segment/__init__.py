from spark_druid_olap_tpu.segment.column import (
    ColumnKind,
    DimColumn,
    MetricColumn,
    TimeColumn,
)
from spark_druid_olap_tpu.segment.store import Datasource, Segment, SegmentStore
from spark_druid_olap_tpu.segment.ingest import ingest_dataframe

__all__ = [
    "ColumnKind",
    "DimColumn",
    "MetricColumn",
    "TimeColumn",
    "Datasource",
    "Segment",
    "SegmentStore",
    "ingest_dataframe",
]
