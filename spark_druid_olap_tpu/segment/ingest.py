"""Batch ingest: pandas/Parquet/CSV -> time-sharded columnar segments.

The in-tree replacement for Druid's batch index task (the reference submits
``quickstart/tpch_index_task.json.template`` through
``DruidOverlordClient.submitTask``, reference
``client/DruidOverlordClient.scala:65-125``; here ingest is a library call —
no overlord, no HTTP).

Pipeline: parse time column to UTC epoch millis -> stable-sort by time ->
build *global sorted dictionaries* per string dimension -> slice the sorted
rows into ~target_rows segments (time-contiguous, so each segment has tight
time bounds for pruning) -> encode columns.
"""

from __future__ import annotations

from typing import Dict, Iterable, Optional

import numpy as np
import pandas as pd

from spark_druid_olap_tpu.segment.column import (
    ColumnKind,
    TimeColumn,
    build_dim_column,
    build_metric_column,
    encode_time_millis,
)
from spark_druid_olap_tpu.segment.store import Datasource, Segment


def _to_epoch_millis(series: pd.Series) -> np.ndarray:
    if pd.api.types.is_datetime64_any_dtype(series):
        dt = series
    elif pd.api.types.is_integer_dtype(series):
        return series.to_numpy(dtype=np.int64)
    else:
        dt = pd.to_datetime(series, utc=True, format="mixed")
    vals = dt.astype("datetime64[ns, UTC]" if getattr(dt.dtype, "tz", None)
                     else "datetime64[ns]")
    return (vals.astype(np.int64) // 1_000_000).to_numpy() \
        if hasattr(vals, "to_numpy") else np.asarray(vals, np.int64) // 1_000_000


def infer_kind(series: pd.Series) -> ColumnKind:
    t = pd.api.types
    if t.is_float_dtype(series):
        return ColumnKind.DOUBLE
    if t.is_integer_dtype(series) or t.is_bool_dtype(series):
        return ColumnKind.LONG
    if t.is_datetime64_any_dtype(series):
        return ColumnKind.DATE
    return ColumnKind.DIM


def ingest_dataframe(
    name: str,
    df: pd.DataFrame,
    time_column: Optional[str] = None,
    dimensions: Optional[Iterable[str]] = None,
    metrics: Optional[Iterable[str]] = None,
    target_rows: int = 1 << 20,
    metric_kinds: Optional[Dict[str, ColumnKind]] = None,
    spatial_dims: Optional[Dict[str, Iterable[str]]] = None,
    drop_columns: Optional[Iterable[str]] = None,
    n_hosts: Optional[int] = None,
    host_id: Optional[int] = None,
) -> Datasource:
    """Ingest a DataFrame as a datasource.

    ``dimensions``/``metrics`` override column-kind inference (a numeric
    column listed in ``dimensions`` is dictionary-encoded as a string dim,
    matching Druid's all-dims-are-strings model when desired).

    ``spatial_dims`` declares spatial dimensions: name -> axis columns
    (numeric, e.g. ``{"pickup": ["pickup_lat", "pickup_lon"]}``), the
    analog of Druid's ingest-time spatialDimensions (reference:
    SpatialDruidDimensionInfo, DruidRelationColumn spatial axes). Axis
    columns stay queryable as plain metrics; conjunctive range predicates
    on them collapse into a rectangular spatial filter with segment-level
    bounding-box pruning.
    """
    df = df.reset_index(drop=True)
    n = len(df)

    order = None
    if time_column is not None:
        millis = _to_epoch_millis(df[time_column])
        order = np.argsort(millis, kind="stable")
        if np.array_equal(order, np.arange(n)):
            order = None        # already time-sorted
        else:
            millis = millis[order]
        days, ms_in_day = encode_time_millis(millis)
        time_col = TimeColumn(name=time_column, days=days, ms_in_day=ms_in_day)
    else:
        millis = np.zeros(n, dtype=np.int64)
        time_col = None

    dim_names = set(dimensions) if dimensions is not None else None
    metric_names = set(metrics) if metrics is not None else None
    metric_kinds = metric_kinds or {}

    dims = {}
    mets = {}

    def encode_one(col):
        series = df[col]
        if order is not None:
            # per-column time-sort take inside the encode pool — far
            # cheaper than materializing a row-reordered DataFrame up
            # front, and it parallelizes
            series = series.take(order).reset_index(drop=True)
        kind = infer_kind(series)
        if dim_names is not None and col in dim_names:
            kind = ColumnKind.DIM
        elif metric_names is not None and col in metric_names:
            kind = metric_kinds.get(col) or (
                kind if kind != ColumnKind.DIM else ColumnKind.DOUBLE)
        elif col in metric_kinds:
            kind = metric_kinds[col]
        if kind == ColumnKind.DIM:
            if dim_names is not None and col in dim_names and \
                    infer_kind(series) != ColumnKind.DIM:
                raw = series.to_numpy(dtype=object)
                raw = np.array([None if v is None else str(v) for v in raw],
                               dtype=object)
                return col, build_dim_column(col, raw)
            # pass the Series: the native path converts via arrow zero-copy
            return col, build_dim_column(col, series)
        if kind == ColumnKind.DATE:
            ms = _to_epoch_millis(series)
            days = np.floor_divide(ms, 86_400_000)
            from spark_druid_olap_tpu.segment.column import (
                MetricColumn, narrow_int_dtype)
            ddt = narrow_int_dtype(int(days.min()), int(days.max())) \
                if len(days) else np.dtype(np.int32)
            return col, MetricColumn(name=col, values=days.astype(ddt),
                                     validity=None, kind=ColumnKind.DATE)
        return col, build_metric_column(col, series.to_numpy(), kind)

    drop = set(drop_columns or ())
    columns = [c for c in df.columns
               if c not in drop
               and not (time_column is not None and c == time_column)]
    # the native encoder releases the GIL, so columns encode in parallel
    from spark_druid_olap_tpu.segment import native as _native
    if _native.load() is not None and len(columns) > 1:
        import concurrent.futures as cf
        with cf.ThreadPoolExecutor(max_workers=min(8, len(columns))) as ex:
            results = list(ex.map(encode_one, columns))
    else:
        results = [encode_one(c) for c in columns]
    from spark_druid_olap_tpu.segment.column import DimColumn
    for col, built in results:
        if isinstance(built, DimColumn):
            dims[col] = built
        else:
            mets[col] = built

    segments = []
    if n > 0:
        n_seg = max(1, -(-n // target_rows))
        per = -(-n // n_seg)
        for i in range(n_seg):
            s, e = i * per, min((i + 1) * per, n)
            if s >= e:
                break
            segments.append(Segment(
                id=f"{name}_{i:05d}", start_row=s, end_row=e,
                min_millis=int(millis[s:e].min()),
                max_millis=int(millis[s:e].max())))

    spatial = {}
    for sname, axes in (spatial_dims or {}).items():
        axes = tuple(axes)
        for ax in axes:
            if ax not in mets:
                raise ValueError(
                    f"spatial dim {sname!r}: axis {ax!r} is not a numeric "
                    f"column of {name!r}")
        spatial[sname] = axes

    ds = Datasource(name=name, time=time_col, dims=dims, metrics=mets,
                    segments=segments, spatial=spatial)
    # ingest-time encoding hints (cheap, O(schema)): candidate codec per
    # column from dictionary cardinality / sortedness, consumed by the
    # checkpoint-time chooser as a starting point. Advisory only — the
    # snapshot writer re-measures the actual arrays before encoding.
    from spark_druid_olap_tpu.encode import chooser as _enc_chooser
    _enc_chooser.annotate_datasource(ds)
    if n_hosts is not None and n_hosts > 1:
        # multi-host partial ingest (in-memory path): every process
        # ingests the same frame deterministically, then keeps only its
        # host's segment rows. The streamed path
        # (stream_ingest.ingest_parquet_stream) never materializes remote
        # rows at all — this one trades that for simplicity at
        # in-memory scale.
        from spark_druid_olap_tpu.parallel.multihost import (
            assign_segments_to_hosts)
        from spark_druid_olap_tpu.segment.store import restrict_to_host
        rows = np.array([s.num_rows for s in segments], np.int64)
        assignment = assign_segments_to_hosts(rows, int(n_hosts))
        ds = restrict_to_host(ds, assignment, int(host_id or 0))
    return ds


def ingest_parquet(name: str, path: str, **kwargs) -> Datasource:
    return ingest_dataframe(name, pd.read_parquet(path), **kwargs)


def ingest_csv(name: str, path: str, **kwargs) -> Datasource:
    read_kwargs = {k: kwargs.pop(k) for k in ("sep", "names", "header")
                   if k in kwargs}
    return ingest_dataframe(name, pd.read_csv(path, **read_kwargs), **kwargs)
