"""Streaming append: add a batch of rows to a registered datasource.

The in-tree replacement for Druid's real-time (streaming) ingest tier —
the reference delegates it to Tranquility/Kafka indexing into realtime
segments that hand off to deep storage; here an append is a library call
producing a NEW :class:`Datasource` value (columns are immutable after
ingest — every cache layer depends on that), registered under the same
name so the store's ingest-version bump invalidates result caches and
marks rollups stale.

Encoding contract with batch ingest (segment/ingest.py):

- Dimension dictionaries stay *global and sorted*: new values merge into
  the dictionary and existing codes are remapped (old -> new positions
  via one searchsorted over the old dictionary). Order-preserving codes
  survive, so bound/range pushdown stays correct.
- Metric dtypes widen monotonically (narrow_int_dtype over the combined
  min/max; wide longs go int64) — appended values can never silently
  wrap.
- Appended rows are time-sorted *within the batch* and become new
  segments (≈ Druid realtime segments): the datasource is no longer
  globally time-sorted, but segment pruning only needs per-segment
  (min,max) bounds, which stay tight per batch.

Edge cases: an empty batch is a no-op (same Datasource object back, no
version bump — nothing changed, caches stay valid); an all-null column
encodes a validity mask with zeroed codes/values, same as batch ingest;
a column missing from the batch appends as all-null.
"""

from __future__ import annotations

import os
from concurrent.futures import ThreadPoolExecutor
from typing import Optional

import numpy as np
import pandas as pd

from spark_druid_olap_tpu.segment.column import (
    ColumnKind,
    DimColumn,
    MetricColumn,
    TimeColumn,
    build_dim_column,
    encode_time_millis,
    narrow_int_dtype,
)
from spark_druid_olap_tpu.segment.ingest import _to_epoch_millis
from spark_druid_olap_tpu.segment.store import Datasource, Segment


def _null_mask(series: pd.Series) -> np.ndarray:
    return series.isna().to_numpy(dtype=bool)


def _take_remap(remap: np.ndarray, codes: np.ndarray,
                dtype: np.dtype) -> np.ndarray:
    """remap[codes] with an empty-dictionary guard (an all-null column
    has an empty dictionary but zeroed codes under its validity mask)."""
    if len(remap) == 0:
        return np.zeros(len(codes), dtype=dtype)
    return remap[codes.astype(np.int64)].astype(dtype)


def _append_dim(old: DimColumn, series: Optional[pd.Series],
                n_new: int) -> DimColumn:
    if series is None:
        new_codes = np.zeros(n_new, dtype=old.codes.dtype)
        new_valid = np.zeros(n_new, dtype=bool)
        dictionary, codes = old.dictionary, old.codes
    else:
        fresh = build_dim_column(old.name, series)
        extra = np.setdiff1d(fresh.dictionary, old.dictionary)
        if len(extra):
            dictionary = np.sort(np.concatenate([old.dictionary, extra]))
            cdt = narrow_int_dtype(0, max(len(dictionary) - 1, 0))
            codes = _take_remap(
                np.searchsorted(dictionary, old.dictionary),
                old.codes, cdt)
            new_codes = _take_remap(
                np.searchsorted(dictionary, fresh.dictionary),
                fresh.codes, cdt)
        else:
            dictionary = old.dictionary
            cdt = old.codes.dtype
            codes = old.codes
            new_codes = _take_remap(
                np.searchsorted(old.dictionary, fresh.dictionary),
                fresh.codes, cdt)
        if fresh.validity is not None:
            new_codes = np.where(fresh.validity, new_codes, 0).astype(
                new_codes.dtype)
        new_valid = fresh.validity if fresh.validity is not None \
            else np.ones(n_new, dtype=bool)
    if old.validity is None and new_valid.all():
        validity = None
    else:
        old_valid = old.validity if old.validity is not None \
            else np.ones(len(codes), dtype=bool)
        validity = np.concatenate([old_valid, new_valid])
    return DimColumn(name=old.name, dictionary=dictionary,
                     codes=np.concatenate([codes, new_codes]),
                     validity=validity)


def _append_metric(old: MetricColumn, series: Optional[pd.Series],
                   n_new: int) -> MetricColumn:
    if series is None:
        new_vals = np.zeros(n_new, dtype=old.values.dtype)
        new_valid = np.zeros(n_new, dtype=bool)
    elif old.kind == ColumnKind.DATE:
        invalid = _null_mask(series)
        ms = _to_epoch_millis(series.fillna(pd.Timestamp(0)))
        new_vals = np.floor_divide(ms, 86_400_000)
        new_vals = np.where(invalid, 0, new_vals)
        new_valid = ~invalid
    else:
        raw = series.to_numpy()
        if raw.dtype == object:
            new_valid = ~_null_mask(series)
            raw = np.where(new_valid, raw, 0)
        elif np.issubdtype(raw.dtype, np.floating):
            new_valid = ~np.isnan(raw)
            raw = np.where(new_valid, raw, 0)
        else:
            new_valid = np.ones(n_new, dtype=bool)
        new_vals = raw
    if old.kind == ColumnKind.DOUBLE:
        dtype = old.values.dtype  # float32 end-to-end
    else:
        new_valid = np.asarray(new_valid, dtype=bool)
        lows, highs = [], []
        if new_valid.any():
            nv = np.asarray(new_vals)[new_valid]
            lows.append(int(nv.min()))
            highs.append(int(nv.max()))
        olo, ohi = old.min, old.max
        if olo is not None:
            lows.append(int(olo))
            highs.append(int(ohi))
        lo = min(lows, default=0)
        hi = max(highs, default=0)
        ii = np.iinfo(np.int32)
        dtype = np.dtype(np.int64) if (lo < ii.min or hi > ii.max) \
            else narrow_int_dtype(lo, hi)
    values = np.concatenate([old.values.astype(dtype, copy=False),
                             np.asarray(new_vals).astype(dtype)])
    if old.validity is None and new_valid.all():
        validity = None
    else:
        old_valid = old.validity if old.validity is not None \
            else np.ones(len(old.values), dtype=bool)
        validity = np.concatenate([old_valid, new_valid])
    return MetricColumn(name=old.name, values=values, validity=validity,
                        kind=old.kind)


# below this many batch rows a thread pool costs more than it saves
_PARALLEL_MIN_ROWS = 2048


def _build_columns(ds: Datasource, df: pd.DataFrame, n_new: int,
                   parallel: bool):
    """Build the appended dim/metric columns, optionally across a thread
    pool. Each column's dictionary-union + order-preserving remap is
    independent of every other column's, so running them concurrently is
    bit-identical to the serial comprehension — numpy's sort/searchsorted
    kernels release the GIL, which is where the parallel win comes from
    on wide schemas."""
    dim_items = list(ds.dims.items())
    met_items = list(ds.metrics.items())
    n_cols = len(dim_items) + len(met_items)
    if (not parallel or n_cols < 2 or n_new < _PARALLEL_MIN_ROWS):
        dims = {k: _append_dim(d, df[k] if k in df.columns else None,
                               n_new)
                for k, d in dim_items}
        mets = {k: _append_metric(m, df[k] if k in df.columns else None,
                                  n_new)
                for k, m in met_items}
        return dims, mets
    workers = min(n_cols, max(2, (os.cpu_count() or 4) - 1), 8)
    with ThreadPoolExecutor(max_workers=workers,
                            thread_name_prefix="sdot-append") as pool:
        dim_futs = [(k, pool.submit(
            _append_dim, d, df[k] if k in df.columns else None, n_new))
            for k, d in dim_items]
        met_futs = [(k, pool.submit(
            _append_metric, m, df[k] if k in df.columns else None, n_new))
            for k, m in met_items]
        # .result() re-raises a build rejection from any column exactly
        # like the serial path would (the pool context manager joins the
        # rest before the exception propagates)
        dims = {k: f.result() for k, f in dim_futs}
        mets = {k: f.result() for k, f in met_futs}
    return dims, mets


def append_dataframe(ds: Datasource, df: pd.DataFrame,
                     target_rows: int = 1 << 20,
                     parallel: bool = False) -> Datasource:
    """A new :class:`Datasource` with ``df``'s rows appended as fresh
    segments. ``ds`` is untouched (immutable-columns contract)."""
    ds.require_complete("stream append")
    df = df.reset_index(drop=True)
    n_new = len(df)
    if n_new == 0:
        return ds

    known = set(ds.column_names())
    extra = [c for c in df.columns if c not in known]
    if extra:
        raise ValueError(
            f"append to {ds.name!r}: columns {extra} are not in the "
            f"datasource schema (schema evolution needs a re-ingest)")

    if ds.time is not None:
        if ds.time.name not in df.columns:
            raise ValueError(
                f"append to {ds.name!r}: batch is missing the time "
                f"column {ds.time.name!r}")
        millis = _to_epoch_millis(df[ds.time.name])
        order = np.argsort(millis, kind="stable")
        if not np.array_equal(order, np.arange(n_new)):
            df = df.take(order).reset_index(drop=True)
            millis = millis[order]
        days, ms_in_day = encode_time_millis(millis)
        time_col = TimeColumn(
            name=ds.time.name,
            days=np.concatenate([ds.time.days, days]),
            ms_in_day=np.concatenate([ds.time.ms_in_day, ms_in_day]))
    else:
        millis = np.zeros(n_new, dtype=np.int64)
        time_col = None

    dims, mets = _build_columns(ds, df, n_new, parallel)

    base_row = ds.num_rows
    seg_id0 = len(ds.segments)
    segments = list(ds.segments)
    n_seg = max(1, -(-n_new // max(1, int(target_rows))))
    per = -(-n_new // n_seg)
    for i in range(n_seg):
        s, e = i * per, min((i + 1) * per, n_new)
        if s >= e:
            break
        segments.append(Segment(
            id=f"{ds.name}_{seg_id0 + i:05d}",
            start_row=base_row + s, end_row=base_row + e,
            min_millis=int(millis[s:e].min()),
            max_millis=int(millis[s:e].max())))

    out = Datasource(name=ds.name, time=time_col, dims=dims,
                     metrics=mets, segments=segments,
                     spatial=dict(ds.spatial))
    # re-derive encoding hints rather than carrying the parent's: an
    # append can widen dictionaries or break a column's sortedness, so
    # stale hints would steer the checkpoint-time chooser wrong. Cheap —
    # O(schema), not O(rows).
    from spark_druid_olap_tpu.encode import chooser as _enc_chooser
    _enc_chooser.annotate_datasource(out)
    return out


# JSON-serializable keys of the ingest kwargs a WAL create record carries
# (ColumnKind values serialize as their enum value strings).
_WAL_KWARG_KEYS = ("time_column", "dimensions", "metrics", "target_rows",
                   "metric_kinds", "spatial_dims", "drop_columns")


def wal_kwargs_to_dict(kwargs: dict) -> dict:
    out = {}
    for k in _WAL_KWARG_KEYS:
        v = kwargs.get(k)
        if v is None:
            continue
        if k == "metric_kinds":
            v = {c: kk.value for c, kk in v.items()}
        elif k in ("dimensions", "metrics", "drop_columns"):
            v = list(v)
        elif k == "spatial_dims":
            v = {s: list(a) for s, a in v.items()}
        out[k] = v
    return out


def wal_kwargs_from_dict(d: dict) -> dict:
    out = dict(d)
    if "metric_kinds" in out:
        out["metric_kinds"] = {c: ColumnKind(v)
                               for c, v in out["metric_kinds"].items()}
    return out


def apply_stream_ingest(ctx, name: str, df: pd.DataFrame,
                        kwargs: dict) -> Datasource:
    """In-memory half of a stream_ingest: create on first batch, append
    after. The caller (Context / PersistManager) owns durability."""
    from spark_druid_olap_tpu.segment.ingest import ingest_dataframe
    existing = ctx.store._datasources.get(name)
    if existing is None:
        ds = ingest_dataframe(name, df, **kwargs)
        ctx.store.register(ds)
        return ds
    if len(df) == 0:
        return existing          # no-op: no version bump, caches stay valid
    ds = append_dataframe(existing, df,
                          target_rows=int(kwargs.get("target_rows")
                                          or (1 << 20)))
    ctx.store.register(ds)
    return ds
