"""Datasource / segment store — the in-tree replacement for Druid's segment
tier.

The reference's contract with Druid segments (time-partitioned columnar shards
with per-column metadata: ``DruidSegmentInfo``
``metadata/DruidMetadataCache.scala:64-76``, ``MetadataResponse``
``client/DruidMessages.scala:22-57``) is re-seamed for TPU:

- A **datasource** holds its columns time-sorted end-to-end; a **segment** is a
  contiguous row-range over that order (≈ a Druid time-chunk shard).
- The executable layout is the *stacked* form: each column materialized as a
  ``[n_segments, padded_rows]`` tensor. One compiled XLA program scans every
  segment (segment axis = grid/vmap axis), and the same axis is what shards
  across a TPU mesh (≈ one Spark task per historical×segment-group,
  ``DruidRDD.getPartitions:244-277`` — here one program instance per chip).
- Per-segment (min,max) time bounds support host-side interval pruning
  (≈ ``QueryIntervals`` + segment assignment).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

import numpy as np

from spark_druid_olap_tpu.segment.column import (
    ColumnKind,
    DimColumn,
    MetricColumn,
    TimeColumn,
    MILLIS_PER_DAY,
)

ROW_ALIGN = 1024  # pad segment rows to a multiple of this (8 sublanes x 128 lanes)


@dataclasses.dataclass
class Segment:
    """Metadata for one time-sharded segment (a row-range of the datasource).

    ≈ ``DruidSegmentInfo`` (reference ``DruidMetadataCache.scala:64-76``).
    """

    id: str
    start_row: int
    end_row: int
    min_millis: int
    max_millis: int

    @property
    def num_rows(self) -> int:
        return self.end_row - self.start_row


class Datasource:
    """A registered, ingested datasource: time-sorted columns + segment map +
    lazily-built stacked tensors."""

    def __init__(self, name: str, time: Optional[TimeColumn],
                 dims: Dict[str, DimColumn], metrics: Dict[str, MetricColumn],
                 segments: List[Segment],
                 spatial: Optional[Dict[str, Tuple[str, ...]]] = None,
                 host_assignment=None, host_id: int = 0):
        self.name = name
        self.time = time
        self.dims = dims
        self.metrics = metrics
        self.segments = segments
        # spatial dim name -> numeric axis columns (≈ the reference's
        # spatial-index column map, DruidRelationColumn spatial axes)
        self.spatial: Dict[str, Tuple[str, ...]] = {
            k: tuple(v) for k, v in (spatial or {}).items()}
        self._stacked_cache: Dict[str, np.ndarray] = {}
        self._bounds_cache: Dict[str, Tuple[np.ndarray, np.ndarray]] = {}
        # multi-host partial store (parallel/multihost.py): ``segments``
        # stays the GLOBAL metadata list (planning must be deterministic
        # across processes); column arrays cover only rows of the segments
        # assigned to ``host_id``, concatenated in ascending global order.
        # ``host_assignment`` is the global [S] -> host map (≈ Druid's
        # segment->historical assignment, DruidMetadataCache.scala:105-148).
        self.host_id = int(host_id)
        if host_assignment is None:
            self.host_assignment = None
            self.local_seg_ids = None
            self._local_pos = None
        else:
            self.host_assignment = np.asarray(host_assignment, np.int32)
            if len(self.host_assignment) != len(segments):
                raise ValueError("host_assignment length != num segments")
            self.local_seg_ids = np.nonzero(
                self.host_assignment == self.host_id)[0].astype(np.int64)
            pos = np.full(len(segments), -1, dtype=np.int64)
            pos[self.local_seg_ids] = np.arange(len(self.local_seg_ids))
            self._local_pos = pos
        # padded_rows from GLOBAL metadata — identical on every host
        n = max((s.num_rows for s in segments), default=0)
        self.padded_rows = max(ROW_ALIGN, -(-n // ROW_ALIGN) * ROW_ALIGN)

    # -- multi-host partial stores -------------------------------------------
    @property
    def is_partial(self) -> bool:
        """True when this process holds only its host's segment data."""
        return self.local_seg_ids is not None

    def require_complete(self, what: str = "this operation") -> None:
        """Host-tier paths materialize full columns; on a partial store
        that would silently compute over ONE host's rows."""
        if self.is_partial:
            raise RuntimeError(
                f"{what} requires the complete datasource, but "
                f"{self.name!r} holds only host {self.host_id}'s "
                f"{len(self.local_seg_ids)}/{self.num_segments} segments "
                f"(multi-host partial store)")

    @property
    def local_num_rows(self) -> int:
        """Rows THIS process holds (== num_rows on a complete store)."""
        if not self.is_partial:
            return self.num_rows
        return int(sum(self.segments[int(i)].num_rows
                       for i in self.local_seg_ids))

    def local_to_global_rows(self) -> np.ndarray:
        """[local_num_rows] -> global row id (ascending; local column
        arrays are the local segments' rows in ascending global order)."""
        if not self.is_partial:
            return np.arange(self.num_rows, dtype=np.int64)
        parts = [np.arange(self.segments[int(i)].start_row,
                           self.segments[int(i)].end_row, dtype=np.int64)
                 for i in self.local_seg_ids]
        return np.concatenate(parts) if parts \
            else np.empty(0, dtype=np.int64)

    def global_to_local_rows(self, gids: np.ndarray) -> np.ndarray:
        """Global row ids (all owned by this host) -> local row offsets."""
        gids = np.asarray(gids, dtype=np.int64)
        if not self.is_partial:
            return gids
        starts = np.array([s.start_row for s in self.segments],
                          dtype=np.int64)
        seg_of = np.searchsorted(starts, gids, side="right") - 1
        local_rows = np.array(
            [self.segments[int(i)].num_rows for i in self.local_seg_ids],
            dtype=np.int64)
        base = np.concatenate([[0], np.cumsum(local_rows)[:-1]]) \
            if len(local_rows) else np.empty(0, np.int64)
        lpos = self._local_pos[seg_of]
        if (lpos < 0).any():
            raise ValueError("global_to_local_rows: row not owned by "
                             f"host {self.host_id}")
        return base[lpos] + (gids - starts[seg_of])

    def owner_of_rows(self, gids: np.ndarray) -> np.ndarray:
        """Global row ids -> owning host id (via segment assignment)."""
        gids = np.asarray(gids, dtype=np.int64)
        starts = np.array([s.start_row for s in self.segments],
                          dtype=np.int64)
        seg_of = np.searchsorted(starts, gids, side="right") - 1
        if self.host_assignment is None:
            return np.zeros(len(gids), dtype=np.int32)
        return self.host_assignment[seg_of]

    def complete(self, columns=None, page_bytes=None) -> "Datasource":
        """A COMPLETE view of this datasource: itself when already
        complete; on a multi-host partial store, a clone whose column
        arrays are assembled by a cross-process exchange
        (multihost.exchange_block) — the safety valve that lets the host
        fallback tier serve ANY query shape on a partial store (≈ the
        reference's Spark-side fallback scan pulling rows off the
        historicals, ``DruidRelation.scala:111``). Engine paths never
        call this.

        ``columns`` prunes the gather to the NEEDED columns (plus the
        time column, always) — O(needed), not O(table width); gathered
        arrays are cached per column so repeated host-tier statements
        exchange each column once. Gathers run in SORTED column order:
        every process must issue the identical collective sequence, and
        callers' set-typed column collections must never dictate it.

        ``page_bytes`` bounds the staging footprint of ONE exchange page
        (sdot.host.gather.page.bytes at the session layer); page row
        counts derive from the column's per-row footprint plus GLOBAL
        metadata, so every process pages identically."""
        if not self.is_partial:
            return self
        from spark_druid_olap_tpu.parallel import multihost as MH
        if not MH.is_multihost():
            # single-process partial store (tests): nothing to gather from
            self.require_complete("cross-host gather")
        import dataclasses as _dc
        sel_dims = [k for k in self.dims
                    if columns is None or k in columns]
        sel_mets = [k for k in self.metrics
                    if columns is None or k in columns]

        cache = getattr(self, "_gathered_cols", None)
        if cache is None:
            from spark_druid_olap_tpu.cache.result_cache import ByteBudgetLRU
            cache = self._gathered_cols = \
                ByteBudgetLRU(GATHERED_CACHE_MAX_BYTES)
        n_rows = self.num_rows

        def _plan():
            """(gids, n_hosts, max_local): per-host global-row write
            targets + the paging denominator. O(num_rows) to build —
            computed on the FIRST cache miss only (a cache-hit
            complete() call must not pay it; the SF100 host tier calls
            complete() per column per statement)."""
            p = getattr(self, "_gather_plan", None)
            if p is not None:
                return p
            assignment = self.host_assignment
            n_hosts = (int(assignment.max()) + 1) if len(assignment) \
                else 1
            ranges = {h: [(self.segments[int(i)].start_row,
                           self.segments[int(i)].end_row)
                          for i in np.nonzero(assignment == h)[0]]
                      for h in range(n_hosts)}
            # per-host global row ids, ascending (the write targets)
            gids = {h: (np.concatenate(
                [np.arange(s, e, dtype=np.int64) for s, e in ranges[h]])
                if ranges[h] else np.empty(0, np.int64))
                for h in range(n_hosts)}
            # max local rows over hosts comes from GLOBAL metadata —
            # identical on every process, or the collectives would
            # mismatch.
            max_local = max((int(g.shape[0]) for g in gids.values()),
                            default=0)
            p = self._gather_plan = (gids, n_hosts, max_local)
            return p

        budget = int(page_bytes) if page_bytes \
            else DEFAULT_GATHER_PAGE_BYTES

        def _gather(arr):
            if arr is None:
                return None
            gids, n_hosts, max_local = _plan()
            # byte-budgeted paging: the collective stages data through
            # device memory, so a whole-column gather of a large store
            # would blow HBM. Page rows derive from the column's per-row
            # footprint (dtype + trailing dims are schema, identical on
            # every host), NOT a fixed row count — a fixed 4M-row chunk
            # lets one f64 column stage 8x the bytes an i8 validity does.
            row_bytes = int(arr.dtype.itemsize) * int(
                np.prod(arr.shape[1:], dtype=np.int64))
            page = max(1, budget // max(1, row_bytes))
            n_pages = max(1, -(-max_local // page))
            out = np.empty((n_rows,) + arr.shape[1:], arr.dtype)
            offs = {h: 0 for h in range(n_hosts)}
            for c in range(n_pages):
                blocks = MH.exchange_block(arr[c * page: (c + 1) * page])
                for h, blk in enumerate(blocks):
                    if len(blk) == 0:
                        continue
                    tgt = gids[h][offs[h]: offs[h] + len(blk)]
                    out[tgt] = blk
                    offs[h] += len(blk)
            return out

        def col(name, build):
            hit = cache.get(name)
            if hit is None:
                hit = build()
                cache.put(name, hit)
            return hit

        time = None
        if self.time is not None:
            days, ms = col("\x00time", lambda: (
                _gather(self.time.days), _gather(self.time.ms_in_day)))
            time = _dc.replace(self.time, days=days, ms_in_day=ms)
        dims = {}
        mets = {}
        for k in sorted(sel_dims + sel_mets):
            if k in self.dims:
                d = self.dims[k]
                codes, valid = col(k, lambda d=d: (
                    _gather(d.codes), _gather(d.validity)))
                dims[k] = _dc.replace(d, codes=codes, validity=valid)
            else:
                m = self.metrics[k]
                gmin, gmax = m.min, m.max
                values, valid = col(k, lambda m=m: (
                    _gather(m.values), _gather(m.validity)))
                mm = _dc.replace(m, values=values, validity=valid)
                mm._bounds_cache = (gmin, gmax)
                mets[k] = mm
        # dict order mirrors the source store (column_names contract)
        dims = {k: dims[k] for k in self.dims if k in dims}
        mets = {k: mets[k] for k in self.metrics if k in mets}
        ds = Datasource(name=self.name, time=time, dims=dims,
                        metrics=mets, segments=list(self.segments),
                        spatial=dict(self.spatial))
        ds.gathered_from_partial = True
        return ds

    # -- basic shape ----------------------------------------------------------
    @property
    def num_rows(self) -> int:
        return sum(s.num_rows for s in self.segments)

    @property
    def num_segments(self) -> int:
        return len(self.segments)

    @property
    def time_column(self) -> Optional[str]:
        return self.time.name if self.time is not None else None

    def interval(self) -> Tuple[int, int]:
        """(min,max+1ms) millis over all segments (≈ datasource intervals)."""
        if not self.segments:
            return (0, 0)
        return (min(s.min_millis for s in self.segments),
                max(s.max_millis for s in self.segments) + 1)

    def column_names(self) -> List[str]:
        out = list(self.dims) + list(self.metrics)
        if self.time is not None:
            out.append(self.time.name)
        return out

    def column_kind(self, name: str) -> ColumnKind:
        if self.time is not None and name == self.time.name:
            return ColumnKind.TIME
        if name in self.dims:
            return ColumnKind.DIM
        if name in self.metrics:
            return self.metrics[name].kind
        raise KeyError(f"{self.name} has no column {name!r}")

    def cardinality(self, name: str) -> Optional[int]:
        """Exact dictionary cardinality for dims; None for metrics (estimated
        upstream). ≈ ``ColumnDetails.cardinality``."""
        if name in self.dims:
            return self.dims[name].cardinality
        if self.time is not None and name == self.time.name:
            lo, hi = self.interval()
            return max(1, (hi - lo) // MILLIS_PER_DAY + 1)
        return None

    def metadata(self) -> dict:
        """Druid segmentMetadata-equivalent summary (reference:
        ``MetadataResponse`` fields)."""
        cols = {}
        # metadata accessors, not raw arrays: on a tiered store a
        # .nbytes / validity peek through the array property would fault
        # every column into the hot set just to answer /metadata
        for d in self.dims.values():
            cols[d.name] = {"type": "STRING", "cardinality": d.cardinality,
                            "size": d.data_nbytes(),
                            "hasNulls": d.has_nulls()}
        for m in self.metrics.values():
            cols[m.name] = {"type": "LONG" if m.kind == ColumnKind.LONG else "DOUBLE",
                            "cardinality": None, "size": m.data_nbytes(),
                            "hasNulls": m.has_nulls()}
        if self.time is not None:
            cols[self.time.name] = {"type": "TIME", "cardinality": None,
                                    "size": self.time.footprint_nbytes(),
                                    "hasNulls": False}
        return {"datasource": self.name, "numRows": self.num_rows,
                "numSegments": self.num_segments, "interval": self.interval(),
                "columns": cols}

    # -- stacked tensors ------------------------------------------------------
    def _boundaries(self):
        """Per-stacked-row (start, end) into the column arrays. Complete
        store: global row ranges, one per segment. Partial store: LOCAL
        row ranges (columns hold only local rows), one per local segment
        — derived from global segment sizes, so the layout contract with
        the per-host ingest is metadata-only."""
        if not self.is_partial:
            return [(s.start_row, s.end_row) for s in self.segments]
        sizes = np.asarray([self.segments[int(i)].num_rows
                            for i in self.local_seg_ids], dtype=np.int64)
        starts = np.concatenate([[0], np.cumsum(sizes)[:-1]]) \
            if len(sizes) else np.zeros(0, np.int64)
        return [(int(s), int(s + n)) for s, n in zip(starts, sizes)]

    def _stack(self, values: np.ndarray, fill=0) -> np.ndarray:
        bounds = self._boundaries()
        out = np.full((len(bounds), self.padded_rows), fill,
                      dtype=values.dtype)
        for i, (s, e) in enumerate(bounds):
            out[i, : e - s] = values[s:e]
        return out

    def stacked(self, name: str) -> np.ndarray:
        """Stacked [S, R] tensor for a column (codes for dims, values for
        metrics, days for time; see ``stacked_time_ms`` for the ms part)."""
        hit = self._stacked_cache.get(name)
        if hit is not None:
            return hit
        if name in self.dims:
            arr = self._stack(self.dims[name].codes)
        elif name in self.metrics:
            arr = self._stack(self.metrics[name].values)
        elif self.time is not None and name == self.time.name:
            arr = self._stack(self.time.days)
        else:
            raise KeyError(f"{self.name} has no column {name!r}")
        self._stacked_cache[name] = arr
        return arr

    def stacked_time_ms(self) -> np.ndarray:
        key = "__time_ms__"
        if key not in self._stacked_cache:
            assert self.time is not None
            self._stacked_cache[key] = self._stack(self.time.ms_in_day)
        return self._stacked_cache[key]

    def stacked_row_validity(self) -> np.ndarray:
        """[S, R] bool: True for real rows, False for padding (S = local
        segments on a partial store)."""
        key = "__rows__"
        if key not in self._stacked_cache:
            bounds = self._boundaries()
            out = np.zeros((len(bounds), self.padded_rows), dtype=bool)
            for i, (s, e) in enumerate(bounds):
                out[i, : e - s] = True
            self._stacked_cache[key] = out
        return self._stacked_cache[key]

    def stacked_null_validity(self, name: str) -> Optional[np.ndarray]:
        """[S, R] bool column-null validity, or None when the column has no
        nulls (padding rows read as invalid)."""
        col = self.dims.get(name) or self.metrics.get(name)
        # has_nulls() is metadata: checking ``col.validity is None`` on a
        # tiered column would fault the whole validity array just to
        # learn the column has no nulls
        if col is None or not col.has_nulls():
            return None
        key = f"__nulls__{name}"
        if key not in self._stacked_cache:
            self._stacked_cache[key] = self._stack(col.validity)
        return self._stacked_cache[key]

    def segment_time_bounds(self) -> Tuple[np.ndarray, np.ndarray]:
        """([S] min_millis, [S] max_millis) for host-side interval pruning."""
        mins = np.array([s.min_millis for s in self.segments], dtype=np.int64)
        maxs = np.array([s.max_millis for s in self.segments], dtype=np.int64)
        return mins, maxs

    def segment_metric_bounds(self, name: str):
        """([S] min, [S] max) of a numeric metric column per segment (NaNs /
        null rows ignored) — zone-map pruning metadata, and the bounding-box
        analog of the reference's spatial index."""
        self.require_complete("zone-map bounds")
        hit = self._bounds_cache.get(name)
        if hit is not None:
            return hit
        col = self.metrics[name]
        vals = col.values.astype(np.float64, copy=False)
        mins = np.full(self.num_segments, np.inf)
        maxs = np.full(self.num_segments, -np.inf)
        for i, (s, e) in enumerate(self._boundaries()):
            v = vals[s:e]
            if col.validity is not None:
                v = v[col.validity[s:e]]
            v = v[~np.isnan(v)] if v.dtype.kind == "f" else v
            if len(v):
                mins[i] = v.min()
                maxs[i] = v.max()
        self._bounds_cache[name] = (mins, maxs)
        return mins, maxs

    def prune_segments(self, intervals, filter_spec=None) -> np.ndarray:
        """Indices of segments overlapping any [lo, hi) milli-interval AND
        not provably excluded by the filter's numeric/spatial bounds.

        ≈ interval-based segment selection (reference ``QueryIntervals`` +
        ``DruidMetadataCache.assignHistoricalServers:276``); the filter part
        is zone-map pruning over per-segment column bounds (the scan-era
        analog of Druid's spatial R-tree / bitmap indexes). Conservative:
        only top-level AND conjuncts prune; the full row-level filter still
        runs on device."""
        if intervals is None:
            keep = np.ones(self.num_segments, dtype=bool)
        else:
            mins, maxs = self.segment_time_bounds()
            keep = np.zeros(self.num_segments, dtype=bool)
            for lo, hi in intervals:
                keep |= (maxs >= lo) & (mins < hi)
        if filter_spec is not None and keep.any() and not self.is_partial:
            # zone maps read column data — on a partial store they would
            # differ per process, and a divergent pruning decision changes
            # program shapes (mesh deadlock). Time pruning above is
            # metadata-only and stays; the row-level filter still runs.
            keep &= self._filter_keep_mask(filter_spec)
        return np.nonzero(keep)[0]

    def _filter_keep_mask(self, f) -> np.ndarray:
        from spark_druid_olap_tpu.ir import spec as S
        ones = np.ones(self.num_segments, dtype=bool)
        if isinstance(f, S.LogicalFilter) and f.op == "and":
            keep = ones
            for x in f.fields:
                keep = keep & self._filter_keep_mask(x)
            return keep
        if isinstance(f, S.SpatialFilter):
            keep = ones
            for ax, lo, hi in zip(f.axes, f.min_coords, f.max_coords):
                if ax not in self.metrics:
                    continue
                mins, maxs = self.segment_metric_bounds(ax)
                keep = keep & (maxs >= lo) & (mins <= hi)
            return keep
        if isinstance(f, S.BoundFilter) and f.dimension in self.metrics \
                and self.metrics[f.dimension].kind.name in ("LONG", "DOUBLE"):
            try:
                mins, maxs = self.segment_metric_bounds(f.dimension)
                keep = ones
                if f.lower is not None:
                    lo = float(f.lower)
                    keep = keep & ((maxs > lo) if f.lower_strict
                                   else (maxs >= lo))
                if f.upper is not None:
                    hi = float(f.upper)
                    keep = keep & ((mins < hi) if f.upper_strict
                                   else (mins <= hi))
                return keep
            except (TypeError, ValueError):
                return ones
        return ones


def restrict_to_host(ds: Datasource, host_assignment,
                     host_id: int) -> Datasource:
    """Partial copy of a complete datasource holding only ``host_id``'s
    segment rows (the in-memory analog of per-host streamed ingest — each
    test process ingests the same frame deterministically, then drops the
    rows it doesn't own). Metric min/max bounds are computed GLOBALLY
    before slicing and injected, so cost-model selectivity stays identical
    on every process."""
    import dataclasses as _dc

    assignment = np.asarray(host_assignment, np.int32)
    local = np.nonzero(assignment == int(host_id))[0]
    ranges = [(ds.segments[int(i)].start_row, ds.segments[int(i)].end_row)
              for i in local]

    def _slice(arr):
        if arr is None or not ranges:
            return None if arr is None else arr[:0]
        return np.concatenate([arr[s:e] for s, e in ranges])

    dims = {}
    for k, d in ds.dims.items():
        dims[k] = _dc.replace(d, codes=_slice(d.codes),
                              validity=_slice(d.validity))
    mets = {}
    for k, m in ds.metrics.items():
        gmin, gmax = m.min, m.max            # global, pre-slice
        mm = _dc.replace(m, values=_slice(m.values),
                         validity=_slice(m.validity))
        mm._bounds_cache = (gmin, gmax)
        mets[k] = mm
    time = None
    if ds.time is not None:
        time = _dc.replace(ds.time, days=_slice(ds.time.days),
                           ms_in_day=_slice(ds.time.ms_in_day))
    return Datasource(name=ds.name, time=time, dims=dims, metrics=mets,
                      segments=list(ds.segments),
                      spatial=dict(ds.spatial),
                      host_assignment=assignment, host_id=int(host_id))


def slice_segments(ds: Datasource, segment_indexes,
                   name: Optional[str] = None) -> Datasource:
    """COMPLETE datasource holding only the given segments' rows,
    renumbered to contiguous row ranges (ascending source order).

    Unlike ``restrict_to_host`` the result is a normal complete store:
    a cluster historical registers one slice per assigned shard
    (cluster/historical.py) and every engine path — device tiers, host
    fallback, shared-scan — serves it as an ordinary datasource. Dim
    dictionaries are shared with the source; codes keep referencing the
    full dictionary, so decode stays exact on every node. Metric bounds
    are NOT inherited: a shard's local min/max is correct for its own
    rows and recomputes lazily."""
    import dataclasses as _dc

    ds.require_complete("segment slicing")
    ids = sorted(int(i) for i in segment_indexes)
    ranges = [(ds.segments[i].start_row, ds.segments[i].end_row)
              for i in ids]

    def _slice(arr):
        if arr is None:
            return None
        if not ranges:
            return arr[:0]
        return np.concatenate([arr[s:e] for s, e in ranges])

    dims = {}
    for k, d in ds.dims.items():
        dims[k] = _dc.replace(d, codes=_slice(d.codes),
                              validity=_slice(d.validity))
    mets = {}
    for k, m in ds.metrics.items():
        mets[k] = _dc.replace(m, values=_slice(m.values),
                              validity=_slice(m.validity))
    time = None
    if ds.time is not None:
        time = _dc.replace(ds.time, days=_slice(ds.time.days),
                           ms_in_day=_slice(ds.time.ms_in_day))
    segs, row = [], 0
    for i in ids:
        s = ds.segments[i]
        n = s.end_row - s.start_row
        segs.append(Segment(s.id, row, row + n, s.min_millis, s.max_millis))
        row += n
    return Datasource(name=name or ds.name, time=time, dims=dims,
                      metrics=mets, segments=segs, spatial=dict(ds.spatial))


# Byte bound on a partial datasource's gathered-column cache (tuples of
# host arrays rebuilt from the cross-host exchange on miss). Keeps the
# host tier's residual-gather working set from growing without bound as
# statements touch ever more columns of a large partial store.
GATHERED_CACHE_MAX_BYTES = 4 << 30

# Fallback staging budget for one paged gather when the caller doesn't
# thread sdot.host.gather.page.bytes through (engine-internal callers
# gathering a single small column).
DEFAULT_GATHER_PAGE_BYTES = 32 << 20


class SegmentStore:
    """Registry of ingested datasources (≈ ``DruidMetadataCache`` — the
    driver-side singleton cache of datasource schemas,
    ``DruidMetadataCache.scala:176-271`` — minus the remote cluster I/O: the
    segments live in-process)."""

    def __init__(self):
        self._datasources: Dict[str, Datasource] = {}
        self.version = 0      # bumped on any change; invalidates caches
        # per-datasource ingest version: the store version at the last
        # register/drop of that name. Result-cache keys fold it in, so a
        # re-ingest or stream append structurally invalidates only that
        # datasource's cached answers.
        self._versions: Dict[str, int] = {}
        # change listeners (persist/ dirty tracking): called as
        # cb(event, name) with event in register|drop|clear|restore
        self._listeners = []
        # per-datasource recovery provenance set by persist recovery
        # (source, snapshot version, checksum-verify ms); surfaced as
        # stats['persist'] on queries over a recovered datasource
        self.recovery_info: Dict[str, dict] = {}

    def add_listener(self, cb) -> None:
        self._listeners.append(cb)

    def _notify(self, event: str, name) -> None:
        for cb in self._listeners:
            try:
                cb(event, name)
            except Exception:  # noqa: BLE001 — a listener never breaks
                pass           # the store

    def register(self, ds: Datasource) -> None:
        self._datasources[ds.name] = ds
        self.version += 1
        self._versions[ds.name] = self.version
        self._notify("register", ds.name)

    def restore(self, ds: Datasource, ingest_version: int) -> None:
        """Recovery-path registration: install ``ds`` under its EXACT
        pre-crash ingest version instead of bumping. Result-cache keys
        and rollup built_version freshness compare against these
        numbers, so restoring them verbatim is what makes staleness
        semantics hold across restarts (persist/manager.py)."""
        self._datasources[ds.name] = ds
        self._versions[ds.name] = int(ingest_version)
        self.version = max(self.version, int(ingest_version))
        self._notify("restore", ds.name)

    def get(self, name: str) -> Datasource:
        if name not in self._datasources:
            raise KeyError(f"unknown datasource {name!r}; registered: "
                           f"{sorted(self._datasources)}")
        return self._datasources[name]

    def drop(self, name: str) -> None:
        self._datasources.pop(name, None)
        self.version += 1
        self._versions[name] = self.version
        self.recovery_info.pop(name, None)
        self._notify("drop", name)

    def names(self) -> List[str]:
        return sorted(self._datasources)

    def datasource_version(self, name: str) -> int:
        """Monotone ingest version of one datasource (0 = never seen)."""
        return self._versions.get(name, 0)

    def clear(self) -> None:
        """≈ ``CLEAR DRUID CACHE`` (reference
        ``DruidMetadataCommands.scala:30-47``)."""
        self._datasources.clear()
        self.version += 1
        self._versions.clear()
        self.recovery_info.clear()
        self._notify("clear", None)
