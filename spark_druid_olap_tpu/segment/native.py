"""Loader + Python bindings for the native ingest kernels.

Builds ``native/segment_encoder.cpp`` into a CPython extension on first use
(g++, cached as a .so beside the source; rebuilt when the source is newer)
and exposes :func:`encode_strings` — the fast path of
``segment.column.build_dim_column``. Arrow handles object->buffer conversion
(C++ inside pyarrow); our extension does the sort/unique/encode with the GIL
released, so the ingest thread pool encodes columns in parallel.

When the toolchain or pyarrow is unavailable, callers fall back to the numpy
path (same results, slower) — mirroring how the framework gates every
optional fast path.
"""

from __future__ import annotations

import logging
import os
import subprocess
import sys
import sysconfig
import threading
from typing import Optional, Tuple

import numpy as np

log = logging.getLogger("sdot.native")

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "native")
_SRC = os.path.join(_NATIVE_DIR, "segment_encoder.cpp")
_SO = os.path.join(_NATIVE_DIR, "_sdot_native.so")

_lock = threading.Lock()
_module = None
_tried = False


def _build() -> bool:
    inc = sysconfig.get_path("include")
    cmd = ["g++", "-O3", "-std=c++17", "-shared", "-fPIC",
           f"-I{inc}", _SRC, "-o", _SO]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        return True
    except (subprocess.SubprocessError, FileNotFoundError) as e:
        log.warning("native build failed (%s); using numpy ingest path", e)
        return False


def load():
    """Returns the native module or None."""
    global _module, _tried
    with _lock:
        if _module is not None or _tried:
            return _module
        _tried = True
        if not os.path.exists(_SRC):
            return None
        if (not os.path.exists(_SO)
                or os.path.getmtime(_SO) < os.path.getmtime(_SRC)):
            if not _build():
                return None
        try:
            import importlib.util
            spec = importlib.util.spec_from_file_location("_sdot_native", _SO)
            mod = importlib.util.module_from_spec(spec)
            spec.loader.exec_module(mod)
            _module = mod
        except Exception as e:  # noqa: BLE001
            log.warning("native load failed (%s)", e)
            _module = None
        return _module


def encode_strings(raw) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """Fast path: string column (numpy object array or pandas Series — a
    pandas Arrow-backed Series converts zero-copy) -> (dictionary object
    array sorted ascending, int32 codes). None when unavailable/ineligible."""
    mod = load()
    if mod is None:
        return None
    try:
        import pyarrow as pa
    except ImportError:
        return None
    try:
        if isinstance(raw, np.ndarray):
            arr = pa.array(raw, type=pa.string())
        else:  # pandas Series: zero-copy for arrow-backed string dtypes
            arr = pa.Array.from_pandas(raw)
            if pa.types.is_large_string(arr.type):
                if arr.nbytes < (1 << 31) - 1:
                    arr = arr.cast(pa.string())
                else:
                    return None
            elif not pa.types.is_string(arr.type):
                arr = arr.cast(pa.string())
    except (pa.ArrowInvalid, pa.ArrowTypeError, pa.ArrowNotImplementedError):
        return None
    if isinstance(arr, pa.ChunkedArray):
        arr = arr.combine_chunks()
    if arr.null_count:
        return None
    bufs = arr.buffers()  # [validity, offsets, data]
    offsets = bufs[1]
    data = bufs[2] if bufs[2] is not None else b""
    if arr.offset != 0:
        arr = pa.concat_arrays([arr])  # realign
        bufs = arr.buffers()
        offsets = bufs[1]
        data = bufs[2] if bufs[2] is not None else b""
    codes_b, dict_data, dict_off_b = mod.encode_utf8(data, offsets)
    codes = np.frombuffer(codes_b, dtype=np.int32).copy()
    dict_offsets = np.frombuffer(dict_off_b, dtype=np.int32)
    k = len(dict_offsets) - 1
    dict_arr = pa.StringArray.from_buffers(
        k, pa.py_buffer(dict_off_b), pa.py_buffer(dict_data))
    dictionary = np.asarray(dict_arr.to_pandas(), dtype=object)
    return dictionary, codes


def _string_col_buffers(series):
    """object/string column -> (data, offsets, valid_u8) arrow buffers, or
    None when not string-like."""
    import pyarrow as pa
    try:
        arr = pa.array(series, type=pa.string(), from_pandas=True)
    except (pa.ArrowInvalid, pa.ArrowTypeError, pa.ArrowNotImplementedError):
        return None
    if isinstance(arr, pa.ChunkedArray):
        arr = arr.combine_chunks()
    if arr.offset != 0:
        arr = pa.concat_arrays([arr])
    valid = None
    if arr.null_count:
        import pyarrow.compute as pc
        valid = np.asarray(pc.is_valid(arr)).astype(np.uint8)
        arr = arr.fill_null("")
        if isinstance(arr, pa.ChunkedArray):
            arr = arr.combine_chunks()
        if arr.offset != 0:
            arr = pa.concat_arrays([arr])
    bufs = arr.buffers()
    data = bufs[2] if bufs[2] is not None else b""
    return data, bufs[1], valid


def encode_json_rows(df) -> Optional[bytes]:
    """Fast path for the serving tier: DataFrame -> JSON rows-array bytes
    (the ``"rows": [...]`` payload), encoded in C++ with the GIL released.
    Returns None when the native module is unavailable or a column type is
    not supported (caller falls back to the python json path)."""
    mod = load()
    if mod is None or not hasattr(mod, "encode_json_rows"):
        return None
    import json as _json
    names = []
    cols = []
    n = len(df)
    for c in df.columns:
        s = df[c]
        dt = s.dtype
        names.append((_json.dumps(str(c)) + ":").encode())
        if dt == object or str(dt).startswith(("string", "str")):
            r = _string_col_buffers(s)
            if r is None:
                return None
            data, offsets, valid = r
            cols.append((2, data, offsets, valid))
            continue
        if not isinstance(dt, np.dtype):
            return None        # extension dtypes (categorical, nullable...)
        if np.issubdtype(dt, np.floating):
            cols.append((0, np.ascontiguousarray(s.to_numpy(np.float64)),
                         None, None))
        elif np.issubdtype(dt, np.bool_):
            cols.append((3, np.ascontiguousarray(
                s.to_numpy()).astype(np.uint8), None, None))
        elif np.issubdtype(dt, np.integer):
            if np.issubdtype(dt, np.unsignedinteger) and dt.itemsize == 8:
                v = s.to_numpy()
                if len(v) and int(v.max()) > np.iinfo(np.int64).max:
                    return None   # would wrap negative through int64
            cols.append((1, np.ascontiguousarray(s.to_numpy(np.int64)),
                         None, None))
        elif np.issubdtype(dt, np.datetime64):
            v = s.to_numpy()
            valid = (~np.isnat(v)).astype(np.uint8)
            ms = v.astype("datetime64[ms]").astype(np.int64)
            cols.append((4, np.ascontiguousarray(ms), None,
                         valid if (valid == 0).any() else None))
        else:
            return None
    try:
        return mod.encode_json_rows(tuple(names), tuple(cols), n)
    except Exception as e:  # noqa: BLE001
        log.warning("native json encode failed (%s)", e)
        return None
