"""Column encodings for the TPU segment store.

Druid-equivalent columnar storage (the capability the reference delegates to
the external Druid cluster; contract encoded in
``client/DruidMessages.scala:22-57`` ``MetadataResponse``/``ColumnDetails`` and
``metadata/DruidDataSource.scala:42-92``), redesigned for TPU residency:

- **Dimensions** are dictionary-encoded with a *global, sorted* dictionary per
  datasource (Druid uses per-segment dictionaries merged at the broker; a
  global sorted dictionary makes codes comparable across segments *and*
  order-preserving, so bound/range predicates lower to integer comparisons on
  codes — no string compare ever reaches the device).
- **Metrics** are float32 / int32 device arrays (f32 accumulation; exactness
  beyond ~1e-6 relative is restored host-side at merge when needed).
- **Time** is split into int32 days-since-epoch + int32 millis-in-day so the
  device never touches int64 (TPU emulates int64; day-grain covers OLAP time
  bucketing, ms-in-day restores full precision when required).

Null handling: validity is a separate bool mask (present only when the column
actually has nulls); codes/values under an invalid row are 0. Predicates are
three-valued at the planner: a selector/bound never matches null, ``IS NULL``
reads the validity mask — matching Druid/SQL semantics.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Optional

import numpy as np


class ColumnKind(enum.Enum):
    DIM = "dimension"          # dictionary-encoded string
    LONG = "long"              # int32 on device
    DOUBLE = "double"          # float32 on device
    DATE = "date"              # int32 days-since-epoch (non-time date column)
    TIME = "time"              # int32 days + int32 ms-in-day


@dataclasses.dataclass
class DimColumn:
    """Dictionary-encoded string dimension.

    ``dictionary`` is sorted ascending; ``codes[i]`` indexes into it.
    ``validity`` is None when no nulls exist.
    """

    name: str
    dictionary: np.ndarray            # object array of str, sorted ascending
    codes: np.ndarray                 # int32 [n]
    validity: Optional[np.ndarray]    # bool [n] or None

    kind: ColumnKind = ColumnKind.DIM

    @property
    def cardinality(self) -> int:
        return int(len(self.dictionary))

    @property
    def code_bits(self) -> int:
        """Bits per code at this dictionary's cardinality — the
        bit-packed width an encoded snapshot stores codes at
        (encode/codecs.py bitpack; the ingest-time chooser hint).
        Metadata-only: derived from the dictionary, never the codes, so
        it is free on tiered columns."""
        return max(1, int(max(self.cardinality - 1, 0)).bit_length())

    def code_of(self, value: str) -> int:
        """Binary-search a value; -1 if absent (selector on absent value ==
        constant-false filter)."""
        i = int(np.searchsorted(self.dictionary, value))
        if i < len(self.dictionary) and self.dictionary[i] == value:
            return i
        return -1

    def code_range(self, lower=None, upper=None,
                   lower_strict: bool = False, upper_strict: bool = False):
        """Lexicographic bound -> half-open code range [lo, hi).

        This is the payoff of the sorted global dictionary: Druid's bound
        filter (``BoundFilterSpec``, reference ``DruidQuerySpec.scala:214-253``)
        becomes two integer comparisons on codes.
        """
        lo = 0
        hi = len(self.dictionary)
        if lower is not None:
            side = "right" if lower_strict else "left"
            lo = int(np.searchsorted(self.dictionary, lower, side=side))
        if upper is not None:
            side = "left" if upper_strict else "right"
            hi = int(np.searchsorted(self.dictionary, upper, side=side))
        return lo, hi

    def decode(self, codes: np.ndarray) -> np.ndarray:
        return self.dictionary[np.asarray(codes, dtype=np.int64)]

    # Metadata accessors: planning / sizing paths MUST use these instead
    # of touching ``codes`` / ``validity`` directly — on a tiered column
    # (tier/handles.py) the arrays are fault-on-access properties, and a
    # dtype or nbytes peek through the array would fault the whole
    # column into the hot set.
    def data_dtype(self) -> np.dtype:
        return self.codes.dtype

    def has_nulls(self) -> bool:
        return self.validity is not None

    def data_nbytes(self) -> int:
        return int(self.codes.nbytes)

    def footprint_nbytes(self) -> int:
        v = int(self.validity.nbytes) if self.validity is not None else 0
        return int(self.codes.nbytes) + v


@dataclasses.dataclass
class MetricColumn:
    """Numeric metric column (long or double)."""

    name: str
    values: np.ndarray                # float32 / int32 [n]; int64 when wide
    validity: Optional[np.ndarray]    # bool [n] or None
    kind: ColumnKind = ColumnKind.DOUBLE

    def _bounds(self):
        """(min, max) over valid values — computed once (columns are
        immutable after ingest; the planner consults bounds on every
        query, and a full-column scan per access would dominate warm
        planning)."""
        b = getattr(self, "_bounds_cache", None)
        if b is None:
            v = self.values if self.validity is None \
                else self.values[self.validity]
            b = (v.min(), v.max()) if len(v) else (None, None)
            self._bounds_cache = b
        return b

    @property
    def min(self):
        return self._bounds()[0]

    @property
    def max(self):
        return self._bounds()[1]

    # metadata accessors (see DimColumn.data_dtype)
    def data_dtype(self) -> np.dtype:
        return self.values.dtype

    def has_nulls(self) -> bool:
        return self.validity is not None

    def data_nbytes(self) -> int:
        return int(self.values.nbytes)

    def footprint_nbytes(self) -> int:
        v = int(self.validity.nbytes) if self.validity is not None else 0
        return int(self.values.nbytes) + v


MILLIS_PER_DAY = 86_400_000


@dataclasses.dataclass
class TimeColumn:
    """The datasource time column, day/ms split (see module docstring)."""

    name: str
    days: np.ndarray                  # int32 [n], days since 1970-01-01 UTC
    ms_in_day: np.ndarray             # int32 [n]
    kind: ColumnKind = ColumnKind.TIME

    @property
    def millis(self) -> np.ndarray:
        return self.days.astype(np.int64) * MILLIS_PER_DAY + self.ms_in_day

    @property
    def min_millis(self) -> int:
        if len(self.days) == 0:
            return 0
        i = int(np.lexsort((self.ms_in_day, self.days))[0])
        return int(self.days[i]) * MILLIS_PER_DAY + int(self.ms_in_day[i])

    @property
    def max_millis(self) -> int:
        if len(self.days) == 0:
            return 0
        i = int(np.lexsort((self.ms_in_day, self.days))[-1])
        return int(self.days[i]) * MILLIS_PER_DAY + int(self.ms_in_day[i])

    # metadata accessors (see DimColumn.data_dtype)
    def data_dtype(self) -> np.dtype:
        return self.days.dtype

    def ms_dtype(self) -> np.dtype:
        return self.ms_in_day.dtype

    def has_nulls(self) -> bool:
        return False

    def data_nbytes(self) -> int:
        return int(self.days.nbytes)

    def footprint_nbytes(self) -> int:
        return int(self.days.nbytes) + int(self.ms_in_day.nbytes)


def encode_time_millis(millis: np.ndarray):
    millis = np.asarray(millis, dtype=np.int64)
    days = np.floor_divide(millis, MILLIS_PER_DAY)
    ms = millis - days * MILLIS_PER_DAY
    return days.astype(np.int32), ms.astype(np.int32)


def build_dim_column(name: str, raw: np.ndarray,
                     dictionary: Optional[np.ndarray] = None) -> DimColumn:
    """Dictionary-encode a string column.

    When ``dictionary`` is given (the datasource-global dictionary built at
    ingest), codes are looked up against it; otherwise a fresh sorted
    dictionary is built from this chunk. The no-null fresh-dictionary case
    takes the native C++ encoder when available.
    """
    if dictionary is None:
        from spark_druid_olap_tpu.segment import native
        fast = native.encode_strings(raw)
        if fast is not None:
            d, codes = fast
            codes = codes.astype(
                narrow_int_dtype(0, max(len(d) - 1, 0)), copy=False)
            return DimColumn(name=name, dictionary=d, codes=codes,
                             validity=None)
    raw = np.asarray(raw, dtype=object)
    # pandas-style null detection: None, float nan, or pd.NA
    validity = np.array(
        [not (v is None or (isinstance(v, float) and np.isnan(v))
              or type(v).__name__ == "NAType")
         for v in raw], dtype=bool)
    has_null = not validity.all()
    safe = np.where(validity, raw, "")
    safe = safe.astype(str)
    if dictionary is None:
        dictionary = np.unique(safe[validity] if has_null else safe)
    cdt = narrow_int_dtype(0, max(len(dictionary) - 1, 0))
    codes = np.searchsorted(dictionary, safe)
    codes = np.clip(codes, 0, max(len(dictionary) - 1, 0)).astype(cdt)
    if has_null:
        codes = np.where(validity, codes, 0).astype(cdt)
    return DimColumn(name=name, dictionary=np.asarray(dictionary, dtype=object),
                     codes=codes, validity=validity if has_null else None)


def narrow_int_dtype(lo: int, hi: int) -> np.dtype:
    """Smallest signed integer dtype holding [lo, hi]. Storage (host RSS,
    HBM residency, transfer) is bandwidth-bound; narrow columns read
    upcast to i32 inside the scan programs (ScanContext.col), so compute
    kernels never see sub-32-bit values."""
    for dt in (np.int8, np.int16, np.int32):
        ii = np.iinfo(dt)
        if lo >= ii.min and hi <= ii.max:
            return np.dtype(dt)
    return np.dtype(np.int64)


def build_metric_column(name: str, raw: np.ndarray, kind: ColumnKind) -> MetricColumn:
    raw = np.asarray(raw)
    if raw.dtype == object:
        validity = np.array([v is not None for v in raw], dtype=bool)
        raw = np.where(validity, raw, 0)
    elif np.issubdtype(raw.dtype, np.floating):
        validity = ~np.isnan(raw)
        raw = np.where(validity, raw, 0)
    else:
        validity = None
    if kind == ColumnKind.DOUBLE:
        dtype = np.float32
    else:
        # wide longs keep int64 host-side rather than silently wrapping
        # (Druid LONG is a 64-bit type); 32-bit device backends route
        # queries over them to the host tier. In-range longs store at
        # the narrowest width their min/max allows.
        i64 = raw.astype(np.int64)
        ii = np.iinfo(np.int32)
        lo, hi = (int(i64.min()), int(i64.max())) if len(i64) else (0, 0)
        wide = len(i64) > 0 and (lo < ii.min or hi > ii.max)
        dtype = np.int64 if wide else (
            narrow_int_dtype(lo, hi) if len(i64)
            else np.dtype(np.int32))
    values = raw.astype(dtype)
    has_null = validity is not None and not validity.all()
    return MetricColumn(name=name, values=values,
                        validity=validity if has_null else None, kind=kind)
