"""Derived-table view merging.

``SELECT ... FROM (SELECT <projection> FROM R WHERE P) t WHERE Q`` collapses
to ``SELECT ...[substituted] FROM R WHERE P AND Q[substituted]`` when the
inner block is a plain projection/filter (no aggregation, DISTINCT, LIMIT or
HAVING). Spark's optimizer (CollapseProject / PushDownPredicate) does this
before the reference's rewrite rules run, which is why TPC-H q22-shaped
queries still reach DruidStrategy anchored at a relation leaf — this pass
reproduces that normalization for the pushdown builder.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional

from spark_druid_olap_tpu.ir import expr as E
from spark_druid_olap_tpu.sql import ast as A


def _and(parts):
    parts = [p for p in parts if p is not None]
    if not parts:
        return None
    return parts[0] if len(parts) == 1 else E.And(tuple(parts))


def _mapping(inner: A.SelectStmt) -> Optional[Dict[str, E.Expr]]:
    """Output-name -> source-expression map of the inner projection; None
    when an item is unmappable. '*' items pass unselected names through
    untouched (identity)."""
    out: Dict[str, E.Expr] = {}
    for it in inner.items:
        if it.expr == "*" or (isinstance(it.expr, E.Column)
                              and it.expr.name == "*"):
            continue
        if it.alias:
            out[it.alias] = it.expr
        elif isinstance(it.expr, E.Column):
            out[it.expr.name] = it.expr
        else:
            return None     # unaliased computed item: no stable name
    return out


def merge_derived(ctx, stmt: A.SelectStmt) -> A.SelectStmt:
    """Iteratively merge a top-level single derived table into the outer
    statement."""
    while isinstance(stmt.relation, A.SubqueryRef):
        inner = stmt.relation.query
        if not isinstance(inner, A.SelectStmt) or inner.relation is None:
            break
        if inner.group_by is not None or inner.having is not None \
                or inner.limit is not None or inner.distinct \
                or inner.order_by or inner.offset:
            break
        if any(it.expr == "*" or (isinstance(it.expr, E.Column)
                                  and it.expr.name == "*")
               for it in stmt.items):
            # outer '*' means "the derived table's columns"; merging would
            # widen it to every base-table column
            break
        mapping = _mapping(inner)
        if mapping is None:
            break
        nontrivial = {k for k, v in mapping.items()
                      if not (isinstance(v, E.Column) and v.name == k)}

        def subst(e):
            if e is None or e == "*":
                return e

            def rep(n):
                if isinstance(n, E.Column) and n.name in mapping:
                    return mapping[n.name]
                return n
            return E.transform(e, rep)

        # expression substitution cannot reach inside nested subquery
        # blocks; bail if one references a non-identity-mapped name
        from spark_druid_olap_tpu.planner.host_exec import (
            _free_columns, _subquery_nodes)
        safe = True
        for e in [it.expr for it in stmt.items if it.expr != "*"] \
                + [stmt.where, stmt.having] \
                + [o.expr for o in stmt.order_by]:
            if e is None:
                continue
            for node in _subquery_nodes(e):
                try:
                    if _free_columns(ctx, node.query) & nontrivial:
                        safe = False
                except Exception:  # noqa: BLE001
                    safe = False
        if not safe:
            break

        gb = stmt.group_by
        if isinstance(gb, A.GroupingSets):
            gb = A.GroupingSets(tuple(tuple(subst(g) for g in s)
                                      for s in gb.sets))
        elif gb is not None:
            gb = tuple(subst(g) for g in gb)
        def merge_item(it):
            # a bare reference to a computed derived column keeps its name:
            # SELECT cntrycode FROM (SELECT substr(...) AS cntrycode ...)
            alias = it.alias
            if alias is None and isinstance(it.expr, E.Column) \
                    and it.expr.name in nontrivial:
                alias = it.expr.name
            return dataclasses.replace(it, expr=subst(it.expr), alias=alias)

        stmt = dataclasses.replace(
            stmt,
            items=tuple(merge_item(it) for it in stmt.items),
            relation=inner.relation,
            where=_and([inner.where, subst(stmt.where)]),
            group_by=gb,
            having=subst(stmt.having),
            order_by=tuple(dataclasses.replace(o, expr=subst(o.expr))
                           for o in stmt.order_by))
    return stmt
