"""Alias-scope resolution for correlated subqueries.

The engine binds columns by GLOBALLY-UNIQUE bare names, mirroring the
reference's star-schema contract (StarSchemaInfo.scala:127-165 requires
globally-unique column names; Spark's analyzer then resolves alias
qualifiers before the rewrite ever sees the plan). The parser therefore
stores ``s2.region`` as bare ``region`` — which silently mis-scopes a
correlated SELF-reference: in

    select .. from sales s
    where qty > (select avg(qty) from sales s2 where s2.region = s.region)

both sides collapse to ``region = region``, the subquery loses its free
variable, and the "correlation" becomes an always-true inner conjunct
(the subquery then computes ONE global aggregate — a wrong answer, not
an error).

This pass runs right after parsing, while :class:`ir.expr.Column` still
carries the written qualifier as non-comparing metadata. For every
subquery scope it detects outer-qualified references whose bare name
collides with a column of the subquery's own relation ("shadowed"), and
rewrites the scope capture-avoidingly: the inner relation is wrapped in
a derived table that RENAMES the shadowed columns, every inner-bound
reference follows the rename, and the outer reference keeps its bare
name — now genuinely free, so the existing decorrelation machinery
(planner/decorrelate.py, host_exec._execute_sub_decorrelated) applies
unchanged. This is exactly the manual workaround TPC-H q21 needed
before; published q21 text now parses and runs verbatim.

Scopes compose: each level renames only collisions with ITS own
relation; deeper scopes handle their own when the pass recurses.
Derived tables and CTE bodies are self-contained scopes (no LATERAL).
After resolution every qualifier is stripped, so downstream planning,
caching, and serde see exactly the bare-name trees they always did.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

from spark_druid_olap_tpu.ir import expr as E
from spark_druid_olap_tpu.sql import ast as A
from spark_druid_olap_tpu.sql.lexer import SqlSyntaxError

_SUBQ = (A.ScalarSubquery, A.InSubquery, A.Exists)


def _rebuild_subqueries(e, on_query):
    """E.transform over ``e`` rebuilding each subquery node with
    ``on_query`` applied to its statement (InSubquery children recurse
    with the same rewriter) — the shared traversal of the strip and
    database-resolution passes."""
    def fn(n):
        if isinstance(n, A.ScalarSubquery):
            return A.ScalarSubquery(on_query(n.query))
        if isinstance(n, A.Exists):
            return A.Exists(on_query(n.query), n.negated)
        if isinstance(n, A.InSubquery):
            return A.InSubquery(_rebuild_subqueries(n.child, on_query),
                                on_query(n.query), n.negated)
        return n
    return E.transform(e, fn)


def resolve_alias_scopes(ctx, stmt):
    """Entry point: resolve qualifiers in a parsed statement tree and
    strip them. Idempotent; the qualifier-free common case returns the
    SAME object (one cheap walk, no rebuild) — this runs on the hot
    path of every statement."""
    if not _has_quals(stmt):
        return stmt
    if isinstance(stmt, A.UnionAll):
        return dataclasses.replace(
            stmt, parts=tuple(resolve_alias_scopes(ctx, p)
                              for p in stmt.parts),
            order_by=tuple(_strip_order(o) for o in stmt.order_by))
    if not isinstance(stmt, A.SelectStmt):
        return stmt
    out = _resolve_scope(ctx, stmt, outer=())
    return _strip_stmt(out)


def _has_quals(stmt) -> bool:
    if isinstance(stmt, A.UnionAll):
        return any(_has_quals(p) for p in stmt.parts) \
            or any(_expr_has_quals(o.expr) for o in stmt.order_by)
    if not isinstance(stmt, A.SelectStmt):
        return False
    for e in _iter_stmt_exprs(stmt):
        if _expr_has_quals(e):
            return True
    rel = stmt.relation
    stack = [rel]
    while stack:
        r = stack.pop()
        if isinstance(r, A.SubqueryRef) and _has_quals(r.query):
            return True
        if isinstance(r, A.Join):
            stack.extend((r.left, r.right))
    return False


def _expr_has_quals(e) -> bool:
    for n in E.walk(e):
        if isinstance(n, E.Column) and n.qual is not None:
            return True
        if isinstance(n, _SUBQ) and _has_quals(n.query):
            return True
    return False


# -- scope walk ---------------------------------------------------------------

def _relation_aliases(rel) -> frozenset:
    if rel is None:
        return frozenset()
    if isinstance(rel, A.TableRef):
        # an alias HIDES the table name (SQL scoping): 'from sales s2'
        # makes 'sales.region' an OUTER reference inside a subquery
        return frozenset({rel.alias or rel.name})
    if isinstance(rel, A.SubqueryRef):
        return frozenset({rel.alias})
    if isinstance(rel, A.Join):
        return _relation_aliases(rel.left) | _relation_aliases(rel.right)
    return frozenset()


def _try_columns(ctx, rel) -> Optional[frozenset]:
    from spark_druid_olap_tpu.planner.host_exec import relation_columns
    try:
        return frozenset(relation_columns(ctx, rel))
    except Exception:  # noqa: BLE001 — unknown tables: resolve leniently
        return None


def _map_stmt_exprs(q: A.SelectStmt, f) -> A.SelectStmt:
    """Rebuild ``q`` with ``f`` applied to every top-level expression."""
    items = tuple(it if it.expr == "*"
                  else A.SelectItem(f(it.expr), it.alias) for it in q.items)
    where = None if q.where is None else f(q.where)
    having = None if q.having is None else f(q.having)
    gb = q.group_by
    if isinstance(gb, A.GroupingSets):
        gb = A.GroupingSets(tuple(tuple(f(e) for e in s) for s in gb.sets))
    elif gb is not None:
        gb = tuple(f(e) for e in gb)
    ob = tuple(A.OrderItem(f(o.expr), o.ascending) for o in q.order_by)
    return dataclasses.replace(q, items=items, where=where, group_by=gb,
                               having=having, order_by=ob)


def _map_relation(rel, f_query, f_expr=None):
    """Rebuild a relation tree: derived-table bodies through ``f_query``,
    Join ON conditions (expressions of the ENCLOSING scope) through
    ``f_expr``."""
    if isinstance(rel, A.SubqueryRef):
        return A.SubqueryRef(f_query(rel.query), rel.alias)
    if isinstance(rel, A.Join):
        cond = rel.condition
        if cond is not None and f_expr is not None:
            cond = f_expr(cond)
        return A.Join(_map_relation(rel.left, f_query, f_expr),
                      _map_relation(rel.right, f_query, f_expr),
                      rel.kind, cond)
    return rel


def _iter_relation_conditions(rel):
    """Join ON conditions in a relation tree (derived-table bodies are
    separate scopes and are NOT entered)."""
    if isinstance(rel, A.Join):
        if rel.condition is not None:
            yield rel.condition
        yield from _iter_relation_conditions(rel.left)
        yield from _iter_relation_conditions(rel.right)


def _equi_key_refs(rel):
    """(qualifier, column) pairs the JOIN LAYER binds by itself: the
    qualified columns of top-level AND-ed ``a.x = b.y`` equality keys in
    ON conditions. The downstream merge resolves these by qualifier and
    collapses the key pair into one output column, so for
    different-table joins they must not trigger a scope rename (and must
    stay exposed under their bare names)."""
    out = set()

    def eq_terms(c):
        if isinstance(c, E.And):
            for p in c.parts:
                eq_terms(p)
        elif (isinstance(c, E.Comparison) and c.op == "="
              and isinstance(c.left, E.Column)
              and isinstance(c.right, E.Column)
              and c.left.qual and c.right.qual):
            out.add((c.left.qual, c.left.name))
            out.add((c.right.qual, c.right.name))

    for cond in _iter_relation_conditions(rel):
        eq_terms(cond)
    return out


def _disambiguate_join_duplicates(ctx, q):
    """Same-scope duplicate-column joins (self-joins): columns bind by
    bare name, so ``t a join t b`` exposes every column of ``t`` twice
    and ``a.x < b.x`` would collapse to ``x < x`` (an unbound-column
    error at best, a silently-degenerate predicate at worst). Every
    duplicated TableRef leaf AFTER a column's first owner is wrapped in
    a derived table that RENAMES the duplicated columns REFERENCED
    THROUGH ITS QUALIFIER (pruned to the referenced set); those
    references follow the rename — nested subquery scopes that rebind
    the alias are left alone. Unqualified references keep the legacy
    bind-by-global-name behavior: the star-schema convention
    deliberately duplicates dimension columns between the flat index
    and its members (StarSchemaInfo's globally-unique-name contract),
    so ONLY qualifier-distinguished duplicates are rewritten. ≈ Spark's
    analyzer deduplicating attribute ids on self-join, which the
    reference's planner relies on upstream of its rewrites."""
    rel = q.relation
    if not isinstance(rel, A.Join):
        return q

    leaves = []

    def collect(r):
        if isinstance(r, A.Join):
            collect(r.left)
            collect(r.right)
        else:
            leaves.append(r)
    collect(rel)
    cols_of = [_try_columns(ctx, lf) or frozenset() for lf in leaves]
    from collections import Counter
    cnt = Counter()
    for cols in cols_of:
        cnt.update(cols)
    dup = {c for c, k in cnt.items() if k > 1}
    if not dup:
        return q
    # TRUE self-joins: the SAME base table appearing twice. Two
    # DIFFERENT tables sharing column names (t1 a join t2 b on a.id =
    # b.id) are the star-schema convention — their equi-join keys bind
    # by qualifier at the join layer (the merge collapses them), so ON
    # key references must neither rename nor star-raise there; only
    # duplicated columns referenced OUTSIDE the ON keys (a.x, b.x in
    # the select list) still need the rename to survive the merge's
    # bare-name suffixing.
    base_cnt = Counter(lf.name for lf in leaves
                       if isinstance(lf, A.TableRef))
    self_joined = {t for t, k in base_cnt.items() if k > 1}
    on_keys = _equi_key_refs(rel)

    # every referenced name in this scope (subquery expressions
    # included — they may reference our aliases); derived-table bodies
    # are separate scopes and contribute nothing
    refs: set = set()
    quals_used: set = set()

    def scan(e, nested=()):
        for n in E.walk(e):
            if isinstance(n, E.Column) and n.name != "*":
                refs.add(n.name)
                # a qualifier REBOUND by a nested FROM belongs to that
                # scope: 'exists (select 1 from u b where b.x ...)' must
                # not mark OUR leaf b's x as qualifier-referenced (the
                # same guard fix()/_fix_nested apply on the rewrite side)
                if n.qual and not any(n.qual in na for na in nested):
                    quals_used.add((n.qual, n.name))
            elif isinstance(n, _SUBQ):
                _scan_nested(n.query, nested)

    def _scan_nested(q2, nested):
        if isinstance(q2, A.UnionAll):
            for p in q2.parts:
                _scan_nested(p, nested)
            return
        if not isinstance(q2, A.SelectStmt):
            return
        nested2 = nested + (_relation_aliases(q2.relation),)
        for e2 in _iter_stmt_exprs(q2):
            scan(e2, nested2)
    for e in _iter_stmt_exprs(q):
        scan(e)                 # includes the join ON conditions

    alias_of = [lf.alias or getattr(lf, "name", None) for lf in leaves]
    seen: set = set()
    renmaps = []           # per leaf: {bare: renamed} (empty = unwrapped)
    owned_elsewhere = []   # per leaf: dup columns an EARLIER leaf owns
    for i, (lf, cols) in enumerate(zip(leaves, cols_of)):
        ren = {}
        if isinstance(lf, A.TableRef):
            if lf.name in self_joined:
                # a self-join duplicates EVERY column: any qualified
                # reference (ON keys included) needs the rename
                ren = {c: f"__sj{i}_{c}"
                       for c in sorted(cols & dup & seen)
                       if (alias_of[i], c) in quals_used}
            else:
                ren = {c: f"__sj{i}_{c}"
                       for c in sorted(cols & dup & seen)
                       if (alias_of[i], c) in quals_used
                       and (alias_of[i], c) not in on_keys}
        owned_elsewhere.append(cols & dup & seen)
        seen |= cols
        renmaps.append(ren)
    if not any(renmaps):
        return q
    for i, ren in enumerate(renmaps):
        if ren and alias_of.count(alias_of[i]) > 1:
            raise SqlSyntaxError(
                f"self-join of {alias_of[i]!r} needs DISTINCT aliases to "
                f"disambiguate its duplicated columns")

    star = any(it.expr == "*" or (isinstance(it.expr, E.Column)
                                  and it.expr.name == "*")
               for it in q.items)
    if star and any(ren and leaves[i].name in self_joined
                    for i, ren in enumerate(renmaps)):
        # SELECT * over a qualifier-disambiguated SELF-join is
        # ill-defined (the duplicated columns have no bare names to
        # expose) — require an explicit list, like the shadow rename.
        # Different-table joins never hit this: their renamed leaves
        # keep full exposure under star below.
        raise SqlSyntaxError(
            f"select * cannot combine with a self-join of "
            f"{sorted(self_joined)} that disambiguates duplicated "
            f"columns via aliases: list the needed columns explicitly "
            f"(qualified)")

    wrapped = {}
    for i, (lf, cols, ren) in enumerate(zip(leaves, cols_of, renmaps)):
        if not ren:
            continue
        # expose bare: referenced columns this leaf FIRST-owns (incl.
        # duplicated ones a LATER leaf shares — hiding those would
        # unbind a first-owner reference); plus the renamed duplicates
        # and the leaf's ON equi-keys (exposed bare so the merge can
        # collapse them). Duplicated columns an EARLIER leaf owns stay
        # unexposed unless renamed, so the bare copy binds that first
        # owner without a merge collision. Under star the leaf keeps
        # full exposure (pruning would silently shrink the star).
        on_i = {c for (al, c) in on_keys
                if al == alias_of[i] and c in cols}
        if star:
            used = sorted(cols)
        else:
            used = sorted(((refs & cols) - owned_elsewhere[i])
                          | set(ren) | on_i) or sorted(cols)[:1]
        body = A.SelectStmt(
            items=tuple(A.SelectItem(E.Column(c), ren.get(c, c))
                        for c in used),
            relation=A.TableRef(lf.name))
        wrapped[id(lf)] = A.SubqueryRef(body, alias=alias_of[i])
    ren_by_alias = {alias_of[i]: renmaps[i]
                    for i in range(len(leaves)) if renmaps[i]}

    def rebuild(r):
        if isinstance(r, A.Join):
            cond = r.condition
            if cond is not None:
                cond = fix(cond)
            return A.Join(rebuild(r.left), rebuild(r.right), r.kind,
                          cond)
        return wrapped.get(id(r), r)

    def fix(e, nested=()):
        def fn(n):
            if isinstance(n, A.ScalarSubquery):
                return A.ScalarSubquery(_fix_nested(n.query, nested))
            if isinstance(n, A.Exists):
                return A.Exists(_fix_nested(n.query, nested), n.negated)
            if isinstance(n, A.InSubquery):
                return A.InSubquery(fix(n.child, nested),
                                    _fix_nested(n.query, nested),
                                    n.negated)
            if isinstance(n, E.Column) and n.qual \
                    and n.qual in ren_by_alias \
                    and not any(n.qual in na for na in nested):
                new = ren_by_alias[n.qual].get(n.name)
                if new is not None:
                    return E.Column(new)
            return n
        return E.transform(e, fn)

    def _fix_nested(q2, nested):
        if isinstance(q2, A.UnionAll):
            return dataclasses.replace(
                q2, parts=tuple(_fix_nested(p, nested)
                                for p in q2.parts))
        if not isinstance(q2, A.SelectStmt):
            return q2
        nested2 = nested + (_relation_aliases(q2.relation),)
        f = lambda e: fix(e, nested2)   # noqa: E731
        rel2 = _map_relation(q2.relation, lambda s: s, f)
        if rel2 is not q2.relation:
            q2 = dataclasses.replace(q2, relation=rel2)
        return _map_stmt_exprs(q2, f)

    q = dataclasses.replace(q, relation=rebuild(rel))
    # unaliased projections keep the name the user WROTE: 'select
    # b.region' must come back as column 'region', not '__sj1_region'
    items = []
    for it in q.items:
        alias = it.alias
        if alias is None and isinstance(it.expr, E.Column) \
                and it.expr.qual in ren_by_alias \
                and it.expr.name in ren_by_alias[it.expr.qual]:
            alias = it.expr.name
        items.append(A.SelectItem(it.expr, alias))
    q = dataclasses.replace(q, items=tuple(items))
    return _map_stmt_exprs(q, fix)


def _resolve_scope(ctx, q, outer: Tuple[frozenset, ...]):
    """Resolve a SELECT scope: derived tables are fresh self-contained
    scopes; subquery expressions are nested scopes that see this one."""
    if isinstance(q, A.UnionAll):          # union-bodied derived table/CTE
        return dataclasses.replace(
            q, parts=tuple(_resolve_scope(ctx, p, outer)
                           for p in q.parts))
    q = _disambiguate_join_duplicates(ctx, q)
    aliases = _relation_aliases(q.relation)
    inner = outer + (aliases,)

    def fix(e):
        def fn(n):
            if isinstance(n, A.ScalarSubquery):
                return A.ScalarSubquery(_resolve_subscope(ctx, n.query,
                                                          inner))
            if isinstance(n, A.Exists):
                # EXISTS ignores its select list, so 'select *' in its
                # body is compatible with the shadow rename
                return A.Exists(_resolve_subscope(ctx, n.query, inner,
                                                  allow_star=True),
                                n.negated)
            if isinstance(n, A.InSubquery):
                return A.InSubquery(fix(n.child),
                                    _resolve_subscope(ctx, n.query, inner),
                                    n.negated)
            return n
        return E.transform(e, fn)

    rel = _map_relation(q.relation,
                        lambda sub: _resolve_scope(ctx, sub, ()), fix)
    if rel is not q.relation:
        q = dataclasses.replace(q, relation=rel)
    return _map_stmt_exprs(q, fix)


def _resolve_subscope(ctx, q, outer: Tuple[frozenset, ...],
                      allow_star: bool = False):
    """Resolve one correlated-capable subquery scope: rename shadowed
    self-references, then recurse."""
    if not isinstance(q, A.SelectStmt):
        return _resolve_scope(ctx, q, outer)
    aliases = _relation_aliases(q.relation)
    outer_names = frozenset().union(*outer) if outer else frozenset()
    inner_cols = _try_columns(ctx, q.relation)
    shadowed = _shadowed_names(ctx, q, aliases, inner_cols,
                               outer_names - aliases)
    if shadowed:
        q = _rename_shadowed(ctx, q, aliases, inner_cols, shadowed,
                             allow_star=allow_star)
    return _resolve_scope(ctx, q, outer)


def _shadowed_names(ctx, q, aliases, inner_cols, outer_names) -> frozenset:
    """Bare names referenced with a strictly-outer alias qualifier that
    collide with this scope's own relation columns."""
    if not inner_cols or not outer_names:
        return frozenset()
    out = set()

    def scan_stmt(q2, nested_aliases):
        for e in _iter_stmt_exprs(q2):
            scan_expr(e, nested_aliases)

    def scan_expr(e, nested_aliases):
        for n in E.walk(e):
            if isinstance(n, _SUBQ):
                scan_stmt(n.query, nested_aliases
                          | _relation_aliases(n.query.relation))
            elif isinstance(n, E.Column) and n.qual:
                if n.qual in nested_aliases or n.qual in aliases:
                    continue
                if n.qual in outer_names and n.name in inner_cols:
                    out.add(n.name)

    scan_stmt(q, frozenset())
    return frozenset(out)


def _iter_stmt_exprs(q: A.SelectStmt):
    for it in q.items:
        if it.expr != "*":
            yield it.expr
    if q.where is not None:
        yield q.where
    gb = q.group_by
    if isinstance(gb, A.GroupingSets):
        for s in gb.sets:
            yield from s
    elif gb is not None:
        yield from gb
    if q.having is not None:
        yield q.having
    for o in q.order_by:
        yield o.expr
    # Join ON conditions belong to THIS scope; derived-table bodies are
    # separate scopes and are not ours
    yield from _iter_relation_conditions(q.relation)


def _referenced_names(q) -> set:
    """Every column name mentioned anywhere in a statement, including
    nested subquery scopes (an over-approximation is safe: it only
    widens the pruned derived table)."""
    out = set()

    star = [False]

    def scan_stmt(q2, root=False):
        if isinstance(q2, A.UnionAll):       # union-bodied derived table
            for p in q2.parts:
                scan_stmt(p, root)
            return
        # SQL '*' never binds an OUTER scope: only the ROOT scope's own
        # star expands the relation being renamed; deeper scopes' stars
        # expand THEIR relations and are irrelevant here
        if root and any(it.expr == "*" for it in q2.items):
            star[0] = True
        for e in _iter_stmt_exprs(q2):
            scan_expr(e, root)
        rel = q2.relation
        stack = [rel]
        while stack:
            r = stack.pop()
            if isinstance(r, A.SubqueryRef):
                scan_stmt(r.query)
            elif isinstance(r, A.Join):
                stack.extend((r.left, r.right))

    def scan_expr(e, root):
        for n in E.walk(e):
            if isinstance(n, E.Column):
                if n.name == "*":
                    if root:
                        star[0] = True
                else:
                    out.add(n.name)
            elif isinstance(n, _SUBQ):
                scan_stmt(n.query)

    scan_stmt(q, root=True)
    return None if star[0] else out


def _rename_shadowed(ctx, q, aliases, inner_cols, shadowed,
                     allow_star: bool = False):
    """Capture-avoiding rewrite: wrap the inner relation in a derived
    table renaming the shadowed columns, redirect every inner-bound
    reference, and leave outer-qualified references bare (now free)."""
    if not isinstance(q.relation, A.TableRef):
        raise SqlSyntaxError(
            f"correlated reference to outer column(s) "
            f"{sorted(shadowed)} shadowed by the subquery's own FROM "
            f"(non-simple relation): rename the inner columns via a "
            f"derived table, e.g. (select c as c2 ... ) x")
    ren = {c: f"__sc_{c}" for c in sorted(shadowed)}
    t = q.relation
    # prune: expose only the inner columns the subquery actually
    # references (plus every shadowed one) — materializing the full
    # table width per correlated execution is the q21 hot path
    refs = _referenced_names(q)
    if refs is None:
        if not allow_star:
            # SELECT * in a value-producing scope would re-expose
            # renamed columns
            raise SqlSyntaxError(
                f"correlated reference to outer column(s) "
                f"{sorted(shadowed)} shadowed by the subquery's own FROM "
                f"cannot combine with SELECT *: list the needed columns "
                f"explicitly")
        # EXISTS body: its select list is semantically irrelevant —
        # expose every inner column (shadowed ones renamed)
        used = frozenset(inner_cols)
    else:
        used = (refs & inner_cols) | shadowed
    body = A.SelectStmt(
        items=tuple(A.SelectItem(E.Column(c), ren.get(c, c))
                    for c in sorted(used)),
        relation=A.TableRef(t.name))
    new_rel = A.SubqueryRef(body, alias=t.alias or t.name)

    def rename_stmt(q2, nested):
        # nested: ((aliases, cols-or-None), ...) for scopes between the
        # expression and this one
        f = lambda e: rename_expr(e, nested)  # noqa: E731
        rel2 = _map_relation(q2.relation, lambda s: s, f)
        if rel2 is not q2.relation:
            q2 = dataclasses.replace(q2, relation=rel2)
        return _map_stmt_exprs(q2, f)

    def rename_expr(e, nested):
        def fn(n):
            if isinstance(n, A.ScalarSubquery):
                return A.ScalarSubquery(rec(n.query, nested))
            if isinstance(n, A.Exists):
                return A.Exists(rec(n.query, nested), n.negated)
            if isinstance(n, A.InSubquery):
                return A.InSubquery(rename_expr(n.child, nested),
                                    rec(n.query, nested), n.negated)
            if not isinstance(n, E.Column) or n.name not in ren:
                return n
            if n.qual:
                if any(n.qual in na for na, _ in nested):
                    return n                      # binds a nested scope
                if n.qual in aliases:
                    return E.Column(ren[n.name])  # explicit inner ref
                return n                          # outer/unknown: free
            # unqualified: binds the nearest enclosing scope holding the
            # column — a nested scope that has it wins over ours
            for _, nc in nested:
                if nc is not None and n.name in nc:
                    return n
            return E.Column(ren[n.name])
        return E.transform(e, fn)

    def rec(q2, nested):
        na = _relation_aliases(q2.relation)
        nc = _try_columns(ctx, q2.relation)
        return rename_stmt(q2, nested + ((na, nc),))

    return dataclasses.replace(rename_stmt(q, ()), relation=new_rel)


# -- database-namespace resolution --------------------------------------------

def resolve_databases(ctx, stmt):
    """Rewrite unqualified table names to '<default_db>.<name>' when only
    the qualified form is registered (reference: multi-DB operation,
    MultiDBTest.scala — Hive database resolution ahead of the rewrite).
    Explicit 'db.table' names pass through; registered bare names win."""
    from spark_druid_olap_tpu.utils.config import DATABASE_DEFAULT
    db = ctx.config.get(DATABASE_DEFAULT)
    if not db:
        return stmt
    known = set(ctx.store.names())

    def fix_rel(rel):
        if isinstance(rel, A.TableRef):
            if rel.name not in known and f"{db}.{rel.name}" in known:
                return A.TableRef(f"{db}.{rel.name}",
                                  rel.alias or rel.name)
            return rel
        if isinstance(rel, A.SubqueryRef):
            return A.SubqueryRef(fix_stmt(rel.query), rel.alias)
        if isinstance(rel, A.Join):
            cond = None if rel.condition is None \
                else fix_expr(rel.condition)   # ON may hold subqueries
            return A.Join(fix_rel(rel.left), fix_rel(rel.right),
                          rel.kind, cond)
        return rel

    def fix_expr(e):
        return _rebuild_subqueries(e, fix_stmt)

    def fix_stmt(q):
        if isinstance(q, A.UnionAll):
            return dataclasses.replace(
                q, parts=tuple(fix_stmt(p) for p in q.parts))
        if not isinstance(q, A.SelectStmt):
            return q
        if q.relation is not None:
            q = dataclasses.replace(q, relation=fix_rel(q.relation))
        return _map_stmt_exprs(q, fix_expr)

    return fix_stmt(stmt)


# -- qualifier strip ----------------------------------------------------------

def _strip_order(o: A.OrderItem) -> A.OrderItem:
    return A.OrderItem(_strip_expr(o.expr), o.ascending)


def _strip_expr(e):
    def fn(n):
        if isinstance(n, E.Column) and n.qual is not None:
            return E.Column(n.name)
        return n
    return E.transform(_rebuild_subqueries(e, _strip_stmt), fn)


def _strip_stmt(q):
    if isinstance(q, A.UnionAll):
        return dataclasses.replace(
            q, parts=tuple(_strip_stmt(p) for p in q.parts),
            order_by=tuple(_strip_order(o) for o in q.order_by))
    rel = _map_relation(q.relation, _strip_stmt, _strip_expr)
    if rel is not q.relation:
        q = dataclasses.replace(q, relation=rel)
    return _map_stmt_exprs(q, _strip_expr)
