"""Host (pandas) execution of a full SelectStmt.

The completeness safety net: whatever the device planner cannot push down
runs here — the analog of the reference leaving non-rewritten plans to plain
Spark execution (every DruidTransform returning Nil means Spark's own
strategies plan the query). Also serves as the differential-test oracle.

Supports joins (equi via merge + residual post-filter), scalar/IN/EXISTS
subqueries (uncorrelated inlined once; correlated evaluated row-wise),
aggregates, grouping sets, distinct, order/limit.
"""

from __future__ import annotations

import dataclasses
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

import numpy as np
import pandas as pd

from spark_druid_olap_tpu.ir import expr as E
from spark_druid_olap_tpu.sql import ast as A
from spark_druid_olap_tpu.utils import host_eval


class HostExecError(Exception):
    pass


# SQL-queryable metadata views (≈ DruidMetadataViews.metadataDFs — the
# reference exposes druidrelations/druidservers/druidsegments as resolvable
# tables via a catalog hook, SPLSessionState.scala:67-74)
def _sys_rollups(ctx):
    from spark_druid_olap_tpu.mv.registry import rollups_view
    return rollups_view(ctx)


def _sys_queries(ctx):
    """In-flight queries (state queued/running, live from the engine's
    inflight registry) ahead of the completed history, with uniform
    state / lane / queued_ms / wall_ms columns so load is observable
    while it is happening."""
    rows = []
    for r in ctx.engine.inflight.snapshot():
        rows.append({"state": r["state"], "queryType": r["query_type"],
                     "datasource": r["datasource"],
                     "query_id": r["query_id"], "lane": r["lane"],
                     "tenant": r["tenant"], "startedAt": r["started_at"],
                     "queued_ms": round(r["queued_ms"], 2),
                     "wall_ms": round(r["wall_ms"], 2)})
    for rec in ctx.history.entries():
        d = rec.to_dict()
        wlm = d.get("wlm") or {}
        d.setdefault("state", "completed")
        d.setdefault("lane", wlm.get("lane"))
        d.setdefault("tenant", wlm.get("tenant"))
        d.setdefault("queued_ms", wlm.get("queued_ms", 0.0))
        d.setdefault("wall_ms", d.get("total_ms"))
        rows.append(d)
    return pd.DataFrame(rows)


def _sys_snapshots(ctx):
    """Deep-storage state (persist/): empty frame with the view's schema
    when persistence is off — the view stays queryable either way."""
    if getattr(ctx, "persist", None) is not None:
        return ctx.persist.snapshots_view()
    cols = ["datasource", "version", "state", "current", "rows",
            "bytes", "wal_seq", "wal_bytes", "dirty", "created_at"]
    return pd.DataFrame(columns=cols)


SYS_VIEWS = {
    "sys_datasources": lambda ctx: ctx.catalog.datasources_view(),
    "sys_segments": lambda ctx: ctx.catalog.segments_view(),
    "sys_columns": lambda ctx: ctx.catalog.columns_view(),
    "sys_queries": _sys_queries,
    "sys_lanes": lambda ctx: ctx.engine.wlm.lanes_view(),
    "sys_rollups": _sys_rollups,
    "sys_snapshots": _sys_snapshots,
}


_TLS_INIT_LOCK = __import__("threading").Lock()


def ctx_tls(ctx):
    """Per-context thread-local scratch (temp frames, current query id) —
    concurrent server sessions must not see each other's state. Creation is
    locked: an unsynchronized check-then-set could let two first requests
    each install a threading.local and one lose its state mid-query."""
    tls = getattr(ctx, "_tls", None)
    if tls is None:
        import threading
        with _TLS_INIT_LOCK:
            tls = getattr(ctx, "_tls", None)
            if tls is None:
                tls = ctx._tls = threading.local()
    return tls


def temp_frames(ctx):
    return getattr(ctx_tls(ctx), "temp_frames", None)


def datasource_frame(ctx, name: str, columns=None) -> pd.DataFrame:
    """Materialize a datasource as pandas; ``columns`` (a set) limits the
    materialized columns to those present in the table (callers pass the
    statement's referenced columns — projection pushdown for the host
    tier)."""
    from spark_druid_olap_tpu.parallel.executor import _host_column_values
    temps = temp_frames(ctx)
    if temps and name in temps:
        df = temps[name]
        if columns is not None:
            df = df[[c for c in df.columns if c in columns]]
        return df
    if name in SYS_VIEWS and name not in ctx.store.names():
        return SYS_VIEWS[name](ctx)
    ds = ctx.store.get(name)
    names = ds.column_names()
    if columns is not None:
        names = [c for c in names if c in columns]
    # multi-host partial store: assemble a complete view of the NEEDED
    # columns by a cross-process exchange (cached per column) — the
    # host tier serves ANY query shape on partial stores at O(needed)
    # transfer (VERDICT r4 item 2; ≈ DruidRelation.scala:111's
    # Spark-side fallback scan)
    src = ds
    from spark_druid_olap_tpu.utils.config import HOST_GATHER_PAGE_BYTES
    ds = ds.complete(columns=names,
                     page_bytes=ctx.config.get(HOST_GATHER_PAGE_BYTES))
    if getattr(ds, "gathered_from_partial", False):
        gathered = getattr(src, "_gathered_cols", None)
        if gathered is not None:
            # observable memory guarantee of the (byte-bounded) gather
            # cache — surfaced per statement like the engine's counters
            ctx.engine.last_stats["gathered_bytes"] = int(gathered.bytes)
    data = {c: _host_column_values(ds, c, None) for c in names}
    out = pd.DataFrame(data)
    if len(out.columns) == 0:
        # no referenced columns (e.g. count(*) only): keep the row count
        out.index = range(ds.num_rows)
    return out


_RESULT_CACHE_BOUND = 64


def result_cache(ctx, kind: str, stmt):
    """(cache_dict, key) for session-scoped result caches. Each kind
    ("assist", "subquery") gets its own bounded LRU namespace so the two
    pathways cannot evict each other's entries. The key folds in the
    store version (ingest/drop invalidates) AND the session config
    fingerprint (a timezone or precision change must never serve results
    computed under the old settings)."""
    caches = getattr(ctx, "_result_cache", None)
    if caches is None:
        caches = ctx._result_cache = {}
    cache = caches.get(kind)
    if cache is None:
        cache = caches[kind] = OrderedDict()
    key = (ctx.store.version, ctx.config.fingerprint(), repr(stmt))
    return cache, key


def result_cache_put(cache, key, value):
    """Insert with LRU eviction (oldest-inserted first), keeping the
    cache at most _RESULT_CACHE_BOUND entries *after* the insert."""
    cache[key] = value
    cache.move_to_end(key)
    while len(cache) > _RESULT_CACHE_BOUND:
        cache.popitem(last=False)


def try_engine(ctx, stmt: A.SelectStmt) -> Optional[pd.DataFrame]:
    """Engine-assisted host tier: attempt device pushdown of an
    uncorrelated sub-statement (derived table, inner block of a subquery).

    ≈ the reference's property that a non-rewritten outer plan still gets
    Druid acceleration for rewritable *subtrees* (Catalyst plans each
    relational subtree independently, so a derived table over the fact
    table hits DruidStrategy even when the outer join does not). Returns
    None when the sub-statement cannot push down.
    """
    from spark_druid_olap_tpu.parallel.executor import EngineFallback
    from spark_druid_olap_tpu.planner import builder as B
    from spark_druid_olap_tpu.planner.plans import PlanUnsupported
    cache, key = result_cache(ctx, "assist", stmt)
    if key in cache:
        cache.move_to_end(key)               # keep hot entries resident
        return cache[key]
    try:
        from spark_druid_olap_tpu.planner.decorrelate import \
            inline_subqueries
        from spark_druid_olap_tpu.planner.viewmerge import merge_derived
        from spark_druid_olap_tpu.sql.session import execute_planned
        stmt2 = inline_subqueries(ctx, merge_derived(ctx, stmt))
        pq = B.build(ctx, stmt2)
        df = execute_planned(ctx, pq)
        ctx.history.record(stmt2, {**ctx.engine.last_stats,
                                   "mode": "engine"},
                           sql="(engine-assisted subtree)")
    except (PlanUnsupported, EngineFallback, HostExecError,
            host_eval.HostEvalError, KeyError):
        df = None
    result_cache_put(cache, key, df)
    return df


# -- schema resolution --------------------------------------------------------

def relation_columns(ctx, rel: A.Relation) -> List[str]:
    if isinstance(rel, A.TableRef):
        temps = temp_frames(ctx)
        if temps and rel.name in temps:
            return list(temps[rel.name].columns)
        if rel.name in SYS_VIEWS and rel.name not in ctx.store.names():
            return list(SYS_VIEWS[rel.name](ctx).columns)
        return list(ctx.store.get(rel.name).column_names())
    if isinstance(rel, A.SubqueryRef):
        return select_output_names(ctx, rel.query)
    if isinstance(rel, A.Join):
        return relation_columns(ctx, rel.left) + relation_columns(ctx, rel.right)
    raise HostExecError(f"relation {type(rel).__name__}")


def select_output_names(ctx, stmt) -> List[str]:
    if isinstance(stmt, A.UnionAll):
        return select_output_names(ctx, stmt.parts[0])
    names = []
    for i, item in enumerate(stmt.items):
        if item.expr == "*" or (isinstance(item.expr, E.Column)
                                and item.expr.name == "*"):
            if stmt.relation is not None:
                names.extend(relation_columns(ctx, stmt.relation))
            continue
        if item.alias:
            names.append(item.alias)
        elif isinstance(item.expr, E.Column):
            names.append(item.expr.name)
        else:
            names.append(f"_c{i}")
    return names


# -- subquery handling --------------------------------------------------------

def _subquery_nodes(e: E.Expr):
    for n in E.walk(e):
        if isinstance(n, (A.ScalarSubquery, A.InSubquery, A.Exists)):
            yield n


def _free_columns(ctx, stmt) -> set:
    """Columns referenced by ``stmt`` that its own relation doesn't provide
    (i.e. correlation bindings)."""
    if isinstance(stmt, A.UnionAll):
        out = set()
        for p in stmt.parts:
            out |= _free_columns(ctx, p)
        return out
    visible = set(relation_columns(ctx, stmt.relation)) \
        if stmt.relation is not None else set()
    for i, item in enumerate(stmt.items):
        if item.alias:
            visible.add(item.alias)
    refs = set()

    def collect(e):
        if e is None or isinstance(e, str):
            return
        for n in E.walk(e):
            if isinstance(n, E.Column) and n.name != "*":
                refs.add(n.name)
            elif isinstance(n, (A.ScalarSubquery, A.Exists)):
                refs.update(_free_columns(ctx, n.query))
            elif isinstance(n, A.InSubquery):
                refs.update(_free_columns(ctx, n.query))

    for item in stmt.items:
        collect(item.expr if item.expr != "*" else None)
    collect(stmt.where)
    gb = stmt.group_by
    if isinstance(gb, tuple):
        for g in gb:
            collect(g)
    elif isinstance(gb, A.GroupingSets):
        for s in gb.sets:
            for g in s:
                collect(g)
    collect(stmt.having)
    for o in stmt.order_by:
        collect(o.expr)

    def collect_join_conds(rel):
        # Join ON conditions are expressions of THIS scope (a correlated
        # reference may live there); derived-table bodies declare their
        # own free columns via relation_columns, not here
        if isinstance(rel, A.Join):
            collect(rel.condition)
            collect_join_conds(rel.left)
            collect_join_conds(rel.right)

    collect_join_conds(stmt.relation)
    return refs - visible


def resolve_subqueries(ctx, e: E.Expr, env: Dict[str, np.ndarray],
                       outer_env: Optional[dict] = None) -> E.Expr:
    """Replace subquery nodes with literal values/lists/flags.

    Uncorrelated subqueries execute once. Equality-correlated ones are
    decorrelated into one grouped/semi-joined inner execution; the rest
    evaluate row-wise (slow path — the reference likewise leaves these to
    Spark)."""
    subs = list(_subquery_nodes(e))
    if not subs:
        return e

    n_rows = None
    for v in env.values():
        n_rows = len(v)
        break

    def replace(node):
        if isinstance(node, (A.ScalarSubquery, A.Exists, A.InSubquery)):
            free = _free_columns(ctx, node.query)
            free = {f for f in free if f in env or
                    (outer_env is not None and f in outer_env)}
            if not free:
                val = _execute_sub_once(ctx, node, outer_env)
                return val
            val = _execute_sub_decorrelated(ctx, node, env, free, n_rows,
                                            outer_env)
            if val is not None:
                return val
            return _execute_sub_rowwise(ctx, node, env, free, n_rows,
                                        outer_env)
        return node

    return E.transform(e, replace)


def _execute_sub_once(ctx, node, outer_env):
    df = None
    if not outer_env and getattr(ctx, "host_engine_assist", True):
        df = try_engine(ctx, node.query)
    if df is None:
        df = execute_select(ctx, node.query, outer_env=outer_env)
    if isinstance(node, A.ScalarSubquery):
        if df.shape[0] == 0:
            return E.Literal(None)
        return E.Literal(df.iloc[0, 0])
    if isinstance(node, A.Exists):
        flag = (len(df) > 0) != node.negated
        return E.Literal(flag)
    from spark_druid_olap_tpu.planner.decorrelate import build_in_list_expr
    return build_in_list_expr(node.child, df.iloc[:, 0], node.negated)


_PrecomputedColumn = host_eval.Precomputed


def _expr_refs(ctx, e) -> set:
    """Column names referenced by ``e``, including the *free* columns of any
    nested subquery (a nested subquery's own columns are not references)."""
    refs = set()
    for n in E.walk(e):
        if isinstance(n, E.Column) and n.name != "*":
            refs.add(n.name)
        elif isinstance(n, (A.ScalarSubquery, A.Exists, A.InSubquery)):
            refs.update(_free_columns(ctx, n.query))
    return refs


def _has_subquery(e) -> bool:
    return any(True for _ in _subquery_nodes(e))


def _relation_free_refs(ctx, rel) -> set:
    """Free/outer references made from inside a FROM clause."""
    if rel is None or isinstance(rel, A.TableRef):
        return set()
    if isinstance(rel, A.SubqueryRef):
        return _free_columns(ctx, rel.query)
    if isinstance(rel, A.Join):
        r = _relation_free_refs(ctx, rel.left) | \
            _relation_free_refs(ctx, rel.right)
        if rel.condition is not None:
            r |= _expr_refs(ctx, rel.condition)
        return r
    return set()


def _outer_key_array(env, outer_env, name, n_rows):
    if name in env:
        v = np.asarray(env[name])
        return v if v.ndim > 0 else np.broadcast_to(v, (n_rows,))
    v = (outer_env or {}).get(name)
    if isinstance(v, np.ndarray) and v.ndim > 0:
        return None  # array from a different scope; length unknown — bail
    return np.full(n_rows, v, dtype=object) if isinstance(v, str) else \
        np.broadcast_to(np.asarray(v), (n_rows,))


def _align_key(left: pd.Series, right: pd.Series):
    """Promote two merge-key columns to a common dtype so pandas joins them."""
    lk, rk = left.to_numpy(), right.to_numpy()
    if lk.dtype == object or rk.dtype == object:
        return left.astype(object), right.astype(object)
    if lk.dtype != rk.dtype:
        try:
            t = np.result_type(lk.dtype, rk.dtype)
            return left.astype(t), right.astype(t)
        except TypeError:
            return left.astype(object), right.astype(object)
    return left, right


_MINMAX_FLIP = E.FLIP_CMP


def _residual_minmax(ctx, c, free, inner_cols):
    """(op, inner_expr, outer_col_name) when the residual conjunct is a
    single comparison 'inner_expr <op> outer_col' with op in
    {<, <=, >, >=, <>} — decidable from per-key (min, max) of the inner
    expression. op is normalized so the inner side reads on the LEFT.
    Returns None for any other shape."""
    if not isinstance(c, E.Comparison) \
            or c.op not in ("<", "<=", ">", ">=", "<>", "!="):
        return None
    for a, b, op in ((c.left, c.right, c.op),
                     (c.right, c.left, _MINMAX_FLIP.get(c.op, c.op))):
        if isinstance(b, E.Column) and b.name in free:
            try:
                arefs = _expr_refs(ctx, a)
            except Exception:  # noqa: BLE001
                return None
            if arefs and not (arefs & free) and arefs <= inner_cols \
                    and not _has_subquery(a):
                return ("<>" if op == "!=" else op, a, b.name)
    return None


def _execute_sub_decorrelated(ctx, node, env, free, n_rows, outer_env):
    """Vectorized correlated-subquery evaluation.

    Classic decorrelation: when every outer reference occurs only in
    top-level equality conjuncts of the inner WHERE (plus, for EXISTS/IN,
    residual predicates over plain inner columns), run the inner query ONCE —
    grouped by (for scalar aggregates) or projected onto (for EXISTS/IN) the
    correlation keys — then join the result back to the outer rows. The
    reference leaves correlated subqueries to Spark, whose optimizer performs
    the same rewrite (``RewriteCorrelatedScalarSubquery``); this is our host
    analog. Returns a ``Precomputed`` column or ``None`` to fall back to the
    row-wise path.
    """
    q = node.query
    if q.relation is None or q.limit is not None or q.having is not None:
        return None
    if _relation_free_refs(ctx, q.relation) & free:
        return None
    aggs = []
    for item in q.items:
        if item.expr != "*":
            aggs.extend(E.agg_calls_in(item.expr))
    is_scalar = isinstance(node, A.ScalarSubquery)
    if is_scalar:
        if len(q.items) != 1 or q.items[0].expr == "*" or not aggs \
                or q.group_by is not None or q.distinct:
            return None
        if _expr_refs(ctx, q.items[0].expr) & free:
            return None
    else:
        if q.group_by is not None or aggs:
            return None
        if isinstance(node, A.InSubquery):
            if not q.items or q.items[0].expr == "*" or \
                    _expr_refs(ctx, q.items[0].expr) & free or \
                    _has_subquery(q.items[0].expr):
                return None
    try:
        inner_cols = set(relation_columns(ctx, q.relation))
    except Exception:
        return None
    # classify WHERE conjuncts
    join_pairs = []        # (free col name, inner key expr)
    inner_conjs = []       # pushed into the single inner execution
    residual_conjs = []    # evaluated post-join (EXISTS/IN only)
    for c in _split_conjuncts(q.where):
        refs = _expr_refs(ctx, c)
        fref = refs & free
        if not fref:
            inner_conjs.append(c)
            continue
        pair = None
        if isinstance(c, E.Comparison) and c.op == "=" and \
                not _has_subquery(c):
            for a, b in ((c.left, c.right), (c.right, c.left)):
                if isinstance(a, E.Column) and a.name in free:
                    brefs = _expr_refs(ctx, b)
                    if not (brefs & free) and brefs <= inner_cols:
                        pair = (a.name, b)
                        break
        if pair is not None:
            join_pairs.append(pair)
            continue
        if is_scalar:
            return None        # scalar aggs need pure equality correlation
        rrefs = refs - free
        if not (rrefs <= inner_cols) or _has_subquery(c):
            return None
        residual_conjs.append(c)
    if not join_pairs:
        return None

    inner_where = None
    for c in inner_conjs:
        inner_where = c if inner_where is None else E.And((inner_where, c))

    # EXISTS with exactly one ordered/inequality residual against one
    # outer column -> per-key min/max instead of the row-level join:
    # 'exists inner.c <op> outer.c' is decidable from (min(c), max(c))
    # per correlation key, so the inner collapses to a GROUPED aggregate
    # (engine-pushable) and the probe is a key-merge + vector compare —
    # never the outer x inner-set cross product (TPC-H q21 shape;
    # Spark's RewritePredicateSubquery + agg pushdown does the same).
    minmax = None                  # (op, inner_expr, outer_free_name)
    if isinstance(node, A.Exists) and len(residual_conjs) == 1:
        minmax = _residual_minmax(ctx, residual_conjs[0], free, inner_cols)

    jk_cols = [f"__jk{j}" for j in range(len(join_pairs))]
    items = [A.SelectItem(b, jk_cols[j])
             for j, (_, b) in enumerate(join_pairs)]
    residual_cols = sorted(set().union(
        *[_expr_refs(ctx, c) - free for c in residual_conjs])) \
        if residual_conjs else []
    if minmax is None:
        for rc in residual_cols:
            items.append(A.SelectItem(E.Column(rc), rc))
    if is_scalar:
        items.append(A.SelectItem(q.items[0].expr, "__val"))
        q2 = dataclasses.replace(
            q, items=tuple(items), where=inner_where,
            group_by=tuple(b for _, b in join_pairs), having=None,
            order_by=(), limit=None)
    elif minmax is not None:
        items.append(A.SelectItem(E.AggCall("min", minmax[1]), "__mn"))
        items.append(A.SelectItem(E.AggCall("max", minmax[1]), "__mx"))
        q2 = dataclasses.replace(
            q, items=tuple(items), where=inner_where,
            group_by=tuple(b for _, b in join_pairs), having=None,
            order_by=(), limit=None, distinct=False)
    else:
        if isinstance(node, A.InSubquery):
            items.append(A.SelectItem(q.items[0].expr, "__inval"))
        q2 = dataclasses.replace(
            q, items=tuple(items), where=inner_where, group_by=None,
            having=None, order_by=(), limit=None, distinct=False)
    df2 = None
    if not outer_env and getattr(ctx, "host_engine_assist", True):
        df2 = try_engine(ctx, q2)
    if df2 is None:
        try:
            df2 = execute_select(ctx, q2, outer_env=outer_env)
        except (HostExecError, host_eval.HostEvalError):
            return None

    # outer side
    outer = {}
    for j, (f, _) in enumerate(join_pairs):
        arr = _outer_key_array(env, outer_env, f, n_rows)
        if arr is None:
            return None
        outer[f"__ok{j}"] = arr
    ok_cols = list(outer.keys())
    if isinstance(node, A.InSubquery):
        ch = host_eval.eval_expr(
            resolve_subqueries(ctx, node.child, env, outer_env), env)
        ch = np.asarray(ch)
        outer["__okv"] = ch if ch.ndim > 0 else \
            np.broadcast_to(ch, (n_rows,))
        ok_cols.append("__okv")
    res_free = set().union(
        *[_expr_refs(ctx, c) & free for c in residual_conjs]) \
        if residual_conjs else set()
    for f in sorted(res_free):
        arr = _outer_key_array(env, outer_env, f, n_rows)
        if arr is None:
            return None
        outer[f"__of_{f}"] = arr
    odf = pd.DataFrame(outer)
    odf["__oidx"] = np.arange(n_rows)

    right_keys = list(jk_cols)
    # NULL never equi-matches (pandas merge would pair NaN with NaN): drop
    # NULL-keyed inner rows; NULL-keyed outer rows then simply never match
    if len(df2):
        df2 = df2[~df2[right_keys].isna().any(axis=1)]
    key_ok_cols = [c for c in ok_cols if c != "__okv"]
    for lc, rc in zip(key_ok_cols, right_keys):
        odf[lc], df2[rc] = _align_key(odf[lc], df2[rc])
    if isinstance(node, A.InSubquery):
        odf["__okv"], df2["__inval"] = _align_key(odf["__okv"],
                                                  df2["__inval"])

    if is_scalar:
        merged = odf.merge(df2, left_on=ok_cols, right_on=right_keys,
                           how="left", sort=False, indicator=True)
        merged = merged.drop_duplicates("__oidx").sort_values("__oidx")
        vals = merged["__val"].to_numpy()
        # an outer row with no matching group still sees the inner GLOBAL
        # aggregate's one identity row: evaluate the select expression over
        # the empty group (count->0, sum/min/max/avg->NULL)
        unmatched = (merged["_merge"] == "left_only").to_numpy()
        if unmatched.any():
            fill = _empty_group_value(q.items[0].expr)
            vals = vals.copy()
            vals[unmatched] = fill
        return _PrecomputedColumn(vals)

    negated = getattr(node, "negated", False)
    if minmax is not None:
        op, _, fname = minmax
        if df2["__mn"].dtype.kind == "M":
            return None    # datetime min/max: row-wise fallback
        merged = odf.merge(df2, left_on=key_ok_cols, right_on=right_keys,
                           how="left", sort=False) \
            .drop_duplicates("__oidx").sort_values("__oidx")
        mn = merged["__mn"].to_numpy()
        mx = merged["__mx"].to_numpy()
        ocv = merged[f"__of_{fname}"].to_numpy()
        str_mode = mn.dtype == object       # lexicographic string min/max
        if not str_mode and ocv.dtype == object:
            ocv = pd.to_numeric(pd.Series(ocv), errors="coerce").to_numpy()
        # ordered compares are UNKNOWN on NULL (no group / all-NULL inner
        # / NULL probe) — EXISTS' UNKNOWN-drops-row rule; evaluated under
        # an explicit validity mask so string mode never compares None
        valid = (pd.Series(mn).notna() & pd.Series(ocv).notna()).to_numpy()
        hit = np.zeros(len(mn), dtype=bool)
        try:
            if op == "<":
                hit[valid] = mn[valid] < ocv[valid]
            elif op == "<=":
                hit[valid] = mn[valid] <= ocv[valid]
            elif op == ">":
                hit[valid] = mx[valid] > ocv[valid]
            elif op == ">=":
                hit[valid] = mx[valid] >= ocv[valid]
            else:                  # '<>'
                hit[valid] = (mn[valid] != ocv[valid]) \
                    | (mx[valid] != ocv[valid])
        except TypeError:
            return None            # mixed-type compare: row-wise fallback
        return _PrecomputedColumn(hit ^ negated)
    if isinstance(node, A.InSubquery) and not residual_conjs:
        # Fast path (no residual predicates): never materialize the
        # outer x per-key-inner-set cross product. Membership is a
        # keys+value equi-merge; the per-group facts 3VL needs (set
        # non-empty? contains NULL?) come from one groupby over df2.
        member = np.zeros(n_rows, dtype=bool)
        dfv = df2[df2["__inval"].notna()]
        hitm = odf[pd.Series(outer["__okv"]).notna().to_numpy()].merge(
            dfv, left_on=key_ok_cols + ["__okv"],
            right_on=right_keys + ["__inval"], how="inner", sort=False)
        if len(hitm):
            member[hitm["__oidx"].unique()] = True
        if len(df2):
            g = df2.groupby(right_keys, sort=False, dropna=False)["__inval"] \
                .agg([("__n", "size"),
                      ("__nulls", lambda s: s.isna().any())]).reset_index()
            stat = odf.merge(g, left_on=key_ok_cols, right_on=right_keys,
                             how="left", sort=False).drop_duplicates("__oidx") \
                .sort_values("__oidx")
            has_group = stat["__n"].notna().to_numpy()
            has_null_inner = stat["__nulls"].fillna(False).to_numpy(bool)
        else:
            has_group = np.zeros(n_rows, dtype=bool)
            has_null_inner = has_group
        return _PrecomputedColumn(_in_flags(
            member, has_group, has_null_inner,
            pd.isna(pd.Series(outer["__okv"])).to_numpy(), negated))

    merged = odf.merge(df2, left_on=key_ok_cols, right_on=right_keys,
                       how="inner", sort=False)
    if residual_conjs:
        menv = {}
        for j, (f, _) in enumerate(join_pairs):
            menv[f] = merged[f"__ok{j}"].to_numpy()
        for f in res_free:
            menv[f] = merged[f"__of_{f}"].to_numpy()
        for rc in residual_cols:
            menv[rc] = merged[rc].to_numpy()
        mask = np.ones(len(merged), dtype=bool)
        for c in residual_conjs:
            mask &= host_eval.eval_pred3(c, menv)
        merged = merged[mask]
    if isinstance(node, A.InSubquery):
        # residual path: merged rows = each outer row's correlated inner set
        member = np.zeros(n_rows, dtype=bool)
        has_group = np.zeros(n_rows, dtype=bool)
        has_null_inner = np.zeros(n_rows, dtype=bool)
        if len(merged):
            has_group[merged["__oidx"].unique()] = True
            nulls = merged["__inval"].isna()
            if nulls.any():
                has_null_inner[merged.loc[nulls, "__oidx"].unique()] = True
            hit = (merged["__okv"].notna() & merged["__inval"].notna() &
                   (merged["__okv"] == merged["__inval"]))
            if hit.any():
                member[merged.loc[hit, "__oidx"].unique()] = True
        return _PrecomputedColumn(_in_flags(
            member, has_group, has_null_inner,
            pd.isna(pd.Series(outer["__okv"])).to_numpy(), negated))
    flags = np.zeros(n_rows, dtype=bool)
    if len(merged):
        flags[merged["__oidx"].unique()] = True
    return _PrecomputedColumn(flags ^ negated)


def _in_flags(member, has_group, has_null_inner, nan_child, negated):
    """SQL 3VL for ``x [NOT] IN S``: membership needs a non-NULL equal pair;
    otherwise the result is UNKNOWN (-> false) when S is non-empty and x is
    NULL or S contains NULL; NOT IN over an empty S is TRUE."""
    if not negated:
        return member
    return ~member & ~(has_group & (nan_child | has_null_inner))


def _empty_group_value(expr):
    """Value of a scalar-aggregate select expression over zero input rows
    (count -> 0, other aggregates -> NULL, then the surrounding arithmetic)."""
    def rep(n):
        if isinstance(n, E.AggCall):
            return E.Literal(0 if n.fn == "count" else None)
        return n
    try:
        v = host_eval.eval_expr(E.transform(expr, rep), {})
        return v.item() if isinstance(v, np.generic) else v
    except Exception:
        return None


def _execute_sub_rowwise(ctx, node, env, free, n_rows, outer_env):
    results = []
    child_vals = None
    if isinstance(node, A.InSubquery):
        ch = host_eval.eval_expr(resolve_subqueries(ctx, node.child, env,
                                                    outer_env), env)
        child_vals = np.broadcast_to(np.asarray(ch, dtype=object), (n_rows,))
    for i in range(n_rows):
        row_env = dict(outer_env or {})
        for f in free:
            src = env if f in env else (outer_env or {})
            v = src[f]
            row_env[f] = v[i] if isinstance(v, np.ndarray) else v
        df = execute_select(ctx, node.query, outer_env=row_env)
        if isinstance(node, A.ScalarSubquery):
            results.append(None if len(df) == 0 else df.iloc[0, 0])
        elif isinstance(node, A.Exists):
            results.append((len(df) > 0) != node.negated)
        else:
            # SQL 3VL: a NULL probe, or a miss against a NULL-bearing
            # list, is UNKNOWN (never TRUE under either polarity)
            inner = df.iloc[:, 0]
            probe = child_vals[i]
            probe_null = probe is None or (isinstance(probe, float)
                                           and np.isnan(probe))
            inset = (not probe_null
                     and probe in set(inner.dropna()))
            if inset:
                results.append(not node.negated)
            elif len(inner) and (probe_null or inner.isna().any()):
                results.append(False)          # UNKNOWN -> drop
            else:
                results.append(bool(node.negated))
    arr = np.array(results, dtype=object)
    try:
        arr = arr.astype(np.float64)
    except (ValueError, TypeError):
        pass
    return _PrecomputedColumn(arr)


# -- relation materialization -------------------------------------------------

def _split_conjuncts(e: Optional[E.Expr]) -> List[E.Expr]:
    if e is None:
        return []
    if isinstance(e, E.And):
        out = []
        for p in e.parts:
            out.extend(_split_conjuncts(p))
        return out
    return [e]


def materialize_relation(ctx, rel: A.Relation, outer_env: Optional[dict],
                         need=None) -> pd.DataFrame:
    """``need``: optional set of columns the enclosing statement references
    — projection pushdown for the host tier; join keys/conditions are added
    as the walk descends. None = everything."""
    if isinstance(rel, A.TableRef):
        return datasource_frame(ctx, rel.name, columns=need)
    if isinstance(rel, A.SubqueryRef):
        if isinstance(rel.query, A.UnionAll):
            return _materialize_union(ctx, rel.query, outer_env)
        if getattr(ctx, "host_engine_assist", True):
            df = try_engine(ctx, rel.query)
            if df is not None:
                return df
        return execute_select(ctx, rel.query, outer_env=outer_env)
    if isinstance(rel, A.Join):
        if need is not None and rel.condition is not None:
            need = need | _expr_refs(ctx, rel.condition)
        left = materialize_relation(ctx, rel.left, outer_env, need)
        right = materialize_relation(ctx, rel.right, outer_env, need)
        conjs = _split_conjuncts(rel.condition)
        eq_pairs = []
        residual = []
        for c in conjs:
            if (isinstance(c, E.Comparison) and c.op == "=" and
                    isinstance(c.left, E.Column) and
                    isinstance(c.right, E.Column)):
                l, r = c.left.name, c.right.name
                if l in left.columns and r in right.columns:
                    eq_pairs.append((l, r))
                    continue
                if r in left.columns and l in right.columns:
                    eq_pairs.append((r, l))
                    continue
            residual.append(c)
        how = {"inner": "inner", "left": "left", "cross": "cross"}[rel.kind]
        if how == "left" and residual:
            # an outer join's ON residual filters the match, not the output:
            # right-only predicates pre-filter the right side (the null
            # extension survives); mixed-side residuals are unsupported
            kept = []
            for c in residual:
                # _expr_refs (not columns_in) so a nested subquery's free
                # correlated columns count as references of this predicate
                cols = _expr_refs(ctx, c)
                if cols <= set(right.columns):
                    renv = {k: right[k].to_numpy() for k in cols}
                    c2 = resolve_subqueries(ctx, c, renv, outer_env)
                    m = host_eval.eval_pred3(c2, renv)
                    right = right[m].reset_index(drop=True)
                else:
                    kept.append(c)
            if kept:
                raise HostExecError(
                    "LEFT JOIN with mixed-side non-equi ON condition")
            residual = []
        if eq_pairs:
            lk = [p[0] for p in eq_pairs]
            rk = [p[1] for p in eq_pairs]
            df = left.merge(right, left_on=lk, right_on=rk, how="inner"
                            if how == "cross" else how)
        elif how == "left" and len(right) == 0:
            # ON condition matched nothing on the right: every left row
            # survives null-extended
            df = left.copy()
            for c in right.columns:
                df[c] = np.nan
        else:
            df = left.merge(right, how="cross")
        if residual:
            env = {c: df[c].to_numpy() for c in df.columns}
            if outer_env:
                # correlated references inside a JOIN ON condition read
                # the enclosing row's scalars (broadcast by eval)
                for k, v in outer_env.items():
                    if k not in env and not isinstance(v, np.ndarray):
                        env[k] = np.full(len(df), v, dtype=object) \
                            if isinstance(v, str) else v
            mask = np.ones(len(df), dtype=bool)
            for c in residual:
                c2 = resolve_subqueries(ctx, c, env, outer_env)
                mask &= host_eval.eval_pred3(c2, env)
            df = df[mask].reset_index(drop=True)
        return df
    raise HostExecError(f"relation {type(rel).__name__}")


# -- aggregation --------------------------------------------------------------

def _agg_key(call: E.AggCall) -> str:
    return E.to_sql(call)


def _grp_key(e: E.Expr) -> str:
    return E.to_sql(e)


def _replace_for_output(e: E.Expr, agg_cols: Dict[str, str],
                        grp_cols: Dict[str, str]) -> E.Expr:
    def rep(n):
        if isinstance(n, E.AggCall) and _agg_key(n) in agg_cols:
            return E.Column(agg_cols[_agg_key(n)])
        return n

    # replace whole group-expr subtrees first (top-down), then agg calls
    def walk_replace(n):
        k = _grp_key(n)
        if k in grp_cols:
            return E.Column(grp_cols[k])
        if isinstance(n, E.AggCall):
            return rep(n)
        # rebuild children
        return None

    def go(n):
        r = walk_replace(n)
        if r is not None:
            return r
        return E.transform(n, rep)

    k = _grp_key(e)
    if k in grp_cols:
        return E.Column(grp_cols[k])
    return go(e)


def _compute_agg(series_env, df, call: E.AggCall, ctx, outer_env, group_ids,
                 n_groups):
    """Aggregate one AggCall over group ids -> array [n_groups]."""
    if call.arg is None:
        vals = np.ones(len(df), dtype=np.int64)
    else:
        arg = resolve_subqueries(ctx, call.arg, series_env, outer_env)
        vals = np.asarray(host_eval.eval_expr(arg, series_env))
        vals = np.broadcast_to(vals, (len(df),)) if vals.ndim == 0 else vals
    s = pd.Series(vals)
    g = pd.Series(group_ids)
    if call.fn == "count":
        if call.distinct:
            out = s.groupby(g).nunique()
        elif call.arg is None:
            out = s.groupby(g).size()
        else:
            out = s.groupby(g).count()
    elif call.fn == "sum":
        out = s.groupby(g).sum()
    elif call.fn == "min":
        out = s.groupby(g).min()
    elif call.fn == "max":
        out = s.groupby(g).max()
    elif call.fn == "avg":
        out = s.groupby(g).mean()
    elif call.fn == "theta":
        # theta-sketch-class approx distinct: the host tier computes exact
        # (nunique already excludes nulls, like the count-distinct branch)
        out = s.groupby(g).nunique()
    elif call.fn == "percentile":
        # host tier computes the exact quantile (the KLL estimate is
        # checked against this within the configured rank-error bound)
        out = s.astype(np.float64).groupby(g).quantile(call.fraction)
    else:
        raise HostExecError(f"aggregate {call.fn}")
    full = out.reindex(range(n_groups))
    if call.fn in ("count", "theta"):
        # keep counts integer: fillna promotes to float64
        full = full.fillna(0).astype(np.int64)
    return full.to_numpy()


def _stmt_column_refs(ctx, stmt: A.SelectStmt):
    """Columns the statement references (incl. free columns of nested
    subqueries), or None when a '*' item needs everything."""
    refs = set()

    def add(e):
        if e is None:
            return
        refs.update(_expr_refs(ctx, e))

    for item in stmt.items:
        if item.expr == "*" or (isinstance(item.expr, E.Column)
                                and item.expr.name == "*"):
            return None
        add(item.expr)
    add(stmt.where)
    add(stmt.having)
    gb = stmt.group_by
    if isinstance(gb, A.GroupingSets):
        for s in gb.sets:
            for g in s:
                add(g)
    elif gb is not None:
        for g in gb:
            add(g)
    for o in stmt.order_by:
        add(o.expr)
    return refs


def execute_select(ctx, stmt: A.SelectStmt,
                   outer_env: Optional[dict] = None) -> pd.DataFrame:
    # FROM
    if stmt.relation is None:
        df = pd.DataFrame({"__dummy__": [0]})
    else:
        # column-pruned materialization: only decode columns the statement
        # (or a join condition on the way down) references — the host-tier
        # analog of projection pushdown; decoding every string column of a
        # fact table dwarfs the actual query work otherwise
        need = _stmt_column_refs(ctx, stmt)
        df = materialize_relation(ctx, stmt.relation, outer_env, need)
    env = {c: df[c].to_numpy() for c in df.columns}
    if outer_env:
        for k, v in outer_env.items():
            if k not in env:
                env[k] = v

    # WHERE
    if stmt.where is not None:
        w = resolve_subqueries(ctx, stmt.where, env, outer_env)
        mask = host_eval.eval_pred3(w, env)
        mask = np.broadcast_to(mask, (len(df),)).astype(bool)
        df = df[mask].reset_index(drop=True)
        env = {c: df[c].to_numpy() for c in df.columns}
        if outer_env:
            for k, v in outer_env.items():
                if k not in env:
                    env[k] = v

    # aggregate detection
    agg_calls: Dict[str, E.AggCall] = {}

    def collect_aggs(e):
        if e is None or isinstance(e, str):
            return
        for n in E.walk(e):
            if isinstance(n, E.AggCall):
                agg_calls[_agg_key(n)] = n

    for item in stmt.items:
        collect_aggs(item.expr if item.expr != "*" else None)
    collect_aggs(stmt.having)
    for o in stmt.order_by:
        collect_aggs(o.expr)

    is_agg = bool(agg_calls) or stmt.group_by is not None

    out_names = select_output_names(ctx, stmt)

    if not is_agg:
        out = {}
        cols = []
        for i, item in enumerate(stmt.items):
            if item.expr == "*" or (isinstance(item.expr, E.Column)
                                    and item.expr.name == "*"):
                for c in df.columns:
                    out[c] = df[c].to_numpy()
                    cols.append(c)
                continue
            name = out_names[len(cols)]
            e2 = resolve_subqueries(ctx, item.expr, env, outer_env)
            v = host_eval.eval_expr(e2, env)
            v = np.broadcast_to(np.asarray(v), (len(df),)) \
                if np.ndim(v) == 0 else np.asarray(v)
            out[name] = v
            cols.append(name)
        res = pd.DataFrame({c: out[c] for c in cols})
        return _order_limit_distinct(ctx, res, stmt, env)

    # group sets
    if isinstance(stmt.group_by, A.GroupingSets):
        group_sets = [list(s) for s in stmt.group_by.sets]
    elif stmt.group_by is None:
        group_sets = [[]]
    else:
        group_sets = [list(stmt.group_by)]
    # resolve ordinal / alias group keys
    alias_map = {}
    for i, item in enumerate(stmt.items):
        if item.alias and item.expr != "*":
            alias_map[item.alias] = item.expr
    resolved_sets = []
    for gs in group_sets:
        rs = []
        for g in gs:
            if isinstance(g, E.Literal) and isinstance(g.value, int):
                rs.append(stmt.items[g.value - 1].expr)
            elif isinstance(g, E.Column) and g.name in alias_map:
                rs.append(alias_map[g.name])
            else:
                rs.append(g)
        resolved_sets.append(rs)

    all_group_exprs = []
    seen = set()
    for rs in resolved_sets:
        for g in rs:
            k = _grp_key(g)
            if k not in seen:
                seen.add(k)
                all_group_exprs.append(g)

    frames = []
    for rs in resolved_sets:
        frames.append(_one_grouping(ctx, stmt, df, env, rs, all_group_exprs,
                                    agg_calls, outer_env, out_names))
    res = pd.concat(frames, ignore_index=True) if len(frames) > 1 else frames[0]
    return _order_limit_distinct(ctx, res, stmt, env)


def _one_grouping(ctx, stmt, df, env, group_exprs, all_group_exprs, agg_calls,
                  outer_env, out_names):
    n = len(df)
    grp_cols: Dict[str, str] = {}
    key_arrays = []
    for j, g in enumerate(group_exprs):
        e2 = resolve_subqueries(ctx, g, env, outer_env)
        v = np.asarray(host_eval.eval_expr(e2, env))
        v = np.broadcast_to(v, (n,)) if v.ndim == 0 else v
        grp_cols[_grp_key(g)] = f"__grp{j}"
        key_arrays.append(v)
    if key_arrays:
        key_df = pd.DataFrame({f"__grp{j}": key_arrays[j]
                               for j in range(len(key_arrays))})
        codes, uniques = pd.factorize(
            pd.MultiIndex.from_frame(key_df)) if len(key_arrays) > 1 else \
            pd.factorize(key_df["__grp0"])
        group_ids = codes
        n_groups = len(uniques)
    else:
        group_ids = np.zeros(n, dtype=np.int64)
        n_groups = 1
    if n == 0:
        # grouped agg over zero rows -> zero groups; GLOBAL agg over zero
        # rows -> one row (NULL sums, 0 counts) per SQL semantics
        n_groups = 0 if key_arrays else 1

    agg_cols: Dict[str, str] = {}
    gagg = {}
    for j, (k, call) in enumerate(agg_calls.items()):
        cname = f"__agg{j}"
        agg_cols[k] = cname
        gagg[cname] = _compute_agg(env, df, call, ctx, outer_env, group_ids,
                                   n_groups)

    # group key values per group
    gkey = {}
    if key_arrays and n_groups > 0:
        first_idx = np.zeros(n_groups, dtype=np.int64)
        seen = np.zeros(n_groups, dtype=bool)
        for i, gid in enumerate(group_ids):
            if not seen[gid]:
                seen[gid] = True
                first_idx[gid] = i
        for j in range(len(key_arrays)):
            gkey[f"__grp{j}"] = key_arrays[j][first_idx]

    genv = {**gkey, **gagg}

    # HAVING
    keep = None
    if stmt.having is not None:
        h = _replace_for_output(
            resolve_subqueries(ctx, stmt.having, env, outer_env),
            agg_cols, grp_cols)
        keep = host_eval.eval_pred3(h, genv)

    out = {}
    cols = []
    for i, item in enumerate(stmt.items):
        if item.expr == "*":
            raise HostExecError("SELECT * with GROUP BY")
        name = out_names[i]
        e2 = _replace_for_output(
            resolve_subqueries(ctx, item.expr, env, outer_env),
            agg_cols, grp_cols)
        # group expr not in this grouping set -> null fill (grouping sets)
        try:
            v = host_eval.eval_expr(e2, genv)
        except host_eval.HostEvalError:
            v = np.full(n_groups, None, dtype=object)
        v = np.broadcast_to(np.asarray(v), (n_groups,)) \
            if np.ndim(v) == 0 else np.asarray(v)
        out[name] = v
        cols.append(name)
    res = pd.DataFrame({c: pd.Series(out[c]) for c in cols})
    if keep is not None:
        res = res[keep].reset_index(drop=True)
    # stash order-by helper columns
    res.attrs["agg_cols"] = agg_cols
    res.attrs["grp_cols"] = grp_cols
    res.attrs["genv"] = genv
    res.attrs["keep"] = keep
    return res


def finish_union(frames, u: A.UnionAll) -> pd.DataFrame:
    """Concatenate UNION ALL branch frames positionally under the first
    branch's names and apply the union's trailing ORDER BY / OFFSET /
    LIMIT (the one implementation shared by the session and host
    tiers)."""
    cols = None
    aligned = []
    for i, df in enumerate(frames):
        if cols is None:
            cols = list(df.columns)
        elif len(df.columns) != len(cols):
            raise HostExecError(
                f"UNION ALL branch {i} has {len(df.columns)} columns, "
                f"expected {len(cols)}")
        else:
            df = df.copy(deep=False)
            df.columns = cols
        aligned.append(df)
    out = pd.concat(aligned, ignore_index=True)
    if u.order_by:
        sort_cols, asc = [], []
        for o in u.order_by:
            e = o.expr
            if isinstance(e, E.Literal) and isinstance(e.value, int):
                if not 1 <= e.value <= len(cols):
                    raise HostExecError(
                        f"ORDER BY ordinal {e.value} out of range "
                        f"(1..{len(cols)})")
                col = cols[e.value - 1]
            elif isinstance(e, E.Column) and e.name in cols:
                col = e.name
            else:
                raise HostExecError(
                    "UNION ORDER BY must reference output columns")
            sort_cols.append(col)
            asc.append(o.ascending)
        out = out.sort_values(sort_cols, ascending=asc,
                              kind="mergesort").reset_index(drop=True)
    if u.offset:
        out = out.iloc[u.offset:].reset_index(drop=True)
    if u.limit is not None:
        out = out.head(u.limit).reset_index(drop=True)
    return out


def _materialize_union(ctx, u: A.UnionAll, outer_env):
    """Derived UNION ALL: branches materialize independently (engine
    assist per branch); see finish_union for the trailing clauses."""
    frames = []
    for part in u.parts:
        df = None
        if not outer_env and getattr(ctx, "host_engine_assist", True):
            df = try_engine(ctx, part)
        if df is None:
            df = execute_select(ctx, part, outer_env=outer_env)
        frames.append(df)
    return finish_union(frames, u)


def _order_limit_distinct(ctx, res: pd.DataFrame, stmt: A.SelectStmt, env):
    if stmt.distinct:
        res = res.drop_duplicates().reset_index(drop=True)
    if stmt.order_by:
        sort_cols = []
        ascending = []
        tmp = res.copy()
        alias_map = {}
        for i, item in enumerate(stmt.items):
            if item.expr != "*":
                alias_map[_grp_key(item.expr)] = res.columns[i] \
                    if i < len(res.columns) else None
        for j, o in enumerate(stmt.order_by):
            e = o.expr
            if isinstance(e, E.Literal) and isinstance(e.value, int):
                col = res.columns[e.value - 1]
            elif isinstance(e, E.Column) and e.name in res.columns:
                col = e.name
            elif _grp_key(e) in alias_map and alias_map[_grp_key(e)]:
                col = alias_map[_grp_key(e)]
            else:
                # compute from result columns
                envr = {c: res[c].to_numpy() for c in res.columns}
                agg_cols = res.attrs.get("agg_cols", {})
                grp_cols = res.attrs.get("grp_cols", {})
                genv = res.attrs.get("genv", {})
                e2 = _replace_for_output(e, agg_cols, grp_cols)
                try:
                    v = host_eval.eval_expr(e2, envr)
                except host_eval.HostEvalError:
                    keep = res.attrs.get("keep")
                    fullenv = dict(genv)
                    v = np.asarray(host_eval.eval_expr(e2, fullenv))
                    if keep is not None:
                        v = v[keep]
                col = f"__ord{j}"
                tmp[col] = v
            sort_cols.append(col)
            ascending.append(o.ascending)
        tmp = tmp.sort_values(sort_cols, ascending=ascending,
                              kind="mergesort")
        res = tmp[res.columns].reset_index(drop=True)
    if stmt.offset:
        res = res.iloc[stmt.offset:].reset_index(drop=True)
    if stmt.limit is not None:
        res = res.head(stmt.limit).reset_index(drop=True)
    return res
