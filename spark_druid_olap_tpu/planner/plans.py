"""Physical plan descriptions produced by the pushdown builder."""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

from spark_druid_olap_tpu.ir import spec as S


class PlanUnsupported(Exception):
    """The device planner can't push this query; the session falls back to
    host execution (≈ a DruidTransform returning Nil so Spark plans the
    query itself)."""


@dataclasses.dataclass
class DistinctPhase2:
    """Exact count-distinct via two phases: phase 1 groups by
    (dims + distinct arg) on device; phase 2 re-aggregates on host.
    ≈ the reference's SPLRewriteDistinctAggregates Expand form, collapsed to
    two physical stages."""
    group_cols: List[str]
    distinct_out: str           # output column name of the distinct count
    distinct_dim: str           # phase-1 dim column holding the arg values
    other_aggs: Dict[str, str]  # phase-1 agg col -> re-agg fn (sum|min|max)


@dataclasses.dataclass
class PlannedQuery:
    datasource: str
    specs: List[S.QuerySpec]
    spec_dims: List[List[str]]            # dim output names present per spec
    all_dims: List[str]                   # union of dim names (output order)
    output_columns: List[str]             # final projection (ordered)
    order_by: List[Tuple[str, bool]] = dataclasses.field(default_factory=list)
    limit: Optional[int] = None
    order_applied_in_spec: bool = False
    distinct_phase2: Optional[DistinctPhase2] = None
    select_path: bool = False             # non-agg raw select
    # source column -> output alias renames (select path)
    select_renames: Dict[str, str] = dataclasses.field(default_factory=dict)
    # post-aggregations deferred past phase 2 (only with distinct_phase2)
    deferred_posts: List[S.PostAggregationSpec] = \
        dataclasses.field(default_factory=list)
    # unpushable WHERE conjuncts evaluated on the (small) engine result —
    # over dim OUTPUT names (agg path) or source columns (select path);
    # ≈ the Spark FilterExec the reference leaves above the Druid scan
    residual: Optional[object] = None
    # name of the materialized rollup the specs were rewritten onto
    # (mv/match.py); None = specs scan the base datasource
    rollup: Optional[str] = None
