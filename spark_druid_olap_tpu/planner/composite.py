"""Composite plans: engine-planned derived tables + a host finishing step.

The reference's execution shape for a query that does not rewrite whole-plan
is a Spark plan whose *relational subtrees* still become DruidQuery scans,
with Spark joins/aggregates above them (Catalyst plans each subtree
independently, so a derived table over the fact table hits ``DruidStrategy``
even when the outer join does not — see ``DruidStrategy.buildPlan:368-398``
under a Spark ``SortMergeJoin``). A CompositePlan is that shape made
explicit: every derived table in FROM is planned through the pushdown
builder (device scans), and the outer statement — restricted to *dimension-
scale* base tables — runs on the host over the small derived results.

Two plan kinds:

- :class:`CompositePlan` — derived tables -> engine plans, outer statement
  host-executed with the results as temp frames (TPC-H q15 shape).
- :class:`LeftJoinAggPlan` — ``A LEFT JOIN B ON A.k = B.fk [AND P(B)]``
  aggregated by ``A.k`` with all aggregates over B: the engine computes the
  B-side group-by; the host left-merges A's key column and zero-fills counts
  (TPC-H q13 shape; count(col) over the null extension is 0, sums stay
  NULL).
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Tuple, Union

import numpy as np
import pandas as pd

from spark_druid_olap_tpu.ir import expr as E
from spark_druid_olap_tpu.planner.plans import PlannedQuery, PlanUnsupported
from spark_druid_olap_tpu.sql import ast as A


@dataclasses.dataclass
class LeftJoinAggPlan:
    left_table: str
    left_key: str
    out_key: str                       # output name of the key column
    inner: PlannedQuery                # engine plan over the right side
    fk_col: str                        # key output name in the inner result
    agg_cols: List[Tuple[str, bool]]   # (output name, zero-fill?)


@dataclasses.dataclass
class CompositePlan:
    sub_plans: List[Tuple[str, object]]  # (temp name, engine/leftjoin plan)
    outer_stmt: A.SelectStmt


SubPlan = Union[PlannedQuery, LeftJoinAggPlan, CompositePlan]


def _chain(ctx, stmt: A.SelectStmt, execute: bool = True) -> A.SelectStmt:
    """Rewrite pipeline ahead of the builder. ``execute=False`` (EXPLAIN)
    skips the inlining passes, which RUN subqueries through the session —
    explain must never dispatch engine queries or pollute the history."""
    from spark_druid_olap_tpu.planner.decorrelate import (
        decorrelate_semijoins, inline_correlated_scalars,
        inline_subqueries)
    from spark_druid_olap_tpu.planner.viewmerge import merge_derived
    s = merge_derived(ctx, stmt)
    s = decorrelate_semijoins(ctx, s)
    if not execute:
        return s
    s = inline_correlated_scalars(ctx, s)
    return inline_subqueries(ctx, s)


def _build_sub(ctx, stmt: A.SelectStmt, execute: bool = True) -> SubPlan:
    from spark_druid_olap_tpu.planner import builder as B
    if isinstance(stmt, A.UnionAll):
        raise PlanUnsupported("union derived table (host tier handles)")
    s = _chain(ctx, stmt, execute)
    try:
        return B.build(ctx, s)
    except PlanUnsupported:
        return _build_leftjoin_agg(ctx, s)


def _fact_scale_tables(ctx) -> set:
    """Datasources the host side must never scan raw in a composite: the
    star flat indexes and their fact tables."""
    out = set()
    for star in ctx.catalog.star_schemas.values():
        out.add(star.flat_datasource)
        out.add(star.fact_table)
    return out


def build_composite(ctx, stmt: A.SelectStmt,
                    execute: bool = True) -> CompositePlan:
    """Plan the statement as engine-built derived tables + host finish.
    Raises PlanUnsupported unless every derived table plans through the
    engine and every remaining base table is dimension-scale."""
    if stmt.relation is None:
        raise PlanUnsupported("no FROM clause")
    subs: List[Tuple[str, object]] = []
    banned = _fact_scale_tables(ctx)

    def walk(rel):
        if isinstance(rel, A.TableRef):
            if rel.name in banned:
                raise PlanUnsupported(
                    f"host join over fact-scale table {rel.name!r}")
            return rel
        if isinstance(rel, A.SubqueryRef):
            sub = _build_sub(ctx, rel.query, execute)
            name = f"__derived{len(subs)}"
            subs.append((name, sub))
            return A.TableRef(name)
        if isinstance(rel, A.Join):
            return dataclasses.replace(rel, left=walk(rel.left),
                                       right=walk(rel.right))
        raise PlanUnsupported(f"relation {type(rel).__name__}")

    new_rel = walk(stmt.relation)
    if not subs:
        # Dim-only FROM whose WHERE still engages the fact table through
        # subqueries (TPC-H q20: supplier x suppnation filtered by an IN
        # chain whose correlated scalar scans lineitem): route every
        # base-table scan through an engine Select so ALL data access
        # stays on the engine path — the host joins the dim-scale
        # results and resolves the subqueries (their fact scans run
        # engine-assisted). ≈ the reference's DruidQuery-scans-under-
        # Spark-join shape with dim relations as scans.
        if not _subqueries_touch_fact(ctx, stmt, banned):
            raise PlanUnsupported(
                "no derived table to plan through the engine")
        new_rel = _tables_to_engine_selects(ctx, stmt.relation, subs,
                                            execute)
    return CompositePlan(sub_plans=subs,
                         outer_stmt=dataclasses.replace(stmt,
                                                        relation=new_rel))


def _subqueries_touch_fact(ctx, stmt: A.SelectStmt, banned: set) -> bool:
    """Whether any subquery under the statement references a fact-scale
    table (directly or in ITS nested subqueries/relations)."""
    from spark_druid_olap_tpu.planner.host_exec import _subquery_nodes

    def rel_tables(rel, out):
        if isinstance(rel, A.TableRef):
            out.add(rel.name)
        elif isinstance(rel, A.SubqueryRef):
            stmt_tables(rel.query, out)
        elif isinstance(rel, A.Join):
            rel_tables(rel.left, out)
            rel_tables(rel.right, out)

    def stmt_tables(q, out):
        parts = q.parts if isinstance(q, A.UnionAll) else (q,)
        for p in parts:
            if p.relation is not None:
                rel_tables(p.relation, out)
            for e in (p.where, p.having):
                if e is not None:
                    for n in _subquery_nodes(e):
                        stmt_tables(n.query, out)

    names: set = set()
    for e in (stmt.where, stmt.having):
        if e is not None:
            for n in _subquery_nodes(e):
                stmt_tables(n.query, names)
    return bool(names & banned)


def _tables_to_engine_selects(ctx, rel, subs, execute: bool):
    """Replace each base TableRef with an engine full-table Select plan
    registered as a temp frame (aliases preserved for the host join)."""
    if isinstance(rel, A.TableRef):
        sub = _build_sub(ctx, A.SelectStmt(
            items=(A.SelectItem("*"),),
            relation=A.TableRef(rel.name)), execute)
        name = f"__dim{len(subs)}"
        subs.append((name, sub))
        return A.TableRef(name, alias=rel.alias or rel.name)
    if isinstance(rel, A.Join):
        return dataclasses.replace(
            rel,
            left=_tables_to_engine_selects(ctx, rel.left, subs, execute),
            right=_tables_to_engine_selects(ctx, rel.right, subs, execute))
    raise PlanUnsupported(f"relation {type(rel).__name__}")


def _build_leftjoin_agg(ctx, stmt: A.SelectStmt) -> LeftJoinAggPlan:
    """``SELECT A.k, agg(B...) FROM A LEFT JOIN B ON A.k = B.fk [AND P(B)]
    GROUP BY A.k`` -> engine group-by on B + host left-merge of A's keys."""
    from spark_druid_olap_tpu.planner import builder as B
    from spark_druid_olap_tpu.planner.host_exec import relation_columns
    rel = stmt.relation
    if not (isinstance(rel, A.Join) and rel.kind == "left"
            and isinstance(rel.left, A.TableRef)
            and isinstance(rel.right, A.TableRef)):
        raise PlanUnsupported("not a left-join aggregate")
    if stmt.where is not None or stmt.having is not None or stmt.distinct \
            or stmt.limit is not None:
        raise PlanUnsupported("left-join aggregate with WHERE/HAVING/LIMIT")
    left_cols = set(relation_columns(ctx, rel.left))
    right_cols = set(relation_columns(ctx, rel.right))
    from spark_druid_olap_tpu.planner.decorrelate import _split_and

    key = fk = None
    right_preds = []
    for c in _split_and(rel.condition):
        if (key is None and isinstance(c, E.Comparison) and c.op == "="
                and isinstance(c.left, E.Column)
                and isinstance(c.right, E.Column)):
            a, b = c.left.name, c.right.name
            if a in left_cols and b in right_cols:
                key, fk = a, b
                continue
            if b in left_cols and a in right_cols:
                key, fk = b, a
                continue
        refs = E.columns_in(c)
        if refs <= right_cols:
            right_preds.append(c)
        else:
            raise PlanUnsupported("left-join ON not (equi + right-side)")
    if key is None:
        raise PlanUnsupported("left join without an equi key")
    gb = stmt.group_by
    if not (isinstance(gb, tuple) and len(gb) == 1
            and isinstance(gb[0], E.Column) and gb[0].name == key):
        raise PlanUnsupported("grouping is not the left join key")

    out_key = None
    inner_items = [A.SelectItem(E.Column(fk), alias=fk)]
    agg_cols: List[Tuple[str, bool]] = []
    for i, it in enumerate(stmt.items):
        if isinstance(it.expr, E.Column) and it.expr.name == key:
            out_key = it.alias or key
            continue
        if not isinstance(it.expr, E.AggCall):
            raise PlanUnsupported("non-aggregate output in left-join agg")
        call = it.expr
        refs = E.columns_in(call)
        if not refs or not refs <= right_cols:
            # count(*) counts the null extension (1 per unmatched left
            # row); only right-side aggregates translate
            raise PlanUnsupported("aggregate not over the right side")
        name = it.alias or f"_c{i}"
        inner_items.append(A.SelectItem(call, alias=name))
        agg_cols.append((name, call.fn == "count"))
    if out_key is None:
        raise PlanUnsupported("left-join agg must output the key")

    inner_stmt = A.SelectStmt(
        items=tuple(inner_items), relation=rel.right,
        where=None if not right_preds else (
            right_preds[0] if len(right_preds) == 1
            else E.And(tuple(right_preds))),
        group_by=(E.Column(fk),))
    pq = B.build(ctx, _chain(ctx, inner_stmt))
    return LeftJoinAggPlan(left_table=rel.left.name, left_key=key,
                           out_key=out_key, inner=pq, fk_col=fk,
                           agg_cols=agg_cols)


def execute_composite(ctx, plan: SubPlan) -> pd.DataFrame:
    from spark_druid_olap_tpu.planner import host_exec
    from spark_druid_olap_tpu.sql.session import execute_planned
    if isinstance(plan, PlannedQuery):
        return execute_planned(ctx, plan)
    if isinstance(plan, LeftJoinAggPlan):
        left = host_exec.datasource_frame(ctx, plan.left_table,
                                          columns={plan.left_key})
        if left[plan.left_key].duplicated().any():
            # duplicate left keys mean one output row per left ROW with
            # per-key counts repeated; that is a plain host join, not this
            # rewrite (checked before spending the engine execution)
            raise host_exec.HostExecError(
                f"left join key {plan.left_key!r} is not unique")
        inner = execute_planned(ctx, plan.inner)
        df = left.merge(inner, left_on=plan.left_key, right_on=plan.fk_col,
                        how="left")
        out = pd.DataFrame({plan.out_key: df[plan.left_key]})
        for name, zero_fill in plan.agg_cols:
            col = df[name]
            out[name] = col.fillna(0).astype(np.int64) if zero_fill else col
        return out
    frames = {}
    for name, sub in plan.sub_plans:
        frames[name] = execute_composite(ctx, sub)
    tls = host_exec.ctx_tls(ctx)
    prev = getattr(tls, "temp_frames", None)
    tls.temp_frames = {**(prev or {}), **frames}
    try:
        return host_exec.execute_select(ctx, plan.outer_stmt)
    finally:
        tls.temp_frames = prev


def describe(plan: SubPlan, indent: str = "") -> str:
    """Explain text for a composite plan."""
    if isinstance(plan, PlannedQuery):
        specs = ", ".join(type(q).__name__ for q in plan.specs)
        return f"{indent}engine: {plan.datasource} [{specs}]"
    if isinstance(plan, LeftJoinAggPlan):
        return (f"{indent}left-join agg: host merge {plan.left_table}."
                f"{plan.left_key} with\n"
                + describe(plan.inner, indent + "  "))
    lines = [f"{indent}composite: host finish over"]
    for name, sub in plan.sub_plans:
        lines.append(f"{indent}  {name} <-")
        lines.append(describe(sub, indent + "    "))
    return "\n".join(lines)
