"""Uncorrelated-subquery inlining.

The reference leaves subqueries to Spark, which evaluates uncorrelated scalar
subqueries before pushdown rewriting sees them — so queries like TPC-H Q11's
``having sum(...) > (select ... )`` still hit the Druid path for both the
inner and outer blocks. This pass reproduces that: each *uncorrelated*
scalar / IN / EXISTS subquery in WHERE or HAVING is executed through the full
session path (so the inner query itself gets engine pushdown!) and replaced
by a literal / value list, leaving the outer block subquery-free for the
builder. Correlated subqueries remain and route to the host executor.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np
import pandas as pd

from spark_druid_olap_tpu.ir import expr as E
from spark_druid_olap_tpu.sql import ast as A


def _to_python(v):
    if isinstance(v, np.generic):
        return v.item()
    if isinstance(v, (pd.Timestamp, np.datetime64)):
        ts = pd.Timestamp(v)
        return ts.to_pydatetime().date() if ts.tz is None else ts
    return v


def _is_correlated(ctx, q: A.SelectStmt) -> bool:
    from spark_druid_olap_tpu.planner.host_exec import _free_columns
    try:
        return bool(_free_columns(ctx, q))
    except Exception:
        return True  # unknown tables etc. — leave it to the host path


def inline_subqueries(ctx, stmt: A.SelectStmt) -> A.SelectStmt:
    """Replace uncorrelated subquery nodes in WHERE/HAVING with literals."""

    def run_inner(q: A.SelectStmt) -> pd.DataFrame:
        from spark_druid_olap_tpu.sql.session import _run_select
        return _run_select(ctx, q, sql="<subquery>").to_pandas()

    changed = [False]

    def resolve(e: Optional[E.Expr]) -> Optional[E.Expr]:
        if e is None:
            return None

        def rep(n):
            if isinstance(n, A.ScalarSubquery) and \
                    not _is_correlated(ctx, n.query):
                df = run_inner(n.query)
                changed[0] = True
                if len(df) == 0:
                    return E.Literal(None)
                return E.Literal(_to_python(df.iloc[0, 0]))
            if isinstance(n, A.InSubquery) and \
                    not _is_correlated(ctx, n.query):
                df = run_inner(n.query)
                changed[0] = True
                vals = tuple(_to_python(v)
                             for v in pd.unique(df.iloc[:, 0].dropna()))
                if not vals:
                    # empty IN-list: constant false (true for NOT IN)
                    return E.Literal(bool(n.negated))
                return E.InList(n.child, vals, negated=n.negated)
            if isinstance(n, A.Exists) and not _is_correlated(ctx, n.query):
                df = run_inner(n.query)
                changed[0] = True
                return E.Literal((len(df) > 0) != n.negated)
            return n

        return E.transform(e, rep)

    new_where = resolve(stmt.where)
    new_having = resolve(stmt.having)
    if not changed[0]:
        return stmt
    return dataclasses.replace(stmt, where=new_where, having=new_having)
