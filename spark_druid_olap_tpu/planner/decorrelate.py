"""Uncorrelated-subquery inlining.

The reference leaves subqueries to Spark, which evaluates uncorrelated scalar
subqueries before pushdown rewriting sees them — so queries like TPC-H Q11's
``having sum(...) > (select ... )`` still hit the Druid path for both the
inner and outer blocks. This pass reproduces that: each *uncorrelated*
scalar / IN / EXISTS subquery in WHERE or HAVING is executed through the full
session path (so the inner query itself gets engine pushdown!) and replaced
by a literal / value list, leaving the outer block subquery-free for the
builder. Correlated subqueries remain and route to the host executor.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np
import pandas as pd

from spark_druid_olap_tpu.ir import expr as E
from spark_druid_olap_tpu.sql import ast as A


def _to_python(v):
    if isinstance(v, np.generic):
        return v.item()
    if isinstance(v, (pd.Timestamp, np.datetime64)):
        ts = pd.Timestamp(v)
        return ts.to_pydatetime().date() if ts.tz is None else ts
    return v


def _is_correlated(ctx, q: A.SelectStmt) -> bool:
    from spark_druid_olap_tpu.planner.host_exec import _free_columns
    try:
        return bool(_free_columns(ctx, q))
    except Exception:
        return True  # unknown tables etc. — leave it to the host path


def _split_and(e: Optional[E.Expr]):
    if e is None:
        return []
    if isinstance(e, E.And):
        out = []
        for p in e.parts:
            out.extend(_split_and(p))
        return out
    return [e]


def _and_all(parts):
    if not parts:
        return None
    return parts[0] if len(parts) == 1 else E.And(tuple(parts))


def _column_non_null(ctx, rel, name: str) -> bool:
    """True when ``name`` resolves to a provably non-nullable column of a
    base table in ``rel``."""
    tables = []

    def walk(r):
        if isinstance(r, A.TableRef):
            tables.append(r.name)
        elif isinstance(r, A.Join):
            walk(r.left)
            walk(r.right)
    if rel is not None:
        walk(rel)
    for t in tables:
        try:
            ds = ctx.store.get(t)
        except KeyError:
            continue
        if name in ds.dims:
            return ds.dims[name].validity is None
        if name in ds.metrics:
            return ds.metrics[name].validity is None
        if ds.time is not None and name == ds.time.name:
            return True
    return False


def decorrelate_semijoins(ctx, stmt: A.SelectStmt) -> A.SelectStmt:
    """Correlated EXISTS / NOT EXISTS with a single equi-correlation
    conjunct -> uncorrelated IN / NOT IN subquery over the inner key
    (semi/anti join), which `inline_subqueries` then evaluates through the
    engine. ≈ Spark's RewritePredicateSubquery giving the reference a
    pushable plan on both sides of TPC-H q4/q21/q22-style predicates.

    NOT EXISTS additionally requires a provably non-null probe column (a
    NULL probe makes NOT IN unknown where the anti join keeps the row).
    """
    if stmt.where is None:
        return stmt
    changed = False
    conjs = []
    for c in _split_and(stmt.where):
        r = _try_semijoin(ctx, stmt, c)
        if r is not None:
            changed = True
            conjs.append(r)
        else:
            conjs.append(c)
    if not changed:
        return stmt
    return dataclasses.replace(stmt, where=_and_all(conjs))


def _try_semijoin(ctx, outer: A.SelectStmt, c) -> Optional[E.Expr]:
    negated = False
    while isinstance(c, E.Not):      # parser may emit NOT Exists(...)
        negated = not negated
        c = c.child
    if not isinstance(c, A.Exists):
        return None
    negated = negated != c.negated
    q = c.query
    if q.group_by is not None or q.having is not None \
            or q.limit is not None or q.distinct:
        return None
    from spark_druid_olap_tpu.planner.host_exec import _free_columns
    try:
        free = _free_columns(ctx, q)
    except Exception:  # noqa: BLE001 — unknown tables etc.
        return None
    if len(free) != 1:
        return None
    (outer_col,) = free
    inner_col = None
    rest = []
    for cj in _split_and(q.where):
        if (inner_col is None and isinstance(cj, E.Comparison)
                and cj.op == "=" and isinstance(cj.left, E.Column)
                and isinstance(cj.right, E.Column)
                and {cj.left.name, cj.right.name} & {outer_col}):
            other = cj.right.name if cj.left.name == outer_col \
                else cj.left.name
            if other != outer_col:
                inner_col = other
                continue
        rest.append(cj)
    if inner_col is None:
        return None
    # the correlation must live ONLY in that conjunct
    from spark_druid_olap_tpu.planner.host_exec import _expr_refs
    for cj in rest:
        try:
            if outer_col in _expr_refs(ctx, cj):
                return None
        except Exception:  # noqa: BLE001
            return None
    if negated and not _column_non_null(ctx, outer.relation, outer_col):
        return None
    inner = A.SelectStmt(
        items=(A.SelectItem(E.Column(inner_col)),),
        relation=q.relation, where=_and_all(rest), distinct=True)
    return A.InSubquery(child=E.Column(outer_col), query=inner,
                        negated=negated)


def _classify_correlation(ctx, q, free, inner_cols, max_residuals,
                          max_pairs=1):
    """Split ``q.where`` into (pairs, rest, residuals): up to
    ``max_pairs`` equality conjuncts each bind a DISTINCT free column to
    an inner key expression; up to ``max_residuals`` further
    free-referencing conjuncts may be min/max-decidable comparisons
    (host_exec._residual_minmax); everything else must be inner-only.
    Returns None when the correlation has any other shape. Shared by the
    scalar and EXISTS inlining passes so their gating cannot diverge."""
    from spark_druid_olap_tpu.planner.host_exec import (
        _expr_refs, _residual_minmax)
    pairs = []               # (outer_col, inner_key_expr)
    bound = set()
    residuals = []
    rest = []
    for c in _split_and(q.where):
        refs = _expr_refs(ctx, c)
        if not (refs & free):
            rest.append(c)
            continue
        if len(pairs) < max_pairs and isinstance(c, E.Comparison) \
                and c.op == "=":
            pair = None
            for a, b in ((c.left, c.right), (c.right, c.left)):
                if isinstance(a, E.Column) and a.name in free \
                        and a.name not in bound:
                    brefs = _expr_refs(ctx, b)
                    if brefs and not (brefs & free) \
                            and brefs <= inner_cols:
                        pair = (a.name, b)
                        break
            if pair is not None:
                pairs.append(pair)
                bound.add(pair[0])
                continue
        if len(residuals) < max_residuals:
            mm = _residual_minmax(ctx, c, free, inner_cols)
            if mm is not None:
                residuals.append(mm)
                continue
        return None
    if not pairs:
        return None
    return pairs, rest, residuals


def _numeric_series(s):
    """The engine result column as float64, or None when it is not
    numeric (string/timestamp aggregates must NOT silently coerce to
    NULL)."""
    if s.dtype == object or s.dtype.kind not in "biuf":
        return None
    return pd.to_numeric(s, errors="coerce").to_numpy(dtype=np.float64)


def _cached_inner(ctx, q2, sql_tag):
    """Run an inlined subquery through the full session path, cached per
    (store version, statement): dashboard-repetitive statements re-plan
    on every execution, and without this every warm run re-executed each
    decorrelated inner (ingest bumps store.version, so results can never
    go stale; bounded like the engine-assist cache).

    Gated on ``sdot.plan.cache.enabled`` like the plan/cplan channels:
    benchmarks disable that key expecting measured reps to pay the full
    execute path, and an ungated subquery cache let nested-subquery
    statements (TPC-H q20) report zero device dispatches on warm reps."""
    from spark_druid_olap_tpu.planner.host_exec import (result_cache,
                                                        result_cache_put)
    from spark_druid_olap_tpu.utils.config import PLAN_CACHE_ENABLED
    use_cache = bool(ctx.config.get(PLAN_CACHE_ENABLED))
    if use_cache:
        cache, key = result_cache(ctx, "subquery", q2)
        hit = cache.get(key)
        if hit is not None:
            cache.move_to_end(key)           # keep hot entries resident
            from spark_druid_olap_tpu.sql.session import _note_subquery_hit
            _note_subquery_hit()             # served_from provenance
            return hit
    from spark_druid_olap_tpu.sql.session import _run_select
    df = _run_select(ctx, q2, sql=sql_tag).to_pandas()
    if use_cache:
        result_cache_put(cache, key, df)
    return df


def _run_grouped_inner(ctx, q, inner_keys, rest, value_items):
    """Execute the decorrelated per-key aggregate through the full session
    path (engine pushdown for the inner). Returns ([int64 key arrays],
    [value arrays]) or None."""
    q2 = A.SelectStmt(
        items=tuple(A.SelectItem(k, f"__k{j}")
                    for j, k in enumerate(inner_keys))
        + tuple(A.SelectItem(e, f"__v{i}")
                for i, e in enumerate(value_items)),
        relation=q.relation, where=_and_all(rest),
        group_by=tuple(inner_keys))
    try:
        df = _cached_inner(ctx, q2, "<correlated subquery>")
    except Exception:  # noqa: BLE001 — leave to the host tier
        return None
    keep = np.ones(len(df), dtype=bool)
    for j in range(len(inner_keys)):
        keep &= df[f"__k{j}"].notna().to_numpy()
    keys = []
    for j in range(len(inner_keys)):
        k = df[f"__k{j}"][keep]
        if len(k) and np.asarray(k).dtype.kind not in "iu":
            return None
        keys.append(np.asarray(k, dtype=np.int64))
    vals = []
    for i in range(len(value_items)):
        v = _numeric_series(df[f"__v{i}"][keep])
        if v is None:
            return None
        vals.append(v)
    return keys, vals


_NAN_SAFE_CMP = ("=", "<", "<=", ">", ">=")


def _cols_outside_lookups(e) -> set:
    """Column names referenced by ``e`` OUTSIDE KeyedLookup subtrees (a
    lookup's key column handles its own NULLs in lowering — miss value —
    and must not be over-guarded: a NULL key with a count-default still
    compares meaningfully)."""
    out = set()

    def rec(n):
        if isinstance(n, (E.KeyedLookup, E.KeyedLookup2)):
            return
        if isinstance(n, E.Column):
            out.add(n.name)
        for c in n.children():
            rec(c)

    rec(e)
    return out


def _null_guarded(ctx, rel, cmp_expr):
    """Device column payloads are zero-FILLED for NULL rows, so a pushed
    comparison touching a nullable outer column needs explicit IS NOT
    NULL guards to keep SQL's UNKNOWN-drops-row semantics (the host tier
    gets them right via eval_pred3, the compiled path via the column
    validity masks behind IsNull)."""
    guards = tuple(
        E.IsNull(E.Column(c), negated=True)
        for c in sorted(_cols_outside_lookups(cmp_expr))
        if not _column_non_null(ctx, rel, c))
    if not guards:
        return cmp_expr
    return E.And(guards + (cmp_expr,))


def inline_correlated_scalars(ctx, stmt: A.SelectStmt) -> A.SelectStmt:
    """Correlated subqueries in WHERE -> :class:`E.KeyedLookup`
    expressions over decorrelated per-key aggregates (executed ONCE
    through the full session path, so the inner gets engine pushdown),
    leaving the outer statement subquery-free and itself pushable — the
    TPC-H q2/q17/q21 shapes run entirely on device as scan-collapsed
    broadcast joins. ≈ Spark's RewriteCorrelatedScalarSubquery /
    RewritePredicateSubquery followed by a broadcast hash join.

    NULL discipline: a lookup miss is NaN-coded (or the aggregate's
    non-NULL empty-group identity, e.g. count -> 0). NaN evaluates False
    under {=, <, <=, >, >=} — exactly SQL's UNKNOWN-drops-row — but True
    under IEEE !=, and NOT flips a spurious False into a spurious True.
    The walker therefore tracks polarity and only inlines a scalar
    subquery under an even number of NOTs inside one of the safe
    comparison ops, reached through NaN-transparent arithmetic. EXISTS
    rewrites are polarity-independent (EXISTS is never UNKNOWN; the
    generated predicate is False on miss, which negation maps correctly).
    """
    if stmt.where is None:
        return stmt
    changed = [False]

    def subst_scalar(n):
        q = n.query
        if q.relation is None or q.group_by is not None \
                or q.having is not None or q.limit is not None \
                or q.distinct or len(q.items) != 1 \
                or q.items[0].expr == "*":
            return None
        from spark_druid_olap_tpu.planner.host_exec import (
            _empty_group_value, _expr_refs, _free_columns,
            _relation_free_refs, relation_columns)
        try:
            free = _free_columns(ctx, q)
            if not free or len(free) > 2:
                return None
            if _relation_free_refs(ctx, q.relation) & free:
                return None
            if _expr_refs(ctx, q.items[0].expr) & free:
                return None
            inner_cols = set(relation_columns(ctx, q.relation))
            cl = _classify_correlation(ctx, q, free, inner_cols, 0,
                                       max_pairs=len(free))
        except Exception:  # noqa: BLE001 — unknown tables/columns
            return None
        if cl is None or not E.agg_calls_in(q.items[0].expr):
            return None
        pairs, rest, _ = cl
        if len(pairs) != len(free):
            return None              # a free column escaped the key pairs
        r = _run_grouped_inner(ctx, q, [b for _, b in pairs], rest,
                               [q.items[0].expr])
        if r is None:
            return None
        keys, (varr,) = r
        d = _empty_group_value(q.items[0].expr)
        default = None
        if isinstance(d, (int, float, np.number)) \
                and not (isinstance(d, float) and np.isnan(d)):
            default = float(d)
        if len(pairs) == 1:
            return E.KeyedLookup(E.Column(pairs[0][0]),
                                 E.FrozenKeyedTable(keys[0], varr),
                                 default)
        # composite key: both key domains must fit int32 (the host packs
        # pairs into one int64; the device compares i32 pairs)
        for k in keys:
            if len(k) and (k.min() < -(2**31) or k.max() >= 2**31):
                return None
        return E.KeyedLookup2(E.Column(pairs[0][0]), E.Column(pairs[1][0]),
                              E.FrozenKeyedTable2(keys[0], keys[1], varr),
                              default)

    def val(e, allow):
        """Value position: inline only when ``allow`` (reached from a
        positively-oriented safe comparison through NaN-transparent
        arithmetic)."""
        if isinstance(e, A.ScalarSubquery) and allow:
            r = subst_scalar(e)
            if r is not None:
                changed[0] = True
                return r
            return e
        if isinstance(e, E.BinaryOp):
            l2, r2 = val(e.left, allow), val(e.right, allow)
            if l2 is e.left and r2 is e.right:
                return e
            return E.BinaryOp(e.op, l2, r2)
        if isinstance(e, E.Cast):
            c2 = val(e.child, allow)
            return e if c2 is e.child else E.Cast(c2, e.to)
        return e

    def boolean(e, pos):
        if isinstance(e, E.And):
            return E.And(tuple(boolean(p, pos) for p in e.parts))
        if isinstance(e, E.Or):
            return E.Or(tuple(boolean(p, pos) for p in e.parts))
        if isinstance(e, E.Not):
            return E.Not(boolean(e.child, not pos))
        if isinstance(e, A.Exists):
            r = _minmax_exists(ctx, e, stmt.relation)
            if r is not None:
                changed[0] = True
                return r
            return e
        if isinstance(e, E.Comparison):
            allow = pos and e.op in _NAN_SAFE_CMP
            out = E.Comparison(e.op, val(e.left, allow),
                               val(e.right, allow))
            if out.left is not e.left or out.right is not e.right:
                return _null_guarded(ctx, stmt.relation, out)
            return e
        if isinstance(e, E.Between):
            allow = pos and not e.negated
            out = E.Between(val(e.child, allow), val(e.low, allow),
                            val(e.high, allow), e.negated)
            if out.child is not e.child or out.low is not e.low \
                    or out.high is not e.high:
                return _null_guarded(ctx, stmt.relation, out)
            return e
        return e

    new_where = boolean(stmt.where, True)
    if not changed[0]:
        return stmt
    return dataclasses.replace(stmt, where=new_where)


def _minmax_exists(ctx, node, outer_rel=None) -> Optional[E.Expr]:
    """EXISTS with one integer equi-correlation AND one comparison residual
    against a second outer column -> an expression over per-key (min, max)
    KeyedLookups: 'exists (inner.k = outer.k and inner.c <op> outer.c)'
    is decidable from min(c)/max(c) per k, so the inner collapses to ONE
    grouped aggregate (engine-executed here) and the outer stays pushable
    — q21's shape runs on device end to end. NULL semantics: a missing
    key gives NaN lookups whose ordered comparisons are false (EXISTS'
    UNKNOWN-drops-row rule); '<>' adds explicit NOT-NULL guards because
    IEEE NaN != x is true."""
    from spark_druid_olap_tpu.planner.host_exec import (
        _free_columns, _relation_free_refs, relation_columns)
    q = node.query
    if q.relation is None or q.group_by is not None \
            or q.having is not None or q.limit is not None or q.distinct:
        return None
    try:
        free = _free_columns(ctx, q)
        if not free or len(free) > 2:
            return None
        if _relation_free_refs(ctx, q.relation) & free:
            return None
        inner_cols = set(relation_columns(ctx, q.relation))
        cl = _classify_correlation(ctx, q, free, inner_cols, 1)
    except Exception:  # noqa: BLE001 — unknown tables/columns
        return None
    if cl is None or len(cl[2]) != 1:
        return None
    pairs, rest, (mm,) = cl
    (kcol, inner_key), = pairs
    op, inner_expr, ccol = mm
    if ccol == kcol:
        return None
    r = _run_grouped_inner(ctx, q, [inner_key], rest,
                           [E.AggCall("min", inner_expr),
                            E.AggCall("max", inner_expr)])
    if r is None:
        return None
    (karr,), (mnv, mxv) = r
    mn = E.KeyedLookup(E.Column(kcol), E.FrozenKeyedTable(karr, mnv))
    mx = E.KeyedLookup(E.Column(kcol), E.FrozenKeyedTable(karr, mxv))
    c = E.Column(ccol)
    if op == "<":
        cond = E.Comparison("<", mn, c)
    elif op == "<=":
        cond = E.Comparison("<=", mn, c)
    elif op == ">":
        cond = E.Comparison(">", mx, c)
    elif op == ">=":
        cond = E.Comparison(">=", mx, c)
    else:                                  # '<>'
        cond = E.And((E.IsNull(mn, negated=True),
                      E.IsNull(c, negated=True),
                      E.Or((E.Comparison("!=", mn, c),
                            E.Comparison("!=", mx, c)))))
    if op != "<>" and not _column_non_null(ctx, outer_rel, ccol):
        # NULL outer probe: every residual comparison is UNKNOWN, so the
        # EXISTS is false — zero-filled device payloads need the guard
        cond = E.And((E.IsNull(c, negated=True), cond))
    return E.Not(cond) if node.negated else cond


def stmt_has_subqueries(stmt: A.SelectStmt) -> bool:
    """Any subquery node in WHERE or HAVING — the public hook for EXPLAIN,
    which must DESCRIBE the execution-time inlining (inline_subqueries /
    inline_correlated_scalars run real engine queries) without running
    it."""
    for e in (stmt.where, stmt.having):
        if e is None:
            continue
        for n in E.walk(e):
            if isinstance(n, (A.ScalarSubquery, A.InSubquery, A.Exists)):
                return True
    return False


def build_in_list_expr(child: E.Expr, raw: pd.Series,
                       negated: bool) -> E.Expr:
    """An executed IN-subquery's value list -> the membership expr, with
    SQL 3VL for NULL-bearing lists: membership in such a list is TRUE on
    a match else UNKNOWN (never FALSE), so NOT IN can never be TRUE.
    Encoded as Kleene 'inlist OR NULL', which eval_pred3 resolves
    through the node's own negation AND any enclosing NOT. Null-free
    lists keep the pushdown-friendly negated-InList shape (lowers to
    the engine's InFilter). The ONE shared encoding of the uncorrelated
    inline pass and the host executor."""
    col = raw.dropna()
    had_null = len(col) < len(raw)
    if len(col) > 1024 and \
            np.issubdtype(col.to_numpy().dtype, np.integer):
        # semi-join-scale integer key list: O(1)-repr sorted set
        base = E.InList(child, E.FrozenIntSet(col.to_numpy()),
                        negated=False)
    elif len(col):
        base = E.InList(child, tuple(_to_python(v) for v in pd.unique(col)),
                        negated=False)
    else:
        base = None                        # empty list matches nothing
    if not had_null:
        if base is None:
            return E.Literal(bool(negated))
        return dataclasses.replace(base, negated=negated)
    base = E.Literal(None) if base is None \
        else E.Or((base, E.Literal(None)))
    return E.Not(base) if negated else base


def inline_subqueries(ctx, stmt: A.SelectStmt) -> A.SelectStmt:
    """Replace uncorrelated subquery nodes in WHERE/HAVING with literals."""

    def run_inner(q: A.SelectStmt) -> pd.DataFrame:
        return _cached_inner(ctx, q, "<subquery>")

    changed = [False]

    def resolve(e: Optional[E.Expr]) -> Optional[E.Expr]:
        if e is None:
            return None

        def rep(n):
            if isinstance(n, A.ScalarSubquery) and \
                    not _is_correlated(ctx, n.query):
                df = run_inner(n.query)
                changed[0] = True
                if len(df) == 0:
                    return E.Literal(None)
                return E.Literal(_to_python(df.iloc[0, 0]))
            if isinstance(n, A.InSubquery) and \
                    not _is_correlated(ctx, n.query):
                df = run_inner(n.query)
                changed[0] = True
                return build_in_list_expr(n.child, df.iloc[:, 0],
                                          n.negated)
            if isinstance(n, A.Exists) and not _is_correlated(ctx, n.query):
                df = run_inner(n.query)
                changed[0] = True
                return E.Literal((len(df) > 0) != n.negated)
            return n

        return E.transform(e, rep)

    new_where = resolve(stmt.where)
    new_having = resolve(stmt.having)
    if not changed[0]:
        return stmt
    return dataclasses.replace(stmt, where=new_where, having=new_having)
