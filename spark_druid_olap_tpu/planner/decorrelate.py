"""Uncorrelated-subquery inlining.

The reference leaves subqueries to Spark, which evaluates uncorrelated scalar
subqueries before pushdown rewriting sees them — so queries like TPC-H Q11's
``having sum(...) > (select ... )`` still hit the Druid path for both the
inner and outer blocks. This pass reproduces that: each *uncorrelated*
scalar / IN / EXISTS subquery in WHERE or HAVING is executed through the full
session path (so the inner query itself gets engine pushdown!) and replaced
by a literal / value list, leaving the outer block subquery-free for the
builder. Correlated subqueries remain and route to the host executor.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np
import pandas as pd

from spark_druid_olap_tpu.ir import expr as E
from spark_druid_olap_tpu.sql import ast as A


def _to_python(v):
    if isinstance(v, np.generic):
        return v.item()
    if isinstance(v, (pd.Timestamp, np.datetime64)):
        ts = pd.Timestamp(v)
        return ts.to_pydatetime().date() if ts.tz is None else ts
    return v


def _is_correlated(ctx, q: A.SelectStmt) -> bool:
    from spark_druid_olap_tpu.planner.host_exec import _free_columns
    try:
        return bool(_free_columns(ctx, q))
    except Exception:
        return True  # unknown tables etc. — leave it to the host path


def _split_and(e: Optional[E.Expr]):
    if e is None:
        return []
    if isinstance(e, E.And):
        out = []
        for p in e.parts:
            out.extend(_split_and(p))
        return out
    return [e]


def _and_all(parts):
    if not parts:
        return None
    return parts[0] if len(parts) == 1 else E.And(tuple(parts))


def _column_non_null(ctx, rel, name: str) -> bool:
    """True when ``name`` resolves to a provably non-nullable column of a
    base table in ``rel``."""
    tables = []

    def walk(r):
        if isinstance(r, A.TableRef):
            tables.append(r.name)
        elif isinstance(r, A.Join):
            walk(r.left)
            walk(r.right)
    if rel is not None:
        walk(rel)
    for t in tables:
        try:
            ds = ctx.store.get(t)
        except KeyError:
            continue
        if name in ds.dims:
            return ds.dims[name].validity is None
        if name in ds.metrics:
            return ds.metrics[name].validity is None
        if ds.time is not None and name == ds.time.name:
            return True
    return False


def decorrelate_semijoins(ctx, stmt: A.SelectStmt) -> A.SelectStmt:
    """Correlated EXISTS / NOT EXISTS with a single equi-correlation
    conjunct -> uncorrelated IN / NOT IN subquery over the inner key
    (semi/anti join), which `inline_subqueries` then evaluates through the
    engine. ≈ Spark's RewritePredicateSubquery giving the reference a
    pushable plan on both sides of TPC-H q4/q21/q22-style predicates.

    NOT EXISTS additionally requires a provably non-null probe column (a
    NULL probe makes NOT IN unknown where the anti join keeps the row).
    """
    if stmt.where is None:
        return stmt
    changed = False
    conjs = []
    for c in _split_and(stmt.where):
        r = _try_semijoin(ctx, stmt, c)
        if r is not None:
            changed = True
            conjs.append(r)
        else:
            conjs.append(c)
    if not changed:
        return stmt
    return dataclasses.replace(stmt, where=_and_all(conjs))


def _try_semijoin(ctx, outer: A.SelectStmt, c) -> Optional[E.Expr]:
    negated = False
    while isinstance(c, E.Not):      # parser may emit NOT Exists(...)
        negated = not negated
        c = c.child
    if not isinstance(c, A.Exists):
        return None
    negated = negated != c.negated
    q = c.query
    if q.group_by is not None or q.having is not None \
            or q.limit is not None or q.distinct:
        return None
    from spark_druid_olap_tpu.planner.host_exec import _free_columns
    try:
        free = _free_columns(ctx, q)
    except Exception:  # noqa: BLE001 — unknown tables etc.
        return None
    if len(free) != 1:
        return None
    (outer_col,) = free
    inner_col = None
    rest = []
    for cj in _split_and(q.where):
        if (inner_col is None and isinstance(cj, E.Comparison)
                and cj.op == "=" and isinstance(cj.left, E.Column)
                and isinstance(cj.right, E.Column)
                and {cj.left.name, cj.right.name} & {outer_col}):
            other = cj.right.name if cj.left.name == outer_col \
                else cj.left.name
            if other != outer_col:
                inner_col = other
                continue
        rest.append(cj)
    if inner_col is None:
        return None
    # the correlation must live ONLY in that conjunct
    from spark_druid_olap_tpu.planner.host_exec import _expr_refs
    for cj in rest:
        try:
            if outer_col in _expr_refs(ctx, cj):
                return None
        except Exception:  # noqa: BLE001
            return None
    if negated and not _column_non_null(ctx, outer.relation, outer_col):
        return None
    inner = A.SelectStmt(
        items=(A.SelectItem(E.Column(inner_col)),),
        relation=q.relation, where=_and_all(rest), distinct=True)
    return A.InSubquery(child=E.Column(outer_col), query=inner,
                        negated=negated)


def inline_subqueries(ctx, stmt: A.SelectStmt) -> A.SelectStmt:
    """Replace uncorrelated subquery nodes in WHERE/HAVING with literals."""

    def run_inner(q: A.SelectStmt) -> pd.DataFrame:
        from spark_druid_olap_tpu.sql.session import _run_select
        return _run_select(ctx, q, sql="<subquery>").to_pandas()

    changed = [False]

    def resolve(e: Optional[E.Expr]) -> Optional[E.Expr]:
        if e is None:
            return None

        def rep(n):
            if isinstance(n, A.ScalarSubquery) and \
                    not _is_correlated(ctx, n.query):
                df = run_inner(n.query)
                changed[0] = True
                if len(df) == 0:
                    return E.Literal(None)
                return E.Literal(_to_python(df.iloc[0, 0]))
            if isinstance(n, A.InSubquery) and \
                    not _is_correlated(ctx, n.query):
                df = run_inner(n.query)
                changed[0] = True
                col = df.iloc[:, 0].dropna()
                if len(col) > 1024 and \
                        np.issubdtype(col.to_numpy().dtype, np.integer):
                    # semi-join-scale integer key list: O(1)-repr sorted set
                    return E.InList(n.child,
                                    E.FrozenIntSet(col.to_numpy()),
                                    negated=n.negated)
                vals = tuple(_to_python(v) for v in pd.unique(col))
                if not vals:
                    # empty IN-list: constant false (true for NOT IN)
                    return E.Literal(bool(n.negated))
                return E.InList(n.child, vals, negated=n.negated)
            if isinstance(n, A.Exists) and not _is_correlated(ctx, n.query):
                df = run_inner(n.query)
                changed[0] = True
                return E.Literal((len(df) > 0) != n.negated)
            return n

        return E.transform(e, rep)

    new_where = resolve(stmt.where)
    new_having = resolve(stmt.having)
    if not changed[0]:
        return stmt
    return dataclasses.replace(stmt, where=new_where, having=new_having)
