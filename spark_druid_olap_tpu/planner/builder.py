"""Pushdown builder: SelectStmt -> engine QuerySpec(s).

The rewrite heart of the framework — merges the reference's planner stack:

- ``DruidPlanner.plan`` + transform pipeline (``DruidPlanner.scala:39-48``)
- project/filter translation (``ProjectFilterTransfom.scala``: native
  comparisons -> Selector/Bound, In -> InFilter, Like -> PatternFilter,
  fallback to compiled-expression filters ≈ the JS filter tier)
- time predicates -> query intervals (``DateTimeExtractor`` +
  ``QueryIntervals``)
- aggregate translation (``AggregateTransform.scala``: grouping exprs ->
  dimension specs with time/expr extractions, avg -> sum+count (+ post-agg
  division), count-distinct -> HLL ``cardinality`` (approx) or a two-phase
  exact rewrite ≈ ``SPLRewriteDistinctAggregates``)
- star-join collapse (``JoinTransform.scala``: validate the join tree against
  the declared star schema, then fold everything onto the flat datasource)
- sort/limit -> LimitSpec / TopN (``LimitTransfom`` + QuerySpecTransforms)

Raises :class:`PlanUnsupported` when the query can't push; the session then
runs the host path (≈ Spark executing the un-rewritten plan).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Set, Tuple

from spark_druid_olap_tpu.ir import expr as E
from spark_druid_olap_tpu.ir import spec as S
from spark_druid_olap_tpu.ir import transforms as QT
from spark_druid_olap_tpu.ir.intervals import IntervalAccumulator
from spark_druid_olap_tpu.metadata.star import StarSchema
from spark_druid_olap_tpu.planner.plans import (
    DistinctPhase2,
    PlannedQuery,
    PlanUnsupported,
)
from spark_druid_olap_tpu.segment.column import ColumnKind
from spark_druid_olap_tpu.sql import ast as A
from spark_druid_olap_tpu.utils import phases as PH
from spark_druid_olap_tpu.utils.config import NON_AGG_PUSHDOWN

_TIME_FIELD_FUNCS = {"year", "month", "quarter", "day", "week", "dow", "doy",
                     "hour", "minute", "second"}


def _has_subquery(e) -> bool:
    if e is None or isinstance(e, str):
        return False
    for n in E.walk(e):
        if isinstance(n, (A.ScalarSubquery, A.InSubquery, A.Exists)):
            return True
    return False


def _stmt_has_subquery(stmt: A.SelectStmt) -> bool:
    for item in stmt.items:
        if item.expr != "*" and _has_subquery(item.expr):
            return True
    if _has_subquery(stmt.where) or _has_subquery(stmt.having):
        return True
    gb = stmt.group_by
    groups = []
    if isinstance(gb, tuple):
        groups = list(gb)
    elif isinstance(gb, A.GroupingSets):
        groups = [g for s in gb.sets for g in s]
    for g in groups:
        if _has_subquery(g):
            return True
    for o in stmt.order_by:
        if _has_subquery(o.expr):
            return True
    return False


def _split_conjuncts(e: Optional[E.Expr]) -> List[E.Expr]:
    if e is None:
        return []
    if isinstance(e, E.And):
        out = []
        for p in e.parts:
            out.extend(_split_conjuncts(p))
        return out
    return [e]


class Builder:
    def __init__(self, ctx, stmt: A.SelectStmt):
        self.ctx = ctx
        self.stmt = stmt
        self.ds = None                      # Datasource
        self.hidden: Set[str] = set()
        self._aggs: Dict[str, S.AggregationSpec] = {}   # by output name
        self._agg_by_call: Dict[str, str] = {}          # AggCall sql -> name
        self._post: Dict[str, S.PostAggregationSpec] = {}
        self._dim_specs: List[S.DimensionSpec] = []
        self._dim_by_expr: Dict[str, str] = {}          # expr sql -> out name
        self._n = 0
        self.distinct2: Optional[DistinctPhase2] = None

    def fresh(self, prefix: str) -> str:
        self._n += 1
        return f"__{prefix}{self._n}"

    # =========================================================================
    # relation resolution / star-join collapse
    # =========================================================================
    def resolve_relation(self) -> Tuple[str, List[E.Expr]]:
        """Returns (datasource name, join equi-conjunct predicates consumed
        from WHERE)."""
        rel = self.stmt.relation
        if rel is None:
            raise PlanUnsupported("no FROM clause")
        tables: List[str] = []
        join_conds: List[E.Expr] = []

        def walk(r):
            if isinstance(r, A.TableRef):
                tables.append(r.name)
            elif isinstance(r, A.Join):
                if r.kind not in ("inner", "cross"):
                    raise PlanUnsupported(f"{r.kind} join")
                walk(r.left)
                walk(r.right)
                if r.condition is not None:
                    join_conds.extend(_split_conjuncts(r.condition))
            else:
                raise PlanUnsupported("derived table in FROM")

        walk(rel)
        store = self.ctx.store
        if len(tables) == 1:
            t = tables[0]
            star = self.ctx.catalog.star_schema_of(t)
            if t in store.names():
                return t, []
            if star is not None and star.flat_datasource in store.names():
                return star.flat_datasource, []
            raise PlanUnsupported(f"unknown table {t!r}")

        # multi-table: must be a star join against SOME registered star
        # (shared dim tables can belong to several stars — e.g. supplier in
        # both the lineitem and partsupp stars; try each candidate and keep
        # the one whose fact anchors this join tree)
        with PH.phase("plan.star"):
            cands: List[StarSchema] = []
            for t in tables:
                for s in self.ctx.catalog.star_schemas_of(t):
                    if s not in cands:
                        cands.append(s)
            if not cands:
                raise PlanUnsupported("join without a registered star schema")
            where_conjs = _split_conjuncts(self.stmt.where)
            errors: List[str] = []
            for star in cands:
                r = self._try_star(star, tables, join_conds, where_conjs,
                                   store)
                if isinstance(r, tuple):
                    return r
                errors.append(r)
            raise PlanUnsupported("; ".join(dict.fromkeys(errors)))

    def _try_star(self, star: StarSchema, tables, join_conds, where_conjs,
                  store):
        """Validate the join tree against one candidate star; returns
        (flat_datasource, consumed_predicates) or an error string."""
        eq_pairs: List[Tuple[str, str]] = []
        consumed: List[E.Expr] = []
        star_cols = self._star_key_columns(star)
        for c in join_conds + where_conjs:
            if (isinstance(c, E.Comparison) and c.op == "=" and
                    isinstance(c.left, E.Column) and
                    isinstance(c.right, E.Column)):
                pair = (c.left.name, c.right.name)
                if frozenset(pair) in star_cols:
                    eq_pairs.append(pair)
                    consumed.append(c)
                    continue
            if c in join_conds:
                return f"non-star join condition ({E.to_sql(c)})"
        if star.fact_table not in tables:
            # a dim-only join has dim-table grain; folding it onto the flat
            # fact would change row multiplicity (the reference likewise
            # anchors every rewrite at the fact DruidRelation leaf,
            # JoinTransform.scala:305-385)
            return "join does not include the fact table"
        if not star.is_star_join(set(tables), eq_pairs):
            return "join tree is not a sub-star of the declared star schema"
        if star.flat_datasource not in store.names():
            return "star schema flat datasource not ingested"
        return star.flat_datasource, consumed

    @staticmethod
    def _star_key_columns(star: StarSchema) -> Set[frozenset]:
        out = set()
        for r in star.relations:
            for lc, rc in r.join_columns:
                out.add(frozenset((lc, rc)))
        return out

    # =========================================================================
    # filters
    # =========================================================================
    def build_filter(self, conjuncts: List[E.Expr]):
        """conjuncts -> (intervals, FilterSpec, residue).

        Pushable conjuncts become intervals / native filters / compiled
        expression filters; a conjunct whose compiled form the device
        compiler rejects (checked by a shape-only trial trace of the REAL
        lowering) is returned as host residue instead of failing the whole
        plan — ≈ the reference recording unpushed predicates and leaving a
        Spark FilterExec above the Druid scan
        (ProjectFilterTransfom.addUnpushedAttributes:36-50,
        DruidStrategy.scala:244-270).
        """
        from spark_druid_olap_tpu.utils.config import TZ_ID
        acc = IntervalAccumulator(tz=self.ctx.config.get(TZ_ID))
        specs: List[S.FilterSpec] = []
        residue: List[E.Expr] = []
        tcol = self.ds.time.name if self.ds.time is not None else None
        for c in conjuncts:
            if isinstance(c, E.Literal):
                if c.value is True:
                    continue  # inlined EXISTS etc. — constant true
                specs.append(S.ExprFilter(E.Literal(False)))
                continue
            if not E.columns_in(c):
                # column-free conjunct (e.g. the Kleene NULL-list
                # encoding fully folded): 3VL constant-fold at plan
                # time — it must act at SCAN level, never as a
                # post-aggregation residual (which would drop the
                # global identity row)
                from spark_druid_olap_tpu.utils import host_eval as HEv
                try:
                    keep = bool(HEv.eval_pred3(c, {}).all())
                except Exception:  # noqa: BLE001 — leave to lowering
                    keep = None
                if keep is True:
                    continue
                if keep is False:
                    specs.append(S.ExprFilter(E.Literal(False)))
                    continue
            if tcol is not None and self._try_interval(c, tcol, acc):
                continue
            try:
                spec = self.to_filter(c)
            except PlanUnsupported:
                residue.append(c)
                continue
            if self._has_expr_filter(spec) and \
                    not self._spec_pushable(spec):
                residue.append(c)
                continue
            specs.append(spec)
        if acc.empty:
            # contradiction: empty interval (executor prunes everything)
            return ((0, 0),), S.filter_and(specs), residue
        return acc.to_intervals(), S.filter_and(specs), residue

    @staticmethod
    def _has_expr_filter(spec: S.FilterSpec) -> bool:
        if isinstance(spec, S.ExprFilter):
            return True
        if isinstance(spec, S.LogicalFilter):
            return any(Builder._has_expr_filter(x) for x in spec.fields)
        return False

    def _spec_pushable(self, spec: S.FilterSpec) -> bool:
        """Shape-only trial trace of the real filter lowering: no coverage
        drift, no data movement."""
        import jax
        from spark_druid_olap_tpu.ops import filters as F
        from spark_druid_olap_tpu.ops.scan import (
            ScanContext, array_dtype, array_names)
        ds = self.ds
        try:
            cols = sorted(c for c in F.columns_of_filter(spec)
                          if c in ds.dims or c in ds.metrics
                          or (ds.time is not None and c == ds.time.name))
            names = array_names(ds, cols, ds.time is not None)
            shapes = {k: jax.ShapeDtypeStruct((1, 8), array_dtype(ds, k))
                      for k in names}
            jax.eval_shape(
                lambda arrays: F.lower_filter(
                    spec, ScanContext(ds, arrays, 0, 0)), shapes)
            return True
        except Exception:  # noqa: BLE001 — any rejection means host residue
            return False

    def _try_interval(self, c: E.Expr, tcol: str,
                      acc: IntervalAccumulator) -> bool:
        def lit_of(e):
            if isinstance(e, E.Literal) and not isinstance(e.value, bool):
                return e.value
            return None

        if isinstance(c, E.Comparison):
            l, r = c.left, c.right
            if isinstance(l, E.Column) and l.name == tcol and \
                    lit_of(r) is not None:
                v = lit_of(r)
                op = c.op
            elif isinstance(r, E.Column) and r.name == tcol and \
                    lit_of(l) is not None:
                v = lit_of(l)
                op = {"<": ">", "<=": ">=", ">": "<", ">=": "<="}.get(c.op,
                                                                     c.op)
            else:
                return False
            try:
                if op == ">=":
                    acc.ge(v)
                elif op == ">":
                    acc.gt(v)
                elif op == "<=":
                    acc.le(v)
                elif op == "<":
                    acc.lt(v)
                elif op == "=":
                    acc.eq(v)
                else:
                    return False
            except (ValueError, TypeError):
                return False
            return True
        if isinstance(c, E.Between) and not c.negated and \
                isinstance(c.child, E.Column) and c.child.name == tcol:
            lo, hi = lit_of(c.low), lit_of(c.high)
            if lo is None or hi is None:
                return False
            acc.ge(lo)
            acc.le(hi)
            return True
        return False

    def to_filter(self, e: E.Expr) -> S.FilterSpec:
        """Expr -> FilterSpec, preferring native filters, falling back to
        compiled-expression filters (≈ the JS filter tier)."""
        if isinstance(e, E.Comparison):
            f = self._native_comparison(e)
            if f is not None:
                return f
            return S.ExprFilter(e)
        if isinstance(e, E.And):
            return S.LogicalFilter(
                "and", tuple(self.to_filter(p) for p in e.parts))
        if isinstance(e, E.Or):
            return S.LogicalFilter(
                "or", tuple(self.to_filter(p) for p in e.parts))
        if isinstance(e, E.Not):
            return self._kleene_not(self.to_filter(e.child), e.child)
        if isinstance(e, E.IsNull):
            if isinstance(e.child, E.Column):
                return S.NullFilter(e.child.name, negated=e.negated)
            return S.ExprFilter(e)
        if isinstance(e, E.InList) and isinstance(e.child, E.Column):
            kind = self._col_kind(e.child.name)
            if isinstance(e.values, E.FrozenIntSet):
                if kind not in (ColumnKind.LONG, ColumnKind.DATE):
                    raise PlanUnsupported(
                        "large integer IN set over non-integer column")
                f = S.InFilter(e.child.name, e.values)
            elif kind == ColumnKind.DIM:
                f = S.InFilter(e.child.name,
                               tuple(str(v) for v in e.values))
            else:
                f = S.InFilter(e.child.name, tuple(e.values))
            return self._kleene_not(f, e) if e.negated else f
        if isinstance(e, E.Between) and isinstance(e.child, E.Column):
            kind = self._col_kind(e.child.name)
            lo = e.low.value if isinstance(e.low, E.Literal) else None
            hi = e.high.value if isinstance(e.high, E.Literal) else None
            if lo is not None and hi is not None:
                f = S.BoundFilter(e.child.name, lower=lo, upper=hi,
                                  numeric=kind in (ColumnKind.LONG,
                                                   ColumnKind.DOUBLE))
                return self._kleene_not(f, e) if e.negated else f
            return S.ExprFilter(e)
        if isinstance(e, E.Like) and isinstance(e.child, E.Column) and \
                self._col_kind(e.child.name) == ColumnKind.DIM:
            f = S.PatternFilter(e.child.name, "like", e.pattern)
            return self._kleene_not(f, e) if e.negated else f
        return S.ExprFilter(e)

    def _kleene_not(self, inner: S.FilterSpec, negated_expr: E.Expr):
        """SQL NOT with Kleene null semantics: a NULL operand keeps the
        predicate UNKNOWN (never TRUE), so the negation carries IS NOT
        NULL guards for every NULLABLE column it negates over — columns
        under IS [NOT] NULL or KeyedLookup subtrees excepted (those
        predicates are never UNKNOWN / handle their own misses).
        Planner-generated negations are BOOLEAN by construction: the
        decorrelation pass only inlines lookups under polarity-checked
        positions (its generated predicates are False on miss/NULL), so
        any lookup under the negation means the 3VL analysis already
        happened — plain boolean not there.

        The guard equivalence 'NOT(P) is UNKNOWN iff a referenced
        column is NULL' is EXACT only for a single column-vs-literal
        predicate; for compound children (NOT(U AND F) is TRUE, but a
        blanket guard would drop the row) the conjunct goes to the host
        tier when nullable columns are involved (eval_pred3 is a full
        Kleene evaluator)."""
        if any(isinstance(n, (E.KeyedLookup, E.KeyedLookup2))
               for n in E.walk(negated_expr)):
            return S.LogicalFilter("not", (inner,))
        nullable = sorted(
            c for c in self._cols_outside_isnull(negated_expr)
            if (col := self.ds.dims.get(c) or self.ds.metrics.get(c))
            is not None and col.validity is not None)
        if not nullable:
            return S.LogicalFilter("not", (inner,))
        if not self._simple_negatable(negated_expr):
            raise PlanUnsupported(
                "NOT over a compound predicate with nullable columns "
                "(Kleene semantics need the host evaluator)")
        return S.LogicalFilter(
            "and", (S.LogicalFilter("not", (inner,)),)
            + tuple(S.NullFilter(c, negated=True) for c in nullable))

    @staticmethod
    def _cols_outside_isnull(e: E.Expr) -> Set[str]:
        out: Set[str] = set()

        def rec(n):
            if isinstance(n, E.IsNull):
                return
            if isinstance(n, E.Column):
                out.add(n.name)
            for ch in n.children():
                rec(ch)

        rec(e)
        return out

    @staticmethod
    def _simple_negatable(e: E.Expr) -> bool:
        """One column-vs-literal predicate: its UNKNOWN-ness is exactly
        'the column is NULL', so the IS NOT NULL guard is lossless."""
        def col_or_lit(x):
            return isinstance(x, (E.Column, E.Literal))

        if isinstance(e, E.Comparison):
            return col_or_lit(e.left) and col_or_lit(e.right)
        if isinstance(e, (E.InList, E.Like)):
            return isinstance(e.child, E.Column)
        if isinstance(e, E.Between):
            return isinstance(e.child, E.Column) \
                and col_or_lit(e.low) and col_or_lit(e.high)
        return False

    def _col_kind(self, name: str) -> Optional[ColumnKind]:
        try:
            return self.ds.column_kind(name)
        except KeyError:
            raise PlanUnsupported(f"unknown column {name!r}")

    def _native_comparison(self, c: E.Comparison) -> Optional[S.FilterSpec]:
        l, r = c.left, c.right
        op = c.op
        if isinstance(r, E.Column) and isinstance(l, E.Literal):
            l, r = r, l
            op = E.FLIP_CMP.get(op, op)
        if not (isinstance(l, E.Column) and isinstance(r, E.Literal)):
            return None
        kind = self._col_kind(l.name)
        v = r.value
        if v is None and op != "=":
            # NULL comparison is three-valued-unknown -> matches nothing
            return S.ExprFilter(E.Literal(False))
        if kind == ColumnKind.TIME:
            return None  # handled via intervals or ExprFilter
        numeric = kind in (ColumnKind.LONG, ColumnKind.DOUBLE)
        if op == "=":
            return S.SelectorFilter(l.name, None if v is None else str(v)) \
                if kind == ColumnKind.DIM else \
                S.BoundFilter(l.name, lower=v, upper=v, numeric=numeric)
        if op == "!=":
            inner = self._native_comparison(E.Comparison("=", l, r))
            nn = S.NullFilter(l.name, negated=True)
            return S.LogicalFilter("and",
                                   (S.LogicalFilter("not", (inner,)), nn))
        if op in ("<", "<=", ">", ">="):
            if op in (">", ">="):
                return S.BoundFilter(l.name, lower=v,
                                     lower_strict=(op == ">"),
                                     numeric=numeric)
            return S.BoundFilter(l.name, upper=v,
                                 upper_strict=(op == "<"), numeric=numeric)
        return None

    # =========================================================================
    # dimensions
    # =========================================================================
    def to_dimension(self, e: E.Expr, name: str) -> S.DimensionSpec:
        if isinstance(e, E.Column):
            kind = self._col_kind(e.name)
            if kind == ColumnKind.TIME:
                raise PlanUnsupported("group by raw timestamp")
            return S.DimensionSpec(e.name, name)
        if isinstance(e, E.Func) and e.name.lower() in _TIME_FIELD_FUNCS \
                and len(e.args) == 1 and isinstance(e.args[0], E.Column):
            return S.DimensionSpec(e.args[0].name, name,
                                   S.TimeExtraction(e.name.lower()))
        if isinstance(e, E.Func) and e.name.lower() in ("date_trunc", "trunc") \
                and isinstance(e.args[0], E.Literal) \
                and isinstance(e.args[1], E.Column):
            grain = str(e.args[0].value).lower()
            if grain in ("year", "quarter", "month", "week", "day"):
                return S.DimensionSpec(e.args[1].name, name,
                                       S.TimeExtraction("trunc_" + grain))
        if isinstance(e, E.Func) and e.name == "__lookup_pairs" \
                and isinstance(e.args[0], E.Column) \
                and isinstance(e.args[1], E.Literal):
            return S.DimensionSpec(e.args[0].name, name,
                                   S.LookupExtraction(tuple(e.args[1].value)))
        if isinstance(e, E.Func) and e.name.lower() == "regexp_extract" \
                and isinstance(e.args[0], E.Column) \
                and all(isinstance(a, E.Literal) for a in e.args[1:]):
            idx = int(e.args[2].value) if len(e.args) > 2 else 1
            return S.DimensionSpec(
                e.args[0].name, name,
                S.RegexExtraction(str(e.args[1].value), idx,
                                  replace_missing=True))
        return S.DimensionSpec(self._expr_dim_source(e), name,
                               S.ExprExtraction(e))

    def _expr_dim_source(self, e: E.Expr) -> str:
        cols = sorted(E.columns_in(e))
        if not cols:
            raise PlanUnsupported(f"constant group expression {E.to_sql(e)}")
        return cols[0]

    # =========================================================================
    # aggregations
    # =========================================================================
    def agg_for_call(self, call: E.AggCall) -> str:
        """Register an AggregationSpec (or avg/distinct decomposition) for an
        AggCall; returns the output column name carrying its value."""
        key = E.to_sql(call)
        if key in self._agg_by_call:
            return self._agg_by_call[key]
        name = self._agg_output_name(call)
        if call.fn == "sum" and isinstance(call.arg, E.Literal) \
                and isinstance(call.arg.value, (int, float)) \
                and not isinstance(call.arg.value, bool) \
                and not call.distinct:
            # sum(lit) == count(*) * lit (≈ SumOfLiteralRewrite,
            # DruidLogicalOptimizer.scala:245-302); over zero rows SQL's
            # SUM is NULL, not 0, so guard on the count
            c = self.fresh("cnt")
            self._register_agg(E.AggCall("count", None), c)
            self._post[name] = S.PostAggregationSpec(
                name, E.Case(
                    ((E.Comparison("=", E.Column(c), E.Literal(0)),
                      E.Literal(float("nan"))),),
                    E.BinaryOp("*", E.Column(c), call.arg)))
            self.hidden.add(c)
            self._agg_by_call[key] = name
            return name
        if call.fn == "avg":
            s = self.fresh("sum")
            c = self.fresh("cnt")
            self._register_agg(E.AggCall("sum", call.arg), s)
            self._register_agg(E.AggCall("count", call.arg), c)
            self._post[name] = S.PostAggregationSpec(
                name, E.BinaryOp("/", E.Column(s), E.Column(c)))
            self.hidden.add(s)
            self.hidden.add(c)
            self._agg_by_call[key] = name
            return name
        if call.distinct and call.fn == "count":
            if call.approx:
                self._register_cardinality(call, name)
                self._agg_by_call[key] = name
                return name
            self._plan_exact_distinct(call, name)
            self._agg_by_call[key] = name
            return name
        if call.fn == "theta":
            if not isinstance(call.arg, E.Column):
                raise PlanUnsupported("theta sketch over expression")
            self._aggs[name] = S.AggregationSpec("thetasketch", name,
                                                 field=call.arg.name)
            self._agg_by_call[key] = name
            return name
        if call.fn == "percentile":
            if not isinstance(call.arg, E.Column):
                raise PlanUnsupported("percentile_approx over expression")
            kind = self._col_kind(call.arg.name)
            if kind not in (ColumnKind.LONG, ColumnKind.DOUBLE):
                raise PlanUnsupported(
                    "percentile_approx over non-numeric column")
            self._aggs[name] = S.AggregationSpec(
                "quantile", name, field=call.arg.name,
                fraction=call.fraction)
            self._agg_by_call[key] = name
            return name
        if call.distinct:
            raise PlanUnsupported(f"distinct {call.fn}")
        self._register_agg(call, name)
        self._agg_by_call[key] = name
        return name

    def _agg_output_name(self, call: E.AggCall) -> str:
        # prefer the select alias when the item is exactly this agg
        for item in self.stmt.items:
            if item.expr == call and item.alias:
                return item.alias
        return self.fresh(call.fn)

    def _register_agg(self, call: E.AggCall, name: str):
        arg = call.arg
        filt = None
        if call.fn == "count":
            if arg is None:
                self._aggs[name] = S.AggregationSpec("count", name)
                return
            if isinstance(arg, E.Column):
                col = self.ds.dims.get(arg.name) or \
                    self.ds.metrics.get(arg.name)
                if col is not None and col.validity is not None:
                    filt = S.NullFilter(arg.name, negated=True)
                self._aggs[name] = S.AggregationSpec("count", name,
                                                     filter=filt)
                return
            self._aggs[name] = S.AggregationSpec("count", name)
            return
        if call.fn not in ("sum", "min", "max"):
            raise PlanUnsupported(f"aggregate {call.fn}")
        if isinstance(arg, E.Column):
            kind = self._col_kind(arg.name)
            if kind == ColumnKind.DIM:
                k = "doublesum" if call.fn == "sum" else f"double{call.fn}"
                self._aggs[name] = S.AggregationSpec(k, name, field=arg.name)
                return
            if kind == ColumnKind.DATE and call.fn in ("min", "max"):
                raise PlanUnsupported("min/max over date column")
            prefix = "long" if kind in (ColumnKind.LONG,) else "double"
            self._aggs[name] = S.AggregationSpec(f"{prefix}{call.fn}", name,
                                                 field=arg.name)
            return
        # computed input
        self._aggs[name] = S.AggregationSpec(
            "doublesum" if call.fn == "sum" else f"double{call.fn}",
            name, expr=arg)

    def _plan_exact_distinct(self, call: E.AggCall, name: str):
        if self.distinct2 is not None:
            raise PlanUnsupported("multiple exact count-distincts")
        if not isinstance(call.arg, E.Column):
            raise PlanUnsupported("count(distinct <expr>)")
        dimname = self.fresh("dd")
        self._dim_specs.append(self.to_dimension(call.arg, dimname))
        self._dim_by_expr[E.to_sql(call.arg)] = self._dim_by_expr.get(
            E.to_sql(call.arg), dimname)
        self.distinct2 = DistinctPhase2(
            group_cols=[], distinct_out=name, distinct_dim=dimname,
            other_aggs={})

    # cardinality agg for approx distinct
    def _register_cardinality(self, call: E.AggCall, name: str):
        if not isinstance(call.arg, E.Column):
            raise PlanUnsupported("approx_count_distinct(<expr>)")
        self._aggs[name] = S.AggregationSpec("cardinality", name,
                                             field=call.arg.name)

    # =========================================================================
    # the main build
    # =========================================================================
    def build(self) -> PlannedQuery:
        stmt = self.stmt
        if _stmt_has_subquery(stmt):
            raise PlanUnsupported("subquery")
        # the session's window post-pass strips WindowCalls before
        # planning; one surviving here (derived table / assisted subtree)
        # can't be pushed
        for item in stmt.items:
            if item.expr != "*" and any(
                    isinstance(n, E.WindowCall) for n in E.walk(item.expr)):
                raise PlanUnsupported("window function in a subtree")
        ds_name, consumed = self.resolve_relation()
        self.ds = self.ctx.store.get(ds_name)

        # WHERE minus consumed join conjuncts
        conjs = [c for c in _split_conjuncts(stmt.where)
                 if not any(c is k for k in consumed)]
        intervals, filter_spec, residue = self.build_filter(conjs)
        filter_spec = QT.merge_spatial_bounds(filter_spec, self.ds)
        self._residue = residue

        # resolve group-by expressions
        alias_map = {item.alias: item.expr for item in stmt.items
                     if item.alias and item.expr != "*"}
        if isinstance(stmt.group_by, A.GroupingSets):
            raw_sets = [list(s) for s in stmt.group_by.sets]
        elif stmt.group_by is None:
            raw_sets = [[]]
        else:
            raw_sets = [list(stmt.group_by)]

        def resolve_g(g):
            if isinstance(g, E.Literal) and isinstance(g.value, int):
                it = stmt.items[g.value - 1]
                if it.expr == "*":
                    raise PlanUnsupported("GROUP BY ordinal of *")
                return it.expr
            if isinstance(g, E.Column) and g.name in alias_map:
                return alias_map[g.name]
            return g

        resolved_sets = [[resolve_g(g) for g in s] for s in raw_sets]

        is_agg = stmt.group_by is not None or any(
            item.expr != "*" and E.agg_calls_in(item.expr)
            for item in stmt.items)
        if stmt.having is not None:
            is_agg = True

        if not is_agg:
            return self._build_select_path(ds_name, intervals, filter_spec,
                                           residue)

        # dims for the union of group exprs
        for s_ in resolved_sets:
            for g in s_:
                k = E.to_sql(g)
                if k in self._dim_by_expr:
                    continue
                name = None
                for item in stmt.items:
                    if item.expr == g:
                        name = item.alias or (
                            g.name if isinstance(g, E.Column) else None)
                        break
                if name is None and isinstance(g, E.Column):
                    name = g.name
                if name is None:
                    name = self.fresh("g")
                self._dim_by_expr[k] = name
                self._dim_specs.append(self.to_dimension(g, name))

        # FD demotion: a plain grouping column functionally determined by
        # another grouping column leaves the fused key and becomes an
        # 'anyvalue' aggregation (≈ FunctionalDependencies keeping the group
        # key small; critical for TPC-H Q3/Q10-style keys+attributes groups)
        if len(resolved_sets) == 1 and len(self._dim_specs) > 1:
            g = self.ctx.catalog.fd_graph_for(ds_name, self.ctx.store)
            if g is not None:
                plain = [d for d in self._dim_specs if d.extraction is None]

                def demoted(d, i):
                    # any OTHER plain dim determines d -> d leaves the key;
                    # mutually-determining pairs (1-1) keep the earlier one
                    for j, k in enumerate(plain):
                        if k is d or not g.determines(k.dimension,
                                                     d.dimension):
                            continue
                        if g.determines(d.dimension, k.dimension) and \
                                plain.index(d) < j:
                            continue
                        return True
                    return False

                kept: List[S.DimensionSpec] = []
                attached: List[S.DimensionSpec] = []
                for i, d in enumerate(self._dim_specs):
                    if d.extraction is None and demoted(d, i):
                        attached.append(d)
                    else:
                        kept.append(d)
                for d in attached:
                    self._aggs[d.output_name] = S.AggregationSpec(
                        "anyvalue", d.output_name, field=d.dimension)
                self._dim_specs = kept

        # WHERE residue over an aggregate: sound only when every residue
        # column is a grouping column present in EVERY grouping set (then
        # filtering result groups == filtering source rows); map source
        # names onto dim output names for the host-side evaluation
        residual_expr = None
        if self._residue:
            out_of = {}
            for c in set().union(*(E.columns_in(r) for r in self._residue)):
                k = E.to_sql(E.Column(c))
                if k not in self._dim_by_expr:
                    raise PlanUnsupported(
                        f"unpushable predicate over non-grouped column {c}")
                out_of[c] = self._dim_by_expr[k]
                for s_ in resolved_sets:
                    if not any(E.to_sql(g) == k for g in s_):
                        raise PlanUnsupported(
                            "unpushable predicate over a column absent "
                            "from one grouping set")
            combined = self._residue[0] if len(self._residue) == 1 \
                else E.And(tuple(self._residue))

            def ren(n):
                if isinstance(n, E.Column) and n.name in out_of:
                    return E.Column(out_of[n.name])
                return n
            residual_expr = E.transform(combined, ren)

        # select outputs
        output_columns: List[str] = []
        for i, item in enumerate(stmt.items):
            if item.expr == "*":
                raise PlanUnsupported("SELECT * in aggregate query")
            out = self._plan_output_item(item, i)
            output_columns.append(out)

        # HAVING
        having_spec = None
        if stmt.having is not None:
            h = self._replace_aggs_and_dims(stmt.having)
            having_spec = S.HavingSpec(h)

        # ORDER BY / LIMIT
        order_by: List[Tuple[str, bool]] = []
        for o in stmt.order_by:
            order_by.append((self._order_col(o, output_columns), o.ascending))

        multi_set = len(resolved_sets) > 1
        limit_spec = None
        order_in_spec = False
        if not multi_set and self.distinct2 is None \
                and residual_expr is None and (order_by or stmt.limit):
            # an in-spec limit would truncate before the host residue runs
            limit_spec = S.LimitSpec(
                tuple(S.OrderByColumn(n, asc) for n, asc in order_by),
                stmt.limit)
            order_in_spec = True

        if stmt.distinct:
            raise PlanUnsupported("SELECT DISTINCT with aggregation")

        # assemble one spec per grouping set
        specs = []
        spec_dims = []
        aggs = tuple(self._aggs.values())
        posts = tuple(self._post.values())
        deferred_posts = []
        if self.distinct2 is not None:
            if having_spec is not None:
                raise PlanUnsupported("HAVING with exact count-distinct")
            # post-aggs must evaluate after the phase-2 merge
            deferred_posts = list(posts)
            posts = ()
        rollup_used = None
        for s_ in resolved_sets:
            set_dim_names = [self._dim_by_expr[E.to_sql(g)] for g in s_]
            dimlist = [d for d in self._dim_specs
                       if d.output_name in set_dim_names
                       or d.output_name == (self.distinct2.distinct_dim
                                            if self.distinct2 else None)]
            q = S.GroupByQuerySpec(
                datasource=ds_name, dimensions=tuple(dimlist),
                aggregations=aggs, post_aggregations=posts,
                filter=filter_spec, having=having_spec,
                limit=limit_spec if not multi_set else None,
                intervals=intervals)
            # materialized-rollup rewrite, BEFORE spec transforms so a
            # rewritten GroupBy can still become timeseries/topN/search
            from spark_druid_olap_tpu.mv import match as MV
            with PH.phase("plan.rollup"):
                q2, mv_name = MV.try_rewrite(self.ctx, q)
            if q2 is not None:
                q = q2
                rollup_used = mv_name
            q = QT.transform(q, self.ctx.config,
                             getattr(self.ctx, "spec_rules", ()))
            specs.append(q)
            spec_dims.append(set_dim_names)

        all_dims = [d.output_name for d in self._dim_specs
                    if not (self.distinct2 and
                            d.output_name == self.distinct2.distinct_dim)]
        if self.distinct2 is not None:
            self.distinct2.group_cols = all_dims
            for aname, aspec in self._aggs.items():
                if aspec.kind in ("longsum", "doublesum", "count"):
                    self.distinct2.other_aggs[aname] = "sum"
                elif aspec.kind.endswith("min"):
                    self.distinct2.other_aggs[aname] = "min"
                elif aspec.kind.endswith("max") or aspec.kind == "anyvalue":
                    self.distinct2.other_aggs[aname] = "max"
                elif aspec.kind == "cardinality":
                    raise PlanUnsupported(
                        "mixing exact and approx count-distinct")

        return PlannedQuery(
            datasource=ds_name, specs=specs, spec_dims=spec_dims,
            all_dims=all_dims, output_columns=output_columns,
            order_by=order_by, limit=stmt.limit,
            order_applied_in_spec=order_in_spec,
            distinct_phase2=self.distinct2,
            deferred_posts=deferred_posts,
            residual=residual_expr,
            rollup=rollup_used)

    def _plan_output_item(self, item: A.SelectItem, idx: int) -> str:
        e = item.expr
        k = E.to_sql(e)
        # exactly a group expr?
        if k in self._dim_by_expr:
            return self._dim_by_expr[k]
        calls = E.agg_calls_in(e)
        if isinstance(e, E.AggCall):
            name = self.agg_for_call(e)
            if item.alias and item.alias != name:
                # alias differs from generated (e.g. repeated agg): post-agg
                self._post[item.alias] = S.PostAggregationSpec(
                    item.alias, E.Column(name))
                return item.alias
            return name
        if calls or not E.columns_in(e):
            name = item.alias or f"_c{idx}"
            expr2 = self._replace_aggs_and_dims(e)
            self._post[name] = S.PostAggregationSpec(name, expr2)
            return name
        # expression over group dims only
        expr2 = self._replace_aggs_and_dims(e)
        leftover = E.columns_in(expr2) - set(self._dim_by_expr.values()) \
            - set(self._aggs) - set(self._post)
        if leftover:
            raise PlanUnsupported(
                f"select item {E.to_sql(e)} not derivable from GROUP BY")
        name = item.alias or f"_c{idx}"
        self._post[name] = S.PostAggregationSpec(name, expr2)
        return name

    def _replace_aggs_and_dims(self, e: E.Expr) -> E.Expr:
        dimmap = self._dim_by_expr

        def rep(n):
            if isinstance(n, E.AggCall):
                return E.Column(self.agg_for_call(n))
            k = E.to_sql(n)
            if k in dimmap and not isinstance(n, (E.Literal, E.Column)):
                return E.Column(dimmap[k])
            if isinstance(n, E.Column) and k in dimmap:
                return E.Column(dimmap[k])
            return n

        return E.transform(e, rep)

    def _order_col(self, o: A.OrderItem, output_columns: List[str]) -> str:
        e = o.expr
        if isinstance(e, E.Literal) and isinstance(e.value, int):
            return output_columns[e.value - 1]
        k = E.to_sql(e)
        if k in self._dim_by_expr:
            return self._dim_by_expr[k]
        if isinstance(e, E.Column):
            if e.name in output_columns or e.name in self._aggs \
                    or e.name in self._post:
                return e.name
        if isinstance(e, E.AggCall):
            return self.agg_for_call(e)
        # expression over aggs/dims -> hidden post-agg
        expr2 = self._replace_aggs_and_dims(e)
        name = self.fresh("ord")
        self._post[name] = S.PostAggregationSpec(name, expr2)
        self.hidden.add(name)
        return name

    # =========================================================================
    # non-aggregate (select) path
    # =========================================================================
    def _build_select_path(self, ds_name, intervals, filter_spec,
                           residue=None):
        from spark_druid_olap_tpu.utils.config import SELECT_PAGE_SIZE
        mode = self.ctx.config.get(NON_AGG_PUSHDOWN)
        if mode == "push_none":
            raise PlanUnsupported("non-aggregate pushdown disabled")
        stmt = self.stmt
        residual_expr = None
        residue_cols: List[str] = []
        if residue:
            residual_expr = residue[0] if len(residue) == 1 \
                else E.And(tuple(residue))
            residue_cols = sorted(E.columns_in(residual_expr))
            for c in residue_cols:
                if c not in self.ds.column_names():
                    raise PlanUnsupported(
                        f"unpushable predicate over unknown column {c}")
        cols: List[str] = []
        renames: Dict[str, str] = {}
        for item in stmt.items:
            if item.expr == "*" or (isinstance(item.expr, E.Column)
                                    and item.expr.name == "*"):
                cols.extend(self.ds.column_names())
                continue
            if not isinstance(item.expr, E.Column):
                raise PlanUnsupported("computed select item on select path")
            if item.alias and item.alias != item.expr.name:
                if item.expr.name in renames:
                    raise PlanUnsupported(
                        "column selected twice with different aliases")
                renames[item.expr.name] = item.alias
            cols.append(item.expr.name)
        for src in renames:
            if cols.count(src) > 1:
                # SELECT region, region AS r would apply the rename to every
                # occurrence; let the host tier keep both output columns.
                raise PlanUnsupported(
                    "column selected both bare and aliased")
        out_cols = [renames.get(c, c) for c in cols]
        for src, tgt in renames.items():
            if tgt != src and (tgt in cols or tgt in residue_cols):
                # SELECT qty AS region ... with 'region' also fetched
                # (selected or needed by the residue) would duplicate the
                # label after renaming
                raise PlanUnsupported(
                    f"alias {tgt!r} collides with a fetched column")
        if stmt.distinct:
            if residual_expr is not None:
                raise PlanUnsupported(
                    "unpushable predicate with SELECT DISTINCT")
            # SELECT DISTINCT dims -> group-by rewrite
            dims = tuple(S.DimensionSpec(c, c) for c in cols)
            q = S.GroupByQuerySpec(
                datasource=ds_name, dimensions=dims,
                aggregations=(S.AggregationSpec("count", "__count__"),),
                filter=filter_spec, intervals=intervals)
            order_by = [(self._select_order_col(o, cols), o.ascending)
                        for o in stmt.order_by]
            return PlannedQuery(
                datasource=ds_name, specs=[q], spec_dims=[list(cols)],
                all_dims=list(cols), output_columns=out_cols,
                order_by=order_by, limit=stmt.limit,
                select_renames=renames)
        order_by = [(self._select_order_col(o, cols), o.ascending)
                    for o in stmt.order_by]
        fetch = list(cols)
        for c in residue_cols:           # hidden columns the residue needs
            if c not in fetch:
                fetch.append(c)
        q = S.SelectQuerySpec(
            datasource=ds_name, columns=tuple(fetch), filter=filter_spec,
            intervals=intervals,
            page_size=(stmt.limit
                       if stmt.limit is not None and not order_by
                       and residual_expr is None
                       else 1 << 31))
        return PlannedQuery(
            datasource=ds_name, specs=[q], spec_dims=[[]], all_dims=[],
            output_columns=out_cols, order_by=order_by, limit=stmt.limit,
            select_path=True, select_renames=renames,
            residual=residual_expr)

    def _select_order_col(self, o: A.OrderItem, cols: List[str]) -> str:
        e = o.expr
        if isinstance(e, E.Literal) and isinstance(e.value, int):
            return cols[e.value - 1]
        if isinstance(e, E.Column) and e.name in cols:
            return e.name
        raise PlanUnsupported("ORDER BY expression on select path")


def build(ctx, stmt: A.SelectStmt) -> PlannedQuery:
    if isinstance(stmt, A.UnionAll):
        raise PlanUnsupported("UNION ALL (session plans each branch)")
    if getattr(stmt, "offset", 0):
        # the top-level session strips OFFSET before building; an
        # offset-bearing stmt here is a derived table / assisted subtree,
        # where the host tier must apply it
        raise PlanUnsupported("OFFSET in a derived table (host tier)")
    return Builder(ctx, stmt).build()
