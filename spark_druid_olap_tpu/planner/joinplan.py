"""General-join recognition + tier selection (the ``join/`` planner).

``planner/builder.py`` only pushes STAR joins down (FD-closure rewrite
onto one fact scan); everything else used to fall straight to the host
pandas tier. This pass sits BETWEEN the composite planner and the host
fallback in the session dispatch: when a statement is a two-table
inner/cross join of stored datasources with at least one equi key and a
plain aggregate shape, it lowers to a :class:`JoinPlan` and executes on
one of the device join tiers:

- ``join/broadcast.py`` when the build side fits
  ``sdot.join.broadcast.max.bytes`` (device-resident hash table probed
  inside the segment wave loop);
- ``join/partitioned.py`` when a cluster is attached and the exchange
  prices cheaper (or the build side exceeds the broadcast cap).

``parallel/cost.py:join_estimate`` arbitrates; ``sdot.join.mode``
forces a tier. Anything outside the recognized surface — or any
execution-time decline (:class:`JoinUnsupported`) — falls through to
the host tier unchanged, so this pass can only ADD servable shapes.

Column attribution: the alias-scoping pass has already rewritten
duplicate self-join legs into rename projections (``__sj<i>_<col>``),
so every query-visible name maps to exactly one side — except join keys
between DIFFERENT tables, which scoping leaves bare on both sides
(``k = k``); those are equi keys on both sides and, after an inner equi
join, either side's value is THE value, so other references attribute
to the probe side."""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

import numpy as np
import pandas as pd

from spark_druid_olap_tpu.ir import expr as E
from spark_druid_olap_tpu.ops.hash_join import JoinUnsupported
from spark_druid_olap_tpu.segment.column import ColumnKind
from spark_druid_olap_tpu.sql import ast as A
from spark_druid_olap_tpu.utils import phases as PH
from spark_druid_olap_tpu.utils.config import (
    JOIN_ENABLED,
    JOIN_MAX_MATCHES,
)

_AGG_FNS = ("count", "sum", "min", "max", "avg")


@dataclasses.dataclass
class SideInfo:
    ds: str                        # stored datasource name
    ren: Dict[str, str]            # query-visible name -> physical column

    def phys(self, qname: str) -> str:
        return self.ren[qname]


@dataclasses.dataclass
class AggSpec:
    out: str                       # output column name
    fn: str                        # count | sum | min | max | avg
    arg: Optional[E.Expr]          # in query names; None for count(*)


@dataclasses.dataclass
class JoinPlan:
    probe: SideInfo
    build: SideInfo
    keys: List[Tuple[str, str]]            # (probe phys, build phys)
    probe_filter: Optional[E.Expr]         # physical names
    build_filter: Optional[E.Expr]         # physical names
    residual: Optional[E.Expr]             # query names (post-probe)
    colside: Dict[str, Tuple[str, str]]    # qname -> ('probe'|'build', phys)
    group_by: List[str]                    # query names
    aggs: List[AggSpec]
    having: Optional[E.Expr]
    order_by: Tuple[A.OrderItem, ...]
    limit: Optional[int]
    items: Tuple[A.SelectItem, ...]

    def probe_cols(self) -> set:
        out = {pc for pc, _ in self.keys}
        out |= {phys for q, (s, phys) in self.colside.items()
                if s == "probe"}
        if self.probe_filter is not None:
            out |= E.columns_in(self.probe_filter)
        return out

    def build_cols(self) -> set:
        out = {bc for _, bc in self.keys}
        out |= {phys for q, (s, phys) in self.colside.items()
                if s == "build"}
        if self.build_filter is not None:
            out |= E.columns_in(self.build_filter)
        return out

    def build_value_cols(self) -> set:
        """Build phys columns needed as device payload (agg args and
        residual refs — group columns travel as codes instead)."""
        used = set()
        for s in self.aggs:
            if s.arg is not None:
                used |= E.columns_in(s.arg)
        if self.residual is not None:
            used |= E.columns_in(self.residual)
        return {self.colside[q][1] for q in used
                if q in self.colside and self.colside[q][0] == "build"}

    def swapped(self) -> "JoinPlan":
        flip = {"probe": "build", "build": "probe"}
        return JoinPlan(
            probe=self.build, build=self.probe,
            keys=[(b, p) for p, b in self.keys],
            probe_filter=self.build_filter,
            build_filter=self.probe_filter,
            residual=self.residual,
            colside={q: (flip[s], c)
                     for q, (s, c) in self.colside.items()},
            group_by=self.group_by, aggs=self.aggs, having=self.having,
            order_by=self.order_by, limit=self.limit, items=self.items)


def plan_to_dict(plan: JoinPlan, max_matches: int) -> dict:
    """JSON-safe lowered spec for the partitioned tier's exec hop."""
    from spark_druid_olap_tpu.ir import serde as SERDE
    return {
        "keys": [[p, b] for p, b in plan.keys],
        "colside": {q: [s, c] for q, (s, c) in plan.colside.items()},
        "group_by": list(plan.group_by),
        "aggs": [{"out": s.out, "fn": s.fn,
                  "arg": SERDE.expr_to_dict(s.arg)
                  if s.arg is not None else None}
                 for s in plan.aggs],
        "residual": SERDE.expr_to_dict(plan.residual)
        if plan.residual is not None else None,
        "max_matches": int(max_matches),
    }


# =============================================================================
# recognition
# =============================================================================

def _unwrap_leaf(ctx, rel) -> Optional[SideInfo]:
    """A join leaf -> SideInfo, or None when outside the surface.
    Accepts a stored TableRef or the alias-scoping pass's rename
    projection (SubqueryRef over a pure column projection)."""
    store = ctx.store
    if isinstance(rel, A.TableRef):
        try:
            ds = store.get(rel.name)
        except KeyError:
            return None
        return SideInfo(rel.name, {c: c for c in ds.column_names()})
    if isinstance(rel, A.SubqueryRef):
        q = rel.query
        if not isinstance(q, A.SelectStmt) \
                or not isinstance(q.relation, A.TableRef) \
                or q.where is not None or q.group_by is not None \
                or q.having is not None or q.order_by \
                or q.limit is not None or q.distinct:
            return None
        try:
            ds = store.get(q.relation.name)
        except KeyError:
            return None
        ren: Dict[str, str] = {}
        for it in q.items:
            if not isinstance(it.expr, E.Column):
                return None
            ren[it.alias or it.expr.name] = it.expr.name
        if any(c not in ds.column_names() for c in ren.values()):
            return None
        return SideInfo(q.relation.name, ren)
    return None


def _flatten_and(e: Optional[E.Expr]) -> List[E.Expr]:
    if e is None:
        return []
    if isinstance(e, E.And):
        out = []
        for p in e.parts:
            out.extend(_flatten_and(p))
        return out
    return [e]


def _rewrite_phys(e: E.Expr, ren: Dict[str, str]) -> E.Expr:
    def fn(n):
        if isinstance(n, E.Column):
            return E.Column(ren[n.name])
        return n
    return E.transform(e, fn)


def try_plan(ctx, stmt: A.SelectStmt) -> Optional[JoinPlan]:
    """Recognize ``stmt`` as a servable two-table join; None when it is
    not (the caller falls through to the host tier)."""
    rel = stmt.relation
    if not isinstance(rel, A.Join) or rel.kind not in ("inner", "cross"):
        return None
    if stmt.distinct or isinstance(stmt.group_by, A.GroupingSets):
        return None
    a = _unwrap_leaf(ctx, rel.left)
    b = _unwrap_leaf(ctx, rel.right)
    if a is None or b is None:
        return None
    store = ctx.store
    ds_a, ds_b = store.get(a.ds), store.get(b.ds)
    vis_a, vis_b = set(a.ren), set(b.ren)
    shared = vis_a & vis_b

    def owner(name: str) -> Optional[str]:
        if name in shared:
            return "shared"
        if name in vis_a:
            return "a"
        if name in vis_b:
            return "b"
        return None

    def refs_side(e: E.Expr) -> Optional[str]:
        """'a'|'b' when every column of ``e`` resolves to one side
        (shared names count as either), 'x' for cross-side, None for
        an unknown name."""
        sides = set()
        for c in E.columns_in(e):
            o = owner(c)
            if o is None:
                return None
            sides.add(o)
        only = sides - {"shared"}
        if len(only) > 1:
            return "x"
        if only:
            return only.pop()
        return "a"      # shared-only (or constant): either side works

    # -- conjuncts: side filters / equi keys / residual -----------------------
    conjuncts = _flatten_and(rel.condition) + _flatten_and(stmt.where)
    filt: Dict[str, List[E.Expr]] = {"a": [], "b": []}
    keys_ab: List[Tuple[str, str]] = []
    residual: List[E.Expr] = []
    for c in conjuncts:
        if any(isinstance(n, (A.ScalarSubquery, A.InSubquery, A.Exists))
               for n in E.walk(c)):
            return None
        if isinstance(c, E.Comparison) and c.op == "=" \
                and isinstance(c.left, E.Column) \
                and isinstance(c.right, E.Column):
            lo, ro = owner(c.left.name), owner(c.right.name)
            if lo is None or ro is None:
                return None
            if {lo, ro} == {"a", "b"}:
                l, r = (c.left.name, c.right.name) if lo == "a" \
                    else (c.right.name, c.left.name)
                keys_ab.append((l, r))
                continue
            if lo == ro == "shared" and c.left.name == c.right.name:
                keys_ab.append((c.left.name, c.right.name))
                continue
        side = refs_side(c)
        if side is None:
            return None
        if side == "x":
            residual.append(c)
        else:
            filt[side].append(c)
    if not keys_ab:
        return None         # pure cross joins stay on the host tier

    # -- output shape ---------------------------------------------------------
    group_exprs = stmt.group_by or ()
    group_by: List[str] = []
    for g in group_exprs:
        if not isinstance(g, E.Column) or owner(g.name) is None:
            return None
        group_by.append(g.name)
    aggs: List[AggSpec] = []
    used_names: List[str] = list(group_by)
    for i, item in enumerate(stmt.items):
        e = item.expr
        if e == "*" or (isinstance(e, E.Column) and e.name == "*"):
            return None
        if isinstance(e, E.Column):
            if e.name not in group_by:
                return None
            continue
        if not isinstance(e, E.AggCall):
            return None
        if e.fn not in _AGG_FNS or e.distinct or e.approx:
            return None
        if e.arg is not None:
            for c in E.columns_in(e.arg):
                if owner(c) is None:
                    return None
                used_names.append(c)
        aggs.append(AggSpec(item.alias or f"_c{i}", e.fn, e.arg))
    if not aggs:
        return None         # row-returning joins stay on the host tier
    for r in residual:
        used_names.extend(E.columns_in(r))

    # no time columns anywhere in the join surface (the wave loop's
    # ms-since-epoch pseudo column needs interval machinery this tier
    # does not carry)
    def is_time(side: SideInfo, ds, qname: str) -> bool:
        phys = side.ren.get(qname)
        return phys is not None and ds.time is not None \
            and phys == ds.time.name
    for qname in set(used_names) | {k for k, _ in keys_ab} \
            | {k for _, k in keys_ab}:
        if is_time(a, ds_a, qname) or is_time(b, ds_b, qname):
            return None
    for side, ds, fl in (("a", ds_a, filt["a"]), ("b", ds_b, filt["b"])):
        si = a if side == "a" else b
        for f in fl:
            if any(is_time(si, ds, c) for c in E.columns_in(f)):
                return None

    # -- colside attribution (shared names resolve to side a = probe) ---------
    colside: Dict[str, Tuple[str, str]] = {}
    for qname in set(used_names):
        o = owner(qname)
        if o in ("a", "shared"):
            colside[qname] = ("probe", a.ren[qname])
        else:
            colside[qname] = ("build", b.ren[qname])

    def mk_filter(side: SideInfo, parts: List[E.Expr]) -> Optional[E.Expr]:
        if not parts:
            return None
        reww = [_rewrite_phys(p, side.ren) for p in parts]
        return reww[0] if len(reww) == 1 else E.And(tuple(reww))

    # HAVING in terms of output columns: every AggCall must match a
    # projected aggregate (the epilogue evaluates over grouped output)
    having = stmt.having
    if having is not None:
        class _NoMatch(Exception):
            pass

        def rw_having(n):
            if isinstance(n, E.AggCall):
                for s in aggs:
                    if s.fn == n.fn and s.arg == n.arg \
                            and not n.distinct and not n.approx:
                        return E.Column(s.out)
                raise _NoMatch()
            return n
        try:
            having = E.transform(having, rw_having)
        except _NoMatch:
            return None

    return JoinPlan(
        probe=a, build=b,
        keys=[(a.ren[l], b.ren[r]) for l, r in keys_ab],
        probe_filter=mk_filter(a, filt["a"]),
        build_filter=mk_filter(b, filt["b"]),
        residual=(residual[0] if len(residual) == 1
                  else E.And(tuple(residual))) if residual else None,
        colside=colside,
        group_by=group_by, aggs=aggs,
        having=having, order_by=stmt.order_by, limit=stmt.limit,
        items=stmt.items)


# =============================================================================
# execution + shared epilogue
# =============================================================================

def _epilogue(plan: JoinPlan, data: Dict[str, np.ndarray]) -> pd.DataFrame:
    """Grouped data (query/output names) -> final frame: projection in
    item order, HAVING, ORDER BY, LIMIT — shared by both tiers so their
    answers can only differ if the grouped data differs."""
    from spark_druid_olap_tpu.utils import host_eval
    env = dict(data)
    cols: List[Tuple[str, str]] = []        # (title, env key)
    agg_i = 0
    for i, item in enumerate(plan.items):
        if isinstance(item.expr, E.Column):
            title = item.alias or item.expr.name
            cols.append((title, item.expr.name))
        else:
            out = plan.aggs[agg_i].out
            agg_i += 1
            title = item.alias or out
            cols.append((title, out))
    for title, key in cols:
        env.setdefault(title, env[key])
    if plan.having is not None:
        if any(c not in env for c in E.columns_in(plan.having)):
            raise JoinUnsupported("HAVING references a non-output column")
        mask = host_eval.eval_pred3(plan.having, env)
        env = {k: np.asarray(v)[mask] for k, v in env.items()}
    df = pd.DataFrame({title: env[key] for title, key in cols})
    if plan.order_by:
        by, asc = [], []
        for oi in plan.order_by:
            if not isinstance(oi.expr, E.Column) \
                    or oi.expr.name not in env:
                raise JoinUnsupported(
                    "ORDER BY references a non-output column")
            name = oi.expr.name
            title = next((t for t, k in cols
                          if t == name or k == name), None)
            if title is None:
                raise JoinUnsupported(
                    "ORDER BY references a non-projected column")
            by.append(title)
            asc.append(bool(oi.ascending))
        df = df.sort_values(by, ascending=asc, kind="mergesort") \
            .reset_index(drop=True)
    if plan.limit is not None:
        df = df.head(int(plan.limit)).reset_index(drop=True)
    return df


_RECOGNIZE = object()   # default: recognize internally via try_plan


def try_execute(ctx, stmt: A.SelectStmt,
                plan=_RECOGNIZE) -> Optional[pd.DataFrame]:
    """Session hook: None = not recognized (host tier takes over);
    raises :class:`JoinUnsupported` when recognized but undeliverable
    (same outcome for the caller). On success the join stats land in
    ``ctx.engine.last_stats['join']``. The session's planning-cascade
    memo passes its cached :func:`try_plan` outcome (a JoinPlan, or
    None for a memoized decline) via ``plan``; recognition is the only
    memoizable part — the kill switch, cost arbitration and execution
    below stay live on every call."""
    conf = ctx.config
    # a previous statement's join stats must never survive into this
    # one's snapshot (engine.execute clears last_stats per statement;
    # the host/composite tiers do not run it)
    ctx.engine.last_stats.pop("join", None)
    if not bool(conf.get(JOIN_ENABLED)):
        return None
    if plan is _RECOGNIZE:
        plan = try_plan(ctx, stmt)
    if plan is None:
        return None
    # same per-statement contract as engine.execute (executor clears
    # last_stats at dispatch): the join tiers bypass engine.execute, so
    # clear here or the previous statement's stats leak into this one's
    ctx.engine.last_stats.clear()
    from spark_druid_olap_tpu.join import broadcast as JB
    from spark_druid_olap_tpu.join import partitioned as JPT
    from spark_druid_olap_tpu.parallel import cost as C

    store = ctx.store
    probe_ds = store.get(plan.probe.ds)
    build_ds = store.get(plan.build.ds)
    cl = ctx.cluster
    n_nodes = len(cl.nodes) if cl is not None else 0
    est = C.join_estimate(
        conf, probe_ds=probe_ds, build_ds=build_ds,
        probe_cols=sorted(plan.probe_cols()),
        build_cols=sorted(plan.build_cols()),
        cluster_nodes=n_nodes)
    if est.mode == "host":
        raise JoinUnsupported(est.reason)
    # orient the smaller side as build (the estimate is orientation-
    # symmetric in bytes; swap before executing, not inside the tiers)
    if est.mode == "broadcast" and est.probe_bytes < est.build_bytes:
        sw = plan.swapped()
        sw_est = C.join_estimate(
            conf, probe_ds=build_ds, build_ds=probe_ds,
            probe_cols=sorted(sw.probe_cols()),
            build_cols=sorted(sw.build_cols()),
            cluster_nodes=n_nodes)
        if sw_est.mode == "broadcast":
            plan, est = sw, sw_est

    max_matches = int(conf.get(JOIN_MAX_MATCHES))
    js: Optional[dict] = None
    data = None
    if est.mode == "partitioned":
        spec = plan_to_dict(plan, max_matches=1 << 20)
        try:
            data, js = JPT.execute_partitioned(ctx, plan, spec)
        except JoinUnsupported:
            # the broker holds the full store: local broadcast is the
            # fallback (mirrors the scatter path's local_fallbacks)
            data = None
    if data is None:
        try:
            data, js = JB.execute_broadcast(ctx, plan)
        except JoinUnsupported:
            sw = plan.swapped()
            data, js = JB.execute_broadcast(ctx, sw)
            plan = sw
    js["estimate"] = {
        "mode": est.mode, "reason": est.reason,
        "build_bytes": est.build_bytes, "probe_bytes": est.probe_bytes,
        "shuffle_bytes": est.shuffle_bytes,
    }
    js.setdefault("shuffle_bytes", 0)
    with PH.phase("epilogue"):
        df = _epilogue(plan, data)
    ctx.engine.last_stats["join"] = js
    return df
