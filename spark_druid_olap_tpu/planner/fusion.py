"""Cross-lane fusion planning: predicate CSE for shared-scan programs.

The shared-scan tier (parallel/sharedscan.py) already coalesces a
dashboard storm into ONE bind and ONE dispatch per segment wave, but the
fused program it traces is a *concatenation* of per-lane filter/agg
stages: every lane re-lowers its own predicate tree and re-streams the
shared columns. Flare (arxiv 1703.08219) and SystemML's fusion-plan
optimizer (arxiv 1801.00829) put the next multiple in a fusion planner
that partitions the lanes' DAGs into fused operator sets sharing
sub-computations — dashboard lanes share predicates (a global time
window, a tenant selector), so identical sub-filters must evaluate once
for every lane.

This module is that planner, split into two halves that must agree:

- ``plan_lanes`` / ``analyze_query`` — HOST-SIDE, pure analysis over the
  ``FilterSpec`` trees. Canonicalizes every sub-predicate (AND/OR operand
  order folded, so commuted trees unify), counts total vs. distinct
  evaluations, and produces the deterministic counters
  (``shared_predicates``, ``predicate_evals_saved``,
  ``column_streams_saved``) plus a compile-cache token. Runs on EVERY
  execution — warm program-cache runs included — so the counters are
  CI-guardable without a chip.
- ``CSECache`` — TRACE-TIME, a memoizing wrapper over
  ``ops.filters.lower_filter`` bound to one ``ScanContext``. Logical
  nodes recurse *through* the cache (plain ``lower_filter`` recurses
  past it), so a shared sub-predicate lowers once and every consumer
  reuses the same mask value. Masks combine with ``&``/``|``/``~`` only,
  which are exact on bool lanes, so CSE'd programs are bit-identical to
  unfused ones.

Fallback contract: planning is advisory. Any planning error makes the
caller lower the unfused way (routing tiers never change), and the
``CSECache`` replicates ``lower_filter``'s semantics node for node —
including the OR-of-all-true -> all-true (None) short circuit.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

from spark_druid_olap_tpu.ir import spec as S
from spark_druid_olap_tpu.ops import filters as F

# canonical key of the "no filter" / all-true node; never cached (lowering
# None is free) but it must not collide with a real node's key
_TRUE_KEY = "\x00T"


def canon_key(f: Optional[S.FilterSpec]) -> str:
    """Deterministic canonical form of a filter subtree. AND/OR operand
    keys sort, so ``a AND b`` and ``b AND a`` share one key (bool masks
    combine exactly, so reusing either lowering is bit-identical). NOT
    and leaves keep structural ``repr`` — every FilterSpec is a frozen
    dataclass of plain values (large IN-sets repr as their digest via
    ``FrozenIntSet``), so ``repr`` is value-based and O(1)-ish."""
    if f is None:
        return _TRUE_KEY
    if isinstance(f, S.LogicalFilter):
        ks = [canon_key(x) for x in f.fields]
        if f.op in ("and", "or"):
            ks.sort()
        return "(" + f.op + " " + " ".join(ks) + ")"
    return repr(f)


def interval_key(intervals) -> Optional[str]:
    """Pseudo-node key for a lane's residual time-interval mask (the
    intervals tuple lowers as one unit in ``ops.filters.interval_mask``)."""
    if not intervals:
        return None
    return "(iv " + repr(tuple(intervals)) + ")"


def _walk(f: Optional[S.FilterSpec], seen: set,
          totals: List[int]) -> None:
    """Simulate one memoized lowering of ``f``: every node requests once
    per occurrence (totals[0]), but a cached subtree stops the descent —
    exactly what ``CSECache.lower`` does at trace time."""
    if f is None:
        return
    totals[0] += 1
    k = canon_key(f)
    if k in seen:
        return
    seen.add(k)
    if isinstance(f, S.LogicalFilter):
        for x in f.fields:
            _walk(x, seen, totals)


def _lane_keys(f: Optional[S.FilterSpec], out: set) -> None:
    """All distinct sub-predicate keys of one lane's tree."""
    if f is None:
        return
    out.add(canon_key(f))
    if isinstance(f, S.LogicalFilter):
        for x in f.fields:
            _lane_keys(x, out)


# one fused lane's predicate surface: (root filter, intervals tuple,
# per-aggregation filters in declaration order)
LaneExprs = Tuple[Optional[S.FilterSpec], Optional[tuple],
                  Tuple[Optional[S.FilterSpec], ...]]


@dataclasses.dataclass(frozen=True)
class FusionPlan:
    """Host-side CSE analysis of a fused group. All counters are exact
    functions of the (sorted) lane set, never of arrival order or
    program-cache warmth."""
    n_lanes: int
    # predicate lowering REQUESTS under memoization (a request that hits
    # the cache stops the descent, so a duplicated deep subtree counts
    # once — the counter is conservative) vs. the distinct sub-predicates
    # the fused program actually evaluates
    n_nodes: int
    n_distinct: int
    shared_predicates: int         # distinct sub-predicates used by >= 2 lanes
    predicate_evals_saved: int     # n_nodes - n_distinct (= CSE cache hits)
    column_streams_saved: int      # sum(per-lane columns) - union columns
    # representative nodes for the cross-lane shared sub-predicates, in
    # canonical-key order: the builder lowers these FIRST so shared masks
    # materialize once before any lane's residual combine
    shared_nodes: Tuple[S.FilterSpec, ...] = ()
    shared_intervals: Tuple[tuple, ...] = ()

    def token(self) -> tuple:
        """Folded into the fused-program compile signature. The plan is a
        pure function of the sorted lane set, so identical groups (any
        arrival order) produce identical tokens."""
        return ("fusion", self.n_lanes, self.n_nodes, self.n_distinct,
                self.shared_predicates, self.column_streams_saved)

    def counters(self) -> dict:
        return {"shared_predicates": self.shared_predicates,
                "predicate_evals_saved": self.predicate_evals_saved,
                "predicate_evals_total": self.n_nodes,
                "column_streams_saved": self.column_streams_saved}


def plan_lanes(lanes: Sequence[LaneExprs],
               per_lane_cols: Sequence[int],
               union_cols: int,
               max_nodes: int = 0) -> FusionPlan:
    """Analyze a fused group's lanes (already deduped + sorted by plan
    signature by the caller). Raises on anything unexpected — the caller
    treats any exception as "plan unfused"."""
    seen: set = set()
    totals = [0]
    per_lane_sets: List[set] = []
    node_budget = 0
    for (filt, intervals, agg_filters) in lanes:
        lane_set: set = set()
        _lane_keys(filt, lane_set)
        for af in agg_filters:
            _lane_keys(af, lane_set)
        ik = interval_key(intervals)
        if ik is not None:
            lane_set.add(ik)
        node_budget += len(lane_set)
        if max_nodes and node_budget > max_nodes:
            raise ValueError(
                f"fusion plan over sdot.sharedscan.fusion.max.nodes "
                f"({node_budget} > {max_nodes})")
        per_lane_sets.append(lane_set)
        # memoized-traversal simulation, in the builder's lowering order
        _walk(filt, seen, totals)
        if ik is not None:
            totals[0] += 1
            seen.add(ik)   # interval tuples cache whole, never descend
        for af in agg_filters:
            _walk(af, seen, totals)
    n_distinct = len(seen)
    n_nodes = totals[0]

    counts: Dict[str, int] = {}
    for lane_set in per_lane_sets:
        for k in lane_set:
            counts[k] = counts.get(k, 0) + 1
    shared = {k for k, c in counts.items() if c >= 2}

    # representative spec node per shared key (filters only; shared
    # interval tuples are tracked separately so the builder can prelower
    # them through the interval cache)
    reps: Dict[str, S.FilterSpec] = {}
    iv_reps: Dict[str, tuple] = {}

    def _collect(f: Optional[S.FilterSpec]) -> None:
        if f is None:
            return
        k = canon_key(f)
        if k in shared and k not in reps:
            reps[k] = f
        if isinstance(f, S.LogicalFilter):
            for x in f.fields:
                _collect(x)

    for (filt, intervals, agg_filters) in lanes:
        _collect(filt)
        for af in agg_filters:
            _collect(af)
        ik = interval_key(intervals)
        if ik is not None and ik in shared and ik not in iv_reps:
            iv_reps[ik] = tuple(intervals)

    saved = n_nodes - n_distinct
    streams_saved = max(0, int(sum(per_lane_cols)) - int(union_cols))
    return FusionPlan(
        n_lanes=len(lanes), n_nodes=n_nodes, n_distinct=n_distinct,
        shared_predicates=len(shared), predicate_evals_saved=saved,
        column_streams_saved=streams_saved,
        shared_nodes=tuple(reps[k] for k in sorted(reps)),
        shared_intervals=tuple(iv_reps[k] for k in sorted(iv_reps)))


def analyze_query(filter_spec: Optional[S.FilterSpec], intervals,
                  agg_filters: Sequence[Optional[S.FilterSpec]]
                  ) -> Tuple[int, int]:
    """(total_evals, distinct_evals) for ONE query's predicate surface —
    the solo-path CSE accounting (a single query's tree repeats
    sub-predicates too: OR-of-bounds over one column, one filtered
    aggregation per month over a shared selector, ...)."""
    seen: set = set()
    totals = [0]
    _walk(filter_spec, seen, totals)
    ik = interval_key(intervals)
    if ik is not None:
        totals[0] += 1
        seen.add(ik)
    for af in agg_filters:
        _walk(af, seen, totals)
    return totals[0], len(seen)


def plan_device_waves(seg_idx, spw: int, n_dev: int,
                      seg_rows=None) -> list:
    """Partition a segment selection into dispatch waves of ``spw``
    slots and, within each wave, order the segments so the mesh's
    contiguous per-device blocks (``spw / n_dev`` slots each — the
    layout ``NamedSharding(P(SEGMENT_AXIS))`` splits a ``[S, R]`` bind
    into) carry balanced ROW loads. The wave kernel's runtime is its
    slowest device; greedy LPT over per-segment valid-row counts keeps
    the straggler gap small when segment fill is skewed (a tail segment
    is routinely near-empty). ``seg_rows`` maps segment id -> valid
    rows; None degrades to slot-count balancing (original order).

    Correctness-neutral by construction: each wave holds the same
    segment SET, ``row_valid`` travels in the bound arrays, and the
    merge algebra is grouping-invariant (psum over f64-exact pairs /
    pmin / pmax) — only which chip scans which segment changes. The
    tail wave (fewer than ``spw`` real segments) binds pad slots at the
    end, so its device blocks are approximate; padding rows are zero
    work either way.

    Returns the list of per-wave segment-id arrays (``np.ndarray``,
    last one possibly short — the bind layer pads to ``spw``)."""
    import numpy as _np
    seg_idx = _np.asarray(seg_idx)
    waves = [seg_idx[i: i + spw] for i in range(0, len(seg_idx), spw)]
    if n_dev <= 1 or seg_rows is None:
        return waves
    per_dev = max(1, spw // max(1, n_dev))
    out = []
    for w in waves:
        rows = _np.array([int(seg_rows.get(int(s), 0)) for s in w],
                         dtype=_np.int64)
        order = _np.argsort(-rows, kind="stable")
        buckets: list = [[] for _ in range(n_dev)]
        loads = _np.zeros(n_dev, dtype=_np.int64)
        for j in order:
            free = [d for d in range(n_dev) if len(buckets[d]) < per_dev]
            if not free:
                free = list(range(n_dev))
            d = min(free, key=lambda k: (int(loads[k]), k))
            buckets[d].append(int(w[j]))
            loads[d] += int(rows[j])
        out.append(_np.array([s for b in buckets for s in b],
                             dtype=w.dtype))
    return out


def plan_wave_tiles(itemsizes: Sequence[int],
                    int_sum_maxabs: Sequence[float],
                    scratch_rows: int, budget_bytes: int,
                    min_rows: int = 128, max_rows: int = 2048) -> int:
    """Tile-shape planning for the wave mega-kernel (ops/pallas_wave.py):
    the largest power-of-two sublane block depth such that (a) every
    union-column tile double-buffered PLUS the resident [scratch_rows,
    128] f32 accumulator block fits the VMEM budget
    (parallel/cost.py:pallas_tile_budget_bytes), and (b) every integer
    sum's per-lane block partial stays exactly representable in f32
    (``maxabs * block_rows < 2^24`` — the same invariant as
    ops/pallas_groupby.py:choose_block_rows, which this generalizes to
    a multi-lane scratch layout). Deterministic from plan metadata alone
    so the compile signature and the kernel dispatch always agree.

    ``itemsizes`` are the POST-prep widths (``_prep_dtype``): on an
    encoded store the cold bytes may be bit-packed, but chunks decode
    at fault time, so the VMEM tiles budgeted here are always logical-
    width — encoding never perturbs the tile plan or the signature."""
    lanes = 128                    # TPU VPU lane width (minor axis)
    per_row = lanes * max(1, int(sum(itemsizes)))
    scratch = int(scratch_rows) * lanes * 4
    b = max_rows
    while b > min_rows and b * per_row * 2 + scratch > budget_bytes:
        b //= 2
    for maxabs in int_sum_maxabs:
        while b > min_rows and float(maxabs) * b >= 2 ** 24:
            b //= 2
    return b


class CSECache:
    """Memoizing filter lowering bound to ONE ScanContext. Logical nodes
    recurse through the cache (plain ``lower_filter`` would recurse past
    it), leaves delegate to ``ops.filters``. A cached ``None`` (all-true)
    is a real entry — presence is tested with ``in``, not truthiness.

    MUST be rebuilt whenever the context changes shape (the late-
    materialization path swaps ``ScanContext`` for ``CompactScanContext``
    mid-core: masks from the full-width context cannot combine with
    compacted lanes)."""

    __slots__ = ("ctx", "_masks", "hits", "misses")

    def __init__(self, ctx):
        self.ctx = ctx
        self._masks: Dict[str, object] = {}
        self.hits = 0
        self.misses = 0

    def lower(self, f: Optional[S.FilterSpec]):
        if f is None:
            return None
        k = canon_key(f)
        if k in self._masks:
            self.hits += 1
            return self._masks[k]
        self.misses += 1
        if isinstance(f, S.LogicalFilter):
            m = self._logical(f)
        else:
            m = F.lower_filter(f, self.ctx)
        self._masks[k] = m
        return m

    def _logical(self, f: S.LogicalFilter):
        # mirrors ops.filters._logical exactly, with child lowering
        # routed back through the cache
        if f.op == "not":
            inner = self.lower(f.fields[0])
            return self.ctx.row_valid() if inner is None else ~inner
        masks = [self.lower(x) for x in f.fields]
        if f.op == "or":
            if not masks or any(m is None for m in masks):
                return None
        else:
            masks = [m for m in masks if m is not None]
            if not masks:
                return None
        out = masks[0]
        for m in masks[1:]:
            out = (out & m) if f.op == "and" else (out | m)
        return out

    def interval(self, intervals):
        """Memoized ``ops.filters.interval_mask`` (lanes sharing a time
        window share the residual mask)."""
        k = interval_key(intervals)
        if k is None:
            return None
        if k in self._masks:
            self.hits += 1
            return self._masks[k]
        self.misses += 1
        m = F.interval_mask(intervals, self.ctx)
        self._masks[k] = m
        return m

    def prelower(self, plan: FusionPlan) -> None:
        """Materialize the cross-lane shared masks FIRST (canonical-key
        order): each union column streams through VMEM once while the
        shared masks compute, then every lane's residual combine is
        cache hits plus lane-private leaves."""
        for node in plan.shared_nodes:
            self.lower(node)
        for iv in plan.shared_intervals:
            self.interval(iv)
