"""Bounded in-memory query history.

≈ ``DruidQueryHistory`` (reference ``DruidQueryHistory.scala:39-76``: bounded
queue of 500 executed Druid queries with timings, surfaced in a web-UI tab and
SQL-queryable metadata views)."""

from __future__ import annotations

import collections
import threading
import time
from typing import List, Optional


class QueryExecutionRecord:
    __slots__ = ("started_at", "query_type", "datasource", "sql", "stats")

    def __init__(self, query_type, datasource, stats, sql=None):
        self.started_at = time.time()
        self.query_type = query_type
        self.datasource = datasource
        self.stats = dict(stats)
        self.sql = sql

    def to_dict(self):
        return {"startedAt": self.started_at, "queryType": self.query_type,
                "datasource": self.datasource, "sql": self.sql,
                **self.stats}


class InflightRegistry:
    """Live queries by admission state (``queued`` → ``running``),
    surfaced by ``sys_queries`` next to the completed-history rows so
    in-flight load is observable while it is happening (≈ Druid's
    broker `sys.queries` / running-query endpoint)."""

    __slots__ = ("_lock", "_rows", "_next")

    def __init__(self):
        self._lock = threading.Lock()
        self._rows = {}
        self._next = 0

    def begin(self, query_id, datasource, query_type) -> int:
        with self._lock:
            tok = self._next
            self._next += 1
            self._rows[tok] = {
                "query_id": query_id, "datasource": datasource,
                "query_type": query_type, "state": "queued",
                "lane": None, "tenant": None,
                "started_at": time.time(), "t0": time.perf_counter(),
                "queued_ms": 0.0}
            return tok

    def running(self, tok: int, lane=None, tenant=None,
                queued_ms: float = 0.0) -> None:
        with self._lock:
            row = self._rows.get(tok)
            if row is not None:
                row["state"] = "running"
                row["lane"] = lane
                row["tenant"] = tenant
                row["queued_ms"] = queued_ms

    def annotate(self, tok, **fields) -> None:
        """Attach extra columns to a live row (e.g. the shared-scan
        coalesced-group id); snapshot() copies rows, so annotations flow
        into ``sys_queries`` without schema changes here."""
        if tok is None:
            return
        with self._lock:
            row = self._rows.get(tok)
            if row is not None:
                row.update(fields)

    def done(self, tok: int) -> None:
        with self._lock:
            self._rows.pop(tok, None)

    def snapshot(self) -> List[dict]:
        now = time.perf_counter()
        with self._lock:
            out = []
            for row in self._rows.values():
                d = dict(row)
                d["wall_ms"] = (now - d.pop("t0")) * 1000.0
                if d["state"] == "queued":
                    # still accruing; report the live wait
                    d["queued_ms"] = d["wall_ms"]
                out.append(d)
            return out


class QueryHistory:
    def __init__(self, max_size: int = 500):
        self._q = collections.deque(maxlen=max_size)
        self._lock = threading.Lock()

    def record(self, query, stats, sql: Optional[str] = None):
        rec = QueryExecutionRecord(type(query).__name__,
                                   getattr(query, "datasource", None),
                                   stats, sql)
        with self._lock:
            self._q.append(rec)
        return rec

    def entries(self) -> List[QueryExecutionRecord]:
        with self._lock:
            return list(self._q)

    def clear(self):
        with self._lock:
            self._q.clear()
