"""Bounded in-memory query history.

≈ ``DruidQueryHistory`` (reference ``DruidQueryHistory.scala:39-76``: bounded
queue of 500 executed Druid queries with timings, surfaced in a web-UI tab and
SQL-queryable metadata views)."""

from __future__ import annotations

import collections
import threading
import time
from typing import List, Optional


class QueryExecutionRecord:
    __slots__ = ("started_at", "query_type", "datasource", "sql", "stats")

    def __init__(self, query_type, datasource, stats, sql=None):
        self.started_at = time.time()
        self.query_type = query_type
        self.datasource = datasource
        self.stats = dict(stats)
        self.sql = sql

    def to_dict(self):
        return {"startedAt": self.started_at, "queryType": self.query_type,
                "datasource": self.datasource, "sql": self.sql,
                **self.stats}


class InflightRegistry:
    """Live queries by admission state (``queued`` → ``running``),
    surfaced by ``sys_queries`` next to the completed-history rows so
    in-flight load is observable while it is happening (≈ Druid's
    broker `sys.queries` / running-query endpoint)."""

    __slots__ = ("_lock", "_rows", "_next")

    def __init__(self):
        self._lock = threading.Lock()
        self._rows = {}
        self._next = 0

    def begin(self, query_id, datasource, query_type) -> int:
        with self._lock:
            tok = self._next
            self._next += 1
            self._rows[tok] = {
                "query_id": query_id, "datasource": datasource,
                "query_type": query_type, "state": "queued",
                "lane": None, "tenant": None,
                "started_at": time.time(), "t0": time.perf_counter(),
                "queued_ms": 0.0}
            return tok

    def running(self, tok: int, lane=None, tenant=None,
                queued_ms: float = 0.0) -> None:
        with self._lock:
            row = self._rows.get(tok)
            if row is not None:
                row["state"] = "running"
                row["lane"] = lane
                row["tenant"] = tenant
                row["queued_ms"] = queued_ms

    def annotate(self, tok, **fields) -> None:
        """Attach extra columns to a live row (e.g. the shared-scan
        coalesced-group id); snapshot() copies rows, so annotations flow
        into ``sys_queries`` without schema changes here."""
        if tok is None:
            return
        with self._lock:
            row = self._rows.get(tok)
            if row is not None:
                row.update(fields)

    def done(self, tok: int) -> None:
        with self._lock:
            self._rows.pop(tok, None)

    def snapshot(self) -> List[dict]:
        now = time.perf_counter()
        with self._lock:
            out = []
            for row in self._rows.values():
                d = dict(row)
                d["wall_ms"] = (now - d.pop("t0")) * 1000.0
                if d["state"] == "queued":
                    # still accruing; report the live wait
                    d["queued_ms"] = d["wall_ms"]
                out.append(d)
            return out


def _filter_columns(f, out: set) -> None:
    """Column names a filter tree touches (duck-typed over the spec
    classes: logical nodes carry ``fields``, leaf filters ``dimension``,
    spatial filters ``axes``)."""
    if f is None:
        return
    for sub in getattr(f, "fields", ()) or ():
        _filter_columns(sub, out)
    d = getattr(f, "dimension", None)
    if isinstance(d, str):
        out.add(d)
    for ax in getattr(f, "axes", ()) or ():
        if isinstance(ax, str):
            out.add(ax)


def referenced_columns(query) -> set:
    """Column names one query spec reads (dimensions, aggregation
    inputs, filter columns) — the popularity signal."""
    cols: set = set()
    try:
        from spark_druid_olap_tpu.ir import spec as S
        for d in S.query_dimensions(query):
            name = getattr(d, "dimension", None)
            if isinstance(name, str):
                cols.add(name)
        for a in S.query_aggregations(query):
            f = getattr(a, "field", None)
            if isinstance(f, str):
                cols.add(f)
            _filter_columns(getattr(a, "filter", None), cols)
        _filter_columns(getattr(query, "filter", None), cols)
    except Exception:  # noqa: BLE001 — scoring must never break record()
        pass
    return cols


# distinct (datasource, column) scores retained; above this the lowest
# half is dropped (ad-hoc fuzzers emit unbounded distinct columns)
_COL_SCORE_BOUND = 4096


class QueryHistory:
    def __init__(self, max_size: int = 500):
        self._q = collections.deque(maxlen=max_size)
        self._lock = threading.Lock()
        # (datasource, column) -> hit count. The same access signal that
        # orders recovery warmup (persist/manager.py) also ranks the
        # tiered hot set's eviction order (tier/store.py): a column the
        # dashboard mix keeps touching survives budget pressure.
        self._col_scores = {}

    def record(self, query, stats, sql: Optional[str] = None):
        rec = QueryExecutionRecord(type(query).__name__,
                                   getattr(query, "datasource", None),
                                   stats, sql)
        ds = rec.datasource
        cols = referenced_columns(query) if ds is not None else ()
        with self._lock:
            self._q.append(rec)
            for c in cols:
                k = (ds, c)
                self._col_scores[k] = self._col_scores.get(k, 0) + 1
            if len(self._col_scores) > _COL_SCORE_BOUND:
                keep = sorted(self._col_scores.items(),
                              key=lambda kv: -kv[1])[:_COL_SCORE_BOUND // 2]
                self._col_scores = dict(keep)
        return rec

    def column_score(self, datasource: str, column: str) -> float:
        """Popularity of one column (0.0 = never seen)."""
        with self._lock:
            return float(self._col_scores.get((datasource, column), 0))

    def entries(self) -> List[QueryExecutionRecord]:
        with self._lock:
            return list(self._q)

    def clear(self):
        with self._lock:
            self._q.clear()
            self._col_scores.clear()
