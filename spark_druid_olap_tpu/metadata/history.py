"""Bounded in-memory query history.

≈ ``DruidQueryHistory`` (reference ``DruidQueryHistory.scala:39-76``: bounded
queue of 500 executed Druid queries with timings, surfaced in a web-UI tab and
SQL-queryable metadata views)."""

from __future__ import annotations

import collections
import threading
import time
from typing import List, Optional


class QueryExecutionRecord:
    __slots__ = ("started_at", "query_type", "datasource", "sql", "stats")

    def __init__(self, query_type, datasource, stats, sql=None):
        self.started_at = time.time()
        self.query_type = query_type
        self.datasource = datasource
        self.stats = dict(stats)
        self.sql = sql

    def to_dict(self):
        return {"startedAt": self.started_at, "queryType": self.query_type,
                "datasource": self.datasource, "sql": self.sql,
                **self.stats}


class QueryHistory:
    def __init__(self, max_size: int = 500):
        self._q = collections.deque(maxlen=max_size)
        self._lock = threading.Lock()

    def record(self, query, stats, sql: Optional[str] = None):
        rec = QueryExecutionRecord(type(query).__name__,
                                   getattr(query, "datasource", None),
                                   stats, sql)
        with self._lock:
            self._q.append(rec)
        return rec

    def entries(self) -> List[QueryExecutionRecord]:
        with self._lock:
            return list(self._q)

    def clear(self):
        with self._lock:
            self._q.clear()
