"""Star-schema metadata and join validation.

≈ ``StarSchemaInfo.scala``: the user declares the star-join graph — fact
table plus n-1 / 1-1 relations to dimension tables — and the planner
validates that a query's join tree is a connected subgraph of it before
collapsing the join onto the flat (denormalized) datasource
(``StarSchema.isStarJoin:215-275``). Column names must be globally unique
across the schema (reference doc :127-165) — that constraint is what lets the
collapse be a pure name-mapping (the flat index carries every column under
its original name).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Set, Tuple


@dataclasses.dataclass(frozen=True)
class StarRelation:
    """An edge of the star graph: ``left`` joins ``right`` (its dimension)
    on pairwise-equal columns. ≈ ``StarRelationInfo``."""
    left_table: str
    right_table: str
    join_columns: Tuple[Tuple[str, str], ...]   # (left_col, right_col)
    relation_type: str = "n-1"                  # 'n-1' | '1-1'


class StarSchemaError(Exception):
    pass


class StarSchema:
    def __init__(self, fact_table: str, flat_datasource: str,
                 relations: Sequence[StarRelation]):
        self.fact_table = fact_table
        self.flat_datasource = flat_datasource
        self.relations = list(relations)
        self._validate()

    def _validate(self):
        # single parent per dim table, graph connected from the fact
        parents: Dict[str, str] = {}
        for r in self.relations:
            if r.right_table in parents:
                raise StarSchemaError(
                    f"table {r.right_table!r} joined from multiple parents "
                    f"({parents[r.right_table]!r} and {r.left_table!r}); "
                    "the star graph must give each table a unique join path")
            parents[r.right_table] = r.left_table
        reachable = {self.fact_table}
        pending = list(self.relations)
        progress = True
        while pending and progress:
            progress = False
            for r in list(pending):
                if r.left_table in reachable:
                    reachable.add(r.right_table)
                    pending.remove(r)
                    progress = True
        if pending:
            bad = [r.right_table for r in pending]
            raise StarSchemaError(
                f"tables not reachable from fact {self.fact_table!r}: {bad}")

    # -- persistence (persist/manager.py catalog.json) -------------------------
    def to_dict(self) -> dict:
        return {
            "factTable": self.fact_table,
            "flatDatasource": self.flat_datasource,
            "relations": [
                {"leftTable": r.left_table, "rightTable": r.right_table,
                 "joinColumns": [list(p) for p in r.join_columns],
                 "relationType": r.relation_type}
                for r in self.relations],
        }

    @staticmethod
    def from_dict(d: dict) -> "StarSchema":
        rels = [StarRelation(
            left_table=r["leftTable"], right_table=r["rightTable"],
            join_columns=tuple((p[0], p[1]) for p in r["joinColumns"]),
            relation_type=r.get("relationType", "n-1"))
            for r in d.get("relations", ())]
        return StarSchema(d["factTable"], d["flatDatasource"], rels)

    def tables(self) -> Set[str]:
        out = {self.fact_table}
        for r in self.relations:
            out.add(r.left_table)
            out.add(r.right_table)
        return out

    def _pair_index(self) -> Dict[frozenset, StarRelation]:
        idx = {}
        for r in self.relations:
            for lc, rc in r.join_columns:
                idx[frozenset((lc, rc))] = r
        return idx

    def is_star_join(self, tables: Set[str],
                     eq_pairs: Sequence[Tuple[str, str]]) -> bool:
        """Validate a query join: every equi-pair is a declared star edge and
        the joined tables form a connected subgraph containing each pair's
        endpoints (≈ ``isStarJoin``). Requires every edge between joined
        tables to be fully specified."""
        if not tables <= self.tables():
            return False
        idx = self._pair_index()
        used_rels = set()
        for a, b in eq_pairs:
            r = idx.get(frozenset((a, b)))
            if r is None:
                return False
            if not (r.left_table in tables and r.right_table in tables):
                return False
            used_rels.add(id(r))
        # each relation whose two tables are both in the query must have ALL
        # its join columns present
        needed = {}
        for a, b in eq_pairs:
            r = idx[frozenset((a, b))]
            needed.setdefault(id(r), set()).add(frozenset((a, b)))
        for r in self.relations:
            if r.left_table in tables and r.right_table in tables:
                want = {frozenset(p) for p in r.join_columns}
                if needed.get(id(r), set()) != want:
                    return False
        # connectivity over the used edges
        adj: Dict[str, Set[str]] = {t: set() for t in tables}
        for r in self.relations:
            if id(r) in needed and r.left_table in tables \
                    and r.right_table in tables:
                adj[r.left_table].add(r.right_table)
                adj[r.right_table].add(r.left_table)
        if not tables:
            return False
        start = next(iter(tables))
        seen = {start}
        stack = [start]
        while stack:
            t = stack.pop()
            for u in adj[t]:
                if u not in seen:
                    seen.add(u)
                    stack.append(u)
        return seen == tables
