"""Catalog: datasource registry views + star-schema bindings.

≈ the reference metadata layer: ``DruidMetadataCache`` (datasource schemas),
``DruidRelationInfo`` (table ↔ datasource binding), ``DruidMetadataViews``
(SQL-queryable virtual tables). Star-schema specifics live in
``metadata/star.py``.
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np
import pandas as pd

from spark_druid_olap_tpu.segment.store import SegmentStore


class Catalog:
    def __init__(self, store: SegmentStore):
        self.store = store
        self.star_schemas: Dict[str, object] = {}   # fact table -> StarSchema
        self._table_to_star: Dict[str, object] = {}

    def register_star_schema(self, star) -> None:
        self.star_schemas[star.fact_table] = star
        for t in star.tables():
            self._table_to_star[t] = star

    def star_schema_of(self, table: str):
        return self._table_to_star.get(table)

    # -- metadata views (≈ DruidMetadataViews.metadataDFs) --------------------
    def datasources_view(self) -> pd.DataFrame:
        rows = []
        for name in self.store.names():
            ds = self.store.get(name)
            lo, hi = ds.interval()
            rows.append({"name": name, "numRows": ds.num_rows,
                         "numSegments": ds.num_segments,
                         "intervalStart": np.datetime64(int(lo), "ms"),
                         "intervalEnd": np.datetime64(int(hi), "ms"),
                         "timeColumn": ds.time_column})
        return pd.DataFrame(rows)

    def segments_view(self) -> pd.DataFrame:
        rows = []
        for name in self.store.names():
            ds = self.store.get(name)
            for s in ds.segments:
                rows.append({"datasource": name, "segment": s.id,
                             "rows": s.num_rows,
                             "start": np.datetime64(s.min_millis, "ms"),
                             "end": np.datetime64(s.max_millis, "ms")})
        return pd.DataFrame(rows)

    def columns_view(self) -> pd.DataFrame:
        rows = []
        for name in self.store.names():
            md = self.store.get(name).metadata()
            for col, info in md["columns"].items():
                rows.append({"datasource": name, "column": col, **info})
        return pd.DataFrame(rows)
