"""Catalog: datasource registry views + star-schema bindings.

≈ the reference metadata layer: ``DruidMetadataCache`` (datasource schemas),
``DruidRelationInfo`` (table ↔ datasource binding), ``DruidMetadataViews``
(SQL-queryable virtual tables). Star-schema specifics live in
``metadata/star.py``.
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np
import pandas as pd

from spark_druid_olap_tpu.segment.store import SegmentStore


class Catalog:
    def __init__(self, store: SegmentStore):
        self.store = store
        self.star_schemas: Dict[str, object] = {}   # fact table -> StarSchema
        self._table_to_stars: Dict[str, list] = {}

    def register_star_schema(self, star) -> None:
        prev = self.star_schemas.get(star.fact_table)
        self.star_schemas[star.fact_table] = star
        if prev is not None:
            # drop the superseded star everywhere, including tables the new
            # version no longer declares
            for lst in self._table_to_stars.values():
                if prev in lst:
                    lst.remove(prev)
        for t in star.tables():
            self._table_to_stars.setdefault(t, []).append(star)
        if hasattr(self, "_fd_cache"):
            self._fd_cache.pop(star.fact_table, None)

    def star_schema_of(self, table: str):
        lst = self._table_to_stars.get(table)
        return lst[0] if lst else None

    def star_schemas_of(self, table: str) -> list:
        """All stars a table participates in — shared dimension tables
        (e.g. supplier in both a lineitem star and a partsupp star) make
        this a list; the planner picks the candidate whose fact anchors
        the query's join tree."""
        return list(self._table_to_stars.get(table, ()))

    def fd_graph_for(self, ds_name: str, store=None):
        """FD graph applicable to a datasource (its star schema's, matched by
        flat-datasource or member-table name); None when no star declared."""
        store = store or self.store
        for star in self.star_schemas.values():
            if star.flat_datasource == ds_name or ds_name in star.tables():
                key = star.fact_table
                if not hasattr(self, "_fd_cache"):
                    self._fd_cache = {}
                if key not in self._fd_cache:
                    from spark_druid_olap_tpu.metadata.fd import build_fd_graph
                    self._fd_cache[key] = build_fd_graph(star, store)
                return self._fd_cache[key]
        return None

    # -- metadata views (≈ DruidMetadataViews.metadataDFs) --------------------
    def datasources_view(self) -> pd.DataFrame:
        rows = []
        for name in self.store.names():
            ds = self.store.get(name)
            lo, hi = ds.interval()
            rows.append({"name": name, "numRows": ds.num_rows,
                         "numSegments": ds.num_segments,
                         "intervalStart": np.datetime64(int(lo), "ms"),
                         "intervalEnd": np.datetime64(int(hi), "ms"),
                         "timeColumn": ds.time_column})
        return pd.DataFrame(rows)

    def segments_view(self) -> pd.DataFrame:
        rows = []
        for name in self.store.names():
            ds = self.store.get(name)
            for s in ds.segments:
                rows.append({"datasource": name, "segment": s.id,
                             "rows": s.num_rows,
                             "start": np.datetime64(s.min_millis, "ms"),
                             "end": np.datetime64(s.max_millis, "ms")})
        return pd.DataFrame(rows)

    def columns_view(self) -> pd.DataFrame:
        rows = []
        for name in self.store.names():
            md = self.store.get(name).metadata()
            for col, info in md["columns"].items():
                rows.append({"datasource": name, "column": col, **info})
        return pd.DataFrame(rows)
