"""Functional-dependency graph over star-schema columns.

≈ ``FunctionalDependency.scala``: a 1-1 / n-1 column dependency graph with
transitive closure (reference uses Floyd-Warshall :176-185) used to estimate
GROUP BY cardinality. Here it additionally powers a *rewrite*: a grouping
column functionally determined by another grouping column is demoted from the
fused group key to an ``anyvalue`` aggregation — which is what keeps dense
group keys dense (TPC-H Q3/Q10 group by an order/customer key plus columns
that key determines; without FDs the fused key space multiplies out).

Derivation: for every star relation, the dimension-side join key determines
every column of the dimension table; join-column pairs are equivalences.
"""

from __future__ import annotations

from typing import Dict, Set

from spark_druid_olap_tpu.metadata.star import StarSchema


class FDGraph:
    def __init__(self):
        self._edges: Dict[str, Set[str]] = {}
        # join-key equality edges only — a strictly stronger relation than
        # mutual determination (two keys of one table determine each other
        # but hold different VALUES)
        self._equiv: Dict[str, Set[str]] = {}

    def add(self, a: str, b: str):
        self._edges.setdefault(a, set()).add(b)

    def add_equiv(self, a: str, b: str):
        self.add(a, b)
        self.add(b, a)
        self._equiv.setdefault(a, set()).add(b)
        self._equiv.setdefault(b, set()).add(a)

    def equivalents(self, a: str) -> Set[str]:
        """Columns guaranteed value-equal to ``a`` on the flat datasource:
        the transitive closure of join-key equalities (includes ``a``)."""
        seen = {a}
        stack = [a]
        while stack:
            x = stack.pop()
            for y in self._equiv.get(x, ()):
                if y not in seen:
                    seen.add(y)
                    stack.append(y)
        return seen

    def determines(self, a: str, b: str) -> bool:
        """True if column ``a`` functionally determines ``b``."""
        if a == b:
            return True
        seen = {a}
        stack = [a]
        while stack:
            x = stack.pop()
            for y in self._edges.get(x, ()):
                if y == b:
                    return True
                if y not in seen:
                    seen.add(y)
                    stack.append(y)
        return False


def build_fd_graph(star: StarSchema, store) -> FDGraph:
    g = FDGraph()
    for r in star.relations:
        for lc, rc in r.join_columns:
            g.add_equiv(lc, rc)
        if len(r.join_columns) == 1:
            # single-column key of the dim table determines all its columns
            _, key = r.join_columns[0]
            try:
                cols = store.get(r.right_table).column_names()
            except KeyError:
                continue
            for c in cols:
                if c != key:
                    g.add(key, c)
            if r.relation_type == "1-1":
                lkey = r.join_columns[0][0]
                try:
                    lcols = store.get(r.left_table).column_names()
                except KeyError:
                    continue
                for c in lcols:
                    if c != lkey:
                        g.add(lkey, c)
    return g
