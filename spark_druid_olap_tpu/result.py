"""Query result: ordered named numpy columns.

≈ the rows the reference materializes from Druid result iterators into Spark
``GenericInternalRow``s (``DruidRDD.scala:235-241``) — here the engine output
is already columnar, so the result *stays* columnar.
"""

from __future__ import annotations

from typing import Dict, List

import numpy as np
import pandas as pd


class QueryResult:
    # Set by the broker in partial-results mode when shards were
    # unreachable: {"missing_shards": [...], "coverage_rows": int,
    # "total_rows": int}. None = exact full answer (degraded results
    # never enter the result cache).
    degraded = None

    def __init__(self, columns: List[str], data: Dict[str, np.ndarray]):
        self.columns = list(columns)
        self.data = data
        n = {len(v) for v in data.values()}
        assert len(n) <= 1, f"ragged result: { {k: len(v) for k, v in data.items()} }"

    def __len__(self) -> int:
        if not self.data:
            return 0
        return len(next(iter(self.data.values())))

    def __getitem__(self, name: str) -> np.ndarray:
        return self.data[name]

    def to_pandas(self) -> pd.DataFrame:
        return pd.DataFrame({c: self.data[c] for c in self.columns})

    def to_rows(self) -> List[dict]:
        df = self.to_pandas()
        return df.to_dict(orient="records")

    def __repr__(self) -> str:
        return f"QueryResult({len(self)} rows x {self.columns})"

    @staticmethod
    def empty(columns: List[str]) -> "QueryResult":
        return QueryResult(columns, {c: np.array([]) for c in columns})
