"""Named query lanes: configuration grammar and runtime state.

≈ Druid's laning strategies (`QueryScheduler` lanes: a total slot pool
carved into named lanes, each with its own concurrency limit). A lane
here additionally owns a bounded priority wait-queue, a max queue-wait
budget, and a default per-query timeout propagated into
``QueryContext`` when the client set none.
"""

from __future__ import annotations

import dataclasses
import heapq
import itertools
import threading
from typing import Dict, List, Optional


class AdmissionRejected(RuntimeError):
    """Base of every load-shed rejection (lane full, wait budget blown,
    quota exhausted). ``retry_after_s`` is the server's backoff hint —
    surfaced as HTTP 429 + ``Retry-After`` by the serving layer."""

    def __init__(self, message: str, retry_after_s: float = 1.0):
        super().__init__(message)
        self.retry_after_s = max(0.0, float(retry_after_s))


@dataclasses.dataclass(frozen=True)
class LaneConfig:
    name: str
    slots: int = 4              # concurrent queries executing in the lane
    max_queue: int = 32         # waiters beyond slots before shedding
    max_wait_ms: float = 0.0    # queue-wait budget; 0 = only the query's
    #                             own timeout bounds the wait
    timeout_millis: Optional[int] = None   # default QueryContext timeout
    priority: int = 0           # default admission priority (higher first)


_LANE_FIELDS = {"slots": int, "queue": int, "wait_ms": float,
                "timeout_ms": int, "priority": int}


def parse_lanes(spec: str) -> Dict[str, LaneConfig]:
    """Parse the ``sdot.wlm.lanes`` grammar:
    ``name:slots=N,queue=N,wait_ms=N,timeout_ms=N,priority=N;name2:...``
    Unknown options raise — a typo silently dropping a concurrency cap
    would defeat the whole subsystem."""
    out: Dict[str, LaneConfig] = {}
    for part in (spec or "").split(";"):
        part = part.strip()
        if not part:
            continue
        name, _, opts = part.partition(":")
        name = name.strip()
        if not name:
            raise ValueError(f"lane with empty name in {spec!r}")
        kw = {}
        for opt in opts.split(","):
            opt = opt.strip()
            if not opt:
                continue
            k, _, v = opt.partition("=")
            k = k.strip()
            if k not in _LANE_FIELDS:
                raise ValueError(
                    f"unknown lane option {k!r} (lane {name!r}); "
                    f"known: {sorted(_LANE_FIELDS)}")
            kw[k] = _LANE_FIELDS[k](v.strip())
        out[name] = LaneConfig(
            name,
            slots=max(1, kw.get("slots", 4)),
            max_queue=max(0, kw.get("queue", 32)),
            max_wait_ms=float(kw.get("wait_ms", 0.0)),
            timeout_millis=kw.get("timeout_ms") or None,
            priority=kw.get("priority", 0))
    return out


class _Waiter:
    __slots__ = ("priority", "seq", "event", "granted", "removed")

    def __init__(self, priority: int, seq: int):
        self.priority = priority
        self.seq = seq
        self.event = threading.Event()
        self.granted = False
        self.removed = False

    def __lt__(self, other):     # heapq order: higher priority, then FIFO
        return (-self.priority, self.seq) < (-other.priority, other.seq)


class Lane:
    """Runtime state of one lane. All mutation happens under the owning
    WorkloadManager's lock — the lane itself holds no lock, so slot
    transfer (release -> grant) is a single atomic section."""

    def __init__(self, config: LaneConfig, seq=None):
        self.config = config
        self.active = 0
        self.max_active_seen = 0     # high-water mark: the tests' cap proof
        self._heap: List[_Waiter] = []
        self._seq = seq if seq is not None else itertools.count()
        # counters (monotone; surfaced by sys_lanes / GET /metadata/wlm)
        self.admitted = 0
        self.demoted_in = 0          # admissions arriving via cost demotion
        self.shed = 0                # queue-depth rejections
        self.timed_out = 0           # wait-budget rejections
        self.cancelled_queued = 0    # cancels honored while still queued
        self.coalesced_handoff = 0   # waiters bypassed into a shared-scan
        #                              group (counted in `admitted` too)
        self.queued_ms_total = 0.0
        self.run_ms_ewma = 0.0       # released-query runtime (retry hints)

    # -- under the manager lock -----------------------------------------------
    def queue_len(self) -> int:
        return sum(1 for w in self._heap if not w.removed)

    def try_acquire(self) -> bool:
        """Fast path: a free slot and nobody queued ahead."""
        if self.active < self.config.slots and self.queue_len() == 0:
            self.active += 1
            self.max_active_seen = max(self.max_active_seen, self.active)
            return True
        return False

    def enqueue(self, priority: int) -> _Waiter:
        w = _Waiter(priority, next(self._seq))
        heapq.heappush(self._heap, w)
        return w

    def remove(self, waiter: _Waiter) -> None:
        """Lazy delete: mark removed; the grant loop skips dead entries."""
        waiter.removed = True

    def grant_next(self) -> None:
        """Hand a free slot to the best waiter (priority, then FIFO)."""
        while self.active < self.config.slots and self._heap:
            w = heapq.heappop(self._heap)
            if w.removed:
                continue
            self.active += 1
            self.max_active_seen = max(self.max_active_seen, self.active)
            w.granted = True
            w.event.set()

    def release(self, run_ms: Optional[float] = None) -> None:
        self.active = max(0, self.active - 1)
        if run_ms is not None:
            a = 0.2
            self.run_ms_ewma = run_ms if self.run_ms_ewma == 0.0 \
                else (1 - a) * self.run_ms_ewma + a * run_ms
        self.grant_next()

    def retry_after_s(self) -> float:
        """Backoff hint: rough time for the backlog to drain one slot's
        worth of work (EWMA runtime), floored at 100ms."""
        est = self.run_ms_ewma or 1000.0
        backlog = self.queue_len() + 1
        return max(0.1, backlog * est / 1000.0 / max(1, self.config.slots))

    def snapshot(self) -> dict:
        c = self.config
        return {"lane": c.name, "slots": c.slots, "active": self.active,
                "queued": self.queue_len(), "max_queue": c.max_queue,
                "max_wait_ms": c.max_wait_ms,
                "default_timeout_ms": c.timeout_millis or 0,
                "priority": c.priority, "admitted": self.admitted,
                "demoted_in": self.demoted_in, "shed": self.shed,
                "timed_out": self.timed_out,
                "cancelled_queued": self.cancelled_queued,
                "coalesced_handoff": self.coalesced_handoff,
                "max_active_seen": self.max_active_seen,
                "queued_ms_total": round(self.queued_ms_total, 2),
                "run_ms_ewma": round(self.run_ms_ewma, 2)}
