"""Per-tenant quotas: concurrent-query caps and cost token buckets.

≈ Druid's per-user `druid.query.scheduler` limits + the reference
deployment's per-BI-tool resource groups: a tenant (the serving layer's
``X-Sdot-Tenant`` header / ``context.tenant``) gets

- a **concurrent-query cap** — hard ceiling on in-flight queries, and
- a **token bucket denominated in estimated cost units** (the abstract
  units of ``parallel/cost.estimate``): each admission charges the
  query's estimated cost; the bucket refills at a configured rate, so a
  tenant can burst to its capacity but sustains only its refill rate.

Quotas are configured as ``sdot.wlm.quota.<tenant>`` config keys with a
``concurrent=N,budget=F,refill=F`` grammar; ``sdot.wlm.quota.default``
applies to tenants without an explicit entry. No configured quota (and
no default) = unlimited — the subsystem must cost nothing when unused.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, Optional

from spark_druid_olap_tpu.wlm.lanes import AdmissionRejected

QUOTA_PREFIX = "sdot.wlm.quota."


class QuotaExceededError(AdmissionRejected):
    """Tenant over its concurrent cap or out of budget tokens."""


class TokenBucket:
    """Classic token bucket over float cost units. ``now_fn`` is
    injectable so tests advance time deterministically."""

    def __init__(self, capacity: float, refill_per_s: float,
                 now_fn: Callable[[], float] = time.monotonic):
        self.capacity = float(capacity)
        self.refill_per_s = float(refill_per_s)
        self._now = now_fn
        self._tokens = self.capacity
        self._last = self._now()

    def _refill(self) -> None:
        now = self._now()
        dt = max(0.0, now - self._last)
        self._last = now
        self._tokens = min(self.capacity,
                           self._tokens + dt * self.refill_per_s)

    def tokens(self) -> float:
        self._refill()
        return self._tokens

    def try_charge(self, cost: float) -> bool:
        self._refill()
        if self._tokens >= cost:
            self._tokens -= cost
            return True
        return False

    def seconds_until(self, cost: float) -> float:
        """Time until ``cost`` tokens are available (inf if the bucket
        can never hold that many)."""
        self._refill()
        if self._tokens >= cost:
            return 0.0
        if cost > self.capacity or self.refill_per_s <= 0:
            return float("inf")
        return (cost - self._tokens) / self.refill_per_s


class _TenantState:
    __slots__ = ("name", "max_concurrent", "bucket", "active", "admitted",
                 "rejected", "cost_charged")

    def __init__(self, name: str, max_concurrent: int,
                 bucket: Optional[TokenBucket]):
        self.name = name
        self.max_concurrent = max_concurrent   # 0 = unlimited
        self.bucket = bucket                   # None = no budget
        self.active = 0
        self.admitted = 0
        self.rejected = 0
        self.cost_charged = 0.0


def _parse_quota(tenant: str, spec: str):
    """``concurrent=N,budget=F,refill=F`` -> (max_concurrent, budget,
    refill). budget without refill never replenishes past the burst."""
    kw = {"concurrent": 0, "budget": 0.0, "refill": 0.0}
    for opt in str(spec).split(","):
        opt = opt.strip()
        if not opt:
            continue
        k, _, v = opt.partition("=")
        k = k.strip()
        if k not in kw:
            raise ValueError(f"unknown quota option {k!r} for tenant "
                             f"{tenant!r}; known: {sorted(kw)}")
        kw[k] = float(v) if k != "concurrent" else int(v)
    return kw["concurrent"], kw["budget"], kw["refill"]


class QuotaManager:
    """Tenant registry; all mutation under the WorkloadManager's lock
    (passed-in critical sections — this class holds no lock itself
    except bucket arithmetic, which is per-call and cheap)."""

    def __init__(self, now_fn: Callable[[], float] = time.monotonic):
        self._now = now_fn
        self._tenants: Dict[str, _TenantState] = {}
        self._configured: Dict[str, str] = {}

    def configure(self, quota_specs: Dict[str, str]) -> None:
        """(Re)build tenant states from ``{tenant: spec}``; live active
        counts survive a reconfigure, buckets reset (a changed budget
        starts full — the operator just asked for new limits)."""
        if quota_specs == self._configured:
            return
        self._configured = dict(quota_specs)
        old = self._tenants
        self._tenants = {}
        for tenant, spec in quota_specs.items():
            conc, budget, refill = _parse_quota(tenant, spec)
            bucket = TokenBucket(budget, refill, self._now) \
                if budget > 0 else None
            st = _TenantState(tenant, conc, bucket)
            prev = old.get(tenant)
            if prev is not None:
                st.active = prev.active
                st.admitted = prev.admitted
                st.rejected = prev.rejected
                st.cost_charged = prev.cost_charged
        # keep unconfigured-but-active tenants visible (pure observation)
            self._tenants[tenant] = st
        for name, prev in old.items():
            if name not in self._tenants and (prev.active or prev.admitted):
                self._tenants[name] = _TenantState(name, 0, None)
                self._tenants[name].active = prev.active
                self._tenants[name].admitted = prev.admitted

    def _state_for(self, tenant: str) -> _TenantState:
        st = self._tenants.get(tenant)
        if st is None:
            # fall back to the 'default' template if configured
            tpl = self._configured.get("default")
            if tpl is not None:
                conc, budget, refill = _parse_quota(tenant, tpl)
                bucket = TokenBucket(budget, refill, self._now) \
                    if budget > 0 else None
                st = _TenantState(tenant, conc, bucket)
            else:
                st = _TenantState(tenant, 0, None)
            self._tenants[tenant] = st
        return st

    def acquire(self, tenant: Optional[str], cost: float) -> Optional[str]:
        """Admit one query for ``tenant`` (None = untracked). Raises
        :class:`QuotaExceededError` on cap/budget violation; returns the
        tenant key to pass back to :meth:`release`."""
        if not tenant:
            return None
        st = self._state_for(tenant)
        if st.max_concurrent > 0 and st.active >= st.max_concurrent:
            st.rejected += 1
            raise QuotaExceededError(
                f"tenant {tenant!r} at its concurrent-query cap "
                f"({st.max_concurrent})", retry_after_s=1.0)
        if st.bucket is not None and not st.bucket.try_charge(cost):
            st.rejected += 1
            wait = st.bucket.seconds_until(cost)
            raise QuotaExceededError(
                f"tenant {tenant!r} out of cost budget "
                f"(need {cost:.4g} units)",
                retry_after_s=min(wait if wait != float("inf") else 60.0,
                                  60.0))
        st.active += 1
        st.admitted += 1
        st.cost_charged += cost
        return tenant

    def release(self, tenant: Optional[str]) -> None:
        if not tenant:
            return
        st = self._tenants.get(tenant)
        if st is not None:
            st.active = max(0, st.active - 1)

    def snapshot(self) -> list:
        out = []
        for name in sorted(self._tenants):
            st = self._tenants[name]
            out.append({
                "tenant": name, "active": st.active,
                "max_concurrent": st.max_concurrent,
                "budget": st.bucket.capacity if st.bucket else 0.0,
                "tokens": round(st.bucket.tokens(), 4) if st.bucket else 0.0,
                "refill_per_s": st.bucket.refill_per_s if st.bucket else 0.0,
                "admitted": st.admitted, "rejected": st.rejected,
                "cost_charged": round(st.cost_charged, 4)})
        return out


def quotas_from_config(config) -> Dict[str, str]:
    """Extract ``sdot.wlm.quota.<tenant>`` entries from a session
    Config (unknown sdot.* keys are accepted by design, so quota specs
    ride the normal config channel)."""
    return {k[len(QUOTA_PREFIX):]: str(v)
            for k, v in config.prefixed(QUOTA_PREFIX).items()
            if k[len(QUOTA_PREFIX):]}
