"""Workload management — query lanes, admission control, tenant quotas.

≈ Druid's broker-tier *query laning and prioritization* (Druid docs
"query laning"; `QueryScheduler` + laning strategies), the piece that
lets the reference's serving tier survive concurrent BI traffic: every
query is classified into a named **lane** with bounded concurrency and
queue depth, expensive queries are demoted to a low-priority lane by the
cost model, and per-tenant **quotas** (concurrent-query caps + a
token-bucket budget denominated in estimated cost units) keep one tenant
from starving the rest. Overload sheds load with a retryable rejection
instead of melting every in-flight query at once.

Layout:

- :mod:`~spark_druid_olap_tpu.wlm.lanes` — lane configuration and
  runtime state (slots, bounded priority queue, counters);
- :mod:`~spark_druid_olap_tpu.wlm.admit` — :class:`WorkloadManager`:
  classification (explicit ``context.lane`` / cost-threshold demotion),
  priority-ordered FIFO admission, load shedding;
- :mod:`~spark_druid_olap_tpu.wlm.quota` — per-tenant concurrent caps
  and token buckets.

Wired into ``QueryEngine.execute`` (the single funnel every front door
— HTTP, Flight, raw specs — drains into), so a shed query never reaches
the executor and queue wait counts against the query's deadline.
"""

from spark_druid_olap_tpu.wlm.lanes import (AdmissionRejected, Lane,
                                            LaneConfig, parse_lanes)
from spark_druid_olap_tpu.wlm.admit import (LaneFullError, Ticket,
                                            WorkloadManager)
from spark_druid_olap_tpu.wlm.quota import (QuotaExceededError, QuotaManager,
                                            TokenBucket)

__all__ = [
    "AdmissionRejected", "Lane", "LaneConfig", "parse_lanes",
    "LaneFullError", "Ticket", "WorkloadManager",
    "QuotaExceededError", "QuotaManager", "TokenBucket",
]
