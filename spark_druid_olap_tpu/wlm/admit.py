"""Admission control: classify -> quota -> lane queue -> slot.

≈ Druid's `QueryScheduler.laneQuery/run` + the prioritization strategies:
every engine query passes through :meth:`WorkloadManager.admit` before
any planning/binding/dispatch work happens. Classification is by
explicit ``context.lane``, else by the calibrated cost model —
queries whose estimated single-chip cost crosses
``sdot.wlm.batch.cost.threshold`` are demoted to the batch lane (≈
Druid's `HiLoQueryLaningStrategy` sending "low" priority queries to a
bounded lane). Admission within a lane is priority-ordered FIFO; load
past the queue bound or wait budget sheds with :class:`LaneFullError`
(HTTP 429 + ``Retry-After`` at the serving layer), so overload degrades
to fast rejections instead of collapsing every in-flight query.

Queue wait is charged against the query's own deadline (the engine's
``t0`` is taken before admission), and a cooperative cancel registered
for the query id is honored *while queued* — the waiter unhooks itself
without ever taking a slot.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Optional, Tuple

from spark_druid_olap_tpu.utils import phases as PH
from spark_druid_olap_tpu.wlm.lanes import (AdmissionRejected, Lane,
                                            LaneConfig, parse_lanes)
from spark_druid_olap_tpu.wlm.quota import QuotaManager, quotas_from_config

# how often a queued waiter polls for grant/cancel/deadline; grants set
# the waiter's Event so the happy path wakes immediately — the poll only
# bounds cancel/timeout latency
_POLL_S = 0.02


class LaneFullError(AdmissionRejected):
    """Lane queue depth or queue-wait budget exceeded — load shed."""


class _IngestContext:
    """QueryContext stand-in for stream-ingest admission: pins the
    ``ingest`` lane, inherits everything else from the lane config."""
    lane = "ingest"
    tenant = None
    priority = None
    timeout_millis = None
    query_id = None


class _IngestShim:
    """Synthetic spec routing a stream-ingest batch through lane
    admission. No datasource / aggregations: the shared-scan handoff
    and cost model both pass it over, so only the ``ingest`` lane's
    slot/queue accounting applies."""
    context = _IngestContext()
    datasource = None
    aggregations = ()


_INGEST_SHIM = _IngestShim()


@dataclasses.dataclass
class Ticket:
    """Proof of admission; passed back to :meth:`WorkloadManager.release`."""
    lane: str
    tenant: Optional[str]
    priority: int
    queued_ms: float
    est_cost: float
    demoted: bool
    timeout_millis: Optional[int]   # effective (context or lane default)
    _lane_obj: Lane = dataclasses.field(repr=False, default=None)
    _started: float = 0.0
    # admitted via the shared-scan handoff: the query rides a coalesced
    # group's dispatch instead of a lane slot, so release() must not hand
    # back a slot it never took
    coalesced: bool = False

    def stats(self) -> dict:
        d = {"lane": self.lane, "queued_ms": round(self.queued_ms, 2),
             "priority": self.priority}
        if self.tenant:
            d["tenant"] = self.tenant
        if self.demoted:
            d["demoted"] = True
        if self.coalesced:
            d["coalesced_handoff"] = True
        return d


class WorkloadManager:
    """One per QueryEngine. Reads its lane/quota layout from the session
    Config lazily, so a config change (tests, operator SET) takes effect
    on the next admission without a rebuild handshake."""

    def __init__(self, config):
        self._config = config
        self._lock = threading.Lock()
        self._lanes = {}
        self._lanes_src: Optional[str] = None
        self._default_lane = "interactive"
        self.quotas = QuotaManager()
        self._tls = threading.local()
        # global counters
        self.admitted_total = 0
        self.shed_total = 0
        # set by the owning QueryEngine; lets queued waiters hand off to
        # an open shared-scan group instead of draining serially
        self.sharedscan = None
        # fault injector (fault/, docs/CHAOS.md) wired by the owning
        # QueryEngine; None unless sdot.fault.plan is set
        self.fault = None

    # -- configuration ---------------------------------------------------------
    @property
    def enabled(self) -> bool:
        from spark_druid_olap_tpu.utils.config import WLM_ENABLED
        return bool(self._config.get(WLM_ENABLED))

    def _refresh_locked(self) -> None:
        from spark_druid_olap_tpu.utils.config import (WLM_DEFAULT_LANE,
                                                       WLM_LANES)
        src = str(self._config.get(WLM_LANES))
        if src != self._lanes_src:
            configs = parse_lanes(src)
            old = self._lanes
            self._lanes = {}
            for name, cfg in configs.items():
                lane = old.get(name)
                if lane is not None:
                    # keep live occupancy/counters across a re-config,
                    # just swap the limits
                    lane.config = cfg
                    self._lanes[name] = lane
                else:
                    self._lanes[name] = Lane(cfg)
            self._lanes_src = src
        self._default_lane = str(self._config.get(WLM_DEFAULT_LANE))
        if self._default_lane not in self._lanes:
            # config error containment: a bad default must not brick the
            # engine; fall back to any defined lane
            self._lanes.setdefault(
                self._default_lane,
                Lane(LaneConfig(self._default_lane)))
        self.quotas.configure(quotas_from_config(self._config))

    # -- request-context fallback (serving layer -> engine) --------------------
    def push_request(self, lane: Optional[str], tenant: Optional[str],
                     priority: Optional[int]) -> None:
        """Serving layers stash the request's lane/tenant/priority on
        this thread; specs that don't carry them in ``QueryContext``
        (host-tier subqueries, composite inner queries) inherit them."""
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        stack.append((lane, tenant, priority))

    def pop_request(self) -> None:
        stack = getattr(self._tls, "stack", None)
        if stack:
            stack.pop()

    def _request_fallback(self) -> Tuple[Optional[str], Optional[str],
                                         Optional[int]]:
        stack = getattr(self._tls, "stack", None)
        return stack[-1] if stack else (None, None, None)

    # -- classification --------------------------------------------------------
    def _estimate_cost(self, engine, q) -> float:
        """Estimated single-chip cost units (the quota denomination and
        the demotion signal). Never raises: sys-shaped or odd specs cost
        the compile floor."""
        try:
            from spark_druid_olap_tpu.parallel import cost as C
            return float(C.estimate(engine, q).single_cost)
        except Exception:  # noqa: BLE001 — estimate is advisory
            return 0.05

    def classify(self, engine, q) -> Tuple[str, float, bool, Optional[str],
                                           int]:
        """-> (lane_name, est_cost, demoted, tenant, priority)."""
        from spark_druid_olap_tpu.utils.config import WLM_BATCH_COST
        ctxq = getattr(q, "context", None)
        fb_lane, fb_tenant, fb_priority = self._request_fallback()
        lane = getattr(ctxq, "lane", None) or fb_lane
        tenant = getattr(ctxq, "tenant", None) or fb_tenant
        priority = getattr(ctxq, "priority", None)
        if priority is None:
            priority = fb_priority
        est = None
        demoted = False
        if lane not in self._lanes:
            lane = self._default_lane
            threshold = float(self._config.get(WLM_BATCH_COST))
            if threshold > 0 and "batch" in self._lanes:
                est = self._estimate_cost(engine, q)
                if est >= threshold:
                    lane, demoted = "batch", True
        need_cost = any(st.bucket is not None
                        for st in self.quotas._tenants.values()) \
            or "default" in self.quotas._configured
        if est is None and tenant and need_cost:
            est = self._estimate_cost(engine, q)
        if est is None:
            est = 0.0
        if priority is None:
            priority = self._lanes[lane].config.priority
        return lane, est, demoted, tenant, int(priority)

    # -- admission -------------------------------------------------------------
    def admit(self, engine, q, t0: float,
              cancel_event: Optional[threading.Event] = None) -> Ticket:
        """Block until a lane slot is granted (or raise). ``t0`` is the
        engine's query start — queue wait counts against the deadline.
        Admission time (queue wait INCLUDED) lands in the per-query
        phase profile as ``wlm.admit``."""
        with PH.phase("wlm.admit"):
            return self._admit(engine, q, t0, cancel_event)

    def _admit(self, engine, q, t0: float,
               cancel_event: Optional[threading.Event] = None) -> Ticket:
        inj = self.fault
        if inj is not None:
            # chaos site (before the lock — a delay rule models slot
            # starvation, an error rule a queue-full shed)
            inj.fire("wlm.admit")
        with self._lock:
            self._refresh_locked()
            lane_name, est, demoted, tenant, priority = \
                self.classify(engine, q)
            lane = self._lanes[lane_name]
            cfg = lane.config
            ctxq = getattr(q, "context", None)
            timeout_ms = getattr(ctxq, "timeout_millis", None)
            if timeout_ms is None:
                timeout_ms = cfg.timeout_millis
            # quota before the queue: a tenant over budget must not
            # occupy queue depth others could use
            self.quotas.acquire(tenant, est)
            try:
                if lane.try_acquire():
                    lane.admitted += 1
                    if demoted:
                        lane.demoted_in += 1
                    self.admitted_total += 1
                    return Ticket(lane_name, tenant, priority, 0.0, est,
                                  demoted, timeout_ms, lane,
                                  time.perf_counter())
                if lane.queue_len() >= cfg.max_queue:
                    lane.shed += 1
                    self.shed_total += 1
                    raise LaneFullError(
                        f"lane {lane_name!r} full "
                        f"({cfg.slots} running, {lane.queue_len()} queued)",
                        retry_after_s=lane.retry_after_s())
                waiter = lane.enqueue(priority)
            except BaseException:
                self.quotas.release(tenant)
                raise
        # --- queued: wait outside the lock ---------------------------------
        unhooked = False    # quota + queue entry handed off or released
        try:
            enq = time.perf_counter()
            wait_deadline = enq + cfg.max_wait_ms / 1000.0 \
                if cfg.max_wait_ms > 0 else None
            query_deadline = t0 + timeout_ms / 1000.0 \
                if timeout_ms is not None else None
            while True:
                if waiter.event.wait(_POLL_S):
                    break
                now = time.perf_counter()
                coal = self.sharedscan
                if coal is not None and coal.should_try(q) \
                        and coal.open_group_hint(
                            getattr(q, "datasource", None)):
                    # shared-scan handoff: a compatible group is holding
                    # its micro-batch window — ride its fused dispatch
                    # instead of waiting for a serial slot. The query
                    # leaves the queue WITHOUT taking a slot (the group
                    # leader owns the lane occupancy for the dispatch).
                    # LOCK ORDER: note_handoff() takes the coalescer's
                    # group lock while self._lock is held — the global
                    # order is WorkloadManager._lock BEFORE
                    # SharedScanCoalescer._lock (docs/LINT.md); the
                    # coalescer must never call back into admission
                    # under its lock.
                    with self._lock:
                        if not waiter.granted:
                            # note_handoff BEFORE remove: if the
                            # coalescer refuses (raises), the waiter is
                            # still queued and the error path below
                            # unhooks it cleanly
                            coal.note_handoff()
                            lane.remove(waiter)
                            unhooked = True   # ticket owns quota now
                            lane.admitted += 1
                            lane.coalesced_handoff += 1
                            self.admitted_total += 1
                            queued_ms = (now - enq) * 1000.0
                            lane.queued_ms_total += queued_ms
                            return Ticket(lane_name, tenant, priority,
                                          queued_ms, est, demoted,
                                          timeout_ms, lane,
                                          time.perf_counter(),
                                          coalesced=True)
                        # a grant raced the handoff: keep the slot
                        break
                if cancel_event is not None and cancel_event.is_set():
                    self._unhook(lane, waiter, tenant, "cancel")
                    unhooked = True
                    from spark_druid_olap_tpu.parallel.executor import (
                        QueryCancelled)
                    qid = getattr(ctxq, "query_id", None)
                    raise QueryCancelled(
                        f"query {qid} cancelled while queued in lane "
                        f"{lane_name!r}")
                if wait_deadline is not None and now >= wait_deadline:
                    self._unhook(lane, waiter, tenant, "wait")
                    unhooked = True
                    raise LaneFullError(
                        f"lane {lane_name!r} queue-wait budget "
                        f"({cfg.max_wait_ms:.0f}ms) exceeded",
                        retry_after_s=lane.retry_after_s())
                if query_deadline is not None and now >= query_deadline:
                    self._unhook(lane, waiter, tenant, "deadline")
                    unhooked = True
                    from spark_druid_olap_tpu.parallel.executor import (
                        QueryTimeout)
                    raise QueryTimeout(
                        f"query exceeded {timeout_ms}ms "
                        f"(queued in lane {lane_name!r})")
            queued_ms = (time.perf_counter() - enq) * 1000.0
            with self._lock:
                lane.admitted += 1
                if demoted:
                    lane.demoted_in += 1
                self.admitted_total += 1
                lane.queued_ms_total += queued_ms
            return Ticket(lane_name, tenant, priority, queued_ms, est,
                          demoted, timeout_ms, lane, time.perf_counter())
        except BaseException:
            # anything that escapes the wait (KeyboardInterrupt landing
            # in event.wait, a raising stats hook, ...) must give back
            # the queue entry — or the granted slot, if a grant raced —
            # and the tenant quota, or the lane wedges permanently
            if not unhooked:
                self._unhook(lane, waiter, tenant, "error")
            raise

    def _unhook(self, lane: Lane, waiter, tenant: Optional[str],
                why: str) -> None:
        """Remove a queued waiter. If a grant raced us, the slot is ours
        — hand it straight back so it is never leaked."""
        with self._lock:
            if waiter.granted:
                lane.release()
            else:
                lane.remove(waiter)
            if why == "cancel":
                lane.cancelled_queued += 1
            elif why == "wait":
                lane.timed_out += 1
                self.shed_total += 1
            self.quotas.release(tenant)

    def admit_ingest(self) -> Optional[Ticket]:
        """Lane admission for one stream-ingest batch (the write-side
        twin of :meth:`admit`). Routes through the ``ingest`` lane when
        the operator configured one in ``sdot.wlm.lanes`` — producers
        then share the same slot/queue/shed fabric as queries, so an
        ingest storm cannot starve dashboards (and vice versa: the
        lane's slot count caps concurrent local applies). Returns a
        Ticket for :meth:`release`, or None (no admission, no release)
        when WLM is off or no ``ingest`` lane exists — ingest is never
        throttled by default."""
        if not self.enabled:
            return None
        with self._lock:
            self._refresh_locked()
            if "ingest" not in self._lanes:
                return None
        return self.admit(None, _INGEST_SHIM, time.perf_counter())

    def release(self, ticket: Ticket) -> None:
        run_ms = (time.perf_counter() - ticket._started) * 1000.0
        with self._lock:
            if not ticket.coalesced:
                # coalesced handoffs never took a lane slot
                ticket._lane_obj.release(run_ms)
            self.quotas.release(ticket.tenant)

    # -- observability ---------------------------------------------------------
    def stats(self) -> dict:
        with self._lock:
            self._refresh_locked()
            out = {"enabled": self.enabled,
                   "admitted": self.admitted_total,
                   "shed": self.shed_total,
                   "default_lane": self._default_lane,
                   "lanes": [ln.snapshot()
                             for _, ln in sorted(self._lanes.items())],
                   "tenants": self.quotas.snapshot()}
        if self.sharedscan is not None:
            out["sharedscan"] = self.sharedscan.stats()
        return out

    def lanes_view(self):
        """``sys_lanes`` — one row per configured lane."""
        import pandas as pd
        with self._lock:
            self._refresh_locked()
            rows = [ln.snapshot() for _, ln in sorted(self._lanes.items())]
        return pd.DataFrame(rows)
