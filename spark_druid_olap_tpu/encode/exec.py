"""Encoded-domain execution: aggregate and prune WITHOUT decoding.

The routing tiers of the engine never change for encoded stores — the
device path decodes chunks on fault and computes on the same stacked
arrays as always (the ``materialize()`` fallback guarantees every query
shape works). What this module adds are the paths where the encoded
form answers a question outright:

- **zone maps from headers** (:func:`chunk_bounds`): integer codec
  headers carry vmin/vmax, so segment min/max pruning reads the header
  — no payload decode, no cold-tier fault.
- **FoR-domain interval pruning** (:func:`chunk_day_overlap`): a
  fordelta time-days header bounds the chunk's day range; an interval
  that misses it skips the chunk before any decode
  (``ops/time_ops.py:interval_day_range`` supplies the day arithmetic).
- **RLE-run aggregation** (:func:`rle_groupby`): group-by over an
  RLE-encoded dimension aggregates run-at-a-time — count partials are
  the run lengths themselves and sum partials multiply run values by
  run length (``ops/groupby.py:run_weighted_partials``), touching
  O(runs) values instead of O(rows).

These functions are pure host-side numpy over (payload, header) chunk
pairs; the differential legs (``tests/test_encoding.py``,
``loadtest --encoded``) verify them bit-exactly against the decoded
path.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple

import numpy as np

from spark_druid_olap_tpu.encode import codecs as C


def chunk_bounds(header: dict) -> Optional[Tuple[int, int]]:
    """(vmin, vmax) of an encoded chunk from its header alone (None for
    raw/float/empty chunks — those need a decode to bound)."""
    return C.header_bounds(header)


def chunk_day_overlap(header: dict, intervals) -> Optional[bool]:
    """Does a time-days chunk overlap any [lo_ms, hi_ms) interval?
    Decided purely in the FoR domain via the header's day bounds; None
    when the header carries no bounds (raw chunk) and the caller must
    fall back to the decoded mask."""
    from spark_druid_olap_tpu.ops import time_ops
    b = C.header_bounds(header)
    if b is None:
        return None
    lo_day, hi_day = b
    for lo, hi in intervals:
        dlo, _rlo, dhi, rhi = time_ops.interval_day_range(int(lo), int(hi))
        # interval covers [dlo, dhi] fully only up to rhi ms on dhi;
        # day-level overlap is the prune test (row-level residual masks
        # still apply on straddling chunks)
        last = dhi if rhi > 0 else dhi - 1
        if lo_day <= last and hi_day >= dlo:
            return True
    return False


def decode_chunk(payload, header: dict) -> np.ndarray:
    """The materialize() fallback: raw rows of one chunk."""
    return C.decode_array(payload, header)


def rle_groupby(dim_payload, dim_header: dict, n_keys: int,
                metric: Optional[np.ndarray] = None,
                ) -> Dict[str, np.ndarray]:
    """Aggregate one segment chunk grouped by an RLE-encoded dimension
    without expanding the dimension to rows.

    Returns ``{"count": int64[n_keys], "sum": f64[n_keys]?}`` partials.
    ``metric`` (decoded rows, same length as the chunk) is reduced per
    run with ``np.add.reduceat`` — the dimension codes themselves never
    materialize. Falls back to a decoded group-by for non-RLE chunks.
    """
    from spark_druid_olap_tpu.ops.groupby import run_weighted_partials
    if dim_header.get("c") == C.RLE:
        values, lengths = C.rle_runs(dim_payload, dim_header)
    else:
        rows = C.decode_array(dim_payload, dim_header)
        change = np.flatnonzero(np.diff(rows.astype(np.int64))) + 1
        starts = np.concatenate([[0], change]) if len(rows) \
            else np.empty(0, dtype=np.int64)
        lengths = np.diff(np.concatenate([starts, [len(rows)]])) \
            if len(rows) else np.empty(0, dtype=np.int64)
        values = rows[starts.astype(np.int64)] if len(rows) \
            else rows[:0]
    run_sums = None
    if metric is not None and len(lengths):
        starts = np.concatenate(
            [[0], np.cumsum(lengths)[:-1]]).astype(np.int64)
        run_sums = np.add.reduceat(
            np.asarray(metric, dtype=np.float64), starts)
    return run_weighted_partials(values, lengths, n_keys,
                                 run_sums=run_sums)


def reduce_chunk(payload, header: dict, op: str):
    """sum / min / max / count over one encoded chunk, computed in the
    encoded domain where the codec allows:

    - count: the header's row count (no payload read at all)
    - min/max (integer codecs): the header's vmin/vmax
    - sum over RLE: run value x run length, O(runs)
    - sum over fordelta: first + weighted deltas (value i contributes
      (n - i) copies of delta i), O(n) adds but zero row materialization
    - anything else: decode fallback
    """
    n = int(header["n"])
    if op == "count":
        return n
    if n == 0:
        return None
    if op in ("min", "max"):
        b = C.header_bounds(header)
        if b is not None:
            return b[0] if op == "min" else b[1]
    if op == "sum":
        c = header.get("c")
        if c == C.RLE:
            values, lengths = C.rle_runs(payload, header)
            return int(np.dot(values.astype(np.int64), lengths)) \
                if values.dtype.kind in "iub" else \
                float(np.dot(values.astype(np.float64), lengths))
        if c == C.FORDELTA:
            d = C._unpack_bits(payload, n - 1,
                               int(header["bits"])).astype(np.int64)
            d += int(header["dmin"])
            weights = np.arange(n - 1, 0, -1, dtype=np.int64)
            return n * int(header["first"]) + int(np.dot(d, weights))
    rows = C.decode_array(payload, header)
    if op == "sum":
        return int(rows.astype(np.int64).sum()) \
            if rows.dtype.kind in "iub" else float(rows.sum())
    return rows.min() if op == "min" else rows.max()


def segment_bounds_from_refs(refs: Sequence) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """Per-segment (mins, maxs) zone maps straight from encoded tier
    refs' headers (tier/store.py:BlobRef.header). None if any non-empty
    segment lacks header bounds — partial zone maps would silently
    unprune."""
    mins = np.full(len(refs), np.inf)
    maxs = np.full(len(refs), -np.inf)
    for i, r in enumerate(refs):
        if not r.count:
            continue
        h = r.header()
        b = C.header_bounds(h) if h is not None else None
        if b is None:
            return None
        mins[i], maxs[i] = float(b[0]), float(b[1])
    return mins, maxs
