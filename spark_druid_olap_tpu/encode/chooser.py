"""Per-column encoding chooser (ingest / checkpoint / compaction time).

One analytic O(n) pass per column (``codecs.estimate_sizes``) scores
every eligible codec; the winner must beat raw by at least
``sdot.encode.min.ratio`` or the column stays raw. Heuristics mirror
what the estimates measure:

- **bool** (validity masks): bitpack, 1 bit/row — 8x, always wins.
- **dictionary codes**: bitpack at ``ceil(log2(card))`` bits; when the
  data is sorted/low-cardinality enough that runs/rows falls under
  ``sdot.encode.rle.max.run.frac``, RLE competes and wins on long runs.
- **time days** (monotone after ingest's time sort): fordelta — the
  per-row cost is the delta width, near-zero on dense time ranges.
- **LONG/DATE metrics**: bitpack over the value range; RLE when runny.
- **floats**: raw, always (bit-exactness contract; see codecs.py).

The choice is advisory and per COLUMN; the encoder still falls back to
raw per SEGMENT chunk when a choice fails to shrink a particular chunk
(``codecs.encode_chunk``), so an adversarial segment never inflates.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional

import numpy as np

from spark_druid_olap_tpu.encode import codecs as CODECS


@dataclasses.dataclass(frozen=True)
class EncodeOptions:
    """Checkpoint-time encoding policy, resolved from config ONCE by the
    PersistManager (sdot.encode.*) and threaded through write_snapshot —
    snapshot.py itself never reads config."""

    enabled: bool = False
    min_ratio: float = 1.2
    rle_max_run_frac: float = 0.5

    @classmethod
    def from_config(cls, conf) -> "EncodeOptions":
        from spark_druid_olap_tpu.utils.config import (
            ENCODE_ENABLED, ENCODE_MIN_RATIO, ENCODE_RLE_MAX_RUN_FRAC)
        return cls(
            enabled=bool(conf.get(ENCODE_ENABLED)),
            min_ratio=float(conf.get(ENCODE_MIN_RATIO)),
            rle_max_run_frac=float(conf.get(ENCODE_RLE_MAX_RUN_FRAC)))


def choose_codec(arr: np.ndarray,
                 opts: EncodeOptions) -> Optional[str]:
    """Codec name for one column array, or None for raw. Pure function
    of (array, options) — ingest and compaction choose identically."""
    if not opts.enabled or arr.ndim != 1 or len(arr) == 0:
        return None
    if arr.dtype.kind == "f":
        return None
    sizes = CODECS.estimate_sizes(arr)
    if not sizes:
        return None
    if CODECS.RLE in sizes:
        # near-unique columns degenerate to ~1 run/row; drop the RLE
        # candidate before it can win on a fluke estimate
        runs = sizes[CODECS.RLE] // (arr.dtype.itemsize + 4)
        if runs > opts.rle_max_run_frac * len(arr):
            sizes.pop(CODECS.RLE)
    if not sizes:
        return None
    codec = min(sizes, key=lambda c: (sizes[c], c))
    best = max(1, sizes[codec])
    if arr.nbytes / best < max(1.0, opts.min_ratio):
        return None
    return codec


def annotate_datasource(ds, opts: Optional[EncodeOptions] = None) -> Dict[str, str]:
    """Cheap ingest-time hints: codec candidates derivable WITHOUT a
    data pass (dictionary cardinality -> bitpack width; bool validity ->
    bitpack). Stored as ``ds.encodings`` for the cost model and
    observability; the checkpoint-time chooser (which sees the actual
    arrays) remains authoritative and re-runs ``choose_codec`` per blob."""
    hints: Dict[str, str] = {}
    for name, d in ds.dims.items():
        if d.code_bits < 8 * d.data_dtype().itemsize:
            hints[name] = CODECS.BITPACK
        if d.has_nulls():
            hints["__nulls__" + name] = CODECS.BITPACK
    for name, m in ds.metrics.items():
        if m.has_nulls():
            hints["__nulls__" + name] = CODECS.BITPACK
    if ds.time is not None:
        # ingest time-sorts, so days are monotone by construction
        hints[ds.time.name] = CODECS.FORDELTA
    ds.encodings = hints
    return hints
