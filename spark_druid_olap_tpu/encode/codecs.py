"""Column codecs: bit-exact encode/decode between raw little-endian
arrays and compressed byte payloads.

Druid segments store compressed columns (LZ4-framed dictionary codes,
RLE bitmaps, delta-packed timestamps); the reference repo delegated all
of that to the external Druid cluster. This module is the in-tree
replacement, restricted to codecs whose DECODED form is bit-identical
to the raw path — compression must never change an answer:

========  ====================================================
codec     layout (all integers little-endian, numpy semantics)
========  ====================================================
raw       ``arr.tobytes()`` — the identity codec (per-segment
          fallback when a chosen codec fails to shrink a chunk)
bitpack   frame-of-reference + fixed-width bit packing:
          ``packbits(arr - ref, bits)`` where ``bits`` covers
          ``max - min``. Dictionary codes, bools (1 bit), and
          narrow-range LONG metrics. Order-preserving — code
          compares stay valid on the decoded form.
rle       run-length runs: ``values[R] || lengths[R]`` (lengths
          int32). Sorted / low-cardinality columns.
fordelta  frame-of-reference + delta for monotone arrays (time
          days): first value + bit-packed ``diff(arr) - dmin``.
========  ====================================================

Every header is a small JSON-able dict carrying the codec name ``c``,
row count ``n``, logical dtype ``dt``, per-codec parameters, and (for
integer codecs) the chunk's value bounds ``vmin``/``vmax`` — zone maps
read straight off the header, so planning never decodes a payload.

Floats are never encoded (raw only): reordering or re-deriving float
payloads risks the bit-exactness contract this engine is built on.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import numpy as np

#: bump when a header/payload layout changes shape (the manifest's
#: ``encoding`` block records it; loaders reject newer versions)
ENCODING_VERSION = 1

RAW = "raw"
BITPACK = "bitpack"
RLE = "rle"
FORDELTA = "fordelta"

CODECS = (RAW, BITPACK, RLE, FORDELTA)

#: run lengths are stored i32 — a single segment never holds 2^31 rows
_LEN_DTYPE = np.dtype("<i4")


class EncodingError(ValueError):
    """A payload/header failed structural validation at decode time."""


# -- fixed-width bit packing (the primitive under bitpack + fordelta) ---------

def _pack_bits(vals: np.ndarray, bits: int) -> bytes:
    """Pack non-negative ints < 2**bits at ``bits`` per value, little
    bit order (value i occupies bits [i*bits, (i+1)*bits))."""
    if len(vals) == 0:
        return b""
    v = vals.astype(np.uint64, copy=False)
    shifts = np.arange(bits, dtype=np.uint64)
    m = ((v[:, None] >> shifts) & np.uint64(1)).astype(np.uint8)
    return np.packbits(m.reshape(-1), bitorder="little").tobytes()


def _unpack_bits(buf, n: int, bits: int) -> np.ndarray:
    """Inverse of :func:`_pack_bits` -> uint64[n]."""
    if n == 0:
        return np.empty(0, dtype=np.uint64)
    total = n * bits
    raw = np.frombuffer(buf, dtype=np.uint8)
    if len(raw) * 8 < total:
        raise EncodingError(
            f"bitpack payload: {len(raw)} bytes < {n} x {bits} bits")
    b = np.unpackbits(raw, count=total, bitorder="little")
    m = b.reshape(n, bits).astype(np.uint64)
    shifts = np.arange(bits, dtype=np.uint64)
    out = np.zeros(n, dtype=np.uint64)
    for j in range(bits):
        out |= m[:, j] << shifts[j]
    return out


def _as_int64(arr: np.ndarray) -> np.ndarray:
    """Lossless view of an int/bool array as int64 work values."""
    if arr.dtype.kind == "b":
        return arr.astype(np.int64)
    if arr.dtype.kind == "u" and arr.dtype.itemsize == 8:
        # uint64 > 2^63-1 would wrap; engine columns never store u8,
        # but refuse loudly rather than corrupt
        if len(arr) and int(arr.max()) > np.iinfo(np.int64).max:
            raise EncodingError("uint64 values exceed int64 range")
    return arr.astype(np.int64)


def _restore_dtype(vals64: np.ndarray, dt: np.dtype) -> np.ndarray:
    if dt.kind == "b":
        return vals64.astype(bool)
    return vals64.astype(dt)


# -- per-codec encode ---------------------------------------------------------

def _header(codec: str, arr: np.ndarray, **params) -> dict:
    h = {"c": codec, "n": int(len(arr)), "dt": arr.dtype.str}
    h.update(params)
    return h


def encode_raw(arr: np.ndarray) -> Tuple[bytes, dict]:
    return arr.tobytes(), _header(RAW, arr)


def encode_bitpack(arr: np.ndarray) -> Tuple[bytes, dict]:
    v = _as_int64(arr)
    if len(v) == 0:
        return b"", _header(BITPACK, arr, ref=0, bits=1, vmin=None,
                            vmax=None)
    vmin, vmax = int(v.min()), int(v.max())
    bits = max(1, int(vmax - vmin).bit_length())
    payload = _pack_bits((v - vmin).astype(np.uint64), bits)
    return payload, _header(BITPACK, arr, ref=vmin, bits=bits,
                            vmin=vmin, vmax=vmax)


def encode_rle(arr: np.ndarray) -> Tuple[bytes, dict]:
    v = _as_int64(arr)
    if len(v) == 0:
        return b"", _header(RLE, arr, runs=0, vmin=None, vmax=None)
    change = np.flatnonzero(np.diff(v)) + 1
    starts = np.concatenate([[0], change])
    lengths = np.diff(np.concatenate([starts, [len(v)]]))
    values = arr[starts]                      # logical dtype run values
    payload = values.tobytes() + lengths.astype(_LEN_DTYPE).tobytes()
    return payload, _header(RLE, arr, runs=int(len(starts)),
                            vmin=int(v.min()), vmax=int(v.max()))


def encode_fordelta(arr: np.ndarray) -> Tuple[bytes, dict]:
    v = _as_int64(arr)
    if len(v) == 0:
        return b"", _header(FORDELTA, arr, first=0, dmin=0, bits=1,
                            vmin=None, vmax=None)
    first = int(v[0])
    d = np.diff(v)
    dmin = int(d.min()) if len(d) else 0
    dmax = int(d.max()) if len(d) else 0
    bits = max(1, int(dmax - dmin).bit_length())
    payload = _pack_bits((d - dmin).astype(np.uint64), bits)
    return payload, _header(FORDELTA, arr, first=first, dmin=dmin,
                            bits=bits, vmin=int(v.min()),
                            vmax=int(v.max()))


_ENCODERS = {RAW: encode_raw, BITPACK: encode_bitpack, RLE: encode_rle,
             FORDELTA: encode_fordelta}


def encode_array(arr: np.ndarray, codec: str) -> Tuple[bytes, dict]:
    """Encode one 1-D array chunk -> (payload bytes, JSON-able header).
    The caller (not this function) decides whether the result is worth
    keeping — see :func:`encode_chunk`."""
    if arr.ndim != 1:
        raise EncodingError(f"encode expects 1-D chunks, got {arr.shape}")
    try:
        enc = _ENCODERS[codec]
    except KeyError:
        raise EncodingError(f"unknown codec {codec!r}") from None
    return enc(arr)


def encode_chunk(arr: np.ndarray, codec: str) -> Tuple[bytes, dict]:
    """Encode with a per-chunk raw fallback: if the chosen codec fails
    to shrink THIS chunk (adversarial cardinality, degenerate runs) the
    chunk stays raw — a column-level choice never inflates a segment."""
    if codec == RAW:
        return encode_raw(arr)
    payload, header = encode_array(arr, codec)
    if len(payload) >= arr.nbytes:
        return encode_raw(arr)
    return payload, header


# -- decode -------------------------------------------------------------------

def decode_array(buf, header: dict) -> np.ndarray:
    """Decode a payload back to its raw little-endian array. Always
    returns a fresh writable array of the header's logical dtype;
    bit-identical to the chunk that was encoded."""
    codec = header.get("c")
    n = int(header["n"])
    dt = np.dtype(header["dt"])
    if codec == RAW:
        out = np.frombuffer(buf, dtype=dt, count=n)
        return out.copy()
    if codec == BITPACK:
        vals = _unpack_bits(buf, n, int(header["bits"])).astype(np.int64)
        vals += int(header["ref"])
        return _restore_dtype(vals, dt)
    if codec == RLE:
        runs = int(header["runs"])
        mv = memoryview(np.frombuffer(buf, dtype=np.uint8))
        vbytes = runs * dt.itemsize
        if len(mv) != vbytes + runs * _LEN_DTYPE.itemsize:
            raise EncodingError(
                f"rle payload: {len(mv)} bytes for {runs} runs of {dt}")
        values = np.frombuffer(mv[:vbytes], dtype=dt)
        lengths = np.frombuffer(mv[vbytes:], dtype=_LEN_DTYPE)
        if runs and int(lengths.sum()) != n:
            raise EncodingError("rle payload: run lengths do not sum to n")
        return np.repeat(values, lengths) if runs \
            else np.empty(0, dtype=dt)
    if codec == FORDELTA:
        if n == 0:
            return np.empty(0, dtype=dt)
        d = _unpack_bits(buf, n - 1, int(header["bits"])).astype(np.int64)
        d += int(header["dmin"])
        out = np.empty(n, dtype=np.int64)
        out[0] = int(header["first"])
        np.cumsum(d, out=out[1:]) if n > 1 else None
        out[1:] += int(header["first"])
        return _restore_dtype(out, dt)
    raise EncodingError(f"unknown codec {codec!r}")


def decoded_nbytes(header: dict) -> int:
    """Logical (decoded) byte size of a chunk, from its header alone."""
    return int(header["n"]) * np.dtype(header["dt"]).itemsize


def header_bounds(header: dict) -> Optional[Tuple[int, int]]:
    """(vmin, vmax) of an integer chunk without touching the payload —
    the encoded-domain zone map. None when the codec carries no bounds
    (raw/float) or the chunk is empty."""
    vmin, vmax = header.get("vmin"), header.get("vmax")
    if vmin is None or vmax is None:
        return None
    return int(vmin), int(vmax)


def rle_runs(buf, header: dict) -> Tuple[np.ndarray, np.ndarray]:
    """(run values, run lengths) of an RLE chunk WITHOUT expanding to
    rows — the encoded form ``ops/groupby.py:run_weighted_partials``
    aggregates directly (count partials are the run lengths; sum
    partials multiply run values by run length)."""
    if header.get("c") != RLE:
        raise EncodingError(f"not an rle chunk: {header.get('c')!r}")
    dt = np.dtype(header["dt"])
    runs = int(header["runs"])
    mv = memoryview(np.frombuffer(buf, dtype=np.uint8))
    vbytes = runs * dt.itemsize
    values = np.frombuffer(mv[:vbytes], dtype=dt).copy()
    lengths = np.frombuffer(mv[vbytes:], dtype=_LEN_DTYPE).astype(np.int64)
    return values, lengths


# -- analytic size estimates (the chooser's input; no encode performed) -------

def estimate_sizes(arr: np.ndarray) -> Dict[str, int]:
    """Estimated encoded payload bytes per eligible codec for one whole
    column (one O(n) pass: min/max, run count, monotonicity). Floats
    and empty arrays return {} — raw only."""
    if arr.ndim != 1 or len(arr) == 0 or arr.dtype.kind == "f":
        return {}
    v = _as_int64(arr)
    n = len(v)
    out: Dict[str, int] = {}
    vmin, vmax = int(v.min()), int(v.max())
    bits = max(1, int(vmax - vmin).bit_length())
    out[BITPACK] = (n * bits + 7) // 8
    d = np.diff(v)
    runs = 1 + int(np.count_nonzero(d))
    out[RLE] = runs * (arr.dtype.itemsize + _LEN_DTYPE.itemsize)
    if n > 1 and bool((d >= 0).all()):
        dmin, dmax = int(d.min()), int(d.max())
        dbits = max(1, int(dmax - dmin).bit_length())
        out[FORDELTA] = ((n - 1) * dbits + 7) // 8
    return out
