"""Dictionary-predicate rewrite: string predicates -> code-domain tests.

Dimension columns never store strings — rows hold integer codes into a
global SORTED dictionary, and that dictionary order is exactly
lexicographic string order. Every string predicate therefore has an
integer-domain equivalent that evaluates on the ENCODED form (plain or
bit-packed codes decode to the same integers):

- equality     -> one code compare (``selector_code``)
- range/BETWEEN-> a half-open code interval (``bound_code_range``)
- IN           -> a bool mask over the dictionary, gathered by code
- LIKE/regex/contains -> the same mask, built by running the pattern
  over the O(cardinality) dictionary instead of O(rows) strings

``ops/filters.py`` lowers through these helpers, so the device masks
NEVER materialize a string column; the helpers are also pure host
functions so tests can verify the rewrite against brute-force string
evaluation on commuted / NOT / OR filter trees.
"""

from __future__ import annotations

import re
from typing import Iterable, Optional, Tuple

import numpy as np


def selector_code(dim, value: str) -> int:
    """The dictionary code of ``value``, or -1 when absent (the caller
    lowers a miss to a constant-false mask — no scan at all)."""
    return int(dim.code_of(str(value)))


def bound_code_range(dim, lower: Optional[str], upper: Optional[str],
                     lower_strict: bool, upper_strict: bool
                     ) -> Tuple[int, int]:
    """Half-open code interval [lo, hi) equivalent to the string bound —
    sorted global dictionaries make lexicographic bounds code ranges.
    lo >= hi means the bound selects nothing."""
    lo, hi = dim.code_range(
        None if lower is None else str(lower),
        None if upper is None else str(upper),
        lower_strict, upper_strict)
    return int(lo), int(hi)


def in_code_mask(dictionary: np.ndarray, values: Iterable) -> np.ndarray:
    """bool[cardinality] membership mask: mask[code] == (dict[code] in
    values). Gathering it by code is the IN filter on encoded data."""
    return np.isin(np.asarray(dictionary).astype(str),
                   np.array([str(v) for v in values]))


def pattern_code_mask(dictionary: np.ndarray, kind: str,
                      pattern: str, like_to_regex=None) -> np.ndarray:
    """bool[cardinality] mask for LIKE / regex / contains patterns,
    evaluated once per dictionary entry."""
    vals = np.asarray(dictionary)
    if kind == "like":
        if like_to_regex is None:
            from spark_druid_olap_tpu.ops.expr_compile import like_to_regex
        rx = re.compile(like_to_regex(pattern))
        return np.array([bool(rx.match(s)) for s in vals])
    if kind == "regex":
        rx = re.compile(pattern)
        return np.array([bool(rx.search(s)) for s in vals])
    if kind == "contains":
        return np.array([pattern in s for s in vals])
    raise ValueError(f"pattern kind {kind!r}")


def code_mask_bounds(mask: np.ndarray) -> Tuple[int, int]:
    """Tightest [lo, hi) code interval covering a membership mask —
    lets a sparse IN over a contiguous dictionary slice degrade to the
    two-compare range test instead of a gather."""
    idx = np.flatnonzero(mask)
    if len(idx) == 0:
        return 0, 0
    return int(idx[0]), int(idx[-1]) + 1
