"""Compressed columnar subsystem: encoded segments on disk and in the
tiered hot set, with compute pushed onto the encoded form.

Layers (see docs/ENCODING.md for the full matrix):

- :mod:`codecs` — bit-exact encode/decode (raw, bitpack, rle, fordelta)
- :mod:`chooser` — per-column codec choice at ingest/checkpoint time
- :mod:`predicates` — dictionary-predicate rewrite (string filters ->
  code-domain tests; consumed by ops/filters.py)
- :mod:`exec` — encoded-domain aggregation and pruning (RLE run
  aggregation, header zone maps, FoR-domain interval pruning)

The on-disk integration lives in persist/snapshot.py (the manifest's
``encoding`` block) and tier/ (encoded BlobRef faulting); everything
here is pure numpy with no engine dependencies above ops/.
"""

from spark_druid_olap_tpu.encode import codecs, chooser, predicates  # noqa: F401
