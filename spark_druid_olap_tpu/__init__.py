"""spark_druid_olap_tpu — a TPU-native OLAP acceleration framework.

A ground-up rebuild of the capabilities of SparklineData's ``spark-druid-olap``
(the Sparkline BI Accelerator, reference at ``/root/reference``): a SQL front
end with an extensible rewrite engine that turns star-schema analytic queries
(project/filter pushdown, star-join collapse, group-by / grouping sets,
approximate count-distinct, sort/limit/topN) into plans executed by an
**in-tree columnar engine on TPU** — where the reference delegated execution to
an external Druid cluster over HTTP (reference:
``org/sparklinedata/druid/client/DruidClient.scala``), here the engine is
JAX/XLA/Pallas: dictionary-encoded column chunks live in TPU HBM as
time-sharded segments, scan-filter-aggregate kernels replace Druid
historicals, and ICI collectives replace the broker's scatter/gather.

Public API::

    import spark_druid_olap_tpu as sdot
    ctx = sdot.Context()
    ctx.ingest_dataframe("lineitem", df, time_column="l_shipdate")
    result = ctx.sql("SELECT l_returnflag, sum(l_quantity) FROM lineitem GROUP BY 1")
    result.to_pandas()

Layer map (mirrors SURVEY.md §1, re-seamed for TPU):

==========  ==============================  =========================================
Layer       Package                         Reference counterpart
==========  ==============================  =========================================
server      ``server/``                     thriftserver (``HiveThriftServer2.scala``)
session     ``context.py``                  ``SPLSessionState`` / ``ModuleLoader``
sql         ``sql/``                        ``SparklineDataParser`` + Spark SQL parser
planner     ``planner/``                    ``DruidPlanner``/``DruidStrategy`` + transforms
IR          ``ir/``                         ``DruidQuerySpec``/``DruidQueryBuilder``
kernels     ``ops/``                        Druid historical scan/agg engine (external)
segments    ``segment/``                    Druid segment store (external)
parallel    ``parallel/``                   broker scatter/gather + ``DruidRDD``
metadata    ``metadata/``                   ``org/sparklinedata/druid/metadata/``
utils       ``utils/``                      conf/retry/logging shims
==========  ==============================  =========================================
"""

from spark_druid_olap_tpu.context import Context
from spark_druid_olap_tpu.utils.config import Config

__version__ = "0.1.0"

__all__ = ["Context", "Config", "__version__"]
