"""Star Schema Benchmark (SSB) workload — generator, star schema, and the
13 standard queries.

BASELINE.json config 3 ("SSB SF30 — denormalized wide fact table"). The
reference demonstrates its BI acceleration on star-schema TPC-H; SSB is the
canonical star-schema benchmark (O'Neil et al.) with a lineorder fact and
date/customer/supplier/part dimensions. All 13 queries are pure star joins
with dimension predicates + grouped aggregation, so every one should
collapse onto the flat index and push down to the device engine.

Synthetic generator (same spirit as tools/tpch.py): value distributions
follow the SSB spec's shapes (25 nations in 5 regions, 10 cities per
nation, MFGR#category/brand hierarchy, 1992-1998 dates) at
``sf``-proportional row counts; it is a workload generator for
benchmarking, not a dbgen clone.
"""

from __future__ import annotations

from typing import Dict

import numpy as np
import pandas as pd

from spark_druid_olap_tpu.metadata.star import StarRelation, StarSchema

REGIONS = ["AFRICA", "AMERICA", "ASIA", "EUROPE", "MIDDLE EAST"]
NATIONS = {
    "AFRICA": ["ALGERIA", "ETHIOPIA", "KENYA", "MOROCCO", "MOZAMBIQUE"],
    "AMERICA": ["ARGENTINA", "BRAZIL", "CANADA", "PERU", "UNITED STATES"],
    "ASIA": ["CHINA", "INDIA", "INDONESIA", "JAPAN", "VIETNAM"],
    "EUROPE": ["FRANCE", "GERMANY", "ROMANIA", "RUSSIA", "UNITED KINGDOM"],
    "MIDDLE EAST": ["EGYPT", "IRAN", "IRAQ", "JORDAN", "SAUDI ARABIA"],
}
MONTHS = ["Jan", "Feb", "Mar", "Apr", "May", "Jun", "Jul", "Aug", "Sep",
          "Oct", "Nov", "Dec"]


def _nation_city(rng, n):
    region = rng.choice(REGIONS, n)
    nation = np.array([rng.choice(NATIONS[r]) for r in region], dtype=object)
    # SSB city = first 9 chars of nation + digit 0..9
    city = np.array([f"{nat[:9]:<9}{d}" for nat, d in
                     zip(nation, rng.integers(0, 10, n))], dtype=object)
    return region, nation, city


def _sizes(sf: float):
    n_lo = max(1000, int(6_000_000 * sf))
    n_cust = max(60, int(30_000 * sf))
    n_supp = max(40, int(2_000 * sf))
    n_part = max(80, int(200_000 * min(1.0, 1 + np.log2(max(sf, 1e-6)) / 10)
                         * sf + 2000 * (sf ** 0.5)))
    return n_lo, n_cust, n_supp, n_part


def _gen_dims(rng, sf: float) -> Dict[str, pd.DataFrame]:
    _, n_cust, n_supp, n_part = _sizes(sf)

    dates = pd.date_range("1992-01-01", "1998-12-31", freq="D")
    nd = len(dates)
    date = pd.DataFrame({
        "d_datekey": dates,
        "d_year": dates.year.astype(np.int64),
        "d_month": np.array([MONTHS[m - 1] for m in dates.month],
                            dtype=object),
        "d_yearmonthnum": (dates.year * 100 + dates.month).astype(np.int64),
        "d_yearmonth": np.array(
            [f"{MONTHS[m - 1]}{y}" for y, m in zip(dates.year, dates.month)],
            dtype=object),
        "d_daynuminweek": (dates.dayofweek + 1).astype(np.int64),
        "d_monthnuminyear": dates.month.astype(np.int64),
        "d_weeknuminyear": pd.Index(dates.isocalendar().week).astype(np.int64),
        "d_sellingseason": np.array(
            ["Winter" if m in (12, 1, 2) else "Spring" if m in (3, 4, 5)
             else "Summer" if m in (6, 7, 8) else "Fall"
             for m in dates.month], dtype=object),
    })

    creg, cnat, ccity = _nation_city(rng, n_cust)
    customer = pd.DataFrame({
        "c_custkey": np.arange(1, n_cust + 1, dtype=np.int64),
        "c_name": [f"Customer#{i:09d}" for i in range(1, n_cust + 1)],
        "c_city": ccity, "c_nation": cnat, "c_region": creg,
        "c_mktsegment": rng.choice(["AUTOMOBILE", "BUILDING", "FURNITURE",
                                    "MACHINERY", "HOUSEHOLD"], n_cust),
    })

    sreg, snat, scity = _nation_city(rng, n_supp)
    supplier = pd.DataFrame({
        "s_suppkey": np.arange(1, n_supp + 1, dtype=np.int64),
        "s_name": [f"Supplier#{i:09d}" for i in range(1, n_supp + 1)],
        "s_city": scity, "s_nation": snat, "s_region": sreg,
    })

    mfgr = rng.integers(1, 6, n_part)
    cat = mfgr * 10 + rng.integers(1, 6, n_part)
    brand = cat * 100 + rng.integers(1, 41, n_part)
    part = pd.DataFrame({
        "p_partkey": np.arange(1, n_part + 1, dtype=np.int64),
        "p_name": rng.choice(["almond", "antique", "aquamarine", "azure",
                              "beige", "bisque", "black", "blanched"],
                             n_part),
        "p_mfgr": np.array([f"MFGR#{m}" for m in mfgr], dtype=object),
        "p_category": np.array([f"MFGR#{c}" for c in cat], dtype=object),
        "p_brand1": np.array([f"MFGR#{b}" for b in brand], dtype=object),
        "p_color": rng.choice(["red", "green", "blue", "ivory", "maroon"],
                              n_part),
        "p_size": rng.integers(1, 51, n_part).astype(np.int64),
    })
    return {"date": date, "customer": customer, "supplier": supplier,
            "part": part}


def _gen_lineorder(rng, dims: Dict[str, pd.DataFrame], n_lo: int,
                   start_key: int = 1) -> pd.DataFrame:
    dates = pd.DatetimeIndex(dims["date"]["d_datekey"])
    nd = len(dates)
    n_cust = len(dims["customer"])
    n_supp = len(dims["supplier"])
    n_part = len(dims["part"])
    od = rng.integers(0, nd, n_lo)
    qty = rng.integers(1, 51, n_lo).astype(np.int64)
    eprice = np.round(rng.uniform(90.0, 105_000.0, n_lo), 2)
    disc = rng.integers(0, 11, n_lo).astype(np.int64)
    rev = np.round(eprice * (100 - disc) / 100.0, 2)
    return pd.DataFrame({
        "lo_orderkey": np.arange(start_key, start_key + n_lo,
                                 dtype=np.int64),
        "lo_custkey": rng.integers(1, n_cust + 1, n_lo).astype(np.int64),
        "lo_partkey": rng.integers(1, n_part + 1, n_lo).astype(np.int64),
        "lo_suppkey": rng.integers(1, n_supp + 1, n_lo).astype(np.int64),
        "lo_orderdate": dates[od],
        "lo_quantity": qty,
        "lo_extendedprice": eprice,
        "lo_discount": disc,
        "lo_revenue": rev,
        "lo_supplycost": np.round(rng.uniform(50.0, 60_000.0, n_lo), 2),
        "lo_shipmode": rng.choice(["AIR", "FOB", "MAIL", "RAIL", "SHIP",
                                   "TRUCK", "REG AIR"], n_lo),
    })


def generate(sf: float = 0.01, seed: int = 20260729) -> Dict[str, pd.DataFrame]:
    rng = np.random.default_rng(seed)
    n_lo, _, _, _ = _sizes(sf)
    dims = _gen_dims(rng, sf)
    lineorder = _gen_lineorder(rng, dims, n_lo)
    return {"lineorder": lineorder, **dims}


def generate_stream(sf: float, lineorder_path: str, seed: int = 20260729,
                    batch_rows: int = 1 << 22):
    """Out-of-core generator for SF where the 6M*sf-row lineorder (and a
    fortiori the ~30-column flat index) must not materialize in pandas —
    SF30 is 180M rows. Dimensions stay in memory (largest is part, ~6M
    rows at SF30); lineorder is generated chunk-by-chunk straight into a
    Parquet file. Returns (dims, n_lineorder_rows)."""
    import pyarrow as pa
    import pyarrow.parquet as pq
    rng = np.random.default_rng(seed)
    n_lo, _, _, _ = _sizes(sf)
    dims = _gen_dims(rng, sf)
    writer = None
    written = 0
    try:
        while written < n_lo:
            n = min(int(batch_rows), n_lo - written)
            chunk = _gen_lineorder(rng, dims, n, start_key=written + 1)
            table = pa.Table.from_pandas(chunk, preserve_index=False)
            if writer is None:
                writer = pq.ParquetWriter(lineorder_path, table.schema)
            writer.write_table(table)
            written += n
    finally:
        if writer is not None:
            writer.close()
    return dims, written


def flatten_stream(dims: Dict[str, pd.DataFrame], lineorder_path: str,
                   out_path: str, batch_rows: int = 1 << 20) -> int:
    """Chunked star-join of the streamed lineorder against the in-memory
    dimensions (same machinery as the TPC-H SF10 out-of-core flatten).
    Returns flat rows written."""
    from spark_druid_olap_tpu.segment.stream_ingest import (
        flatten_join_stream)
    joins = [
        (dims["date"], "lo_orderdate", "d_datekey"),
        (dims["customer"], "lo_custkey", "c_custkey"),
        (dims["supplier"], "lo_suppkey", "s_suppkey"),
        (dims["part"], "lo_partkey", "p_partkey"),
    ]
    return flatten_join_stream(lineorder_path, out_path, joins,
                               batch_rows=batch_rows)


def flatten(tables) -> pd.DataFrame:
    df = tables["lineorder"].merge(tables["date"], left_on="lo_orderdate",
                                   right_on="d_datekey")
    df = df.merge(tables["customer"], left_on="lo_custkey",
                  right_on="c_custkey")
    df = df.merge(tables["supplier"], left_on="lo_suppkey",
                  right_on="s_suppkey")
    df = df.merge(tables["part"], left_on="lo_partkey", right_on="p_partkey")
    return df.reset_index(drop=True)


def star_schema(flat_datasource: str = "ssb_flat") -> StarSchema:
    return StarSchema("lineorder", flat_datasource, [
        StarRelation("lineorder", "date", (("lo_orderdate", "d_datekey"),)),
        StarRelation("lineorder", "customer",
                     (("lo_custkey", "c_custkey"),)),
        StarRelation("lineorder", "supplier",
                     (("lo_suppkey", "s_suppkey"),)),
        StarRelation("lineorder", "part", (("lo_partkey", "p_partkey"),)),
    ])


def setup_context(ctx, sf: float = 0.01, seed: int = 20260729,
                  target_rows: int = 1 << 20, flat_only: bool = False):
    tables = generate(sf, seed)
    flat = flatten(tables)
    ctx.ingest_dataframe("ssb_flat", flat, time_column="lo_orderdate",
                         target_rows=target_rows)
    if not flat_only:
        for name, df in tables.items():
            tcol = {"lineorder": "lo_orderdate"}.get(name)
            ctx.ingest_dataframe(name, df, time_column=tcol,
                                 target_rows=target_rows)
    ctx.register_star_schema(star_schema("ssb_flat"))
    return tables, flat


QUERIES: Dict[str, str] = {
    "q1.1": """
        select sum(lo_extendedprice * lo_discount) as revenue
        from lineorder join date on lo_orderdate = d_datekey
        where d_year = 1993 and lo_discount between 1 and 3
              and lo_quantity < 25
    """,
    "q1.2": """
        select sum(lo_extendedprice * lo_discount) as revenue
        from lineorder join date on lo_orderdate = d_datekey
        where d_yearmonthnum = 199401 and lo_discount between 4 and 6
              and lo_quantity between 26 and 35
    """,
    "q1.3": """
        select sum(lo_extendedprice * lo_discount) as revenue
        from lineorder join date on lo_orderdate = d_datekey
        where d_weeknuminyear = 6 and d_year = 1994
              and lo_discount between 5 and 7
              and lo_quantity between 26 and 35
    """,
    "q2.1": """
        select sum(lo_revenue) as lo_revenue, d_year, p_brand1
        from lineorder join date on lo_orderdate = d_datekey
             join part on lo_partkey = p_partkey
             join supplier on lo_suppkey = s_suppkey
        where p_category = 'MFGR#12' and s_region = 'AMERICA'
        group by d_year, p_brand1 order by d_year, p_brand1
    """,
    "q2.2": """
        select sum(lo_revenue) as lo_revenue, d_year, p_brand1
        from lineorder join date on lo_orderdate = d_datekey
             join part on lo_partkey = p_partkey
             join supplier on lo_suppkey = s_suppkey
        where p_brand1 between 'MFGR#2221' and 'MFGR#2228'
              and s_region = 'ASIA'
        group by d_year, p_brand1 order by d_year, p_brand1
    """,
    "q2.3": """
        select sum(lo_revenue) as lo_revenue, d_year, p_brand1
        from lineorder join date on lo_orderdate = d_datekey
             join part on lo_partkey = p_partkey
             join supplier on lo_suppkey = s_suppkey
        where p_brand1 = 'MFGR#2239' and s_region = 'EUROPE'
        group by d_year, p_brand1 order by d_year, p_brand1
    """,
    "q3.1": """
        select c_nation, s_nation, d_year, sum(lo_revenue) as lo_revenue
        from lineorder join date on lo_orderdate = d_datekey
             join customer on lo_custkey = c_custkey
             join supplier on lo_suppkey = s_suppkey
        where c_region = 'ASIA' and s_region = 'ASIA'
              and d_year >= 1992 and d_year <= 1997
        group by c_nation, s_nation, d_year
        order by d_year asc, lo_revenue desc
    """,
    "q3.2": """
        select c_city, s_city, d_year, sum(lo_revenue) as lo_revenue
        from lineorder join date on lo_orderdate = d_datekey
             join customer on lo_custkey = c_custkey
             join supplier on lo_suppkey = s_suppkey
        where c_nation = 'UNITED STATES' and s_nation = 'UNITED STATES'
              and d_year >= 1992 and d_year <= 1997
        group by c_city, s_city, d_year
        order by d_year asc, lo_revenue desc
    """,
    "q3.3": """
        select c_city, s_city, d_year, sum(lo_revenue) as lo_revenue
        from lineorder join date on lo_orderdate = d_datekey
             join customer on lo_custkey = c_custkey
             join supplier on lo_suppkey = s_suppkey
        where (c_city = 'UNITED KI1' or c_city = 'UNITED KI5')
              and (s_city = 'UNITED KI1' or s_city = 'UNITED KI5')
              and d_year >= 1992 and d_year <= 1997
        group by c_city, s_city, d_year
        order by d_year asc, lo_revenue desc
    """,
    "q3.4": """
        select c_city, s_city, d_year, sum(lo_revenue) as lo_revenue
        from lineorder join date on lo_orderdate = d_datekey
             join customer on lo_custkey = c_custkey
             join supplier on lo_suppkey = s_suppkey
        where (c_city = 'UNITED KI1' or c_city = 'UNITED KI5')
              and (s_city = 'UNITED KI1' or s_city = 'UNITED KI5')
              and d_yearmonth = 'Dec1997'
        group by c_city, s_city, d_year
        order by d_year asc, lo_revenue desc
    """,
    "q4.1": """
        select d_year, c_nation,
               sum(lo_revenue - lo_supplycost) as profit
        from lineorder join date on lo_orderdate = d_datekey
             join customer on lo_custkey = c_custkey
             join supplier on lo_suppkey = s_suppkey
             join part on lo_partkey = p_partkey
        where c_region = 'AMERICA' and s_region = 'AMERICA'
              and (p_mfgr = 'MFGR#1' or p_mfgr = 'MFGR#2')
        group by d_year, c_nation order by d_year, c_nation
    """,
    "q4.2": """
        select d_year, s_nation, p_category,
               sum(lo_revenue - lo_supplycost) as profit
        from lineorder join date on lo_orderdate = d_datekey
             join customer on lo_custkey = c_custkey
             join supplier on lo_suppkey = s_suppkey
             join part on lo_partkey = p_partkey
        where c_region = 'AMERICA' and s_region = 'AMERICA'
              and (d_year = 1997 or d_year = 1998)
              and (p_mfgr = 'MFGR#1' or p_mfgr = 'MFGR#2')
        group by d_year, s_nation, p_category
        order by d_year, s_nation, p_category
    """,
    "q4.3": """
        select d_year, s_city, p_brand1,
               sum(lo_revenue - lo_supplycost) as profit
        from lineorder join date on lo_orderdate = d_datekey
             join customer on lo_custkey = c_custkey
             join supplier on lo_suppkey = s_suppkey
             join part on lo_partkey = p_partkey
        where s_nation = 'UNITED STATES'
              and (d_year = 1997 or d_year = 1998)
              and p_category = 'MFGR#14'
        group by d_year, s_city, p_brand1
        order by d_year, s_city, p_brand1
    """,
}
