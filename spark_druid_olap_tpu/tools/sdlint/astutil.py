"""Best-effort call-graph and type resolution over a :class:`Project`.

The resolution here is deliberately approximate (no execution, no
imports): names resolve through ``import`` statements, ``self.x``
through recorded attribute assignments, constructor parameters through
the types observed at the class's instantiation sites, and — as a last
resort — method calls through a project-unique method name. Anything
unresolvable is silently dropped: the passes built on top are designed
so an unresolved call can only *miss* a finding, never invent one.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from spark_druid_olap_tpu.tools.sdlint.core import Module, Project

# (module_name, qualname) — qualname is "func", "Class.method",
# "outer.inner" for nested defs, "Cls.meth.Nested.meth" for nested classes
FuncId = Tuple[str, str]
# (module_name, class_qualname)
TypeRef = Tuple[str, str]

_LOCK_FACTORIES = ("Lock", "RLock", "Condition")

# method names that collide with builtin container / io / threading
# protocols: never resolved through the unique-name fallback, because
# `self._entries.pop(...)` on an untyped dict would otherwise bind to
# the project's one class that happens to define `pop`
_FALLBACK_EXCLUDE = frozenset({
    "get", "set", "pop", "popitem", "update", "clear", "append", "add",
    "remove", "discard", "extend", "insert", "setdefault", "items",
    "keys", "values", "copy", "index", "count", "sort", "split", "join",
    "strip", "read", "write", "close", "open", "flush", "seek",
    "acquire", "release", "wait", "notify", "notify_all", "put",
    "start", "stop", "run", "join", "send", "recv", "encode", "decode",
})


def _threading_factory(call: ast.expr) -> Optional[str]:
    """'Lock'/'RLock'/'Condition' when ``call`` constructs one, handling
    both ``threading.Lock()`` and ``__import__("threading").Lock()``."""
    if not (isinstance(call, ast.Call)
            and isinstance(call.func, ast.Attribute)
            and call.func.attr in _LOCK_FACTORIES):
        return None
    base = call.func.value
    if isinstance(base, ast.Name) and base.id == "threading":
        return call.func.attr
    if (isinstance(base, ast.Call) and isinstance(base.func, ast.Name)
            and base.func.id == "__import__" and base.args
            and isinstance(base.args[0], ast.Constant)
            and base.args[0].value == "threading"):
        return call.func.attr
    return None


class ClassInfo:
    def __init__(self, module: str, qual: str, node: ast.ClassDef):
        self.module = module
        self.qual = qual            # dotted position, e.g. "SqlServer.start.Handler"
        self.node = node
        self.methods: Dict[str, ast.FunctionDef] = {}
        self.attr_types: Dict[str, TypeRef] = {}
        self.lock_attrs: Dict[str, str] = {}   # attr -> Lock/RLock/Condition
        # attr -> __init__ parameter name it was assigned from (resolved
        # against instantiation-site argument types in a second round)
        self.attr_from_param: Dict[str, str] = {}

    @property
    def ref(self) -> TypeRef:
        return (self.module, self.qual)


class ModuleInfo:
    def __init__(self, mod: Module):
        self.mod = mod
        # alias -> ("module", dotted) | ("symbol", dotted_module, symbol)
        self.imports: Dict[str, tuple] = {}
        self.functions: Dict[str, ast.FunctionDef] = {}   # top-level only
        self.classes: Dict[str, ClassInfo] = {}           # by qual AND bare name
        self.module_locks: Dict[str, str] = {}


class Index:
    """Project-wide symbol/type index + call resolution."""

    def __init__(self, project: Project):
        self.project = project
        self.modules: Dict[str, ModuleInfo] = {}
        # every function (incl. methods and nested defs), by FuncId
        self.functions: Dict[FuncId, ast.FunctionDef] = {}
        self.func_class: Dict[FuncId, Optional[ClassInfo]] = {}
        # method name -> FuncIds across all classes (fallback resolution)
        self.method_index: Dict[str, List[FuncId]] = {}
        for mod in project.modules.values():
            self._index_module(mod)
        # attr/type recording second: it resolves imports across modules,
        # so every module must be indexed first
        for mi in self.modules.values():
            for ci in set(mi.classes.values()):
                self._record_attrs(mi, ci)
        self._infer_ctor_param_types()

    # -- construction ----------------------------------------------------------
    def _index_module(self, mod: Module) -> None:
        mi = ModuleInfo(mod)
        self.modules[mod.name] = mi
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    mi.imports[a.asname or a.name.split(".")[0]] = \
                        ("module", a.name)
            elif isinstance(node, ast.ImportFrom) and node.module:
                base = node.module
                if node.level:      # relative: anchor inside the package
                    parts = mod.name.split(".")
                    parts = parts[: len(parts) - node.level]
                    base = ".".join(parts + [node.module])
                for a in node.names:
                    target = self.project.module_for_import(
                        f"{base}.{a.name}")
                    if target is not None:
                        mi.imports[a.asname or a.name] = \
                            ("module", f"{base}.{a.name}")
                    else:
                        mi.imports[a.asname or a.name] = \
                            ("symbol", base, a.name)
        self._index_body(mi, mod.tree.body, "", None, top=True)

    def _index_body(self, mi: ModuleInfo, body, prefix: str,
                    ci: Optional[ClassInfo], top: bool) -> None:
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = prefix + stmt.name
                fid = (mi.mod.name, qual)
                self.functions[fid] = stmt
                self.func_class[fid] = ci
                if ci is None and not prefix:
                    mi.functions.setdefault(stmt.name, stmt)
                direct_method = ci is not None and prefix == ci.qual + "."
                if direct_method and stmt.name not in ci.methods:
                    ci.methods[stmt.name] = stmt
                    self.method_index.setdefault(stmt.name, []).append(fid)
                # nested defs/classes live inside, with this fn's scope
                self._index_body(mi, stmt.body, qual + ".", ci, top=False)
            elif isinstance(stmt, ast.ClassDef):
                qual = prefix + stmt.name
                sub = ClassInfo(mi.mod.name, qual, stmt)
                mi.classes[qual] = sub
                mi.classes.setdefault(stmt.name, sub)
                self._index_body(mi, stmt.body, qual + ".", sub, top=False)
            elif top and isinstance(stmt, ast.Assign) \
                    and len(stmt.targets) == 1 \
                    and isinstance(stmt.targets[0], ast.Name):
                kind = _threading_factory(stmt.value)
                if kind is not None:
                    mi.module_locks[stmt.targets[0].id] = kind
            elif isinstance(stmt, (ast.If, ast.Try, ast.While, ast.For,
                                   ast.AsyncFor, ast.With, ast.AsyncWith)):
                # defs under guards/loops/with (e.g. `if pa is not None:`
                # fallbacks, a build closure inside a retry loop) are
                # still defs of the enclosing scope
                self._index_body(mi, stmt.body, prefix, ci, top)
                for h in getattr(stmt, "handlers", ()):
                    self._index_body(mi, h.body, prefix, ci, top)
                self._index_body(mi, getattr(stmt, "orelse", ()), prefix,
                                 ci, top)
                self._index_body(mi, getattr(stmt, "finalbody", ()),
                                 prefix, ci, top)

    def _record_attrs(self, mi: ModuleInfo, ci: ClassInfo) -> None:
        """``self.x = ...`` sites: lock factories, known-class
        constructions, and parameter pass-throughs."""
        for meth in set(ci.methods.values()):
            params = [a.arg for a in meth.args.args[1:]]
            for node in ast.walk(meth):
                if not (isinstance(node, ast.Assign)
                        and len(node.targets) == 1):
                    continue
                t = node.targets[0]
                if not (isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"):
                    continue
                kind = _threading_factory(node.value)
                if kind is not None:
                    ci.lock_attrs[t.attr] = kind
                    continue
                tr = self._type_of_construction(mi, node.value)
                if tr is not None:
                    ci.attr_types.setdefault(t.attr, tr)
                elif (meth.name == "__init__"
                      and isinstance(node.value, ast.Name)
                      and node.value.id in params):
                    ci.attr_from_param.setdefault(t.attr, node.value.id)

    def _type_of_construction(self, mi: ModuleInfo,
                              value: ast.expr) -> Optional[TypeRef]:
        """``ClassName(...)`` / ``alias.ClassName(...)`` -> TypeRef."""
        if not isinstance(value, ast.Call):
            return None
        f = value.func
        if isinstance(f, ast.Name):
            return self._class_named(mi, f.id)
        if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name):
            imp = mi.imports.get(f.value.id)
            if imp and imp[0] == "module":
                tm = self.project.module_for_import(imp[1])
                if tm is not None:
                    tci = self.modules[tm.name].classes.get(f.attr)
                    if tci is not None:
                        return tci.ref
        return None

    def _class_named(self, mi: ModuleInfo, name: str) -> Optional[TypeRef]:
        ci = mi.classes.get(name)
        if ci is not None:
            return ci.ref
        imp = mi.imports.get(name)
        if imp and imp[0] == "symbol":
            tm = self.project.module_for_import(imp[1])
            if tm is not None:
                tci = self.modules[tm.name].classes.get(imp[2])
                if tci is not None:
                    return tci.ref
        return None

    def _infer_ctor_param_types(self) -> None:
        """Round 2: for ``self.engine = engine`` style pass-throughs,
        look at every ``Cls(...)`` instantiation in the project and, when
        all sites agree on the argument's type, adopt it."""
        wanted: Dict[TypeRef, Dict[str, str]] = {}
        for mi in self.modules.values():
            for ci in set(mi.classes.values()):
                if ci.attr_from_param:
                    wanted[ci.ref] = ci.attr_from_param
        if not wanted:
            return
        observed: Dict[Tuple[TypeRef, str], Set[TypeRef]] = {}
        for fid, fn in self.functions.items():
            mi = self.modules[fid[0]]
            ci = self.func_class[fid]
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                tr = self._type_of_construction(mi, node)
                if tr is None or tr not in wanted:
                    continue
                init = self.class_info(tr).methods.get("__init__")
                if init is None:
                    continue
                pnames = [a.arg for a in init.args.args[1:]]
                bound: Dict[str, ast.expr] = {}
                for i, a in enumerate(node.args):
                    if i < len(pnames):
                        bound[pnames[i]] = a
                for kw in node.keywords:
                    if kw.arg:
                        bound[kw.arg] = kw.value
                for attr, pname in wanted[tr].items():
                    a = bound.get(pname)
                    if a is None:
                        continue
                    at = self._static_expr_type(mi, ci, a)
                    if at is not None:
                        observed.setdefault((tr, attr), set()).add(at)
        for (tr, attr), types in observed.items():
            if len(types) == 1:
                self.class_info(tr).attr_types.setdefault(
                    attr, next(iter(types)))

    def _static_expr_type(self, mi: ModuleInfo, ci: Optional[ClassInfo],
                          expr: ast.expr) -> Optional[TypeRef]:
        if isinstance(expr, ast.Name) and expr.id == "self" \
                and ci is not None:
            return ci.ref
        if isinstance(expr, ast.Attribute) \
                and isinstance(expr.value, ast.Name) \
                and expr.value.id == "self" and ci is not None:
            return ci.attr_types.get(expr.attr)
        return self._type_of_construction(mi, expr)

    # -- lookups ---------------------------------------------------------------
    def class_info(self, ref: TypeRef) -> ClassInfo:
        return self.modules[ref[0]].classes[ref[1]]

    def func_node(self, fid: FuncId) -> Optional[ast.FunctionDef]:
        return self.functions.get(fid)

    # -- expression typing inside a function body ------------------------------
    def local_types(self, mi: ModuleInfo, ci: Optional[ClassInfo],
                    fn: ast.FunctionDef) -> Dict[str, TypeRef]:
        """Locals with inferable types: ``eng = self.engine``,
        ``x = Cls(...)``; single forward pass, last assignment wins."""
        out: Dict[str, TypeRef] = {}
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                tr = self._expr_type(mi, ci, node.value, out)
                if tr is not None:
                    out[node.targets[0].id] = tr
        return out

    def _expr_type(self, mi: ModuleInfo, ci: Optional[ClassInfo],
                   expr: ast.expr,
                   local: Dict[str, TypeRef]) -> Optional[TypeRef]:
        if isinstance(expr, ast.Name):
            if expr.id == "self" and ci is not None:
                return ci.ref
            return local.get(expr.id)
        if isinstance(expr, ast.Attribute):
            base = self._expr_type(mi, ci, expr.value, local)
            if base is not None:
                return self.class_info(base).attr_types.get(expr.attr)
            return None
        return self._type_of_construction(mi, expr)

    # -- call resolution -------------------------------------------------------
    def resolve_call(self, mi: ModuleInfo, ci: Optional[ClassInfo],
                     call: ast.Call, local: Dict[str, TypeRef],
                     enclosing_qual: str = "",
                     unique_fallback: bool = False) -> List[FuncId]:
        """Call expression -> candidate FuncIds (empty when external)."""
        f = call.func
        if isinstance(f, ast.Name):
            nested = self._nested_def(mi, enclosing_qual, f.id)
            if nested is not None:
                return [nested]
            if f.id in mi.functions:
                return [(mi.mod.name, f.id)]
            tr = self._class_named(mi, f.id)
            if tr is not None:
                tci = self.class_info(tr)
                if "__init__" in tci.methods:
                    return [(tr[0], f"{tr[1]}.__init__")]
                return []
            imp = mi.imports.get(f.id)
            if imp and imp[0] == "symbol":
                tm = self.project.module_for_import(imp[1])
                if tm is not None \
                        and imp[2] in self.modules[tm.name].functions:
                    return [(tm.name, imp[2])]
            return []
        if not isinstance(f, ast.Attribute):
            return []
        # alias.func / alias.Class(...) / ClassName.method(obj, ...)
        if isinstance(f.value, ast.Name):
            imp = mi.imports.get(f.value.id)
            if imp and imp[0] == "module":
                tm = self.project.module_for_import(imp[1])
                if tm is not None:
                    tmi = self.modules[tm.name]
                    if f.attr in tmi.functions:
                        return [(tm.name, f.attr)]
                    tci = tmi.classes.get(f.attr)
                    if tci is not None and "__init__" in tci.methods:
                        return [(tm.name, f"{tci.qual}.__init__")]
                    return []
            tr = self._class_named(mi, f.value.id)
            if tr is not None:
                tci = self.class_info(tr)
                if f.attr in tci.methods:
                    return [(tr[0], f"{tr[1]}.{f.attr}")]
                return []
        # obj.method() through typed expressions (self, self.attr, locals)
        base = self._expr_type(mi, ci, f.value, local)
        if base is not None:
            tci = self.class_info(base)
            if f.attr in tci.methods:
                return [(base[0], f"{base[1]}.{f.attr}")]
            return []
        if unique_fallback and f.attr not in _FALLBACK_EXCLUDE:
            cands = self.method_index.get(f.attr, [])
            if len(cands) == 1:
                return list(cands)
        return []

    def _nested_def(self, mi: ModuleInfo, enclosing_qual: str,
                    name: str) -> Optional[FuncId]:
        """Resolve a bare Name to a def nested in the enclosing function
        (or any enclosing scope up the qualname chain)."""
        parts = enclosing_qual.split(".") if enclosing_qual else []
        while parts:
            fid = (mi.mod.name, ".".join(parts + [name]))
            if fid in self.functions:
                return fid
            parts.pop()
        return None

    def resolve_func_ref(self, mi: ModuleInfo, ci: Optional[ClassInfo],
                         expr: ast.expr, local: Dict[str, TypeRef],
                         enclosing_qual: str = "") -> Optional[FuncId]:
        """A *reference* to a function (``Thread(target=here)``)."""
        if isinstance(expr, ast.Name):
            nested = self._nested_def(mi, enclosing_qual, expr.id)
            if nested is not None:
                return nested
            if expr.id in mi.functions:
                return (mi.mod.name, expr.id)
            return None
        if isinstance(expr, ast.Attribute):
            base = self._expr_type(mi, ci, expr.value, local)
            if base is not None:
                tci = self.class_info(base)
                if expr.attr in tci.methods:
                    return (base[0], f"{base[1]}.{expr.attr}")
            if expr.attr not in _FALLBACK_EXCLUDE:
                cands = self.method_index.get(expr.attr, [])
                if len(cands) == 1:
                    return cands[0]
        return None

    # -- lock expression resolution --------------------------------------------
    def resolve_lock(self, mi: ModuleInfo, ci: Optional[ClassInfo],
                     expr: ast.expr,
                     local: Dict[str, TypeRef]) -> Optional[Tuple[str, str]]:
        """Lock identity ("<mod>.<Cls>.<attr>" / "<mod>.<name>") + kind,
        or None when ``expr`` is not a recognizable lock."""
        if isinstance(expr, ast.Name):
            kind = mi.module_locks.get(expr.id)
            if kind is not None:
                return (f"{mi.mod.name}.{expr.id}", kind)
            return None
        if not isinstance(expr, ast.Attribute):
            return None
        base = self._expr_type(mi, ci, expr.value, local)
        if base is not None:
            bci = self.class_info(base)
            kind = bci.lock_attrs.get(expr.attr)
            if kind is not None:
                return (f"{base[0]}.{base[1]}.{expr.attr}", kind)
        if isinstance(expr.value, ast.Name):
            imp = mi.imports.get(expr.value.id)
            if imp and imp[0] == "module":
                tm = self.project.module_for_import(imp[1])
                if tm is not None:
                    kind = self.modules[tm.name].module_locks.get(expr.attr)
                    if kind is not None:
                        return (f"{tm.name}.{expr.attr}", kind)
        return None


def resolve_kernel_refs(idx: Index, mi: ModuleInfo,
                        ci: Optional[ClassInfo], expr: ast.expr,
                        local: Dict[str, TypeRef],
                        enclosing_qual: str = "",
                        depth: int = 4) -> List[FuncId]:
    """Every function a kernel-position expression may denote.

    Handles the three spellings the pallas/shard_map call sites use:

    - a direct reference (``pl.pallas_call(kernel, ...)``),
    - ``functools.partial(kernel, n)`` — unwraps to ``kernel``,
    - a *factory call* (``pl.pallas_call(_make_kernel(...), ...)``) —
      resolves to whatever the factory's ``return`` statements denote,
      recursively, so factories that return partials or call further
      inner factories still root the innermost def.

    ``depth`` bounds the factory recursion (cycles in pathological
    trees); unresolvable expressions drop silently, as everywhere else.
    """
    out: List[FuncId] = []
    if depth < 0:
        return out
    if isinstance(expr, ast.Call):
        chain = call_chain(expr.func)
        if chain and chain[-1] == "partial":
            if expr.args:
                out.extend(resolve_kernel_refs(
                    idx, mi, ci, expr.args[0], local,
                    enclosing_qual=enclosing_qual, depth=depth))
            return out
        for factory in idx.resolve_call(mi, ci, expr, local,
                                        enclosing_qual=enclosing_qual):
            ffn = idx.functions.get(factory)
            if ffn is None:
                continue
            fmi = idx.modules[factory[0]]
            fci = idx.func_class[factory]
            flocal = idx.local_types(fmi, fci, ffn)
            for node in ast.walk(ffn):
                if isinstance(node, ast.Return) and node.value is not None:
                    out.extend(resolve_kernel_refs(
                        idx, fmi, fci, node.value, flocal,
                        enclosing_qual=factory[1], depth=depth - 1))
        return out
    ref = idx.resolve_func_ref(mi, ci, expr, local,
                               enclosing_qual=enclosing_qual)
    if ref is not None:
        out.append(ref)
    return out


def dotted_name(expr: ast.expr) -> Optional[str]:
    """'a.b.c' for a pure attribute chain, else None."""
    parts = []
    while isinstance(expr, ast.Attribute):
        parts.append(expr.attr)
        expr = expr.value
    if isinstance(expr, ast.Name):
        parts.append(expr.id)
        return ".".join(reversed(parts))
    return None


def call_chain(expr: ast.expr) -> List[str]:
    """Best-effort segment chain of a call target, descending through
    intermediate calls and subscripts: ``self._wal_for(name).append``
    -> ``['self', '_wal_for', 'append']``; empty when nothing named."""
    parts: List[str] = []
    while True:
        if isinstance(expr, ast.Attribute):
            parts.append(expr.attr)
            expr = expr.value
        elif isinstance(expr, ast.Call):
            expr = expr.func
        elif isinstance(expr, ast.Subscript):
            expr = expr.value
        elif isinstance(expr, ast.Name):
            parts.append(expr.id)
            break
        else:
            break
    return list(reversed(parts))


def walk_shallow(node: ast.AST):
    """``ast.walk`` that does not descend into nested function / class /
    lambda bodies — the statements of *this* frame only. (A call inside
    a nested ``def`` runs when the closure runs, not here.)"""
    stack = [node]
    while stack:
        n = stack.pop()
        yield n
        for child in ast.iter_child_nodes(n):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda, ast.ClassDef)):
                continue
            stack.append(child)
