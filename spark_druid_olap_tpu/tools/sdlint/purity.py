"""Tracer-purity pass.

Functions reachable from a ``jax.jit`` / ``jax.shard_map`` /
``pl.pallas_call`` site are *traced*: they run once at trace time with
abstract values, and anything host-visible they do (clock reads, RNG,
locks, I/O) silently bakes into — or falls out of — the compiled
program. Three rules:

- **host-call** — a traced function calls a host-only API
  (``time.*``, ``random.*``, ``np.random.*``, ``threading.*``,
  ``logging.*``, ``os.*``, ``open``/``print``/``input``, sockets,
  subprocess) or takes a lock.
- **traced-branch** — ``if``/``while`` on a value derived from
  ``jnp.*`` / ``jax.lax.*`` results (a tracer): raises
  ``TracerBoolConversionError`` at best, shape-specializes at worst.
  Branches on static python values (shapes, config, plan parameters)
  are fine and not flagged — taint starts at jax expressions only,
  never at function parameters.
- **concretize** — ``float()/int()/bool()/np.asarray()/np.array()`` or
  ``.item()/.tolist()`` on a tainted value forces a device sync inside
  the trace.

Root discovery understands the repo's wrapper idioms: a function that
passes one of its own parameters into a jit-like call (e.g.
``QueryEngine._shard_wrap``) marks the corresponding argument at every
call site as a traced root, so nested ``def core(...)`` programs are
followed even though ``jax.jit`` is two frames away; and a *factory*
call in kernel position — ``pl.pallas_call(_make_kernel(...), ...)``,
the pallas group-by/wave idiom — roots the nested defs the factory
returns, so hand-written kernel bodies obey the same rules.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from spark_druid_olap_tpu.tools.sdlint.astutil import (FuncId, Index,
                                                       dotted_name,
                                                       resolve_kernel_refs)
from spark_druid_olap_tpu.tools.sdlint.core import Finding, Project

# dotted-name heads/prefixes that mean "this call is jit-like: its
# function-valued argument gets traced"
_JIT_LIKE = {"jax.jit", "jit", "jax.shard_map", "shard_map",
             "pl.pallas_call", "pallas_call", "jax.vmap", "vmap",
             "jax.pmap", "checkify.checkify"}

_HOST_PREFIXES = ("time.", "random.", "np.random.", "numpy.random.",
                  "threading.", "logging.", "os.", "socket.",
                  "subprocess.", "requests.", "shutil.", "pathlib.")
_HOST_CALLS = {"open", "print", "input", "time", "sleep"}
_CONCRETIZE_FUNCS = {"float", "int", "bool", "np.asarray", "np.array",
                     "numpy.asarray", "numpy.array", "np.frombuffer"}
_CONCRETIZE_METHODS = {"item", "tolist", "block_until_ready"}
# attribute reads that stay static even on a tracer: array metadata plus
# the engine's own plan/route metadata vocabulary (AggInput.is_int,
# Route.kind/tag, AggregationSpec.name, ... — python values computed at
# plan time, carried on objects that also hold traced arrays)
_STATIC_ATTRS = {"shape", "dtype", "ndim", "size", "is_float", "is_int",
                 "kind", "card", "merged", "tag", "maxabs", "spec",
                 "name", "n_lanes"}
# array-producing namespaces; deliberately NOT bare "jax." — calls like
# jax.default_backend()/jax.devices() return host values
_TAINT_PREFIXES = ("jnp.", "lax.", "jax.lax.", "jax.numpy.", "jax.nn.",
                   "jsp.")


def _expr_tainted(expr: ast.expr, tainted: Set[str]) -> bool:
    """May ``expr`` evaluate to a tracer? Attribute reads in
    ``_STATIC_ATTRS`` cut taint (metadata, not arrays); ``x is None``
    comparisons are static control flow even on tracers; comprehension
    variables inherit taint from their iterable."""
    if isinstance(expr, ast.Name):
        return expr.id in tainted
    if isinstance(expr, ast.Attribute):
        if expr.attr in _STATIC_ATTRS:
            return False
        return _expr_tainted(expr.value, tainted)
    if isinstance(expr, ast.Compare):
        if all(isinstance(op, (ast.Is, ast.IsNot)) for op in expr.ops):
            return False
        return any(_expr_tainted(e, tainted)
                   for e in [expr.left] + expr.comparators)
    if isinstance(expr, ast.Call):
        name = dotted_name(expr.func)
        if name in ("isinstance", "issubclass", "len", "type", "hasattr",
                    "callable", "id", "repr", "str"):
            return False            # static predicates even on tracers
        if name and (name.startswith(_TAINT_PREFIXES)
                     or name.split(".")[0] == "jnp"):
            return True
        parts = ([] if name else [expr.func]) + list(expr.args) \
            + [kw.value for kw in expr.keywords]
        if name and isinstance(expr.func, ast.Attribute):
            parts.append(expr.func.value)   # x.sum() on a tracer
        return any(_expr_tainted(e, tainted) for e in parts)
    if isinstance(expr, (ast.GeneratorExp, ast.ListComp, ast.SetComp,
                         ast.DictComp)):
        inner = set(tainted)
        for gen in expr.generators:
            if _expr_tainted(gen.iter, tainted):
                for n in ast.walk(gen.target):
                    if isinstance(n, ast.Name):
                        inner.add(n.id)
        elts = [expr.key, expr.value] if isinstance(expr, ast.DictComp) \
            else [expr.elt]
        return any(_expr_tainted(e, inner) for e in elts)
    if isinstance(expr, (ast.Lambda, ast.FunctionDef)):
        return False
    return any(_expr_tainted(e, tainted)
               for e in ast.iter_child_nodes(expr)
               if isinstance(e, ast.expr))


def _is_jit_like(idx: Index, mi, name: str) -> bool:
    if name in _JIT_LIKE:
        return True
    # imported-alias forms: `from jax.experimental import pallas as pl`
    # already covered by the `pl.pallas_call` spelling; anything ending
    # in `.pallas_call` or `.shard_map` or `.jit` counts
    return name.split(".")[-1] in {"jit", "shard_map", "pallas_call",
                                   "vmap", "pmap"} and "." in name


class _Purity:
    def __init__(self, project: Project):
        self.project = project
        self.index = project.index()   # shared: parsed/typed once for all passes
        # param positions (by name) of each function that get traced
        self.wrapper_params: Dict[FuncId, Set[str]] = {}
        self._find_wrapper_params()
        self.roots: Dict[FuncId, Tuple[str, int]] = {}   # fid -> site
        self._find_roots()
        self.reachable = self._reach()

    # -- roots -----------------------------------------------------------------
    def _find_wrapper_params(self) -> None:
        for fid, fn in self.index.functions.items():
            params = {a.arg for a in fn.args.args}
            traced: Set[str] = set()
            aliases: Dict[str, str] = {}    # local alias -> param
            for node in ast.walk(fn):
                if isinstance(node, ast.Assign) \
                        and len(node.targets) == 1 \
                        and isinstance(node.targets[0], ast.Name) \
                        and isinstance(node.value, ast.Name) \
                        and node.value.id in params:
                    aliases[node.targets[0].id] = node.value.id
                if not isinstance(node, ast.Call):
                    continue
                name = dotted_name(node.func)
                if name is None or not _is_jit_like(self.index, None, name):
                    continue
                for a in node.args[:1]:
                    if isinstance(a, ast.Name):
                        p = a.id if a.id in params else aliases.get(a.id)
                        if p:
                            traced.add(p)
            if traced:
                self.wrapper_params[fid] = traced

    def _add_root_expr(self, mi, ci, expr: ast.expr, local,
                       enclosing_qual: str, site: Tuple[str, int]) -> None:
        idx = self.index
        if isinstance(expr, ast.Lambda):
            # the lambda body is one expression: follow the calls it makes
            for node in ast.walk(expr.body):
                if isinstance(node, ast.Call):
                    for callee in idx.resolve_call(
                            mi, ci, node, local,
                            enclosing_qual=enclosing_qual):
                        self.roots.setdefault(callee, site)
            return
        # direct refs, factory-returned kernels (``pl.pallas_call(
        # _make_kernel(...), ...)``), ``functools.partial``-wrapped
        # kernels, and factories-returning-factories all resolve through
        # the shared helper — the factory call runs on the host at build
        # time, but the function it ultimately denotes is what traces
        for ref in resolve_kernel_refs(idx, mi, ci, expr, local,
                                       enclosing_qual=enclosing_qual):
            self.roots.setdefault(ref, site)
        # `smfn = jax.shard_map(fn, ...)` then `jax.jit(smfn)` needs no
        # unwrapping here — shard_map itself is jit-like.

    def _find_roots(self) -> None:
        idx = self.index
        for fid, fn in self.index.functions.items():
            mi = idx.modules[fid[0]]
            ci = idx.func_class[fid]
            local = idx.local_types(mi, ci, fn)
            site = (mi.mod.relpath, fn.lineno)
            # decorators: @jax.jit / @jit / @partial(jax.jit, ...)
            for dec in fn.decorator_list:
                name = dotted_name(dec if not isinstance(dec, ast.Call)
                                   else dec.func)
                if name and _is_jit_like(idx, mi, name):
                    self.roots.setdefault(fid, site)
                elif name in ("partial", "functools.partial") \
                        and isinstance(dec, ast.Call) and dec.args:
                    inner = dotted_name(dec.args[0])
                    if inner and _is_jit_like(idx, mi, inner):
                        self.roots.setdefault(fid, site)
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                name = dotted_name(node.func)
                if name is not None and _is_jit_like(idx, mi, name):
                    for a in node.args[:1]:
                        self._add_root_expr(mi, ci, a, local, fid[1],
                                            (mi.mod.relpath, node.lineno))
                    continue
                # wrapper call sites: self._shard_wrap(core, ...)
                for callee in idx.resolve_call(mi, ci, node, local,
                                               enclosing_qual=fid[1],
                                               unique_fallback=True):
                    traced = self.wrapper_params.get(callee)
                    if not traced:
                        continue
                    cfn = idx.functions[callee]
                    pnames = [a.arg for a in cfn.args.args]
                    if pnames and pnames[0] == "self":
                        pnames = pnames[1:]
                    for i, a in enumerate(node.args):
                        if i < len(pnames) and pnames[i] in traced:
                            self._add_root_expr(
                                mi, ci, a, local, fid[1],
                                (mi.mod.relpath, node.lineno))
                    for kw in node.keywords:
                        if kw.arg in traced:
                            self._add_root_expr(
                                mi, ci, kw.value, local, fid[1],
                                (mi.mod.relpath, node.lineno))

    def _reach(self) -> Set[FuncId]:
        idx = self.index
        seen = set(self.roots)
        stack = list(self.roots)
        while stack:
            fid = stack.pop()
            fn = idx.functions.get(fid)
            if fn is None:
                continue
            mi = idx.modules[fid[0]]
            ci = idx.func_class[fid]
            local = idx.local_types(mi, ci, fn)
            for node in self._own_nodes(fn):
                if isinstance(node, ast.Call):
                    for callee in idx.resolve_call(mi, ci, node, local,
                                                   enclosing_qual=fid[1]):
                        if callee not in seen:
                            seen.add(callee)
                            stack.append(callee)
        return seen

    @staticmethod
    def _own_nodes(fn: ast.FunctionDef):
        """Walk a function's body without descending into nested defs or
        lambdas (they are traced only if themselves reachable)."""
        stack: List[ast.AST] = list(ast.iter_child_nodes(fn))
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda, ast.ClassDef)):
                continue
            yield node
            stack.extend(ast.iter_child_nodes(node))

    # -- per-function violation scan -------------------------------------------
    def _taints(self, fn: ast.FunctionDef) -> Set[str]:
        """Names bound (anywhere in the function) to jax expressions —
        a fixpoint over-approximation of 'is a tracer'."""
        tainted: Set[str] = set()

        def target_names(t: ast.expr):
            """Names BOUND by an assignment target — the base of a
            subscript/attribute, not names appearing in its slice."""
            if isinstance(t, ast.Name):
                yield t.id
            elif isinstance(t, (ast.Subscript, ast.Attribute,
                                ast.Starred)):
                base = t.value if not isinstance(t, ast.Starred) else t.value
                yield from target_names(base)
            elif isinstance(t, (ast.Tuple, ast.List)):
                for e in t.elts:
                    yield from target_names(e)

        changed = True
        while changed:
            changed = False
            for node in ast.walk(fn):
                if isinstance(node, ast.Assign):
                    if _expr_tainted(node.value, tainted):
                        for t in node.targets:
                            for nid in target_names(t):
                                if nid not in tainted:
                                    tainted.add(nid)
                                    changed = True
                elif isinstance(node, ast.AugAssign):
                    if _expr_tainted(node.value, tainted) \
                            and isinstance(node.target, ast.Name) \
                            and node.target.id not in tainted:
                        tainted.add(node.target.id)
                        changed = True
        return tainted

    def scan(self, fid: FuncId) -> List[Finding]:
        idx = self.index
        fn = idx.functions.get(fid)
        if fn is None:
            return []
        mi = idx.modules[fid[0]]
        ci = idx.func_class[fid]
        local = idx.local_types(mi, ci, fn)
        path = mi.mod.relpath
        tainted = self._taints(fn)
        out: List[Finding] = []

        def is_tainted(expr: ast.expr) -> bool:
            return _expr_tainted(expr, tainted)

        for node in self._own_nodes(fn):
            if isinstance(node, (ast.If, ast.While)) \
                    and is_tainted(node.test):
                out.append(Finding(
                    "purity", "traced-branch", path, node.lineno,
                    f"{fid[1]}:{'while' if isinstance(node, ast.While) else 'if'}",
                    f"{fid[1]} is traced under jit but branches on a "
                    f"value derived from jax ops; use jnp.where/"
                    f"lax.cond or hoist the decision to trace time"))
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    lk = idx.resolve_lock(mi, ci, item.context_expr, local)
                    if lk is not None:
                        out.append(Finding(
                            "purity", "host-call", path, node.lineno,
                            f"{fid[1]}:lock", f"{fid[1]} is traced under "
                            f"jit but acquires lock {lk[0]}; the acquire "
                            f"runs once at trace time, not per call"))
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func)
            if name is not None:
                if any(name.startswith(p) for p in _HOST_PREFIXES) \
                        or name in _HOST_CALLS:
                    out.append(Finding(
                        "purity", "host-call", path, node.lineno,
                        f"{fid[1]}:{name}",
                        f"{fid[1]} is traced under jit but calls "
                        f"host-only API {name}(); its value freezes at "
                        f"trace time"))
                    continue
                if name in _CONCRETIZE_FUNCS and node.args \
                        and is_tainted(node.args[0]):
                    out.append(Finding(
                        "purity", "concretize", path, node.lineno,
                        f"{fid[1]}:{name}",
                        f"{fid[1]} concretizes a traced value via "
                        f"{name}(); this fails (or syncs) under jit"))
                    continue
            if isinstance(node.func, ast.Attribute) \
                    and node.func.attr in _CONCRETIZE_METHODS \
                    and is_tainted(node.func.value):
                out.append(Finding(
                    "purity", "concretize", path, node.lineno,
                    f"{fid[1]}:.{node.func.attr}",
                    f"{fid[1]} calls .{node.func.attr}() on a traced "
                    f"value; this forces a device sync inside the trace"))
        return out


def run(project: Project) -> List[Finding]:
    p = _Purity(project)
    out: List[Finding] = []
    for fid in sorted(p.reachable):
        out.extend(p.scan(fid))
    return out
