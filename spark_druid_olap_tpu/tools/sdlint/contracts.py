"""Contract-registry pass.

Two string-keyed contracts hold the engine together and have historically
drifted one PR at a time:

- **config keys** — every ``sdot.*`` key passed to ``.get() / .set() /
  .is_set()`` anywhere in the package must be declared with a default in
  ``utils/config.py`` (``_entry(...)``), and every declared key must be
  read somewhere. Genuinely dynamic families (``sdot.wlm.quota.<tenant>``,
  ``sdot.datasource.option.<ds>.<opt>``) are allowlisted via
  ``DYNAMIC_KEY_PREFIXES`` in ``utils/config.py`` — the allowlist itself
  lives next to the registry so it is part of the declared contract.
- **stats keys** — every key written into the engine's observability
  surface (``last_stats[...] = ``, ``last_stats.update({...})``,
  ``m.stats = {...}``) must be documented in ``docs/STATS.md``, and every
  documented key must still be emitted somewhere.

A third contract rides the same doc: **phase names** — every name timed
via ``PH.phase("...")`` / ``PH.add("...")`` / ``PH.stash("...")`` must be
registered in the ``PHASES`` literal of ``utils/phases.py``, and the
registry must match the marker-delimited phase table in ``docs/STATS.md``
(``<!-- phases:begin -->`` .. ``<!-- phases:end -->``) in both
directions. The marker region is excluded from the stats-key scan — phase
names are not stats keys.

Rules: ``undeclared-key``, ``unread-key``, ``undocumented-stats-key``,
``stale-stats-doc``, ``unregistered-phase``, ``undocumented-phase``,
``stale-phase-doc``.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Optional, Set, Tuple

from spark_druid_olap_tpu.tools.sdlint.astutil import dotted_name
from spark_druid_olap_tpu.tools.sdlint.core import Finding, Module, Project

_CONFIG_SUFFIX = "utils/config.py"
_READ_METHODS = {"get", "set", "is_set"}
_STATS_BASES = ("stats", "last_stats")
_DOC_KEY_RE = re.compile(r"`([a-z_][a-z0-9_.]*)`")


def _declared(config_mod: Module) \
        -> Tuple[Dict[str, int], List[str], Dict[str, str]]:
    """(declared key -> _entry line, dynamic prefixes,
    entry-constant name -> key). Keys are consumed both as string
    literals (``cfg.get("sdot.x")``) and through the module-level entry
    constants (``NAME = _entry("sdot.x", ...)`` then
    ``cfg.get(C.NAME)``), so both spellings must count as reads."""
    keys: Dict[str, int] = {}
    prefixes: List[str] = []
    names: Dict[str, str] = {}
    for node in ast.walk(config_mod.tree):
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Name) \
                and node.func.id == "_entry" and node.args \
                and isinstance(node.args[0], ast.Constant) \
                and isinstance(node.args[0].value, str):
            keys[node.args[0].value] = node.lineno
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            tname = node.targets[0].id
            if tname in ("DYNAMIC_KEY_PREFIXES",
                         "DATASOURCE_OVERRIDE_PREFIX"):
                try:
                    v = ast.literal_eval(node.value)
                except ValueError:
                    continue
                if isinstance(v, str):
                    prefixes.append(v)
                else:
                    prefixes.extend(x for x in v if isinstance(x, str))
            elif isinstance(node.value, ast.Call) \
                    and isinstance(node.value.func, ast.Name) \
                    and node.value.func.id == "_entry" \
                    and node.value.args \
                    and isinstance(node.value.args[0], ast.Constant):
                names[tname] = node.value.args[0].value
    return keys, prefixes, names


def _entry_references(project: Project, config_mod: Module,
                      names: Dict[str, str]) -> Set[str]:
    """Keys whose entry constant is referenced anywhere — any module's
    Name/Attribute use, or a use inside a config.py function body (its
    own module-level ``NAME = _entry(...)`` assignment doesn't count)."""
    read: Set[str] = set()
    for mod in project.modules.values():
        if mod is config_mod:
            continue
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Name) and node.id in names:
                read.add(names[node.id])
            elif isinstance(node, ast.Attribute) and node.attr in names:
                read.add(names[node.attr])
            elif isinstance(node, ast.ImportFrom):
                for a in node.names:
                    if a.name in names:
                        read.add(names[a.name])
    for node in ast.walk(config_mod.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for n in ast.walk(node):
                if isinstance(n, ast.Name) and n.id in names:
                    read.add(names[n.id])
                elif isinstance(n, ast.Attribute) and n.attr in names:
                    read.add(names[n.attr])
    return read


def _config_reads(project: Project) -> List[Tuple[str, str, int, str]]:
    """(key, relpath, line, method) for every constant-keyed config
    access; ``prefixed("sdot.x.")`` reads count as reading every
    declared key under that prefix (returned with method='prefixed')."""
    out = []
    for mod in project.modules.values():
        for node in ast.walk(mod.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)):
                continue
            meth = node.func.attr
            if meth not in _READ_METHODS and meth != "prefixed":
                continue
            if not (node.args and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)):
                continue
            key = node.args[0].value
            if not key.startswith("sdot."):
                continue
            out.append((key, mod.relpath, node.lineno, meth))
    return out


_PHASES_BEGIN = "<!-- phases:begin -->"
_PHASES_END = "<!-- phases:end -->"


def _stats_md_path(project: Project) -> Optional[str]:
    for cand in (os.path.join(project.root, os.pardir, "docs", "STATS.md"),
                 os.path.join(project.root, "docs", "STATS.md")):
        if os.path.exists(cand):
            return cand
    return None


def _stats_doc(project: Project) -> Tuple[Optional[str], Dict[str, int]]:
    """docs/STATS.md keys (backticked tokens in table rows). The
    marker-delimited phase table is excluded — phase names (``plan.build``
    etc.) document profiler phases, not stats keys, and are cross-checked
    separately against the ``PHASES`` registry."""
    cand = _stats_md_path(project)
    if cand is None:
        return None, {}
    keys: Dict[str, int] = {}
    in_phases = False
    with open(cand, encoding="utf-8") as f:
        for i, ln in enumerate(f, start=1):
            if _PHASES_BEGIN in ln:
                in_phases = True
                continue
            if _PHASES_END in ln:
                in_phases = False
                continue
            if in_phases or not ln.lstrip().startswith("|"):
                continue
            for m in _DOC_KEY_RE.finditer(ln):
                keys.setdefault(m.group(1), i)
    rel = os.path.relpath(os.path.abspath(cand),
                          os.path.dirname(project.root))
    return rel, keys


def _phases_doc(project: Project) -> Tuple[Optional[str], Dict[str, int]]:
    """Phase names documented in STATS.md's marker-delimited table."""
    cand = _stats_md_path(project)
    if cand is None:
        return None, {}
    names: Dict[str, int] = {}
    in_phases = False
    with open(cand, encoding="utf-8") as f:
        for i, ln in enumerate(f, start=1):
            if _PHASES_BEGIN in ln:
                in_phases = True
                continue
            if _PHASES_END in ln:
                in_phases = False
                continue
            if not in_phases or not ln.lstrip().startswith("|"):
                continue
            for m in _DOC_KEY_RE.finditer(ln):
                names.setdefault(m.group(1), i)
    rel = os.path.relpath(os.path.abspath(cand),
                          os.path.dirname(project.root))
    return rel, names


def _phases_registry(project: Project) \
        -> Tuple[Optional[Module], Dict[str, int]]:
    """The ``PHASES = {...}`` literal in utils/phases.py (name -> line).
    Absent module (lint fixture projects) disables the phase contract."""
    mod = project.by_suffix("utils/phases.py")
    if mod is None:
        return None, {}
    names: Dict[str, int] = {}
    for stmt in mod.tree.body:
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                and isinstance(stmt.targets[0], ast.Name) \
                and stmt.targets[0].id == "PHASES" \
                and isinstance(stmt.value, ast.Dict):
            for k in stmt.value.keys:
                if isinstance(k, ast.Constant) \
                        and isinstance(k.value, str):
                    names.setdefault(k.value, k.lineno)
    return mod, names


_PHASE_METHODS = {"phase", "add", "stash"}
_PHASE_RECEIVERS = {"PH", "phases"}


def _phase_call_sites(project: Project) -> List[Tuple[str, str, int]]:
    """(name, relpath, line) for every literal-named timer call —
    ``PH.phase("x")`` / ``PH.add("x", dt)`` / ``PH.stash("x", dt)``."""
    out = []
    for mod in project.modules.values():
        for node in ast.walk(mod.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _PHASE_METHODS):
                continue
            recv = dotted_name(node.func.value)
            if recv is None \
                    or recv.split(".")[-1] not in _PHASE_RECEIVERS:
                continue
            if not (node.args and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)):
                continue
            out.append((node.args[0].value, mod.relpath, node.lineno))
    return out


def _is_stats_base(expr: ast.expr) -> bool:
    name = dotted_name(expr)
    if name is None:
        return False
    leaf = name.split(".")[-1]
    return leaf in _STATS_BASES


def _stats_emissions(project: Project) -> List[Tuple[str, str, int]]:
    """(key, relpath, line) for every statically-visible stats write."""
    out = []
    for mod in project.modules.values():
        # aliases: `st = self.last_stats` makes `st[...]` a stats write
        aliases = {n.targets[0].id for n in ast.walk(mod.tree)
                   if isinstance(n, ast.Assign) and len(n.targets) == 1
                   and isinstance(n.targets[0], ast.Name)
                   and _is_stats_base(n.value)}

        def _base(expr: ast.expr) -> bool:
            if _is_stats_base(expr):
                return True
            return isinstance(expr, ast.Name) and expr.id in aliases

        for node in ast.walk(mod.tree):
            # stats["k"] = v / self.last_stats["k"] = v
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Subscript) \
                            and _base(t.value) \
                            and isinstance(t.slice, ast.Constant) \
                            and isinstance(t.slice.value, str):
                        out.append((t.slice.value, mod.relpath,
                                    node.lineno))
                # m.stats = {...} dict literal
                if len(node.targets) == 1 \
                        and isinstance(node.targets[0], ast.Attribute) \
                        and node.targets[0].attr in _STATS_BASES \
                        and isinstance(node.value, ast.Dict):
                    for k in node.value.keys:
                        if isinstance(k, ast.Constant) \
                                and isinstance(k.value, str):
                            out.append((k.value, mod.relpath,
                                        node.lineno))
            # last_stats.update({...})
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "update" \
                    and _base(node.func.value) \
                    and node.args and isinstance(node.args[0], ast.Dict):
                for k in node.args[0].keys:
                    if isinstance(k, ast.Constant) \
                            and isinstance(k.value, str):
                        out.append((k.value, mod.relpath, node.lineno))
    return out


def run(project: Project) -> List[Finding]:
    out: List[Finding] = []
    config_mod = project.by_suffix(_CONFIG_SUFFIX)
    if config_mod is not None:
        declared, prefixes, names = _declared(config_mod)
        reads = _config_reads(project)
        read_keys: Set[str] = _entry_references(project, config_mod,
                                                names)
        for key, path, line, meth in reads:
            if meth == "prefixed":
                read_keys.update(k for k in declared
                                 if k.startswith(key))
                continue
            read_keys.add(key)
            if key in declared:
                continue
            if any(key.startswith(p) for p in prefixes):
                continue
            out.append(Finding(
                "contracts", "undeclared-key", path, line, key,
                f"config key {key!r} is read here but never declared "
                f"with a default in utils/config.py (_entry) and matches "
                f"no DYNAMIC_KEY_PREFIXES pattern"))
        for key, line in sorted(declared.items()):
            if key not in read_keys:
                out.append(Finding(
                    "contracts", "unread-key", config_mod.relpath, line,
                    key,
                    f"config key {key!r} is declared in utils/config.py "
                    f"but no code reads it (dead contract surface)"))
    doc_path, documented = _stats_doc(project)
    if doc_path is not None:
        emitted: Dict[str, Tuple[str, int]] = {}
        for key, path, line in _stats_emissions(project):
            emitted.setdefault(key, (path, line))
        for key, (path, line) in sorted(emitted.items()):
            if key not in documented:
                out.append(Finding(
                    "contracts", "undocumented-stats-key", path, line,
                    key,
                    f"stats key {key!r} is emitted here but not "
                    f"documented in docs/STATS.md"))
        for key, line in sorted(documented.items()):
            if key not in emitted:
                out.append(Finding(
                    "contracts", "stale-stats-doc", doc_path, line, key,
                    f"docs/STATS.md documents stats key {key!r} but "
                    f"nothing emits it"))
    phases_mod, registry = _phases_registry(project)
    if phases_mod is not None and registry:
        for name, path, line in sorted(_phase_call_sites(project)):
            if name not in registry:
                out.append(Finding(
                    "contracts", "unregistered-phase", path, line, name,
                    f"phase {name!r} is timed here but not registered in "
                    f"the PHASES literal of utils/phases.py — it would "
                    f"surface in stats['phases'] undocumented"))
        ph_doc_path, ph_documented = _phases_doc(project)
        if ph_doc_path is not None:
            for name, line in sorted(registry.items()):
                if name not in ph_documented:
                    out.append(Finding(
                        "contracts", "undocumented-phase",
                        phases_mod.relpath, line, name,
                        f"phase {name!r} is registered in utils/phases.py "
                        f"but missing from the phases:begin/phases:end "
                        f"table in docs/STATS.md"))
            for name, line in sorted(ph_documented.items()):
                if name not in registry:
                    out.append(Finding(
                        "contracts", "stale-phase-doc", ph_doc_path, line,
                        name,
                        f"docs/STATS.md phase table documents {name!r} "
                        f"but utils/phases.py does not register it"))
    return out
