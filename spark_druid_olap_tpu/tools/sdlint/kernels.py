"""Pallas kernel-contract pass.

The wave mega-kernel (``ops/pallas_wave.py``) and the dense group-by
kernel (``ops/pallas_groupby.py``) are guarded at runtime by a trace
probe against a Mosaic-safe whitelist plus interpreter-mode
differentials — but the probe only sees the lane *builders*, and the
VMEM/layout arithmetic it relies on is spread across four modules that
must stay mutually consistent. This pass verifies the contract
documented in ``docs/KERNELS.md`` statically, chip-free:

- **vmem-budget** — the resident scratch block (``MAX_OUT_ROWS x 128``
  f32) plus one floor-sized double-buffered input tile must fit the
  declared VMEM budget: a tile planner that cannot shrink below its
  floor would hand Mosaic an overcommitted BlockSpec at exactly the
  widest storms the clamp exists for.
- **tile-clamp-mismatch** — ``planner/fusion.py:plan_wave_tiles``
  generalizes ``pallas_groupby.choose_block_rows`` and *inherits its
  proof* (`wave_eligible` requires 'ffl' routes, proven at the group-by
  clamp bounds); its ``min_rows``/``max_rows`` defaults and the
  ``sdot.pallas.wave.tile.bytes`` default must therefore equal
  ``MIN_BLOCK_ROWS``/``MAX_BLOCK_ROWS``/``VMEM_BUDGET``.
- **cost-floor-mismatch** — ``parallel/cost.py:wave_tile_itemsize``
  must price operands at the dtypes ``_prep_dtype`` actually ships
  (masks as 1 byte, narrow ints widened to 4), or the planner's budget
  arithmetic diverges from the kernel's real VMEM footprint.
- **dtype-promotion-gap** — every promotion ``_prep_dtype`` plans
  BlockSpecs with must be applied by the dispatch function's operand
  prep (`.astype(jnp.int8)` / `.astype(jnp.int32)`): a planned-vs-
  shipped dtype divergence is a Mosaic tiling error on device only.
- **missing-stripe-init** — a kernel that accumulates across grid
  steps without a ``@pl.when(step == 0)`` init block reads garbage
  VMEM on step 0 (TPU grids are sequential; the output block is only a
  legal accumulator when step 0 writes every stripe's identity).
- **incomplete-identity-init** — the step-0 identity column must cover
  every scratch-stripe family the kernel accumulates into: the
  accumulate-side and init-side row arithmetic must address the same
  layout fields (this is the bug class the explicit identity-column
  operand papered over — e.g. theta stripes minimum-folded against
  uninitialized rows).
- **non-whitelisted-primitive** — a static complement of the runtime
  ``_check_jaxpr`` whitelist: code reachable from a kernel *body* that
  the trace probe does NOT cover (the probe only traces lane builders)
  must not call gather/sort/scan/dot-class jnp/lax primitives — those
  fail only at Mosaic compile time on a real chip.
- **dynamic-ref-index** — ref indices inside kernel bodies must be
  static Python ints: an index derived from ``pl.program_id`` or from
  tile *values* is a traced scalar, which Mosaic refs reject (or worse,
  interpret mode accepts and the TPU build then diverges).

Kernel bodies are discovered at every ``pl.pallas_call`` site through
``astutil.resolve_kernel_refs`` (direct refs, ``functools.partial``,
and factory calls, the same rooting the purity pass uses). Anchors
resolve by path suffix; a missing anchor skips its cross-check, so
fixture trees carry only what their seeded violation needs.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from spark_druid_olap_tpu.tools.sdlint.astutil import (FuncId, call_chain,
                                                       dotted_name,
                                                       resolve_kernel_refs,
                                                       walk_shallow)
from spark_druid_olap_tpu.tools.sdlint.core import Finding, Module, Project

_WAVE_SUFFIX = "ops/pallas_wave.py"
_GROUPBY_SUFFIX = "ops/pallas_groupby.py"
_FUSION_SUFFIX = "planner/fusion.py"
_COST_SUFFIX = "parallel/cost.py"
_CONFIG_SUFFIX = "utils/config.py"

_TILE_BYTES_KEY = "sdot.pallas.wave.tile.bytes"

# widest dtype an operand can have after _prep_dtype (f64 under x64
# canonicalization) — the floor tile must fit even an all-f64 storm
_MAX_ITEMSIZE = 8

#: jnp/lax call names outside the Mosaic-safe elementwise set
#: (``pallas_wave._SAFE_PRIMS``): gathers, sorts, scans, contractions,
#: scatter-class ops. The runtime probe rejects these in lane builders;
#: this is the static complement for kernel-side helpers the probe
#: never traces.
_UNSAFE_CALLS = frozenset({
    "take", "take_along_axis", "gather", "scatter", "scatter_add",
    "sort", "argsort", "lexsort", "searchsorted", "unique", "nonzero",
    "flatnonzero", "argwhere", "argmax", "argmin", "top_k",
    "approx_max_k", "approx_min_k", "dot", "dot_general", "matmul",
    "vdot", "tensordot", "einsum", "cumsum", "cumprod", "cummax",
    "cummin", "associative_scan", "scan", "while_loop", "fori_loop",
    "cond", "switch", "bincount", "digitize", "histogram",
    "segment_sum", "segment_min", "segment_max", "segment_prod",
    "dynamic_slice", "dynamic_update_slice", "convolve",
    "conv_general_dilated", "roll", "repeat", "sort_key_val",
})
_JAX_NS_PREFIXES = ("jnp.", "lax.", "jax.lax.", "jax.numpy.", "jax.nn.",
                    "jax.ops.", "jsp.")


# =============================================================================
# small static evaluators
# =============================================================================

def _const(expr: ast.expr) -> Optional[float]:
    """Compile-time numeric value of an expression (literals, + - * //
    / % ** << >>, unary minus); None when dynamic."""
    if isinstance(expr, ast.Constant) and isinstance(expr.value,
                                                     (int, float)):
        return expr.value
    if isinstance(expr, ast.UnaryOp) and isinstance(expr.op, ast.USub):
        v = _const(expr.operand)
        return None if v is None else -v
    if isinstance(expr, ast.BinOp):
        a, b = _const(expr.left), _const(expr.right)
        if a is None or b is None:
            return None
        try:
            if isinstance(expr.op, ast.Add):
                return a + b
            if isinstance(expr.op, ast.Sub):
                return a - b
            if isinstance(expr.op, ast.Mult):
                return a * b
            if isinstance(expr.op, ast.FloorDiv):
                return a // b
            if isinstance(expr.op, ast.Div):
                return a / b
            if isinstance(expr.op, ast.Mod):
                return a % b
            if isinstance(expr.op, ast.Pow):
                return a ** b
            if isinstance(expr.op, ast.LShift):
                return int(a) << int(b)
            if isinstance(expr.op, ast.RShift):
                return int(a) >> int(b)
        except (ValueError, ZeroDivisionError, OverflowError):
            return None
    return None


def _module_consts(mod: Module) -> Dict[str, Tuple[float, int]]:
    """Top-level ``NAME = <const-expr>`` assignments: name -> (value,
    lineno)."""
    out: Dict[str, Tuple[float, int]] = {}
    for node in mod.tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            v = _const(node.value)
            if v is not None:
                out[node.targets[0].id] = (v, node.lineno)
    return out


def _fn_defaults(fn: ast.FunctionDef) -> Dict[str, float]:
    """Constant parameter defaults of ``fn`` by name."""
    out: Dict[str, float] = {}
    args = fn.args
    pos = args.posonlyargs + args.args
    for a, d in zip(pos[len(pos) - len(args.defaults):], args.defaults):
        v = _const(d)
        if v is not None:
            out[a.arg] = v
    for a, d in zip(args.kwonlyargs, args.kw_defaults):
        if d is not None:
            v = _const(d)
            if v is not None:
                out[a.arg] = v
    return out


def _top_level_fn(mod: Module, name: str) -> Optional[ast.FunctionDef]:
    for node in mod.tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node.name == name:
            return node
    return None


def _entry_default(mod: Module, key: str) -> Optional[float]:
    """The declared default of ``_entry("<key>", default, ...)`` in
    utils/config.py (the same declaration shape the keys pass reads)."""
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Call) and len(node.args) >= 2 \
                and call_chain(node.func)[-1:] == ["_entry"] \
                and isinstance(node.args[0], ast.Constant) \
                and node.args[0].value == key:
            return _const(node.args[1])
    return None


# =============================================================================
# budget / clamp / cost-model cross-checks (module-level arithmetic)
# =============================================================================

def _budget_findings(project: Project) -> List[Finding]:
    out: List[Finding] = []
    wave = project.by_suffix(_WAVE_SUFFIX)
    gb = project.by_suffix(_GROUPBY_SUFFIX)
    fusion = project.by_suffix(_FUSION_SUFFIX)
    config = project.by_suffix(_CONFIG_SUFFIX)

    gbc = _module_consts(gb) if gb is not None else {}
    lanes = int(gbc.get("LANES", (128, 0))[0])
    min_rows = gbc.get("MIN_BLOCK_ROWS", (None, 0))[0]
    max_rows = gbc.get("MAX_BLOCK_ROWS", (None, 0))[0]
    gb_budget = gbc.get("VMEM_BUDGET", (None, 0))[0]

    budget = None
    if config is not None:
        budget = _entry_default(config, _TILE_BYTES_KEY)
    if budget is None:
        budget = gb_budget

    plan = _top_level_fn(fusion, "plan_wave_tiles") \
        if fusion is not None else None
    plan_defaults = _fn_defaults(plan) if plan is not None else {}
    floor = plan_defaults.get("min_rows", min_rows)

    # -- vmem-budget: scratch + floor tile must fit -----------------------
    if wave is not None and budget is not None and floor is not None:
        wc = _module_consts(wave)
        max_out = wc.get("MAX_OUT_ROWS")
        if max_out is not None:
            scratch = max_out[0] * lanes * 4
            tile = floor * lanes * _MAX_ITEMSIZE * 2
            if scratch + tile > budget:
                out.append(Finding(
                    "kernels", "vmem-budget", wave.relpath, max_out[1],
                    "MAX_OUT_ROWS",
                    f"resident scratch block ({int(max_out[0])} rows x "
                    f"{lanes} lanes f32 = {int(scratch)} bytes) plus one "
                    f"floor-sized double-buffered tile ({int(tile)} "
                    f"bytes) exceeds the {int(budget)}-byte VMEM budget; "
                    f"plan_wave_tiles cannot shrink below its "
                    f"{int(floor)}-row floor, so wide storms would hand "
                    f"Mosaic an overcommitted BlockSpec"))
    if gb is not None and gb_budget is not None and min_rows is not None:
        # floor block: i32 key + one f32 value per row, double-buffered
        tile = min_rows * lanes * 8 * 2
        if tile > gb_budget:
            out.append(Finding(
                "kernels", "vmem-budget", gb.relpath,
                gbc["MIN_BLOCK_ROWS"][1], "MIN_BLOCK_ROWS",
                f"floor block ({int(min_rows)} rows, key + one value, "
                f"double-buffered = {int(tile)} bytes) exceeds "
                f"VMEM_BUDGET ({int(gb_budget)}); choose_block_rows "
                f"cannot shrink below the floor"))

    # -- tile-clamp-mismatch: plan_wave_tiles must inherit the proven
    # choose_block_rows bounds -------------------------------------------
    if plan is not None and gb is not None:
        for pname, cname, gval in (("min_rows", "MIN_BLOCK_ROWS",
                                    min_rows),
                                   ("max_rows", "MAX_BLOCK_ROWS",
                                    max_rows)):
            pval = plan_defaults.get(pname)
            if pval is not None and gval is not None and pval != gval:
                out.append(Finding(
                    "kernels", "tile-clamp-mismatch", fusion.relpath,
                    plan.lineno, f"plan_wave_tiles.{pname}",
                    f"plan_wave_tiles default {pname}={int(pval)} != "
                    f"pallas_groupby.{cname}={int(gval)}; wave_eligible "
                    f"inherits choose_block_rows' exactness proof, which "
                    f"only holds at the group-by clamp bounds"))
    if config is not None and gb_budget is not None:
        cfg_budget = _entry_default(config, _TILE_BYTES_KEY)
        if cfg_budget is not None and cfg_budget != gb_budget:
            out.append(Finding(
                "kernels", "tile-clamp-mismatch", config.relpath, 1,
                _TILE_BYTES_KEY,
                f"{_TILE_BYTES_KEY} default ({int(cfg_budget)}) != "
                f"pallas_groupby.VMEM_BUDGET ({int(gb_budget)}); both "
                f"kernels share the same VMEM and docs/KERNELS.md "
                f"documents them as one budget"))

    # -- cost-floor-mismatch: cost model must price _prep_dtype's
    # shipped dtypes ------------------------------------------------------
    cost = project.by_suffix(_COST_SUFFIX)
    if cost is not None and wave is not None:
        promos = _prep_dtype_targets(wave)
        fn = _top_level_fn(cost, "wave_tile_itemsize")
        if fn is not None and promos:
            consts = {c.value for c in ast.walk(fn)
                      if isinstance(c, ast.Constant)
                      and isinstance(c.value, int)}
            needed = {}
            if "int8" in promos:
                needed[1] = "masks ship as int8 (1 byte)"
            if "int32" in promos:
                needed[4] = "narrow ints widen to int32 (4 bytes)"
            if "int64" in promos:
                needed[8] = "narrow ints widen to int64 (8 bytes)"
            for size, why in sorted(needed.items()):
                if size not in consts:
                    out.append(Finding(
                        "kernels", "cost-floor-mismatch", cost.relpath,
                        fn.lineno, f"wave_tile_itemsize:{size}",
                        f"wave_tile_itemsize never prices {size} "
                        f"bytes/row but _prep_dtype plans it ({why}); "
                        f"the planner's VMEM arithmetic diverges from "
                        f"the kernel's real tile footprint"))
    return out


def _prep_dtype_targets(mod: Module) -> Set[str]:
    """dtype names ``_prep_dtype`` promotes operands *to* (attribute
    returns like ``jnp.int8``/``jnp.int32``; the identity passthrough
    return is a bare name and drops out)."""
    fn = _top_level_fn(mod, "_prep_dtype")
    if fn is None:
        return set()
    out: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Return) \
                and isinstance(node.value, ast.Attribute):
            name = dotted_name(node.value)
            if name and name.split(".")[0] in ("jnp", "np", "numpy",
                                               "jax"):
                out.add(name.split(".")[-1])
    return out


# =============================================================================
# kernel-body discovery + per-kernel rules
# =============================================================================

class _Kernels:
    def __init__(self, project: Project):
        self.project = project
        self.index = project.index()
        # (owner fid, pallas_call node) per site; kernel fid -> site
        self.sites: List[Tuple[FuncId, ast.Call]] = []
        self.kernels: Dict[FuncId, Tuple[str, int]] = {}
        self.probe_covered: Set[FuncId] = set()
        self._discover()

    def _discover(self) -> None:
        idx = self.index
        probe_roots: Set[FuncId] = set()
        for fid, fn in idx.functions.items():
            mi = idx.modules[fid[0]]
            ci = idx.func_class[fid]
            local = idx.local_types(mi, ci, fn)
            for node in walk_shallow(fn):
                if not isinstance(node, ast.Call) or not node.args:
                    continue
                # ``pl.pallas_call(k, ...)(operands)``: only the inner
                # call is the site — call_chain sees "pallas_call" from
                # the outer invocation too (it descends through Calls)
                if not isinstance(node.func, (ast.Name, ast.Attribute)):
                    continue
                chain = call_chain(node.func)
                if not chain:
                    continue
                if chain[-1] == "pallas_call":
                    self.sites.append((fid, node))
                    for k in resolve_kernel_refs(
                            idx, mi, ci, node.args[0], local,
                            enclosing_qual=fid[1]):
                        self.kernels.setdefault(
                            k, (mi.mod.relpath, node.lineno))
                elif chain[-1] == "make_jaxpr":
                    # the runtime probe: whatever it traces is covered
                    # by _check_jaxpr at build time — the static
                    # whitelist skips it to avoid double jeopardy with
                    # the (deliberately narrower) runtime set
                    probe_roots.update(resolve_kernel_refs(
                        idx, mi, ci, node.args[0], local,
                        enclosing_qual=fid[1]))
        self.probe_covered = self._closure(probe_roots)

    def _closure(self, roots: Set[FuncId]) -> Set[FuncId]:
        idx = self.index
        seen = set(roots)
        stack = list(roots)
        while stack:
            fid = stack.pop()
            fn = idx.functions.get(fid)
            if fn is None:
                continue
            mi = idx.modules[fid[0]]
            ci = idx.func_class[fid]
            local = idx.local_types(mi, ci, fn)
            for node in walk_shallow(fn):
                if isinstance(node, ast.Call):
                    for callee in idx.resolve_call(
                            mi, ci, node, local, enclosing_qual=fid[1]):
                        if callee not in seen:
                            seen.add(callee)
                            stack.append(callee)
        return seen

    # -- dtype-promotion-gap ---------------------------------------------------
    def promotion_findings(self) -> List[Finding]:
        out: List[Finding] = []
        idx = self.index
        for fid, call in self.sites:
            mi = idx.modules[fid[0]]
            promos = _prep_dtype_targets(mi.mod)
            if not promos:
                continue
            fn = idx.functions[fid]
            applied: Set[str] = set()
            for node in ast.walk(fn):
                if isinstance(node, ast.Call) \
                        and isinstance(node.func, ast.Attribute) \
                        and node.func.attr == "astype" and node.args:
                    name = dotted_name(node.args[0])
                    if name:
                        applied.add(name.split(".")[-1])
            for t in sorted(promos - applied):
                out.append(Finding(
                    "kernels", "dtype-promotion-gap", mi.mod.relpath,
                    call.lineno, f"{fid[1]}:{t}",
                    f"{fid[1]} dispatches pl.pallas_call with BlockSpecs "
                    f"planned by _prep_dtype but never applies the "
                    f"{t} promotion to its operands (.astype(jnp.{t})); "
                    f"planned tile dtype and shipped operand dtype "
                    f"diverge — a Mosaic tiling error on device only"))
        return out

    # -- per-kernel scans ------------------------------------------------------
    def kernel_findings(self) -> List[Finding]:
        out: List[Finding] = []
        for kfid in sorted(self.kernels):
            fn = self.index.functions.get(kfid)
            if fn is None:
                continue
            path = self.index.modules[kfid[0]].mod.relpath
            refs = _ref_names(fn)
            out.extend(self._stripe_init(kfid, fn, path, refs))
            out.extend(self._dynamic_index(kfid, fn, path, refs))
        out.extend(self._whitelist())
        return out

    def _stripe_init(self, kfid: FuncId, fn: ast.FunctionDef, path: str,
                     refs: Set[str]) -> List[Finding]:
        accum_idx = _accum_index_exprs(fn, refs)
        if not accum_idx:
            return []
        when_blocks = [n for n in ast.walk(fn)
                       if isinstance(n, ast.FunctionDef) and n is not fn
                       and any(isinstance(d, ast.Call)
                               and call_chain(d.func)[-1:] == ["when"]
                               for d in n.decorator_list)]
        if not when_blocks:
            return [Finding(
                "kernels", "missing-stripe-init", path, fn.lineno,
                kfid[1],
                f"{kfid[1]} accumulates into its output ref across grid "
                f"steps but has no @pl.when(step == 0) init block; the "
                f"output block is only a legal cross-step accumulator "
                f"when step 0 writes every stripe's identity (step-0 "
                f"VMEM contents are undefined)")]
        # identity-init completeness: accumulate-side row arithmetic
        # must address the same layout fields the init side writes
        env = _binding_env(fn)
        accum_vocab: Set[str] = set()
        for e in accum_idx:
            accum_vocab |= _attr_vocab(e, env)
        init_vocab: Set[str] = set()
        for wb in when_blocks:
            wenv = dict(env)
            wenv.update(_binding_env(wb))
            for node in ast.walk(wb):
                if isinstance(node, ast.expr):
                    init_vocab |= _attr_vocab(node, wenv,
                                              include_call_args=True)
        # host-side identity buffers (wave: the init_col operand built
        # in the enclosing factory) — scan the whole defining module
        mod = self.index.modules[kfid[0]].mod
        for ofid, ofn in self.index.functions.items():
            if ofid[0] != kfid[0]:
                continue
            oenv = _binding_env(ofn)
            for node in walk_shallow(ofn):
                if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                        and isinstance(node.targets[0], ast.Subscript):
                    base = call_chain(node.targets[0].value)
                    if base and "init" in base[-1].lower():
                        init_vocab |= _attr_vocab(
                            node.targets[0].slice, oenv)
                elif isinstance(node, ast.Call) \
                        and call_chain(node.func)[-1:] == ["init_rows"] \
                        and len(node.args) > 1:
                    init_vocab |= _attr_vocab(node.args[1], oenv)
        missing = sorted(accum_vocab - init_vocab)
        if not missing:
            return []
        return [Finding(
            "kernels", "incomplete-identity-init", path, fn.lineno,
            f"{kfid[1]}:{','.join(missing)}",
            f"{kfid[1]} accumulates into scratch stripes addressed via "
            f"{', '.join(missing)} but the step-0 identity init "
            f"(pl.when block / identity-column build in "
            f"{mod.relpath}) never writes rows addressed by "
            f"{'it' if len(missing) == 1 else 'them'}; those stripes "
            f"fold against undefined VMEM on step 0")]

    def _dynamic_index(self, kfid: FuncId, fn: ast.FunctionDef,
                       path: str, refs: Set[str]) -> List[Finding]:
        out: List[Finding] = []
        tainted = _traced_names(fn, refs)

        def dynamic(e: ast.expr) -> bool:
            for node in ast.walk(e):
                if isinstance(node, ast.Name) and node.id in tainted:
                    return True
                if isinstance(node, ast.Call) \
                        and call_chain(node.func)[-1:] == ["program_id"]:
                    return True
            return False

        seen: Set[Tuple[int, str]] = set()
        for node in ast.walk(fn):
            if not isinstance(node, ast.Subscript):
                continue
            base = call_chain(node.value)
            if not base or base[0] not in refs:
                continue
            if (node.lineno, base[0]) in seen:
                continue       # load + store on one line: report once
            if dynamic(node.slice):
                seen.add((node.lineno, base[0]))
                out.append(Finding(
                    "kernels", "dynamic-ref-index", path, node.lineno,
                    f"{kfid[1]}:{base[0]}",
                    f"{kfid[1]} indexes ref {base[0]} with a traced "
                    f"value (derived from pl.program_id or tile reads); "
                    f"Mosaic refs require static Python-int indices — "
                    f"interpret mode accepts this and the TPU build "
                    f"then diverges"))
        return out

    def _whitelist(self) -> List[Finding]:
        out: List[Finding] = []
        idx = self.index
        reach = self._closure(set(self.kernels)) - self.probe_covered
        reach |= set(self.kernels)      # kernel bodies always checked
        for fid in sorted(reach):
            fn = idx.functions.get(fid)
            if fn is None:
                continue
            path = idx.modules[fid[0]].mod.relpath
            for node in walk_shallow(fn):
                if not isinstance(node, ast.Call):
                    continue
                name = dotted_name(node.func)
                if not name or not name.startswith(_JAX_NS_PREFIXES):
                    continue
                leaf = name.split(".")[-1]
                if leaf in _UNSAFE_CALLS:
                    out.append(Finding(
                        "kernels", "non-whitelisted-primitive", path,
                        node.lineno, f"{fid[1]}:{name}",
                        f"{fid[1]} is reachable from a pallas kernel "
                        f"body outside the trace probe's coverage and "
                        f"calls {name}(), which lowers outside the "
                        f"Mosaic-safe elementwise set "
                        f"(pallas_wave._SAFE_PRIMS); this fails only at "
                        f"Mosaic compile time on a real chip"))
        return out


def _ref_names(fn: ast.FunctionDef) -> Set[str]:
    """Kernel parameters (pallas passes refs positionally, ``*refs``
    included) plus local aliases bound from a plain ref subscript
    (``out_ref = refs[n_in]`` — a full-slice subscript is a *read* and
    stays a value)."""
    refs = {a.arg for a in fn.args.posonlyargs + fn.args.args
            + fn.args.kwonlyargs}
    if fn.args.vararg is not None:
        refs.add(fn.args.vararg.arg)
    changed = True
    while changed:
        changed = False
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and isinstance(node.value, ast.Subscript) \
                    and not _has_slice(node.value.slice):
                base = call_chain(node.value.value)
                if base and base[0] in refs \
                        and node.targets[0].id not in refs:
                    refs.add(node.targets[0].id)
                    changed = True
    return refs


def _has_slice(e: ast.expr) -> bool:
    return any(isinstance(n, ast.Slice) for n in ast.walk(e))


def _accum_index_exprs(fn: ast.FunctionDef,
                       refs: Set[str]) -> List[ast.expr]:
    """Row-index expressions of cross-step accumulation: subscript
    stores on a ref whose value re-reads the same ref (read-modify-
    write), plus ``accumulate_rows(ref, row, ...)`` helper calls."""
    out: List[ast.expr] = []
    for node in walk_shallow(fn):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if not isinstance(t, ast.Subscript):
                    continue
                base = call_chain(t.value)
                if not base or base[0] not in refs:
                    continue
                rmw = any(isinstance(n, ast.Name) and n.id == base[0]
                          for n in ast.walk(node.value))
                if rmw:
                    out.append(t.slice)
        elif isinstance(node, ast.Call) \
                and call_chain(node.func)[-1:] == ["accumulate_rows"] \
                and len(node.args) > 1:
            out.append(node.args[1])
    return out


def _binding_env(fn: ast.FunctionDef) -> Dict[str, ast.expr]:
    """name -> defining expression, for one level of index-arithmetic
    expansion: plain assignments plus for-loop targets (bound to the
    loop's iterable — the *source* of the values the name ranges
    over)."""
    env: Dict[str, ast.expr] = {}
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            env.setdefault(node.targets[0].id, node.value)
        elif isinstance(node, (ast.For, ast.AsyncFor)):
            for t in ast.walk(node.target):
                if isinstance(t, ast.Name):
                    env.setdefault(t.id, node.iter)
    return env


def _attr_vocab(expr: ast.expr, env: Dict[str, ast.expr],
                depth: int = 3, include_call_args: bool = False) -> Set[str]:
    """Attribute names an index expression's arithmetic reaches —
    the layout-field vocabulary (``lay.base``, ``lay.theta_base``,
    ``TH.K_LANES``). Call *method* names and (by default) call
    arguments are excluded: ``lay.theta_base.get(p.spec.name)``
    contributes ``theta_base``, not ``get``/``spec``/``name``. Names
    expand one ``env`` level at a time through local assignments and
    for-targets."""
    out: Set[str] = set()

    def visit(e: ast.expr, d: int) -> None:
        if isinstance(e, ast.Call):
            f = e.func
            if isinstance(f, ast.Attribute):
                visit(f.value, d)       # drop the method name itself
            elif isinstance(f, ast.expr):
                visit(f, d)
            if include_call_args:
                for a in e.args:
                    visit(a, d)
                for kw in e.keywords:
                    visit(kw.value, d)
            return
        if isinstance(e, ast.Attribute):
            out.add(e.attr)
            visit(e.value, d)
            return
        if isinstance(e, ast.Name):
            if d > 0 and e.id in env:
                visit(env[e.id], d - 1)
            return
        for c in ast.iter_child_nodes(e):
            if isinstance(c, ast.expr):
                visit(c, d)

    visit(expr, depth)
    return out


def _traced_names(fn: ast.FunctionDef, refs: Set[str]) -> Set[str]:
    """Names bound to traced scalars inside a kernel body:
    ``pl.program_id`` results, ref tile reads, and *arithmetic* over
    them (fixpoint). Taint deliberately does NOT flow through calls or
    loop targets — kernels interleave traced tiles with host-side plan
    objects (layout dicts, ``range`` counters) that static analysis
    cannot tell apart, and an index expression like
    ``lay.theta_base.get(name) + k * K_LANES`` is a build-time Python
    int even though its inputs passed through traced-adjacent code.
    Arithmetic chains rooted directly at ``program_id``/ref loads are
    the realistic bug shape and resolve unambiguously."""
    tainted: Set[str] = set()

    def traced(e: ast.expr) -> bool:
        if isinstance(e, ast.Name):
            return e.id in tainted
        if isinstance(e, ast.Call):
            return call_chain(e.func)[-1:] == ["program_id"]
        if isinstance(e, ast.Subscript):
            base = call_chain(e.value)
            return bool(base) and base[0] in refs
        if isinstance(e, ast.BinOp):
            return traced(e.left) or traced(e.right)
        if isinstance(e, ast.UnaryOp):
            return traced(e.operand)
        return False

    changed = True
    while changed:
        changed = False
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and traced(node.value):
                name = node.targets[0].id
                if name not in tainted and name not in refs:
                    tainted.add(name)
                    changed = True
    return tainted


def run(project: Project) -> List[Finding]:
    out = _budget_findings(project)
    k = _Kernels(project)
    out.extend(k.promotion_findings())
    out.extend(k.kernel_findings())
    return out
