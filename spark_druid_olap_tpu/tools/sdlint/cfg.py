"""Per-function control-flow graphs with exception edges.

The dataflow passes (``leaks``, ``ordering``) need one question answered
precisely: *is there any path from A to B that avoids every node in S?*
— where paths include the exceptional exits a ``raise`` or a failing
call introduces. This module builds a statement-level CFG per function:

- ``entry`` / ``exit`` / ``raise_exit`` are synthetic nodes; ``exit``
  is reached by falling off the end or ``return``; ``raise_exit`` by an
  exception no handler in the function absorbs.
- Normal edges follow statement order, branches, and loops.
- Exception edges go from every may-raise statement to the innermost
  enclosing handler chain (``except`` entries, then ``finally``), or to
  ``raise_exit`` when nothing encloses it. ``finally`` bodies are laid
  out once with both a normal and an exceptional continuation — an
  over-approximation of CPython's block duplication that is conservative
  in the right direction: a release placed in the ``finally`` still
  blocks every path through it.
- ``with`` statements are modeled like ``try/finally`` around the body:
  the context manager's ``__exit__`` runs on all paths, represented by a
  synthetic ``WithExit`` node carrying the original ``ast.With``.

May-raise is deliberately coarse (any statement containing a call,
``raise``, ``assert``, subscript store, or ``for`` iteration): the
passes built on top require ``finally``/context-manager discipline, so
over-approximating raise sites only strengthens the check they already
make. Statements that are pure name/constant/attribute assignments are
the one carve-out — without it, ``x = acquired`` between an acquire and
its ``try`` would count as a leak path.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Sequence, Set, Union

#: synthetic node payloads
ENTRY = "<entry>"
EXIT = "<exit>"
RAISE_EXIT = "<raise-exit>"


@dataclasses.dataclass
class WithExit:
    """Synthetic node: the ``__exit__`` of a ``with`` statement (runs on
    both the normal and the exceptional way out of the body)."""
    stmt: ast.With


NodePayload = Union[str, ast.stmt, WithExit]


class CFG:
    def __init__(self, func: ast.AST):
        self.func = func
        self.nodes: List[NodePayload] = []
        self.succ: Dict[int, Set[int]] = {}    # normal control flow
        self.esucc: Dict[int, Set[int]] = {}   # this node raised
        self.entry = self._new(ENTRY)
        self.exit = self._new(EXIT)
        self.raise_exit = self._new(RAISE_EXIT)

    def _new(self, payload: NodePayload) -> int:
        nid = len(self.nodes)
        self.nodes.append(payload)
        self.succ[nid] = set()
        self.esucc[nid] = set()
        return nid

    def edge(self, a: int, b: int) -> None:
        self.succ[a].add(b)

    def eedge(self, a: int, b: int) -> None:
        self.esucc[a].add(b)

    # -- queries ---------------------------------------------------------------
    def stmt_nodes(self) -> List[int]:
        return [i for i, p in enumerate(self.nodes)
                if not isinstance(p, str)]

    def reachable_avoiding(self, start: int, goals: Set[int],
                           avoid: Set[int],
                           skip_start_raise: bool = False,
                           normal_only: bool = False
                           ) -> Optional[List[int]]:
        """BFS witness path start -> any goal that never enters ``avoid``
        (start itself is exempt); None when every path is blocked. With
        ``skip_start_raise`` the start node's own exception edges are
        ignored — "the acquire call itself failed" is not a leak. With
        ``normal_only`` exception edges are ignored entirely (ordering
        checks: an exception unwinding past a publish is not a missing
        post-publish step)."""
        if start in goals:
            return [start]
        seen = {start}
        frontier = [[start]]
        first = True
        while frontier:
            nxt = []
            for path in frontier:
                tail = path[-1]
                succs = set(self.succ[tail])
                if not normal_only and \
                        not (first and skip_start_raise and tail == start):
                    succs |= self.esucc[tail]
                for s in succs:
                    if s in seen or s in avoid:
                        continue
                    if s in goals:
                        return path + [s]
                    seen.add(s)
                    nxt.append(path + [s])
            frontier = nxt
            first = False
        return None


_SAFE_CTX = (ast.Name, ast.Constant, ast.Attribute)


def may_raise(stmt: ast.stmt) -> bool:
    """Coarse: anything that calls, raises, asserts, subscripts, or
    iterates may raise; plain name/constant/attribute moves may not."""
    if isinstance(stmt, (ast.Raise, ast.Assert, ast.For, ast.AsyncFor,
                         ast.With, ast.AsyncWith)):
        return True
    if isinstance(stmt, (ast.Pass, ast.Break, ast.Continue, ast.Global,
                         ast.Nonlocal, ast.Import, ast.ImportFrom,
                         ast.FunctionDef, ast.AsyncFunctionDef,
                         ast.ClassDef)):
        return False
    header = stmt
    if isinstance(stmt, (ast.If, ast.While)):
        header = stmt.test
    elif isinstance(stmt, ast.Return):
        if stmt.value is None:
            return False
        header = stmt.value
    elif isinstance(stmt, (ast.Assign, ast.AugAssign, ast.AnnAssign,
                           ast.Expr)):
        pass                      # inspect the whole statement below
    for node in ast.walk(header):
        if isinstance(node, (ast.Call, ast.Subscript, ast.Await,
                             ast.Yield, ast.YieldFrom, ast.BinOp,
                             ast.Compare, ast.ListComp, ast.SetComp,
                             ast.DictComp, ast.GeneratorExp)):
            return True
    return False


class _Builder:
    """Lays one function body out into a CFG. ``handlers`` is the stack
    of (exception-landing node, finally-landing node) scopes."""

    def __init__(self, func: ast.AST):
        self.g = CFG(func)
        # stack of targets an exception propagates to, innermost last
        self.exc_stack: List[int] = []
        # loop stack: (continue-target, break-target)
        self.loop_stack: List[tuple] = []
        # where a normal `return` routes (through enclosing finallys)
        self.return_stack: List[int] = []

    def build(self) -> CFG:
        body = getattr(self.g.func, "body", [])
        last = self._body(body, self.g.entry)
        if last is not None:
            self.g.edge(last, self.g.exit)
        return self.g

    # -- helpers ---------------------------------------------------------------
    def _exc_target(self) -> int:
        return self.exc_stack[-1] if self.exc_stack else self.g.raise_exit

    def _return_target(self) -> int:
        return self.return_stack[-1] if self.return_stack else self.g.exit

    def _body(self, stmts: Sequence[ast.stmt],
              pred: Optional[int]) -> Optional[int]:
        """Wire ``stmts`` after ``pred``; returns the fall-through node
        (None when control never falls through)."""
        cur = pred
        for s in stmts:
            if cur is None:
                break             # unreachable tail; don't model
            cur = self._stmt(s, cur)
        return cur

    def _stmt(self, s: ast.stmt, pred: int) -> Optional[int]:
        g = self.g
        if isinstance(s, (ast.If,)):
            n = g._new(s)
            g.edge(pred, n)
            if may_raise(s):
                g.eedge(n, self._exc_target())
            t_end = self._body(s.body, n)
            e_end = self._body(s.orelse, n) if s.orelse else n
            join = None
            for end in (t_end, e_end):
                if end is None:
                    continue
                if join is None:
                    join = g._new(ast.Pass())
                g.edge(end, join)
            return join
        if isinstance(s, (ast.While, ast.For, ast.AsyncFor)):
            head = g._new(s)
            g.edge(pred, head)
            if may_raise(s):
                g.eedge(head, self._exc_target())
            after = g._new(ast.Pass())
            g.edge(head, after)           # zero iterations / loop exit
            self.loop_stack.append((head, after))
            body_end = self._body(s.body, head)
            self.loop_stack.pop()
            if body_end is not None:
                g.edge(body_end, head)
            if s.orelse:
                after = self._body(s.orelse, after)
            return after
        if isinstance(s, (ast.With, ast.AsyncWith)):
            head = g._new(s)              # item exprs evaluate here
            g.edge(pred, head)
            g.eedge(head, self._exc_target())
            # three __exit__ copies so the normal / exceptional / return
            # continuations never merge (a merged node would fabricate
            # "body raised, then fell through normally" paths)
            wexit_n = g._new(WithExit(s))
            wexit_e = g._new(WithExit(s))
            wexit_r = g._new(WithExit(s))
            self.exc_stack.append(wexit_e)
            self.return_stack.append(wexit_r)
            body_end = self._body(s.body, head)
            self.return_stack.pop()
            self.exc_stack.pop()
            if body_end is not None:
                g.edge(body_end, wexit_n)
            g.edge(wexit_e, self._exc_target())
            g.edge(wexit_r, self._return_target())
            after = g._new(ast.Pass())
            g.edge(wexit_n, after)
            return after
        if isinstance(s, ast.Try):
            return self._try(s, pred)
        if isinstance(s, ast.Return):
            n = g._new(s)
            g.edge(pred, n)
            if may_raise(s):
                g.eedge(n, self._exc_target())
            g.edge(n, self._return_target())
            return None
        if isinstance(s, ast.Raise):
            n = g._new(s)
            g.edge(pred, n)
            g.eedge(n, self._exc_target())
            return None
        if isinstance(s, ast.Break):
            n = g._new(s)
            g.edge(pred, n)
            if self.loop_stack:
                g.edge(n, self.loop_stack[-1][1])
            return None
        if isinstance(s, ast.Continue):
            n = g._new(s)
            g.edge(pred, n)
            if self.loop_stack:
                g.edge(n, self.loop_stack[-1][0])
            return None
        # plain statement (incl. nested def/class: opaque)
        n = g._new(s)
        g.edge(pred, n)
        if may_raise(s):
            g.eedge(n, self._exc_target())
        return n

    def _try(self, s: ast.Try, pred: int) -> Optional[int]:
        g = self.g
        head = g._new(ast.Pass())
        g.edge(pred, head)
        after = g._new(ast.Pass())

        # the finally body is laid out once per continuation (normal /
        # exceptional / return), mirroring CPython's block duplication —
        # a single shared copy would merge the paths and fabricate
        # "raised, ran finally, then fell through normally" routes
        fin_norm = fin_exc = fin_ret = None
        if s.finalbody:
            fin_norm = g._new(ast.Pass())
            out = self._body(s.finalbody, fin_norm)
            if out is not None:
                g.edge(out, after)
            fin_exc = g._new(ast.Pass())
            out = self._body(s.finalbody, fin_exc)
            if out is not None:
                g.edge(out, self._exc_target())
            fin_ret = g._new(ast.Pass())
            out = self._body(s.finalbody, fin_ret)
            if out is not None:
                g.edge(out, self._return_target())

        exc_out = fin_exc if fin_exc is not None else self._exc_target()
        norm_out = fin_norm if fin_norm is not None else after

        # exception landing: each handler entry; unmatched -> finally/outer
        handler_entries = []
        exc_landing = g._new(ast.Pass())
        for h in s.handlers:
            hn = g._new(h)        # the `except X as e:` header
            g.edge(exc_landing, hn)
            handler_entries.append(hn)
        catch_all = any(
            h.type is None or (isinstance(h.type, ast.Name)
                               and h.type.id == "BaseException")
            for h in s.handlers)
        if not catch_all:
            # no handler matches / none at all (a bare `except:` /
            # `except BaseException:` matches everything — keeping the
            # fall-past edge there would fabricate leak paths around
            # handlers that exist precisely to release on error)
            g.edge(exc_landing, exc_out)

        self.exc_stack.append(exc_landing)
        if fin_ret is not None:
            self.return_stack.append(fin_ret)
        body_end = self._body(s.body, head)
        if s.orelse and body_end is not None:
            body_end = self._body(s.orelse, body_end)
        if fin_ret is not None:
            self.return_stack.pop()
        self.exc_stack.pop()

        # handler bodies: exceptions inside them go to finally/outer
        self.exc_stack.append(exc_out)
        if fin_ret is not None:
            self.return_stack.append(fin_ret)
        for hn, h in zip(handler_entries, s.handlers):
            h_end = self._body(h.body, hn)
            if h_end is not None:
                g.edge(h_end, norm_out)
        if fin_ret is not None:
            self.return_stack.pop()
        self.exc_stack.pop()

        if body_end is not None:
            g.edge(body_end, norm_out)
        return after


def build(func: ast.AST) -> CFG:
    """CFG for one ``FunctionDef`` / ``AsyncFunctionDef``."""
    return _Builder(func).build()
