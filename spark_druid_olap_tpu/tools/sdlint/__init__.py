"""sdlint: domain-aware static analysis for the engine's own invariants.

Four AST-based passes over the package (no imports, no execution — pure
``ast`` analysis, so fixtures with seeded violations never need their
dependencies installed):

- ``locks`` — interprocedural lock-acquisition graph over
  ``threading.Lock/RLock/Condition`` attributes: potential deadlock
  cycles, plus attributes mutated from thread entrypoints without the
  guarding lock that protects them elsewhere.
- ``purity`` — functions reachable from ``jax.jit`` / ``pallas_call``
  sites must not call host-only APIs (time, random, locks, I/O,
  concretization) or branch on traced values.
- ``contracts`` — every ``sdot.*`` config key read anywhere must be
  declared with a default in ``utils/config.py`` and vice versa; every
  emitted ``stats[...]`` key must be documented in ``docs/STATS.md``.
- ``mergeclosure`` — every aggregate registered in the engine must be
  declared in ``ops/agg_registry.py`` and consistently handled by
  ``ops/groupby.py``, the rollup derivation table (``mv/match.py``) and
  the shared-scan demux, so a new agg can never silently break
  wave/shard/rollup/coalesce composition.

Run as ``python -m spark_druid_olap_tpu.tools.sdlint``; CI runs the
same passes via ``tests/test_lint.py``. Known-and-justified findings
live in ``tools/sdlint/baseline.json``; line-level escapes use
``# sdlint: disable=<pass>``. See docs/LINT.md.
"""

from spark_druid_olap_tpu.tools.sdlint.core import (  # noqa: F401
    Baseline,
    Finding,
    Project,
    run_passes,
)

PASSES = ("locks", "purity", "contracts", "mergeclosure")
