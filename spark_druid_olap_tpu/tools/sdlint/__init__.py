"""sdlint: domain-aware static analysis for the engine's own invariants.

Nine AST-based passes over the package (no imports, no execution — pure
``ast`` analysis, so fixtures with seeded violations never need their
dependencies installed):

- ``locks`` — interprocedural lock-acquisition graph over
  ``threading.Lock/RLock/Condition`` attributes: potential deadlock
  cycles, plus attributes mutated from thread entrypoints without the
  guarding lock that protects them elsewhere.
- ``purity`` — functions reachable from ``jax.jit`` / ``pallas_call``
  sites must not call host-only APIs (time, random, locks, I/O,
  concretization) or branch on traced values.
- ``contracts`` — every ``sdot.*`` config key read anywhere must be
  declared with a default in ``utils/config.py`` and vice versa; every
  emitted ``stats[...]`` key must be documented in ``docs/STATS.md``.
- ``mergeclosure`` — every aggregate registered in the engine must be
  declared in ``ops/agg_registry.py`` and consistently handled by
  ``ops/groupby.py``, the rollup derivation table (``mv/match.py``) and
  the shared-scan demux, so a new agg can never silently break
  wave/shard/rollup/coalesce composition.
- ``keys`` — canonical cache keys (cache/keys.py, compile signatures,
  ``Config.fingerprint``) must cover exactly the result-affecting
  state: result-affecting fields/config missing from a key is cache
  poisoning, key terms nothing reads is needless churn.
- ``leaks`` — acquired resources (lane slots, quota tokens, tickets,
  inflight entries, cancel-flag refcounts, WAL handles, snapshot temp
  dirs) must be released on ALL paths of the exception-edge CFG
  (``cfg.py``) — ``finally``/context-manager discipline, machine
  checked.
- ``ordering`` — happens-before on persist paths: fsync before
  ``os.replace`` publish, directory fsync after it, WAL commit append
  before ``store.register``, ``truncate_through`` only after a
  completed checkpoint.
- ``kernels`` — the Pallas kernel contract (docs/KERNELS.md), checked
  statically: VMEM tile arithmetic stays inside the configured budget
  and matches the planner clamps and cost-model itemsize floors,
  ``_prep_dtype`` promotions are applied to every operand, scratch
  stripes are identity-initialised completely, kernel-reachable code
  avoids Mosaic-unfriendly primitives, ref indices are traced values.
- ``mesh`` — SPMD replication safety over every ``shard_map`` site:
  collective axis names must exist on the mesh, sketch registers merge
  with their declared register algebra (HLL max / theta min — never
  psum), min/max merge branches use the matching collective, and
  shard-reachable code must not call host callbacks / ``jax.random``
  or write host-global state.

Run as ``python -m spark_druid_olap_tpu.tools.sdlint``; CI runs the
same passes via ``tests/test_lint.py``. Known-and-justified findings
live in ``tools/sdlint/baseline.json``; line-level escapes use
``# sdlint: disable=<pass>``. See docs/LINT.md.
"""

from spark_druid_olap_tpu.tools.sdlint.core import (  # noqa: F401
    Baseline,
    Finding,
    Project,
    run_passes,
)

PASSES = ("locks", "purity", "contracts", "mergeclosure", "keys",
          "leaks", "ordering", "kernels", "mesh")
