"""ordering pass: happens-before on the persist (durability) paths.

Crash safety is an ordering property — the bytes hit stable storage in
one order or results diverge after kill -9 — so these rules are CFG
reachability questions, not call presence questions. Scope: modules
whose path contains ``persist`` (the subsystem owns every durable write
in the engine; scoping keeps ``str.replace`` and list ``append`` noise
out of a rule set that keys on method names).

Rules (normal edges only — an exception unwinding *past* a publish is
error propagation, not a missing durability step):

- **O1 rename-before-fsync** — a write (``.write``/``.writelines``/
  ``dump``) can reach an ``os.replace`` without an intervening
  ``*fsync*`` call: the rename can publish bytes the kernel never
  flushed, so a crash serves a torn file under the final name.
- **O2 publish-not-durable** — an ``os.replace`` can reach the function
  exit without a ``*fsync_dir*`` call: the rename itself lives in the
  directory inode; un-fsynced, a crash un-publishes (or worse,
  half-publishes) an already-acknowledged state change.
- **O3 register-before-wal-commit** — a ``store.register``-style call
  can reach a WAL ``append`` afterwards: registration makes data
  servable before its journal record is durable, so a crash between the
  two acknowledges rows that recovery cannot rebuild.
- **O4 truncate-without-checkpoint** — ``truncate_through`` reachable
  from function entry without passing a ``write_snapshot``/checkpoint
  call: truncating the journal before the snapshot that supersedes it
  is durable destroys the only recovery source. (Exception edges count
  here: a failed snapshot must not fall through to the truncate.)
- **O5 swap-before-truncate** — a function that performs a generation
  swap (``os.replace``) but can reach a WAL ``truncate_through`` from
  entry WITHOUT passing the swap: the journal records are destroyed
  while the old generation is still the published one, so a crash
  recovers the old snapshot minus the rows the journal held.
  (Exception edges count: a failed swap must not fall through to the
  truncate.)
- **O6 dir-fsync-after-swap** — an ``os.replace`` can reach a destroy
  step (``truncate_through`` / ``rmtree``) without an intervening
  ``*fsync_dir*``: the rename may still be sitting in an un-synced
  directory inode when its superseded recovery source is destroyed —
  a crash can lose BOTH generations.
- **O7 no-register-before-publish** — a ``store.register``-style call
  from which an ``os.replace`` or ``write_snapshot`` is still
  reachable: rows become servable before the durable publish that
  backs them, so a crash in between acknowledges a generation that
  recovery cannot reproduce (the compaction swap protocol requires
  publish-then-swap-in-memory, never the reverse).
"""

from __future__ import annotations

import ast
from typing import List, Sequence, Set, Tuple

from spark_druid_olap_tpu.tools.sdlint.astutil import call_chain
from spark_druid_olap_tpu.tools.sdlint.core import Finding, Project
from spark_druid_olap_tpu.tools.sdlint.leaks import _header_exprs, \
    _scan_calls, _suffix

WRITE_SEGS = frozenset({"write", "writelines", "dump", "tofile"})


def _chain_nodes(g, pred) -> dict:
    """CFG node -> [call chains] passing ``pred(chain)``. Header-only:
    a ``with``/``if`` node must not swallow its body's calls — the body
    statements have CFG nodes of their own, and attributing them to the
    header would merge before/after into "at the same point"."""
    out = {}
    for n in g.stmt_nodes():
        chains = [call_chain(c.func)
                  for h in _header_exprs(g.nodes[n])
                  for c in _scan_calls(h)]
        hits = [ch for ch in chains if ch and pred(ch)]
        if hits:
            out[n] = hits
    return out


def _line(g, n) -> int:
    p = g.nodes[n]
    return getattr(p, "lineno", 0) if isinstance(p, ast.AST) else 0


def _check_function(project: Project, mod, qual: str,
                    fn) -> List[Finding]:
    out: List[Finding] = []
    g = project.cfg(fn)

    replace = _chain_nodes(g, lambda ch: _suffix(ch, ("os", "replace")))
    fsync_any = _chain_nodes(
        g, lambda ch: any("fsync" in seg for seg in ch))
    dsync = _chain_nodes(
        g, lambda ch: any("fsync_dir" in seg for seg in ch))
    writes = _chain_nodes(g, lambda ch: ch[-1] in WRITE_SEGS)
    wal_append = _chain_nodes(
        g, lambda ch: ch[-1] in ("append", "append_group")
        and any("wal" in seg.lower() for seg in ch[:-1]))
    register = _chain_nodes(
        g, lambda ch: ch[-1] == "register" and len(ch) >= 2)
    truncate = _chain_nodes(g, lambda ch: ch[-1] == "truncate_through")
    ckpt = _chain_nodes(
        g, lambda ch: ch[-1] == "write_snapshot"
        or any("checkpoint" in seg for seg in ch))
    destroy = _chain_nodes(
        g, lambda ch: ch[-1] in ("truncate_through", "rmtree"))
    publish = _chain_nodes(
        g, lambda ch: _suffix(ch, ("os", "replace"))
        or ch[-1] == "write_snapshot")

    def emit(rule: str, n: int, anchor: str, msg: str) -> None:
        out.append(Finding("ordering", rule, mod.relpath, _line(g, n),
                           f"{qual}:{anchor}", msg))

    # O1: some write reaches this replace with no fsync between
    for rn in replace:
        for wn in writes:
            if wn == rn:
                continue
            if g.reachable_avoiding(wn, {rn}, set(fsync_any) - {wn, rn},
                                    normal_only=True):
                emit("rename-before-fsync", rn, "os.replace",
                     "os.replace can publish bytes written here without "
                     "an fsync in between — a crash can expose a torn "
                     "file under the final name")
                break

    # O2: replace reaches exit with no directory fsync after it
    for rn in replace:
        if g.reachable_avoiding(rn, {g.exit}, set(dsync) - {rn},
                                normal_only=True):
            emit("publish-not-durable", rn, "os.replace",
                 "rename publish is not followed by a directory fsync "
                 "(*fsync_dir*) on every normal path — the publish "
                 "itself can be lost on crash")

    # O3: a WAL commit append is reachable AFTER a register
    if wal_append and register:
        for rn in register:
            hit = g.reachable_avoiding(
                rn, set(wal_append) - {rn}, set(), normal_only=True)
            if hit:
                emit("register-before-wal-commit", rn, "register",
                     "datasource registered before its WAL commit "
                     "append — a crash between the two acknowledges "
                     "rows recovery cannot rebuild")

    # O4: truncate reachable without a prior successful checkpoint
    for tn in truncate:
        if g.reachable_avoiding(g.entry, {tn}, set(ckpt) - {tn}):
            emit("truncate-without-checkpoint", tn, "truncate_through",
                 "WAL truncate_through reachable without a completed "
                 "write_snapshot/checkpoint on the same path — the only "
                 "recovery source is destroyed before its replacement "
                 "is durable")

    # O5: the function swaps generations, but a truncate can run first
    # (exception edges count: a failed swap must not fall through)
    if replace:
        for tn in truncate:
            if g.reachable_avoiding(g.entry, {tn},
                                    set(replace) - {tn}):
                emit("swap-before-truncate", tn, "truncate_through",
                     "WAL truncate_through reachable before the "
                     "generation swap (os.replace) completes — the "
                     "journal is destroyed while the OLD generation is "
                     "still published, so a crash loses its rows")

    # O6: swap reaches a destroy step with no directory fsync between
    for rn in replace:
        for dn in destroy:
            if dn == rn:
                continue
            if g.reachable_avoiding(rn, {dn}, set(dsync) - {rn, dn},
                                    normal_only=True):
                emit("dir-fsync-after-swap", rn, "os.replace",
                     "rename publish reaches a destroy step "
                     "(truncate_through/rmtree) without a directory "
                     "fsync in between — a crash can lose both the new "
                     "generation and its superseded recovery source")
                break

    # O7: rows registered while their durable publish is still ahead
    for rn in register:
        if g.reachable_avoiding(rn, set(publish) - {rn}, set(),
                                normal_only=True):
            emit("no-register-before-publish", rn, "register",
                 "datasource registered before the durable publish "
                 "(write_snapshot/os.replace) that backs it — a crash "
                 "in between acknowledges a generation recovery cannot "
                 "reproduce")
    return out


def run(project: Project) -> List[Finding]:
    idx = project.index()
    out: List[Finding] = []
    for (mod_name, qual), fn in sorted(idx.functions.items()):
        mod = project.modules[mod_name]
        if "persist" not in mod.relpath:
            continue
        out.extend(_check_function(project, mod, qual, fn))
    return out
