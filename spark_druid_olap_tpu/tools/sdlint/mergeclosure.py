"""Merge-closure pass.

Cross-checks ``ops/agg_registry.py:AGG_CLOSURE`` (the declared closure)
against the four sites that must each handle every aggregate:

- ``unregistered-agg`` — a kind in ``parallel/executor.py:_AGG_KIND``
  missing from ``AGG_CLOSURE``.
- ``stale-registry``   — an ``AGG_CLOSURE`` kind the executor no longer
  registers.
- ``route-mismatch``   — registry route/dtype disagrees with the
  executor's ``_AGG_KIND`` tuple.
- ``unmergeable-agg``  — a non-sketch route kind
  ``ops/groupby.py:merge_partials`` has no branch for (``sum``/``count``
  ride the ``psum`` default; ``min``/``max`` must appear literally).
- ``rollup-gap``       — a declared ``reagg`` kind ``mv/match.py`` never
  mentions (neither in ``_REAGG_KINDS`` nor as a special-case literal).
- ``demux-gap``        — a sketch kind ``parallel/sharedscan.py`` never
  special-cases in its fused program / demux.
- ``undeclared-sketch-merge`` — a sketch-valued kind whose registry
  entry has no ``merge`` field: the register algebra (``max``/``min``/
  ``minsum``) is what every cross-chip and broker merge must agree on,
  so a sketch without a declared algebra is unmergeable by contract.
- ``sketch-merge-drift`` — the declared ``merge`` disagrees with (or is
  missing from) the runtime merge table
  ``ops/groupby.py:SKETCH_MERGE_OPS`` that the device merge dispatches
  on.

Anchors are found by path suffix, so fixture trees carrying only the
anchors their seeded violation needs still exercise the pass; a missing
anchor skips its checks rather than failing.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from spark_druid_olap_tpu.tools.sdlint.core import Finding, Module, Project

_REGISTRY_SUFFIX = "ops/agg_registry.py"
_EXECUTOR_SUFFIX = "parallel/executor.py"
_GROUPBY_SUFFIX = "ops/groupby.py"
_MATCH_SUFFIX = "mv/match.py"
_SHAREDSCAN_SUFFIX = "parallel/sharedscan.py"
# psum is merge_partials' fallthrough: additive routes need no literal
_PSUM_ROUTES = {"sum", "count"}


def _dict_literal(mod: Module, name: str) -> Optional[Dict]:
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and node.targets[0].id == name:
            try:
                v = ast.literal_eval(node.value)
            except ValueError:
                return None
            return v if isinstance(v, dict) else None
    return None


def _registry(mod: Module) -> Optional[Dict[str, dict]]:
    return _dict_literal(mod, "AGG_CLOSURE")


def _agg_kind_literal(mod: Module) -> Dict[str, tuple]:
    """executor's ``_AGG_KIND`` dict literal -> {kind: (route, dtype)};
    dtype read off the ``np.<dtype>`` attribute name."""
    out: Dict[str, tuple] = {}
    for node in ast.walk(mod.tree):
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == "_AGG_KIND"
                and isinstance(node.value, ast.Dict)):
            continue
        for k, v in zip(node.value.keys, node.value.values):
            if not (isinstance(k, ast.Constant)
                    and isinstance(k.value, str)):
                continue
            route = dtype = None
            if isinstance(v, ast.Tuple) and len(v.elts) == 2:
                if isinstance(v.elts[0], ast.Constant):
                    route = v.elts[0].value
                if isinstance(v.elts[1], ast.Attribute):
                    dtype = v.elts[1].attr
            out[k.value] = (route, dtype, node.lineno)
    return out


def _function_literals(mod: Module, name: str) -> Set[str]:
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.FunctionDef) and node.name == name:
            return {n.value for n in ast.walk(node)
                    if isinstance(n, ast.Constant)
                    and isinstance(n.value, str)}
    return set()


def _module_literals(mod: Module) -> Set[str]:
    return {n.value for n in ast.walk(mod.tree)
            if isinstance(n, ast.Constant) and isinstance(n.value, str)}


def run(project: Project) -> List[Finding]:
    reg_mod = project.by_suffix(_REGISTRY_SUFFIX)
    if reg_mod is None:
        return []
    registry = _registry(reg_mod)
    if registry is None:
        return []
    out: List[Finding] = []

    exec_mod = project.by_suffix(_EXECUTOR_SUFFIX)
    if exec_mod is not None:
        agg_kind = _agg_kind_literal(exec_mod)
        for kind, (route, dtype, line) in sorted(agg_kind.items()):
            if kind not in registry:
                out.append(Finding(
                    "mergeclosure", "unregistered-agg", exec_mod.relpath,
                    line, kind,
                    f"aggregate kind {kind!r} is registered in "
                    f"executor._AGG_KIND but missing from "
                    f"ops/agg_registry.py:AGG_CLOSURE — declare its "
                    f"merge closure there first"))
            else:
                ent = registry[kind]
                if route != ent.get("route") or dtype != ent.get("dtype"):
                    out.append(Finding(
                        "mergeclosure", "route-mismatch",
                        exec_mod.relpath, line, kind,
                        f"executor._AGG_KIND maps {kind!r} to "
                        f"({route!r}, {dtype}) but AGG_CLOSURE declares "
                        f"({ent.get('route')!r}, {ent.get('dtype')})"))
        for kind in sorted(set(registry) - set(agg_kind)):
            out.append(Finding(
                "mergeclosure", "stale-registry", reg_mod.relpath, 1,
                kind,
                f"AGG_CLOSURE declares {kind!r} but executor._AGG_KIND "
                f"no longer registers it"))

    # sketch entries must DECLARE their register algebra, and the
    # declaration must match the runtime dispatch table the device
    # merge actually folds with
    for kind, ent in sorted(registry.items()):
        sketch = ent.get("sketch")
        if sketch is not None and not ent.get("merge"):
            out.append(Finding(
                "mergeclosure", "undeclared-sketch-merge",
                reg_mod.relpath, 1, kind,
                f"sketch aggregate {kind!r} ({sketch}) declares no "
                f"'merge' register algebra in AGG_CLOSURE — cross-chip "
                f"and broker merges have nothing to check against, and "
                f"a psum over {sketch} registers corrupts silently"))

    gb_mod = project.by_suffix(_GROUPBY_SUFFIX)
    if gb_mod is not None:
        runtime_ops = _dict_literal(gb_mod, "SKETCH_MERGE_OPS")
        if runtime_ops is not None:
            for kind, ent in sorted(registry.items()):
                sketch, merge = ent.get("sketch"), ent.get("merge")
                if sketch is None or not merge:
                    continue
                got = runtime_ops.get(sketch)
                if got != merge:
                    out.append(Finding(
                        "mergeclosure", "sketch-merge-drift",
                        gb_mod.relpath, 1, kind,
                        f"AGG_CLOSURE declares {sketch} merges via "
                        f"{merge!r} but ops/groupby.py:SKETCH_MERGE_OPS "
                        f"{'has no entry for it' if got is None else f'dispatches {got!r}'}"
                        f" — the device fold and the declared closure "
                        f"disagree"))
        handled = _function_literals(gb_mod, "merge_partials")
        for kind, ent in sorted(registry.items()):
            route = ent.get("route")
            if ent.get("sketch") or route in _PSUM_ROUTES:
                continue
            if route not in handled:
                out.append(Finding(
                    "mergeclosure", "unmergeable-agg", gb_mod.relpath, 1,
                    kind,
                    f"aggregate {kind!r} routes as {route!r} but "
                    f"ops/groupby.py:merge_partials has no branch for "
                    f"{route!r}: cross-chip merge would psum it"))

    match_mod = project.by_suffix(_MATCH_SUFFIX)
    if match_mod is not None:
        mentioned = _module_literals(match_mod)
        for kind, ent in sorted(registry.items()):
            reagg = ent.get("reagg")
            if reagg is not None and reagg not in mentioned:
                out.append(Finding(
                    "mergeclosure", "rollup-gap", match_mod.relpath, 1,
                    kind,
                    f"aggregate {kind!r} declares reagg kind {reagg!r} "
                    f"but mv/match.py never handles it: rollup rewrites "
                    f"would silently reject (or mis-merge) it"))

    ss_mod = project.by_suffix(_SHAREDSCAN_SUFFIX)
    if ss_mod is not None:
        mentioned = _module_literals(ss_mod)
        for kind, ent in sorted(registry.items()):
            sketch = ent.get("sketch")
            if sketch is not None and sketch not in mentioned:
                out.append(Finding(
                    "mergeclosure", "demux-gap", ss_mod.relpath, 1, kind,
                    f"sketch aggregate {kind!r} ({sketch}) has no "
                    f"special-case in the shared-scan fused program / "
                    f"demux: coalesced execution would decode its "
                    f"registers as plain columns"))
    return out
