"""``python -m spark_druid_olap_tpu.tools.sdlint`` — CI entrypoint.

Exit codes: 0 = clean (every finding baselined), 1 = unbaselined
findings, 2 = invalid baseline (entry without a justification).
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys

from spark_druid_olap_tpu.tools.sdlint import PASSES
from spark_druid_olap_tpu.tools.sdlint.core import (Baseline, Project,
                                                    report_human,
                                                    report_json, run_passes)


def default_root() -> str:
    # .../spark_druid_olap_tpu/tools/sdlint/__main__.py -> the package dir
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="sdlint",
        description="domain-aware static analysis for spark_druid_olap_tpu")
    ap.add_argument("--root", default=None,
                    help="package directory to scan (default: the "
                         "installed spark_druid_olap_tpu package)")
    ap.add_argument("--package", default="spark_druid_olap_tpu",
                    help="dotted package name the root maps to")
    ap.add_argument("--passes", default=",".join(PASSES),
                    help=f"comma-separated subset of {','.join(PASSES)}")
    ap.add_argument("--baseline", default=None,
                    help="baseline JSON (default: <root>/tools/sdlint/"
                         "baseline.json; 'none' disables)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable report on stdout")
    ap.add_argument("--format", choices=("human", "json"), default=None,
                    help="report format (--json is shorthand for "
                         "--format json)")
    ap.add_argument("--changed-only", action="store_true",
                    help="report only findings in files changed vs git "
                         "HEAD (analysis still sees the whole project, "
                         "so cross-module resolution is unaffected)")
    ap.add_argument("--timing", action="store_true",
                    help="per-pass wall-clock report on stderr")
    args = ap.parse_args(argv)

    root = os.path.abspath(args.root or default_root())
    passes = [p.strip() for p in args.passes.split(",") if p.strip()]
    bad = [p for p in passes if p not in PASSES]
    if bad:
        ap.error(f"unknown pass(es): {', '.join(bad)}")

    if args.baseline == "none":
        baseline = Baseline()
    else:
        bpath = args.baseline or os.path.join(root, "tools", "sdlint",
                                              "baseline.json")
        baseline = Baseline.load(bpath)
    missing = baseline.missing_justifications()
    if missing:
        for e in missing:
            print(f"sdlint: baseline entry missing justification: "
                  f"{e.get('pass')}/{e.get('rule')} {e.get('symbol')}",
                  file=sys.stderr)
        return 2

    project = Project(root, package=args.package)
    timing = {} if args.timing else None
    findings = run_passes(project, passes, timing=timing)
    if args.changed_only:
        changed = _changed_files(root)
        if changed is not None:
            findings = [f for f in findings if f.path in changed]
    if timing is not None:
        total = sum(timing.values())
        for name, secs in sorted(timing.items(), key=lambda kv: -kv[1]):
            print(f"sdlint: timing {name:>12s} {secs * 1000:8.1f} ms",
                  file=sys.stderr)
        print(f"sdlint: timing {'total':>12s} {total * 1000:8.1f} ms",
              file=sys.stderr)
    if args.json or args.format == "json":
        print(report_json(findings, baseline))
        new = sum(1 for f in findings if not baseline.matches(f))
    else:
        new = report_human(findings, baseline)
    return 1 if new else 0


def _changed_files(root: str):
    """Paths (relative to ``root``) changed vs HEAD, including staged
    and untracked files; None when git is unavailable (fail open: the
    full report is better than no report)."""
    try:
        out = subprocess.run(
            ["git", "status", "--porcelain", "--untracked-files=all"],
            cwd=root, capture_output=True, text=True, timeout=30)
        if out.returncode != 0:
            return None
        top = subprocess.run(["git", "rev-parse", "--show-toplevel"],
                             cwd=root, capture_output=True, text=True,
                             timeout=30).stdout.strip()
    except (OSError, subprocess.SubprocessError):
        return None
    changed = set()
    for line in out.stdout.splitlines():
        name = line[3:].split(" -> ")[-1].strip().strip('"')
        abspath = os.path.join(top, name)
        rel = os.path.relpath(abspath, root)
        if not rel.startswith(".."):
            changed.add(rel)
    return changed


if __name__ == "__main__":
    sys.exit(main())
