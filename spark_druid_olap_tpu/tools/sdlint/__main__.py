"""``python -m spark_druid_olap_tpu.tools.sdlint`` — CI entrypoint.

Exit codes: 0 = clean (every finding baselined), 1 = unbaselined
findings, 2 = invalid baseline (entry without a justification).
"""

from __future__ import annotations

import argparse
import os
import sys

from spark_druid_olap_tpu.tools.sdlint import PASSES
from spark_druid_olap_tpu.tools.sdlint.core import (Baseline, Project,
                                                    report_human,
                                                    report_json, run_passes)


def default_root() -> str:
    # .../spark_druid_olap_tpu/tools/sdlint/__main__.py -> the package dir
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="sdlint",
        description="domain-aware static analysis for spark_druid_olap_tpu")
    ap.add_argument("--root", default=None,
                    help="package directory to scan (default: the "
                         "installed spark_druid_olap_tpu package)")
    ap.add_argument("--package", default="spark_druid_olap_tpu",
                    help="dotted package name the root maps to")
    ap.add_argument("--passes", default=",".join(PASSES),
                    help=f"comma-separated subset of {','.join(PASSES)}")
    ap.add_argument("--baseline", default=None,
                    help="baseline JSON (default: <root>/tools/sdlint/"
                         "baseline.json; 'none' disables)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable report on stdout")
    args = ap.parse_args(argv)

    root = os.path.abspath(args.root or default_root())
    passes = [p.strip() for p in args.passes.split(",") if p.strip()]
    bad = [p for p in passes if p not in PASSES]
    if bad:
        ap.error(f"unknown pass(es): {', '.join(bad)}")

    if args.baseline == "none":
        baseline = Baseline()
    else:
        bpath = args.baseline or os.path.join(root, "tools", "sdlint",
                                              "baseline.json")
        baseline = Baseline.load(bpath)
    missing = baseline.missing_justifications()
    if missing:
        for e in missing:
            print(f"sdlint: baseline entry missing justification: "
                  f"{e.get('pass')}/{e.get('rule')} {e.get('symbol')}",
                  file=sys.stderr)
        return 2

    project = Project(root, package=args.package)
    findings = run_passes(project, passes)
    if args.json:
        print(report_json(findings, baseline))
        new = sum(1 for f in findings if not baseline.matches(f))
    else:
        new = report_human(findings, baseline)
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
